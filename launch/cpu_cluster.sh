#!/usr/bin/env bash
# Start N local processes joined into ONE jax.distributed cluster over
# localhost — the smallest real multi-controller world. Debugs launch logic
# and multi-process code paths without hardware; the same env contract
# works host-per-process on a real CPU/GPU cluster.
#
# Usage: ./launch/cpu_cluster.sh <nprocs> -- <command...>
set -euo pipefail

if [ "$#" -lt 3 ]; then
    echo "usage: $0 <nprocs> -- <command...>" >&2
    exit 2
fi
NPROCS=$1; shift
[ "${1:-}" = "--" ] && shift

PORT=$(( 20000 + RANDOM % 20000 ))
PIDS=()
for (( i=0; i<NPROCS; i++ )); do
    JAX_PLATFORMS=cpu \
    JAX_COORDINATOR_ADDRESS="127.0.0.1:${PORT}" \
    JAX_NUM_PROCESSES="$NPROCS" \
    JAX_PROCESS_ID="$i" \
    DEAR_DISABLE_DISTRIBUTED= \
    "$@" &
    PIDS+=($!)
done

rc=0
for pid in "${PIDS[@]}"; do
    wait "$pid" || rc=$?
done
exit "$rc"
