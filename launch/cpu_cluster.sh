#!/usr/bin/env bash
# Start N local processes joined into ONE jax.distributed cluster over
# localhost — the smallest real multi-controller world. Debugs launch logic
# and multi-process code paths without hardware; the same env contract
# works host-per-process on a real CPU/GPU cluster.
#
# Usage: ./launch/cpu_cluster.sh <nprocs> -- <command...>
#
# Elastic mode: ./launch/cpu_cluster.sh --elastic <supervisor args...>
# delegates to launch/supervisor.py — ranks get the DEAR_ELASTIC_* rejoin
# env contract (FileTransport coordination, no jax.distributed, so a dead
# rank can be relaunched and rejoin at a later membership epoch) instead
# of the fixed-world JAX_* contract below. Example:
#   ./launch/cpu_cluster.sh --elastic --nprocs 3 --dir /tmp/el -- \
#       python worker.py
set -euo pipefail

if [ "${1:-}" = "--elastic" ]; then
    shift
    exec "${PYTHON:-python3}" "$(dirname "$0")/supervisor.py" "$@"
fi

if [ "$#" -lt 3 ]; then
    echo "usage: $0 <nprocs> -- <command...>" >&2
    echo "       $0 --elastic <supervisor.py args...>" >&2
    exit 2
fi
NPROCS=$1; shift
[ "${1:-}" = "--" ] && shift

PORT=$(( 20000 + RANDOM % 20000 ))
PIDS=()
for (( i=0; i<NPROCS; i++ )); do
    JAX_PLATFORMS=cpu \
    JAX_COORDINATOR_ADDRESS="127.0.0.1:${PORT}" \
    JAX_NUM_PROCESSES="$NPROCS" \
    JAX_PROCESS_ID="$i" \
    DEAR_DISABLE_DISTRIBUTED= \
    "$@" &
    PIDS+=($!)
done

rc=0
for pid in "${PIDS[@]}"; do
    wait "$pid" || rc=$?
done
exit "$rc"
