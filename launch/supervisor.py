#!/usr/bin/env python3
"""Elastic rank supervisor: launch N worker ranks, relaunch the dead ones.

The resilience stack's division of labor (docs/RESILIENCE.md "Elastic
membership"): `resilience.membership.ElasticCluster` decides WHO is in the
fleet — survivors shrink the membership when a rank dies, and a relaunched
rank rejoins at a later epoch — but something outside the job has to bring
the dead rank BACK. On a real pod that is the cluster manager (k8s
restartPolicy, GCE instance groups); this supervisor is the same contract
for process clusters on one host, and the reference implementation of the
**rejoin env contract** every relauncher must speak:

    DEAR_ELASTIC_DIR    FileTransport root — the coordination store that
                        outlives any single rank (never the jax
                        coordination service, which dies with process 0)
    DEAR_ELASTIC_RANK   the stable rank id (identity, not position)
    DEAR_ELASTIC_WORLD  the initial world size
    DEAR_ELASTIC_REJOIN "1" on a RELAUNCHED rank — the worker must come
                        back through `ElasticCluster.rejoin` instead of
                        assuming first-launch membership

Policy: a rank exiting 0 is finished and never relaunched; any other exit
(including signals — a SIGKILLed host shows up here as -9) is relaunched
with the rejoin flag after ``relaunch_delay_s``, up to ``max_relaunches``
per rank. Per-rank pid files under ``<dir>/supervisor/pids/<rank>`` let
chaos harnesses (scripts/chaos_check.py --elastic) target a specific rank.

Usage (also via ``launch/cpu_cluster.sh --elastic ...``)::

    python launch/supervisor.py --nprocs 3 --dir /tmp/elastic \
        [--max-relaunches 2] [--deadline 300] -- python worker.py
"""

from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import time
from typing import Dict, List, Optional

ELASTIC_DIR_ENV = "DEAR_ELASTIC_DIR"
ELASTIC_RANK_ENV = "DEAR_ELASTIC_RANK"
ELASTIC_WORLD_ENV = "DEAR_ELASTIC_WORLD"
ELASTIC_REJOIN_ENV = "DEAR_ELASTIC_REJOIN"


class ElasticSupervisor:
    """Supervise one elastic process cluster on this host."""

    def __init__(
        self,
        nprocs: int,
        command: List[str],
        *,
        elastic_dir: str,
        env: Optional[dict] = None,
        max_relaunches: int = 2,
        relaunch_delay_s: float = 0.5,
        log=lambda s: print(s, file=sys.stderr, flush=True),
    ):
        if nprocs < 1:
            raise ValueError(f"nprocs must be >= 1, got {nprocs}")
        if not command:
            raise ValueError("empty worker command")
        self.nprocs = int(nprocs)
        self.command = list(command)
        self.elastic_dir = os.path.abspath(elastic_dir)
        self.base_env = dict(os.environ if env is None else env)
        self.max_relaunches = int(max_relaunches)
        self.relaunch_delay_s = float(relaunch_delay_s)
        self._log = log
        self._procs: Dict[int, subprocess.Popen] = {}
        self._final_rc: Dict[int, int] = {}   # rank -> exit of its LAST run
        self.relaunches: Dict[int, int] = {r: 0 for r in range(self.nprocs)}
        self._pid_dir = os.path.join(self.elastic_dir, "supervisor", "pids")
        os.makedirs(self._pid_dir, exist_ok=True)

    # -- lifecycle -----------------------------------------------------------

    def _spawn(self, rank: int, *, rejoin: bool) -> None:
        env = dict(self.base_env)
        env[ELASTIC_DIR_ENV] = self.elastic_dir
        env[ELASTIC_RANK_ENV] = str(rank)
        env[ELASTIC_WORLD_ENV] = str(self.nprocs)
        if rejoin:
            env[ELASTIC_REJOIN_ENV] = "1"
        else:
            env.pop(ELASTIC_REJOIN_ENV, None)
        proc = subprocess.Popen(self.command, env=env)
        self._procs[rank] = proc
        with open(os.path.join(self._pid_dir, str(rank)), "w") as f:
            f.write(str(proc.pid))
        self._log(
            f"supervisor: rank {rank} {'RELAUNCHED (rejoin)' if rejoin else 'launched'} "
            f"pid={proc.pid}")

    def start(self) -> "ElasticSupervisor":
        for rank in range(self.nprocs):
            self._spawn(rank, rejoin=False)
        return self

    def pid(self, rank: int) -> Optional[int]:
        proc = self._procs.get(rank)
        return proc.pid if proc is not None else None

    def poll(self) -> bool:
        """One supervision pass: reap exits, relaunch failures. Returns
        True while any rank is still running (or pending relaunch)."""
        for rank, proc in list(self._procs.items()):
            rc = proc.poll()
            if rc is None:
                continue
            del self._procs[rank]
            self._final_rc[rank] = rc
            if rc == 0:
                self._log(f"supervisor: rank {rank} finished cleanly")
                continue
            if self.relaunches[rank] >= self.max_relaunches:
                self._log(
                    f"supervisor: rank {rank} exited rc={rc}; relaunch "
                    f"budget ({self.max_relaunches}) exhausted — giving up")
                continue
            self.relaunches[rank] += 1
            self._log(
                f"supervisor: rank {rank} exited rc={rc}; relaunching with "
                f"{ELASTIC_REJOIN_ENV}=1 "
                f"({self.relaunches[rank]}/{self.max_relaunches}) "
                f"in {self.relaunch_delay_s:.1f}s")
            time.sleep(self.relaunch_delay_s)
            self._spawn(rank, rejoin=True)
        return bool(self._procs)

    def wait(self, deadline_s: Optional[float] = None, poll_s: float = 0.2,
             ) -> int:
        """Supervise until every rank has finished (rc 0 or budget
        exhausted) or the deadline expires (everything still alive is
        killed). Returns 0 iff every rank's FINAL run exited 0."""
        t_end = (None if deadline_s is None
                 else time.monotonic() + float(deadline_s))
        while self.poll():
            if t_end is not None and time.monotonic() >= t_end:
                self._log(
                    f"supervisor: deadline {deadline_s:.0f}s expired with "
                    f"rank(s) {sorted(self._procs)} still alive — killing")
                self.kill_all()
                for rank, proc in list(self._procs.items()):
                    self._final_rc[rank] = proc.wait()
                self._procs.clear()
                return 124
            time.sleep(poll_s)
        bad = {r: rc for r, rc in self._final_rc.items() if rc != 0}
        if bad:
            self._log(f"supervisor: failed rank exits: {bad}")
            return 1
        return 0

    def kill_all(self, sig: int = signal.SIGKILL) -> None:
        for proc in self._procs.values():
            try:
                proc.send_signal(sig)
            except OSError:
                pass


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="elastic rank supervisor (see module docstring)")
    ap.add_argument("--nprocs", type=int, required=True)
    ap.add_argument("--dir", required=True,
                    help="elastic coordination dir (FileTransport root)")
    ap.add_argument("--max-relaunches", type=int, default=2,
                    help="relaunch budget PER RANK (default 2)")
    ap.add_argument("--relaunch-delay", type=float, default=0.5)
    ap.add_argument("--deadline", type=float, default=None,
                    help="overall wall-clock budget in seconds")
    ap.add_argument("command", nargs=argparse.REMAINDER,
                    help="-- worker command...")
    args = ap.parse_args(argv)
    command = args.command
    if command and command[0] == "--":
        command = command[1:]
    if not command:
        ap.error("missing worker command (pass it after --)")
    sup = ElasticSupervisor(
        args.nprocs, command, elastic_dir=args.dir,
        max_relaunches=args.max_relaunches,
        relaunch_delay_s=args.relaunch_delay,
    ).start()
    try:
        return sup.wait(args.deadline)
    except KeyboardInterrupt:
        sup.kill_all(signal.SIGTERM)
        return 130


if __name__ == "__main__":
    sys.exit(main())
