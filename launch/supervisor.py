#!/usr/bin/env python3
"""Elastic rank supervisor: launch N worker ranks, relaunch the dead ones,
and — with a `ScalePolicy` — ride external capacity up and down.

The resilience stack's division of labor (docs/RESILIENCE.md "Elastic
membership" / "Autoscaling"): `resilience.membership.ElasticCluster`
decides WHO is in the fleet — survivors shrink the membership when a rank
dies, a relaunched rank rejoins at a later epoch, and a brand-new rank is
admitted through the same barrier (scale-UP) — but something outside the
job has to bring ranks up and down. On a real pod that is the cluster
manager (k8s restartPolicy, GCE instance groups, a spot-pool API); this
supervisor is the same contract for process clusters on one host, and the
reference implementation of the **rejoin env contract** every relauncher
must speak:

    DEAR_ELASTIC_DIR    FileTransport root — the coordination store that
                        outlives any single rank (never the jax
                        coordination service, which dies with process 0)
    DEAR_ELASTIC_RANK   the stable rank id (identity, not position)
    DEAR_ELASTIC_WORLD  the initial world size (a scale-up rank's id is
                        >= this — `ElasticCluster.from_env` joins)
    DEAR_ELASTIC_REJOIN "1" on a RELAUNCHED or SCALE-UP rank — the worker
                        must come back through `ElasticCluster.rejoin`
                        instead of assuming first-launch membership

Policy: a rank exiting 0 is finished and never relaunched (unless it was
being **drained** — then the scale policy may backfill it while capacity
still wants the larger world); any other exit (including signals — a
SIGKILLed host shows up here as -9) is relaunched with the rejoin flag
after ``relaunch_delay_s``, within the per-rank **sliding-window budget**:
at most ``max_relaunches`` relaunches per rank inside the trailing
``relaunch_window_s`` seconds. With no window the budget degrades to the
legacy per-rank lifetime cap — but a long-running continuous-training
service exhausts any lifetime cap by design, so production runs should
always set the window. Per-rank pid files under
``<dir>/supervisor/pids/<rank>`` let chaos harnesses
(scripts/chaos_check.py --elastic/--autoscale) target a specific rank.

With ``--capacity-file`` the supervisor drives a
`dear_pytorch_tpu.resilience.scale.ScalePolicy` each poll: a
``target_world`` above the live world spawns new ranks (fresh ids beyond
the initial world, admitted as scale-UP epochs), below it — or an explicit
``drain`` list — SIGTERMs victims so `resilience.preempt`'s grace window
(``DEAR_PREEMPT_GRACE_S``) turns the exit into an emergency save plus a
*planned* membership shrink.

Usage (also via ``launch/cpu_cluster.sh --elastic ...``)::

    python launch/supervisor.py --nprocs 3 --dir /tmp/elastic \
        [--max-relaunches 2] [--relaunch-window 600] \
        [--capacity-file /tmp/capacity.json] [--deadline 300] \
        -- python worker.py
"""

from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import time
from typing import Dict, List, Optional

ELASTIC_DIR_ENV = "DEAR_ELASTIC_DIR"
ELASTIC_RANK_ENV = "DEAR_ELASTIC_RANK"
ELASTIC_WORLD_ENV = "DEAR_ELASTIC_WORLD"
ELASTIC_REJOIN_ENV = "DEAR_ELASTIC_REJOIN"
#: slice-granular fleets: rank ids are SLICE-ALIGNED by contract
#: (``slice = rank // ranks_per_slice``) — the supervisor exports the
#: value so `resilience.membership.ElasticCluster.from_env` widens
#: failures to whole slices, and mints scale-up ids on slice boundaries
ELASTIC_RPS_ENV = "DEAR_ELASTIC_RANKS_PER_SLICE"


def _repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _import_scale():
    """The policy lives in the package (`resilience.scale`) so its
    counters are audited with everything else; the supervisor is runnable
    from anywhere, so bootstrap the repo root onto sys.path first."""
    repo = _repo_root()
    if repo not in sys.path:
        sys.path.insert(0, repo)
    from dear_pytorch_tpu.resilience import scale

    return scale


def _import_sdc():
    """`resilience.sdc` is jax-free at module scope (the self-test imports
    jax lazily, and runs in a subprocess anyway) — safe for the
    supervisor's no-jax parent process."""
    repo = _repo_root()
    if repo not in sys.path:
        sys.path.insert(0, repo)
    from dear_pytorch_tpu.resilience import sdc

    return sdc


class ElasticSupervisor:
    """Supervise one elastic process cluster on this host."""

    def __init__(
        self,
        nprocs: int,
        command: List[str],
        *,
        elastic_dir: str,
        env: Optional[dict] = None,
        max_relaunches: int = 2,
        relaunch_window_s: Optional[float] = None,
        relaunch_delay_s: float = 0.5,
        policy=None,
        ranks_per_slice: Optional[int] = None,
        log=lambda s: print(s, file=sys.stderr, flush=True),
    ):
        if nprocs < 1:
            raise ValueError(f"nprocs must be >= 1, got {nprocs}")
        if not command:
            raise ValueError("empty worker command")
        if ranks_per_slice is not None:
            ranks_per_slice = int(ranks_per_slice)
            if ranks_per_slice < 1 or nprocs % ranks_per_slice:
                raise ValueError(
                    f"nprocs={nprocs} must be a whole number of slices "
                    f"of {ranks_per_slice} ranks")
        self.ranks_per_slice = ranks_per_slice
        self.nprocs = int(nprocs)
        self.command = list(command)
        self.elastic_dir = os.path.abspath(elastic_dir)
        self.base_env = dict(os.environ if env is None else env)
        self.max_relaunches = int(max_relaunches)
        self.relaunch_window_s = (
            None if relaunch_window_s is None else float(relaunch_window_s))
        self.relaunch_delay_s = float(relaunch_delay_s)
        self.policy = policy
        self._log = log
        self._procs: Dict[int, subprocess.Popen] = {}
        self._final_rc: Dict[int, int] = {}   # rank -> exit of its LAST run
        self.relaunches: Dict[int, int] = {r: 0 for r in range(self.nprocs)}
        self._relaunch_times: Dict[int, List[float]] = {}
        self._draining: set = set()      # ranks SIGTERMed by the policy
        self._backfill: List[int] = []   # drained ranks eligible to respawn
        self._finished: set = set()      # ranks that completed cleanly
        self._ever_ranks: set = set(range(self.nprocs))
        self.events: List[tuple] = []    # (what, rank) policy/churn audit
        self._pid_dir = os.path.join(self.elastic_dir, "supervisor", "pids")
        os.makedirs(self._pid_dir, exist_ok=True)
        # -- SDC quarantine (docs/RESILIENCE.md "SDC sentinel"): the
        # supervisor owns HOST IDENTITY. Rank ids are seats; strikes and
        # convictions in the SDC ledger are charged to the host a seat is
        # on, so a relaunched rank on the same host INHERITS its ledger
        # state. The pool is persisted under <dir>/supervisor/hosts/<rank>
        # so identity survives a supervisor restart, and each spawn
        # exports it as DEAR_SDC_HOST.
        self.sdc_active = self.base_env.get("DEAR_SDC", "") == "1"
        self._host_dir = os.path.join(self.elastic_dir, "supervisor",
                                      "hosts")
        os.makedirs(self._host_dir, exist_ok=True)
        self._hosts: Dict[int, str] = {}
        for name in os.listdir(self._host_dir):
            try:
                with open(os.path.join(self._host_dir, name)) as f:
                    self._hosts[int(name)] = f.read().strip()
            except (ValueError, OSError):
                continue
        self._host_seq = 0
        self._ledger = None              # lazy resilience.sdc.SdcLedger
        self._probation: Dict[str, subprocess.Popen] = {}
        self._probation_done: set = set()  # hosts ever sent to probation

    # -- host identity & the SDC quarantine ledger ---------------------------

    def _mint_host(self) -> str:
        """A fresh host id no seat has ever used (stand-in for asking the
        cluster manager for a different machine)."""
        used = set(self._hosts.values())
        while True:
            self._host_seq += 1
            host = f"host-{self._host_seq}"
            if host not in used:
                return host

    def _set_host(self, rank: int, host: str) -> None:
        self._hosts[rank] = host
        with open(os.path.join(self._host_dir, str(rank)), "w") as f:
            f.write(host)

    def ledger(self):
        """The durable quarantine ledger (first-writer-wins records under
        <dir>/sdc) — the same store every worker rank appends to."""
        if self._ledger is None:
            sdc = _import_sdc()
            root = self.base_env.get(sdc.LEDGER_ENV) or os.path.join(
                self.elastic_dir, "sdc")
            self._ledger = sdc.ledger_from_dir(root)
        return self._ledger

    def _seat_host(self, rank: int) -> str:
        """The host a seat will run on next. A quarantined host is NEVER
        re-seated: the ledger is consulted before every (re)launch and a
        convicted host is swapped for a fresh one — it can only come back
        through the probation self-test, and even then only via a worker's
        own rejoin gate."""
        host = self._hosts.get(rank)
        if host is None:
            host = self._mint_host()
            self._set_host(rank, host)
        if self.sdc_active and self.ledger().quarantined(host):
            fresh = self._mint_host()
            self._log(
                f"supervisor: host {host} (rank {rank}) is quarantined in "
                f"the SDC ledger — re-seating on fresh host {fresh}")
            self.events.append(("sdc_reseat", rank))
            self._start_probation(host)
            self._set_host(rank, fresh)
            host = fresh
        return host

    def _start_probation(self, host: str) -> None:
        """Kick off the known-answer self-test for a quarantined host,
        once per host, without blocking supervision: a subprocess runs
        `resilience.sdc --selftest` and writes the readmission record
        itself iff the burn-in passes."""
        if not self.sdc_active or host in self._probation_done:
            return
        self._probation_done.add(host)
        sdc = _import_sdc()
        root = self.base_env.get(sdc.LEDGER_ENV) or os.path.join(
            self.elastic_dir, "sdc")
        env = dict(self.base_env)
        env["PYTHONPATH"] = _repo_root() + os.pathsep + env.get(
            "PYTHONPATH", "")
        env[sdc.HOST_ENV] = host
        proc = subprocess.Popen(
            [sys.executable, "-m", "dear_pytorch_tpu.resilience.sdc",
             "--selftest", "--ledger", root, "--host", host],
            env=env)
        self._probation[host] = proc
        self.events.append(("sdc_probation", host))
        self._log(f"supervisor: probation self-test started for "
                  f"quarantined host {host} pid={proc.pid}")

    def _reap_probation(self) -> None:
        for host, proc in list(self._probation.items()):
            rc = proc.poll()
            if rc is None:
                continue
            del self._probation[host]
            if rc == 0:
                self.events.append(("sdc_readmit", host))
                self._log(f"supervisor: host {host} passed the probation "
                          "self-test — readmitted in the SDC ledger")
            else:
                self.events.append(("sdc_probation_failed", host))
                self._log(f"supervisor: host {host} FAILED the probation "
                          f"self-test rc={rc} — stays quarantined")

    # -- lifecycle -----------------------------------------------------------

    def _spawn(self, rank: int, *, rejoin: bool) -> None:
        env = dict(self.base_env)
        env[ELASTIC_DIR_ENV] = self.elastic_dir
        env[ELASTIC_RANK_ENV] = str(rank)
        env[ELASTIC_WORLD_ENV] = str(self.nprocs)
        env["DEAR_SDC_HOST"] = self._seat_host(rank)
        if self.ranks_per_slice is not None:
            env[ELASTIC_RPS_ENV] = str(self.ranks_per_slice)
        if rejoin:
            env[ELASTIC_REJOIN_ENV] = "1"
        else:
            env.pop(ELASTIC_REJOIN_ENV, None)
        proc = subprocess.Popen(self.command, env=env)
        self._procs[rank] = proc
        self._ever_ranks.add(rank)
        self.relaunches.setdefault(rank, 0)
        with open(os.path.join(self._pid_dir, str(rank)), "w") as f:
            f.write(str(proc.pid))
        self._log(
            f"supervisor: rank {rank} "
            f"{'RELAUNCHED (rejoin)' if rejoin else 'launched'} "
            f"pid={proc.pid}")

    def start(self) -> "ElasticSupervisor":
        for rank in range(self.nprocs):
            self._spawn(rank, rejoin=False)
        return self

    def pid(self, rank: int) -> Optional[int]:
        proc = self._procs.get(rank)
        return proc.pid if proc is not None else None

    # -- relaunch budget -----------------------------------------------------

    def _budget_ok(self, rank: int) -> bool:
        """Per-rank sliding-window relaunch budget: at most
        ``max_relaunches`` within the trailing ``relaunch_window_s``. With
        no window, the legacy lifetime cap (which a long-running service
        exhausts by design — prefer the window)."""
        if self.relaunch_window_s is None:
            return self.relaunches.get(rank, 0) < self.max_relaunches
        now = time.monotonic()
        times = [t for t in self._relaunch_times.get(rank, [])
                 if now - t < self.relaunch_window_s]
        self._relaunch_times[rank] = times
        return len(times) < self.max_relaunches

    def _relaunch(self, rank: int) -> None:
        self.relaunches[rank] = self.relaunches.get(rank, 0) + 1
        self._relaunch_times.setdefault(rank, []).append(time.monotonic())
        self.events.append(("relaunch", rank))
        time.sleep(self.relaunch_delay_s)
        self._spawn(rank, rejoin=True)

    # -- policy actions ------------------------------------------------------

    def drain(self, rank: int) -> bool:
        """Planned removal: SIGTERM so the worker's `PreemptionHandler`
        turns the exit into an emergency save + a planned membership
        shrink inside the grace window. A clean exit of a draining rank
        is recorded for backfill, not treated as 'finished'."""
        proc = self._procs.get(rank)
        if proc is None:
            return False
        self._draining.add(rank)
        self.events.append(("drain", rank))
        self._log(f"supervisor: draining rank {rank} (SIGTERM, planned "
                  "shrink inside the preemption grace window)")
        try:
            proc.send_signal(signal.SIGTERM)
        except OSError:
            return False
        return True

    def scale_up(self, count: int) -> List[int]:
        """Spawn ``count`` additional ranks: drained ranks are backfilled
        first (stable ids, bounded rank space), then fresh ids beyond
        every rank ever used — admitted by the fleet as scale-UP epochs."""
        spawned = []
        for _ in range(max(int(count), 0)):
            if self._backfill:
                rank = self._backfill.pop(0)
            else:
                # dense minting keeps the slice-aligned rank-id contract
                # (slice = rank // ranks_per_slice) by construction: ids
                # are consecutive from a whole-number-of-slices initial
                # world (validated above), so a fresh slice always starts
                # exactly on a slice boundary
                rank = max(self._ever_ranks) + 1
            self.events.append(("scale_up", rank))
            self._spawn(rank, rejoin=True)
            spawned.append(rank)
        return spawned

    def _policy_tick(self) -> None:
        if self.policy is None or not self._procs or self._finished:
            # the policy scales a LIVE service: a fully-exited fleet is
            # finished, not under-capacity — and the moment ANY rank
            # completes cleanly (not drained) the job is wrapping up, so
            # the policy stands down rather than "backfilling" completed
            # work (observed: the fleet's staggered lockstep exits left a
            # live<target window that spawned ghost ranks which then
            # waited out their whole rejoin timeout against a dead fleet)
            return
        live = tuple(sorted(self._procs))
        quarantined = (len(self.ledger().quarantined_hosts())
                       if self.sdc_active else 0)
        decision = self.policy.decide(
            live_world=len(live), live_ranks=live,
            draining=tuple(sorted(self._draining & set(live))),
            quarantined=quarantined)
        if decision is None:
            return
        if decision.kind == "scale_up":
            self.scale_up(decision.count)
        else:  # "drain" / "scale_down"
            for rank in decision.ranks:
                self.drain(rank)

    # -- the supervision loop ------------------------------------------------

    def poll(self) -> bool:
        """One supervision pass: reap exits, relaunch failures, run the
        scale policy. Returns True while any rank is still running (or
        pending relaunch)."""
        for rank, proc in list(self._procs.items()):
            rc = proc.poll()
            if rc is None:
                continue
            del self._procs[rank]
            self._final_rc[rank] = rc
            if rank in self._draining:
                self._draining.discard(rank)
                if rc == 0:
                    self._log(f"supervisor: rank {rank} drained cleanly; "
                              "eligible for backfill")
                    self.events.append(("drained", rank))
                else:
                    # a dirty drain (crash inside the grace window) is
                    # still a DRAIN: the policy asked for this rank's
                    # removal, so relaunching it would override the
                    # capacity decision and burn its relaunch budget —
                    # it stays out until the policy backfills it
                    self._log(f"supervisor: draining rank {rank} exited "
                              f"rc={rc} (dirty drain; not relaunched — "
                              "eligible for backfill)")
                    self.events.append(("drained_dirty", rank))
                    self._final_rc[rank] = 0  # a requested removal is
                    #                           not a job failure
                host = self._hosts.get(rank)
                if self.sdc_active and host \
                        and self.ledger().quarantined(host):
                    # the seat is now empty and its host sits in the
                    # quarantine ledger: the scale policy holds the
                    # backfill (capacity cap) until a readmission, so
                    # the probation self-test must start NOW — waiting
                    # for a re-seat attempt would deadlock against the
                    # cap that quarantine itself imposes
                    self._start_probation(host)
                self._backfill.append(rank)
                continue
            if rc == 75:  # resilience.sdc.QUARANTINE_RC: the worker
                # convicted its OWN host in the ledger, committed a
                # planned membership shrink, and exited for backfill — a
                # requested removal, so no relaunch budget is burned. The
                # seat respawns immediately; `_seat_host` sees the
                # quarantined host and swaps in a fresh one (and starts
                # the old host's probation self-test).
                self._log(
                    f"supervisor: rank {rank} exited rc=75 (SDC "
                    "quarantine drain); respawning the seat on a fresh "
                    "host")
                self.events.append(("sdc_quarantine", rank))
                self._final_rc[rank] = 0
                time.sleep(self.relaunch_delay_s)
                self._spawn(rank, rejoin=True)
                continue
            if rc == 0:
                self._log(f"supervisor: rank {rank} finished cleanly")
                self._finished.add(rank)
                continue
            if not self._budget_ok(rank):
                window = ("lifetime" if self.relaunch_window_s is None
                          else f"{self.relaunch_window_s:.0f}s window")
                self._log(
                    f"supervisor: rank {rank} exited rc={rc}; relaunch "
                    f"budget ({self.max_relaunches} per {window}) "
                    "exhausted — giving up")
                continue
            self._log(
                f"supervisor: rank {rank} exited rc={rc}; relaunching with "
                f"{ELASTIC_REJOIN_ENV}=1 "
                f"({self.relaunches.get(rank, 0) + 1}/{self.max_relaunches})"
                f" in {self.relaunch_delay_s:.1f}s")
            self._relaunch(rank)
        self._reap_probation()
        self._policy_tick()
        return bool(self._procs)

    def wait(self, deadline_s: Optional[float] = None, poll_s: float = 0.2,
             ) -> int:
        """Supervise until every rank has finished (rc 0 or budget
        exhausted) or the deadline expires (everything still alive is
        killed). Returns 0 iff every rank's FINAL run exited 0."""
        t_end = (None if deadline_s is None
                 else time.monotonic() + float(deadline_s))
        while self.poll():
            if t_end is not None and time.monotonic() >= t_end:
                self._log(
                    f"supervisor: deadline {deadline_s:.0f}s expired with "
                    f"rank(s) {sorted(self._procs)} still alive — killing")
                self.kill_all()
                for rank, proc in list(self._procs.items()):
                    self._final_rc[rank] = proc.wait()
                self._procs.clear()
                return 124
            time.sleep(poll_s)
        # the fleet is done; give any in-flight probation self-test a
        # bounded window to write its readmission record (it is a short
        # known-answer burn-in, not a training job)
        for host, proc in list(self._probation.items()):
            try:
                proc.wait(timeout=120)
            except subprocess.TimeoutExpired:
                proc.kill()
        self._reap_probation()
        bad = {r: rc for r, rc in self._final_rc.items() if rc != 0}
        if bad:
            self._log(f"supervisor: failed rank exits: {bad}")
            return 1
        return 0

    def kill_all(self, sig: int = signal.SIGKILL) -> None:
        for proc in self._procs.values():
            try:
                proc.send_signal(sig)
            except OSError:
                pass


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="elastic rank supervisor (see module docstring)")
    ap.add_argument("--nprocs", type=int, required=True)
    ap.add_argument("--dir", required=True,
                    help="elastic coordination dir (FileTransport root)")
    ap.add_argument("--relaunch-budget", "--max-relaunches",
                    dest="relaunch_budget", type=int, default=2,
                    help="relaunch budget PER RANK (default 2) — within "
                         "--relaunch-window when set, else lifetime "
                         "(--max-relaunches is the legacy alias)")
    ap.add_argument("--relaunch-window", type=float, default=None,
                    metavar="SECS",
                    help="sliding window for the per-rank budget; unset = "
                         "legacy lifetime cap (a long-running service "
                         "should always set this)")
    ap.add_argument("--relaunch-delay", type=float, default=0.5)
    ap.add_argument("--ranks-per-slice", type=int, default=None,
                    help="slice-granular fleet: rank ids are "
                         "slice-aligned (slice = rank // N), failures "
                         "widen to whole slices, scale-ups mint "
                         "slice-boundary ids (exported as "
                         "DEAR_ELASTIC_RANKS_PER_SLICE)")
    ap.add_argument("--capacity-file", default=None,
                    help="watched capacity-hint JSON (spot-pool stand-in); "
                         "enables the ScalePolicy loop "
                         "(DEAR_CAPACITY_FILE also works)")
    ap.add_argument("--max-world", type=int, default=None,
                    help="ScalePolicy ceiling on the fleet size")
    ap.add_argument("--deadline", type=float, default=None,
                    help="overall wall-clock budget in seconds")
    ap.add_argument("command", nargs=argparse.REMAINDER,
                    help="-- worker command...")
    args = ap.parse_args(argv)
    command = args.command
    if command and command[0] == "--":
        command = command[1:]
    if not command:
        ap.error("missing worker command (pass it after --)")
    policy = None
    capacity = args.capacity_file or os.environ.get("DEAR_CAPACITY_FILE")
    if capacity:
        policy = _import_scale().ScalePolicy(
            capacity_file=capacity, max_world=args.max_world)
    sup = ElasticSupervisor(
        args.nprocs, command, elastic_dir=args.dir,
        max_relaunches=args.relaunch_budget,
        relaunch_window_s=args.relaunch_window,
        relaunch_delay_s=args.relaunch_delay,
        policy=policy,
        ranks_per_slice=args.ranks_per_slice,
    ).start()
    try:
        return sup.wait(args.deadline)
    except KeyboardInterrupt:
        sup.kill_all(signal.SIGTERM)
        return 130


if __name__ == "__main__":
    sys.exit(main())
