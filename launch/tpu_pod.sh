#!/usr/bin/env bash
# Launch a command on every host of a TPU pod slice (one process per host).
# TPU-native replacement for the reference's mpirun launcher
# (dear/horovod_mpi_cj.sh): no hostfile, no NCCL env — peers are discovered
# from slice metadata by jax.distributed.initialize inside dear.init().
#
# Usage:
#   ./launch/tpu_pod.sh <tpu-name> <zone> [--project <p>] -- <command...>
set -euo pipefail

if [ "$#" -lt 4 ]; then
    echo "usage: $0 <tpu-name> <zone> [--project <p>] -- <command...>" >&2
    exit 2
fi

TPU_NAME=$1; ZONE=$2; shift 2
PROJECT_ARG=()
if [ "${1:-}" = "--project" ]; then
    PROJECT_ARG=(--project "$2"); shift 2
fi
[ "${1:-}" = "--" ] && shift

# Run from the repo checkout on each worker; DEAR_* env vars present in the
# local shell are forwarded (the launcher-facing config layer, config.py),
# each value shell-quoted so spaces/metacharacters survive the ssh command.
DEAR_ENV=""
while IFS='=' read -r k v; do
    DEAR_ENV+="export ${k}=$(printf %q "$v"); "
done < <(env | grep '^DEAR_[A-Z_]*=' || true)

CMD=$(printf '%q ' "$@")  # preserve argument quoting on the remote shell

exec gcloud compute tpus tpu-vm ssh "$TPU_NAME" \
    --zone="$ZONE" "${PROJECT_ARG[@]}" --worker=all \
    --command="${DEAR_ENV} cd \$HOME/dear_pytorch_tpu && ${CMD}"
