"""Telemetry subsystem tests: tracer semantics, disabled fast path, static
counter accounting against a known FusionPlan, overlap-audit math on a
synthetic α-β model, and the telemetry block's round-trips through
`read_metrics` and the batch driver's log scrape."""

import json
import threading
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dear_pytorch_tpu.observability import counters as CTR
from dear_pytorch_tpu.observability import overlap as OV
from dear_pytorch_tpu.observability import tracer as T
from dear_pytorch_tpu.ops import fusion as F


@pytest.fixture(autouse=True)
def _isolate_global_tracer():
    """Every test leaves the process-global tracer as it found it."""
    old = T._tracer
    yield
    T.set_tracer(old)


# ---------------------------------------------------------------------------
# tracer
# ---------------------------------------------------------------------------


def test_span_nesting_depth_and_order():
    mem = T.MemoryExporter()
    tr = T.Tracer([mem])
    with tr.span("outer", phase="a"):
        with tr.span("inner"):
            pass
        with tr.span("inner2"):
            pass
    # children finish (and export) before the parent
    assert [s.name for s in mem.spans] == ["inner", "inner2", "outer"]
    by_name = {s.name: s for s in mem.spans}
    assert by_name["outer"].depth == 0
    assert by_name["inner"].depth == 1
    assert by_name["inner2"].depth == 1
    assert by_name["outer"].attrs == {"phase": "a"}
    assert by_name["outer"].dur_us >= by_name["inner"].dur_us


def test_tracer_thread_safety():
    mem = T.MemoryExporter()
    tr = T.Tracer([mem])
    n_threads, n_iter = 8, 200
    gate = threading.Barrier(n_threads)  # overlap all threads: distinct
    # OS idents (Python reuses idents of finished threads otherwise)

    def work():
        gate.wait()
        for _ in range(n_iter):
            tr.count("steps")
            tr.count("bytes", 2.5)
            with tr.span("w"):
                pass

    threads = [threading.Thread(target=work) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    counters = tr.counters()
    assert counters["steps"] == n_threads * n_iter
    assert counters["bytes"] == pytest.approx(2.5 * n_threads * n_iter)
    assert len(mem.spans) == n_threads * n_iter
    # every worker thread got its own small tid; depth never leaked across
    assert len({s.tid for s in mem.spans}) == n_threads
    assert {s.depth for s in mem.spans} == {0}


def test_disabled_null_tracer_fast_path():
    tr = T.NullTracer()
    assert tr.enabled is False
    # zero-allocation: every span() call returns the one shared null CM
    assert tr.span("a") is tr.span("b", x=1)
    with tr.span("a"):
        pass
    tr.count("anything", 7)
    tr.event("whatever")
    assert tr.counters() == {}
    with pytest.raises(RuntimeError):
        tr.add_exporter(T.MemoryExporter())


def test_configure_from_env_grammar(tmp_path):
    T.set_tracer(None)
    assert isinstance(T.configure_from_env(""), T.NullTracer)
    T.set_tracer(None)
    assert isinstance(T.configure_from_env("0"), T.NullTracer)
    T.set_tracer(None)
    assert isinstance(T.configure_from_env("1"), T.Tracer)
    T.set_tracer(None)
    tr = T.configure_from_env(f"jsonl:{tmp_path}/t.jsonl")
    assert isinstance(tr, T.Tracer)
    tr.close()
    T.set_tracer(None)
    with pytest.raises(ValueError):
        T.configure_from_env("bogus:/x")
    # a second resolve is a no-op returning the installed tracer
    first = T.configure_from_env("1")
    assert T.configure_from_env("0") is first


def test_chrome_exporter_roundtrip(tmp_path):
    path = str(tmp_path / "trace.json")
    tr = T.Tracer([T.ChromeTraceExporter(path)])
    with tr.span("step", mode="dear"):
        pass
    tr.event("rebuild", buckets=3)
    tr.close()
    data = json.load(open(path))
    names = {e["name"] for e in data["traceEvents"]}
    assert {"step", "rebuild"} <= names
    span = next(e for e in data["traceEvents"] if e["name"] == "step")
    assert span["ph"] == "X" and span["args"] == {"mode": "dear"}


def test_jsonl_exporter_roundtrip_read_metrics(tmp_path):
    from dear_pytorch_tpu.utils import read_metrics

    path = str(tmp_path / "tel.jsonl")
    tr = T.Tracer([T.JsonlExporter(path)])
    with tr.span("pack", bucket=2):
        pass
    tr.event("compile", n=1)
    tr.close()
    recs = read_metrics(path)
    kinds = [r["kind"] for r in recs]
    assert kinds == ["span", "event"]
    assert recs[0]["name"] == "pack" and recs[0]["bucket"] == 2
    assert recs[0]["dur_us"] >= 0
    assert recs[1]["name"] == "compile" and recs[1]["n"] == 1


def test_snapshot_aggregates():
    tr = T.configure()
    with tr.span("step"):
        pass
    with tr.span("step"):
        pass
    tr.count("steps", 2)
    snap = T.snapshot()
    assert snap["enabled"] is True
    assert snap["counters"]["steps"] == 2
    assert snap["spans"]["step"]["count"] == 2
    assert json.loads(json.dumps(snap)) == snap  # JSON-safe


# ---------------------------------------------------------------------------
# counters: static accounting against a known plan
# ---------------------------------------------------------------------------


def _known_plan(world=4):
    # layer a: 110 elems (f32), layer b: 100 elems -> one bucket per layer
    params = {"a": {"w": jnp.zeros((10, 10)), "b": jnp.zeros((10,))},
              "b": {"w": jnp.zeros((10, 10))}}
    return F.plan_by_nearby_layers(params, world=world, k=1)


def test_plan_comm_accounting_dear():
    plan = _known_plan()
    acct = CTR.plan_comm_accounting(plan, mode="dear", comm_itemsize=4)
    # bucket 0: 110 elems padded to 112 (world=4) -> 448 B payload;
    # bucket 1: 100 elems, no pad -> 400 B
    assert [r.leg for r in acct.rows] == [
        "reduce_scatter", "all_gather", "reduce_scatter", "all_gather"]
    assert [r.payload_bytes for r in acct.rows] == [448, 448, 400, 400]
    ring = 3 / 4
    assert [r.wire_bytes for r in acct.rows] == [
        448 * ring, 448 * ring, 400 * ring, 400 * ring]
    assert acct.payload_bytes_per_step == 1696
    assert acct.leg_bytes_per_step("all_gather") == 848
    totals = acct.totals(steps=5, runtime_counters={})
    assert totals["per_leg"]["reduce_scatter"]["payload_bytes"] == 848 * 5


def test_plan_comm_accounting_modes_and_dtypes():
    plan = _known_plan()
    ar = CTR.plan_comm_accounting(plan, mode="allreduce", comm_itemsize=2)
    assert [r.leg for r in ar.rows] == ["all_reduce", "all_reduce"]
    assert ar.rows[0].payload_bytes == 112 * 2
    assert ar.rows[0].wire_bytes == pytest.approx(112 * 2 * 2 * 3 / 4)
    # dear with bf16 grads and f32 gathers: per-leg itemsize differs
    mixed = CTR.plan_comm_accounting(plan, mode="dear", comm_itemsize=2,
                                     gather_itemsize=4)
    by_leg = {r.leg: r.payload_bytes for r in mixed.rows if r.bucket == 0}
    assert by_leg == {"reduce_scatter": 224, "all_gather": 448}
    # compressed gradient leg: priced at the BUFFER itemsize (f32 leaves
    # here), NOT comm_itemsize — the execution path casts back to the
    # buffer dtype before compressing, so a narrower comm dtype never
    # shrinks the compressed payload
    qa = CTR.plan_comm_accounting(plan, mode="dear", comm_itemsize=2,
                                  gather_itemsize=4, compressor="qint8")
    qleg = {r.leg: r for r in qa.rows if r.bucket == 0}
    assert qleg["reduce_scatter"].payload_bytes == round(
        112 * 4 * (112 + 4) / (112 * 4))          # ~1 B/coord + scale
    assert qleg["all_gather"].payload_bytes == 448  # AG leg stays dense
    # world=1 plans carry zero wire bytes (collectives are local copies)
    p1 = F.plan_by_nearby_layers({"a": jnp.zeros((8,))}, world=1, k=1)
    acct1 = CTR.plan_comm_accounting(p1, mode="dear")
    assert all(r.wire_bytes == 0.0 for r in acct1.rows)
    with pytest.raises(ValueError):
        CTR.plan_comm_accounting(plan, mode="nonesuch")


# ---------------------------------------------------------------------------
# overlap: audit math on a synthetic alpha-beta model
# ---------------------------------------------------------------------------


class _StubTS(NamedTuple):
    plan: Any
    mesh: Any = None

    def lower(self, state, batch):  # the audit degrades without a compile
        raise RuntimeError("no backend in this test")


def _one_bucket_plan(world=4, elems=1000):
    return F.plan_by_nearby_layers(
        {"w": jnp.zeros((elems,))}, world=world, k=1)


def test_predict_leg_times_matches_perf_model():
    from dear_pytorch_tpu.utils import perf_model

    alpha, beta = 1e-3, 1e-6
    plan = _one_bucket_plan()
    acct = CTR.plan_comm_accounting(plan, mode="dear", comm_itemsize=4)
    times = OV.predict_leg_times(acct, alpha, beta)
    # each ring leg == the repo's allgather cost model, by construction
    expected = perf_model.allgather_perf_model(4000, 4, alpha, beta)
    assert times == pytest.approx([expected, expected])


def test_audit_math_synthetic():
    alpha, beta = 1e-3, 1e-6
    ts = _StubTS(plan=_one_bucket_plan())
    rep = OV.audit_train_step(
        ts, None, None, alpha=alpha, beta=beta, mode="dear",
        measured_step_s=16e-3, compute_time_s=10e-3, include_hlo=False,
    )
    # rs = ag = 3*(1e-3 + 1e-6*1000) = 6e-3 each -> comm 12e-3
    assert rep.comm_time_s == pytest.approx(12e-3)
    assert rep.serial_step_s == pytest.approx(22e-3)
    assert rep.ideal_step_s == pytest.approx(12e-3)
    assert rep.exposed_comm_s == pytest.approx(6e-3)
    assert rep.hidden_comm_s == pytest.approx(6e-3)
    assert rep.overlap_efficiency == pytest.approx(0.6)
    assert rep.model_note is None
    # per-leg attribution is proportional and sums back to the totals
    assert sum(leg.exposed_s for leg in rep.legs) == pytest.approx(6e-3)
    assert sum(leg.hidden_s for leg in rep.legs) == pytest.approx(6e-3)
    assert json.loads(json.dumps(rep.to_dict()))["mode"] == "dear"


def test_audit_clips_and_notes_model_mismatch():
    ts = _StubTS(plan=_one_bucket_plan())
    # measured beats the ideal -> saturated efficiency + an honest note
    rep = OV.audit_train_step(
        ts, None, None, alpha=1e-3, beta=1e-6, mode="dear",
        measured_step_s=5e-3, compute_time_s=10e-3, include_hlo=False,
    )
    assert rep.overlap_efficiency == 1.0
    assert rep.exposed_comm_s == 0.0
    assert "beat the modeled ideal" in rep.model_note
    # measured worse than fully serial -> clipped to 0 + note
    rep = OV.audit_train_step(
        ts, None, None, alpha=1e-3, beta=1e-6, mode="dear",
        measured_step_s=50e-3, compute_time_s=10e-3, include_hlo=False,
    )
    assert rep.overlap_efficiency == 0.0
    assert "exceeds the serial model" in rep.model_note
    # no measurement -> exposure split honestly absent, never guessed
    rep = OV.audit_train_step(
        ts, None, None, alpha=1e-3, beta=1e-6, mode="dear",
        include_hlo=False,
    )
    assert rep.exposed_comm_s is None and rep.overlap_efficiency is None


def test_render_text_and_comparison():
    from dear_pytorch_tpu.observability import report as R

    ts = _StubTS(plan=_one_bucket_plan())
    rep = OV.audit_train_step(
        ts, None, None, alpha=1e-3, beta=1e-6, mode="dear",
        measured_step_s=16e-3, compute_time_s=10e-3, include_hlo=False,
    )
    text = R.render_text(rep)
    assert "overlap audit: mode=dear" in text
    assert "reduce_scatter" in text and "all_gather" in text
    cmp_text = R.render_comparison({"dear": rep, "allreduce": rep})
    assert "mode comparison" in cmp_text and "allreduce" in cmp_text
    tel = R.render_telemetry({"enabled": True, "counters": {"steps": 3},
                              "spans": {"s": {"count": 1,
                                              "total_us": 12.0}}})
    assert "steps = 3" in tel


# ---------------------------------------------------------------------------
# instrumentation: the train step feeds the tracer
# ---------------------------------------------------------------------------


def test_dear_step_counters_and_spans(mesh):
    from dear_pytorch_tpu.ops.fused_sgd import fused_sgd
    from dear_pytorch_tpu.parallel import build_train_step

    mem = T.MemoryExporter()
    tr = T.Tracer([mem])
    T.set_tracer(tr)

    params = {"l0": {"w": jnp.zeros((16, 16)), "b": jnp.zeros((16,))},
              "l1": {"w": jnp.zeros((16, 16))}}

    def loss(p, b):
        x = jnp.tanh(b @ p["l0"]["w"] + p["l0"]["b"])
        return jnp.mean((x @ p["l1"]["w"]) ** 2)

    ts = build_train_step(
        loss, params, mesh=mesh, mode="dear", nearby_layers=1,
        optimizer=fused_sgd(lr=0.01), donate=False,
    )
    state = ts.init(params)
    batch = jnp.ones((8, 16))
    for _ in range(3):
        state, _ = ts.step(state, batch)
    counters = tr.counters()
    assert counters["dear.plan_builds"] == 1
    assert counters["dear.steps"] == 3
    assert counters["dear.compiles"] == 1  # one structure -> one program
    acct = CTR.plan_comm_accounting(ts.plan, mode="dear", comm_itemsize=4)
    assert counters["dear.reduce_scatter_bytes"] == (
        3 * acct.leg_bytes_per_step("reduce_scatter"))
    assert counters["dear.all_gather_bytes"] == (
        3 * acct.leg_bytes_per_step("all_gather"))
    assert sum(1 for s in mem.spans if s.name == "dear.step") == 3
    assert any(e.name == "dear.plan_built" for e in mem.events)

    # disabled tracer: the same step path must not record anything
    T.set_tracer(T.NullTracer())
    state, _ = ts.step(state, batch)
    assert sum(1 for s in mem.spans if s.name == "dear.step") == 3


def test_pipeline_span(monkeypatch):
    from dear_pytorch_tpu.runtime import pipeline as P

    mem = T.MemoryExporter()
    T.set_tracer(T.Tracer([mem]))
    pipe = P.NumpyPipeline(P.mnist_spec(4), seed=0)
    batch = pipe.next()
    assert batch["image"].shape == (4, 28, 28, 1)
    assert [s.name for s in mem.spans] == ["pipeline.next"]
    assert T.get_tracer().counters()["pipeline.batches"] == 1


# ---------------------------------------------------------------------------
# telemetry block round-trips
# ---------------------------------------------------------------------------


def test_telemetry_roundtrip_metrics_and_driver(tmp_path):
    from dear_pytorch_tpu.benchmarks import driver
    from dear_pytorch_tpu.utils import MetricsLogger, read_metrics

    snap = {"enabled": True,
            "counters": {"dear.steps": 10, "dear.compiles": 1},
            "spans": {"dear.step": {"count": 10, "total_us": 123.4}}}

    # JSONL leg: the runner writes the block as a JSON string scalar
    mpath = str(tmp_path / "m.jsonl")
    with MetricsLogger(mpath) as ml:
        ml.log(step=9, loss=0.5)
        ml.log(kind="telemetry", telemetry=json.dumps(snap))
    recs = read_metrics(mpath)
    assert json.loads(recs[-1]["telemetry"]) == snap

    # driver leg: the TELEMETRY line is scraped from a cell log
    log = tmp_path / "cell.log"
    log.write_text(
        "Running benchmark...\n"
        f"TELEMETRY {json.dumps(snap)}\n"
        "Total img/sec on 8 CPU(s): 1234.5 +-10.0\n"
    )
    assert driver.extract_telemetry(str(log)) == snap
    assert driver.extract_log(str(log)) == (1234.5, 10.0)
    assert driver.extract_telemetry(str(tmp_path / "missing.log")) is None
    # unparsable telemetry is absent, not fatal
    bad = tmp_path / "bad.log"
    bad.write_text("TELEMETRY {not json}\n")
    assert driver.extract_telemetry(str(bad)) is None


def test_runner_emits_telemetry_line(capsys, tmp_path):
    from dear_pytorch_tpu.benchmarks import runner
    from dear_pytorch_tpu.utils import MetricsLogger, read_metrics

    T.configure()
    T.get_tracer().count("dear.steps", 4)
    mpath = str(tmp_path / "m.jsonl")
    with MetricsLogger(mpath) as ml:
        runner.run_timed(
            lambda: None, batch_size=1, num_warmup_batches=0,
            num_batches_per_iter=1, num_iters=1, metrics=ml,
        )
    line = next(ln for ln in capsys.readouterr().out.splitlines()
                if ln.startswith("TELEMETRY "))
    snap = json.loads(line[len("TELEMETRY "):])
    assert snap["counters"]["dear.steps"] == 4
    recs = read_metrics(mpath)
    tel = [r for r in recs if r.get("kind") == "telemetry"]
    assert len(tel) == 1 and json.loads(tel[0]["telemetry"]) == snap


def test_run_timed_respects_health_warmup_env(monkeypatch):
    from dear_pytorch_tpu.benchmarks import runner
    from dear_pytorch_tpu.observability import anomaly as AN

    T.configure()
    built = []
    real = AN.AnomalyMonitor.from_env.__func__

    def spy(cls, **kw):
        m = real(cls, **kw)
        built.append(m)
        return m

    monkeypatch.setattr(AN.AnomalyMonitor, "from_env", classmethod(spy))
    kwargs = dict(batch_size=1, num_warmup_batches=0,
                  num_batches_per_iter=1, num_iters=1)
    monkeypatch.delenv("DEAR_HEALTH_WARMUP", raising=False)
    runner.run_timed(lambda: None, **kwargs)
    assert built[-1].warmup == 2  # benchmark default: few iters, arm early
    monkeypatch.setenv("DEAR_HEALTH_WARMUP", "7")
    runner.run_timed(lambda: None, **kwargs)
    assert built[-1].warmup == 7  # the documented env knob wins


# ---------------------------------------------------------------------------
# overhead contract
# ---------------------------------------------------------------------------


def test_overhead_script_fast_and_green(capsys):
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "check_telemetry_overhead",
        os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "scripts",
            "check_telemetry_overhead.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    rc = mod.main(["--iters", "2000"])
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rc == 0 and out["ok"] is True
    # the acceptance bar: the disabled gates are far below 1% of any real
    # step (~1 ms step -> 10 us budget; each gate must sit under 1 us —
    # generous for this container: measured ~100-300 ns)
    assert out["disabled_ns_per_call"] < 1000.0
    assert out["flight_disabled_ns_per_call"] < 1000.0
    # the SDC sentinel's recurring host shape when DEAR_SDC is off (the
    # fingerprint itself is in-program, so this attribute check is the
    # entire disabled cost) sits under the same budget
    assert out["sdc_disabled_ns_per_call"] < 1000.0
    # the enabled flight record stays production-cheap too (micro-seconds)
    assert out["flight_enabled_ns_per_call"] < 100_000.0


# ---------------------------------------------------------------------------
# docs <-> code counter audit — now a dearlint rule on the shared scanner
# ---------------------------------------------------------------------------


def test_counter_docs_in_sync():
    """docs/OBSERVABILITY.md's counter tables are load-bearing: every
    counter the code emits must be documented, and every documented
    counter must exist in code — in both directions, so the tables can't
    rot (the `retry.attempts` incident: a counter documented before it
    was wired). The audit itself lives in the static-analysis suite
    (`analysis.rules_registry.CounterDocsRule`, docs/ANALYSIS.md) so the
    repo has ONE source-walking layer; this test drives that rule over
    the live tree and keeps the historical assertion surface."""
    from dear_pytorch_tpu.analysis.core import Scanner, repo_root
    from dear_pytorch_tpu.analysis.rules_registry import CounterDocsRule

    import os

    scanner = Scanner([os.path.join(repo_root(), "dear_pytorch_tpu")])
    findings = list(CounterDocsRule().check(scanner))
    # scanner-rot sentinels surface as findings too — an empty result
    # really means "both sides parsed and agree"
    assert not findings, "counter <-> docs audit violations:\n" + "\n".join(
        f.render() for f in findings)
