"""Fleet tracing (`observability.dtrace` + `observability.critical_path`
+ `costmodel.calibrate_from_traces` + `scripts/fleet_trace.py`): trace
contexts, the per-rank span stream and its env gate, clock-aligned
merging, the Perfetto export, critical-path attribution arithmetic,
trace-driven sim calibration, and the one-command collector CLI.

Everything here is jax-free by design (the collector contract) — these
tests import the modules directly, never through the package heavyweights.
"""

import json
import os

import pytest

from dear_pytorch_tpu.observability import costmodel
from dear_pytorch_tpu.observability import critical_path as CP
from dear_pytorch_tpu.observability import dtrace
from dear_pytorch_tpu.observability.redaction import REDACTED


@pytest.fixture(autouse=True)
def _restore_stream():
    yield
    dtrace.disable_stream()


# -- trace contexts ----------------------------------------------------------


def test_trace_context_roundtrip_and_child():
    ctx = dtrace.new_trace()
    assert ctx.parent is None
    child = ctx.child()
    assert child.trace_id == ctx.trace_id
    assert child.parent == ctx.span_id
    assert child.span_id != ctx.span_id
    back = dtrace.TraceContext.from_dict(child.to_dict())
    assert back == child
    assert dtrace.TraceContext.from_dict(None) is None
    assert dtrace.TraceContext.from_dict({"span_id": "x"}) is None


def test_step_trace_is_deterministic_and_epoch_scoped():
    assert dtrace.step_trace(0, 7).trace_id == "step-0-7"
    # the trace id is the coordination-free join key; each call's
    # span_id is fresh (every emission is its own span on that trace)
    assert (dtrace.step_trace(0, 7).trace_id
            == dtrace.step_trace(0, 7).trace_id)
    assert (dtrace.step_trace(1, 7).trace_id
            != dtrace.step_trace(2, 7).trace_id)


# -- the stream and its gate -------------------------------------------------


def test_env_gate_file_sink_rank_substitution(tmp_path, monkeypatch):
    monkeypatch.setenv(dtrace.TRACE_ENV,
                       str(tmp_path / "trace-{rank}.jsonl"))
    monkeypatch.setenv(dtrace.TRACE_RANK_ENV, "3")
    ds = dtrace._configure_from_env(refresh=True)
    assert ds.enabled and ds.rank == 3
    ds.emit("x.span", dur_s=0.001, cat="step")
    dtrace.disable_stream()          # flush + close
    recs = dtrace.read_stream(str(tmp_path / "trace-3.jsonl"))
    kinds = [r["kind"] for r in recs]
    assert kinds[0] == "meta" and "span" in kinds
    meta = recs[0]
    assert meta["rank"] == 3 and "off" in meta


def test_env_gate_off_and_strict_typo(monkeypatch):
    monkeypatch.setenv(dtrace.TRACE_ENV, "0")
    assert not dtrace._configure_from_env(refresh=True).enabled
    monkeypatch.setenv(dtrace.TRACE_ENV, "definitely-not-a-path")
    with pytest.raises(ValueError):
        dtrace._configure_from_env(refresh=True)


def test_non_numeric_rank_label(monkeypatch):
    monkeypatch.setenv(dtrace.TRACE_RANK_ENV, "router")
    ds = dtrace.SpanStream(dtrace.MemoryWriter())
    assert ds.rank == "router"


def test_elastic_rank_fallback(monkeypatch):
    monkeypatch.delenv(dtrace.TRACE_RANK_ENV, raising=False)
    monkeypatch.setenv("DEAR_ELASTIC_RANK", "5")
    ds = dtrace.SpanStream(dtrace.MemoryWriter())
    assert ds.rank == 5


def test_span_attrs_are_redacted_on_emit():
    mw = dtrace.MemoryWriter()
    ds = dtrace.SpanStream(mw, rank=0)
    ds.emit("x.span", dur_s=0.001, api_token="hunter2", batch=4)
    span = next(r for r in mw.records if r["kind"] == "span")
    assert span["attrs"]["api_token"] == REDACTED
    assert span["attrs"]["batch"] == 4


def test_null_stream_is_disabled_and_inert():
    ds = dtrace.get_stream() if not dtrace.get_stream().enabled \
        else dtrace.NullStream()
    assert not ds.enabled
    ds.emit("never")                 # no-ops, no guard needed cold
    ds.clock_sample()
    with ds.span("never"):
        pass
    assert ds.buffered() == []


# -- merge + export ----------------------------------------------------------


def _stream_records(rank, off, spans):
    """Hand-built stream: meta with a clock offset + span records."""
    recs = [{"kind": "meta", "rank": rank, "t": 1000.0 + off,
             "mono": 1000.0, "off": off,
             "env": {"DEAR_TRACE": "1", "DEAR_API_TOKEN": "s3cret"}}]
    for name, mono, dur, extra in spans:
        recs.append({"kind": "span", "name": name, "rank": rank,
                     "mono": mono, "dur": dur, **extra})
    return recs


def test_merge_aligns_clocks_across_ranks(tmp_path):
    # rank 0 booted 100s of monotonic time before rank 1; both spans
    # happened at the same WALL moment
    a = _stream_records(0, 500.0, [("s", 100.0, 0.01, {"cat": "step"})])
    b = _stream_records(1, 400.0, [("s", 200.0, 0.01, {"cat": "step"})])
    merged = dtrace.merge_streams([a, b])
    assert merged["ranks"] == [0, 1]
    walls = {s["rank"]: s["t_wall"] for s in merged["spans"]}
    assert walls[0] == pytest.approx(walls[1])
    # file round-trip path too
    p = tmp_path / "t0.jsonl"
    with open(p, "w") as f:
        for r in a:
            f.write(json.dumps(r) + "\n")
        f.write("{torn")              # crashed writer's last line
    assert len(dtrace.read_stream(str(p))) == len(a)


def test_chrome_trace_export_lanes_and_env_redaction(tmp_path):
    a = _stream_records(0, 0.0, [
        ("guard.step", 10.0, 0.02, {"cat": "step", "step": 1}),
        ("dcn.round", 10.01, 0.005,
         {"cat": "comm", "trace": {"trace_id": "step-0-1",
                                   "span_id": "ab"}}),
        ("mark", 10.02, 0.0, {"cat": "guard"}),
    ])
    merged = dtrace.merge_streams([a])
    out = tmp_path / "fleet.trace.json"
    n = dtrace.write_chrome_trace(merged, str(out))
    doc = json.loads(out.read_text())
    assert n == len(doc["traceEvents"])
    evs = doc["traceEvents"]
    names = {e["name"] for e in evs}
    assert {"process_name", "guard.step", "dcn.round", "mark"} <= names
    round_ev = next(e for e in evs if e["name"] == "dcn.round")
    assert round_ev["tid"] == 2          # the comm lane
    assert round_ev["args"]["trace_id"] == "step-0-1"
    mark_ev = next(e for e in evs if e["name"] == "mark")
    assert mark_ev["ph"] == "i"          # zero-duration -> instant
    env = doc["otherData"]["env_rank_0"]
    assert env["DEAR_API_TOKEN"] == REDACTED
    assert env["DEAR_TRACE"] == "1"


# -- critical-path attribution -----------------------------------------------


def _fleet(step_s_by_rank, comm_by_rank, compute_by_rank):
    """One step's spans across ranks: one stream per rank."""
    streams = []
    for rank, step_s in step_s_by_rank.items():
        recs = [{"kind": "span", "name": "guard.step", "rank": rank,
                 "mono": 0.0, "dur": step_s, "cat": "step",
                 "step": 1, "mem_epoch": 0}]
        for (t0, dur) in comm_by_rank.get(rank, ()):
            recs.append({"kind": "span", "name": "dcn.round",
                         "rank": rank, "mono": t0, "dur": dur,
                         "cat": "comm", "step": 1, "mem_epoch": 0})
        for (t0, dur) in compute_by_rank.get(rank, ()):
            recs.append({"kind": "span", "name": "dear.backward",
                         "rank": rank, "mono": t0, "dur": dur,
                         "cat": "compute", "step": 1, "mem_epoch": 0})
        streams.append(recs)
    return dtrace.merge_streams(streams)


def test_step_attribution_exposed_vs_hidden_and_straggler():
    # rank 1 is the straggler: 2.0s step; its comm [0,2) is half covered
    # by compute [1,3) -> exposed 1.0, hidden 1.0
    merged = _fleet({0: 1.0, 1: 2.0},
                    comm_by_rank={1: [(0.0, 2.0)]},
                    compute_by_rank={1: [(1.0, 2.0)]})
    att = CP.step_attribution(merged)
    row = att["steps"][0]
    assert row["straggler"] == "1"
    assert row["step_s"] == pytest.approx(2.0)
    assert row["exposed_comm_s"] == pytest.approx(1.0)
    assert row["hidden_comm_s"] == pytest.approx(1.0)
    assert row["ranks"]["1"]["longest_leg"]["name"] == "dcn.round"
    chain = [c["name"] for c in row["critical_chain"]]
    assert chain[0] in ("guard.step", "dcn.round")
    assert att["summary"]["stragglers"] == {"1": 1}
    assert att["summary"]["exposed_frac"] == pytest.approx(0.5)


def test_fully_hidden_comm_is_not_exposed():
    merged = _fleet({0: 1.0},
                    comm_by_rank={0: [(0.2, 0.4)]},
                    compute_by_rank={0: [(0.0, 1.0)]})
    att = CP.step_attribution(merged)
    assert att["steps"][0]["exposed_comm_s"] == pytest.approx(0.0)
    assert att["steps"][0]["hidden_comm_s"] == pytest.approx(0.4)


# -- trace-driven calibration ------------------------------------------------


def _training_spans(step_times, dcn_times=()):
    recs = []
    t = 0.0
    for i, st in enumerate(step_times):
        recs.append({"kind": "span", "name": "guard.step", "rank": 0,
                     "mono": t, "dur": st, "cat": "step",
                     "step": i, "mem_epoch": 0})
        if i < len(dcn_times):
            recs.append({"kind": "span", "name": "dcn.round", "rank": 0,
                         "mono": t, "dur": dcn_times[i], "cat": "comm",
                         "step": i, "mem_epoch": 0})
        t += st
    return dtrace.merge_streams([recs])


def test_calibrate_from_traces_fits_and_warmup_drops_compile():
    # step 0 is a 50x compile step; warmup=1 must drop it from the fit
    merged = _training_spans([0.5] + [0.01] * 9, dcn_times=[0.002] * 10)
    cal = costmodel.calibrate_from_traces(merged, min_steps=4, warmup=1)
    assert cal.n_steps == 9
    assert cal.step_time_s["p50"] == pytest.approx(0.01)
    assert cal.compute_time_s > 0
    assert cal.dcn_round_s
    assert all(d == pytest.approx(0.002) for d in cal.dcn_round_s)
    uncal = costmodel.calibrate_from_traces(merged, min_steps=4)
    # without warmup the compile step poisons the distribution
    assert uncal.step_time_s["mean"] > 5 * cal.step_time_s["mean"]

    with pytest.raises(ValueError):
        costmodel.calibrate_from_traces(
            _training_spans([0.01] * 3), min_steps=4)


def test_trace_calibration_dump_load_roundtrip(tmp_path):
    merged = _training_spans([0.01] * 8)
    cal = costmodel.calibrate_from_traces(merged, min_steps=4)
    p = str(tmp_path / "cal.json")
    cal.dump(p)
    back = costmodel.load_trace_calibration(p)
    assert back.step_time_s["p50"] == cal.step_time_s["p50"]
    assert back.n_steps == cal.n_steps
    # embedded form (the perf-artifact shape)
    wrapped = str(tmp_path / "art.json")
    with open(wrapped, "w") as f:
        json.dump({"round": 19,
                   "trace_calibration": json.load(open(p))}, f)
    assert costmodel.load_trace_calibration(
        wrapped).n_steps == cal.n_steps


# -- the collector CLI -------------------------------------------------------


def test_fleet_trace_cli_end_to_end(tmp_path, capsys):
    import scripts.fleet_trace as FT

    for rank in (0, 1):
        recs = _stream_records(rank, 0.0, [
            ("guard.step", float(i), 0.01,
             {"cat": "step", "step": i, "mem_epoch": 0})
            for i in range(6)
        ])
        with open(tmp_path / f"trace-{rank}.jsonl", "w") as f:
            for r in recs:
                f.write(json.dumps(r) + "\n")
    out = tmp_path / "fleet.trace.json"
    rep = tmp_path / "attr.json"
    cal = tmp_path / "cal.json"
    rc = FT.main([str(tmp_path), "--out", str(out), "--report",
                  str(rep), "--calibration", str(cal),
                  "--min-steps", "4", "--warmup", "1", "--quiet"])
    assert rc == 0
    verdict = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert verdict["ok"] and verdict["ranks"] == [0, 1]
    assert verdict["steps"]["n_steps"] == 6
    assert json.loads(out.read_text())["traceEvents"]
    assert json.loads(rep.read_text())["steps"]["summary"]["n_steps"] == 6
    assert costmodel.load_trace_calibration(
        str(cal)).step_time_s["p50"] == pytest.approx(0.01)


def test_fleet_trace_cli_empty_inputs(tmp_path, capsys):
    import scripts.fleet_trace as FT

    assert FT.main([str(tmp_path / "nope-*.jsonl")]) == 3
    empty = tmp_path / "trace-0.jsonl"
    empty.write_text(json.dumps(
        {"kind": "meta", "rank": 0, "off": 0.0}) + "\n")
    assert FT.main([str(empty)]) == 2
    capsys.readouterr()
