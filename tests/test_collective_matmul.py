"""Fused computation-collective Pallas kernels (ops/collective_matmul.py)
and the ``mode="dear-fused"`` schedule.

Every kernel runs under Pallas interpret mode on the 8-device emulated CPU
mesh — the exact ring schedule, async-remote-copy slot protocol, and
traced optimizer epilogue that would run on chip. The contract asserted
here: the fused schedule agrees with the unfused 'dear' schedule at
dtype-appropriate tolerance (the ring reduction order differs from
psum_scatter; the gather leg is bitwise, the update math is traced from
the same `ShardOptimizer`).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dear_pytorch_tpu.comm import collectives as C
from dear_pytorch_tpu.comm.backend import DP_AXIS
from dear_pytorch_tpu.ops import collective_matmul as CM
from dear_pytorch_tpu.ops.fused_sgd import fused_adamw, fused_sgd
from dear_pytorch_tpu.ops.schedules import warmup_cosine
from dear_pytorch_tpu.parallel import build_train_step

# fp32 ring sums differ from psum_scatter only in association order
FP32_TOL = dict(rtol=2e-5, atol=2e-6)
BF16_TOL = dict(rtol=2e-2, atol=2e-2)


def _spmd(fn, mesh, n_in, n_out=1):
    return jax.jit(jax.shard_map(
        fn, mesh=mesh,
        in_specs=(jax.P(DP_AXIS),) * n_in,
        out_specs=(jax.P(DP_AXIS),) * n_out if n_out > 1 else jax.P(DP_AXIS),
        check_vma=False,
    ))


# ---------------------------------------------------------------------------
# ring all-gather
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("n", [8, 24, 129])  # incl. a non-128-multiple
@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_ring_all_gather_matches_lax(mesh, world, n, dtype):
    """Pure data movement: bitwise equal to lax.all_gather (tiled)."""
    shards = jax.random.normal(
        jax.random.PRNGKey(0), (world, n), jnp.float32).astype(dtype)

    def fn(s):
        return CM.ring_all_gather(s[0], DP_AXIS)[None]

    got = np.asarray(_spmd(fn, mesh, 1)(shards))
    want = np.tile(np.asarray(shards).reshape(-1), (world, 1))
    assert got.dtype == want.dtype
    np.testing.assert_array_equal(got, want)


# ---------------------------------------------------------------------------
# fused reduce-scatter + optimizer epilogue
# ---------------------------------------------------------------------------


def _unfused_reference(gstack, p0, opt_state0, opt, world, dtype,
                       step=None):
    """What 'dear' computes: psum_scatter-equivalent reduction + the plain
    ShardOptimizer.update per shard, on the host in fp64-free numpy."""
    gsum = np.asarray(gstack, np.float32).sum(0)
    ss = p0.shape[0] // world
    new_p, new_states = [], []
    for i in range(world):
        sl = slice(i * ss, (i + 1) * ss)
        grad = jnp.asarray(gsum[sl]).astype(dtype) / world
        state_i = jax.tree.map(
            lambda l: l[sl] if getattr(l, "ndim", 0) == 1 else l, opt_state0)
        kw = {"step": step} if step is not None else {}
        p_i, s_i = opt.update(grad, state_i, jnp.asarray(p0[sl], dtype), **kw)
        new_p.append(np.asarray(p_i, np.float32))
        new_states.append(s_i)
    return np.concatenate(new_p), new_states


@pytest.mark.parametrize("dtype,tol", [(jnp.float32, FP32_TOL),
                                       (jnp.bfloat16, BF16_TOL)])
@pytest.mark.parametrize("optname", ["sgd", "sgd_momentum", "adamw"])
@pytest.mark.parametrize("ss", [16, 37])  # incl. a non-divisible-by-8 shard
def test_fused_rs_update_matches_unfused(mesh, world, dtype, tol, optname,
                                         ss):
    opt = {
        "sgd": fused_sgd(lr=0.05),
        "sgd_momentum": fused_sgd(lr=0.05, momentum=0.9, weight_decay=1e-4),
        "adamw": fused_adamw(lr=1e-3),
    }[optname]
    padded = world * ss
    gstack = jax.random.normal(jax.random.PRNGKey(1), (world, padded),
                               jnp.float32).astype(dtype)
    p0 = jax.random.normal(jax.random.PRNGKey(2), (padded,),
                           jnp.float32).astype(dtype)
    opt_state0 = opt.init(p0)

    def fn(g, p, *state_leaves):
        leaves = [l[0] for l in state_leaves]
        state = jax.tree_util.tree_unflatten(
            jax.tree_util.tree_structure(opt_state0), leaves)
        new_p, new_s = CM.fused_reduce_scatter_update(
            g[0], p[0], state, opt, DP_AXIS, mean_world=world)
        outs = [new_p[None]] + [
            jnp.broadcast_to(l, (1,) + jnp.shape(l))
            for l in jax.tree_util.tree_flatten(new_s)[0]]
        return tuple(outs)

    # shard vector state leaves; replicate scalars by stacking per device
    def stage(leaf):
        if getattr(leaf, "ndim", 0) == 1:
            return jnp.reshape(leaf, (world, ss))
        return jnp.broadcast_to(jnp.asarray(leaf)[None], (world,))

    state_stacked = [stage(l) for l in jax.tree_util.tree_flatten(
        opt_state0)[0]]
    p_stacked = p0.reshape(world, ss)
    n_in = 2 + len(state_stacked)
    outs = _spmd(fn, mesh, n_in, n_out=1 + len(state_stacked))(
        gstack, p_stacked, *state_stacked)

    want_p, want_states = _unfused_reference(
        gstack, np.asarray(p0, np.float32), opt_state0, opt, world, dtype)
    got_p = np.asarray(outs[0], np.float32).reshape(-1)
    np.testing.assert_allclose(got_p, want_p, **tol)

    # state agreement (momentum / adam moments / counters / flags)
    got_leaves = [np.asarray(o) for o in outs[1:]]
    want_leaf_rows = [jax.tree_util.tree_flatten(s)[0]
                      for s in want_states]
    for j, got in enumerate(got_leaves):
        for i in range(world):
            want = np.asarray(want_leaf_rows[i][j], np.float32)
            np.testing.assert_allclose(
                np.asarray(got[i], np.float32), want, **tol)


def test_fused_rs_update_lr_schedule_needs_step(mesh, world):
    """needs_step optimizers receive the replicated step scalar inside the
    kernel (SMEM), and the schedule evaluates identically."""
    opt = fused_sgd(lr=warmup_cosine(0.1, warmup_steps=2, total_steps=10))
    assert opt.needs_step
    ss = 16
    padded = world * ss
    gstack = jax.random.normal(jax.random.PRNGKey(3), (world, padded))
    p0 = jax.random.normal(jax.random.PRNGKey(4), (padded,))
    step = jnp.asarray(5, jnp.int32)

    def fn(g, p):
        new_p, _ = CM.fused_reduce_scatter_update(
            g[0], p[0], opt.init(p[0]), opt, DP_AXIS,
            mean_world=world, step=step)
        return new_p[None]

    got = np.asarray(_spmd(fn, mesh, 2)(
        gstack, p0.reshape(world, ss))).reshape(-1)
    want, _ = _unfused_reference(
        gstack, np.asarray(p0), opt.init(p0), opt, world, jnp.float32,
        step=step)
    np.testing.assert_allclose(got, want, **FP32_TOL)


def test_fused_rs_update_rejects_layerwise_state(mesh, world):
    """A state leaf that is neither shard-shaped nor scalar is unfusable
    and must raise with the reason (not silently mis-update)."""
    opt = fused_sgd(lr=0.1)
    bad_state = (jnp.zeros((4, 4)),)

    def fn(g, p):
        new_p, _ = CM.fused_reduce_scatter_update(
            g[0], p[0], bad_state, opt, DP_AXIS, mean_world=world)
        return new_p[None]

    with pytest.raises(ValueError, match="cannot .*fused|can only fuse"):
        _spmd(fn, mesh, 2)(jnp.zeros((world, world * 8)),
                           jnp.zeros((world, 8)))


# ---------------------------------------------------------------------------
# ring collective-matmul (all-gather fused into the matmul)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("dtype,tol", [(jnp.float32, FP32_TOL),
                                       (jnp.bfloat16, BF16_TOL)])
def test_allgather_matmul_matches_dense(mesh, world, dtype, tol):
    m, k, n = 16, 8 * world, 24
    x = jax.random.normal(jax.random.PRNGKey(5), (m, k),
                          jnp.float32).astype(dtype)
    w = jax.random.normal(jax.random.PRNGKey(6), (k, n),
                          jnp.float32).astype(dtype)
    want = np.asarray(x.astype(jnp.float32) @ w.astype(jnp.float32))

    def fn(xs, ws):
        y = CM.allgather_matmul(xs[0], ws[0], DP_AXIS)
        return y[None]

    xs = jnp.broadcast_to(x[None], (world,) + x.shape)  # replicated acts
    ws = w.reshape(world, k // world, n)                # row shards
    got = np.asarray(_spmd(fn, mesh, 2)(xs, ws), np.float32)
    for i in range(world):
        np.testing.assert_allclose(got[i], want, **tol)


def test_allgather_matmul_gradients_match_dense(mesh, world):
    """custom VJP: dx (shards re-streamed) and the ring-reduced dw_shard
    equal the dense matmul's gradients."""
    m, k, n = 8, 8 * world, 16
    kc = k // world
    x = jax.random.normal(jax.random.PRNGKey(7), (m, k))
    w = jax.random.normal(jax.random.PRNGKey(8), (k, n))
    co = jax.random.normal(jax.random.PRNGKey(9), (m, n))

    def dense_loss(x_, w_):
        return jnp.sum((x_ @ w_) * co)

    want_dx, want_dw = jax.grad(dense_loss, argnums=(0, 1))(x, w)

    def fn(xs, ws, cs):
        def loss(x_, w_shard):
            return jnp.sum(CM.allgather_matmul(x_, w_shard, DP_AXIS)
                           * cs[0])
        dx, dws = jax.grad(loss, argnums=(0, 1))(xs[0], ws[0])
        return dx[None], dws[None]

    xs = jnp.broadcast_to(x[None], (world,) + x.shape)
    cs = jnp.broadcast_to(co[None], (world,) + co.shape)
    ws = w.reshape(world, kc, n)
    dx, dws = _spmd(fn, mesh, 3, n_out=2)(xs, ws, cs)
    # every device sees the same x, so each device's dx is the full dense dx
    for i in range(world):
        np.testing.assert_allclose(np.asarray(dx[i]), np.asarray(want_dx),
                                   rtol=1e-4, atol=1e-5)
    # dw_shard arrives cross-device reduced: with x replicated the dense dw
    # equals world * (per-device contribution)?? No — the ring sums the SAME
    # contribution from every device, so dw_shard = world * local x^T dy ...
    # The dense reference for REPLICATED x/dy: each device's local grad is
    # the full dense dw; the ring-reduced shard is world * dense rows.
    got_dw = np.concatenate([np.asarray(dws[i]) for i in range(world)])
    np.testing.assert_allclose(got_dw, world * np.asarray(want_dw),
                               rtol=1e-4, atol=1e-4)


def test_ring_projection_impl_matches_dense(mesh, world):
    """The models' projection hook: slice-shard + ring matmul + bias ==
    the plain dense projection."""
    impl = CM.make_ring_projection_impl(DP_AXIS)
    m, k, n = 8, 8 * world, 12
    x = jax.random.normal(jax.random.PRNGKey(10), (m, k))
    w = jax.random.normal(jax.random.PRNGKey(11), (k, n))
    b = jax.random.normal(jax.random.PRNGKey(12), (n,))
    want = np.asarray(x @ w + b[None])

    def fn(xs, ws, bs):
        return impl(xs[0], ws[0], bs[0], jnp.float32)[None]

    xs = jnp.broadcast_to(x[None], (world,) + x.shape)
    ws = jnp.broadcast_to(w[None], (world,) + w.shape)  # replicated full W
    bs = jnp.broadcast_to(b[None], (world,) + b.shape)
    got = np.asarray(_spmd(fn, mesh, 3)(xs, ws, bs))
    for i in range(world):
        np.testing.assert_allclose(got[i], want, rtol=2e-5, atol=2e-5)


# ---------------------------------------------------------------------------
# mode="dear-fused": end-to-end agreement with mode="dear"
# ---------------------------------------------------------------------------


def _mlp(width, n_layers=3, dtype=jnp.float32):
    ks = jax.random.split(jax.random.PRNGKey(0), n_layers)
    params = {
        f"l{i}": {"w": (jax.random.normal(ks[i], (width, width)) * 0.1
                        ).astype(dtype),
                  "b": jnp.zeros((width,), dtype)}
        for i in range(n_layers)
    }

    def loss(p, b):
        x, y = b
        for i in range(n_layers):
            x = jnp.tanh(x @ p[f"l{i}"]["w"] + p[f"l{i}"]["b"])
        return jnp.mean((x - y).astype(jnp.float32) ** 2)

    return params, loss


def _run_mode(mode, params, loss, mesh, batch, opt, steps=4, **kw):
    ts = build_train_step(loss, params, mesh=mesh, mode=mode,
                          optimizer=opt, donate=False, **kw)
    state = ts.init(params)
    metrics = None
    for _ in range(steps):
        state, metrics = ts.step(state, batch)
    return (jax.tree.map(np.asarray, ts.gather_params(state)),
            float(metrics["loss"]), ts)


@pytest.mark.parametrize("buckets_kw", [dict(nearby_layers=1),
                                        dict(threshold_mb=25.0)])
@pytest.mark.parametrize("optname", ["sgd_momentum", "adamw"])
def test_dear_fused_matches_dear_e2e(mesh, buckets_kw, optname):
    """The acceptance gate: multi-step training under dear-fused tracks
    dear at fp32 tolerance across bucket counts (multi- and single-bucket
    plans) and both fused optimizers."""
    opt = (fused_sgd(lr=0.05, momentum=0.9) if optname == "sgd_momentum"
           else fused_adamw(lr=1e-3))
    params, loss = _mlp(64)
    batch = (jax.random.normal(jax.random.PRNGKey(20), (32, 64)),
             jax.random.normal(jax.random.PRNGKey(21), (32, 64)))
    p_dear, l_dear, ts = _run_mode("dear", params, loss, mesh, batch, opt,
                                   **buckets_kw)
    p_fused, l_fused, ts_f = _run_mode("dear-fused", params, loss, mesh,
                                       batch, opt, **buckets_kw)
    assert ts_f.plan.num_buckets == ts.plan.num_buckets
    assert l_fused == pytest.approx(l_dear, rel=1e-5)
    for a, b in zip(jax.tree.leaves(p_dear), jax.tree.leaves(p_fused)):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)


def test_dear_fused_matches_dear_bf16_params(mesh):
    """bf16 params / fp32 in-kernel accumulation: tracks dear's bf16 wire
    at bf16 tolerance (the ring accumulates in fp32, never worse)."""
    params, loss = _mlp(64, dtype=jnp.bfloat16)
    batch = (jax.random.normal(jax.random.PRNGKey(22), (32, 64),
                               jnp.bfloat16),
             jax.random.normal(jax.random.PRNGKey(23), (32, 64),
                               jnp.bfloat16))
    opt = fused_sgd(lr=0.05, momentum=0.9)
    p_dear, l_dear, _ = _run_mode("dear", params, loss, mesh, batch, opt,
                                  nearby_layers=1)
    p_fused, l_fused, _ = _run_mode("dear-fused", params, loss, mesh,
                                    batch, opt, nearby_layers=1)
    assert l_fused == pytest.approx(l_dear, rel=2e-2)
    for a, b in zip(jax.tree.leaves(p_dear), jax.tree.leaves(p_fused)):
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b, np.float32), **BF16_TOL)


def test_dear_fused_non_divisible_bucket_padding(mesh, world):
    """Bucket sizes that do not divide by world exercise the padded tail
    through the ring (the pad rides the last shard exactly as in dear)."""
    params = {"a": {"w": jax.random.normal(jax.random.PRNGKey(1),
                                           (13, 5))},
              "b": {"w": jax.random.normal(jax.random.PRNGKey(2), (9,))}}

    def loss(p, batch):
        x, y = batch
        return jnp.mean((x @ p["a"]["w"] + p["b"]["w"][None, :5] - y) ** 2)

    batch = (jax.random.normal(jax.random.PRNGKey(3), (16, 13)),
             jax.random.normal(jax.random.PRNGKey(4), (16, 5)))
    opt = fused_sgd(lr=0.05, momentum=0.9)
    p_dear, _, ts = _run_mode("dear", params, loss, mesh, batch, opt,
                              nearby_layers=1)
    assert any(b.pad for b in ts.plan.buckets)  # the case under test
    p_fused, _, _ = _run_mode("dear-fused", params, loss, mesh, batch, opt,
                              nearby_layers=1)
    for a, b in zip(jax.tree.leaves(p_dear), jax.tree.leaves(p_fused)):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)


def test_dear_fused_gather_dtype_and_comm_dtype(mesh):
    """comm_dtype=bf16 wire + gather_dtype=bf16 compose with the rings the
    same way they compose with the XLA collectives."""
    params, loss = _mlp(64)
    batch = (jax.random.normal(jax.random.PRNGKey(24), (32, 64)),
             jax.random.normal(jax.random.PRNGKey(25), (32, 64)))
    opt = fused_sgd(lr=0.05, momentum=0.9)
    kw = dict(nearby_layers=1, comm_dtype=jnp.bfloat16,
              gather_dtype=jnp.bfloat16)
    p_dear, _, _ = _run_mode("dear", params, loss, mesh, batch, opt, **kw)
    p_fused, _, _ = _run_mode("dear-fused", params, loss, mesh, batch, opt,
                              **kw)
    for a, b in zip(jax.tree.leaves(p_dear), jax.tree.leaves(p_fused)):
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b, np.float32), **BF16_TOL)


def test_dear_fused_rejects_unsupported_configs(mesh):
    params, loss = _mlp(64)
    with pytest.raises(ValueError, match="clip_norm"):
        build_train_step(loss, params, mesh=mesh, mode="dear-fused",
                         clip_norm=1.0)
    from dear_pytorch_tpu.ops.fused_sgd import fused_lamb

    with pytest.raises(ValueError, match="Layerwise|LAMB"):
        build_train_step(loss, params, mesh=mesh, mode="dear-fused",
                         optimizer=fused_lamb(lr=1e-3))


def test_dear_fused_counters_flow_to_tracer(mesh):
    """kernel.* counters reach the tracer: builds at trace time, launches
    per step (what the overlap auditor joins with the static leg bytes)."""
    from dear_pytorch_tpu.observability import tracer as T

    old = T.get_tracer()
    T.set_tracer(T.Tracer([T.MemoryExporter()]))
    try:
        params, loss = _mlp(64)
        batch = (jax.random.normal(jax.random.PRNGKey(26), (32, 64)),
                 jax.random.normal(jax.random.PRNGKey(27), (32, 64)))
        ts = build_train_step(loss, params, mesh=mesh, mode="dear-fused",
                              nearby_layers=1,
                              optimizer=fused_sgd(lr=0.05), donate=False)
        state = ts.init(params)
        state, _ = ts.step(state, batch)
        state, _ = ts.step(state, batch)
        counts = T.get_tracer().counters()
        nb = ts.plan.num_buckets
        assert counts["kernel.ring_ag_builds"] >= nb
        assert counts["kernel.fused_rs_builds"] >= nb
        assert counts["kernel.fused_rs_launches"] == 2 * nb
        assert counts["kernel.ring_ag_launches"] == 2 * nb
        assert counts["dear.reduce_scatter_bytes"] > 0
        assert counts["dear.all_gather_bytes"] > 0
    finally:
        T.set_tracer(old)


# ---------------------------------------------------------------------------
# transformer paths: BERT and GPT end-to-end under dear-fused
# ---------------------------------------------------------------------------


def _tiny_bert():
    from dear_pytorch_tpu import models
    from dear_pytorch_tpu.models.bert import BertConfig, BertForPreTraining
    from dear_pytorch_tpu.models.data import synthetic_bert_batch

    cfg = BertConfig(
        vocab_size=64, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=64,
        max_position_embeddings=16, hidden_dropout_prob=0.0,
        attention_probs_dropout_prob=0.0,
    )
    batch = synthetic_bert_batch(jax.random.PRNGKey(0), 16, seq_len=16,
                                 vocab_size=64)

    def build(projection_impl=None):
        model = BertForPreTraining(cfg, projection_impl=projection_impl)
        params = BertForPreTraining(cfg).init(
            {"params": jax.random.PRNGKey(0)}, batch["input_ids"],
            train=False)["params"]

        def loss(p, b):
            logits, nsp = model.apply(
                {"params": p}, b["input_ids"], b["token_type_ids"],
                b["attention_mask"], train=False)
            return models.bert_pretraining_loss(
                logits, nsp, b["masked_lm_labels"],
                b["next_sentence_labels"])

        return params, loss

    return build, batch


def _tiny_gpt():
    from dear_pytorch_tpu.models.data import synthetic_gpt_batch
    from dear_pytorch_tpu.models.gpt import (
        GptConfig,
        GptLmHeadModel,
        gpt_lm_loss,
    )

    cfg = GptConfig(
        vocab_size=64, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=64,
        max_position_embeddings=16, embd_dropout_prob=0.0,
        hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0,
    )
    batch = synthetic_gpt_batch(jax.random.PRNGKey(0), 16, seq_len=16,
                                vocab_size=64)

    def build(projection_impl=None):
        model = GptLmHeadModel(cfg, projection_impl=projection_impl)
        params = GptLmHeadModel(cfg).init(
            {"params": jax.random.PRNGKey(0)}, batch["input_ids"],
            train=False)["params"]

        def loss(p, b):
            logits = model.apply({"params": p}, b["input_ids"],
                                 train=False)
            return gpt_lm_loss(logits, b["input_ids"], vocab_size=64)

        return params, loss

    return build, batch


@pytest.mark.parametrize("family", ["bert", "gpt"])
def test_transformer_dear_fused_matches_dear(mesh, family):
    """BERT and GPT train end-to-end under dear-fused on the emulated
    mesh, matching dear (the issue's acceptance criterion)."""
    build, batch = (_tiny_bert if family == "bert" else _tiny_gpt)()
    params, loss = build()
    opt = fused_sgd(lr=0.01, momentum=0.9)
    p_dear, l_dear, _ = _run_mode("dear", params, loss, mesh, batch, opt,
                                  steps=3, threshold_mb=0.05)
    p_fused, l_fused, _ = _run_mode("dear-fused", params, loss, mesh,
                                    batch, opt, steps=3, threshold_mb=0.05)
    assert l_fused == pytest.approx(l_dear, rel=1e-4)
    for a, b in zip(jax.tree.leaves(p_dear), jax.tree.leaves(p_fused)):
        np.testing.assert_allclose(a, b, rtol=5e-5, atol=5e-6)


@pytest.mark.parametrize("family", ["bert", "gpt"])
def test_transformer_ring_projections_match_dense(mesh, family):
    """The QKV/MLP projection paths route through the ring
    collective-matmul (projection_impl hook) and still track the dense
    model under dear-fused — the (b) fusion exercised in the real model
    graph, gradients included."""
    build, batch = (_tiny_bert if family == "bert" else _tiny_gpt)()
    params, loss_dense = build()
    _, loss_ring = build(
        projection_impl=CM.make_ring_projection_impl(DP_AXIS))
    opt = fused_sgd(lr=0.01, momentum=0.9)
    # one step, default (single-bucket) plan: the CM kernels dominate the
    # cost here and bucketing / multi-step coverage lives in the other
    # e2e tests — this one pins the in-model fwd+bwd CM path
    p_ref, l_ref, _ = _run_mode("dear-fused", params, loss_dense, mesh,
                                batch, opt, steps=1)
    p_ring, l_ring, _ = _run_mode("dear-fused", params, loss_ring, mesh,
                                  batch, opt, steps=1)
    assert l_ring == pytest.approx(l_ref, rel=1e-4)
    for a, b in zip(jax.tree.leaves(p_ref), jax.tree.leaves(p_ring)):
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5)


def test_dear_fused_multi_step_scan(mesh):
    """The scanned multi-step protocol (one lax.scan program) composes
    with the ring kernels."""
    params, loss = _mlp(64)
    batch = (jax.random.normal(jax.random.PRNGKey(30), (32, 64)),
             jax.random.normal(jax.random.PRNGKey(31), (32, 64)))
    ts = build_train_step(loss, params, mesh=mesh, mode="dear-fused",
                          nearby_layers=1, optimizer=fused_sgd(lr=0.05),
                          donate=False)
    state = ts.init(params)
    state2, m = ts.multi_step(3)(state, batch)
    assert np.isfinite(float(m["loss"]))

    # equals three single steps at tolerance (same program content)
    state1 = ts.init(params)
    for _ in range(3):
        state1, m1 = ts.step(state1, batch)
    np.testing.assert_allclose(float(m["loss"]), float(m1["loss"]),
                               rtol=1e-6)
