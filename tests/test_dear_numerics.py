"""Numerical equivalence of every schedule vs a single-device baseline.

The reference could only eyeball norms on a live cluster (test_comm.py) and
rely on MNIST convergence. Here we assert: DeAR (decoupled RS+AG, sharded
state), 'rsag', 'rb', and 'allreduce' schedules all reproduce plain
full-batch SGD to floating-point tolerance, step for step.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dear_pytorch_tpu.ops.fused_sgd import fused_sgd, from_optax
from dear_pytorch_tpu.parallel import build_train_step


def _mlp_params(key):
    k1, k2, k3 = jax.random.split(key, 3)
    return {
        "dense1": {
            "kernel": jax.random.normal(k1, (12, 32)) * 0.1,
            "bias": jnp.zeros((32,)),
        },
        "dense2": {
            "kernel": jax.random.normal(k2, (32, 16)) * 0.1,
            "bias": jnp.zeros((16,)),
        },
        "out": {
            "kernel": jax.random.normal(k3, (16, 4)) * 0.1,
            "bias": jnp.zeros((4,)),
        },
    }


def _forward(params, x):
    h = jnp.tanh(x @ params["dense1"]["kernel"] + params["dense1"]["bias"])
    h = jnp.tanh(h @ params["dense2"]["kernel"] + params["dense2"]["bias"])
    return h @ params["out"]["kernel"] + params["out"]["bias"]


def _loss_fn(params, batch):
    x, y = batch
    logits = _forward(params, x)
    logp = jax.nn.log_softmax(logits)
    return -jnp.mean(jnp.sum(logp * jax.nn.one_hot(y, 4), axis=-1))


def _data(key, n=64):
    kx, ky = jax.random.split(key)
    x = jax.random.normal(kx, (n, 12))
    y = jax.random.randint(ky, (n,), 0, 4)
    return x, y


def _baseline(params, batches, lr=0.1, momentum=0.9, steps=5):
    """Plain full-batch SGD+momentum (torch semantics) on one device."""
    opt = fused_sgd(lr=lr, momentum=momentum)
    flat, treedef = jax.tree_util.tree_flatten(params)
    states = [opt.init(p.reshape(-1)) for p in flat]
    losses = []
    for b in batches[:steps]:
        loss, grads = jax.value_and_grad(_loss_fn)(params, b)
        losses.append(float(loss))
        gflat = jax.tree_util.tree_leaves(grads)
        new_flat = []
        for i, (p, g) in enumerate(zip(flat, gflat)):
            newp, states[i] = opt.update(
                g.reshape(-1), states[i], p.reshape(-1)
            )
            new_flat.append(newp.reshape(p.shape))
        flat = new_flat
        params = jax.tree_util.tree_unflatten(treedef, flat)
    return params, losses


@pytest.fixture(scope="module")
def problem():
    key = jax.random.PRNGKey(0)
    params = _mlp_params(key)
    batches = [_data(jax.random.PRNGKey(100 + i)) for i in range(5)]
    ref_params, ref_losses = _baseline(params, batches)
    return params, batches, ref_params, ref_losses


@pytest.mark.parametrize("mode", ["dear", "allreduce", "rsag", "rb", "fsdp"])
def test_schedule_matches_baseline(mesh, world, problem, mode):
    params, batches, ref_params, ref_losses = problem
    ts = build_train_step(
        _loss_fn,
        params,
        optimizer=fused_sgd(lr=0.1, momentum=0.9),
        mesh=mesh,
        mode=mode,
        threshold_mb=0.0008,  # tiny threshold -> several buckets
        donate=False,
    )
    assert ts.plan.num_buckets >= 2
    state = ts.init(params)
    losses = []
    for b in batches:
        state, metrics = ts.step(state, b)
        losses.append(float(metrics["loss"]))
    np.testing.assert_allclose(losses, ref_losses, rtol=1e-5, atol=1e-6)
    got = ts.gather_params(state)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5
        ),
        got,
        ref_params,
    )
    assert int(state.step) == 5


def test_dear_state_is_sharded(mesh, world, problem):
    params, batches, _, _ = problem
    ts = build_train_step(
        _loss_fn, params, mesh=mesh, mode="dear", threshold_mb=None, donate=False
    )
    state = ts.init(params)
    buf = state.buffers[0]
    # global padded buffer, sharded across dp: each device holds 1/world
    shard_bytes = buf.addressable_shards[0].data.size
    assert shard_bytes == buf.size // world
    # optimizer state: no momentum configured -> empty tuples
    ts2 = build_train_step(
        _loss_fn,
        params,
        optimizer=fused_sgd(lr=0.1, momentum=0.9),
        mesh=mesh,
        mode="dear",
        threshold_mb=None,
        donate=False,
    )
    st2 = ts2.init(params)
    mom = st2.opt_state[0][0]
    assert mom.addressable_shards[0].data.size == mom.size // world


def test_no_fusion_mode(mesh, world, problem):
    # nearby_layers=1: one bucket per layer (reference no-TF ablation)
    params, batches, ref_params, ref_losses = problem
    ts = build_train_step(
        _loss_fn,
        params,
        optimizer=fused_sgd(lr=0.1, momentum=0.9),
        mesh=mesh,
        mode="dear",
        nearby_layers=1,
        donate=False,
    )
    assert ts.plan.num_buckets == 3
    state = ts.init(params)
    for b in batches[:2]:
        state, metrics = ts.step(state, b)
    np.testing.assert_allclose(
        float(metrics["loss"]), ref_losses[1], rtol=1e-5, atol=1e-6
    )


def test_exclude_parts_runs(mesh, world, problem):
    # ablation instruments must execute (numerics intentionally garbage)
    params, batches, _, _ = problem
    for excl in (("reducescatter",), ("allgather",)):
        ts = build_train_step(
            _loss_fn,
            params,
            mesh=mesh,
            mode="dear",
            threshold_mb=None,
            exclude_parts=excl,
            donate=False,
        )
        state = ts.init(params)
        state, metrics = ts.step(state, batches[0])
        assert np.isfinite(float(metrics["loss"]))
    with pytest.raises(ValueError):
        build_train_step(
            _loss_fn, params, mesh=mesh, mode="allreduce",
            exclude_parts=("allgather",),
        )
    with pytest.raises(ValueError):
        build_train_step(_loss_fn, params, mesh=mesh, mode="bogus")


def test_optax_adamw_on_shards(mesh, world, problem):
    import optax

    params, batches, _, _ = problem
    tx = optax.adamw(1e-3)
    ts = build_train_step(
        _loss_fn,
        params,
        optimizer=from_optax(tx),
        mesh=mesh,
        mode="dear",
        threshold_mb=0.0008,
        donate=False,
    )
    state = ts.init(params)
    for b in batches:
        state, m = ts.step(state, b)

    # parity vs full-tree optax on one device
    opt_state = tx.init(params)
    p = params
    for b in batches:
        g = jax.grad(_loss_fn)(p, b)
        upd, opt_state = tx.update(g, opt_state, p)
        p = optax.apply_updates(p, upd)
    got = ts.gather_params(state)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-3, atol=2e-5
        ),
        got,
        p,
    )


@pytest.mark.parametrize("mode", ["dear", "fsdp", "allreduce"])
def test_clip_norm_matches_optax_global_clip(mesh, problem, mode):
    """clip_norm on (sharded) buckets == optax clip_by_global_norm on the
    full tree: shard-local square-norms psum to the exact global norm."""
    import optax

    params, batches, _, _ = problem
    clip = 0.05  # small enough to be active every step
    ts = build_train_step(
        _loss_fn, params, mesh=mesh, mode=mode, threshold_mb=0.0008,
        optimizer=fused_sgd(lr=0.1, momentum=0.9), clip_norm=clip,
        donate=False,
    )
    state = ts.init(params)
    norms = []
    for b in batches:
        state, m = ts.step(state, b)
        norms.append(float(m["grad_norm"]))
    assert all(n > clip for n in norms), norms  # the clip was active

    tx = optax.chain(
        optax.clip_by_global_norm(clip),
        optax.trace(decay=0.9),  # torch-style momentum (trace), lr applied
        optax.scale(-0.1),
    )
    opt_state = tx.init(params)
    p = params
    for b in batches:
        g = jax.grad(_loss_fn)(p, b)
        upd, opt_state = tx.update(g, opt_state, p)
        p = optax.apply_updates(p, upd)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-6
        ),
        ts.gather_params(state), p,
    )


def test_accum_clip_gather_dtype_compose(mesh, problem):
    """The three newest builder options stack: microbatch accumulation,
    global-norm clipping of the accumulated gradient, bf16 gathers — and
    still match the same configuration without accumulation."""
    params, batches, _, _ = problem
    common = dict(
        mesh=mesh, mode="dear", threshold_mb=0.0008, clip_norm=0.05,
        gather_dtype=jnp.bfloat16,
        optimizer=fused_sgd(lr=0.1, momentum=0.9), donate=False,
    )
    ts1 = build_train_step(_loss_fn, params, **common)
    ts4 = build_train_step(_loss_fn, params, accum_steps=4, **common)
    s1, s4 = ts1.init(params), ts4.init(params)
    for b in batches[:3]:
        s1, m1 = ts1.step(s1, b)
        s4, m4 = ts4.step(s4, b)
        assert float(m4["grad_norm"]) == pytest.approx(
            float(m1["grad_norm"]), rel=1e-2
        )
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-2, atol=1e-4
        ),
        s1.buffers, s4.buffers,
    )


def test_clip_norm_validation(mesh, problem):
    params, _, _, _ = problem
    with pytest.raises(ValueError, match="positive"):
        build_train_step(_loss_fn, params, mesh=mesh, clip_norm=0.0)
    with pytest.raises(ValueError, match="compression"):
        build_train_step(
            _loss_fn, params, mesh=mesh, mode="allreduce",
            compressor="eftopk", density=0.1, clip_norm=1.0,
        )


def test_optax_lr_schedule_on_shards(mesh, problem):
    """optax schedules (stateful count) work on sharded buffers: the 0-d
    count leaf is replicated by _opt_bucket_specs, per-element state shards
    with its bucket — parity vs full-tree optax on one device."""
    import optax

    params, batches, _, _ = problem
    tx = optax.sgd(optax.exponential_decay(0.1, 2, 0.5))
    ts = build_train_step(
        _loss_fn, params, optimizer=from_optax(tx), mesh=mesh, mode="dear",
        threshold_mb=0.0008, donate=False,
    )
    state = ts.init(params)
    for b in batches:
        state, _ = ts.step(state, b)

    opt_state = tx.init(params)
    p = params
    for b in batches:
        g = jax.grad(_loss_fn)(p, b)
        upd, opt_state = tx.update(g, opt_state, p)
        p = optax.apply_updates(p, upd)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-6
        ),
        ts.gather_params(state), p,
    )


def test_comm_dtype_bf16(mesh, world, problem):
    params, batches, _, _ = problem
    ts = build_train_step(
        _loss_fn,
        params,
        optimizer=fused_sgd(lr=0.1),
        mesh=mesh,
        mode="dear",
        threshold_mb=None,
        comm_dtype=jnp.bfloat16,
        donate=False,
    )
    state = ts.init(params)
    state, m = ts.step(state, batches[0])
    assert np.isfinite(float(m["loss"]))


def test_donation(mesh, world, problem):
    params, batches, _, _ = problem
    ts = build_train_step(
        _loss_fn, params, mesh=mesh, mode="dear", threshold_mb=None, donate=True
    )
    state = ts.init(params)
    state2, _ = ts.step(state, batches[0])
    # donated: the old state's buffers are invalidated
    assert state.buffers[0].is_deleted()
    assert not state2.buffers[0].is_deleted()


def test_model_state_batchnorm(mesh, world):
    """Non-trained model collections (BN running stats) are carried through
    the step, updated, and cross-replica averaged (the reference/DDP leave
    them replica-local; see DearState docstring)."""
    import flax.linen as nn

    class TinyBN(nn.Module):
        @nn.compact
        def __call__(self, x, train: bool = True):
            x = nn.Dense(8)(x)
            x = nn.BatchNorm(use_running_average=not train, momentum=0.9)(x)
            return nn.Dense(4)(x)

    model = TinyBN()
    x = jax.random.normal(jax.random.PRNGKey(0), (16, 12)) * 3.0 + 1.0
    y = jax.random.randint(jax.random.PRNGKey(1), (16,), 0, 4)
    variables = model.init({"params": jax.random.PRNGKey(2)}, x, train=False)
    params = variables["params"]
    mstate = {"batch_stats": variables["batch_stats"]}

    def loss_fn(p, ms, b):
        bx, by = b
        logits, new_state = model.apply(
            {"params": p, **ms}, bx, train=True, mutable=["batch_stats"]
        )
        logp = jax.nn.log_softmax(logits)
        loss = -jnp.mean(jnp.sum(logp * jax.nn.one_hot(by, 4), axis=-1))
        return loss, new_state

    ts = build_train_step(
        loss_fn,
        params,
        optimizer=fused_sgd(lr=0.05),
        mesh=mesh,
        mode="dear",
        threshold_mb=None,
        model_state_template=mstate,
        donate=False,
    )
    state = ts.init(params, mstate)
    losses = []
    for i in range(4):
        state, m = ts.step(state, (x, y))
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0]
    stats = state.model_state["batch_stats"]["BatchNorm_0"]
    mean = np.asarray(stats["mean"])
    assert np.abs(mean).sum() > 0  # running stats actually moved
    # Replica consistency: every device's copy of the nominally replicated
    # stats must be identical (guards the pmean in _sync_leaf; with
    # check_vma=False, divergence would otherwise be silent).
    shards = [np.asarray(s.data) for s in stats["mean"].addressable_shards]
    for s in shards[1:]:
        np.testing.assert_array_equal(shards[0], s)
    # ... and equal to the pmean of per-device batch stats, not any single
    # device's local value: devices saw different batch shards, so a missing
    # pmean could not produce shard-identical values matched here.
    assert len(shards) == 8


def test_init_rejects_unexpected_model_state(mesh):
    params = _mlp_params(jax.random.PRNGKey(0))
    ts = build_train_step(_loss_fn, params, mesh=mesh, threshold_mb=None,
                          donate=False)
    with pytest.raises(ValueError, match="model_state"):
        ts.init(params, {"batch_stats": {}})


def test_rng_seed_varies_per_step(mesh):
    """With rng_seed, loss_fn receives a fresh per-step key (dropout masks
    change across steps)."""
    params = {"w": {"kernel": jnp.ones((4, 4))}}

    def loss2(p, b, rng):
        mask = jax.random.bernoulli(rng, 0.5, (4,))
        return jnp.sum((b * mask) @ p["w"]["kernel"])

    ts = build_train_step(loss2, params, mesh=mesh, threshold_mb=None,
                          rng_seed=7, donate=False)
    state = ts.init(params)
    b = jnp.ones((8, 4))
    losses = []
    for _ in range(3):
        state, m = ts.step(state, b)
        losses.append(float(m["loss"]))
    # distinct dropout masks -> losses differ across steps with prob ~1
    assert len(set(losses)) > 1, losses


def test_init_does_not_alias_caller_arrays(mesh):
    """ts.init must COPY what it stages: a same-device device_put aliases,
    and the donated step would delete the caller's arrays (e.g. the
    batch_stats pytree the user still holds) on the first step."""
    import flax.linen as nn

    class TinyBN(nn.Module):
        @nn.compact
        def __call__(self, x, train: bool = True):
            x = nn.Dense(8)(x)
            x = nn.BatchNorm(use_running_average=not train)(x)
            return nn.Dense(4)(x)

    model = TinyBN()
    x = jax.random.normal(jax.random.PRNGKey(0), (16, 12))
    y = jax.random.randint(jax.random.PRNGKey(1), (16,), 0, 4)
    variables = model.init({"params": jax.random.PRNGKey(2)}, x, train=False)
    params, mstate = variables["params"], {"batch_stats": variables["batch_stats"]}

    def loss_fn(p, ms, b):
        bx, by = b
        logits, new_state = model.apply(
            {"params": p, **ms}, bx, train=True, mutable=["batch_stats"]
        )
        logp = jax.nn.log_softmax(logits)
        return -jnp.mean(
            jnp.sum(logp * jax.nn.one_hot(by, 4), axis=-1)
        ), new_state

    ts = build_train_step(loss_fn, params, mesh=mesh, threshold_mb=None,
                          optimizer=fused_sgd(lr=0.05),
                          model_state_template=mstate, donate=True)
    state = ts.init(params, mstate)
    state, _ = ts.step(state, (x, y))
    # the caller's originals survive the donated step
    np.asarray(jax.tree.leaves(mstate)[0])
    np.asarray(jax.tree.leaves(params)[0])
    # and a SECOND independent training run can start from them
    state2 = ts.init(params, mstate)
    state2, m2 = ts.step(state2, (x, y))
    assert np.isfinite(float(m2["loss"]))


def test_init_does_not_alias_single_leaf_1d_params(mesh):
    """pack_all's reshape(-1) + 1-element concat are identity for a
    single-leaf 1-D unpadded bucket, so the packed buffer can BE the
    caller's array — init must unlink it before the donated step."""
    w = jnp.ones((8,))
    params = {"scale": {"w": w}}

    def loss_fn(p, b):
        return jnp.sum(p["scale"]["w"] * b[0])

    ts = build_train_step(loss_fn, params, mesh=mesh, mode="allreduce",
                          threshold_mb=None, donate=True,
                          optimizer=fused_sgd(lr=0.1))
    state = ts.init(params)
    batch = jnp.ones((8, 8))
    state, _ = ts.step(state, batch)
    np.asarray(w)  # caller's array survives
    state2 = ts.init(params)
    state2, m = ts.step(state2, batch)
    assert np.isfinite(float(m["loss"]))


def test_grad_accumulation_matches_full_batch(mesh, problem):
    """accum_steps=k (k scanned microbatches, one collective+update) must
    reproduce the single-pass step: grads average over microbatches exactly
    as the full-batch mean does."""
    params, batches, ref_params, ref_losses = problem
    ts = build_train_step(
        _loss_fn,
        params,
        optimizer=fused_sgd(lr=0.1, momentum=0.9),
        mesh=mesh,
        mode="dear",
        threshold_mb=0.0008,
        accum_steps=4,
        donate=False,
    )
    state = ts.init(params)
    losses = []
    for b in batches:
        state, m = ts.step(state, b)
        losses.append(float(m["loss"]))
    np.testing.assert_allclose(losses, ref_losses, rtol=1e-5, atol=1e-6)
    got = ts.gather_params(state)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5
        ),
        got,
        ref_params,
    )


def test_grad_accumulation_validates(mesh, problem):
    params, batches, _, _ = problem
    with pytest.raises(ValueError, match="accum_steps"):
        build_train_step(_loss_fn, params, mesh=mesh, accum_steps=0)
    ts = build_train_step(
        _loss_fn, params, mesh=mesh, threshold_mb=None, accum_steps=3,
        donate=False,
    )
    state = ts.init(params)
    # 64-sample batch over 8 devices = 8/device, not divisible by 3
    with pytest.raises(Exception, match="divisible by accum_steps"):
        ts.step(state, batches[0])


def test_grad_accumulation_rng_distinct_keys(mesh):
    """Each microbatch sees a distinct dropout key (folded from the step
    key), so accumulated stochastic losses differ from accum=1 on the same
    seed but remain finite and step-varying."""
    params = {"w": {"kernel": jnp.ones((4, 4))}}

    def loss2(p, b, rng):
        mask = jax.random.bernoulli(rng, 0.5, (4,))
        return jnp.sum((b * mask) @ p["w"]["kernel"])

    ts = build_train_step(loss2, params, mesh=mesh, threshold_mb=None,
                          rng_seed=7, accum_steps=2, donate=False)
    state = ts.init(params)
    b = jnp.ones((16, 4))
    losses = []
    for _ in range(3):
        state, m = ts.step(state, b)
        losses.append(float(m["loss"]))
    assert all(np.isfinite(losses))
    assert len(set(losses)) > 1, losses


def test_multi_step_equals_sequential_steps(mesh):
    """ts.multi_step(n) (one scanned program) must equal n sequential
    ts.step calls exactly — state and final metrics."""
    params = _mlp_params(jax.random.PRNGKey(0))
    batch = _data(jax.random.PRNGKey(50))
    opt = fused_sgd(lr=0.05, momentum=0.9)

    ts = build_train_step(_loss_fn, params, mesh=mesh, optimizer=opt,
                          threshold_mb=0.0008, donate=False)
    s_seq = ts.init(params)
    for _ in range(4):
        s_seq, m_seq = ts.step(s_seq, batch)

    s_scan = ts.init(params)
    s_scan, m_scan = ts.multi_step(4)(s_scan, batch)

    assert float(m_scan["loss"]) == pytest.approx(float(m_seq["loss"]),
                                                  rel=1e-6)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-7
        ),
        s_scan.buffers, s_seq.buffers,
    )


def test_fused_adamw_matches_torch():
    """fused_adamw must reproduce torch.optim.AdamW exactly (the fused-path
    generalization the reference lacks — its fused path is SGD-only,
    dear/dear_dopt.py:310-336)."""
    import torch

    from dear_pytorch_tpu.ops.fused_sgd import fused_adamw

    rng = np.random.RandomState(0)
    p0 = rng.randn(257).astype(np.float32)  # odd length: no shape luck
    grads = [rng.randn(257).astype(np.float32) for _ in range(6)]
    lr, betas, eps, wd = 1e-2, (0.9, 0.999), 1e-8, 0.1

    tp = torch.nn.Parameter(torch.tensor(p0))
    topt = torch.optim.AdamW([tp], lr=lr, betas=betas, eps=eps,
                             weight_decay=wd)
    opt = fused_adamw(lr=lr, betas=betas, eps=eps, weight_decay=wd)
    jp = jnp.asarray(p0)
    st = opt.init(jp)
    for g in grads:
        tp.grad = torch.tensor(g)
        topt.step()
        jp, st = opt.update(jnp.asarray(g), st, jp)
        # torch's foreach kernels contract FMAs differently, so agreement
        # is to f32 rounding (observed <=1 ULP/step drift), not bit-exact
        np.testing.assert_allclose(
            np.asarray(jp), tp.detach().numpy(), rtol=1e-5, atol=1e-6
        )


def test_adamw_dear_schedule_matches_single_device(mesh, world):
    """The sharded dear schedule with fused_adamw (Adam state sharded with
    the params — ZeRO-1 where it matters most, state being 2x params) must
    equal a single-device AdamW loop step for step."""
    from dear_pytorch_tpu.ops.fused_sgd import fused_adamw

    params = _mlp_params(jax.random.PRNGKey(3))
    batches = [_data(jax.random.PRNGKey(200 + i)) for i in range(4)]
    mk = lambda: fused_adamw(lr=1e-2, weight_decay=0.05)  # noqa: E731

    # single-device reference: flat per-leaf updates
    opt = mk()
    flat, treedef = jax.tree_util.tree_flatten(params)
    states = [opt.init(p.reshape(-1)) for p in flat]
    ref_losses = []
    cur = params
    for b in batches:
        loss, grads = jax.value_and_grad(_loss_fn)(cur, b)
        ref_losses.append(float(loss))
        gflat = jax.tree_util.tree_leaves(grads)
        new_flat = []
        for i, (p, g) in enumerate(zip(flat, gflat)):
            newp, states[i] = opt.update(g.reshape(-1), states[i],
                                         p.reshape(-1))
            new_flat.append(newp.reshape(p.shape))
        flat = new_flat
        cur = jax.tree_util.tree_unflatten(treedef, flat)

    ts = build_train_step(
        _loss_fn, params, optimizer=mk(), mesh=mesh, mode="dear",
        threshold_mb=0.0008, donate=False,
    )
    assert ts.plan.num_buckets >= 2
    state = ts.init(params)
    losses = []
    for b in batches:
        state, m = ts.step(state, b)
        losses.append(float(m["loss"]))
    np.testing.assert_allclose(losses, ref_losses, rtol=1e-5, atol=1e-6)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5
        ),
        ts.gather_params(state), cur,
    )


def test_lamb_sharded_trust_ratios_exact(mesh, world):
    """fused_lamb on the dear schedule: per-parameter trust ratios must be
    EXACT even though every parameter spans shard boundaries (world devices
    each own 1/world of each bucket). Pinned against a per-leaf
    single-device LAMB written directly from the paper."""
    from dear_pytorch_tpu.ops.fused_sgd import fused_lamb

    lr, b1, b2, eps, wd = 1e-2, 0.9, 0.999, 1e-6, 0.05
    params = _mlp_params(jax.random.PRNGKey(5))
    batches = [_data(jax.random.PRNGKey(300 + i)) for i in range(4)]

    # single-device reference: leaf-shaped state, python floats for norms
    cur = jax.tree.map(lambda x: np.asarray(x, np.float64), params)
    m_tree = jax.tree.map(np.zeros_like, cur)
    v_tree = jax.tree.map(np.zeros_like, cur)
    ref_losses = []
    for t, b in enumerate(batches, start=1):
        loss, grads = jax.value_and_grad(_loss_fn)(
            jax.tree.map(lambda x: jnp.asarray(x, jnp.float32), cur), b
        )
        ref_losses.append(float(loss))
        grads = jax.tree.map(lambda g: np.asarray(g, np.float64), grads)

        def upd(p, g, m, v):
            m = b1 * m + (1 - b1) * g
            v = b2 * v + (1 - b2) * g * g
            mh = m / (1 - b1 ** t)
            vh = v / (1 - b2 ** t)
            u = mh / (np.sqrt(vh) + eps) + wd * p
            wn, un = np.linalg.norm(p), np.linalg.norm(u)
            trust = wn / max(un, 1e-12) if (wn > 0 and un > 0) else 1.0
            return p - lr * trust * u, m, v

        flat_p, treedef = jax.tree_util.tree_flatten(cur)
        flat_g = jax.tree_util.tree_leaves(grads)
        flat_m = jax.tree_util.tree_leaves(m_tree)
        flat_v = jax.tree_util.tree_leaves(v_tree)
        out = [upd(p, g, m, v) for p, g, m, v
               in zip(flat_p, flat_g, flat_m, flat_v)]
        cur = jax.tree_util.tree_unflatten(treedef, [o[0] for o in out])
        m_tree = jax.tree_util.tree_unflatten(treedef, [o[1] for o in out])
        v_tree = jax.tree_util.tree_unflatten(treedef, [o[2] for o in out])

    ts = build_train_step(
        _loss_fn, params,
        optimizer=fused_lamb(lr=lr, betas=(b1, b2), eps=eps,
                             weight_decay=wd),
        mesh=mesh, mode="dear", threshold_mb=0.0008, donate=False,
    )
    assert ts.plan.num_buckets >= 2
    state = ts.init(params)
    losses = []
    for b in batches:
        state, mtr = ts.step(state, b)
        losses.append(float(mtr["loss"]))
    np.testing.assert_allclose(losses, ref_losses, rtol=1e-5, atol=1e-6)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5
        ),
        ts.gather_params(state), cur,
    )


def test_lamb_works_under_fsdp(mesh, world):
    """The layerwise (segment-metadata) update path must compose with the
    fsdp schedule too — grads there are already shards from the AD
    transpose, and dear-vs-fsdp numerics must agree."""
    from dear_pytorch_tpu.ops.fused_sgd import fused_lamb

    params = _mlp_params(jax.random.PRNGKey(6))
    batches = [_data(jax.random.PRNGKey(400 + i)) for i in range(3)]
    mk = lambda: fused_lamb(lr=1e-2, weight_decay=0.05)  # noqa: E731

    runs = {}
    for mode in ("dear", "fsdp"):
        ts = build_train_step(
            _loss_fn, params, optimizer=mk(), mesh=mesh, mode=mode,
            threshold_mb=0.0008, donate=False,
        )
        state = ts.init(params)
        losses = []
        for b in batches:
            state, m = ts.step(state, b)
            losses.append(float(m["loss"]))
        runs[mode] = losses
    np.testing.assert_allclose(runs["dear"], runs["fsdp"],
                               rtol=1e-6, atol=1e-7)


def test_multi_step_does_not_stack_state(mesh):
    """The scanned n-step program must carry ONE state through the loop,
    not stack per-step buffers: its temp memory stays within a constant
    factor of the single-step program's (a scan that accumulated state
    would grow ~n-fold)."""
    params = _mlp_params(jax.random.PRNGKey(0))
    batch = _data(jax.random.PRNGKey(50))
    ts = build_train_step(
        _loss_fn, params, mesh=mesh,
        optimizer=fused_sgd(lr=0.05, momentum=0.9),
        threshold_mb=0.0008, donate=False,
    )
    state = ts.init(params)

    def temp_bytes(compiled):
        return compiled.memory_analysis().temp_size_in_bytes

    one = temp_bytes(ts.lower(state, batch).compile())
    eight = temp_bytes(ts.multi_step(8).lower(state, batch).compile())
    assert eight < 3 * max(one, 1), (one, eight)
