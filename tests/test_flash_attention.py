"""Pallas flash-attention kernels vs the dense reference — forward and
backward, causal and padded, f32 and bf16. Runs the EXACT kernel code via
interpret mode on the CPU test mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dear_pytorch_tpu.ops.flash_attention import (
    flash_attention,
    make_flash_attention_impl,
)
from dear_pytorch_tpu.parallel.ring_attention import full_attention

B, S, H, D = 2, 64, 4, 16


def _qkv(key, dtype=jnp.float32):
    ks = jax.random.split(key, 3)
    return tuple(
        jax.random.normal(k, (B, S, H, D), dtype) for k in ks
    )


@pytest.mark.parametrize("causal", [False, True])
def test_forward_matches_dense(causal):
    q, k, v = _qkv(jax.random.PRNGKey(0))
    got = flash_attention(q, k, v, causal=causal)
    want = full_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_forward_with_padding_mask():
    q, k, v = _qkv(jax.random.PRNGKey(1))
    kv_mask = jnp.arange(S)[None, :] < jnp.array([[40], [64]])  # per-batch
    got = flash_attention(q, k, v, kv_mask=kv_mask)
    # dense reference with additive mask
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * (D ** -0.5)
    s = jnp.where(kv_mask[:, None, None, :], s, -jnp.inf)
    want = jnp.einsum("bhqk,bkhd->bqhd", jax.nn.softmax(s, axis=-1), v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_gradients_match_dense(causal):
    q, k, v = _qkv(jax.random.PRNGKey(2))

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=causal) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(full_attention(q, k, v, causal=causal) ** 2)

    got = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    want = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for g, w, name in zip(got, want, "qkv"):
        np.testing.assert_allclose(
            np.asarray(g), np.asarray(w), rtol=5e-4, atol=5e-5,
            err_msg=f"d{name}",
        )


def test_gradients_with_padding_mask():
    q, k, v = _qkv(jax.random.PRNGKey(3))
    kv_mask = jnp.arange(S)[None, :] < jnp.array([[48], [16]])

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, kv_mask=kv_mask) ** 2)

    def loss_dense(q, k, v):
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * (D ** -0.5)
        s = jnp.where(kv_mask[:, None, None, :], s, -jnp.inf)
        out = jnp.einsum("bhqk,bkhd->bqhd", jax.nn.softmax(s, axis=-1), v)
        return jnp.sum(out ** 2)

    got = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    want = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for g, w, name in zip(got, want, "qkv"):
        np.testing.assert_allclose(
            np.asarray(g), np.asarray(w), rtol=5e-4, atol=5e-5,
            err_msg=f"d{name}",
        )


def test_bf16_inputs():
    q, k, v = _qkv(jax.random.PRNGKey(4), jnp.bfloat16)
    got = flash_attention(q, k, v)
    assert got.dtype == jnp.bfloat16
    want = full_attention(q, k, v)
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want, np.float32),
        rtol=5e-2, atol=5e-2,
    )


def test_bert_impl_contract_and_dropout_fallback():
    """The attention_impl adapter matches the dense model path exactly at
    dropout 0 and falls back to the dense implementation (same rng stream)
    when dropout is active."""
    from dear_pytorch_tpu.models.bert import dot_product_attention

    impl = make_flash_attention_impl()
    q, k, v = _qkv(jax.random.PRNGKey(5))
    additive = jnp.where(
        jnp.arange(S)[None, None, None, :] < 50, 0.0, _big := -1e9
    ) * jnp.ones((B, 1, 1, 1))
    got = impl(q, k, v, additive)
    want = dot_product_attention(q, k, v, additive)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)
    rng = jax.random.PRNGKey(9)
    got_dp = impl(q, k, v, additive, dropout_rng=rng, dropout_rate=0.5)
    want_dp = dot_product_attention(q, k, v, additive, dropout_rng=rng,
                                    dropout_rate=0.5)
    np.testing.assert_allclose(np.asarray(got_dp), np.asarray(want_dp),
                               rtol=1e-5, atol=1e-6)


def test_bert_end_to_end_with_flash_impl():
    """A BERT built with the flash impl produces the same logits as the
    default dense-attention BERT (dropout off)."""
    from dear_pytorch_tpu.models import data as mdata
    from dear_pytorch_tpu.models.bert import BertConfig, BertForPreTraining

    cfg = BertConfig(
        num_hidden_layers=2, hidden_size=32, num_attention_heads=4,
        intermediate_size=64, vocab_size=64, max_position_embeddings=32,
        hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0,
    )
    batch = mdata.synthetic_bert_batch(
        jax.random.PRNGKey(2), 2, seq_len=32, vocab_size=64
    )
    dense = BertForPreTraining(cfg)
    flash = BertForPreTraining(cfg, attention_impl=make_flash_attention_impl())
    params = dense.init(
        {"params": jax.random.PRNGKey(0)}, batch["input_ids"], train=False
    )["params"]
    out_d, nsp_d = dense.apply(
        {"params": params}, batch["input_ids"], batch["token_type_ids"],
        batch["attention_mask"], train=False,
    )
    out_f, nsp_f = flash.apply(
        {"params": params}, batch["input_ids"], batch["token_type_ids"],
        batch["attention_mask"], train=False,
    )
    np.testing.assert_allclose(np.asarray(out_f), np.asarray(out_d),
                               rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(np.asarray(nsp_f), np.asarray(nsp_d),
                               rtol=2e-4, atol=2e-4)


def test_mosaic_block_rule():
    """Every BlockSpec the wrappers emit must satisfy Mosaic's real-TPU
    block rule (trailing dims (8k, 128k) or equal to the array's): the CPU
    interpret path never checks it, so this pins the rule host-side. The
    (1, S) rank-2 vector specs that passed the whole CPU suite but died on
    first chip contact (2026-07-31) are the regression under test."""
    from dear_pytorch_tpu.ops.flash_attention import check_mosaic_block

    # legal: full-dim blocks, 8/128-multiples, trailing singletons
    check_mosaic_block((1, 128, 64), (384, 128, 64))
    check_mosaic_block((1, 128, 1), (384, 128, 1))
    check_mosaic_block((1, 64, 64), (384, 192, 64))
    # the round-4 on-chip failure shape: rank-2 (1, S) over [BH, S]
    with pytest.raises(ValueError, match="Mosaic-illegal"):
        check_mosaic_block((1, 128), (384, 128))
    # sublane block neither 8-multiple nor full
    with pytest.raises(ValueError, match="second-to-last"):
        check_mosaic_block((1, 4, 64), (384, 192, 64))
    # lane block neither 128-multiple nor full
    with pytest.raises(ValueError, match="last block dim"):
        check_mosaic_block((1, 128, 32), (384, 128, 64))
    # dtype-aware sublane rule: 8 rows is legal for f32 but BELOW the
    # native (16, 128) tile for bf16 — must be rejected for 16-bit
    check_mosaic_block((1, 8, 128), (4, 256, 128), jnp.float32)
    with pytest.raises(ValueError, match="sublane tile 16"):
        check_mosaic_block((1, 8, 128), (4, 256, 128), jnp.bfloat16)
    with pytest.raises(ValueError, match="sublane tile 32"):
        check_mosaic_block((1, 16, 128), (4, 256, 128), jnp.int8)


def test_wrappers_reject_mosaic_illegal_blocks():
    """An odd sequence length that forces a tiny sub-tile query block must
    be rejected at trace time on every backend, not at Mosaic lowering on
    the chip."""
    rng = jax.random.PRNGKey(0)
    # S=132 -> largest halving divisor is 4 (132 = 4*33): below every
    # dtype's sublane tile
    q = jax.random.normal(rng, (2, 132, 2, 8), jnp.float32)
    with pytest.raises(ValueError, match="sublane tile"):
        flash_attention(q, q, q)
    # the ADVICE.md round-4 scenario: S=136 = 8*17 tiles to 8-row blocks,
    # which PASSES the naive %8 rule but mis-tiles bf16 on real chips
    qb = jax.random.normal(rng, (2, 136, 2, 8)).astype(jnp.bfloat16)
    with pytest.raises(ValueError, match="sublane tile"):
        flash_attention(qb, qb, qb)
