"""Cluster coordination layer (`resilience.cluster`) unit tests.

The consensus protocol is exercised here WITHOUT a real multi-process
cluster: N coordinators on N threads share one `LocalTransport` and
behave like N ranks (the real 2-process cluster legs live in
tests/test_multiprocess.py::test_coordinated_recovery_cluster and
scripts/chaos_check.py --procs 2). Also covers the per-host local
checkpoint format these protocols restore from.
"""

import os
import threading

import jax
import numpy as np
import pytest

from dear_pytorch_tpu.observability import tracer as T
from dear_pytorch_tpu.resilience import cluster as CL
from dear_pytorch_tpu.utils import checkpoint as ckpt


def run_ranks(n, fn, *, timeout_s=5.0):
    """Run ``fn(coordinator, rank)`` on ``n`` thread-ranks sharing one
    LocalTransport; returns the per-rank results, re-raising the first
    failure."""
    transport = CL.LocalTransport(n)
    cos = [
        CL.ClusterCoordinator(
            namespace="t", process_index=i, process_count=n,
            transport=transport, timeout_s=timeout_s, instance=0,
        )
        for i in range(n)
    ]
    results, errs = [None] * n, [None] * n

    def work(i):
        try:
            results[i] = fn(cos[i], i)
        except BaseException as exc:  # noqa: BLE001 - surfaced below
            errs[i] = exc

    threads = [threading.Thread(target=work, args=(i,)) for i in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    for e in errs:
        if e is not None:
            raise e
    return results


# -- exchange / consensus -----------------------------------------------------


def test_exchange_is_index_ordered():
    out = run_ranks(3, lambda co, i: co.exchange("hello", f"msg{i}"))
    assert out == [["msg0", "msg1", "msg2"]] * 3


def test_consensus_restore_step_intersects_views():
    views = {0: [12, 8, 4], 1: [8, 4], 2: [12, 8]}
    out = run_ranks(3, lambda co, i: co.consensus_restore_step(views[i]))
    assert out == [8, 8, 8]  # newest step EVERY rank verified


def test_consensus_restore_step_no_common_step():
    out = run_ranks(2, lambda co, i: co.consensus_restore_step([4] if i
                                                               else [8]))
    assert out == [None, None]


def test_consensus_restore_step_deferring_ranks():
    """None = "no local opinion" (shared storage: rank 0 verifies for
    everyone); deferring ranks are excluded from the intersection, and
    all-deferred yields nothing restorable."""
    out = run_ranks(3, lambda co, i: co.consensus_restore_step(
        [12, 8] if i == 0 else None))
    assert out == [12, 12, 12]
    out = run_ranks(2, lambda co, i: co.consensus_restore_step(None))
    assert out == [None, None]


def test_consensus_caps_candidates():
    co = CL.ClusterCoordinator(process_index=0, process_count=1,
                               max_candidates=2)
    # single-process fast path: newest of the capped local view
    assert co.consensus_restore_step(list(range(100))) == 99


# -- health verdicts ----------------------------------------------------------


def test_health_check_all_ok():
    out = run_ranks(2, lambda co, i: co.health_check(
        ok=True, fingerprint="same", step=1))
    for v in out:
        assert v.ok and not v.desync and not v.any_preempted
        assert v.unhealthy_ranks == ()


def test_health_check_any_rank_unhealthy_propagates():
    out = run_ranks(3, lambda co, i: co.health_check(ok=(i != 1), step=2))
    for v in out:
        assert not v.ok and v.unhealthy_ranks == (1,) and not v.desync


def test_health_check_desync_sentinel():
    out = run_ranks(2, lambda co, i: co.health_check(
        ok=True, fingerprint=f"fp{i}", step=3))
    for v in out:
        assert v.desync and not v.ok and v.unhealthy_ranks == ()


def test_health_check_preempt_propagates():
    out = run_ranks(2, lambda co, i: co.health_check(
        ok=True, fingerprint="same", step=4, preempted=(i == 0)))
    for v in out:
        assert v.any_preempted and v.ok  # preemption is not ill health


def test_unhealthy_rank_fingerprint_not_a_desync():
    # a NaN rank has no meaningful fingerprint: its (empty or stale) value
    # must not masquerade as replica divergence
    out = run_ranks(2, lambda co, i: co.health_check(
        ok=(i == 0), fingerprint="live" if i == 0 else "", step=5))
    for v in out:
        assert not v.ok and v.unhealthy_ranks == (1,) and not v.desync


# -- timeouts (dead-peer detection) -------------------------------------------


def test_exchange_peer_timeout():
    co = CL.ClusterCoordinator(
        namespace="solo", process_index=0, process_count=2,
        transport=CL.LocalTransport(2), timeout_s=0.2, instance=0)
    with pytest.raises(CL.PeerTimeout, match="no peer published"):
        co.exchange("health", "ok")  # rank 1 never shows up


def test_barrier_peer_timeout():
    co = CL.ClusterCoordinator(
        namespace="solo", process_index=0, process_count=2,
        transport=CL.LocalTransport(2), timeout_s=0.2, instance=0)
    with pytest.raises(CL.PeerTimeout):
        co.barrier("b")


def test_cluster_counters():
    prev = T._tracer
    tracer = T.Tracer([T.MemoryExporter()])
    T.set_tracer(tracer)
    try:
        run_ranks(2, lambda co, i: co.health_check(
            ok=True, fingerprint=f"fp{i}", step=1))
        co = CL.ClusterCoordinator(
            namespace="solo", process_index=0, process_count=2,
            transport=CL.LocalTransport(2), timeout_s=0.1, instance=0)
        with pytest.raises(CL.PeerTimeout):
            co.exchange("x", "y")
        c = tracer.counters()
        assert c["cluster.exchanges"] >= 3
        assert c["cluster.health_checks"] == 2
        assert c["cluster.desync_detected"] == 2
        assert c["cluster.peer_timeouts"] == 1
    finally:
        T.set_tracer(prev)


# -- single-process fast paths ------------------------------------------------


def test_single_process_fast_paths():
    co = CL.ClusterCoordinator(process_index=0, process_count=1)
    assert co.exchange("t", "x") == ["x"]
    assert co.consensus_restore_step([8, 4]) == 8
    assert co.consensus_restore_step([]) is None
    v = co.health_check(ok=True, fingerprint="f")
    assert v.ok and not v.desync
    co.barrier()  # no transport, no-op


def test_fingerprint_is_bit_exact():
    fp = CL.ClusterCoordinator.fingerprint
    assert fp(1.5) == fp(1.5)
    assert fp(1.5) != fp(1.5 + 1e-12)
    assert fp(np.float32(2.0)) != fp(np.float64(2.0))  # dtype-tagged
    # the FULL buffer is hashed: arrays agreeing on a prefix but
    # diverging later must not collide (the desync sentinel's contract)
    a = np.zeros(100, np.float32)
    b = a.copy()
    b[50] = 1.0
    assert fp(a) != fp(b)
    assert fp(a.reshape(4, 25)) != fp(a)  # shape-tagged


def test_enabled_by_env(monkeypatch):
    monkeypatch.delenv(CL.CLUSTER_ENV, raising=False)
    assert CL.enabled_by_env()
    monkeypatch.setenv(CL.CLUSTER_ENV, "0")
    assert not CL.enabled_by_env()


def test_unknown_transport_name_lists_valid_ones():
    with pytest.raises(ValueError, match="'kv', 'allgather', and 'file:<dir>'"):
        CL.ClusterCoordinator(process_index=0, process_count=2,
                              transport="carrier-pigeon")


# -- the allgather transport (encode/decode; single-process collective) -------


def test_allgather_transport_roundtrip():
    t = CL.AllgatherTransport(0, 1)
    t.set("ns/tag/0/0", "payload-π")  # non-ascii survives the byte slot
    assert t.get("ns/tag/0/0", 1.0) == "payload-π"
    t.delete("ns/tag/0/0")
    t.barrier("ns/b/0", 1.0)


def test_allgather_transport_rejects_oversized_payload():
    t = CL.AllgatherTransport(0, 1)
    with pytest.raises(CL.ClusterError, match="byte"):
        t.set("ns/tag/0/0", "x" * 4096)


# -- the per-host local checkpoint format -------------------------------------


def test_local_checkpoint_roundtrip(tmp_path):
    import jax.numpy as jnp

    tree = {
        "w": jnp.arange(6.0, dtype=jnp.float32).reshape(2, 3),
        "b16": jnp.ones((3,), dtype=jnp.bfloat16) * 1.5,
        "step": np.int64(7),
        "empty": np.zeros((0, 4), np.float32),
    }
    d = str(tmp_path / "step_0000000007")
    ckpt.local_save(d, tree)
    assert ckpt.is_local_checkpoint(d)
    out = ckpt.local_restore(d, tree)
    assert out["w"].dtype == jnp.float32 and out["b16"].dtype == jnp.bfloat16
    np.testing.assert_array_equal(np.asarray(out["w"]), np.asarray(tree["w"]))
    np.testing.assert_array_equal(
        np.asarray(out["b16"], np.float32),
        np.asarray(tree["b16"], np.float32))
    assert int(out["step"]) == 7
    assert out["empty"].shape == (0, 4)
    # restored jax leaves land on the template's devices
    assert isinstance(out["w"], jax.Array)


def test_local_checkpoint_commit_is_atomic(tmp_path):
    d = str(tmp_path / "step_0000000001")
    ckpt.local_save(d, {"x": np.ones((2,))})
    assert os.path.isdir(d)
    assert not os.path.exists(d + ckpt._LOCAL_TMP_MARK)  # renamed away


def test_local_checkpoint_overwrites_stale_step_dir(tmp_path):
    """Replay after a consensus rollback re-reaches a step whose
    corrupted dir is still on disk — the fresh save must replace it, not
    crash on the rename (and a crash-leftover tmp dir must not break the
    next save either)."""
    d = str(tmp_path / "step_0000000004")
    ckpt.local_save(d, {"x": np.ones((2,))})
    os.makedirs(d + ckpt._LOCAL_TMP_MARK)  # interrupted-save leftover
    ckpt.local_save(d, {"x": np.full((2,), 7.0)})
    out = ckpt.local_restore(d, {"x": np.zeros((2,))})
    np.testing.assert_array_equal(out["x"], np.full((2,), 7.0))
    assert not os.path.exists(d + ckpt._LOCAL_TMP_MARK)
    assert not os.path.exists(d + ckpt._LOCAL_TMP_MARK + "-old")


def test_local_checkpoint_rejects_structure_mismatch(tmp_path):
    d = str(tmp_path / "step_0000000002")
    ckpt.local_save(d, {"x": np.ones((2,)), "y": np.zeros((1,))})
    with pytest.raises(ValueError, match="different model"):
        ckpt.local_restore(d, {"x": np.ones((2,))})


def test_per_host_storage_env(monkeypatch):
    monkeypatch.delenv(ckpt.SHARED_ENV, raising=False)
    assert not ckpt.per_host_storage()
    monkeypatch.setenv(ckpt.SHARED_ENV, "0")
    assert ckpt.per_host_storage()


# -- the coordinated guard paths, driven single-process via a stub ------------


class _StubCoordinator:
    """Plays a 2-process coordinator against a single-process guard: the
    verdict/consensus logic is scripted, so the guard's coordinated
    branches (deferred errors, co-scheduled fault drain, consensus
    rollback) are unit-testable without a cluster."""

    process_count = 2
    index = 0
    max_candidates = 16

    def __init__(self):
        self.health_calls = []

    def health_check(self, ok, *, fingerprint="", step=None,
                     preempted=False):
        self.health_calls.append((step, ok))
        return CL.HealthVerdict(
            ok=ok, unhealthy_ranks=() if ok else (0,), desync=False,
            any_preempted=False, fingerprints=(fingerprint,))

    def consensus_restore_step(self, local_steps):
        return max(local_steps) if local_steps else None


def test_coordinated_guard_drains_stacked_faults(tmp_path, mesh):
    """A nan co-scheduled with a deferred exc at the SAME attempt must
    still be consumed (schedules drain identically on every rank), and
    the guard must take the consensus rollback path."""
    from dear_pytorch_tpu.ops.fused_sgd import fused_sgd
    from dear_pytorch_tpu.parallel import build_train_step
    from dear_pytorch_tpu.resilience import Fault, FaultInjector
    from dear_pytorch_tpu.utils.guard import GuardedTrainer

    from tests.test_dear_numerics import _data, _loss_fn, _mlp_params

    params = _mlp_params(jax.random.PRNGKey(0))
    ts = build_train_step(
        _loss_fn, params, mesh=mesh, threshold_mb=0.0008, donate=False,
        optimizer=fused_sgd(lr=0.05, momentum=0.9),
    )
    inj = FaultInjector([Fault(kind="exc", step=6, rank=0),
                         Fault(kind="nan", step=6)], own_rank=0)
    co = _StubCoordinator()
    tr = GuardedTrainer(ts, str(tmp_path / "g"), params, check_every=1,
                        checkpoint_every=4, injector=inj, coordinator=co)
    assert tr._coordinated
    rolls = []
    tr.on_rollback = lambda c, at: rolls.append(at)
    state = ts.init(params)
    for i in range(8):
        state, _ = tr.step(state, _data(jax.random.PRNGKey(100 + i)))
    assert inj.pending == 0, "stacked same-step faults must both drain"
    assert sorted(f.kind for f in inj.fired) == ["exc", "nan"]
    assert rolls == [4]
    # the guard synced at every check interval (check_every=1)
    assert len(co.health_calls) == 8
