"""Model-zoo tests: shapes, param-count parity with the reference's model
sources (torchvision counts for CNNs, HF BertForPreTraining for BERT), and
trainability through the DeAR step."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dear_pytorch_tpu import models
from dear_pytorch_tpu.models import data


def _param_count(module, *args, rngs=None):
    rngs = rngs or {"params": jax.random.PRNGKey(0)}
    shapes = jax.eval_shape(lambda: module.init(rngs, *args, train=False))
    return sum(int(np.prod(x.shape)) for x in jax.tree.leaves(shapes["params"]))


# Exact torchvision parameter counts (the reference instantiates these by
# name, dear/imagenet_benchmark.py:88-95).
TORCHVISION_COUNTS = {
    "resnet50": 25_557_032,
    "resnet18": 11_689_512,
    "densenet201": 20_013_928,
    "vgg16": 138_357_544,
    "inceptionv4": 42_679_816,  # Cadene inceptionv4 (reference dear/inceptionv4.py)
}


@pytest.mark.parametrize("name,count", sorted(TORCHVISION_COUNTS.items()))
def test_cnn_param_parity(name, count):
    size = 299 if name == "inceptionv4" else 224
    m = models.get_model(name)
    assert _param_count(m, jnp.zeros((1, size, size, 3))) == count


def test_resnet50_forward_shape():
    m = models.get_model("resnet50")
    x = jnp.zeros((2, 64, 64, 3))
    variables = m.init({"params": jax.random.PRNGKey(0)}, x, train=False)
    out = m.apply(variables, x, train=False)
    assert out.shape == (2, 1000)
    assert out.dtype == jnp.float32


def test_s2d_stem_exactly_matches_conv7_stem():
    """The space-to-depth stem with the repacked kernel is the SAME
    function as the 7x7/s2 stem — bitwise-comparable up to conv reduction
    order (f32 tolerance). This is what makes the s2d variant a safe perf
    substitution and keeps torchvision checkpoint conversion valid."""
    from dear_pytorch_tpu.models import resnet as R

    key = jax.random.PRNGKey(7)
    x = jax.random.normal(key, (2, 224, 224, 3), jnp.float32)
    m7 = models.get_model("resnet18")
    ms = models.get_model("resnet18", stem="s2d")
    v7 = m7.init({"params": jax.random.PRNGKey(1)}, x, train=False)
    k7 = v7["params"]["stem_conv"]["kernel"]
    assert k7.shape == (7, 7, 3, 64)
    vs = jax.tree.map(lambda a: a, v7)  # copy structure
    vs["params"] = dict(v7["params"])
    vs["params"]["stem_conv"] = {
        "kernel": R.repack_stem_conv7_to_s2d(k7)
    }
    out7 = m7.apply(v7, x, train=False)
    outs = ms.apply(vs, x, train=False)
    np.testing.assert_allclose(np.asarray(outs), np.asarray(out7),
                               rtol=2e-4, atol=2e-4)


def test_s2d_stem_param_count_and_grad():
    """s2d resnet50 keeps the downstream architecture identical (only the
    stem kernel reshapes 7*7*3 -> 4*4*12 = same 9408+pad... exactly 147->192
    inputs x 64, so counts differ by the documented zero-pad rows) and
    trains (grads flow through space_to_depth)."""
    m = models.get_model("resnet50", stem="s2d")
    x = jnp.zeros((1, 64, 64, 3))
    variables = m.init({"params": jax.random.PRNGKey(0)}, x, train=False)
    k = variables["params"]["stem_conv"]["kernel"]
    assert k.shape == (4, 4, 12, 64)

    def loss(p):
        out = m.apply({"params": p, **{k2: v for k2, v in variables.items()
                                       if k2 != "params"}},
                      x, train=False)
        return jnp.sum(out ** 2)

    g = jax.grad(loss)(variables["params"])
    assert jnp.isfinite(g["stem_conv"]["kernel"]).all()


def test_mnistnet_forward():
    m = models.get_model("mnistnet")
    batch = data.synthetic_mnist_batch(jax.random.PRNGKey(0), 4)
    variables = m.init({"params": jax.random.PRNGKey(0)}, batch["image"],
                       train=False)
    out = m.apply(variables, batch["image"], train=False)
    assert out.shape == (4, 10)
    # log_softmax rows sum to 1 in prob space
    np.testing.assert_allclose(np.exp(out).sum(-1), 1.0, rtol=1e-5)


def test_bert_base_param_parity():
    # HF BertForPreTraining('bert-base-uncased') ≈ 110.1M; ours pads the
    # vocab to %8 (+6 rows, reference dear/bert_benchmark.py:72-78) and ties
    # the MLM decoder to the embedding as HF does.
    m = models.get_model("bert_base")
    ids = jnp.zeros((1, 16), jnp.int32)
    n = _param_count(
        m, ids, rngs={"params": jax.random.PRNGKey(0)})
    assert abs(n - 110_106_428) < 50_000, n


def test_bert_forward_and_loss():
    cfg = models.BertConfig(num_hidden_layers=2, hidden_size=64,
                            num_attention_heads=4, intermediate_size=128,
                            vocab_size=1000, max_position_embeddings=64)
    m = models.BertForPreTraining(cfg)
    batch = data.synthetic_bert_batch(jax.random.PRNGKey(0), 2, seq_len=16,
                                      vocab_size=1000)
    variables = m.init({"params": jax.random.PRNGKey(0)},
                       batch["input_ids"], train=False)
    logits, nsp = m.apply(variables, batch["input_ids"],
                          batch["token_type_ids"], batch["attention_mask"],
                          train=False)
    assert logits.shape == (2, 16, cfg.padded_vocab_size)
    assert nsp.shape == (2, 2)
    loss = models.bert_pretraining_loss(
        logits, nsp, batch["masked_lm_labels"], batch["next_sentence_labels"])
    assert np.isfinite(float(loss)) and float(loss) > 0


def test_bert_trains_with_dear(mesh):
    """End-to-end: tiny BERT under the DeAR schedule learns (loss falls)."""
    from dear_pytorch_tpu.parallel import dear as D

    cfg = models.BertConfig(num_hidden_layers=2, hidden_size=32,
                            num_attention_heads=2, intermediate_size=64,
                            vocab_size=128, max_position_embeddings=32,
                            hidden_dropout_prob=0.0,
                            attention_probs_dropout_prob=0.0)
    m = models.BertForPreTraining(cfg)
    batch = data.synthetic_bert_batch(jax.random.PRNGKey(1), 16, seq_len=8,
                                      vocab_size=128)
    params = m.init({"params": jax.random.PRNGKey(0)}, batch["input_ids"],
                    train=False)["params"]

    def loss_fn(p, b):
        logits, nsp = m.apply({"params": p}, b["input_ids"],
                              b["token_type_ids"], b["attention_mask"],
                              train=False)
        return models.bert_pretraining_loss(
            logits, nsp, b["masked_lm_labels"], b["next_sentence_labels"])

    from dear_pytorch_tpu.ops.fused_sgd import fused_sgd
    ts = D.build_train_step(loss_fn, params, mesh=mesh, mode="dear",
                            threshold_mb=1.0, optimizer=fused_sgd(lr=0.1))
    state = ts.init(params)
    losses = []
    for _ in range(8):
        state, metrics = ts.step(state, batch)
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0], losses
