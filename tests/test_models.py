"""Model-zoo tests: shapes, param-count parity with the reference's model
sources (torchvision counts for CNNs, HF BertForPreTraining for BERT), and
trainability through the DeAR step."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dear_pytorch_tpu import models
from dear_pytorch_tpu.models import data


def _param_count(module, *args, rngs=None):
    rngs = rngs or {"params": jax.random.PRNGKey(0)}
    shapes = jax.eval_shape(lambda: module.init(rngs, *args, train=False))
    return sum(int(np.prod(x.shape)) for x in jax.tree.leaves(shapes["params"]))


# Exact torchvision parameter counts (the reference instantiates these by
# name, dear/imagenet_benchmark.py:88-95).
TORCHVISION_COUNTS = {
    "resnet50": 25_557_032,
    "resnet18": 11_689_512,
    "densenet201": 20_013_928,
    "vgg16": 138_357_544,
    "inceptionv4": 42_679_816,  # Cadene inceptionv4 (reference dear/inceptionv4.py)
}


@pytest.mark.parametrize("name,count", sorted(TORCHVISION_COUNTS.items()))
def test_cnn_param_parity(name, count):
    size = 299 if name == "inceptionv4" else 224
    m = models.get_model(name)
    assert _param_count(m, jnp.zeros((1, size, size, 3))) == count


def test_resnet50_forward_shape():
    m = models.get_model("resnet50")
    x = jnp.zeros((2, 64, 64, 3))
    variables = m.init({"params": jax.random.PRNGKey(0)}, x, train=False)
    out = m.apply(variables, x, train=False)
    assert out.shape == (2, 1000)
    assert out.dtype == jnp.float32


def test_mnistnet_forward():
    m = models.get_model("mnistnet")
    batch = data.synthetic_mnist_batch(jax.random.PRNGKey(0), 4)
    variables = m.init({"params": jax.random.PRNGKey(0)}, batch["image"],
                       train=False)
    out = m.apply(variables, batch["image"], train=False)
    assert out.shape == (4, 10)
    # log_softmax rows sum to 1 in prob space
    np.testing.assert_allclose(np.exp(out).sum(-1), 1.0, rtol=1e-5)


def test_bert_base_param_parity():
    # HF BertForPreTraining('bert-base-uncased') ≈ 110.1M; ours pads the
    # vocab to %8 (+6 rows, reference dear/bert_benchmark.py:72-78) and ties
    # the MLM decoder to the embedding as HF does.
    m = models.get_model("bert_base")
    ids = jnp.zeros((1, 16), jnp.int32)
    n = _param_count(
        m, ids, rngs={"params": jax.random.PRNGKey(0)})
    assert abs(n - 110_106_428) < 50_000, n


def test_bert_forward_and_loss():
    cfg = models.BertConfig(num_hidden_layers=2, hidden_size=64,
                            num_attention_heads=4, intermediate_size=128,
                            vocab_size=1000, max_position_embeddings=64)
    m = models.BertForPreTraining(cfg)
    batch = data.synthetic_bert_batch(jax.random.PRNGKey(0), 2, seq_len=16,
                                      vocab_size=1000)
    variables = m.init({"params": jax.random.PRNGKey(0)},
                       batch["input_ids"], train=False)
    logits, nsp = m.apply(variables, batch["input_ids"],
                          batch["token_type_ids"], batch["attention_mask"],
                          train=False)
    assert logits.shape == (2, 16, cfg.padded_vocab_size)
    assert nsp.shape == (2, 2)
    loss = models.bert_pretraining_loss(
        logits, nsp, batch["masked_lm_labels"], batch["next_sentence_labels"])
    assert np.isfinite(float(loss)) and float(loss) > 0


def test_bert_trains_with_dear(mesh):
    """End-to-end: tiny BERT under the DeAR schedule learns (loss falls)."""
    from dear_pytorch_tpu.parallel import dear as D

    cfg = models.BertConfig(num_hidden_layers=2, hidden_size=32,
                            num_attention_heads=2, intermediate_size=64,
                            vocab_size=128, max_position_embeddings=32,
                            hidden_dropout_prob=0.0,
                            attention_probs_dropout_prob=0.0)
    m = models.BertForPreTraining(cfg)
    batch = data.synthetic_bert_batch(jax.random.PRNGKey(1), 16, seq_len=8,
                                      vocab_size=128)
    params = m.init({"params": jax.random.PRNGKey(0)}, batch["input_ids"],
                    train=False)["params"]

    def loss_fn(p, b):
        logits, nsp = m.apply({"params": p}, b["input_ids"],
                              b["token_type_ids"], b["attention_mask"],
                              train=False)
        return models.bert_pretraining_loss(
            logits, nsp, b["masked_lm_labels"], b["next_sentence_labels"])

    from dear_pytorch_tpu.ops.fused_sgd import fused_sgd
    ts = D.build_train_step(loss_fn, params, mesh=mesh, mode="dear",
                            threshold_mb=1.0, optimizer=fused_sgd(lr=0.1))
    state = ts.init(params)
    losses = []
    for _ in range(8):
        state, metrics = ts.step(state, batch)
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0], losses
