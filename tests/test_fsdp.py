"""The 'fsdp' (ZeRO-3) schedule: re-gather-in-backward via AD transpose.

Exact loss/param parity with the other schedules is covered by the
parametrized baseline test in test_dear_numerics.py; here we check the
structural claims: the backward pass contains a SECOND per-bucket gather
(rematerialized by the named checkpoint policy instead of keeping full
params live), the reduce-scatter appears as the gather's transpose, the
gather_dtype cast halves communicated bytes, and composition with
accumulation / validation of incompatible options.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dear_pytorch_tpu.ops.fused_sgd import fused_sgd
from dear_pytorch_tpu.parallel import build_train_step

from test_dear_numerics import _data, _loss_fn, _mlp_params


def _count(text: str, needle: str) -> int:
    return text.count(needle)


@pytest.fixture(scope="module")
def problem():
    params = _mlp_params(jax.random.PRNGKey(0))
    batch = _data(jax.random.PRNGKey(100))
    return params, batch


def _build(params, mesh, mode, **kw):
    return build_train_step(
        _loss_fn,
        params,
        optimizer=fused_sgd(lr=0.1, momentum=0.9),
        mesh=mesh,
        mode=mode,
        threshold_mb=0.0008,  # several buckets
        donate=False,
        **kw,
    )


def test_fsdp_regathers_in_backward(mesh, problem):
    """Emitted (StableHLO) program: 'dear' gathers each bucket once; 'fsdp'
    re-gathers in backward every bucket whose weights the backward consumes
    (all but the input layer's, whose dL/dx is never needed), same number of
    reduce-scatters (the AD transpose of the gather), plus the remat CSE
    barrier that keeps XLA from folding the re-gathers away. (CPU XLA
    expands the barrier early and CSEs anyway; TPU expands it after
    scheduling, so the memory benefit is a device-side property.)"""
    params, batch = problem
    ts_dear = _build(params, mesh, "dear")
    ts_fsdp = _build(params, mesh, "fsdp")
    assert ts_fsdp.plan.num_buckets == ts_dear.plan.num_buckets >= 2
    nb = ts_fsdp.plan.num_buckets

    hlo_dear = ts_dear.lower(ts_dear.init(params), batch).as_text()
    hlo_fsdp = ts_fsdp.lower(ts_fsdp.init(params), batch).as_text()
    assert _count(hlo_dear, "stablehlo.all_gather") == nb
    assert _count(hlo_dear, "stablehlo.reduce_scatter") == nb
    assert _count(hlo_fsdp, "stablehlo.reduce_scatter") == nb
    assert _count(hlo_fsdp, "stablehlo.all_gather") == 2 * nb - 1
    assert _count(hlo_fsdp, "stablehlo.optimization_barrier") >= 1


def test_fsdp_state_sharded_and_steps(mesh, world, problem):
    params, batch = problem
    ts = _build(params, mesh, "fsdp")
    state = ts.init(params)
    buf = state.buffers[0]
    assert buf.addressable_shards[0].data.size == buf.size // world
    state, m = ts.step(state, batch)
    assert np.isfinite(float(m["loss"]))


def test_fsdp_gather_dtype_bf16(mesh, problem):
    """gather_dtype=bf16: the gather AND its transposed reduce-scatter move
    bf16; masters stay f32 and training still converges on the quadratic."""
    params, batch = problem
    ts = _build(params, mesh, "fsdp", gather_dtype=jnp.bfloat16)
    hlo = ts.lower(ts.init(params), batch).as_text()
    assert "bf16" in hlo
    state = ts.init(params)
    losses = []
    for _ in range(5):
        state, m = ts.step(state, batch)
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0]
    assert state.buffers[0].dtype == jnp.float32


def test_dear_gather_dtype_bf16(mesh, problem):
    params, batch = problem
    ts = _build(params, mesh, "dear", gather_dtype=jnp.bfloat16)
    state = ts.init(params)
    state, m = ts.step(state, batch)
    assert np.isfinite(float(m["loss"]))


def test_fsdp_with_accumulation(mesh, problem):
    """fsdp x accum_steps: every microbatch re-gathers; grads accumulate in
    f32 SHARDS (cheaper than full trees); parity with accum=1."""
    params, batch = problem
    ts1 = _build(params, mesh, "fsdp")
    ts4 = _build(params, mesh, "fsdp", accum_steps=4)
    s1, s4 = ts1.init(params), ts4.init(params)
    for _ in range(3):
        s1, m1 = ts1.step(s1, batch)
        s4, m4 = ts4.step(s4, batch)
    assert float(m1["loss"]) == pytest.approx(float(m4["loss"]), rel=1e-5)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6
        ),
        s1.buffers, s4.buffers,
    )


def test_fsdp_matches_dear_on_two_axis_mesh(problem):
    """fsdp over a 2-D ('dp','sp')-style mesh: the gather/RS-transpose span
    BOTH axes (ZeRO degree = product) and match the dear schedule
    step-for-step."""
    devices = jax.devices()
    mesh2 = jax.sharding.Mesh(
        np.asarray(devices[:8]).reshape(2, 4), ("dp", "sp")
    )
    params, batch = problem
    common = dict(
        optimizer=fused_sgd(lr=0.1, momentum=0.9), mesh=mesh2,
        axis_name=("dp", "sp"), threshold_mb=0.0008, donate=False,
    )
    ts_d = build_train_step(_loss_fn, params, mode="dear", **common)
    ts_f = build_train_step(_loss_fn, params, mode="fsdp", **common)
    sd, sf = ts_d.init(params), ts_f.init(params)
    for _ in range(3):
        sd, md = ts_d.step(sd, batch)
        sf, mf = ts_f.step(sf, batch)
    assert float(md["loss"]) == pytest.approx(float(mf["loss"]), rel=1e-6)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-7
        ),
        sd.buffers, sf.buffers,
    )


def test_fsdp_option_validation(mesh, problem):
    params, _ = problem
    with pytest.raises(ValueError, match="comm_dtype"):
        _build(params, mesh, "fsdp", comm_dtype=jnp.bfloat16)
    with pytest.raises(ValueError, match="gather_dtype"):
        _build(params, mesh, "allreduce", gather_dtype=jnp.bfloat16)
    with pytest.raises(ValueError, match="dear"):
        _build(params, mesh, "fsdp", exclude_parts=("allgather",))
