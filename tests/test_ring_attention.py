"""Sequence-parallel attention tests: ring attention and Ulysses must equal
full single-device attention exactly (same math, different schedule), and be
differentiable end to end."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

# Quarantine (tracking: ISSUE 7 satellite; see test_overlap.py for the
# full note): load-flaky region — reruns-on-failure via the root
# conftest's `flaky` marker so tier-1 dot counts stop wobbling under load.
pytestmark = pytest.mark.flaky(reason="load-flaky: XLA CPU scheduling "
                               "under oversubscription", reruns=2)

from dear_pytorch_tpu.comm.backend import DP_AXIS
from dear_pytorch_tpu.parallel.ring_attention import (
    full_attention,
    ring_attention,
    ring_flash_attention,
    ulysses_attention,
)

B, S, H, D = 2, 64, 8, 16  # S = global sequence; 8 per device on 8 devices


def _qkv(key):
    ks = jax.random.split(key, 3)
    shape = (B, S, H, D)
    return tuple(jax.random.normal(k, shape, jnp.float32) for k in ks)


def _shard_seq(x, world):
    # [B, S, H, D] -> stacked [world, B, S/world, H, D] for spmd dispatch
    b, s, h, d = x.shape
    return x.reshape(b, world, s // world, h, d).transpose(1, 0, 2, 3, 4)


def _unshard_seq(y):
    world, b, s_loc, h, d = y.shape
    return y.transpose(1, 0, 2, 3, 4).reshape(b, world * s_loc, h, d)


def _run_sharded(fn, q, k, v, mesh):
    world = mesh.shape[DP_AXIS]
    qs, ks, vs = (_shard_seq(x, world) for x in (q, k, v))
    mapped = jax.jit(
        jax.shard_map(
            fn,
            mesh=mesh,
            in_specs=(jax.P(DP_AXIS), jax.P(DP_AXIS), jax.P(DP_AXIS)),
            out_specs=jax.P(DP_AXIS),
            check_vma=False,
        )
    )
    return _unshard_seq(mapped(qs, ks, vs))


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_full(mesh, causal):
    q, k, v = _qkv(jax.random.PRNGKey(0))
    want = full_attention(q, k, v, causal=causal)

    def fn(qb, kb, vb):
        # strip the stacked device dim added by shard_map slicing
        out = ring_attention(qb[0], kb[0], vb[0], DP_AXIS, causal=causal)
        return out[None]

    got = _run_sharded(fn, q, k, v, mesh)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_matches_full(mesh, causal):
    q, k, v = _qkv(jax.random.PRNGKey(1))
    want = full_attention(q, k, v, causal=causal)

    def fn(qb, kb, vb):
        out = ulysses_attention(qb[0], kb[0], vb[0], DP_AXIS, causal=causal)
        return out[None]

    got = _run_sharded(fn, q, k, v, mesh)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_flash_attention_matches_full(mesh, causal):
    """The Pallas-per-block ring (LSE combine across blocks) is exact."""
    q, k, v = _qkv(jax.random.PRNGKey(7))
    want = full_attention(q, k, v, causal=causal)

    def fn(qb, kb, vb):
        out = ring_flash_attention(qb[0], kb[0], vb[0], DP_AXIS,
                                   causal=causal)
        return out[None]

    got = _run_sharded(fn, q, k, v, mesh)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_flash_attention_gradients(mesh, causal):
    """The ring-level custom VJP (second ring of flash backward kernels
    under the global LSE) equals the dense gradients for q, k, AND v."""
    q, k, v = _qkv(jax.random.PRNGKey(8))

    def ref_loss(q, k, v):
        return jnp.sum(full_attention(q, k, v, causal=causal) ** 2)

    want = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)

    world = mesh.shape[DP_AXIS]

    def ring_loss(q, k, v):
        qs, ks, vs = (_shard_seq(x, world) for x in (q, k, v))

        def fn(qb, kb, vb):
            out = ring_flash_attention(qb[0], kb[0], vb[0], DP_AXIS,
                                       causal=causal)
            return jnp.sum(out ** 2)[None]

        mapped = jax.shard_map(
            fn, mesh=mesh,
            in_specs=(jax.P(DP_AXIS),) * 3,
            out_specs=jax.P(DP_AXIS),
            check_vma=False,
        )
        return jnp.sum(mapped(qs, ks, vs))

    got = jax.jit(jax.grad(ring_loss, argnums=(0, 1, 2)))(q, k, v)
    for g, w in zip(got, want):
        np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                   rtol=5e-4, atol=5e-5)


def test_ring_flash_attention_padding_mask(mesh):
    """Key-padding masks rotate with K/V and match the dense twin."""
    q, k, v = _qkv(jax.random.PRNGKey(9))
    kv_mask = jnp.ones((B, S), jnp.bool_).at[:, S - 10:].set(False)
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * (D ** -0.5)
    s = jnp.where(kv_mask[:, None, None, :], s, -jnp.inf)
    want = jnp.einsum("bhqk,bkhd->bqhd", jax.nn.softmax(s, axis=-1), v)

    world = mesh.shape[DP_AXIS]

    def fn(qb, kb, vb, mb):
        out = ring_flash_attention(qb[0], kb[0], vb[0], DP_AXIS,
                                   kv_mask=mb[0])
        return out[None]

    qs, ks, vs = (_shard_seq(x, world) for x in (q, k, v))
    ms = kv_mask.reshape(B, world, S // world).transpose(1, 0, 2)
    mapped = jax.jit(jax.shard_map(
        fn, mesh=mesh,
        in_specs=(jax.P(DP_AXIS),) * 4,
        out_specs=jax.P(DP_AXIS),
        check_vma=False,
    ))
    got = _unshard_seq(mapped(qs, ks, vs, ms))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_ulysses_impl_padding_mask(mesh):
    """The ulysses impl all-gathers the LOCAL key-padding mask to global
    validity; fwd must match dense masked attention."""
    from dear_pytorch_tpu.parallel.ring_attention import (
        make_ulysses_attention_impl,
    )

    q, k, v = _qkv(jax.random.PRNGKey(11))
    kv_mask = jnp.ones((B, S), jnp.bool_).at[:, S - 12:].set(False)
    want = full_attention(q, k, v, kv_mask=kv_mask)

    world = mesh.shape[DP_AXIS]
    impl = make_ulysses_attention_impl(DP_AXIS)
    # additive model-mask shard [B, 1, 1, S_loc] (0 = attend, -1e9 = masked)
    add = jnp.where(kv_mask, 0.0, -1e9)[:, None, None, :]
    adds = add.reshape(B, 1, 1, world, S // world).transpose(3, 0, 1, 2, 4)

    def fn(qb, kb, vb, mb):
        return impl(qb[0], kb[0], vb[0], mb[0])[None]

    qs, ks, vs = (_shard_seq(x, world) for x in (q, k, v))
    mapped = jax.jit(jax.shard_map(
        fn, mesh=mesh,
        in_specs=(jax.P(DP_AXIS),) * 4,
        out_specs=jax.P(DP_AXIS),
        check_vma=False,
    ))
    got = _unshard_seq(mapped(qs, ks, vs, adds))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_ring_attention_gradients(mesh):
    """d(loss)/dq through the ring (ppermute/fori_loop transpose) equals the
    full-attention gradient."""
    q, k, v = _qkv(jax.random.PRNGKey(2))

    def ref_loss(q, k, v):
        return jnp.sum(full_attention(q, k, v, causal=True) ** 2)

    want = jax.grad(ref_loss)(q, k, v)

    world = mesh.shape[DP_AXIS]

    def ring_loss(q, k, v):
        qs, ks, vs = (_shard_seq(x, world) for x in (q, k, v))

        def fn(qb, kb, vb):
            out = ring_attention(qb[0], kb[0], vb[0], DP_AXIS, causal=True)
            return jnp.sum(out ** 2)[None]

        mapped = jax.shard_map(
            fn, mesh=mesh,
            in_specs=(jax.P(DP_AXIS),) * 3,
            out_specs=jax.P(DP_AXIS),
            check_vma=False,
        )
        return jnp.sum(mapped(qs, ks, vs))

    got = jax.jit(jax.grad(ring_loss))(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=5e-4, atol=5e-5)


def test_ring_attention_bf16_inputs(mesh):
    q, k, v = _qkv(jax.random.PRNGKey(3))
    qb, kb, vb = (x.astype(jnp.bfloat16) for x in (q, k, v))
    want = full_attention(qb, kb, vb, causal=False)

    def fn(qs, ks, vs):
        out = ring_attention(qs[0], ks[0], vs[0], DP_AXIS)
        return out[None]

    got = _run_sharded(fn, qb, kb, vb, mesh)
    assert got.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want, np.float32),
        rtol=5e-2, atol=5e-2,
    )


def test_ulysses_rejects_indivisible_heads(mesh):
    q = jnp.zeros((1, 8, 4, 8))  # 4 heads on 8 devices

    def fn(qb, kb, vb):
        return ulysses_attention(qb[0], kb[0], vb[0], DP_AXIS)[None]

    with pytest.raises(ValueError, match="heads"):
        _run_sharded(fn, *(jnp.zeros((B, S, 4, D)),) * 3, mesh=mesh)


def test_ring_attention_dropout_matches_blockwise_reference(mesh):
    """Attention-prob dropout in the ring == inverted dropout on the dense
    softmax probs with the ring's per-(q-block, k-block) masks. Regression
    for the silently-ignored dropout_rate (the dense model's
    attention_probs_dropout_prob must be active under sp too)."""
    q, k, v = _qkv(jax.random.PRNGKey(4))
    world = mesh.shape[DP_AXIS]
    rate = 0.3
    drng = jax.random.PRNGKey(42)

    def fn(qb, kb, vb):
        out = ring_attention(qb[0], kb[0], vb[0], DP_AXIS,
                             dropout_rng=drng, dropout_rate=rate)
        return out[None]

    got = _run_sharded(fn, q, k, v, mesh)

    # dense reconstruction with the identical blockwise keep masks
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * (D ** -0.5)
    probs = np.asarray(jax.nn.softmax(s, axis=-1))
    s_loc = S // world
    keep = np.zeros((B, H, S, S), np.float32)
    for i in range(world):          # q-block (device) index
        for j in range(world):      # k-block (owner) index
            blk = jax.random.bernoulli(
                jax.random.fold_in(jax.random.fold_in(drng, i), j),
                1.0 - rate, (B, H, s_loc, s_loc),
            )
            keep[:, :, i * s_loc:(i + 1) * s_loc,
                 j * s_loc:(j + 1) * s_loc] = np.asarray(blk)
    want = np.einsum("bhqk,bkhd->bqhd", probs * keep / (1.0 - rate),
                     np.asarray(v))
    assert keep.mean() < 0.95  # dropout actually dropped something
    np.testing.assert_allclose(np.asarray(got), want, rtol=2e-4, atol=2e-4)


# ---------------------------------------------------------------------------
# Zigzag (striped) causal ring flash
# ---------------------------------------------------------------------------


def _zigzag_perm(world):
    from dear_pytorch_tpu.parallel.ring_attention import zigzag_permutation

    return zigzag_permutation(S, world)


def test_zigzag_matches_full_causal(mesh):
    """Zigzag-layout causal ring flash == full causal attention, after
    undoing the layout permutation."""
    from dear_pytorch_tpu.parallel.ring_attention import (
        zigzag_ring_flash_attention,
    )

    world = mesh.shape[DP_AXIS]
    perm = _zigzag_perm(world)
    q, k, v = _qkv(jax.random.PRNGKey(11))
    want = full_attention(q, k, v, causal=True)

    def fn(qb, kb, vb):
        out = zigzag_ring_flash_attention(qb[0], kb[0], vb[0], DP_AXIS)
        return out[None]

    got_z = _run_sharded(
        fn, q[:, perm], k[:, perm], v[:, perm], mesh
    )
    inv = np.argsort(perm)
    np.testing.assert_allclose(
        np.asarray(got_z)[:, inv], np.asarray(want), rtol=2e-5, atol=2e-5
    )


def test_zigzag_gradients_match_full_causal(mesh):
    """The zigzag ring-level VJP must reproduce full causal attention's
    gradients (after the layout permutation)."""
    from dear_pytorch_tpu.parallel.ring_attention import (
        zigzag_ring_flash_attention,
    )

    world = mesh.shape[DP_AXIS]
    perm = _zigzag_perm(world)
    inv = np.argsort(perm)
    q, k, v = _qkv(jax.random.PRNGKey(12))
    w = jax.random.normal(jax.random.PRNGKey(13), (B, S, H, D))

    def ref_loss(q_, k_, v_):
        return jnp.sum(full_attention(q_, k_, v_, causal=True) * w)

    want = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)

    wz = w[:, perm]

    def fn(qb, kb, vb, wb):
        out = zigzag_ring_flash_attention(qb[0], kb[0], vb[0], DP_AXIS)
        return jnp.sum(out * wb[0])[None]

    def zz_loss(qz, kz, vz):
        mapped = jax.shard_map(
            fn, mesh=mesh,
            in_specs=(jax.P(DP_AXIS),) * 4,
            out_specs=jax.P(DP_AXIS),
            check_vma=False,
        )
        parts = mapped(
            _shard_seq(qz, world), _shard_seq(kz, world),
            _shard_seq(vz, world), _shard_seq(wz, world),
        )
        return jnp.sum(parts)

    got = jax.grad(zz_loss, argnums=(0, 1, 2))(
        q[:, perm], k[:, perm], v[:, perm]
    )
    for g, ref in zip(got, want):
        np.testing.assert_allclose(
            np.asarray(g)[:, inv], np.asarray(ref), rtol=5e-5, atol=5e-5
        )
