"""Config system + batch driver tests."""

import json
import os

import jax.numpy as jnp
import pytest

from dear_pytorch_tpu.benchmarks import driver
from dear_pytorch_tpu.config import DearConfig


def test_config_defaults_mirror_reference():
    cfg = DearConfig()
    assert cfg.threshold_mb == 25.0       # dear/dopt_rsag.py THRESHOLD
    assert cfg.bo_bound == (1.0, 256.0)   # dopt_rsag_bo.py bound
    assert cfg.bo_trials == 10            # tuner.py num_trials
    assert cfg.cycle_time_s == 5e-3       # dopt_rsag_wt.py CYCLE_TIME
    kw = cfg.build_kwargs()
    assert kw["mode"] == "dear" and kw["compressor"] is None


def test_config_validation():
    with pytest.raises(ValueError):
        DearConfig(mode="bogus")
    with pytest.raises(ValueError):
        DearConfig(density=0.0)
    with pytest.raises(ValueError):
        DearConfig(autotune="magic")


def test_config_from_env(monkeypatch):
    monkeypatch.setenv("DEAR_MODE", "allreduce")
    monkeypatch.setenv("DEAR_THRESHOLD_MB", "none")
    monkeypatch.setenv("DEAR_COMPRESSOR", "eftopk")
    monkeypatch.setenv("DEAR_DENSITY", "0.05")
    monkeypatch.setenv("DEAR_GTOPK", "true")
    monkeypatch.setenv("DEAR_COMM_DTYPE", "bf16")
    monkeypatch.setenv("DEAR_EXCLUDE_PARTS", "")
    monkeypatch.setenv("DEAR_CLIP_NORM", "1.5")
    monkeypatch.setenv("DEAR_GATHER_DTYPE", "bf16")
    cfg = DearConfig.from_env()
    assert cfg.mode == "allreduce"
    assert cfg.threshold_mb is None
    assert cfg.compressor == "eftopk" and cfg.density == 0.05 and cfg.gtopk
    assert cfg.comm_dtype is jnp.bfloat16
    assert cfg.clip_norm == 1.5
    assert cfg.gather_dtype is jnp.bfloat16
    # overrides beat env
    cfg2 = DearConfig.from_env(mode="dear", compressor=None, gtopk=False)
    assert cfg2.mode == "dear"


def test_config_usable_by_train_step(mesh):
    from dear_pytorch_tpu.parallel import build_train_step
    from tests.test_dear_numerics import _data, _loss_fn, _mlp_params
    import jax

    cfg = DearConfig(lr=0.1, momentum=0.9, threshold_mb=None, rng_seed=None)
    params = _mlp_params(jax.random.PRNGKey(0))
    ts = build_train_step(_loss_fn, params, mesh=mesh,
                          threshold_mb=cfg.threshold_mb, donate=False,
                          **{k: v for k, v in cfg.build_kwargs().items()
                             if k != "donate"})
    state = ts.init(params)
    state, m = ts.step(state, _data(jax.random.PRNGKey(1)))
    assert float(m["loss"]) > 0


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------


def test_extract_log(tmp_path):
    log = tmp_path / "x.log"
    log.write_text(
        "Running benchmark...\n"
        "Total img/sec on 8 CPU(s): 123.4 +-5.6\n"
        "Total img/sec on 8 CPU(s): 150.0 +-2.0\n"
    )
    assert driver.extract_log(str(log)) == (150.0, 2.0)
    assert driver.extract_log(str(tmp_path / "missing.log")) is None


def test_cell_cmd_routing():
    cmd = driver.cell_cmd("bert_base", 8, "dear", [])
    assert "dear_pytorch_tpu.benchmarks.bert" in cmd
    cmd = driver.cell_cmd("resnet50", 64, "mgwfbp", [])
    assert "dear_pytorch_tpu.benchmarks.imagenet" in cmd
    assert "--mgwfbp" in cmd


def test_driver_sweep_resume_and_report(tmp_path):
    """Full driver pass with pre-seeded logs: every cell resume-skips, so the
    sweep exercises scrape + aggregation without subprocesses."""
    logdir = tmp_path / "logs"
    logdir.mkdir()
    (logdir / "mnistnet-bs4-dear.log").write_text(
        "Total img/sec on 8 CPU(s): 111.0 +-1.0\n")
    (logdir / "mnistnet-bs4-allreduce.log").write_text(
        "Total img/sec on 8 CPU(s): 99.0 +-1.0\n")
    report = driver.main([
        "--logdir", str(logdir), "--tasks", "mnistnet:4",
        "--methods", "dear,allreduce",
    ])
    assert report["mnistnet"]["dear"]["all"] == [111.0, 1.0]
    data = json.load(open(logdir / "reports.json"))
    assert data["mnistnet"]["allreduce"]["all"] == [99.0, 1.0]


@pytest.mark.slow
def test_driver_runs_real_subprocess(tmp_path):
    """One real emulated cell end-to-end (subprocess + scrape)."""
    report = driver.main([
        "--logdir", str(tmp_path), "--tasks", "mnistnet:4",
        "--methods", "dear", "--emulate", "--nworkers", "4",
        "--warmup", "1", "--batches", "2", "--iters", "2",
        "--timeout", "420",
    ])
    cell = report["mnistnet"]["dear"]["4"]
    assert cell is not None and cell[0] > 0


def test_optimizer_env_parsing(monkeypatch):
    """DEAR_OPTIMIZER_NAME / DEAR_ADAM_BETAS / DEAR_ADAM_EPS reach the
    fused optimizers through the env layer."""
    from dear_pytorch_tpu.config import DearConfig
    from dear_pytorch_tpu.ops.fused_sgd import (
        LayerwiseShardOptimizer,
        ShardOptimizer,
    )

    monkeypatch.setenv("DEAR_OPTIMIZER_NAME", "adamw")
    monkeypatch.setenv("DEAR_ADAM_BETAS", "0.8,0.95")
    monkeypatch.setenv("DEAR_ADAM_EPS", "1e-6")
    cfg = DearConfig.from_env()
    assert cfg.optimizer_name == "adamw"
    assert cfg.adam_betas == (0.8, 0.95)
    assert cfg.adam_eps == 1e-6
    assert isinstance(cfg.optimizer(), ShardOptimizer)

    monkeypatch.setenv("DEAR_OPTIMIZER_NAME", "lamb")
    assert isinstance(DearConfig.from_env().optimizer(),
                      LayerwiseShardOptimizer)

    monkeypatch.setenv("DEAR_OPTIMIZER_NAME", "bogus")
    with pytest.raises(ValueError, match="optimizer_name"):
        DearConfig.from_env().optimizer()
