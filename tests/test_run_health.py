"""Run-health layer tests: flight-recorder ring semantics, cluster digest
aggregation + straggler detection, streaming exporters (prom/stream) and
the shared JSONL writer, online anomaly detectors, redaction, the guard's
wiring of all four, and the bench-regression gate fixtures."""

import importlib.util
import json
import os
import threading

import jax
import jax.numpy as jnp
import pytest

from dear_pytorch_tpu.observability import aggregate as AG
from dear_pytorch_tpu.observability import anomaly as AN
from dear_pytorch_tpu.observability import export as EX
from dear_pytorch_tpu.observability import flight as FL
from dear_pytorch_tpu.observability import redaction as RD
from dear_pytorch_tpu.observability import tracer as T

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _isolate_globals():
    """Tests leave the process-global tracer/recorder as they found
    them."""
    old_tr, old_fl, old_auto = T._tracer, FL._recorder, FL._auto_follow
    yield
    T.set_tracer(old_tr)
    FL.set_recorder(old_fl)
    FL._auto_follow = old_auto


def _live_tracer():
    tr = T.Tracer([T.MemoryExporter()])
    T.set_tracer(tr)
    return tr


# ---------------------------------------------------------------------------
# redaction
# ---------------------------------------------------------------------------


def test_redact_env_masks_secret_keys(monkeypatch):
    monkeypatch.setenv("DEAR_FAULTS", "nan@6:r1")
    monkeypatch.setenv("DEAR_API_TOKEN", "hunter2")
    monkeypatch.setenv("DEAR_GCS_SECRET_KEY", "sssh")
    monkeypatch.setenv("NOT_DEAR", "invisible")
    env = RD.redact_env()
    assert env["DEAR_FAULTS"] == "nan@6:r1"       # replay context survives
    assert env["DEAR_API_TOKEN"] == RD.REDACTED
    assert env["DEAR_GCS_SECRET_KEY"] == RD.REDACTED
    assert "NOT_DEAR" not in env
    # arbitrary mappings via prefix=""
    got = RD.redact_env({"password": "x", "plain": "y"}, prefix="")
    assert got == {"password": RD.REDACTED, "plain": "y"}


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------


def test_flight_ring_wraps_and_keeps_newest():
    tr = _live_tracer()
    fl = FL.FlightRecorder(capacity=4, tracer=tr)
    for i in range(7):
        tr.count("dear.steps")
        fl.record(i, step_time_s=0.01 * (i + 1), loss=float(i))
    recs = fl.records()
    assert [r["step"] for r in recs] == [3, 4, 5, 6]
    assert fl.recorded == 7 and fl.head()["step"] == 6
    # counter DELTAS, not totals: exactly one step between records
    assert recs[-1]["counters_delta"] == {"dear.steps": 1}
    stats = fl.step_time_stats()
    assert stats["n"] == 4 and stats["max_s"] == pytest.approx(0.07)
    assert stats["p50_s"] <= stats["p90_s"] <= stats["max_s"]


def test_flight_records_live_spans_and_plan_epoch():
    tr = _live_tracer()
    tr.count("dear.plan_builds")
    fl = FL.FlightRecorder(capacity=4, tracer=tr)
    with tr.span("dear.step"):
        fl.record(1)
    rec = fl.head()
    assert rec["live_spans"] == "dear.step"
    assert rec["plan_epoch"] == 1


def test_flight_nonfinite_loss_stays_strict_json():
    fl = FL.FlightRecorder(capacity=4, tracer=T.NullTracer())
    fl.record(1, loss=float("nan"))
    dumped = json.dumps(fl.dump(env=False))
    json.loads(dumped)  # no bare NaN tokens
    assert '"nan"' in dumped


def test_flight_dump_redacts_env(monkeypatch):
    monkeypatch.setenv("DEAR_FAKE_TOKEN", "leakme")
    fl = FL.FlightRecorder(capacity=4, tracer=T.NullTracer())
    fl.record(1)
    dump = fl.dump()
    assert dump["env"]["DEAR_FAKE_TOKEN"] == RD.REDACTED
    assert dump["records"][0]["step"] == 1


def test_flight_env_resolution(monkeypatch):
    monkeypatch.setattr(FL, "_recorder", None)
    monkeypatch.setenv(FL.FLIGHT_ENV, "0")
    assert not FL.get_recorder().enabled          # forced off
    monkeypatch.setattr(FL, "_recorder", None)
    monkeypatch.setenv(FL.FLIGHT_ENV, "128")
    fl = FL.get_recorder()                        # forced on, sized
    assert fl.enabled and fl.capacity == 128
    # unset: follows the tracer
    monkeypatch.setattr(FL, "_recorder", None)
    monkeypatch.delenv(FL.FLIGHT_ENV, raising=False)
    T.set_tracer(T.NullTracer())
    assert not FL.get_recorder().enabled
    monkeypatch.setattr(FL, "_recorder", None)
    _live_tracer()
    assert FL.get_recorder().enabled


def test_flight_follows_programmatic_tracer_reconfig(monkeypatch):
    # DEAR_FLIGHT unset: the first resolution follows the tracer — and
    # KEEPS following it, so enabling telemetry in code after some
    # instrumented path already touched the ring still brings it up
    monkeypatch.delenv(FL.FLIGHT_ENV, raising=False)
    monkeypatch.setattr(FL, "_recorder", None)
    T.set_tracer(T.NullTracer())
    assert not FL.get_recorder().enabled      # cached as disabled
    _live_tracer()
    assert FL.get_recorder().enabled          # ring came up with telemetry
    T.set_tracer(T.NullTracer())
    assert not FL.get_recorder().enabled      # and down again
    # an explicit DEAR_FLIGHT pins the ring regardless of the tracer
    monkeypatch.setenv(FL.FLIGHT_ENV, "8")
    monkeypatch.setattr(FL, "_recorder", None)
    assert FL.get_recorder().enabled
    _live_tracer()
    T.set_tracer(T.NullTracer())
    assert FL.get_recorder().enabled


def test_watchdog_report_tolerates_malformed_flight_env(monkeypatch):
    # the watchdog must never crash while reporting a crash: a typo'd
    # DEAR_FLIGHT raises on FIRST recorder resolution, which can happen
    # inside the daemon's _make_report (e.g. bench.py arms the watchdog
    # before anything else touches the ring)
    from dear_pytorch_tpu.resilience import StepWatchdog

    monkeypatch.setattr(FL, "_recorder", None)
    monkeypatch.setenv(FL.FLIGHT_ENV, "16k")
    dog = StepWatchdog(deadline_s=60, name="t-dog", dump_stacks=False)
    report = dog._make_report(1.0, {"step": 3})
    assert report.flight == [] and report.name == "t-dog"


def test_watchdog_report_defaults_are_immutable():
    from dear_pytorch_tpu.resilience.watchdog import WatchdogReport

    r = WatchdogReport(name="a", waited_s=1.0, deadline_s=2.0,
                       beat_info={}, live_spans=[])
    assert r.flight == () and dict(r.env) == {}
    # NamedTuple defaults are class-level shared instances: they must not
    # be mutable, or one report's edits would leak into every later one
    with pytest.raises(TypeError):
        r.env["x"] = "y"


def test_flight_env_rejects_malformed_values(monkeypatch):
    monkeypatch.setattr(FL, "_recorder", None)
    monkeypatch.setenv(FL.FLIGHT_ENV, "16k")
    with pytest.raises(ValueError, match="DEAR_FLIGHT"):
        FL.get_recorder()
    monkeypatch.setattr(FL, "_recorder", None)
    monkeypatch.setenv(FL.FLIGHT_ENV, "-5")
    with pytest.raises(ValueError):
        FL.get_recorder()
    monkeypatch.setattr(FL, "_recorder", None)
    monkeypatch.setenv(FL.FLIGHT_ENV, "true")
    assert FL.get_recorder().enabled  # keyword truthies still fine


def test_rank_placeholder_paths(tmp_path):
    prom = EX.PromFileExporter(str(tmp_path / "d.{rank}.prom"))
    prom.export({"counters": {"x.y": 1}})
    assert os.path.exists(tmp_path / "d.0.prom")  # single process: rank 0
    stream = EX.HealthStreamExporter(str(tmp_path / "h.{rank}.jsonl"))
    stream.export({"counters": {}})
    stream.close()
    assert os.path.exists(tmp_path / "h.0.jsonl")
    stream.export({"counters": {}})  # post-close export is a no-op


class _ExplodingSink:
    def span(self, rec):
        pass

    def event(self, rec):
        pass

    def export(self, snapshot, gauges=None):
        raise OSError("disk full")

    def close(self):
        pass


def test_guard_survives_sink_failures(tmp_path, mesh, caplog):
    import logging

    tr = T.Tracer([T.MemoryExporter(), _ExplodingSink()])
    T.set_tracer(tr)
    FL.set_recorder(FL.NullFlightRecorder())
    ts, guard, params = _tiny_trainer(tmp_path, mesh)
    state = ts.init(params)
    with caplog.at_level(logging.WARNING, logger="dear_pytorch_tpu"):
        for _ in range(6):  # 3 check intervals, all with a raising sink
            state, m = guard.step(state, jnp.ones((8, 8)))
    assert "loss" in m  # training survived every failed export
    assert tr.counters()["health.export_errors"] == 3
    warned = [r for r in caplog.records
              if "telemetry export via" in r.getMessage()]
    assert len(warned) == 1  # logged once per sink, not per interval


def test_write_streams_isolates_failing_sink(tmp_path):
    """One dead sink must not starve the healthy ones."""
    stream = str(tmp_path / "h.jsonl")
    tr = T.Tracer([_ExplodingSink(), EX.HealthStreamExporter(stream)])
    T.set_tracer(tr)
    tr.count("dear.steps", 3)
    assert EX.write_streams(tracer=tr) == 1   # the healthy sink wrote
    assert EX.write_streams(tracer=tr) == 1
    recs = [json.loads(ln) for ln in open(stream)]
    assert len(recs) == 2
    assert tr.counters()["health.export_errors"] == 2


def test_jsonl_writer_coerces_numpy_and_jax_scalars(tmp_path):
    """Span/event attrs are routinely numpy/jax scalars; the shared
    writer must coerce them (the old MetricsLogger path did)."""
    import numpy as np

    from dear_pytorch_tpu.utils import read_metrics

    path = str(tmp_path / "t.jsonl")
    tr = T.Tracer([T.JsonlExporter(path)])
    tr.event("x", val=np.float32(1.5), n=np.int64(7),
             arr=np.arange(2.0), dev=jnp.float32(2.5))
    with tr.span("s", b=np.bool_(True)):
        pass
    tr.close()
    recs = read_metrics(path)
    assert recs[0]["val"] == 1.5 and recs[0]["n"] == 7
    assert recs[0]["arr"] == [0.0, 1.0] and recs[0]["dev"] == 2.5
    assert recs[1]["b"] is True


def test_null_recorder_is_free():
    fl = FL.NullFlightRecorder()
    fl.record(1, step_time_s=0.1)
    assert fl.records() == [] and fl.head() is None
    assert fl.step_time_stats() == {} and fl.dump()["records"] == []


def test_flight_thread_safety():
    fl = FL.FlightRecorder(capacity=8, tracer=T.NullTracer())
    stop = threading.Event()
    seen = []

    def reader():
        while not stop.is_set():
            seen.append(len(fl.records()))

    t = threading.Thread(target=reader)
    t.start()
    for i in range(500):
        fl.record(i)
    stop.set()
    t.join()
    assert fl.recorded == 500 and len(fl.records()) == 8


# ---------------------------------------------------------------------------
# aggregation
# ---------------------------------------------------------------------------


def test_local_digest_compact_and_prefix_filtered():
    tr = _live_tracer()
    tr.count("guard.rollbacks", 2)
    tr.count("dear.steps", 10)
    tr.count("dear.reduce_scatter_bytes", 1e9)  # not a digest prefix
    fl = FL.FlightRecorder(capacity=8, tracer=tr)
    fl.record(5, step_time_s=0.02, loss=0.5)
    d = AG.local_digest(rank=3, recorder=fl, tracer=tr)
    assert d["rank"] == 3
    assert d["ctr"]["guard.rollbacks"] == 2
    assert d["ctr"]["dear.steps"] == 10
    assert "dear.reduce_scatter_bytes" not in d["ctr"]
    assert d["head"]["step"] == 5 and d["st"]["p50_s"] == 0.02
    # the allgather transport gives each rank a fixed 2 KB slot
    assert len(json.dumps(d, separators=(",", ":"))) < 1900


def test_oversize_digest_trims_under_slot_budget():
    # a pathological counter explosion must trim, not strand the exchange
    digest = {
        "rank": 0,
        "ctr": {f"health.counter_with_a_long_name_{i:03d}": 123456.789
                for i in range(200)},
        "st": {"p50_s": 0.1, "p90_s": 0.2, "n": 100},
        "head": {"step": 5, "step_time_s": 0.1, "loss": 1.0, "t_s": 12.0},
    }
    fitted = AG._fit_digest(digest)
    assert AG._size(fitted) <= AG.MAX_DIGEST_BYTES
    assert fitted["rank"] == 0
    assert fitted["ctr"]  # trimmed, not emptied


def test_merge_digests_straggler_and_counters():
    fast = {"rank": 0, "ctr": {"dear.steps": 10}, "st": {"p50_s": 0.01}}
    slow = {"rank": 1, "ctr": {"dear.steps": 10, "guard.rollbacks": 1},
            "st": {"p50_s": 0.05}}
    m = AG.merge_digests([fast, slow], skew_threshold=1.5)
    assert m["world"] == 2
    assert m["counters"] == {"dear.steps": 20, "guard.rollbacks": 1}
    assert m["straggler_rank"] == 1
    assert m["straggler_skew"] == pytest.approx(0.05 / 0.03, rel=1e-3)
    assert m["step_time"]["slowest_rank"] == 1
    # balanced fleet: no straggler named
    m2 = AG.merge_digests(
        [fast, {"rank": 1, "ctr": {}, "st": {"p50_s": 0.011}}],
        skew_threshold=1.5)
    assert m2["straggler_rank"] is None
    assert json.loads(json.dumps(m)) is not None  # JSON-safe


def test_metric_aggregator_over_local_transport():
    """N thread-ranks over one LocalTransport behave like N processes —
    the same harness the cluster consensus tests use."""
    from dear_pytorch_tpu.resilience import cluster as CL

    tr = _live_tracer()
    transport = CL.LocalTransport(num_processes=2)
    merged: dict = {}

    def rank(i):
        co = CL.ClusterCoordinator(
            namespace="agg", process_index=i, process_count=2,
            timeout_s=10, transport=transport, instance=0)
        agg = AG.MetricAggregator(co, skew_threshold=1.5)
        digest = {"rank": i, "ctr": {"dear.steps": 5},
                  "st": {"p50_s": 0.01 if i == 0 else 0.04}}
        merged[i] = agg.exchange(digest)

    threads = [threading.Thread(target=rank, args=(i,)) for i in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert merged[0] == merged[1]             # identical on every rank
    assert merged[0]["straggler_rank"] == 1
    assert merged[0]["counters"]["dear.steps"] == 10
    counters = tr.counters()
    assert counters["cluster.metric_exchanges"] == 2
    assert counters["cluster.straggler_detected"] == 2


# ---------------------------------------------------------------------------
# exporters
# ---------------------------------------------------------------------------


def test_jsonl_writer_rotation(tmp_path):
    path = str(tmp_path / "s.jsonl")
    w = EX.JsonlWriter(path, max_bytes=200, backups=2)
    for i in range(50):
        w.write({"i": i, "pad": "x" * 40})
    w.close()
    assert os.path.exists(path)
    assert os.path.exists(path + ".1")
    assert os.path.exists(path + ".2")
    assert not os.path.exists(path + ".3")  # bounded
    # every surviving line is intact JSON
    for p in (path, path + ".1", path + ".2"):
        for line in open(p):
            json.loads(line)


def test_prom_exporter_format_and_redaction(tmp_path, monkeypatch):
    monkeypatch.setenv("DEAR_FAKE_TOKEN", "leakme")
    monkeypatch.setenv("DEAR_FAULTS", "nan@6")
    path = str(tmp_path / "dear.prom")
    ex = EX.PromFileExporter(path)
    ex.export({"counters": {"guard.rollbacks": 3, "dear.steps": 10}},
              {"step_time_p50_seconds": 0.012, "skip_me": None})
    text = open(path).read()
    assert "# TYPE dear_guard_rollbacks counter" in text
    assert "dear_guard_rollbacks 3" in text
    assert "dear_dear_steps 10" in text
    assert "# TYPE dear_step_time_p50_seconds gauge" in text
    assert "dear_step_time_p50_seconds 0.012" in text
    assert "# env DEAR_FAULTS=nan@6" in text
    assert "leakme" not in text and "DEAR_FAKE_TOKEN=[redacted]" in text
    # atomic rewrite: a second export fully replaces the file
    ex.export({"counters": {"guard.rollbacks": 4}}, None)
    text = open(path).read()
    assert "dear_guard_rollbacks 4" in text and "dear_dear_steps" not in text


def test_health_stream_roundtrip(tmp_path):
    from dear_pytorch_tpu.utils import read_metrics

    path = str(tmp_path / "h.jsonl")
    ex = EX.HealthStreamExporter(path)
    ex.export({"counters": {"dear.steps": 2}}, {"g": 1.5})
    ex.export({"counters": {"dear.steps": 4}}, None)
    ex.close()
    recs = read_metrics(path)
    assert [r["kind"] for r in recs] == ["health", "health"]
    assert recs[0]["gauges"] == {"g": 1.5}
    assert recs[1]["counters"] == {"dear.steps": 4}


def test_write_streams_feeds_attached_exporters(tmp_path):
    prom = str(tmp_path / "p.prom")
    stream = str(tmp_path / "h.jsonl")
    tr = T.Tracer([T.MemoryExporter(), EX.PromFileExporter(prom),
                   EX.HealthStreamExporter(stream)])
    T.set_tracer(tr)
    tr.count("dear.steps", 7)
    assert EX.write_streams() == 2
    assert "dear_dear_steps 7" in open(prom).read()
    assert json.loads(open(stream).readline())["counters"]["dear.steps"] == 7
    # disabled tracer: zero writes
    assert EX.write_streams(tracer=T.NullTracer()) == 0


def test_telemetry_env_grammar_prom_stream(tmp_path):
    T.set_tracer(None)
    tr = T.configure_from_env(
        f"prom:{tmp_path}/d.prom,stream:{tmp_path}/h.jsonl")
    assert isinstance(tr, T.Tracer)
    tr.count("x.y", 1)
    assert EX.write_streams(tracer=tr) == 2
    tr.close()
    assert os.path.exists(tmp_path / "d.prom")
    assert os.path.exists(tmp_path / "h.jsonl")
    T.set_tracer(None)
    with pytest.raises(ValueError):
        T.configure_from_env("prom:")  # path required


# ---------------------------------------------------------------------------
# anomaly detectors
# ---------------------------------------------------------------------------


def test_step_time_spike_detector():
    tr = _live_tracer()
    hits = []
    am = AN.AnomalyMonitor(warmup=3, z_threshold=4.0, tracer=tr,
                           on_anomaly=lambda k, d: hits.append(k))
    for _ in range(6):
        assert am.observe(step=1, step_time_s=0.010) == []
    found = am.observe(step=7, step_time_s=0.200)
    assert found == ["step_time_spike"]
    assert hits == ["step_time_spike"]
    c = tr.counters()
    assert c["health.step_time_spike"] == 1 and c["health.anomalies"] == 1
    # steady noise below threshold never fires
    assert am.observe(step=8, step_time_s=0.011) == []


def test_loss_spike_and_plateau():
    tr = _live_tracer()
    am = AN.AnomalyMonitor(warmup=3, plateau_window=4, plateau_rel=1e-3,
                           tracer=tr)
    for i in range(5):
        am.observe(step=i, loss=1.0 - 0.1 * i)
    assert am.observe(step=6, loss=50.0) == ["loss_spike"]
    assert am.observe(step=7, loss=float("nan")) == ["loss_spike"]
    # plateau: flat window fires ONCE, re-arms when the loss moves
    am2 = AN.AnomalyMonitor(warmup=100, plateau_window=4, plateau_rel=1e-3)
    fired = []
    for i in range(8):
        fired += am2.observe(step=i, loss=0.5)
    assert fired == ["loss_plateau"]
    am2.observe(step=9, loss=0.4)       # movement re-arms
    fired2 = []
    for i in range(10, 16):
        fired2 += am2.observe(step=i, loss=0.4)
    assert fired2 == ["loss_plateau"]
    assert am2.anomalies[-1]["kind"] == "loss_plateau"


def test_input_stall_and_mfu_drop():
    am = AN.AnomalyMonitor(tracer=T.NullTracer(), mfu_drop_frac=0.25)
    assert am.observe(counters={"pipeline.stall_timeouts": 0}) == []
    assert am.observe(counters={"pipeline.stall_timeouts": 2}) == \
        ["input_stall"]
    assert am.observe(counters={"pipeline.stall_timeouts": 2}) == []
    assert am.observe(mfu=0.40) == []
    assert am.observe(mfu=0.38) == []       # within window
    assert am.observe(mfu=0.20) == ["mfu_drop"]


def test_pipeline_stall_counters():
    """A starved numpy-free pipeline path: drive Pipeline._fetch through
    a stub `_next` that always times out and assert the stall counters
    the anomaly monitor watches."""
    from dear_pytorch_tpu.runtime import pipeline as P

    tr = _live_tracer()

    class Starved(P.NumpyPipeline):
        def _next(self, timeout_ms=0):
            raise TimeoutError("no batch")

        _next_counted = P.Pipeline._next_counted
        _fetch = P.Pipeline._fetch

    pipe = Starved(P.mnist_spec(2))
    with pytest.raises(TimeoutError):
        pipe._fetch(30)
    c = tr.counters()
    assert c["pipeline.stalls"] == 1
    assert c["pipeline.stall_timeouts"] == 3  # every retried attempt


def test_anomaly_env_knobs(monkeypatch):
    monkeypatch.setenv("DEAR_HEALTH_Z", "7")
    monkeypatch.setenv("DEAR_HEALTH_WARMUP", "3")
    am = AN.AnomalyMonitor.from_env()
    assert am.z_threshold == 7.0 and am.warmup == 3
    monkeypatch.setenv("DEAR_HEALTH", "0")
    assert not AN.AnomalyMonitor.enabled_by_env()
    monkeypatch.delenv("DEAR_HEALTH")
    assert AN.AnomalyMonitor.enabled_by_env()


# ---------------------------------------------------------------------------
# guard + watchdog wiring
# ---------------------------------------------------------------------------


def _tiny_trainer(tmp_path, mesh, **kw):
    from dear_pytorch_tpu.ops.fused_sgd import fused_sgd
    from dear_pytorch_tpu.parallel import build_train_step
    from dear_pytorch_tpu.utils.guard import GuardedTrainer

    params = {"w": jnp.ones((8, 4)) * 0.1}

    def loss(p, b):
        return jnp.mean((b @ p["w"]) ** 2)

    ts = build_train_step(
        loss, params, mesh=mesh, mode="dear", nearby_layers=1,
        optimizer=fused_sgd(lr=0.05), donate=False,
    )
    return ts, GuardedTrainer(ts, str(tmp_path / "ckpt"), params,
                              check_every=2, checkpoint_every=4, **kw), \
        params


def test_guard_feeds_flight_and_health(tmp_path, mesh):
    tr = _live_tracer()
    fl = FL.FlightRecorder(capacity=16, tracer=tr)
    FL.set_recorder(fl)
    ts, guard, params = _tiny_trainer(tmp_path, mesh)
    assert guard._anomaly is not None          # telemetry on -> monitor on
    state = ts.init(params)
    batch = jnp.ones((8, 8))
    for _ in range(6):
        state, m = guard.step(state, batch)
    recs = fl.records()
    assert [r["step"] for r in recs] == [1, 2, 3, 4, 5, 6]
    # checked steps carry the fetched loss; unchecked ones don't
    assert "loss" in recs[1] and "loss" not in recs[0]
    assert recs[1]["checked"] == 1
    assert any("step_time_s" in r for r in recs[1:])


def test_guard_rollback_dumps_flight(tmp_path, mesh, caplog):
    import logging

    from dear_pytorch_tpu.resilience import Fault, FaultInjector

    tr = _live_tracer()
    FL.set_recorder(FL.FlightRecorder(capacity=8, tracer=tr))
    ts, guard, params = _tiny_trainer(
        tmp_path, mesh, injector=FaultInjector([Fault(kind="nan", step=6)]))
    state = ts.init(params)
    batch = jnp.ones((8, 8))
    with caplog.at_level(logging.WARNING, logger="dear_pytorch_tpu"):
        for _ in range(7):
            state, m = guard.step(state, batch)
    dumps = [r for r in caplog.records
             if "flight ring at rollback" in r.getMessage()]
    assert len(dumps) == 1
    payload = json.loads(dumps[0].getMessage().split("records): ", 1)[1])
    assert payload["records"] and payload["records"][-1]["step"] == 6
    assert "env" in payload
    c = tr.counters()
    assert c["guard.flight_dumps"] == 1 and c["guard.rollbacks"] == 1


def test_guard_streams_on_check_cadence(tmp_path, mesh):
    prom = str(tmp_path / "d.prom")
    tr = T.Tracer([T.MemoryExporter(), EX.PromFileExporter(prom)])
    T.set_tracer(tr)
    FL.set_recorder(FL.FlightRecorder(capacity=8, tracer=tr))
    ts, guard, params = _tiny_trainer(tmp_path, mesh)
    state = ts.init(params)
    for _ in range(4):
        state, _ = guard.step(state, jnp.ones((8, 8)))
    text = open(prom).read()
    assert "dear_dear_steps" in text
    assert "dear_step_time_p50_seconds" in text


def test_watchdog_kick_ships_flight_ring(monkeypatch, capfd):
    from dear_pytorch_tpu.resilience import StepWatchdog

    monkeypatch.setenv("DEAR_FAKE_TOKEN", "leakme")
    tr = _live_tracer()
    fl = FL.FlightRecorder(capacity=4, tracer=tr)
    FL.set_recorder(fl)
    for i in range(6):
        fl.record(i, step_time_s=0.01)
    dog = StepWatchdog(deadline_s=60, name="t-dog")
    report = dog.kick("unit probe", step=6)
    assert [r["step"] for r in report.flight] == [2, 3, 4, 5]
    assert report.env["DEAR_FAKE_TOKEN"] == RD.REDACTED
    err = capfd.readouterr().err
    assert "flight ring (4 records)" in err and "leakme" not in err


def test_anomaly_kick_escalation(tmp_path, mesh, monkeypatch):
    from dear_pytorch_tpu.resilience import StepWatchdog

    monkeypatch.setenv("DEAR_HEALTH_KICK", "1")
    _live_tracer()
    FL.set_recorder(FL.NullFlightRecorder())
    dog = StepWatchdog(deadline_s=60, name="esc-dog", dump_stacks=False)
    ts, guard, params = _tiny_trainer(tmp_path, mesh, watchdog=dog)
    guard._on_anomaly("step_time_spike", {"step_time_s": 9.0})
    assert dog.kicked == 1
    assert dog.last_report.beat_info["step_time_s"] == 9.0


# ---------------------------------------------------------------------------
# bench gate
# ---------------------------------------------------------------------------


def _gate():
    spec = importlib.util.spec_from_file_location(
        "bench_gate", os.path.join(REPO, "scripts", "bench_gate.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _bench_doc(resnet=2300.0, bert=1200.0, gpt=60000.0):
    return {
        "metric": "resnet50_bs64_train_img_sec_per_chip", "value": resnet,
        "unit": "img/s", "mfu": 0.28,
        "extra_metrics": [
            {"metric": "bert_base_sen_sec_per_chip", "value": bert},
            {"metric": "gpt2_s1024_tok_sec_per_chip", "value": gpt},
        ],
    }


def test_compare_bench_shapes():
    v = AN.compare_bench(_bench_doc(), _bench_doc(resnet=2310.0))
    assert v["ok"] and len(v["parity"]) == 3
    v = AN.compare_bench(_bench_doc(), _bench_doc(bert=1100.0))
    assert not v["ok"]
    assert [r["metric"] for r in v["regressions"]] == [
        "bert_base_sen_sec_per_chip"]
    v = AN.compare_bench(_bench_doc(), _bench_doc(gpt=80000.0))
    assert v["ok"] and len(v["improvements"]) == 1
    # driver-record shape + errored entry on the run side
    run = {"parsed": {"metric": "resnet50_bs64_train_img_sec_per_chip",
                      "value": 2290.0,
                      "extra_metrics": [
                          {"metric": "bert_base_sen_sec_per_chip",
                           "error": "wedged"},
                          {"metric": "gpt2_s1024_tok_sec_per_chip",
                           "value": 60000.0}]}}
    v = AN.compare_bench(_bench_doc(), run)
    assert not v["ok"] and v["missing"] == ["bert_base_sen_sec_per_chip"]


def test_bench_gate_cli_regression_and_parity(tmp_path, capsys):
    gate = _gate()
    base = tmp_path / "baseline.json"
    base.write_text(json.dumps(_bench_doc()))
    # >5% regression on the primary metric -> nonzero
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps(_bench_doc(resnet=2300.0 * 0.93)))
    assert gate.main(["--baseline", str(base), "--run", str(bad)]) == 2
    verdict = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert not verdict["ok"] and verdict["regressions"][0]["ratio"] < 0.95
    # parity -> zero
    ok = tmp_path / "ok.json"
    ok.write_text(json.dumps(_bench_doc(resnet=2295.0)))
    assert gate.main(["--baseline", str(base), "--run", str(ok)]) == 0
    # improvement -> zero
    fast = tmp_path / "fast.json"
    fast.write_text(json.dumps(_bench_doc(resnet=2600.0)))
    assert gate.main(["--baseline", str(base), "--run", str(fast)]) == 0


def test_bench_gate_cli_missing_metrics_and_flags(tmp_path, capsys):
    gate = _gate()
    base = tmp_path / "baseline.json"
    base.write_text(json.dumps(_bench_doc()))
    partial = tmp_path / "partial.json"
    partial.write_text(json.dumps({
        "metric": "resnet50_bs64_train_img_sec_per_chip", "value": 2300.0}))
    assert gate.main(["--baseline", str(base), "--run", str(partial)]) == 2
    capsys.readouterr()
    # --allow-missing downgrades lost metrics (no regression otherwise)
    assert gate.main(["--baseline", str(base), "--run", str(partial),
                     "--allow-missing"]) == 0
    # --metrics restricts the comparison
    assert gate.main(["--baseline", str(base), "--run", str(partial),
                     "--metrics", "resnet50_bs64_train_img_sec_per_chip"]
                     ) == 0
    capsys.readouterr()
    # unusable input -> 3
    empty = tmp_path / "empty.json"
    empty.write_text("no json here\n")
    assert gate.main(["--baseline", str(base), "--run", str(empty)]) == 3
    capsys.readouterr()


def test_bench_gate_slo_floor(tmp_path, capsys):
    """`--slo METRIC=MIN` gates an ABSOLUTE service-contract floor —
    independently of any baseline (which becomes optional): the
    continuous-training service's steps-per-hour promise is a floor, not
    a ratio (scripts/chaos_check.py --autoscale drives this)."""
    gate = _gate()
    run = tmp_path / "run.json"
    run.write_text(json.dumps({"metric": "steps_per_hour", "value": 900.0}))
    # floor held -> 0, no baseline needed
    assert gate.main(["--run", str(run),
                      "--slo", "steps_per_hour=500"]) == 0
    verdict = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert verdict["ok"] and verdict["slo_violations"] == []
    # floor broken -> 2
    assert gate.main(["--run", str(run),
                      "--slo", "steps_per_hour=1000"]) == 2
    verdict = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert verdict["slo_violations"][0]["run"] == 900.0
    # a metric the run stopped reporting is DOWN, not quiet -> 2
    assert gate.main(["--run", str(run),
                      "--slo", "p99_latency_ms=50"]) == 2
    capsys.readouterr()
    # NaN is DOWN too (not-above-floor, never below-floor comparison)
    nan_run = tmp_path / "nan.json"
    nan_run.write_text('{"metric": "steps_per_hour", "value": NaN}')
    assert gate.main(["--run", str(nan_run),
                      "--slo", "steps_per_hour=1"]) == 2
    capsys.readouterr()
    # malformed --slo -> 3; neither baseline nor slo -> argparse error
    assert gate.main(["--run", str(run), "--slo", "nonsense"]) == 3
    capsys.readouterr()
    with pytest.raises(SystemExit):
        gate.main(["--run", str(run)])
    # SLO composes with a baseline comparison: parity but broken floor
    base = tmp_path / "baseline.json"
    base.write_text(json.dumps({"metric": "steps_per_hour",
                                "value": 905.0}))
    assert gate.main(["--baseline", str(base), "--run", str(run),
                      "--slo", "steps_per_hour=1000"]) == 2
    capsys.readouterr()


def test_bench_gate_slo_ceiling(tmp_path, capsys):
    """`--slo METRIC<=MAX` gates an absolute CEILING — the latency
    direction of the serving contract (scripts/chaos_check.py --serve
    gates p99 latency this way), with the same NaN/missing-fails-loudly
    semantics as floors, and `METRIC>=MIN` as the explicit floor
    spelling."""
    gate = _gate()
    run = tmp_path / "run.json"
    run.write_text(json.dumps({
        "metric": "requests_per_s", "value": 12.0,
        "extra_metrics": [{"metric": "p99_latency_ms", "value": 340.0}]}))
    # ceiling held -> 0; floor and ceiling compose in one invocation
    assert gate.main(["--run", str(run),
                      "--slo", "p99_latency_ms<=500"]) == 0
    verdict = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert verdict["ok"] and verdict["slo_violations"] == []
    assert gate.main(["--run", str(run),
                      "--slo", "requests_per_s>=10",
                      "--slo", "p99_latency_ms<=500"]) == 0
    capsys.readouterr()
    # ceiling broken -> 2, and the verdict names the ceiling
    assert gate.main(["--run", str(run),
                      "--slo", "p99_latency_ms<=100"]) == 2
    verdict = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert verdict["slo_violations"][0] == {
        "metric": "p99_latency_ms", "run": 340.0, "ceiling": 100.0}
    # a missing metric fails a ceiling exactly like a floor
    assert gate.main(["--run", str(run),
                      "--slo", "p50_latency_ms<=100"]) == 2
    capsys.readouterr()
    # NaN is never within a bound: not-(value<=max) fails loudly
    nan_run = tmp_path / "nan.json"
    nan_run.write_text('{"metric": "p99_latency_ms", "value": NaN}')
    assert gate.main(["--run", str(nan_run),
                      "--slo", "p99_latency_ms<=1e9"]) == 2
    capsys.readouterr()
    # malformed bound -> 3
    assert gate.main(["--run", str(run),
                      "--slo", "p99_latency_ms<=fast"]) == 3
    capsys.readouterr()
    # a BAND (floor AND ceiling on the SAME metric) enforces BOTH bounds
    # — neither may silently overwrite the other
    assert gate.main(["--run", str(run),
                      "--slo", "p99_latency_ms>=10",
                      "--slo", "p99_latency_ms<=500"]) == 0
    capsys.readouterr()
    assert gate.main(["--run", str(run),
                      "--slo", "p99_latency_ms>=400",   # broken floor...
                      "--slo", "p99_latency_ms<=500"]) == 2  # ...gates
    verdict = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert verdict["slo_violations"] == [
        {"metric": "p99_latency_ms", "run": 340.0, "floor": 400.0}]
    assert gate.main(["--run", str(run),
                      "--slo", "p99_latency_ms>=400",
                      "--slo", "p99_latency_ms<=100"]) == 2  # both broken
    verdict = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert len(verdict["slo_violations"]) == 2


def test_bench_gate_reads_contract_line_amid_output(tmp_path, capsys):
    gate = _gate()
    base = tmp_path / "b.json"
    base.write_text(json.dumps(_bench_doc()))
    # a captured stdout file: warmup logs + the contract line
    run = tmp_path / "run.log"
    run.write_text("Running warmup...\nIter #0: 100 img/s\n"
                   + json.dumps(_bench_doc(resnet=2400.0)) + "\n")
    assert gate.main(["--baseline", str(base), "--run", str(run)]) == 0
    capsys.readouterr()


def _driver_report(fused=0.98, missing_cell=False, failed_cell=False):
    """Synthetic benchmarks/driver.py reports.json: two models swept over
    'dear' and 'dear-fused'. ``fused`` scales the candidate's throughput
    relative to the base."""
    rep = {
        "bert_base": {"dear": {"8": [100.0, 1.0]},
                      "dear-fused": {"8": [100.0 * fused, 1.0]}},
        "gpt2": {"dear": {"8": [500.0, 2.0]},
                 "dear-fused": {"8": [500.0 * fused, 2.0]}},
        "telemetry": {"cells_run": 4},
    }
    if missing_cell:
        del rep["gpt2"]["dear-fused"]
    if failed_cell:
        rep["gpt2"]["dear-fused"]["8"] = None
    return rep


def test_bench_gate_ab_methods(tmp_path, capsys):
    """--ab-methods gates a driver sweep's dear-fused cells against dear
    (the fused-kernel one-command A/B)."""
    gate = _gate()
    run = tmp_path / "reports.json"
    # within tolerance -> green
    run.write_text(json.dumps(_driver_report(fused=0.98)))
    assert gate.main(["--run", str(run),
                      "--ab-methods", "dear-fused:dear"]) == 0
    verdict = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert verdict["ok"] and len(verdict["cells"]) == 2
    # >tolerance regression -> exit 2, the offending cell named
    run.write_text(json.dumps(_driver_report(fused=0.90)))
    assert gate.main(["--run", str(run),
                      "--ab-methods", "dear-fused:dear"]) == 2
    verdict = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert not verdict["ok"]
    assert all(not c["ok"] for c in verdict["cells"])
    # a loose tolerance admits the same run
    assert gate.main(["--run", str(run), "--tolerance", "0.2",
                      "--ab-methods", "dear-fused:dear"]) == 0
    capsys.readouterr()


def test_bench_gate_ab_methods_missing_cells(tmp_path, capsys):
    """A cell the base produced but the candidate lost fails (a method
    that silently stopped reporting is a harness regression), unless
    --allow-missing downgrades it."""
    gate = _gate()
    run = tmp_path / "reports.json"
    run.write_text(json.dumps(_driver_report(missing_cell=True)))
    assert gate.main(["--run", str(run),
                      "--ab-methods", "dear-fused:dear"]) == 2
    verdict = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert verdict["missing"] == ["gpt2[8]"]
    assert gate.main(["--run", str(run), "--allow-missing",
                      "--ab-methods", "dear-fused:dear"]) == 0
    capsys.readouterr()
    # a FAILED candidate cell (scrape returned nothing) is missing too
    run.write_text(json.dumps(_driver_report(failed_cell=True)))
    assert gate.main(["--run", str(run),
                      "--ab-methods", "dear-fused:dear"]) == 2
    capsys.readouterr()
    # malformed spec -> unusable-input exit code
    assert gate.main(["--run", str(run), "--ab-methods", "nope"]) == 3
    capsys.readouterr()
    # --ab-methods reads a driver reports.json, the other gates read
    # contract metric files: combining would silently gate nothing, so
    # the tool refuses loudly instead
    assert gate.main(["--run", str(run), "--ab-methods", "dear-fused:dear",
                      "--slo", "steps_per_hour=1"]) == 3
    capsys.readouterr()
