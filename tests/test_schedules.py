"""LR schedules: shapes, config resolution, and exactness through the
jitted (and scanned) dear train step — the schedule must see the same
global step a per-step host loop would."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dear_pytorch_tpu.ops import schedules
from dear_pytorch_tpu.ops.fused_sgd import fused_adamw, fused_sgd


def test_warmup_linear_shape():
    f = schedules.warmup_linear(1.0, warmup_steps=10, total_steps=110)
    assert float(f(0)) == 0.0
    assert float(f(5)) == pytest.approx(0.5)
    assert float(f(10)) == pytest.approx(1.0)
    assert float(f(60)) == pytest.approx(0.5)
    assert float(f(110)) == pytest.approx(0.0)
    assert float(f(500)) == pytest.approx(0.0)  # clamped past horizon


def test_warmup_cosine_shape():
    f = schedules.warmup_cosine(2.0, warmup_steps=4, total_steps=104,
                                min_lr=0.2)
    assert float(f(2)) == pytest.approx(1.0)
    assert float(f(4)) == pytest.approx(2.0)
    assert float(f(54)) == pytest.approx(0.5 * (2.0 + 0.2))
    assert float(f(104)) == pytest.approx(0.2)
    assert float(f(999)) == pytest.approx(0.2)


def test_multistep_shape():
    f = schedules.multistep(1.0, milestones=(3, 7), gamma=0.1)
    np.testing.assert_allclose(
        [float(f(s)) for s in (0, 2, 3, 6, 7, 100)],
        [1.0, 1.0, 0.1, 0.1, 0.01, 0.01], rtol=1e-6,
    )


def test_bad_horizons_rejected():
    with pytest.raises(ValueError, match="must exceed"):
        schedules.warmup_linear(1.0, 10, 10)
    with pytest.raises(ValueError, match="non-negative"):
        schedules.multistep(1.0, (-1,))


def test_from_config():
    from dear_pytorch_tpu.config import DearConfig

    cfg = DearConfig(lr=0.5)
    assert schedules.from_config(cfg) == 0.5
    cfg = DearConfig(lr=0.5, lr_schedule="cosine", warmup_steps=2,
                     total_steps=10)
    assert callable(schedules.from_config(cfg))
    with pytest.raises(ValueError, match="needs total_steps"):
        schedules.from_config(DearConfig(lr_schedule="linear"))
    with pytest.raises(ValueError, match="lr_schedule must be"):
        schedules.from_config(
            DearConfig(lr_schedule="sawtooth", total_steps=5)
        )


def _tiny_problem():
    def loss_fn(p, b):
        pred = b["x"] @ p["w"] + p["b"]
        return jnp.mean((pred - b["y"]) ** 2)

    rng = np.random.RandomState(0)
    params = {
        "w": jnp.asarray(rng.randn(6, 4), jnp.float32),
        "b": jnp.zeros((4,), jnp.float32),
    }
    batch = {
        "x": jnp.asarray(rng.randn(8, 6), jnp.float32),
        "y": jnp.asarray(rng.randn(8, 4), jnp.float32),
    }
    return loss_fn, params, batch


@pytest.mark.parametrize("opt_name", ["sgd", "adamw"])
def test_schedule_through_dear_step_matches_manual(mesh, opt_name):
    """3 scanned steps under a schedule == 3 manual full-batch updates with
    lr evaluated at steps 0,1,2 on the host."""
    from dear_pytorch_tpu.parallel import dear as D

    sched = schedules.warmup_linear(0.1, warmup_steps=2, total_steps=6)
    loss_fn, params, batch = _tiny_problem()
    make = fused_sgd if opt_name == "sgd" else fused_adamw
    opt_kwargs = {"momentum": 0.9} if opt_name == "sgd" else {}
    ts = D.build_train_step(
        loss_fn, params, mesh=mesh, mode="dear",
        optimizer=make(sched, **opt_kwargs),
    )
    state = ts.init(params)
    runner = ts.multi_step(3)
    state, _ = runner(state, batch)
    got = ts.gather_params(state)

    # manual reference: same optimizer math at fixed per-step lr floats
    ref_params = params
    ref_opt = None
    for step in range(3):
        lr_t = float(sched(step))
        ref_ts = D.build_train_step(
            loss_fn, ref_params, mesh=mesh, mode="dear",
            optimizer=make(lr_t, **opt_kwargs),
        )
        ref_state = ref_ts.init(ref_params)
        if ref_opt is not None:
            ref_state = ref_state._replace(opt_state=ref_opt)
        ref_state, _ = ref_ts.step(ref_state, batch)
        ref_opt = ref_state.opt_state
        ref_params = ref_ts.gather_params(ref_state)

    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-6, atol=2e-6
        ),
        got, ref_params,
    )


def test_schedule_keeps_bf16_buffer_dtype(mesh):
    """A scheduled lr must not promote bf16 master buffers to f32 (the
    scanned carry's dtype would change mid-trace)."""
    opt = fused_sgd(schedules.warmup_cosine(0.1, 1, 10))
    p = jnp.ones((8,), jnp.bfloat16)
    new_p, _ = opt.update(jnp.ones_like(p), opt.init(p), p,
                          step=jnp.asarray(3))
    assert new_p.dtype == jnp.bfloat16


def test_multistep_requires_milestones():
    from dear_pytorch_tpu.config import DearConfig

    with pytest.raises(ValueError, match="needs lr_milestones"):
        schedules.from_config(DearConfig(lr_schedule="multistep"))


def test_lamb_schedule_through_dear_step(mesh):
    """LAMB's layerwise (segment-sum) update path also threads the step:
    a decayed schedule must move params differently than its base lr."""
    from dear_pytorch_tpu.ops.fused_sgd import fused_lamb
    from dear_pytorch_tpu.parallel import dear as D

    loss_fn, params, batch = _tiny_problem()

    def run(lr):
        ts = D.build_train_step(
            loss_fn, params, mesh=mesh, mode="dear",
            optimizer=fused_lamb(lr, weight_decay=0.0),
        )
        st = ts.init(params)
        st, _ = ts.multi_step(3)(st, batch)
        return ts.gather_params(st)

    sched = schedules.multistep(0.1, milestones=(1,), gamma=0.1)
    got_sched = run(sched)
    got_fixed = run(0.1)
    diffs = jax.tree.leaves(jax.tree.map(
        lambda a, b: float(jnp.max(jnp.abs(a - b))), got_sched, got_fixed
    ))
    assert max(diffs) > 1e-5  # the decay after step 1 must show up
