"""ASC / MGS-SGD analytic grouping + DGC momentum correction.

Grouping tests drive the merge decisions against hand-checkable cost
regimes (reference dear/hv_distributed_optimizer.py:353-427,
wfbp/dopt.py:488-569); the momentum-correction test replays the exact
reference algebra (wfbp/dopt.py:769-775 velocity, compressor residual,
:946-951 post-step velocity mask) in numpy and demands the jitted
train step match it state-for-state."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dear_pytorch_tpu.ops.fused_sgd import fused_sgd
from dear_pytorch_tpu.parallel import build_train_step
from dear_pytorch_tpu.tuning import (
    asc_layer_groups,
    mgs_layer_groups,
    plan_asc,
    plan_mgs,
)

SIZES = [4e6, 2e6, 1e6, 1e6]      # bytes, forward order
TIMES = [3e-3, 2e-3, 2e-3, 1e-3]  # backward seconds, forward order


def test_asc_no_merge_when_comm_is_free():
    # zero-cost comm always finishes before the next gradient is ready
    groups = asc_layer_groups(SIZES, TIMES, alpha=0.0, beta=0.0)
    assert groups == [[0], [1], [2], [3]]


def test_asc_merges_all_when_startup_dominates():
    # alpha >> total backward: every later bucket's comm is still queued
    # when the next gradient arrives -> coalesce. The LAST layer can never
    # merge: its comm starts the moment its gradient is ready (taoc[L-1] ==
    # ready[L-1]), so the started-yet test is always false for it —
    # reference semantics (hv_distributed_optimizer.py:407-409).
    groups = asc_layer_groups(SIZES, TIMES, alpha=1.0, beta=0.0)
    assert groups == [[0, 1, 2], [3]]


def test_asc_middle_regime_matches_hand_computation():
    # tc = [alpha + beta*bytes]: layer 3 comm (1ms) finishes exactly when
    # grad 2 is ready (tb[3]=1ms later? no: grad3 ready at t=1ms, comm3 runs
    # [1,2]ms; grad2 ready at 1+2=3ms > 2ms: comm finished AND started ->
    # no merge. comm2 runs [3,4]ms; grad1 ready at 3+2=5ms -> no merge.
    # comm1 runs [5,7]ms; grad0 ready 5+3=8ms -> no merge.
    alpha, beta = 0.0, 0.25e-9  # 1 MB/ms -> tc = [1, .5, .25, .25] ms? no:
    # bytes 4e6*0.25e-9 = 1e-3 s etc.
    groups = asc_layer_groups(SIZES, TIMES, alpha=alpha, beta=beta)
    assert groups == [[0], [1], [2], [3]]
    # with a 5 ms startup the queue backs up once: layer 2's comm is queued
    # behind layer 3's (start 6.25 ms) when grad 1 lands at 5 ms -> merge 2
    # into 1. The merged bucket then STARTS at 6.25 ms (grad ready 5 ms,
    # queue free 6.25 ms), which is before grad 0 lands at 8 ms -> started
    # -> no further merge. Layer 3's comm starts immediately -> alone.
    groups = asc_layer_groups(SIZES, TIMES, alpha=5e-3, beta=beta)
    assert groups == [[0], [1, 2], [3]]


def test_mgs_merges_when_gather_startup_dominates():
    sizes = [1e6, 1e6, 1e6, 1e6]  # elements
    groups = mgs_layer_groups(
        sizes, TIMES, alpha=1.0, beta=0.0, world=8, density=0.01,
        topk_s=0.0,
    )
    assert groups == [[0, 1, 2, 3]]


def test_mgs_no_merge_when_topk_dominates():
    sizes = [1e6, 1e6, 1e6, 1e6]
    groups = mgs_layer_groups(
        sizes, TIMES, alpha=0.0, beta=0.0, world=8, density=0.01,
        topk_s=1.0,  # re-running top-k over merged tensors is ruinous
    )
    assert groups == [[0], [1], [2], [3]]


def _tiny_params():
    k = jax.random.PRNGKey(0)
    return {
        "a": {"w": jax.random.normal(k, (8, 8)), "b": jnp.zeros((8,))},
        "c": {"w": jax.random.normal(jax.random.fold_in(k, 1), (8, 4))},
    }


def test_plan_builders_cover_all_leaves(mesh):
    params = _tiny_params()
    n_layers = 2  # atomic layers group by parent path: {a: w+b}, {c: w}
    for plan in (
        plan_asc(params, 8, layer_times=[1e-3] * n_layers, alpha=1.0,
                 beta=0.0),
        plan_mgs(params, 8, layer_times=[1e-3] * n_layers, alpha=1.0,
                 beta=0.0, density=0.05),
    ):
        assert plan.world == 8
        covered = sorted(i for b in plan.buckets for i in b.leaf_ids)
        assert covered == list(range(len(plan.leaves)))


def test_momentum_correction_matches_reference_algebra(mesh, world):
    """Jitted mc training == numpy replay of the reference's DGC loop:
    u = mc*u + g; x = u + res; send top-k(x); res = x - sent;
    u = u masked at sent; w -= lr * mean(decompressed sent)."""
    n, k, mc, lr = 32, 2, 0.9, 0.1
    rng = np.random.default_rng(3)
    c = rng.normal(size=(world, n)).astype(np.float32)  # per-device grads

    params = {"w": jnp.zeros((n,), jnp.float32)}

    def loss_fn(p, b):
        return jnp.sum(p["w"] * b[0])

    ts = build_train_step(
        loss_fn, params, mesh=mesh, mode="allreduce",
        compressor="eftopk", density=k / n, momentum_correction=mc,
        threshold_mb=None, donate=False,
        optimizer=fused_sgd(lr=lr, momentum=0.0),
    )
    state = ts.init(params)
    batch = jnp.asarray(c)
    for _ in range(3):
        state, _ = ts.step(state, batch)

    # ---- numpy replay --------------------------------------------------
    w = np.zeros(n, np.float32)
    u = np.zeros((world, n), np.float32)
    res = np.zeros((world, n), np.float32)
    for _ in range(3):
        dense = np.zeros(n, np.float32)
        for d in range(world):
            u[d] = mc * u[d] + c[d]
            x = u[d] + res[d]
            idx = np.argsort(-np.abs(x))[:k]
            sent = np.zeros(n, np.float32)
            sent[idx] = x[idx]
            res[d] = x - sent
            u[d][idx] = 0.0
            dense += sent
        w -= lr * dense / world
    # ---- compare -------------------------------------------------------
    np.testing.assert_allclose(
        np.asarray(state.buffers[0])[:n], w, rtol=1e-5, atol=1e-6
    )
    centry = state.comp_state[0]
    np.testing.assert_allclose(np.asarray(centry["vel"])[:, :n], u,
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(centry["res"])[:, :n], res,
                               rtol=1e-5, atol=1e-6)


def test_momentum_correction_requires_sparse(mesh):
    with pytest.raises(ValueError, match="sparse"):
        build_train_step(
            lambda p, b: jnp.sum(p["w"] * b[0]),
            {"w": jnp.zeros((8,))}, mesh=mesh, mode="allreduce",
            compressor="signum", momentum_correction=0.9,
        )


def test_momentum_correction_training_learns(mesh):
    from tests.test_dear_numerics import _data, _loss_fn, _mlp_params

    params = _mlp_params(jax.random.PRNGKey(0))
    batch = _data(jax.random.PRNGKey(100))
    ts = build_train_step(
        _loss_fn, params, mesh=mesh, mode="allreduce",
        compressor="eftopk", density=0.25, momentum_correction=0.9,
        threshold_mb=0.0008, donate=False,
        optimizer=fused_sgd(lr=0.05, momentum=0.0),
    )
    state = ts.init(params)
    losses = []
    for _ in range(8):
        state, m = ts.step(state, batch)
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0], losses
