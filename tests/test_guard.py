"""Failure detection + rollback: a poisoned batch that NaNs the loss must
be detected, the state rolled back to the newest checkpoint, and training
must continue to convergence — the recovery story the reference lacks
entirely (its CHECK macros abort the process)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dear_pytorch_tpu.ops.fused_sgd import fused_sgd
from dear_pytorch_tpu.parallel import build_train_step
from dear_pytorch_tpu.utils.guard import DivergenceError, GuardedTrainer

from tests.test_dear_numerics import _data, _loss_fn, _mlp_params


def _trainer(mesh, tmp_path, **kw):
    params = _mlp_params(jax.random.PRNGKey(0))
    ts = build_train_step(
        _loss_fn, params, mesh=mesh, threshold_mb=0.0008, donate=False,
        optimizer=fused_sgd(lr=0.05, momentum=0.9),
    )
    kw.setdefault("check_every", 1)
    kw.setdefault("checkpoint_every", 4)
    return params, ts, GuardedTrainer(ts, str(tmp_path / "g"), params, **kw)


def _poison(batch):
    x, y = batch
    return (x.at[0, 0].set(jnp.nan), y)


def test_rollback_on_nan_and_recovery(mesh, tmp_path):
    params, ts, tr = _trainer(mesh, tmp_path)
    batches = [_data(jax.random.PRNGKey(100 + i)) for i in range(12)]
    state = ts.init(params)
    rollbacks = []
    tr.on_rollback = lambda n, at: rollbacks.append((n, at))

    losses = []
    for i, b in enumerate(batches):
        if i == 6:  # after the step-4 checkpoint
            state, m = tr.step(state, _poison(b))
            assert m.get("rolled_back"), m
            continue
        state, m = tr.step(state, b)
        losses.append(float(m["loss"]))

    assert rollbacks == [(1, 4)]
    assert all(np.isfinite(losses)), losses
    # post-rollback training continued and kept improving
    assert losses[-1] < losses[0]
    # the restored state was the step-4 checkpoint, not the poisoned one
    assert int(jax.device_get(state.step)) > 4


def test_rollback_with_async_checkpoints(mesh, tmp_path):
    """async_checkpoints=True: saves overlap training, the in-flight write's
    temp dir survives pruning, and a rollback waits for the commit so it
    restores the NEWEST checkpoint."""
    params, ts, tr = _trainer(mesh, tmp_path, async_checkpoints=True)
    batches = [_data(jax.random.PRNGKey(300 + i)) for i in range(12)]
    state = ts.init(params)
    rollbacks = []
    tr.on_rollback = lambda n, at: rollbacks.append((n, at))

    for i, b in enumerate(batches):
        if i == 9:  # after the step-8 checkpoint (saved asynchronously)
            state, m = tr.step(state, _poison(b))
            assert m.get("rolled_back"), m
            continue
        state, m = tr.step(state, b)
        assert np.isfinite(float(m["loss"]))

    # restored from step 8 (the async save committed before restore), not 4
    assert rollbacks == [(1, 8)]
    assert int(jax.device_get(state.step)) > 8


def test_rollback_survives_failed_inflight_async_write(mesh, tmp_path,
                                                       monkeypatch):
    """A failed in-flight async write must not kill the rollback: the guard
    falls back to the newest COMMITTED checkpoint."""
    from dear_pytorch_tpu.utils import checkpoint as ckpt_mod

    params, ts, tr = _trainer(mesh, tmp_path, async_checkpoints=True)
    batches = [_data(jax.random.PRNGKey(400 + i)) for i in range(6)]
    state = ts.init(params)
    for b in batches[:5]:
        state, _ = tr.step(state, b)  # step-4 checkpoint committed
    tr.finalize()

    def boom():
        raise RuntimeError("disk full")

    monkeypatch.setattr(ckpt_mod, "wait_for_checkpoints", boom)
    state, m = tr.step(state, _poison(batches[5]))
    assert m.get("rolled_back"), m
    assert int(jax.device_get(state.step)) == 4


def test_failed_async_save_does_not_reset_recoveries(mesh, tmp_path,
                                                     monkeypatch):
    """A swallowed async save failure must not count as persisted progress:
    the recoveries counter keeps accumulating so max_recoveries still
    trips."""
    from dear_pytorch_tpu.utils import checkpoint as ckpt_mod

    params, ts, tr = _trainer(mesh, tmp_path, async_checkpoints=True)
    state = ts.init(params)
    batches = [_data(jax.random.PRNGKey(700 + i)) for i in range(5)]
    for b in batches:
        state, _ = tr.step(state, b)  # commits the step-4 checkpoint
    tr.finalize()
    tr.recoveries = 2

    def boom(*a, **kw):
        raise RuntimeError("disk full")

    monkeypatch.setattr(ckpt_mod, "save_checkpoint", boom)
    for i in range(3):
        state, _ = tr.step(state, _data(jax.random.PRNGKey(800 + i)))
    assert tr.recoveries == 2  # failed saves reset nothing


def test_finalize_and_context_manager(mesh, tmp_path):
    params, ts, tr = _trainer(mesh, tmp_path, async_checkpoints=True)
    batches = [_data(jax.random.PRNGKey(500 + i)) for i in range(4)]
    state = ts.init(params)
    with tr:
        for b in batches:
            state, _ = tr.step(state, b)
    # the final async save committed before the with-block exited
    from dear_pytorch_tpu.utils import checkpoint as ckpt_mod

    assert ckpt_mod.latest_step(str(tmp_path / "g")) == 4


def test_prune_removes_orphan_meta_sidecars(mesh, tmp_path):
    """meta_*.json written for a save that never committed (async failure /
    crash) must be cleaned up by the retention pass."""
    import os

    params, ts, tr = _trainer(mesh, tmp_path)
    d = str(tmp_path / "g")
    os.makedirs(d, exist_ok=True)
    orphan = os.path.join(d, "meta_0000000099.json")
    with open(orphan, "w") as f:
        f.write("{}")
    batches = [_data(jax.random.PRNGKey(600 + i)) for i in range(4)]
    state = ts.init(params)
    for b in batches:
        state, _ = tr.step(state, b)  # step-4 checkpoint triggers _prune
    assert not os.path.exists(orphan)
    # the committed checkpoint's sidecar survives
    assert os.path.exists(os.path.join(d, "meta_0000000004.json"))


def test_divergence_before_first_checkpoint_raises(mesh, tmp_path):
    params, ts, tr = _trainer(mesh, tmp_path, checkpoint_every=1000)
    state = ts.init(params)
    with pytest.raises(DivergenceError, match="first checkpoint"):
        tr.step(state, _poison(_data(jax.random.PRNGKey(0))))


def test_max_recoveries_enforced(mesh, tmp_path):
    params, ts, tr = _trainer(mesh, tmp_path, max_recoveries=2,
                              checkpoint_every=1)
    state = ts.init(params)
    good = _data(jax.random.PRNGKey(1))
    state, _ = tr.step(state, good)  # step 1 -> checkpoint exists
    bad = _poison(good)
    state, m = tr.step(state, bad)
    assert m.get("rolled_back")
    state, m = tr.step(state, bad)
    assert m.get("rolled_back")
    with pytest.raises(DivergenceError, match="diverged"):
        tr.step(state, bad)


def test_step_time_accounting(mesh, tmp_path):
    params, ts, tr = _trainer(mesh, tmp_path)
    state = ts.init(params)
    for i in range(3):
        state, _ = tr.step(state, _data(jax.random.PRNGKey(i)))
    assert tr.ema_step_s is not None and tr.ema_step_s > 0
    assert tr.max_step_s >= tr.ema_step_s * 0.5


def test_checkpoint_step_always_verifies_before_saving(mesh, tmp_path):
    """A checkpoint step that is NOT a check step must still verify the
    loss before persisting: saving an unchecked NaN state would make every
    future rollback restore the poison."""
    params, ts, tr = _trainer(mesh, tmp_path, check_every=100,
                              checkpoint_every=2)
    from dear_pytorch_tpu.utils import checkpoint as ckpt

    state = ts.init(params)
    good = _data(jax.random.PRNGKey(5))
    state, _ = tr.step(state, good)          # 1
    state, _ = tr.step(state, good)          # 2 -> checkpoint
    assert ckpt.latest_step(str(tmp_path / "g")) == 2
    state, _ = tr.step(state, good)          # 3
    state, m = tr.step(state, _poison(good))  # 4: ckpt step, poisoned
    assert m.get("rolled_back"), m
    # the poisoned step-4 state was NOT persisted
    assert ckpt.latest_step(str(tmp_path / "g")) == 2


def test_recoveries_reset_after_healthy_checkpoint(mesh, tmp_path):
    """max_recoveries bounds CONSECUTIVE rollbacks, not lifetime faults."""
    params, ts, tr = _trainer(mesh, tmp_path, max_recoveries=1,
                              checkpoint_every=1)
    state = ts.init(params)
    good = _data(jax.random.PRNGKey(6))
    state, _ = tr.step(state, good)
    for _ in range(3):  # three independent faults, healthy steps between
        state, m = tr.step(state, _poison(good))
        assert m.get("rolled_back")
        state, m = tr.step(state, good)  # checkpoint -> counter reset
        assert not m.get("rolled_back")


def test_checkpoint_retention_prunes_old(mesh, tmp_path):
    from dear_pytorch_tpu.utils import checkpoint as ckpt

    params, ts, tr = _trainer(mesh, tmp_path, checkpoint_every=1,
                              max_keep=2)
    state = ts.init(params)
    good = _data(jax.random.PRNGKey(7))
    for _ in range(5):
        state, _ = tr.step(state, good)
    import os

    steps = sorted(
        int(n[len("step_"):]) for n in os.listdir(str(tmp_path / "g"))
        if n.startswith("step_")
    )
    assert steps == [4, 5]
    assert ckpt.latest_step(str(tmp_path / "g")) == 5


def test_prune_removes_orbax_tmp_leftovers(mesh, tmp_path):
    import os

    params, ts, tr = _trainer(mesh, tmp_path, checkpoint_every=1,
                              max_keep=2)
    d = str(tmp_path / "g")
    os.makedirs(d, exist_ok=True)
    junk = os.path.join(d, "step_0000000001.orbax-checkpoint-tmp-42")
    os.makedirs(junk)
    state = ts.init(params)
    state, _ = tr.step(state, _data(jax.random.PRNGKey(8)))
    assert not os.path.exists(junk)
