"""Shared elastic-worker harness.

`tests/mp_worker.py` (``DEAR_MP_MODE=elastic``) and
`scripts/chaos_check.py --worker --elastic` drive the same scenario — a
supervised rank that may SIGKILL itself, survivors that transition
through the guard's membership machinery, and a relaunched rank that
re-enters through rejoin — with different models and different final
verdicts. The protocol-shaped pieces they must agree on live here, in
exactly one place, so a change to the rejoin handshake or the transition
hook ordering cannot drift between the two entry points:

  - `attach_elastic` — the membership-transition hook (plan rescale +
    train-step swap) every elastic worker wires the same way;
  - `reenter` — the relaunched rank's re-entry sequence (sidecar epoch →
    `rejoin` → rescale → `elastic_resume`);
  - `run_loop` — the kill/step/target loop with the idle cadence that
    keeps the member sync polling for rejoin requests.

Import: plain (`import elastic_harness`) when launched from tests/;
`importlib` by file path from scripts/. Deliberately jax-free at module
level — workers configure the backend env BEFORE importing anything
heavy, and this module must not get in the way of that.
"""

from __future__ import annotations

import os
import signal
import time
from typing import Callable, Optional, Tuple


def attach_elastic(guard, tuner) -> Callable:
    """Wire the guard's membership-transition hook: rescale the fusion
    plan for the committed view (epoch-stamped) and swap the guard's
    train step BEFORE the consensus restore, so the elastic re-pack lands
    in the rescaled plan. Returns the hook (already attached)."""
    def on_change(view):
        tuner.rescale(view)
        guard.ts = tuner.ts
        guard._template = None
    guard.on_membership_change = on_change
    return on_change


def reenter(cluster, tuner, guard, ckpt_dir: str, hydrate_store=None):
    """Relaunched-rank re-entry: present the newest sidecar's membership
    epoch as "last known", wait for admission, rescale the plan for the
    admitted view, and consensus-restore through `elastic_resume`.
    Returns ``(state, resumed_at_step, last_epoch)``.

    A **scale-from-zero** rank (brand-new scale-up spawn, or a host whose
    disk was lost with it) has no local checkpoints to contribute to the
    consensus restore; with ``hydrate_store`` (an object store holding a
    fleet replica's uploads) it first materializes the newest uploaded
    step locally (`restore_from_object_store`, sha256-reverified), so its
    consensus view intersects the survivors' at that step. A rank that
    was down a LONG time hydrates too — its local newest is far behind
    the fleet, and since the consensus restores the newest step valid on
    EVERY member, rejoining with the stale view alone would drag every
    survivor back to it (observed: a drained rank's backfill rolled a
    200-step fleet back to step 18). Hydration caps the fleet's loss at
    the upload lag instead of the rejoiner's downtime."""
    from dear_pytorch_tpu.utils import checkpoint as ckpt

    steps = ckpt.valid_steps(ckpt_dir)
    if hydrate_store is not None:
        remote = ckpt.remote_steps(hydrate_store)
        if remote and (not steps or remote[0] > steps[0]):
            hydrated = ckpt.restore_from_object_store(
                hydrate_store, ckpt_dir, step=remote[0])
            if hydrated is not None:
                steps = ckpt.valid_steps(ckpt_dir)
    last_epoch = ckpt.read_mem_epoch(ckpt_dir, steps[0]) if steps else None
    view, context = cluster.rejoin(last_epoch)
    tuner.rescale(view)
    guard.ts = tuner.ts
    state, at_step = guard.elastic_resume(context)
    return state, at_step, last_epoch


def run_loop(
    cluster,
    guard,
    pipe,
    state,
    batch_at: Callable[[int], object],
    tracer,
    *,
    rejoining: bool,
    kill: Optional[Tuple[int, int]] = None,
    post: int = 4,
    t_target: Optional[int] = None,
    no_kill_target: Optional[int] = None,
    deadline_s: float = 300.0,
    idle_s: float = 0.1,
):
    """The elastic training loop every worker runs after setup. The
    scheduled victim SIGKILLs itself before attempt ``kill[1]``;
    survivors keep stepping (transitions happen inside ``guard.step``)
    until ``post`` lockstep steps after the relaunch's admission
    (``cluster.rejoins`` observed); a rejoiner enters with ``t_target``
    already set by `reenter`'s caller. With no kill scheduled the loop
    runs to ``no_kill_target`` attempts. The idle sleep keeps the member
    sync cadence slow enough that the leader's rejoin poll isn't racing
    hundreds of checkpoints past the rejoiner's view. Returns
    ``(state, metrics)``; raises `TimeoutError` if the target is never
    reached within ``deadline_s``."""
    kill_rank, kill_at = kill if kill is not None else (None, None)
    deadline = time.monotonic() + deadline_s
    m = {}
    while True:
        if time.monotonic() >= deadline:
            raise TimeoutError(
                f"rank {cluster.rank} never reached its target "
                f"(epoch {cluster.epoch})")
        i = guard.steps_seen
        if not rejoining and kill_rank == cluster.rank and i + 1 == kill_at:
            os.kill(os.getpid(), signal.SIGKILL)  # a lost host, abruptly
        pipe.next()  # the guarded input stream advances once per step
        state, m = guard.step(state, batch_at(i))
        if kill_rank is None:
            t_target = no_kill_target
        elif (t_target is None
                and tracer.counters().get("cluster.rejoins", 0) >= 1):
            t_target = guard.steps_seen + post  # admission landed HERE
        if t_target is not None and guard.steps_seen >= t_target:
            return state, m
        if t_target is None:
            time.sleep(idle_s)


def run_autoscale_loop(
    cluster,
    guard,
    pipe,
    state,
    batch_at: Callable[[int], object],
    *,
    rejoining: bool,
    target_epoch: int,
    post: int = 3,
    kill: Optional[Tuple[int, int, int]] = None,
    deadline_s: float = 300.0,
    idle_s: float = 0.1,
):
    """The autoscaling worker loop (`scripts/chaos_check.py --autoscale`).

    Differences from `run_loop`: termination is **epoch-driven** —
    membership epochs commit inside the lockstep health sync, so every
    member observes ``cluster.epoch >= target_epoch`` at the SAME attempt
    and the ``post``-step runout stays lockstep without any counter
    heuristics (a rejoiner admitted at the target epoch anchors on the
    admission ack's cadence instead). ``kill`` is
    ``(rank, after_epoch, extra_steps)``: the victim SIGKILLs itself
    ``extra_steps`` attempts after it first observes ``after_epoch``. A
    ``preempted`` metric (the supervisor's SIGTERM drain → planned
    shrink → emergency save) exits the loop cleanly — the policy
    backfills the rank, which re-enters through `reenter`."""
    kill_rank, kill_epoch, kill_extra = kill if kill else (None, None, 0)
    kill_at = None
    deadline = time.monotonic() + deadline_s
    t_target = (guard.steps_seen + post
                if rejoining and cluster.epoch >= target_epoch else None)
    m = {}
    while True:
        if time.monotonic() >= deadline:
            raise TimeoutError(
                f"rank {cluster.rank} never reached epoch {target_epoch} "
                f"(at epoch {cluster.epoch})")
        i = guard.steps_seen
        if not rejoining and kill_rank == cluster.rank:
            if kill_at is None and cluster.epoch >= kill_epoch:
                kill_at = i + 1 + kill_extra
            if kill_at is not None and i + 1 == kill_at:
                os.kill(os.getpid(), signal.SIGKILL)  # abrupt host loss
        pipe.next()  # the guarded input stream advances once per step
        state, m = guard.step(state, batch_at(i))
        if m.get("preempted"):
            return state, m  # drained: clean exit inside the grace window
        if t_target is None and cluster.epoch >= target_epoch:
            t_target = guard.steps_seen + post
        if t_target is not None and guard.steps_seen >= t_target:
            return state, m
        if t_target is None:
            time.sleep(idle_s)
