"""Benchmark harness tests: CLI surface, protocol, and the scrape-able
output contract (reference benchmarks.py:119-128 greps the
``Total ... <DEV>(s): N +-C`` line)."""

import re

import pytest

from dear_pytorch_tpu.benchmarks import bert as bert_bench
from dear_pytorch_tpu.benchmarks import imagenet as imagenet_bench


TINY = ["--num-warmup-batches", "1", "--num-batches-per-iter", "2",
        "--num-iters", "2"]


def test_imagenet_cli_output_contract(mesh, capsys):
    res = imagenet_bench.main(
        ["--model", "mnistnet", "--batch-size", "4"] + TINY
    )
    out = capsys.readouterr().out
    m = re.search(r"Total img/sec on (\d+) \w+\(s\): ([\d.]+) \+-([\d.]+)",
                  out)
    assert m, out
    assert int(m.group(1)) == 8
    assert abs(float(m.group(2)) - res.total_mean) < 0.1
    assert "Running warmup..." in out and "Running benchmark..." in out
    # per-device x world == total
    assert res.total_mean == pytest.approx(8 * res.per_device_mean)


def test_imagenet_scanned_protocol(mesh, capsys):
    """--scan-steps k: one lax.scan program per dispatch; reported
    throughput stays in the same ballpark as per-step dispatch and the
    scrape line shape is unchanged."""
    base = imagenet_bench.main(
        ["--model", "mnistnet", "--batch-size", "4"] + TINY
    )
    scanned = imagenet_bench.main(
        ["--model", "mnistnet", "--batch-size", "4", "--scan-steps", "2",
         "--num-warmup-batches", "2", "--num-batches-per-iter", "4",
         "--num-iters", "2"]
    )
    out = capsys.readouterr().out
    assert "Scanned protocol: 2 steps per dispatch" in out
    # accounting invariant: throughput x per-REAL-step time = per-device
    # batch items, under BOTH protocols. Means of reciprocal quantities are
    # Jensen-biased upward under timing variance, so the tolerance is
    # generous — this checks the scan_steps bookkeeping (a factor-2 error
    # would blow straight through it), not machine speed.
    for res in (base, scanned):
        assert res.per_device_mean * res.iter_time_mean == pytest.approx(
            4.0, rel=0.35
        )
    assert scanned.per_device_mean > 0
    with pytest.raises(SystemExit, match="pipeline"):
        imagenet_bench.main(
            ["--model", "mnistnet", "--batch-size", "4", "--scan-steps",
             "2", "--pipeline", "numpy"] + TINY
        )
    with pytest.raises(SystemExit, match="autotune"):
        imagenet_bench.main(
            ["--model", "mnistnet", "--batch-size", "4", "--scan-steps",
             "2", "--autotune", "bo"] + TINY
        )


def test_imagenet_modes_and_ablations(mesh):
    # baseline schedule + exclude-parts ablation parse & run
    imagenet_bench.main(
        ["--model", "mnistnet", "--batch-size", "4", "--mode", "allreduce"]
        + TINY
    )
    imagenet_bench.main(
        ["--model", "mnistnet", "--batch-size", "4",
         "--exclude-parts", "allgather"] + TINY
    )
    with pytest.raises(SystemExit):
        imagenet_bench.main(
            ["--model", "mnistnet", "--exclude-parts", "bogus"] + TINY
        )


def test_bert_cli_output_contract(mesh, capsys):
    res = bert_bench.main(
        ["--model", "bert_base", "--num-hidden-layers", "1",
         "--sentence-len", "16", "--batch-size", "2"] + TINY
    )
    out = capsys.readouterr().out
    assert re.search(r"Total sen/sec on 8 \w+\(s\): ", out), out
    assert "BERT Base Pretraining, Sentence len: 16" in out
    assert res.unit == "sen"


def test_imagenet_autotune_bo(mesh):
    # BO autotune drives the live re-bucketing machinery from the CLI
    imagenet_bench.main(
        ["--model", "mnistnet", "--batch-size", "4", "--autotune", "bo",
         "--num-warmup-batches", "6", "--num-batches-per-iter", "6",
         "--num-iters", "2"]
    )


def test_imagenet_compressed_allreduce(mesh):
    imagenet_bench.main(
        ["--model", "mnistnet", "--batch-size", "4", "--mode", "allreduce",
         "--compressor", "eftopk", "--density", "0.1"] + TINY
    )


@pytest.mark.parametrize("pl", ["native", "numpy"])
def test_imagenet_streaming_pipeline(mesh, pl):
    """--pipeline native|numpy feeds the timed loop fresh ring-buffer
    batches instead of one re-fed array; throughput must stay in the same
    regime as batch re-feed (catches a stalled producer or a host-side
    serialization)."""
    base = imagenet_bench.main(
        ["--model", "mnistnet", "--batch-size", "4"] + TINY
    )
    res = imagenet_bench.main(
        ["--model", "mnistnet", "--batch-size", "4", "--pipeline", pl]
        + TINY
    )
    assert res.total_mean > 0
    assert res.total_mean > base.total_mean / 5, (res, base)


@pytest.mark.parametrize("flash", [False, True])
def test_bert_sequence_parallel_cli(mesh, capsys, flash):
    """--sp-degree k: dp x sp mesh, ring(-flash) attention inside the
    model, sentences/sec accounted per CHIP (a sentence spans sp chips)."""
    argv = ["--model", "bert_base", "--num-hidden-layers", "1",
            "--sentence-len", "32", "--batch-size", "2",
            "--sp-degree", "4"] + TINY
    if flash:
        argv.append("--flash-attention")
    res = bert_bench.main(argv)
    out = capsys.readouterr().out
    assert "(dp 2 x sp 4)" in out
    assert re.search(r"Total sen/sec on 8 \w+\(s\): ", out), out
    # 4 sentences/step globally: total throughput = 4 / step_time
    assert res.total_mean * res.iter_time_mean == pytest.approx(4.0,
                                                               rel=0.35)
    with pytest.raises(SystemExit, match="divide"):
        bert_bench.main(["--model", "bert_base", "--sp-degree", "3"] + TINY)
    with pytest.raises(SystemExit, match="sentence-len"):
        bert_bench.main(["--model", "bert_base", "--sentence-len", "30",
                         "--sp-degree", "4"] + TINY)
    with pytest.raises(SystemExit, match="sp-degree"):
        bert_bench.main(["--model", "bert_base",
                         "--sp-attention", "ulysses"] + TINY)
    with pytest.raises(SystemExit, match="conflicts"):
        bert_bench.main(["--model", "bert_base", "--sp-degree", "4",
                         "--flash-attention",
                         "--sp-attention", "ulysses"] + TINY)


def test_bert_streaming_pipeline(mesh):
    res = bert_bench.main(
        ["--model", "bert_base", "--num-hidden-layers", "1",
         "--sentence-len", "16", "--batch-size", "2",
         "--pipeline", "native"] + TINY
    )
    assert res.unit == "sen" and res.total_mean > 0


def test_dropout0_and_remat_flags_shape_the_config():
    """--dropout0 / --remat must actually reach the model config (the r5
    perf decomposition depends on them; a silently-ignored flag would
    re-measure the dropout-on model and report it as dropout-0). The
    override logic is the shared models.dropout_free helper — assert it
    zeroes EVERY dropout field of both config families, and that the
    parsers accept the flags."""
    import dataclasses

    from dear_pytorch_tpu import models
    from dear_pytorch_tpu.benchmarks import bert as bert_cli
    from dear_pytorch_tpu.benchmarks import gpt as gpt_cli

    for cfg in (models.get_model("gpt2").config,
                models.get_model("bert_base").config):
        free = models.dropout_free(cfg)
        dropout_fields = [f.name for f in dataclasses.fields(free)
                          if "dropout" in f.name]
        assert dropout_fields  # the helper must actually find some
        assert all(getattr(free, n) == 0.0 for n in dropout_fields), free
        # non-dropout fields untouched
        assert free.hidden_size == cfg.hidden_size

    g = gpt_cli.build_parser().parse_args(
        ["--dropout0", "--remat", "--batch-size", "2"])
    assert g.dropout0 and g.remat
    b = bert_cli.build_parser().parse_args(["--dropout0"])
    assert b.dropout0


def test_bert_dear_fused_ring_projections_cli(mesh, capsys):
    """--mode dear-fused end-to-end through the BERT CLI, with the QKV/MLP
    projections routed through the ring collective-matmul
    (--ring-projections): the scrape-able contract line still appears."""
    res = bert_bench.main(
        ["--model", "bert_base", "--num-hidden-layers", "1",
         "--sentence-len", "16", "--batch-size", "2",
         "--mode", "dear-fused", "--ring-projections", "--dropout0"]
        + TINY
    )
    out = capsys.readouterr().out
    assert re.search(r"Total sen/sec on 8 \w+\(s\): ", out), out
    assert "Schedule: dear-fused" in out
    assert res.unit == "sen"


def test_ring_projections_flag_requires_dear_fused(mesh):
    with pytest.raises(SystemExit, match="ring-projections"):
        bert_bench.main(
            ["--model", "bert_base", "--num-hidden-layers", "1",
             "--sentence-len", "16", "--batch-size", "2",
             "--ring-projections"] + TINY
        )
