"""Plan-space autotuner tests: the typed space and its feasibility rules,
the analytic cost model built on the overlap auditor's α-β machinery, the
mixed bandit/BO tuner protocol (pruning, infeasibility sandboxing, context
invalidation), the live `AutoTuner(strategy='plan')` loop, and the
guard-interplay contract — a diverging trial reverts plan AND state inside
the tuner, with zero ``guard.rollbacks`` booked against the run."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dear_pytorch_tpu.ops import fusion as F
from dear_pytorch_tpu.ops.fused_sgd import fused_sgd
from dear_pytorch_tpu.tuning import (
    AutoTuner,
    CostModel,
    PlanConfig,
    PlanSpace,
    PlanTuner,
    Tuner,
)
from dear_pytorch_tpu.tuning.planspace import dtype_token

from tests.test_dear_numerics import _data, _loss_fn, _mlp_params


# ---------------------------------------------------------------------------
# the space
# ---------------------------------------------------------------------------


def test_space_axes_and_feasibility():
    space = PlanSpace()
    axes = {a.name: a for a in space.axes()}
    assert axes["threshold_mb"].kind == "continuous"
    assert set(axes["mode"].choices) == {"dear", "dear-fused"}
    assert None in axes["compressor"].choices
    # no combination pairs a compressor with dear-fused or a comm dtype
    for cfg in space.configs():
        assert not (cfg.compressor and cfg.mode == "dear-fused")
        assert not (cfg.compressor and cfg.comm_dtype)
    assert space.feasible(PlanConfig(mode="dear-fused",
                                     compressor="eftopk")) is not None
    assert space.feasible(PlanConfig(compressor="eftopk",
                                     comm_dtype="bf16")) is not None
    assert space.feasible(PlanConfig()) is None
    with pytest.raises(ValueError, match="mode axis"):
        PlanSpace(modes=("allreduce",))


def test_space_from_env(monkeypatch):
    monkeypatch.setenv("DEAR_TUNE_MODES", "dear")
    monkeypatch.setenv("DEAR_TUNE_COMPRESSORS", "none,eftopk")
    monkeypatch.setenv("DEAR_TUNE_DTYPES", "none")
    monkeypatch.setenv("DEAR_TUNE_REMAT", "none")
    monkeypatch.setenv("DEAR_TUNE_DENSITY", "0.05")
    space = PlanSpace.from_env()
    assert space.modes == ("dear",)
    assert space.compressors == (None, "eftopk")
    assert space.comm_dtypes == (None,) and space.gather_dtypes == (None,)
    assert space.remats == (None,)
    assert space.density == 0.05
    assert len(space.configs()) == 2  # dense + eftopk


def test_dtype_tokens():
    assert dtype_token(None) is None
    assert dtype_token("f32") is None
    assert dtype_token("bfloat16") == "bf16"
    assert dtype_token(jnp.bfloat16) == "bf16"
    assert dtype_token(jnp.float16) == "f16"
    with pytest.raises(ValueError):
        dtype_token("int7")
    # build_kwargs resolves tokens back to jnp dtypes
    kw = PlanConfig(comm_dtype="bf16").build_kwargs()
    assert kw["comm_dtype"] is jnp.bfloat16
    assert kw["gather_dtype"] is None


# ---------------------------------------------------------------------------
# cost model
# ---------------------------------------------------------------------------


def _toy_plan_fn():
    params = _mlp_params(jax.random.PRNGKey(0))
    return lambda thr: F.make_plan(params, 8, threshold_mb=thr)


def test_cost_model_orders_wire_formats():
    cm = CostModel(_toy_plan_fn(), alpha=1e-6, beta=1e-8)
    dense = cm.comm(PlanConfig(threshold_mb=0.001))
    bf16 = cm.comm(PlanConfig(threshold_mb=0.001, comm_dtype="bf16",
                              gather_dtype="bf16"))
    sparse = cm.comm(PlanConfig(threshold_mb=0.001, compressor="eftopk",
                                density=0.01))
    assert bf16 < dense
    assert sparse < dense
    # no pruning floor before any calibration observation
    assert cm.floor(PlanConfig()) is None
    cm.observe(PlanConfig(threshold_mb=0.001), measured_s=0.010)
    floor = cm.floor(PlanConfig(threshold_mb=0.001))
    assert floor is not None and floor > 0
    # remat recompute inflates the compute side of the floor
    assert cm.floor(PlanConfig(threshold_mb=0.001, remat="full")) >= floor


# ---------------------------------------------------------------------------
# the tuner protocol (host-only: fake clock, no jax step)
# ---------------------------------------------------------------------------


class _FakeTracer:
    enabled = True

    def __init__(self):
        self.counts: dict = {}

    def count(self, name, value=1):
        self.counts[name] = self.counts.get(name, 0) + value

    def event(self, name, **kw):
        pass


def _drive(tuner, iter_time_of, steps=400):
    """Run the step protocol against a synthetic per-config cost surface."""
    t = {"t": 0.0}
    configs = []
    for _ in range(steps):
        if tuner.finished:
            break
        t["t"] += iter_time_of(tuner.current)
        tuner._clock_value = t["t"]
        p = tuner.step()
        if p is not None:
            configs.append(p)
    return configs


def _mk_tuner(space, tracer=None, **kw):
    def clock():
        return tuner._clock_value

    tuner = PlanTuner(space, log=lambda s: None, clock=clock,
                      tracer=tracer or _FakeTracer(), **kw)
    tuner._clock_value = 0.0
    return tuner


def test_plan_tuner_finds_the_fast_arm():
    space = PlanSpace(modes=("dear",),
                      compressors=(None, "eftopk"),
                      comm_dtypes=(None, "bf16"),
                      gather_dtypes=(None,), remats=(None,),
                      threshold_bound=(1.0, 64.0))
    tracer = _FakeTracer()
    tuner = _mk_tuner(space, tracer=tracer, max_trials=8, interval=5, seed=0)

    def iter_time(cfg: PlanConfig) -> float:
        base = 0.02
        if cfg.comm_dtype == "bf16":
            base -= 0.008          # the fast arm
        if cfg.compressor == "eftopk":
            base += 0.005          # compression overhead dominates here
        return base

    _drive(tuner, iter_time)
    assert tuner.finished
    assert tuner.best_config is not None
    assert tuner.best_config.comm_dtype == "bf16"
    assert tuner.current == tuner.best_config  # adopted
    assert tracer.counts["tune.trials"] >= 3
    assert tracer.counts["tune.best_changed"] >= 1


def test_plan_tuner_prunes_analytically_dominated_arms():
    space = PlanSpace(modes=("dear",),
                      compressors=(None, "eftopk"),
                      comm_dtypes=(None,), gather_dtypes=(None,),
                      remats=(None,), density=0.9,
                      threshold_bound=(0.0005, 0.02))
    # a cost model where the compressed arm's predicted comm alone dwarfs
    # any plausible step time: it must be pruned, never measured
    cm = CostModel(_toy_plan_fn(), alpha=0.0, beta=0.0)
    cm.comm = lambda cfg: 10.0 if cfg.compressor else 1e-4  # type: ignore
    tracer = _FakeTracer()
    tuner = _mk_tuner(space, tracer=tracer, max_trials=6, interval=5,
                      cost_model=cm, prune_margin=0.25,
                      min_obs_to_prune=1)

    _drive(tuner, lambda cfg: 0.01)
    assert tuner.finished
    assert tracer.counts.get("tune.prunes", 0) == 1
    summary = tuner.summary()
    assert summary["pruned"], summary
    # the pruned arm never got a measurement
    assert all(k[1] is None for k in tuner._obs)


def test_plan_tuner_fatal_infeasible_retires_arm():
    space = PlanSpace(modes=("dear", "dear-fused"),
                      compressors=(None,), comm_dtypes=(None,),
                      gather_dtypes=(None,), remats=(None,))
    tracer = _FakeTracer()
    tuner = _mk_tuner(space, tracer=tracer, max_trials=6, interval=5)
    bad = PlanConfig(mode="dear-fused", threshold_mb=25.0)
    tuner.mark_infeasible(bad, revert_to=PlanConfig(), fatal=True,
                          why="build raised ValueError")
    assert tuner.current == PlanConfig()
    assert bad.key() in tuner._dead
    assert tracer.counts["tune.infeasible"] == 1
    # a build failure costs milliseconds, not a measurement window: the
    # arm retirement must NOT consume a trial from the search budget
    assert tuner._num_trials == 0
    # the retired arm is never proposed again
    _drive(tuner, lambda cfg: 0.01)
    assert all(c.mode == "dear" for c in [tuner.current])


def test_plan_tuner_context_invalidation():
    space = PlanSpace(modes=("dear",), compressors=(None,),
                      comm_dtypes=(None, "bf16"), gather_dtypes=(None,),
                      remats=(None,))
    tuner = _mk_tuner(space, max_trials=20, interval=5)
    _drive(tuner, lambda cfg: 0.01, steps=60)
    assert tuner._best is not None
    visited_before = len(tuner._obs)
    assert visited_before >= 1
    tuner.notify_context(world=4, epoch=1)
    # stale posteriors shelved: nothing observed in the new context
    assert tuner._best is None and not tuner._obs
    assert tuner._warmup  # next window is warmup
    # switching back restores the shelf
    tuner.notify_context(world=8, epoch=0)
    tuner.notify_context(world=4, epoch=1)
    tuner.notify_context(world=8, epoch=0)
    # original context key was "" at construction; the shelves for the two
    # explicit contexts stay separate
    assert len(tuner._archive) >= 2


def test_bo_tuner_context_invalidation():
    """Satellite: `Tuner`/`BayesianOptimizer` history was keyed only by x —
    `notify_context` must namespace observations so a rescaled fleet
    cannot exploit stale posteriors."""
    state = {"t": 0.0}
    tuner = Tuner(x=25.0, bound=(1.0, 256.0), max_num_steps=20, interval=5,
                  log=lambda s: None, clock=lambda: state["t"])
    for _ in range(40):
        state["t"] += 0.01
        p = tuner.step()
        if tuner._opt.xs:
            break
    assert tuner._opt.xs, "no observation registered — protocol drift?"
    xs_before = list(tuner._opt.xs)
    tuner.notify_context(world=4, epoch=1)
    assert tuner._opt.xs == [] and tuner._best is None
    assert tuner._warmup
    # same context again: no-op
    tuner.notify_context(world=4, epoch=1)
    assert tuner._opt.xs == []
    # returning to the original context restores its observations
    tuner.notify_context()
    # empty kwargs -> key "" == construction default context
    assert tuner._opt.context == ""
    assert tuner._opt.xs == xs_before


# ---------------------------------------------------------------------------
# live AutoTuner(strategy='plan')
# ---------------------------------------------------------------------------


def _problem():
    params = _mlp_params(jax.random.PRNGKey(0))
    batches = [_data(jax.random.PRNGKey(100 + i)) for i in range(5)]
    return params, batches


def _counting_clock():
    t = {"t": 0.0}

    def clock():
        t["t"] += 0.01
        return t["t"]

    return clock


def test_autotuner_plan_searches_and_survives(mesh):
    params, batches = _problem()
    space = PlanSpace(threshold_bound=(0.0005, 0.02),
                      modes=("dear",),
                      compressors=(None, "eftopk", "qint8"),
                      comm_dtypes=(None, "bf16"),
                      gather_dtypes=(None,), remats=(None, "full"),
                      density=0.25)
    at = AutoTuner(
        _loss_fn, params, strategy="plan", threshold_mb=0.0008,
        space=space, max_trials=6, interval=5,
        mesh=mesh, optimizer=fused_sgd(lr=0.05, momentum=0.9),
        donate=False, clock=_counting_clock(), tuner_seed=0,
        alpha_beta=(1e-6, 1e-9),
    )
    state = at.init(params)
    losses = []
    for i in range(70):
        state, m = at.step(state, batches[i % 5])
        losses.append(float(m["loss"]))
        if at.planner.finished:
            break
    assert at.planner.finished
    assert at.rebuilds >= 1          # categorical arms forced real rebuilds
    assert all(np.isfinite(x) for x in losses)
    assert int(jax.device_get(state.step)) > 0
    summary = at.planner.summary()
    assert summary["visited"] >= 2   # more than one arm actually measured


def test_autotuner_plan_rejects_baseline_modes(mesh):
    params, _ = _problem()
    with pytest.raises(ValueError, match="dear/dear-fused"):
        AutoTuner(_loss_fn, params, strategy="plan", mesh=mesh,
                  mode="allreduce", donate=False)


def test_autotuner_plan_rescale_invalidates_observations(mesh):
    """Satellite: a rescaled fleet must not exploit stale posteriors —
    the rescale is a context change for the plan tuner too."""
    params, batches = _problem()
    space = PlanSpace(threshold_bound=(0.0005, 0.02), modes=("dear",),
                      compressors=(None,), comm_dtypes=(None, "bf16"),
                      gather_dtypes=(None,), remats=(None,))
    at = AutoTuner(
        _loss_fn, params, strategy="plan", threshold_mb=0.0008,
        space=space, max_trials=10, interval=5,
        mesh=mesh, optimizer=fused_sgd(lr=0.05, momentum=0.9),
        donate=False, clock=_counting_clock(), tuner_seed=0,
    )
    state = at.init(params)
    for i in range(12):
        state, m = at.step(state, batches[i % 5])
    assert at.planner._obs, "no observation before the rescale?"

    class View:
        world = 4
        epoch = 1

    state = at.rescale(View(), state=state)
    assert at.ts.plan.world == 4 and at.ts.plan.epoch == 1
    assert not at.planner._obs          # stale posteriors shelved
    assert at.planner._best is None
    assert at._trial_backup is None     # old-world snapshot dropped
    # training continues on the rescaled mesh
    smaller = jax.tree.map(lambda x: x[: x.shape[0] // 2], batches[0])
    state, m = at.step(state, smaller)
    assert np.isfinite(float(m["loss"]))


# ---------------------------------------------------------------------------
# guard interplay: diverging trials are the tuner's incident, not the run's
# ---------------------------------------------------------------------------


def test_diverging_trial_reverts_without_guard_rollback(
        mesh, tmp_path, monkeypatch):
    """Satellite: a trial whose wire format diverges (the int8-overflow
    shape) must produce `mark_infeasible` + plan/state revert with ZERO
    ``guard.rollbacks`` booked against the user's run — the guard never
    even sees a non-finite loss."""
    from dear_pytorch_tpu.observability import tracer as T
    from dear_pytorch_tpu.ops import compression as Z
    from dear_pytorch_tpu.utils.guard import GuardedTrainer

    def _nan8():
        # qint8 with a poisoned scale: decompress -> NaN gradients (a
        # deterministic stand-in for int8 dynamic-range overflow). Keeps
        # the family name 'qint8' so the schedule dispatches it to the
        # int8 reduction; registered under its own key 'nan8'.
        base = Z.compressors["qint8"]()

        def compress(buf, state, density):
            payload, st = base.compress(buf, state, density)
            payload = dict(payload,
                           scale=payload["scale"] * jnp.float32(jnp.nan))
            return payload, st

        return Z.Compressor("qint8", base.init, compress, base.decompress)

    monkeypatch.setitem(Z.compressors, "nan8", _nan8)
    live = T.Tracer([T.MemoryExporter()])
    old_tracer = T.get_tracer()
    T.set_tracer(live)
    try:
        params, batches = _problem()
        space = PlanSpace(threshold_bound=(0.0005, 0.02), modes=("dear",),
                          compressors=("nan8",), comm_dtypes=(None,),
                          gather_dtypes=(None,), remats=(None,))
        at = AutoTuner(
            _loss_fn, params, strategy="plan", threshold_mb=0.0008,
            space=space, max_trials=3, interval=5,
            mesh=mesh, optimizer=fused_sgd(lr=0.05, momentum=0.9),
            donate=False, clock=_counting_clock(), tuner_seed=0,
        )
        guard = GuardedTrainer(at, str(tmp_path / "g"), params,
                               check_every=1, checkpoint_every=10 ** 6)
        state = at.init(params)
        reverted = False
        for i in range(40):
            state, m = guard.step(state, batches[i % 5])
            assert np.isfinite(float(m["loss"])), (i, m)
            reverted = reverted or bool(m.get("tuner_reverted"))
            if at.planner.finished:
                break
        counters = live.counters()
        assert reverted, "the diverging trial never reached the tuner"
        assert counters.get("guard.rollbacks", 0) == 0
        assert counters.get("guard.nan_detected", 0) == 0
        assert counters.get("autotune.trial_failures", 0) >= 1
        assert counters.get("tune.infeasible", 0) >= 1
        assert guard.recoveries == 0
        # the bad arm carries only dominated (penalty) observations —
        # 10x the worst feasible measurement, never a real timing
        nan_key = ("dear", "nan8", None, None, None, None)
        nan_obs = at.planner._obs.get(nan_key, [])
        assert nan_obs, "the bad arm was never penalized"
        worst_feasible = max(at.planner._feasible_ys)
        assert all(y >= 5 * worst_feasible for _, y in nan_obs)
        # ...and the live plan is back on the known-good dense config
        assert at._live_config.compressor is None
    finally:
        T.set_tracer(old_tracer)


def test_remat_lever_matches_dense_numerics(mesh):
    """remat='full' recomputes the forward in backward — numerics must be
    IDENTICAL to the default (it's a memory/time trade, not an
    approximation); fsdp owns its own policy and rejects the knob."""
    from dear_pytorch_tpu.parallel import build_train_step

    params, batches = _problem()
    opt = lambda: fused_sgd(lr=0.1, momentum=0.9)  # noqa: E731
    ts0 = build_train_step(_loss_fn, params, mesh=mesh, optimizer=opt(),
                           threshold_mb=None, donate=False)
    ts1 = build_train_step(_loss_fn, params, mesh=mesh, optimizer=opt(),
                           threshold_mb=None, donate=False, remat="full")
    s0, s1 = ts0.init(params), ts1.init(params)
    for b in batches[:3]:
        s0, m0 = ts0.step(s0, b)
        s1, m1 = ts1.step(s1, b)
        np.testing.assert_allclose(float(m0["loss"]), float(m1["loss"]),
                                   rtol=1e-6)
    with pytest.raises(ValueError, match="fsdp"):
        build_train_step(_loss_fn, params, mesh=mesh, mode="fsdp",
                         remat="full")
    with pytest.raises(ValueError, match="remat"):
        build_train_step(_loss_fn, params, mesh=mesh, remat="half")


def test_repack_carries_error_feedback_and_survives_config_switch(mesh):
    """`repack_state` preserves compressor residual mass exactly across a
    re-bucketing, and resets (rather than crashes) when the compressor
    axis itself changes between plans."""
    from dear_pytorch_tpu.parallel import build_train_step
    from dear_pytorch_tpu.tuning.autotune import repack_state

    params, batches = _problem()
    opt = fused_sgd(lr=0.1, momentum=0.9)
    kw = dict(mesh=mesh, mode="dear", optimizer=opt, donate=False)
    ts1 = build_train_step(_loss_fn, params, threshold_mb=0.0008,
                           compressor="eftopk", density=0.25, **kw)
    ts2 = build_train_step(_loss_fn, params, threshold_mb=None,
                           compressor="eftopk", density=0.25, **kw)
    assert ts1.plan.num_buckets != ts2.plan.num_buckets
    state = ts1.init(params)
    for i in range(3):
        state, _ = ts1.step(state, batches[i])

    def mass(comp, plan):
        total = 0.0
        for bi, c in enumerate(comp):
            arr = np.asarray(c)
            for r in range(arr.shape[0]):
                for x in F.unpack_bucket(jnp.asarray(arr[r]),
                                         plan, bi).values():
                    total += float(np.sum(np.asarray(x)))
        return total

    before = mass(state.comp_state, ts1.plan)
    assert abs(before) > 0  # the residual is real
    state2 = repack_state(state, ts1, ts2)
    np.testing.assert_allclose(mass(state2.comp_state, ts2.plan), before,
                               rtol=1e-5, atol=1e-6)
    state2, m = ts2.step(state2, batches[3])
    assert np.isfinite(float(m["loss"]))

    # compressor changed but both carry ADDITIVE residuals in gradient
    # units (eftopk -> qint8): the unsent mass carries across the switch
    ts3 = build_train_step(_loss_fn, params, threshold_mb=None,
                           compressor="qint8", **kw)
    state3 = repack_state(state, ts1, ts3)
    np.testing.assert_allclose(mass(state3.comp_state, ts3.plan), before,
                               rtol=1e-5, atol=1e-6)
    state3, m = ts3.step(state3, batches[4])
    assert np.isfinite(float(m["loss"]))

    # a STRUCTURAL mismatch resets: switching to a stateless compressor
    # has no residual to carry into ('topk' keeps no buffer)
    ts4 = build_train_step(_loss_fn, params, threshold_mb=None,
                           compressor="topk", density=0.25, **kw)
    state4 = repack_state(state, ts1, ts4)
    assert state4.comp_state == () or all(
        not jax.tree.leaves(c) for c in state4.comp_state)


# ---------------------------------------------------------------------------
# the serving retarget: ServeSpace x p99 objective through the same
# PlanTuner machinery (docs/TUNING.md "ServeSpace")
# ---------------------------------------------------------------------------


def test_serve_space_axes_and_feasibility():
    from dear_pytorch_tpu.tuning.planspace import ServeConfig, ServeSpace

    space = ServeSpace(world=1, ring_len=16)
    axes = {a.name: a for a in space.axes()}
    assert axes["prefill_chunk"].kind == "continuous"
    assert set(axes["slots"].choices) == {2, 4, 8}
    # world=1: every tp arm is rejected at space construction
    assert all(not c.tp_decode for c in space.configs())
    assert space.feasible(ServeConfig(tp_decode=True)) is not None
    # a chunk past the ring length cannot build
    assert space.feasible(ServeConfig(prefill_chunk=17.0)) is not None
    assert space.feasible(ServeConfig(prefill_chunk=8.0)) is None
    # world>1 admits tp arms
    assert any(c.tp_decode
               for c in ServeSpace(world=8, ring_len=16).configs())
    # the continuous chunk rounds to the engine's integer knob
    kw = ServeConfig(prefill_chunk=3.6, slots=4).engine_kwargs()
    assert kw == {"slots": 4, "prefill_chunk": 4}
    mk = ServeConfig(kv_dtype="bf16", decode_use_flash=True).model_kwargs()
    assert mk["kv_cache_dtype"] is jnp.bfloat16
    assert mk["decode_use_flash"] is True


def test_serve_cost_model_ticks_and_floor():
    from dear_pytorch_tpu.tuning.planspace import (
        ServeConfig, ServeCostModel,
    )

    cm = ServeCostModel(prompt_tokens=12, decode_tokens=4, world=8,
                        alpha=1e-5, beta=1e-9, weight_bytes=4096,
                        n_projections=8)
    c1 = ServeConfig(prefill_chunk=1.0)
    c4 = ServeConfig(prefill_chunk=4.0)
    assert cm.ticks(c1) == 16 and cm.ticks(c4) == 7
    # tp arms carry ring transport; dense arms order by tick count
    assert cm.comm(ServeConfig(prefill_chunk=4.0, tp_decode=True)) \
        > cm.comm(c4)
    assert cm.comm(c1) > cm.comm(c4)
    assert cm.floor(c1) is None          # never prune blind
    cm.observe(c4, 0.7)                  # 0.1 s/tick calibration
    floor1 = cm.floor(c1)
    assert floor1 == pytest.approx(1.6, rel=1e-6)
    # the floor is an UNDERESTIMATE built from the minimum residual rate
    cm.observe(c1, 3.2)                  # a slower rate never lowers it
    assert cm.floor(c1) == pytest.approx(floor1, rel=1e-6)


def test_serve_tuner_adopts_best_and_prunes():
    """The episode-driven protocol: sweep arms cheapest-first, observe
    synthetic p99s, prune hopeless chunk-1-like arms once calibrated,
    adopt the best config at budget exhaustion."""
    import math

    from dear_pytorch_tpu.tuning.planspace import (
        ServeCostModel, ServeSpace, ServeTuner,
    )

    space = ServeSpace(world=8, slots=(2, 4), kv_dtypes=(None, "bf16"),
                       flash=(False,), tp=(False, True), ring_len=16)
    cm = ServeCostModel(prompt_tokens=12, decode_tokens=5, world=8,
                        alpha=1e-4, beta=1e-8, weight_bytes=4096,
                        n_projections=8)
    tuner = ServeTuner(space, max_trials=6, cost_model=cm,
                       log=lambda s: None, seed=0)

    def p99(cfg):
        ticks = math.ceil(12 / cfg.chunk) + 5
        per_tick = 0.010 * (0.9 if cfg.kv_dtype == "bf16" else 1.0) \
            + (0.008 if cfg.tp_decode else 0.0)
        return ticks * per_tick

    while not tuner.finished:
        tuner.observe(p99(tuner.current))
    best = tuner.current
    assert best.kv_dtype == "bf16" and not best.tp_decode
    assert best.chunk >= 4
    s = tuner.summary()
    assert s["finished"] and s["best_s"] == pytest.approx(p99(best))


def test_serve_tuner_sandboxes_failed_episode_and_moves_on():
    """Episode-mode sandboxing MUST move `current`: a step-driven caller
    reverts to its last good plan, but an episode driver retrying
    `current` would spin forever on a deterministically-failing config
    (and a diverging arm would burn the whole budget in place)."""
    from dear_pytorch_tpu.tuning.planspace import ServeSpace, ServeTuner

    space = ServeSpace(world=1, slots=(2,), kv_dtypes=(None, "bf16"),
                       flash=(False,), tp=(False,), ring_len=16)
    tuner = ServeTuner(space, max_trials=4, log=lambda s: None, seed=1)
    first = tuner.current
    # a crashed episode (non-finite p99) consumes the trial AND switches
    # to a different arm — never re-trial the diverged config in place
    tuner.observe(float("nan"))
    assert not tuner.finished
    assert tuner.current.key() != first.key()
    # a build failure retires the whole arm without charging a trial and
    # likewise moves off it
    broken = tuner.current
    tuner.mark_infeasible(broken, fatal=True, why="no such dtype")
    assert tuner.summary()["dead"]
    assert tuner.current.key() != broken.key()
    while not tuner.finished:
        tuner.observe(0.5)
    assert tuner.current is not None


def test_serve_tuner_finishes_when_every_arm_dies():
    """A space whose every arm fails fatally must FINISH, not strand the
    episode driver loop retrying dead configs."""
    from dear_pytorch_tpu.tuning.planspace import ServeSpace, ServeTuner

    space = ServeSpace(world=1, slots=(2,), kv_dtypes=(None, "bf16"),
                       flash=(False,), tp=(False,), ring_len=16)
    tuner = ServeTuner(space, max_trials=8, log=lambda s: None, seed=2)
    for _ in range(4):       # 2 arms; every trial "fails to build"
        if tuner.finished:
            break
        tuner.mark_infeasible(tuner.current, fatal=True, why="boom")
    assert tuner.finished
    assert len(tuner.summary()["dead"]) == 2
    assert tuner.best_config is None   # nothing measured — caller's cue
