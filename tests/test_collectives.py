"""Collective correctness — asserting port of the reference's manual harness.

The reference validates collectives by eyeballing printed norms under mpirun
(common/comm_core/tests/test_comm.py, launched by test.sh:29). Each of those
checks appears here as a real assertion on an 8-device emulated mesh:

  allreduce          test_comm.py:11-20
  reducescatter      test_comm.py:22-37  (RS+AG round trip vs allReduce)
  decoupleallreduce  test_comm.py:39-53  (THE key invariant: decomposed == fused)
  bcast              test_comm.py:55-64
  reduce             test_comm.py:85-120
  sendrecv           test_comm.py:122-146
"""

import numpy as np
import pytest

from dear_pytorch_tpu.comm import collectives as C
from dear_pytorch_tpu.comm.communicator import Communicator


def _stacked(rng, world, n=1024, dtype=np.float32):
    """One distinct tensor per rank, like each mpirun rank's torch.rand."""
    return rng.standard_normal((world, n)).astype(dtype)


def test_allreduce(mesh, world, rng):
    x = _stacked(rng, world)
    out = C.spmd_call(C.all_reduce, x, mesh=mesh)
    expected = np.broadcast_to(x.sum(axis=0), x.shape)
    np.testing.assert_allclose(np.asarray(out), expected, rtol=1e-5)


def test_reduce_scatter_then_all_gather_roundtrip(mesh, world, rng):
    # test_comm.py:22-37 — RS followed by AG must equal allReduce.
    n = 64 * world
    x = _stacked(rng, world, n)
    shards = C.spmd_call(C.reduce_scatter, x, mesh=mesh)
    assert shards.shape == (world, n // world)
    # each rank's shard is the sum over ranks of its slice
    full_sum = x.sum(axis=0)
    for r in range(world):
        np.testing.assert_allclose(
            np.asarray(shards)[r], full_sum[r * (n // world) : (r + 1) * (n // world)],
            rtol=1e-5,
        )
    gathered = C.spmd_call(C.all_gather, np.asarray(shards), mesh=mesh)
    np.testing.assert_allclose(
        np.asarray(gathered), np.broadcast_to(full_sum, (world, n)), rtol=1e-5
    )


@pytest.mark.parametrize("decomposed", ["rsag", "rb"])
def test_decoupled_allreduce_equals_fused(mesh, world, rng, decomposed):
    """test_comm.py:39-53 — the DeAR core invariant, with a non-divisible
    length to exercise the internal padding path (communicator.cpp:204-213)."""
    n = 1000 + 7  # not a multiple of world
    x = _stacked(rng, world, n)
    fused = C.spmd_call(C.all_reduce, x, mesh=mesh)
    if decomposed == "rsag":
        dec = C.spmd_call(C.all_reduce_rsag, x, mesh=mesh)
    else:
        dec = C.spmd_call(lambda t: C.all_reduce_rb(t, 0), x, mesh=mesh)
    np.testing.assert_allclose(np.asarray(dec), np.asarray(fused), rtol=1e-5)


def test_broadcast(mesh, world, rng):
    x = _stacked(rng, world, 256)
    for root in (0, world - 1):
        out = C.spmd_call(lambda t, r=root: C.broadcast(t, r), x, mesh=mesh)
        np.testing.assert_allclose(
            np.asarray(out), np.broadcast_to(x[root], x.shape), rtol=1e-6
        )


def test_reduce_root_semantics(mesh, world, rng):
    x = _stacked(rng, world, 128)
    root = 1 % world
    out = np.asarray(C.spmd_call(lambda t: C.reduce(t, root), x, mesh=mesh))
    np.testing.assert_allclose(out[root], x.sum(axis=0), rtol=1e-5)
    for r in range(world):
        if r != root:  # non-root buffers untouched (ncclReduce in-place)
            np.testing.assert_allclose(out[r], x[r], rtol=1e-6)


def test_send_recv_ring(mesh, world, rng):
    # test_comm.py:122-146 — pairwise exchange; here a rotation ring.
    x = _stacked(rng, world, 64)
    peers = [(i + 1) % world for i in range(world)]
    out = np.asarray(C.spmd_call(lambda t: C.send_recv(t, peers), x, mesh=mesh))
    for i in range(world):
        np.testing.assert_allclose(out[(i + 1) % world], x[i], rtol=1e-6)


def test_multi_bcast_matches_local_compute(mesh, world, rng):
    xs = [rng.standard_normal((world, 600_000)).astype(np.float32),
          rng.standard_normal((world, 32)).astype(np.float32)]
    fn = lambda t: t * 2.0 + 1.0

    def run(a, b):
        return tuple(C.multi_bcast([a, b], fn, min_elems=512 * 512))

    out = C.spmd_call(run, xs[0], xs[1], mesh=mesh)
    # big tensor: owner rank 0 computes fn on ITS slice, result broadcast
    np.testing.assert_allclose(
        np.asarray(out[0]), np.broadcast_to(fn(xs[0][0]), xs[0].shape), rtol=1e-5
    )
    # small tensor: computed locally per rank
    np.testing.assert_allclose(np.asarray(out[1]), fn(xs[1]), rtol=1e-6)


def test_pad_to_multiple():
    import jax.numpy as jnp

    assert C.padded_length(10, 8) == 16
    assert C.padded_length(16, 8) == 16
    assert C.padded_length(0, 8) == 0
    x = jnp.arange(10, dtype=jnp.float32)
    p = C.pad_to_multiple(x, 8)
    assert p.shape == (16,)
    np.testing.assert_allclose(np.asarray(p[:10]), np.arange(10))
    np.testing.assert_allclose(np.asarray(p[10:]), 0)


class TestCommunicator:
    def test_allreduce_and_sync(self, mesh, world, rng):
        comm = Communicator(nstreams=2, mesh=mesh)
        x = _stacked(rng, world, 512)
        out, handle = comm.allReduce(x)
        assert 0 <= handle < 2
        comm.synchronize()
        np.testing.assert_allclose(
            np.asarray(out), np.broadcast_to(x.sum(0), x.shape), rtol=1e-5
        )

    def test_round_robin_handles(self, mesh, world, rng):
        comm = Communicator(nstreams=3, mesh=mesh)
        handles = [comm.allReduce(_stacked(rng, world, 64))[1] for _ in range(5)]
        assert handles == [0, 1, 2, 0, 1]
        comm.syncStream(0)
        comm.synchronize()
        assert comm.getNumOfFreeStreams() == 3

    def test_repeated_calls_hit_jit_cache(self, mesh, world, rng):
        # Regression: per-call lambdas used to defeat spmd_call's fn-identity
        # cache, recompiling on every collective.
        comm = Communicator(mesh=mesh)
        x = _stacked(rng, world, 32)
        comm.allReduce(x)
        before = len(C._spmd_cache)
        for _ in range(5):
            comm.allReduce(x)
            comm.reduce(x, root=0)
        comm.synchronize()
        assert len(C._spmd_cache) == before + 1  # only the new reduce op

    def test_synchronize_fences_reused_handles(self, mesh, world, rng):
        # Regression: with nstreams=1, a second issue on handle 0 must not
        # evict the first from the synchronize() fence.
        comm = Communicator(nstreams=1, mesh=mesh)
        a, h0 = comm.allReduce(_stacked(rng, world, 16))
        b, h1 = comm.allReduce(_stacked(rng, world, 16))
        assert h0 == h1 == 0
        assert len(comm._pending[0]) == 2
        comm.synchronize()
        assert not comm._pending

    def test_destroy_reload(self, mesh, world, rng):
        comm = Communicator(mesh=mesh)
        comm.destroy()
        with pytest.raises(RuntimeError):
            comm.allReduce(_stacked(rng, world, 8))
        comm.reload()
        out, _ = comm.allReduce(_stacked(rng, world, 8))
        comm.synchronize()

    def test_reduce_scatter_all_gather(self, mesh, world, rng):
        comm = Communicator(mesh=mesh)
        n = 16 * world
        x = _stacked(rng, world, n)
        shards, _ = comm.reduceScatter(x)
        gathered, _ = comm.allGather(np.asarray(shards))
        comm.synchronize()
        np.testing.assert_allclose(
            np.asarray(gathered), np.broadcast_to(x.sum(0), (world, n)), rtol=1e-5
        )


def test_backend_introspection(mesh, world):
    import dear_pytorch_tpu as dear

    assert dear.size() == 1  # single process under pytest
    assert dear.rank() == 0
    assert dear.device_count() == world == 8
    dear.barrier()  # no-op single-process, must not raise
    assert dear.global_mesh() is mesh
