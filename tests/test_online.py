"""Online continual-learning subsystem: the durable feedback log
(manifest-LAST segments, torn-segment walk-past, seq dedup), the
exactly-once streaming ingest (cursor in the sidecar state, blend vs
feed, consensus frontier), the object-store ordering/first-writer-wins
contracts they ride on, the new data-path fault grammar — and the
`scripts/chaos_check.py --online` storm as the end-to-end gate
(docs/ONLINE.md)."""

import json
import os
import threading

import numpy as np
import pytest

from dear_pytorch_tpu.online.feedback import (
    Cursor, FeedbackReader, FeedbackWriter, compact_segments,
    poison_records, record_digest, shard_of,
)
from dear_pytorch_tpu.online.ingest import FeedbackIngest
from dear_pytorch_tpu.online.quality import QualityGate
from dear_pytorch_tpu.resilience.inject import (
    Fault, FaultInjector, parse_faults,
)
from dear_pytorch_tpu.runtime import build as RB
from dear_pytorch_tpu.runtime import pipeline as P
from dear_pytorch_tpu.utils.objectstore import LocalObjectStore


# ---------------------------------------------------------------------------
# object store: the pinned ordering + first-writer-wins contracts
# ---------------------------------------------------------------------------


def test_list_ordering_under_concurrent_appenders(tmp_path):
    """list(prefix) is lexicographic-by-key no matter how many appenders
    raced — the ordering contract segment-walking readers rely on."""
    store = LocalObjectStore(str(tmp_path))
    gate = threading.Barrier(4)

    def appender(w):
        gate.wait()
        for i in range(25):
            store.put_bytes(f"logs/w{w}/seg_{i:08d}", b"x")

    threads = [threading.Thread(target=appender, args=(w,))
               for w in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    keys = store.list("logs")
    assert len(keys) == 100
    assert keys == sorted(keys)
    # and per-writer the segment files come back in segment order
    w0 = [k for k in keys if k.startswith("logs/w0/")]
    assert w0 == [f"logs/w0/seg_{i:08d}" for i in range(25)]


def test_put_bytes_if_absent_first_writer_wins(tmp_path):
    store = LocalObjectStore(str(tmp_path))
    gate = threading.Barrier(8)
    wins = []

    def racer(i):
        gate.wait()
        if store.put_bytes_if_absent("decided/e7", f"writer{i}".encode()):
            wins.append(i)

    threads = [threading.Thread(target=racer, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(wins) == 1
    assert store.get_bytes("decided/e7") == f"writer{wins[0]}".encode()
    # a later duplicate publication is idempotent: loses, content intact
    assert store.put_bytes_if_absent("decided/e7", b"late") is False
    assert store.get_bytes("decided/e7") == f"writer{wins[0]}".encode()


# ---------------------------------------------------------------------------
# feedback log: commit protocol, damage tolerance, dedup, cursor replay
# ---------------------------------------------------------------------------


def _writer(store, wid="r0", **kw):
    kw.setdefault("stream", "s")
    kw.setdefault("flush_records", 4)
    kw.setdefault("start", False)
    return FeedbackWriter(store, writer_id=wid, **kw)


def test_roundtrip_and_manifest_last_commit(tmp_path):
    store = LocalObjectStore(str(tmp_path))
    w = _writer(store)
    for i in range(6):
        assert w.append({"prompt": [i], "response": [i + 1]})
    w.flush()  # 6 records -> one segment
    r = FeedbackReader(store, stream="s")
    fr = r.frontier()
    assert fr == {"r0": 0}
    assert r.committed_records(fr) == 6
    cur = Cursor()
    recs = r.take(cur, fr, 100)
    assert [x["uid"] for x in recs] == [f"r0:{i}" for i in range(6)]
    assert recs[0]["prompt"] == [0] and recs[0]["writer"] == "r0"
    assert cur.consumed_total == 6 and r.drained(cur, fr)
    # manifest-LAST: a payload without its manifest is invisible to the
    # frontier (an in-flight flush can never be read half-committed)
    store.put_bytes("feedback/s/r0/seg_00000001.jsonl", b'{"seq": 6}\n')
    assert r.frontier() == {"r0": 0}


def test_torn_segment_walked_past_never_crashes(tmp_path):
    store = LocalObjectStore(str(tmp_path))
    inj = FaultInjector([Fault(kind="torn_seg", step=2)], own_rank=0)
    w = _writer(store, injector=inj)
    for i in range(12):
        w.append({"i": i})
        if (i + 1) % 4 == 0:
            w.flush()
    # flush 2 (records 4..7) published its payload but no manifest
    r = FeedbackReader(store, stream="s")
    cur = Cursor()
    recs = r.take(cur, r.frontier(), 100)
    assert [x["seq"] for x in recs] == [0, 1, 2, 3, 8, 9, 10, 11]
    assert cur.torn_segments == 1
    assert cur.consumed_total == 8


def test_corrupt_payload_walked_past_and_lag_drains(tmp_path):
    store = LocalObjectStore(str(tmp_path))
    w = _writer(store)
    for i in range(8):
        w.append({"i": i})
        if (i + 1) % 4 == 0:
            w.flush()
    raw = bytearray(store.get_bytes("feedback/s/r0/seg_00000000.jsonl"))
    raw[0] ^= 0xFF
    store.put_bytes("feedback/s/r0/seg_00000000.jsonl", bytes(raw))
    r = FeedbackReader(store, stream="s")
    cur = Cursor()
    recs = r.take(cur, r.frontier(), 100)
    assert [x["seq"] for x in recs] == [4, 5, 6, 7]
    assert cur.torn_segments == 1
    # the corrupt segment's manifest count is written off, so the lag
    # ledger drains to zero — a permanent nonzero ingest_lag would be a
    # standing false alert on a fully-caught-up consumer
    assert cur.dropped_committed == 4
    assert (r.committed_records() - cur.consumed_total - cur.dedup_hits
            - cur.dropped_committed) == 0


def test_duplicate_record_deduplicated(tmp_path):
    store = LocalObjectStore(str(tmp_path))
    inj = FaultInjector([Fault(kind="dup_feedback", step=6)], own_rank=0)
    w = _writer(store, injector=inj)
    for i in range(4):
        w.append({"i": i})
    w.flush()
    for i in range(4, 8):
        w.append({"i": i})  # append 6 re-appends the last COMMITTED rec
    w.flush()
    r = FeedbackReader(store, stream="s")
    cur = Cursor()
    recs = r.take(cur, r.frontier(), 100)
    assert [x["seq"] for x in recs] == list(range(8))
    assert cur.dedup_hits == 1
    assert cur.consumed_total == 8
    # the committed count INCLUDES the duplicate line; the unique count
    # is the exactly-once quantity
    assert r.committed_records() == 9


def test_writer_restart_resumes_committed_tail(tmp_path):
    store = LocalObjectStore(str(tmp_path))
    w1 = _writer(store)
    for i in range(5):
        w1.append({"i": i})
    w1.flush()
    w1.append({"i": 5})  # dies with this record buffered (never flushed)
    w2 = _writer(store)  # the relaunched incarnation
    assert w2._next_seg == 1 and w2._next_seq == 5
    w2.append({"i": "fresh"})
    w2.flush()
    r = FeedbackReader(store, stream="s")
    cur = Cursor()
    recs = r.take(cur, r.frontier(), 100)
    # seq 5 is REUSED by the new life (the buffered record was lost
    # before commit), so the stream stays gap-free
    assert [x["seq"] for x in recs] == list(range(6))
    assert cur.dedup_hits == 0 and cur.torn_segments == 0


def test_restart_after_dup_at_segment_tail_does_not_reuse_seqs(tmp_path):
    """The duplicate re-append lands AFTER the newest record, so a
    positional last_seq would understate the manifest and a relaunched
    writer would re-stamp already-committed seq numbers — which every
    reader then silently dedup-drops (committed-but-never-consumed data
    loss the ledger cannot even see). last_seq must be the MAX."""
    store = LocalObjectStore(str(tmp_path))
    inj = FaultInjector([Fault(kind="dup_feedback", step=5)], own_rank=0)
    w1 = _writer(store, injector=inj)
    for i in range(4):
        w1.append({"i": i})
    w1.flush()                      # seqs 0..3 committed
    w1.append({"i": 4})             # append 5: seq 4 + dup of seq 3
    w1.flush()                      # segment tail is the dup (seq 3)
    w2 = _writer(store)             # relaunched incarnation
    assert w2._next_seq == 5        # NOT 4: seq 4 is already committed
    w2.append({"i": "fresh"})
    w2.flush()
    r = FeedbackReader(store, stream="s")
    cur = Cursor()
    recs = r.take(cur, r.frontier(), 100)
    # every unique committed record consumed, the dup alone dropped
    assert [x["seq"] for x in recs] == [0, 1, 2, 3, 4, 5]
    assert cur.dedup_hits == 1


def test_flush_exhaustion_counts_never_raises(tmp_path):
    class DeadStore(LocalObjectStore):
        def __init__(self, root):
            super().__init__(root)
            self.dead = False

        def put_bytes(self, key, data):
            if self.dead:
                raise OSError("store down")
            super().put_bytes(key, data)

    store = DeadStore(str(tmp_path))
    w = _writer(store, retry_attempts=2)
    for i in range(4):
        w.append({"i": i})
    store.dead = True
    assert w.flush() == 0           # exhausted: dropped, not raised
    assert w.flush_errors == 1 and w.dropped_flush == 4
    store.dead = False
    for i in range(4, 8):
        w.append({"i": i})
    assert w.flush() == 4           # the writer survived its dead store
    assert w.committed == 4


def test_append_never_blocks_on_full_buffer(tmp_path):
    store = LocalObjectStore(str(tmp_path))
    w = _writer(store, max_buffer=3)
    assert all(w.append({"i": i}) for i in range(3))
    assert w.append({"i": 99}) is False    # dropped, counted, no raise
    assert w.append_drops == 1
    w.flush()
    assert w.committed == 3


def test_parse_data_path_faults():
    faults = parse_faults("torn_seg@2:r1,dup_feedback@6")
    assert faults[0].kind == "torn_seg" and faults[0].step == 2
    assert faults[0].rank == 1
    assert faults[1].kind == "dup_feedback" and faults[1].rank is None
    # rank targeting: the fault is consumed (skipped) on other ranks so
    # schedules drain identically everywhere
    inj = FaultInjector([faults[0]], own_rank=0)
    assert inj.torn_segment(2) is False
    assert [f.kind for f in inj.skipped] == ["torn_seg"]
    assert inj.pending == 0


# ---------------------------------------------------------------------------
# ingest: blend vs feed, exactly-once cursor replay, consensus frontier
# ---------------------------------------------------------------------------


def _ingest(store, *, batch_records=4, consensus_fn=None, rows=4,
            exchange_fn=None, quality=None):
    spec = P.SyntheticSpec((
        P.Field("x", (rows, 6), RB.KIND_NORMAL_F32, 0.0, 1.0),
    ))
    base = P.NumpyPipeline(spec, seed=7)

    def batch_fn(base_batch, records):
        x = np.array(base_batch["x"])
        for j, rec in enumerate(records[:rows]):
            rng = np.random.default_rng(
                record_digest(rec["writer"], rec["seq"]) % (1 << 32))
            x[j] = rng.normal(size=x.shape[1]).astype(np.float32)
        return {"x": x, "nrec": len(records)}

    return FeedbackIngest(base, FeedbackReader(store, stream="s"),
                          batch_records=batch_records, batch_fn=batch_fn,
                          consensus_fn=consensus_fn,
                          exchange_fn=exchange_fn, quality=quality)


def test_ingest_blends_when_starved_feeds_when_available(tmp_path):
    store = LocalObjectStore(str(tmp_path))
    ing = _ingest(store)
    b = ing.next()
    assert b["nrec"] == 0 and ing.last_drained    # empty log: pure blend
    w = _writer(store)
    for i in range(6):
        w.append({"i": i})
    w.flush()
    b = ing.next()
    assert b["nrec"] == 4 and not ing.last_drained
    assert ing.lag() == 2
    b = ing.next()
    assert b["nrec"] == 2 and ing.last_drained and ing.lag() == 0
    b = ing.next()
    assert b["nrec"] == 0                          # drained: blend again


def test_ingest_cursor_replay_is_exactly_once(tmp_path):
    """Restoring the state dict (what a guard rollback does) replays the
    stream byte-identically: same records, same batches, same checksum —
    the sidecar transactionality that makes ingest exactly-once."""
    store = LocalObjectStore(str(tmp_path))
    w = _writer(store)
    for i in range(14):
        w.append({"i": i})
        if (i + 1) % 4 == 0:
            w.flush()
    w.flush()
    ing = _ingest(store)
    ing.next()
    snap = ing.state_dict()
    snap_json = json.dumps(snap)          # must be sidecar-JSON-safe
    after = [ing.next() for _ in range(3)]
    end_state = ing.state_dict()
    ing.load_state_dict(json.loads(snap_json))
    replay = [ing.next() for _ in range(3)]
    for a, b in zip(after, replay):
        assert np.allclose(a["x"], b["x"]) and a["nrec"] == b["nrec"]
    assert ing.state_dict() == end_state
    assert ing.cursor.consumed_total == 14


def test_ingest_consensus_frontier_caps_the_read(tmp_path):
    """The fleet-MIN frontier pins every rank to the same availability
    snapshot: records committed past the agreed frontier are invisible
    until the next exchange, so replicas can never diverge on feed vs
    blend."""
    store = LocalObjectStore(str(tmp_path))
    w = _writer(store)
    for i in range(8):
        w.append({"i": i})
        if (i + 1) % 4 == 0:
            w.flush()

    calls = []

    def consensus(frontier):
        calls.append(dict(frontier))
        return {"r0": 0}  # a lagging peer has only seen segment 0

    ing = _ingest(store, consensus_fn=consensus)
    b = ing.next()
    assert b["nrec"] == 4 and ing.cursor.consumed_total == 4
    b = ing.next()
    assert b["nrec"] == 0                  # frontier-capped: blend
    assert calls and calls[-1] == {"r0": 1}  # local view did see seg 1


def test_ingest_checksum_is_interleave_independent(tmp_path):
    """Two consumers with different batch sizes (different interleaves
    across writers) converge to the same consumed_total AND checksum —
    what lets a jax-free auditor replay the log and verify the trainer's
    ledger without reproducing its step cadence."""
    store = LocalObjectStore(str(tmp_path))
    for wid in ("r0", "r1"):
        w = _writer(store, wid=wid)
        for i in range(10):
            w.append({"i": i})
            if (i + 1) % 5 == 0:
                w.flush()
    a, b = _ingest(store, batch_records=3), _ingest(store, batch_records=7)
    for ing in (a, b):
        while not (ing.next() is not None and ing.last_drained
                   and ing.last_records == 0):
            pass
    assert a.cursor.consumed_total == b.cursor.consumed_total == 20
    assert a.cursor.checksum == b.cursor.checksum


def test_ingest_bare_sidecar_restore_resets_cursor(tmp_path):
    """Rolling back to a sidecar written by a bare pipeline (a run that
    predates the online wrapper) must RESET the cursor: keeping the
    in-memory position would leave records trained only into the
    discarded state and never re-consumed — re-training from zero is
    the transactional answer."""
    store = LocalObjectStore(str(tmp_path))
    w = _writer(store)
    for i in range(6):
        w.append({"i": i})
    w.flush()
    ing = _ingest(store)
    bare_state = ing.base.state_dict()     # a pre-online sidecar
    ing.next()
    assert ing.cursor.consumed_total == 4
    ing.load_state_dict(bare_state)
    assert ing.cursor.consumed_total == 0  # reset, not stale
    ing.next()
    ing.next()
    assert ing.cursor.consumed_total == 6  # everything re-consumed


def test_frontier_probe_advances_without_listing(tmp_path):
    """Between discovery listings the frontier advances by exists()
    probes (O(writers) per step, not O(log age)); a numbering gap (a
    wholly-dropped segment) is jumped at the next discovery listing."""
    store = LocalObjectStore(str(tmp_path))
    w = _writer(store)
    for i in range(4):
        w.append({"i": i})
    w.flush()
    r = FeedbackReader(store, stream="s", discover_every=4)
    assert r.frontier() == {"r0": 0}       # call 1: discovery listing
    for i in range(4, 8):
        w.append({"i": i})
    w.flush()
    assert r.frontier() == {"r0": 1}       # call 2: probe fast path
    # a wholly-dropped segment (no objects at all): the writer moved on
    w._next_seg += 1
    for i in range(8, 12):
        w.append({"i": i})
    w.flush()                              # commits seg 3, seg 2 empty
    assert r.frontier() == {"r0": 1}       # call 3: probe stalls at gap
    assert r.frontier(full=True) == {"r0": 3}  # definitive view on demand
    r2 = FeedbackReader(store, stream="s", discover_every=4)
    r2.frontier()                          # fresh reader: discovery
    assert r2.frontier() == {"r0": 3}      # probes continue from there
    cur = Cursor()
    recs = r.take(cur, r.frontier(), 100)
    assert [x["seq"] for x in recs] == list(range(12))


def test_ingest_reshard_keeps_replica_identical_blend(tmp_path):
    """A membership transition reshards the base stream by EPOCH only:
    every member of the new world draws the identical blend stream (the
    ingest is replica-global), while the epoch fold still makes the
    post-transition stream distinct from the pre-transition one."""
    store = LocalObjectStore(str(tmp_path))
    a, b = _ingest(store), _ingest(store)
    a.reshard(0, 3, epoch=2)   # rank 0's view of a 3-world
    b.reshard(2, 3, epoch=2)   # rank 2's view of the same transition
    ba, bb = a.next(), b.next()
    assert np.allclose(ba["x"], bb["x"])
    assert a.state_dict()["epoch"] == 2
    fresh = _ingest(store)     # epoch 0: a different stream
    assert not np.allclose(fresh.next()["x"], ba["x"])


def test_sole_survivor_guard_stays_coordinated(tmp_path):
    """The --online storm's root-caused bug: a 2-rank fleet shrinks to
    ONE survivor — the guard must keep running the coordinated health
    sync (it is where rejoin requests are polled), or the relaunched
    rank is never admitted and the fleet can never grow back."""
    from dear_pytorch_tpu.resilience import membership as M
    from dear_pytorch_tpu.resilience.cluster import FileTransport
    from dear_pytorch_tpu.utils import guard as G

    cluster = M.ElasticCluster(
        transport=FileTransport(str(tmp_path / "store")), rank=0, world=1)
    shim = object.__new__(G.GuardedTrainer)  # property check only
    shim._coordinator = cluster
    assert cluster.process_count == 1
    assert shim._coordinated is True

    class PlainWorld1:
        process_count = 1

    shim._coordinator = PlainWorld1()
    assert shim._coordinated is False  # non-elastic world-1: unchanged


# ---------------------------------------------------------------------------
# partitioned ingest: scatter-read + all-gather (ISSUE-17 tentpole a)
# ---------------------------------------------------------------------------


def _wids_by_shard(per_shard, world=2):
    """Writer ids grouped by `shard_of` ownership — picked by probing so
    the tests never hardcode the hash layout."""
    out = {s: [] for s in range(world)}
    i = 0
    while any(len(v) < per_shard for v in out.values()):
        wid = f"w{i}"
        s = shard_of(wid, world)
        if len(out[s]) < per_shard:
            out[s].append(wid)
        i += 1
    return out


def _pair_exchange():
    """Barrier-coupled exchange_fn factory emulating
    `ElasticCluster.exchange` for a 2-rank fleet: both ranks deposit
    their per-step payload, meet at the barrier, and read the
    member-ordered document list. The second barrier keeps a fast rank
    from depositing round N+1 before the slow rank read round N."""
    slots = {}
    bar = threading.Barrier(2)

    def make(rank):
        def exchange(payload):
            slots[rank] = payload
            bar.wait(timeout=30)
            docs = [slots[r] for r in sorted(slots)]
            bar.wait(timeout=30)
            return docs
        return exchange
    return make


def _run_lockstep(ingests, steps):
    """Drive each rank's ingest `steps` times on its own thread (the
    exchange barrier needs both in flight). Returns batches per rank."""
    outs = {r: [] for r in range(len(ingests))}

    def run(r, ing):
        for _ in range(steps):
            outs[r].append(ing.next())

    threads = [threading.Thread(target=run, args=(r, ing))
               for r, ing in enumerate(ingests)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert not any(t.is_alive() for t in threads)
    return outs


def _full_replay(store):
    """A jax-free auditor's ledger: fresh reader, full-discovery
    frontier, everything consumed into one cursor."""
    audit = Cursor()
    rd = FeedbackReader(store, stream="s")
    fr = rd.frontier(full=True)
    while rd.take(audit, fr, 100):
        pass
    return audit


def test_partitioned_ingest_lockstep_tiles_the_union(tmp_path):
    """Two ranks scatter-read disjoint writer shards and all-gather the
    documents: every rank materialises the IDENTICAL batch (the desync
    sentinel stays meaningful) and the identical union cursor, while
    `shard_cursors()` slices tile that union exactly — disjoint writer
    sets, consumed counts and checksums summing to the whole."""
    store = LocalObjectStore(str(tmp_path))
    by_shard = _wids_by_shard(1)
    wids = by_shard[0] + by_shard[1]
    for wid in wids:
        w = _writer(store, wid=wid)
        for i in range(8):
            w.append({"i": i, "w": wid})
            if (i + 1) % 4 == 0:
                w.flush()
    make = _pair_exchange()
    a = _ingest(store, batch_records=4, exchange_fn=make(0))
    b = _ingest(store, batch_records=4, exchange_fn=make(1))
    a.reshard(0, 2, epoch=1)
    b.reshard(1, 2, epoch=1)
    outs = _run_lockstep([a, b], steps=6)
    for ba, bb in zip(outs[0], outs[1]):
        assert np.allclose(ba["x"], bb["x"]) and ba["nrec"] == bb["nrec"]
    assert a.cursor.consumed_total == b.cursor.consumed_total == 16
    assert a.cursor.checksum == b.cursor.checksum
    audit = _full_replay(store)
    assert audit.consumed_total == 16
    for ing in (a, b):
        sc = ing.shard_cursors()
        assert sorted(sc) == ["0", "1"]
        assert sorted(sc["0"]["writers"] + sc["1"]["writers"]) \
            == sorted(wids)
        assert not set(sc["0"]["writers"]) & set(sc["1"]["writers"])
        assert sc["0"]["consumed"] + sc["1"]["consumed"] == 16
        assert (int(sc["0"]["checksum"]) + int(sc["1"]["checksum"])) \
            % (1 << 64) == audit.checksum


def test_partitioned_reshard_mid_ingest_is_exactly_once(tmp_path):
    """ISSUE-17 acceptance: a world change MID-INGEST redistributes
    writer ownership with NO state transfer — the cursor is already the
    union on every rank — and no record is consumed twice or skipped,
    pinned by the order-independent checksum of a jax-free full replay."""
    store = LocalObjectStore(str(tmp_path))
    by_shard = _wids_by_shard(1)
    wids = by_shard[0] + by_shard[1]
    for wid in wids:
        w = _writer(store, wid=wid)
        for i in range(10):
            w.append({"i": i, "w": wid})
            if (i + 1) % 5 == 0:
                w.flush()
    make = _pair_exchange()
    a = _ingest(store, batch_records=4, exchange_fn=make(0))
    b = _ingest(store, batch_records=4, exchange_fn=make(1))
    a.reshard(0, 2, epoch=1)
    b.reshard(1, 2, epoch=1)
    _run_lockstep([a, b], steps=2)          # 8 of 20 consumed at world 2
    assert a.cursor.consumed_total == 8
    assert a.cursor.to_dict() == b.cursor.to_dict()
    # rank 1 dies; the survivor owns EVERY shard and resumes each writer
    # exactly where the union says it stands
    a.exchange_fn = lambda payload: [payload]
    a.reshard(0, 1, epoch=2)
    for _ in range(20):
        a.next()
        if a.last_drained and a.last_records == 0:
            break
    assert a.cursor.consumed_total == 20
    audit = _full_replay(store)
    assert audit.consumed_total == 20
    assert a.cursor.checksum == audit.checksum
    assert {w: a.cursor.writers[w].consumed for w in wids} \
        == {w: audit.writers[w].consumed for w in wids}


def test_partitioned_blend_on_exchange_unavailable(tmp_path):
    """A failed gather costs FRESHNESS, never correctness: the step
    degrades to a pure blend batch (identical to a starved ingest's),
    the cursor does not move, and the blend is accounted."""
    store = LocalObjectStore(str(tmp_path))
    w = _writer(store)
    for i in range(4):
        w.append({"i": i})
    w.flush()
    ing = _ingest(store, exchange_fn=lambda payload: None)
    ing.reshard(0, 1, epoch=0)
    starved = _ingest(LocalObjectStore(str(tmp_path / "empty")))
    b, ref = ing.next(), starved.next()
    assert b["nrec"] == 0 and ing.cursor.consumed_total == 0
    assert np.array_equal(b["x"], ref["x"])
    assert ing.blend_steps == 1


# ---------------------------------------------------------------------------
# data-quality gate: rejection costs freshness, never position
# ---------------------------------------------------------------------------


def test_quality_gate_poisoned_window_costs_freshness_not_position(
        tmp_path):
    """A 100%-poisoned window: nothing reaches batch_fn (the batch is
    bitwise the pure-blend batch — at trainer level, params untouched by
    feedback), yet the cursor advances past every rejected record and
    the per-reason ledger accounts each one."""
    store = LocalObjectStore(str(tmp_path))
    w = _writer(store)
    for rec in poison_records(6):
        w.append(rec)
    w.flush()
    gate = QualityGate()
    ing = _ingest(store, batch_records=8, quality=gate)
    starved = _ingest(LocalObjectStore(str(tmp_path / "empty")))
    b, ref = ing.next(), starved.next()
    assert b["nrec"] == 0
    assert np.array_equal(b["x"], ref["x"])
    assert ing.cursor.consumed_total == 6          # position advanced
    assert gate.checked == 6 and gate.admitted == 0
    assert gate.rejected == {"schema": 2, "outlier": 2, "oversize": 2}
    assert gate.rejected_total == 6


def test_quality_gate_same_frontier_same_batches(tmp_path):
    """Determinism: the gate is a pure function of the record, so two
    consumers at the same frontier produce bitwise-identical post-filter
    batches and identical reject ledgers — replicas can never diverge on
    what the gate dropped."""
    store = LocalObjectStore(str(tmp_path))
    w = _writer(store)
    recs = poison_records(3)
    good = [{"prompt": [1, 2], "response": [3], "feedback": 0.5},
            {"prompt": [4], "response": [5, 6], "feedback": -0.25}]
    for rec in (good[0], recs[0], good[1], recs[1], recs[2]):
        w.append(rec)
    w.flush()
    g1, g2 = QualityGate(), QualityGate()
    a = _ingest(store, batch_records=8, quality=g1)
    b = _ingest(store, batch_records=8, quality=g2)
    ba, bb = a.next(), b.next()
    assert ba["nrec"] == bb["nrec"] == 2           # the two good records
    assert np.array_equal(ba["x"], bb["x"])
    assert a.cursor.consumed_total == b.cursor.consumed_total == 5
    assert g1.rejected == g2.rejected and g1.rejected_total == 3


def test_poison_feedback_fault_injects_through_append_path(tmp_path):
    """The `poison_feedback@N:count` fault rides the writer's REAL
    append path (committed segments, sequenced, checksummed) — and the
    gate rejects exactly the burst while the real records pass."""
    inj = FaultInjector(parse_faults("poison_feedback@2:5"), own_rank=0)
    store = LocalObjectStore(str(tmp_path))
    w = _writer(store, injector=inj)
    for i in range(3):
        w.append({"prompt": [i + 1], "response": [i + 2], "feedback": 1})
    w.flush()
    audit = _full_replay(store)
    assert audit.consumed_total == 8               # 3 real + 5 poison
    cur = Cursor()
    rd = FeedbackReader(store, stream="s")
    recs = rd.take(cur, rd.frontier(full=True), 100)
    gate = QualityGate()
    kept = gate.admit(recs)
    assert len(kept) == 3 and gate.rejected_total == 5
    assert all(isinstance(r["prompt"], list) and r["feedback"] == 1
               for r in kept)


def test_parse_online_fault_grammar():
    faults = parse_faults("poison_feedback@10:12:r0,bad_version@4:r1")
    assert faults[0].kind == "poison_feedback" and faults[0].step == 10
    assert faults[0].arg == 12 and faults[0].rank == 0
    assert faults[1].kind == "bad_version" and faults[1].step == 4
    assert faults[1].rank == 1
    # rank-targeted consumption keeps schedules aligned across the fleet
    inj = FaultInjector([faults[0]], own_rank=1)
    assert inj.poison_burst(10) == 0
    assert [f.kind for f in inj.skipped] == ["poison_feedback"]


# ---------------------------------------------------------------------------
# retention: compaction below the fleet-min frontier
# ---------------------------------------------------------------------------


def test_compaction_below_cursor_keeps_ledger_and_frontier(tmp_path):
    """Compacting below a consumer's cursor removes segments but never
    accounting: a fresh full replay still balances bit-for-bit against
    the pre-compaction ledger (the marker replays the doomed range), the
    newest committed segment survives, an in-flight reader past the cut
    resumes with no gap, and a cursor BELOW the cut fast-forwards
    through the marker and still balances."""
    store = LocalObjectStore(str(tmp_path))
    w = _writer(store)
    for i in range(16):
        w.append({"i": i})
        if (i + 1) % 4 == 0:
            w.flush()                       # 4 segments of 4
    full = _full_replay(store)
    assert full.consumed_total == 16
    rd = FeedbackReader(store, stream="s")
    fr = rd.frontier(full=True)
    mid = Cursor()                          # partway: inside segment 2
    rd.take(mid, fr, 10)
    below = Cursor()                        # below the cut: segment 0
    rd2 = FeedbackReader(store, stream="s")
    rd2.take(below, rd2.frontier(full=True), 4)
    below = Cursor.from_dict(json.loads(json.dumps(below.to_dict())))

    removed = compact_segments(store, "s", mid)
    assert removed >= 1
    keys = store.list("feedback/s/r0")
    assert any(k.endswith("COMPACTED.json") for k in keys)
    assert any("seg_00000003" in k for k in keys)   # newest survives

    # 1) the full-replay ledger is unchanged by compaction
    audit = _full_replay(store)
    assert audit.consumed_total == 16
    assert audit.checksum == full.checksum
    # 2) the partway consumer resumes across the cut with no gap
    rd3 = FeedbackReader(store, stream="s")
    fr3 = rd3.frontier(full=True)
    while rd3.take(mid, fr3, 100):
        pass
    assert mid.consumed_total == 16 and mid.checksum == full.checksum
    # 3) a below-the-cut cursor fast-forwards via the marker: ledger
    # exact (count + checksum), only freshness lost
    rd4 = FeedbackReader(store, stream="s")
    fr4 = rd4.frontier(full=True)
    while rd4.take(below, fr4, 100):
        pass
    assert below.consumed_total == 16 and below.checksum == full.checksum
    # 4) history stays countable and the writer keeps appending
    assert rd4.committed_records(fr4) == 16
    w2 = _writer(store)
    for i in range(16, 20):
        w2.append({"i": i})
    w2.flush()
    audit2 = _full_replay(store)
    assert audit2.consumed_total == 20


# ---------------------------------------------------------------------------
# the end-to-end gate
# ---------------------------------------------------------------------------


@pytest.mark.timeout(680, method="signal")
def test_chaos_check_online_storm(tmp_path):
    """scripts/chaos_check.py --online: the training↔serving closed-loop
    gate (ISSUE-12 acceptance, grown by ISSUE-17 to production
    fidelity). A serving fleet feeds a live 2-rank PARTITIONED-ingest
    trainer through the durable feedback log while a serving replica and
    a trainer rank are SIGKILLed, a torn segment, a duplicate record and
    a 12-record poisoned burst are injected, feedback retention compacts
    segments mid-storm, and the published version advances through
    rolling drain+backfill swaps (>= 2 observed serving) — then a
    NaN-poisoned publish rides a canary rollout, the router's A/B
    verdict fails it, and the fleet rolls back to the last good version
    before the republish mints a fresh number. Asserts zero
    accepted-then-lost requests, zero training progress lost past the
    newest upload, exactly-once ingest accounting (count AND
    order-independent checksum vs a jax-free replay of the log, with
    per-shard slices tiling the union), and `bench_gate.py --slo`
    holding a throughput floor and the feedback-freshness ceiling."""
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = os.path.join(repo, "scripts", "chaos_check.py")
    env = dict(os.environ)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(
        [sys.executable, script, "--online", "--workdir", str(tmp_path)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True, timeout=640,
    )
    assert proc.returncode == 0, proc.stdout[-3000:]
    assert "CHAOS CHECK PASSED" in proc.stdout, proc.stdout[-3000:]
