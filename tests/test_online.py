"""Online continual-learning subsystem: the durable feedback log
(manifest-LAST segments, torn-segment walk-past, seq dedup), the
exactly-once streaming ingest (cursor in the sidecar state, blend vs
feed, consensus frontier), the object-store ordering/first-writer-wins
contracts they ride on, the new data-path fault grammar — and the
`scripts/chaos_check.py --online` storm as the end-to-end gate
(docs/ONLINE.md)."""

import json
import os
import threading

import numpy as np
import pytest

from dear_pytorch_tpu.online.feedback import (
    Cursor, FeedbackReader, FeedbackWriter, record_digest,
)
from dear_pytorch_tpu.online.ingest import FeedbackIngest
from dear_pytorch_tpu.resilience.inject import (
    Fault, FaultInjector, parse_faults,
)
from dear_pytorch_tpu.runtime import build as RB
from dear_pytorch_tpu.runtime import pipeline as P
from dear_pytorch_tpu.utils.objectstore import LocalObjectStore


# ---------------------------------------------------------------------------
# object store: the pinned ordering + first-writer-wins contracts
# ---------------------------------------------------------------------------


def test_list_ordering_under_concurrent_appenders(tmp_path):
    """list(prefix) is lexicographic-by-key no matter how many appenders
    raced — the ordering contract segment-walking readers rely on."""
    store = LocalObjectStore(str(tmp_path))
    gate = threading.Barrier(4)

    def appender(w):
        gate.wait()
        for i in range(25):
            store.put_bytes(f"logs/w{w}/seg_{i:08d}", b"x")

    threads = [threading.Thread(target=appender, args=(w,))
               for w in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    keys = store.list("logs")
    assert len(keys) == 100
    assert keys == sorted(keys)
    # and per-writer the segment files come back in segment order
    w0 = [k for k in keys if k.startswith("logs/w0/")]
    assert w0 == [f"logs/w0/seg_{i:08d}" for i in range(25)]


def test_put_bytes_if_absent_first_writer_wins(tmp_path):
    store = LocalObjectStore(str(tmp_path))
    gate = threading.Barrier(8)
    wins = []

    def racer(i):
        gate.wait()
        if store.put_bytes_if_absent("decided/e7", f"writer{i}".encode()):
            wins.append(i)

    threads = [threading.Thread(target=racer, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(wins) == 1
    assert store.get_bytes("decided/e7") == f"writer{wins[0]}".encode()
    # a later duplicate publication is idempotent: loses, content intact
    assert store.put_bytes_if_absent("decided/e7", b"late") is False
    assert store.get_bytes("decided/e7") == f"writer{wins[0]}".encode()


# ---------------------------------------------------------------------------
# feedback log: commit protocol, damage tolerance, dedup, cursor replay
# ---------------------------------------------------------------------------


def _writer(store, wid="r0", **kw):
    kw.setdefault("stream", "s")
    kw.setdefault("flush_records", 4)
    kw.setdefault("start", False)
    return FeedbackWriter(store, writer_id=wid, **kw)


def test_roundtrip_and_manifest_last_commit(tmp_path):
    store = LocalObjectStore(str(tmp_path))
    w = _writer(store)
    for i in range(6):
        assert w.append({"prompt": [i], "response": [i + 1]})
    w.flush()  # 6 records -> one segment
    r = FeedbackReader(store, stream="s")
    fr = r.frontier()
    assert fr == {"r0": 0}
    assert r.committed_records(fr) == 6
    cur = Cursor()
    recs = r.take(cur, fr, 100)
    assert [x["uid"] for x in recs] == [f"r0:{i}" for i in range(6)]
    assert recs[0]["prompt"] == [0] and recs[0]["writer"] == "r0"
    assert cur.consumed_total == 6 and r.drained(cur, fr)
    # manifest-LAST: a payload without its manifest is invisible to the
    # frontier (an in-flight flush can never be read half-committed)
    store.put_bytes("feedback/s/r0/seg_00000001.jsonl", b'{"seq": 6}\n')
    assert r.frontier() == {"r0": 0}


def test_torn_segment_walked_past_never_crashes(tmp_path):
    store = LocalObjectStore(str(tmp_path))
    inj = FaultInjector([Fault(kind="torn_seg", step=2)], own_rank=0)
    w = _writer(store, injector=inj)
    for i in range(12):
        w.append({"i": i})
        if (i + 1) % 4 == 0:
            w.flush()
    # flush 2 (records 4..7) published its payload but no manifest
    r = FeedbackReader(store, stream="s")
    cur = Cursor()
    recs = r.take(cur, r.frontier(), 100)
    assert [x["seq"] for x in recs] == [0, 1, 2, 3, 8, 9, 10, 11]
    assert cur.torn_segments == 1
    assert cur.consumed_total == 8


def test_corrupt_payload_walked_past_and_lag_drains(tmp_path):
    store = LocalObjectStore(str(tmp_path))
    w = _writer(store)
    for i in range(8):
        w.append({"i": i})
        if (i + 1) % 4 == 0:
            w.flush()
    raw = bytearray(store.get_bytes("feedback/s/r0/seg_00000000.jsonl"))
    raw[0] ^= 0xFF
    store.put_bytes("feedback/s/r0/seg_00000000.jsonl", bytes(raw))
    r = FeedbackReader(store, stream="s")
    cur = Cursor()
    recs = r.take(cur, r.frontier(), 100)
    assert [x["seq"] for x in recs] == [4, 5, 6, 7]
    assert cur.torn_segments == 1
    # the corrupt segment's manifest count is written off, so the lag
    # ledger drains to zero — a permanent nonzero ingest_lag would be a
    # standing false alert on a fully-caught-up consumer
    assert cur.dropped_committed == 4
    assert (r.committed_records() - cur.consumed_total - cur.dedup_hits
            - cur.dropped_committed) == 0


def test_duplicate_record_deduplicated(tmp_path):
    store = LocalObjectStore(str(tmp_path))
    inj = FaultInjector([Fault(kind="dup_feedback", step=6)], own_rank=0)
    w = _writer(store, injector=inj)
    for i in range(4):
        w.append({"i": i})
    w.flush()
    for i in range(4, 8):
        w.append({"i": i})  # append 6 re-appends the last COMMITTED rec
    w.flush()
    r = FeedbackReader(store, stream="s")
    cur = Cursor()
    recs = r.take(cur, r.frontier(), 100)
    assert [x["seq"] for x in recs] == list(range(8))
    assert cur.dedup_hits == 1
    assert cur.consumed_total == 8
    # the committed count INCLUDES the duplicate line; the unique count
    # is the exactly-once quantity
    assert r.committed_records() == 9


def test_writer_restart_resumes_committed_tail(tmp_path):
    store = LocalObjectStore(str(tmp_path))
    w1 = _writer(store)
    for i in range(5):
        w1.append({"i": i})
    w1.flush()
    w1.append({"i": 5})  # dies with this record buffered (never flushed)
    w2 = _writer(store)  # the relaunched incarnation
    assert w2._next_seg == 1 and w2._next_seq == 5
    w2.append({"i": "fresh"})
    w2.flush()
    r = FeedbackReader(store, stream="s")
    cur = Cursor()
    recs = r.take(cur, r.frontier(), 100)
    # seq 5 is REUSED by the new life (the buffered record was lost
    # before commit), so the stream stays gap-free
    assert [x["seq"] for x in recs] == list(range(6))
    assert cur.dedup_hits == 0 and cur.torn_segments == 0


def test_restart_after_dup_at_segment_tail_does_not_reuse_seqs(tmp_path):
    """The duplicate re-append lands AFTER the newest record, so a
    positional last_seq would understate the manifest and a relaunched
    writer would re-stamp already-committed seq numbers — which every
    reader then silently dedup-drops (committed-but-never-consumed data
    loss the ledger cannot even see). last_seq must be the MAX."""
    store = LocalObjectStore(str(tmp_path))
    inj = FaultInjector([Fault(kind="dup_feedback", step=5)], own_rank=0)
    w1 = _writer(store, injector=inj)
    for i in range(4):
        w1.append({"i": i})
    w1.flush()                      # seqs 0..3 committed
    w1.append({"i": 4})             # append 5: seq 4 + dup of seq 3
    w1.flush()                      # segment tail is the dup (seq 3)
    w2 = _writer(store)             # relaunched incarnation
    assert w2._next_seq == 5        # NOT 4: seq 4 is already committed
    w2.append({"i": "fresh"})
    w2.flush()
    r = FeedbackReader(store, stream="s")
    cur = Cursor()
    recs = r.take(cur, r.frontier(), 100)
    # every unique committed record consumed, the dup alone dropped
    assert [x["seq"] for x in recs] == [0, 1, 2, 3, 4, 5]
    assert cur.dedup_hits == 1


def test_flush_exhaustion_counts_never_raises(tmp_path):
    class DeadStore(LocalObjectStore):
        def __init__(self, root):
            super().__init__(root)
            self.dead = False

        def put_bytes(self, key, data):
            if self.dead:
                raise OSError("store down")
            super().put_bytes(key, data)

    store = DeadStore(str(tmp_path))
    w = _writer(store, retry_attempts=2)
    for i in range(4):
        w.append({"i": i})
    store.dead = True
    assert w.flush() == 0           # exhausted: dropped, not raised
    assert w.flush_errors == 1 and w.dropped_flush == 4
    store.dead = False
    for i in range(4, 8):
        w.append({"i": i})
    assert w.flush() == 4           # the writer survived its dead store
    assert w.committed == 4


def test_append_never_blocks_on_full_buffer(tmp_path):
    store = LocalObjectStore(str(tmp_path))
    w = _writer(store, max_buffer=3)
    assert all(w.append({"i": i}) for i in range(3))
    assert w.append({"i": 99}) is False    # dropped, counted, no raise
    assert w.append_drops == 1
    w.flush()
    assert w.committed == 3


def test_parse_data_path_faults():
    faults = parse_faults("torn_seg@2:r1,dup_feedback@6")
    assert faults[0].kind == "torn_seg" and faults[0].step == 2
    assert faults[0].rank == 1
    assert faults[1].kind == "dup_feedback" and faults[1].rank is None
    # rank targeting: the fault is consumed (skipped) on other ranks so
    # schedules drain identically everywhere
    inj = FaultInjector([faults[0]], own_rank=0)
    assert inj.torn_segment(2) is False
    assert [f.kind for f in inj.skipped] == ["torn_seg"]
    assert inj.pending == 0


# ---------------------------------------------------------------------------
# ingest: blend vs feed, exactly-once cursor replay, consensus frontier
# ---------------------------------------------------------------------------


def _ingest(store, *, batch_records=4, consensus_fn=None, rows=4):
    spec = P.SyntheticSpec((
        P.Field("x", (rows, 6), RB.KIND_NORMAL_F32, 0.0, 1.0),
    ))
    base = P.NumpyPipeline(spec, seed=7)

    def batch_fn(base_batch, records):
        x = np.array(base_batch["x"])
        for j, rec in enumerate(records[:rows]):
            rng = np.random.default_rng(
                record_digest(rec["writer"], rec["seq"]) % (1 << 32))
            x[j] = rng.normal(size=x.shape[1]).astype(np.float32)
        return {"x": x, "nrec": len(records)}

    return FeedbackIngest(base, FeedbackReader(store, stream="s"),
                          batch_records=batch_records, batch_fn=batch_fn,
                          consensus_fn=consensus_fn)


def test_ingest_blends_when_starved_feeds_when_available(tmp_path):
    store = LocalObjectStore(str(tmp_path))
    ing = _ingest(store)
    b = ing.next()
    assert b["nrec"] == 0 and ing.last_drained    # empty log: pure blend
    w = _writer(store)
    for i in range(6):
        w.append({"i": i})
    w.flush()
    b = ing.next()
    assert b["nrec"] == 4 and not ing.last_drained
    assert ing.lag() == 2
    b = ing.next()
    assert b["nrec"] == 2 and ing.last_drained and ing.lag() == 0
    b = ing.next()
    assert b["nrec"] == 0                          # drained: blend again


def test_ingest_cursor_replay_is_exactly_once(tmp_path):
    """Restoring the state dict (what a guard rollback does) replays the
    stream byte-identically: same records, same batches, same checksum —
    the sidecar transactionality that makes ingest exactly-once."""
    store = LocalObjectStore(str(tmp_path))
    w = _writer(store)
    for i in range(14):
        w.append({"i": i})
        if (i + 1) % 4 == 0:
            w.flush()
    w.flush()
    ing = _ingest(store)
    ing.next()
    snap = ing.state_dict()
    snap_json = json.dumps(snap)          # must be sidecar-JSON-safe
    after = [ing.next() for _ in range(3)]
    end_state = ing.state_dict()
    ing.load_state_dict(json.loads(snap_json))
    replay = [ing.next() for _ in range(3)]
    for a, b in zip(after, replay):
        assert np.allclose(a["x"], b["x"]) and a["nrec"] == b["nrec"]
    assert ing.state_dict() == end_state
    assert ing.cursor.consumed_total == 14


def test_ingest_consensus_frontier_caps_the_read(tmp_path):
    """The fleet-MIN frontier pins every rank to the same availability
    snapshot: records committed past the agreed frontier are invisible
    until the next exchange, so replicas can never diverge on feed vs
    blend."""
    store = LocalObjectStore(str(tmp_path))
    w = _writer(store)
    for i in range(8):
        w.append({"i": i})
        if (i + 1) % 4 == 0:
            w.flush()

    calls = []

    def consensus(frontier):
        calls.append(dict(frontier))
        return {"r0": 0}  # a lagging peer has only seen segment 0

    ing = _ingest(store, consensus_fn=consensus)
    b = ing.next()
    assert b["nrec"] == 4 and ing.cursor.consumed_total == 4
    b = ing.next()
    assert b["nrec"] == 0                  # frontier-capped: blend
    assert calls and calls[-1] == {"r0": 1}  # local view did see seg 1


def test_ingest_checksum_is_interleave_independent(tmp_path):
    """Two consumers with different batch sizes (different interleaves
    across writers) converge to the same consumed_total AND checksum —
    what lets a jax-free auditor replay the log and verify the trainer's
    ledger without reproducing its step cadence."""
    store = LocalObjectStore(str(tmp_path))
    for wid in ("r0", "r1"):
        w = _writer(store, wid=wid)
        for i in range(10):
            w.append({"i": i})
            if (i + 1) % 5 == 0:
                w.flush()
    a, b = _ingest(store, batch_records=3), _ingest(store, batch_records=7)
    for ing in (a, b):
        while not (ing.next() is not None and ing.last_drained
                   and ing.last_records == 0):
            pass
    assert a.cursor.consumed_total == b.cursor.consumed_total == 20
    assert a.cursor.checksum == b.cursor.checksum


def test_ingest_bare_sidecar_restore_resets_cursor(tmp_path):
    """Rolling back to a sidecar written by a bare pipeline (a run that
    predates the online wrapper) must RESET the cursor: keeping the
    in-memory position would leave records trained only into the
    discarded state and never re-consumed — re-training from zero is
    the transactional answer."""
    store = LocalObjectStore(str(tmp_path))
    w = _writer(store)
    for i in range(6):
        w.append({"i": i})
    w.flush()
    ing = _ingest(store)
    bare_state = ing.base.state_dict()     # a pre-online sidecar
    ing.next()
    assert ing.cursor.consumed_total == 4
    ing.load_state_dict(bare_state)
    assert ing.cursor.consumed_total == 0  # reset, not stale
    ing.next()
    ing.next()
    assert ing.cursor.consumed_total == 6  # everything re-consumed


def test_frontier_probe_advances_without_listing(tmp_path):
    """Between discovery listings the frontier advances by exists()
    probes (O(writers) per step, not O(log age)); a numbering gap (a
    wholly-dropped segment) is jumped at the next discovery listing."""
    store = LocalObjectStore(str(tmp_path))
    w = _writer(store)
    for i in range(4):
        w.append({"i": i})
    w.flush()
    r = FeedbackReader(store, stream="s", discover_every=4)
    assert r.frontier() == {"r0": 0}       # call 1: discovery listing
    for i in range(4, 8):
        w.append({"i": i})
    w.flush()
    assert r.frontier() == {"r0": 1}       # call 2: probe fast path
    # a wholly-dropped segment (no objects at all): the writer moved on
    w._next_seg += 1
    for i in range(8, 12):
        w.append({"i": i})
    w.flush()                              # commits seg 3, seg 2 empty
    assert r.frontier() == {"r0": 1}       # call 3: probe stalls at gap
    assert r.frontier(full=True) == {"r0": 3}  # definitive view on demand
    r2 = FeedbackReader(store, stream="s", discover_every=4)
    r2.frontier()                          # fresh reader: discovery
    assert r2.frontier() == {"r0": 3}      # probes continue from there
    cur = Cursor()
    recs = r.take(cur, r.frontier(), 100)
    assert [x["seq"] for x in recs] == list(range(12))


def test_ingest_reshard_keeps_replica_identical_blend(tmp_path):
    """A membership transition reshards the base stream by EPOCH only:
    every member of the new world draws the identical blend stream (the
    ingest is replica-global), while the epoch fold still makes the
    post-transition stream distinct from the pre-transition one."""
    store = LocalObjectStore(str(tmp_path))
    a, b = _ingest(store), _ingest(store)
    a.reshard(0, 3, epoch=2)   # rank 0's view of a 3-world
    b.reshard(2, 3, epoch=2)   # rank 2's view of the same transition
    ba, bb = a.next(), b.next()
    assert np.allclose(ba["x"], bb["x"])
    assert a.state_dict()["epoch"] == 2
    fresh = _ingest(store)     # epoch 0: a different stream
    assert not np.allclose(fresh.next()["x"], ba["x"])


def test_sole_survivor_guard_stays_coordinated(tmp_path):
    """The --online storm's root-caused bug: a 2-rank fleet shrinks to
    ONE survivor — the guard must keep running the coordinated health
    sync (it is where rejoin requests are polled), or the relaunched
    rank is never admitted and the fleet can never grow back."""
    from dear_pytorch_tpu.resilience import membership as M
    from dear_pytorch_tpu.resilience.cluster import FileTransport
    from dear_pytorch_tpu.utils import guard as G

    cluster = M.ElasticCluster(
        transport=FileTransport(str(tmp_path / "store")), rank=0, world=1)
    shim = object.__new__(G.GuardedTrainer)  # property check only
    shim._coordinator = cluster
    assert cluster.process_count == 1
    assert shim._coordinated is True

    class PlainWorld1:
        process_count = 1

    shim._coordinator = PlainWorld1()
    assert shim._coordinated is False  # non-elastic world-1: unchanged


# ---------------------------------------------------------------------------
# the end-to-end gate
# ---------------------------------------------------------------------------


@pytest.mark.timeout(560, method="signal")
def test_chaos_check_online_storm(tmp_path):
    """scripts/chaos_check.py --online: the training↔serving closed-loop
    gate (ISSUE-12 acceptance). A serving fleet feeds a live 2-rank
    trainer through the durable feedback log while a serving replica and
    a trainer rank are SIGKILLed, a torn segment and a duplicate record
    are injected, and the published version advances through rolling
    drain+backfill swaps (>= 2 observed serving). Asserts zero
    accepted-then-lost requests, zero training progress lost past the
    newest upload, exactly-once ingest accounting (count AND
    order-independent checksum vs a jax-free replay of the log), and
    `bench_gate.py --slo` holding a throughput floor and the
    feedback-freshness ceiling."""
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = os.path.join(repo, "scripts", "chaos_check.py")
    env = dict(os.environ)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(
        [sys.executable, script, "--online", "--workdir", str(tmp_path)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True, timeout=520,
    )
    assert proc.returncode == 0, proc.stdout[-3000:]
    assert "CHAOS CHECK PASSED" in proc.stdout, proc.stdout[-3000:]
