"""Elastic membership layer (`resilience.membership`) unit tests.

The membership protocol is exercised WITHOUT processes: N `ElasticCluster`
instances on N threads share one `LocalTransport` (or a `FileTransport`
under tmp_path where store persistence across "relaunch" matters) and
behave like N ranks. The real 3-process SIGKILL/rejoin scenario lives in
tests/test_multiprocess.py::test_elastic_membership and
scripts/chaos_check.py --elastic; this file covers the protocol corners
those can't schedule deterministically — a second failure racing a
reconfiguration, a rejoin racing a shrink, eviction — plus the downstream
elastic plumbing: epoch-stamped plan fingerprints, `AutoTuner.rescale`,
pipeline state/reshard determinism, decorrelated retry jitter, and the
guard's membership-transition path against a scripted coordinator.
"""

import json
import os
import signal
import sys
import threading
import time

import jax
import numpy as np
import pytest

from dear_pytorch_tpu.observability import tracer as T
from dear_pytorch_tpu.ops import fusion as F
from dear_pytorch_tpu.resilience import cluster as CL
from dear_pytorch_tpu.resilience import membership as M
from dear_pytorch_tpu.resilience import retry as R
from dear_pytorch_tpu.resilience import scale as SC
from dear_pytorch_tpu.resilience.preempt import PreemptionHandler
from dear_pytorch_tpu.runtime import build as RB
from dear_pytorch_tpu.runtime import pipeline as P
from dear_pytorch_tpu.utils import checkpoint as ckpt
from dear_pytorch_tpu.utils.objectstore import LocalObjectStore


def make_members(n, transport=None, *, timeout_s=2.0, ranks=None):
    """N ElasticClusters sharing one transport (LocalTransport default)."""
    transport = transport or CL.LocalTransport(n)
    ranks = list(ranks if ranks is not None else range(n))
    return transport, [
        M.ElasticCluster(rank=r, members=ranks, transport=transport,
                         timeout_s=timeout_s)
        for r in ranks
    ]


def run_threads(fns, *, join_s=60):
    """Run one callable per thread; returns (results, errors) by index."""
    results, errors = [None] * len(fns), [None] * len(fns)

    def work(i):
        try:
            results[i] = fns[i]()
        except BaseException as exc:  # noqa: BLE001 - asserted by callers
            errors[i] = exc

    threads = [threading.Thread(target=work, args=(i,))
               for i in range(len(fns))]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=join_s)
    return results, errors


def _health_payload(ok=True, fp="", pre=False, rejoin=None):
    return json.dumps({"ok": ok, "fp": fp, "pre": pre,
                       "rejoin": rejoin or {}})


# -- exchange / epochs --------------------------------------------------------


def test_exchange_is_member_ordered():
    _, ms = make_members(3)
    out, errs = run_threads([
        (lambda c=c, i=i: c.exchange("hello", f"msg{i}"))
        for i, c in enumerate(ms)])
    assert not any(errs)
    assert out == [["msg0", "msg1", "msg2"]] * 3


def test_exchange_world_one_short_circuits():
    _, (c,) = make_members(1)
    assert c.exchange("solo", "x") == ["x"]
    assert c.view() == M.MembershipView(epoch=0, members=(0,), rank=0,
                                        index=0, world=1)


def test_missing_member_attaches_missing_ranks():
    _, ms = make_members(3, timeout_s=0.5)
    # rank 2 never shows up
    out, errs = run_threads([
        (lambda c=ms[0]: c.exchange("t", "a")),
        (lambda c=ms[1]: c.exchange("t", "b")),
    ])
    assert all(isinstance(e, CL.PeerTimeout) for e in errs)
    assert all(e.missing_ranks == (2,) for e in errs)


# -- reconfiguration ----------------------------------------------------------


def test_health_check_converts_loss_into_reconfig():
    """A member that never reaches the sync is converted into a committed
    survivor-set epoch — the verdict every guard consumes as a
    transition point."""
    _, ms = make_members(3, timeout_s=0.5)
    out, errs = run_threads([
        (lambda c=ms[0]: c.health_check(True, fingerprint="f", step=7)),
        (lambda c=ms[1]: c.health_check(True, fingerprint="f", step=7)),
    ])
    assert not any(errs[:2])
    for v in out[:2]:
        assert v.reconfigured and v.membership_changed and not v.ok
        assert v.epoch == 1 and v.members == (0, 1) and v.lost == (2,)
    for c in ms[:2]:
        assert c.epoch == 1 and c.members == (0, 1)
        assert c.world == 2 and c.leader == 0
    # the survivors are in lockstep at the new epoch, seqs reset
    out, errs = run_threads([
        (lambda c=ms[0]: c.exchange("post", "p0")),
        (lambda c=ms[1]: c.exchange("post", "p1")),
    ])
    assert not any(errs) and out[0] == ["p0", "p1"]


def test_concurrent_failure_during_reconfig_widens():
    """A member that dies BETWEEN the health exchange and its reconfig
    proposal is absorbed by the union-widening round: the committed epoch
    still bumps by exactly one."""
    transport, ms = make_members(4, timeout_s=0.5)
    # rank 2 published its health key (it was alive at the sync)...
    transport.set(f"{ms[2]._ns}/e0/health/0/2", _health_payload())
    # ...then died before proposing; rank 3 was already dead.
    out, errs = run_threads([
        (lambda c=ms[0]: c.health_check(True, step=1)),
        (lambda c=ms[1]: c.health_check(True, step=1)),
    ])
    assert not any(errs)
    for v in out:
        assert v.reconfigured and v.epoch == 1
        assert v.members == (0, 1)
        # the verdict reports the COMMITTED removal: the sync loss (3)
        # plus the mid-reconfig absorption (2) — everything this epoch
        # actually dropped, which is also what slice closure needs
        assert v.lost == (2, 3)
    assert ms[0].members == (0, 1) and ms[0].epoch == 1


def test_reconfigure_rejects_self_and_non_members():
    _, (c,) = make_members(1)
    with pytest.raises(M.EvictedError):
        c.reconfigure([0])
    with pytest.raises(ValueError, match="no current member"):
        c.reconfigure([9])


def test_evicted_when_peers_declared_me_dead():
    """Asymmetric failure detection: rank 1 declares 0 dead while rank 0
    declares 2 dead. Rank 0 finds itself in a gathered proposal's union
    and must exit for relaunch+rejoin (EvictedError); rank 1's rounds
    widen to {0, 2} and it commits alone."""
    _, ms = make_members(3, timeout_s=0.5)
    out, errs = run_threads([
        (lambda c=ms[0]: c.reconfigure([2])),
        (lambda c=ms[1]: c.reconfigure([0])),
    ])
    assert isinstance(errs[0], M.EvictedError), errs
    assert errs[1] is None and out[1].members == (1,)
    assert ms[1].epoch == 1 and ms[1].world == 1


def test_sole_survivor_commits_unilaterally():
    _, ms = make_members(2, timeout_s=0.5)
    view = ms[0].reconfigure([1])
    assert view == M.MembershipView(epoch=1, members=(0,), rank=0,
                                    index=0, world=1)


def test_decide_once_first_writer_wins(tmp_path):
    lt = CL.LocalTransport(1)
    assert lt.decide_once("k", "a") == "a"
    assert lt.decide_once("k", "b") == "a"  # loser adopts the winner
    ft = CL.FileTransport(str(tmp_path))
    assert ft.decide_once("d/e1", "x") == "x"
    assert ft.decide_once("d/e1", "y") == "x"
    assert ft.get("d/e1", 0.1) == "x"  # durable, a plain key


def test_falsely_evicted_rank_cannot_fork_the_membership():
    """Split-brain guard: peers commit epoch 1 without the stalled rank 0
    (decision record durably present). When rank 0 wakes, times out on
    everyone, and reconfigures itself into sole survivorship, it must
    discover the record and exit for relaunch+rejoin — NOT unilaterally
    commit a parallel one-rank epoch-1 fleet."""
    transport, ms = make_members(3, timeout_s=0.5)
    out, errs = run_threads([
        (lambda c=ms[1]: c.reconfigure([0])),
        (lambda c=ms[2]: c.reconfigure([0])),
    ])
    assert not any(errs)
    assert ms[1].members == (1, 2) and ms[1].epoch == 1
    with pytest.raises(M.EvictedError, match="already decided"):
        ms[0].reconfigure([1, 2])
    assert ms[0].epoch == 0  # nothing committed on the evicted side


def test_missed_commit_ack_defers_to_decided_record():
    """The 2PC ambiguity: a survivor that missed a commit ack widens past
    an epoch its peers already committed. Its eventual (sole-survivor)
    view disagrees with the durable decision record — even though it IS
    in the decided member set, re-entering an epoch whose exchange
    cadence started without it can't be lockstep, so it must exit for
    relaunch+rejoin rather than commit a diverged member set."""
    transport, ms = make_members(3, timeout_s=0.5)
    # peers decided epoch 1 as the full survivor set {0, 1, 2}
    transport.decide_once(f"{ms[0]._ns}/decided/e1", json.dumps([0, 1, 2]))
    with pytest.raises(M.EvictedError, match="already decided"):
        ms[0].reconfigure([1, 2])
    assert ms[0].epoch == 0


def test_admission_writes_the_epoch_decision_record():
    """Every committed epoch — shrink OR admission — must be discoverable
    by a later partitioned rank through its decision record."""
    transport, ms = make_members(3, timeout_s=1.0)
    shrink, errs = run_threads([
        (lambda c=ms[0]: c.health_check(True, step=1)),
        (lambda c=ms[1]: c.health_check(True, step=1)),
    ])
    assert not any(errs) and ms[0].epoch == 1
    # the shrink's commit left its SIGNED world-delta decision record
    rec = json.loads(transport.get(f"{ms[0]._ns}/decided/e1", 0.1))
    assert rec["members"] == [0, 1]
    assert rec["delta"] == {"added": [], "removed": [2]}

    relaunched = M.ElasticCluster(rank=2, members=[0, 1, 2],
                                  transport=transport, timeout_s=1.0)

    def member(c):
        for step in range(2, 40):
            v = c.health_check(True, step=step)
            if v.admitted:
                return v
            time.sleep(0.05)
        raise AssertionError("never admitted the rejoiner")

    out, errs = run_threads([
        (lambda c=ms[0]: member(c)),
        (lambda c=ms[1]: member(c)),
        (lambda: relaunched.rejoin(0, timeout_s=20)),
    ])
    assert not any(errs), errs
    # ...and so did the admission's — signed with the ADDED side
    rec = json.loads(transport.get(f"{ms[0]._ns}/decided/e2", 0.1))
    assert rec["members"] == [0, 1, 2]
    assert rec["delta"] == {"added": [2], "removed": []}


# -- rejoin -------------------------------------------------------------------


def test_rejoin_after_shrink_admits_at_epoch_barrier():
    transport, ms = make_members(3, timeout_s=1.0)
    shrink, errs = run_threads([
        (lambda c=ms[0]: c.health_check(True, step=3)),
        (lambda c=ms[1]: c.health_check(True, step=3)),
    ])
    assert not any(errs) and ms[0].epoch == 1

    # the relaunched rank presents its last known epoch...
    relaunched = M.ElasticCluster(rank=2, members=[0, 1, 2],
                                  transport=transport, timeout_s=1.0)
    rejoin_out = {}

    def rejoiner():
        view, context = relaunched.rejoin(0, timeout_s=20)
        rejoin_out["view"], rejoin_out["context"] = view, context
        return relaunched.exchange("post", "p2")

    def member(c):
        # ...the member cadence polls/admits within a few health syncs
        for step in range(4, 40):
            v = c.health_check(True, step=step)
            if v.admitted:
                assert v.admitted == (2,) and not v.ok
                assert v.epoch == 2 and v.members == (0, 1, 2)
                return c.exchange("post", f"p{c.rank}")
            time.sleep(0.05)
        raise AssertionError("never admitted the rejoiner")

    out, errs = run_threads([
        (lambda c=ms[0]: member(c)),
        (lambda c=ms[1]: member(c)),
        rejoiner,
    ])
    assert not any(errs), errs
    assert rejoin_out["view"].epoch == 2
    assert rejoin_out["view"].members == (0, 1, 2)
    assert rejoin_out["view"].index == 2
    # the fleet's cadence anchor rode in the admission ack
    assert rejoin_out["context"]["steps_seen"] >= 4
    # all three meet in lockstep at the admitted epoch (seq 0 reset)
    assert out[0] == out[2] == ["p0", "p1", "p2"]


def test_rejoin_racing_a_shrink_is_reconfigured_back_out():
    """An admitted rank that dies before reaching the epoch barrier is
    shrunk right back out: the fleet ends at epoch+2 with the original
    survivors and an empty admitted tuple."""
    transport, ms = make_members(2, timeout_s=0.5)
    ns = ms[0]._ns
    transport.set(f"{ns}/rejoin/req/7", json.dumps(
        {"rank": 7, "last_epoch": 0, "nonce": "dead07"}))
    # rank 7 is in initial_ranks for the members' poll to consider it
    for c in ms:
        c.initial_ranks = (0, 1, 7)
    out, errs = run_threads([
        (lambda c=ms[0]: c.health_check(True, step=1)),
        (lambda c=ms[1]: c.health_check(True, step=1)),
    ])
    assert not any(errs), errs
    for v in out:
        assert v.admitted == ()  # admitted, then lost before the barrier
        assert v.epoch == 2 and v.members == (0, 1)
        # the epoch moved INSIDE admit() (admission + eviction): the
        # verdict must still surface a membership change, or the guard
        # would keep a stale plan/pipeline epoch while sidecars advance
        assert v.reconfigured and v.membership_changed and not v.ok
    assert ms[0].members == (0, 1) and ms[0].epoch == 2
    # the dead rank's request was CONSUMED at the admission decision: the
    # next sync must not re-admit it (previously this thrashed forever —
    # one barrier timeout + two spurious epochs per health check)
    out, errs = run_threads([
        (lambda c=ms[0]: c.health_check(True, step=2)),
        (lambda c=ms[1]: c.health_check(True, step=2)),
    ])
    assert not any(errs), errs
    for v in out:
        assert v.ok and not v.membership_changed and v.epoch == 2


def test_fresh_rank_requires_joining_flag():
    with pytest.raises(ValueError, match="joining=True"):
        M.ElasticCluster(rank=5, members=[0, 1],
                         transport=CL.LocalTransport(1))


def test_transport_list_prefix(tmp_path):
    lt = CL.LocalTransport(1)
    lt.set("a/b/1", "x")
    lt.set("a/b/2/deep", "y")
    lt.set("a/other", "z")
    assert lt.list_prefix("a/b") == ["1", "2"]
    ft = CL.FileTransport(str(tmp_path))
    ft.set("a/b/1", "x")
    assert ft.list_prefix("a/b") == ["1"]
    assert ft.list_prefix("never/written") == []


def test_scale_up_admits_a_brand_new_rank():
    """Scale-UP: a rank that never existed (no prior death, no sidecar
    epoch — ``last_epoch=None``) publishes the ordinary join request, is
    DISCOVERED via the transport's list_prefix enumeration (no static
    rank list contains it), admitted at the epoch barrier, and counted
    as ``cluster.scale_ups`` on the members that grew the world."""
    tracer = T.Tracer([T.MemoryExporter()])
    prev = T._tracer
    T.set_tracer(tracer)
    try:
        transport, ms = make_members(2, timeout_s=1.0)
        fresh = M.ElasticCluster(rank=5, members=[0, 1],
                                 transport=transport, timeout_s=1.0,
                                 joining=True)
        out = {}

        def joiner():
            view, context = fresh.rejoin(None, timeout_s=20)
            out["view"], out["context"] = view, context
            return fresh.exchange("post", "p5")

        def member(c):
            for step in range(1, 40):
                v = c.health_check(True, step=step)
                if v.admitted:
                    assert v.admitted == (5,) and not v.ok
                    assert v.epoch == 1 and v.members == (0, 1, 5)
                    return c.exchange("post", f"p{c.rank}")
                time.sleep(0.05)
            raise AssertionError("never admitted the scale-up joiner")

        res, errs = run_threads([
            (lambda c=ms[0]: member(c)),
            (lambda c=ms[1]: member(c)),
            joiner,
        ])
        assert not any(errs), errs
        assert out["view"].epoch == 1 and out["view"].world == 3
        assert out["view"].index == 2  # the new shard slot
        assert res[0] == res[2] == ["p0", "p1", "p5"]
        # signed world-delta record: +[5]
        rec = json.loads(transport.get(f"{ms[0]._ns}/decided/e1", 0.1))
        assert rec["delta"] == {"added": [5], "removed": []}
        # a later DEATH of the scaled-up rank stays admissible even on
        # transports without enumeration: it joined initial_ranks
        assert 5 in ms[0].initial_ranks and 5 in ms[1].initial_ranks
        assert tracer.counters().get("cluster.scale_ups", 0) >= 1
    finally:
        T.set_tracer(prev)


def test_scale_up_racing_a_shrink():
    """A join request pending while a member dies: the sync converts the
    death into a shrink epoch FIRST, then the next sync admits the
    joiner — two clean epochs, and the joiner lands in the post-shrink
    membership (never the dead rank's ghost world)."""
    transport, ms = make_members(3, timeout_s=0.5)
    fresh = M.ElasticCluster(rank=7, members=[0, 1, 2],
                             transport=transport, timeout_s=0.5,
                             joining=True)
    # rank 2 never syncs (dead); rank 7 wants in
    admitted_verdicts = []

    def joiner():
        return fresh.rejoin(None, timeout_s=30)

    def member(c):
        for step in range(1, 60):
            v = c.health_check(True, step=step)
            if v.admitted:
                admitted_verdicts.append(v)
                return v
            time.sleep(0.05)
        raise AssertionError("never admitted the joiner")

    out, errs = run_threads([
        (lambda c=ms[0]: member(c)),
        (lambda c=ms[1]: member(c)),
        joiner,
    ])
    assert not any(errs), errs
    view, _context = out[2]
    assert view.members == (0, 1, 7) and view.epoch == 2
    assert ms[0].members == (0, 1, 7) and ms[0].epoch == 2
    # epoch ledger: e1 = the shrink, e2 = the admission
    rec1 = json.loads(transport.get(f"{ms[0]._ns}/decided/e1", 0.1))
    rec2 = json.loads(transport.get(f"{ms[0]._ns}/decided/e2", 0.1))
    assert rec1["delta"] == {"added": [], "removed": [2]}
    assert rec2["delta"] == {"added": [7], "removed": []}


def test_drain_commits_planned_shrink_without_timeout():
    """A member announcing ``draining=True`` (spot SIGTERM with a grace
    deadline) triggers the shrink at THAT sync: survivors commit epoch+1
    immediately — no peer-timeout window burned against the kill — and
    the drainer's verdict (`self_draining`) tells it to save and exit."""
    _, ms = make_members(3, timeout_s=5.0)
    t0 = time.monotonic()
    out, errs = run_threads([
        (lambda c=ms[0]: c.health_check(True, step=1)),
        (lambda c=ms[1]: c.health_check(True, step=1)),
        (lambda c=ms[2]: c.health_check(True, step=1, draining=True)),
    ])
    elapsed = time.monotonic() - t0
    assert not any(errs), errs
    drainer = out[2]
    assert drainer.self_draining and drainer.drained == (2,)
    assert drainer.epoch == 0  # its membership is frozen; it only exits
    for v in out[:2]:
        assert v.reconfigured and v.membership_changed and not v.ok
        assert v.lost == (2,) and v.drained == (2,)
        assert v.epoch == 1 and v.members == (0, 1)
        assert not v.self_draining
    # planned means FAST: nothing waited out the 5s exchange deadline
    assert elapsed < 4.0, elapsed
    # survivors continue in lockstep at the committed epoch
    post, errs = run_threads([
        (lambda c=ms[0]: c.exchange("post", "a")),
        (lambda c=ms[1]: c.exchange("post", "b")),
    ])
    assert not any(errs) and post[0] == ["a", "b"]


def test_rejoin_times_out_on_dead_fleet(tmp_path):
    c = M.ElasticCluster(rank=1, members=[0, 1],
                         transport=CL.FileTransport(str(tmp_path)),
                         timeout_s=0.2)
    with pytest.raises(CL.ClusterError, match="not admitted"):
        c.rejoin(0, timeout_s=0.5)
    # the stale request was withdrawn — a later fleet won't admit a ghost
    with pytest.raises(CL.PeerTimeout):
        c._transport.get(f"{c._ns}/rejoin/req/1", 0.1)


# -- transports ---------------------------------------------------------------


def test_file_transport_roundtrip(tmp_path):
    t = CL.FileTransport(str(tmp_path))
    t.set("a/b/c", "v1")
    assert t.get("a/b/c", 0.1) == "v1"
    t.set("a/b/c", "v2")  # atomic overwrite
    assert t.get("a/b/c", 0.1) == "v2"
    with pytest.raises(CL.PeerTimeout):
        t.get("a/b/missing", 0.1)
    t.delete("a/b/c")
    with pytest.raises(CL.PeerTimeout):
        t.get("a/b/c", 0.1)
    t.set("sub/tree/x", "1")
    t.set("sub/tree/y", "2")
    t.prune_prefix("sub")
    with pytest.raises(CL.PeerTimeout):
        t.get("sub/tree/x", 0.1)


def test_file_transport_barrier_contract(tmp_path):
    t = CL.FileTransport(str(tmp_path))
    with pytest.raises(CL.ClusterError, match="index/num_processes"):
        t.barrier("b", 0.1)
    t0 = CL.FileTransport(str(tmp_path), index=0, num_processes=2)
    t1 = CL.FileTransport(str(tmp_path), index=1, num_processes=2)
    _, errs = run_threads([lambda: t0.barrier("b", 5), lambda: t1.barrier("b", 5)])
    assert not any(errs)


def test_file_transport_store_survives_instance_loss(tmp_path):
    """The property rank relaunch needs: a NEW ElasticCluster instance
    (fresh process, same stable rank) lands in the same key space."""
    t = CL.FileTransport(str(tmp_path))
    first = M.ElasticCluster(rank=0, world=2, transport=t, timeout_s=0.5)
    first._transport.set(f"{first._ns}/rejoin/req/1", "ghost")
    del first
    again = M.ElasticCluster(rank=0, world=2,
                             transport=CL.FileTransport(str(tmp_path)),
                             timeout_s=0.5)
    assert again._transport.get(f"{again._ns}/rejoin/req/1", 0.1) == "ghost"


def test_superseded_epoch_gc_is_deferred(tmp_path):
    """The split-brain regression: committing a new epoch must NOT prune
    the old epoch's keys immediately — a slow-but-alive peer may still be
    reading them (it commits only after finishing that gather). The GC
    runs after the first COMPLETED exchange at the new epoch."""
    t = CL.FileTransport(str(tmp_path))
    _, ms = make_members(3, t, timeout_s=0.5)
    out, errs = run_threads([
        (lambda c=ms[0]: c.health_check(True, step=1)),
        (lambda c=ms[1]: c.health_check(True, step=1)),
    ])
    assert not any(errs) and ms[0].epoch == 1
    # the e0 health keys are still readable right after the commit
    assert t.get(f"{ms[0]._ns}/e0/health/0/0", 0.1)
    out, errs = run_threads([
        (lambda c=ms[0]: c.exchange("x", "a")),
        (lambda c=ms[1]: c.exchange("x", "b")),
    ])
    assert not any(errs)
    # ...and swept once an epoch-1 exchange completed on this rank
    with pytest.raises(CL.PeerTimeout):
        t.get(f"{ms[0]._ns}/e0/health/0/0", 0.1)


def test_elastic_cluster_accepts_file_transport_string(tmp_path):
    c = M.ElasticCluster(rank=0, world=1,
                         transport=f"file:{tmp_path}", timeout_s=0.5)
    assert isinstance(c._transport, CL.FileTransport)
    with pytest.raises(ValueError, match="explicit transport"):
        M.ElasticCluster(rank=0, world=2, transport=None)


def test_from_env_contract(tmp_path, monkeypatch):
    monkeypatch.setenv(M.ELASTIC_DIR_ENV, str(tmp_path))
    monkeypatch.setenv(M.ELASTIC_RANK_ENV, "1")
    monkeypatch.setenv(M.ELASTIC_WORLD_ENV, "3")
    monkeypatch.delenv(M.ELASTIC_REJOIN_ENV, raising=False)
    c = M.ElasticCluster.from_env()
    assert (c.rank, c.world, c.epoch) == (1, 3, 0)
    assert isinstance(c._transport, CL.FileTransport)
    assert not M.ElasticCluster.rejoining_by_env()
    monkeypatch.setenv(M.ELASTIC_REJOIN_ENV, "1")
    assert M.ElasticCluster.rejoining_by_env()
    monkeypatch.delenv(M.ELASTIC_DIR_ENV)
    with pytest.raises(CL.ClusterError, match="supervisor contract"):
        M.ElasticCluster.from_env()


def test_current_epoch_tracks_live_cluster():
    _, (c,) = make_members(1)
    assert M.current_epoch() == 0
    c._commit(3, [0])
    assert M.current_epoch() == 3


# -- member-scoped consensus restore ------------------------------------------


def test_consensus_restore_is_member_scoped():
    _, ms = make_members(3, timeout_s=1.0)
    run_threads([  # shrink to {0, 1} first
        (lambda c=ms[0]: c.health_check(True, step=1)),
        (lambda c=ms[1]: c.health_check(True, step=1)),
    ])
    views = {0: [12, 8, 4], 1: [8, 4]}
    out, errs = run_threads([
        (lambda c=ms[0]: c.consensus_restore_step(views[0])),
        (lambda c=ms[1]: c.consensus_restore_step(views[1])),
    ])
    assert not any(errs)
    assert out == [8, 8]  # newest step valid on every SURVIVOR


def test_consensus_restore_survives_second_failure():
    """A member lost DURING the restore exchange is reconfigured out and
    the exchange retried over the survivors — a second failure cannot
    deadlock the first one's repair."""
    _, ms = make_members(3, timeout_s=0.5)
    os.environ[CL.RESTORE_TIMEOUT_ENV] = "0.5"
    try:
        out, errs = run_threads([
            (lambda c=ms[0]: c.consensus_restore_step([8, 4])),
            (lambda c=ms[1]: c.consensus_restore_step([8])),
        ])
    finally:
        os.environ.pop(CL.RESTORE_TIMEOUT_ENV, None)
    assert not any(errs), errs
    assert out == [8, 8]
    assert ms[0].epoch == 1 and ms[0].members == (0, 1)


# -- epoch-stamped plans + checkpoint compat ----------------------------------


def _plan(world=4):
    params = {"a": np.zeros((6, 4), np.float32),
              "b": np.zeros((8,), np.float32)}
    return F.make_plan(params, world=world, threshold_mb=0.00002)


def test_rescale_plan_preserves_grouping_and_stamps_epoch():
    plan = _plan(world=4)
    out = F.rescale_plan(plan, 2, epoch=1)
    assert out.world == 2 and out.epoch == 1
    assert [b.leaf_ids for b in out.buckets] == \
        [b.leaf_ids for b in plan.buckets]
    assert all(b.padded_size % 2 == 0 for b in out.buckets)
    # no-op fast path
    assert F.rescale_plan(out, 2, epoch=1) is out


def test_plan_fingerprint_separates_epochs_not_epoch_zero():
    plan = _plan(world=4)
    assert plan.epoch == 0
    import dataclasses
    stamped = dataclasses.replace(plan, epoch=3)
    # same world+layout, different membership epoch -> different restore
    # identity; epoch 0 keeps the pre-elastic fingerprint byte-for-byte
    assert ckpt.plan_fingerprint(stamped) != ckpt.plan_fingerprint(plan)
    assert ckpt.plan_fingerprint(plan) == ckpt.plan_fingerprint(
        F.rescale_plan(stamped, 4, epoch=0))


def test_plan_desc_roundtrips_epoch():
    plan = F.rescale_plan(_plan(world=4), 2, epoch=5)
    desc = ckpt.plan_desc(plan)
    assert desc["epoch"] == 5
    rebuilt = ckpt.plan_from_desc(desc, plan.treedef)
    assert rebuilt.epoch == 5 and rebuilt.world == 2
    assert ckpt.plan_fingerprint(rebuilt) == ckpt.plan_fingerprint(plan)


def test_autotuner_rescale_carries_state_across_worlds(tmp_path, mesh):
    """The guard's on_membership_change hook: rebuild for the shrunk
    world with the epoch stamped, carrying live state (repack), and a
    restore of a pre-shrink checkpoint re-packs through elastic_restore
    instead of silently unpacking the wrong layout."""
    from dear_pytorch_tpu.ops.fused_sgd import fused_sgd
    from dear_pytorch_tpu.tuning.autotune import AutoTuner

    from tests.test_dear_numerics import _data, _loss_fn, _mlp_params

    params = _mlp_params(jax.random.PRNGKey(0))
    devs = list(mesh.devices.flat)
    tuner = AutoTuner(
        _loss_fn, params, strategy="bo", threshold_mb=0.0008,
        interval=10**9, donate=False,
        mesh=jax.sharding.Mesh(np.asarray(devs[:4]), ("dp",)),
        optimizer=fused_sgd(lr=0.05, momentum=0.9),
    )
    assert tuner.ts.plan.world == 4 and tuner.ts.plan.epoch == 0
    state = tuner.init(params)
    for i in range(3):
        state, m = tuner.step(state, _data(jax.random.PRNGKey(i), n=8))
    ckpt.save_checkpoint(str(tmp_path), state, tuner.ts.plan)
    pre_loss = float(m["loss"])

    view = M.MembershipView(epoch=1, members=(0, 2), rank=0, index=0,
                            world=2)
    state = tuner.rescale(view, state=state)
    assert tuner.ts.plan.world == 2 and tuner.ts.plan.epoch == 1
    assert int(jax.device_get(state.step)) == 3  # carried across
    step3_kernel = np.asarray(jax.device_get(
        F.unpack_all(list(state.buffers), tuner.ts.plan)["out"]["kernel"]))
    state, m = tuner.step(state, _data(jax.random.PRNGKey(9), n=8))
    assert np.isfinite(float(m["loss"])), pre_loss

    # the world-4 epoch-0 checkpoint no longer matches the live plan...
    with pytest.raises(ValueError, match="packed under plan"):
        ckpt.restore_checkpoint(str(tmp_path), tuner.ts, step=3,
                                template=tuner.ts.init(params))
    # ...and elastic_restore re-packs it into the rescaled layout,
    # reproducing the step-3 values the repacked live state held before
    # it advanced
    restored = ckpt.elastic_restore(str(tmp_path), tuner.ts, step=3)
    assert int(jax.device_get(restored.step)) == 3
    rparams = F.unpack_all(list(restored.buffers), tuner.ts.plan)
    np.testing.assert_allclose(
        np.asarray(jax.device_get(rparams["out"]["kernel"])),
        step3_kernel, atol=1e-5)


def test_autotuner_rescale_failure_keeps_previous_plan(mesh, monkeypatch):
    from dear_pytorch_tpu.ops.fused_sgd import fused_sgd
    from dear_pytorch_tpu.tuning import autotune as AT

    from tests.test_dear_numerics import _loss_fn, _mlp_params

    tracer = T.Tracer([T.MemoryExporter()])
    prev = T._tracer
    T.set_tracer(tracer)
    try:
        params = _mlp_params(jax.random.PRNGKey(0))
        tuner = AT.AutoTuner(
            _loss_fn, params, strategy="bo", threshold_mb=0.0008,
            interval=10**9, mesh=mesh, donate=False,
            optimizer=fused_sgd(lr=0.05, momentum=0.9),
        )
        before = tuner.ts
        # precondition failures (not enough devices) raise up front
        view99 = M.MembershipView(epoch=1, members=tuple(range(99)),
                                  rank=0, index=0, world=99)
        with pytest.raises(ValueError, match="needs 99 devices"):
            tuner.rescale(view99)
        # a failing REBUILD is sandboxed like a BO trial: counted, and
        # the previous train step stays installed
        def boom(*a, **k):
            raise RuntimeError("compile exploded")

        monkeypatch.setattr(AT.D, "build_train_step", boom)
        view = M.MembershipView(epoch=1, members=(0, 1), rank=0, index=0,
                                world=2)
        with pytest.raises(RuntimeError, match="compile exploded"):
            tuner.rescale(view)
        assert tuner.ts is before  # sandboxed: nothing half-swapped
        assert tuner.ts.plan.epoch == 0
        assert tracer.counters().get("autotune.rescale_failures", 0) == 1
    finally:
        T.set_tracer(prev)


def test_sidecar_mem_epoch_and_pipeline_state(tmp_path, mesh):
    from dear_pytorch_tpu.ops.fused_sgd import fused_sgd
    from dear_pytorch_tpu.parallel import build_train_step

    from tests.test_dear_numerics import _data, _loss_fn, _mlp_params

    params = _mlp_params(jax.random.PRNGKey(0))
    ts = build_train_step(
        _loss_fn, params, mesh=mesh, threshold_mb=0.0008, donate=False,
        optimizer=fused_sgd(lr=0.05, momentum=0.9),
    )
    state = ts.init(params)
    state, _ = ts.step(state, _data(jax.random.PRNGKey(0)))
    pstate = {"backend": "numpy", "produced": 1}
    ckpt.save_checkpoint(str(tmp_path), state, ts.plan,
                         pipeline_state=pstate, mem_epoch=4)
    assert ckpt.read_mem_epoch(str(tmp_path), 1) == 4
    assert ckpt.read_pipeline_state(str(tmp_path), 1) == pstate
    assert ckpt.read_sidecar(str(tmp_path), 99) is None
    assert ckpt.read_mem_epoch(str(tmp_path), 99) is None


def test_prune_future_steps(tmp_path, mesh):
    """After a restore to an older step, newer checkpoints are a dead
    timeline: replayed saves would collide with them and a later restore
    could resurrect them (split-brain across members)."""
    from dear_pytorch_tpu.ops.fused_sgd import fused_sgd
    from dear_pytorch_tpu.parallel import build_train_step

    from tests.test_dear_numerics import _data, _loss_fn, _mlp_params

    params = _mlp_params(jax.random.PRNGKey(0))
    ts = build_train_step(
        _loss_fn, params, mesh=mesh, threshold_mb=0.0008, donate=False,
        optimizer=fused_sgd(lr=0.05, momentum=0.9),
    )
    state = ts.init(params)
    for i in range(3):
        state, _ = ts.step(state, _data(jax.random.PRNGKey(i)))
        ckpt.save_checkpoint(str(tmp_path), state, ts.plan)
    assert ckpt.valid_steps(str(tmp_path)) == [3, 2, 1]
    assert ckpt.prune_future_steps(str(tmp_path), above=1) == [3, 2]
    assert ckpt.valid_steps(str(tmp_path)) == [1]
    assert not os.path.exists(os.path.join(str(tmp_path),
                                           "meta_0000000003.json"))
    assert ckpt.prune_future_steps(str(tmp_path), above=1) == []


# -- pipeline: deterministic resume + elastic resharding ----------------------


def _spec(batch=4):
    return P.SyntheticSpec((
        P.Field("x", (batch, 3), RB.KIND_NORMAL_F32, 0.0, 1.0),
        P.Field("label", (batch,), RB.KIND_UNIFORM_I32, 0, 10),
    ))


def test_numpy_pipeline_state_roundtrip_is_bit_exact():
    p = P.NumpyPipeline(_spec(), seed=3)
    for _ in range(3):
        p.next()
    snap = p.state_dict()
    assert snap["exact"] and snap["produced"] == 3
    expect = [p.next() for _ in range(2)]
    p.load_state_dict(snap)
    assert p.produced == 3
    replay = [p.next() for _ in range(2)]
    for a, b in zip(expect, replay):
        np.testing.assert_array_equal(a["x"], b["x"])
        np.testing.assert_array_equal(a["label"], b["label"])
    # a FRESH pipeline (relaunched rank) resumes the same stream
    q = P.NumpyPipeline(_spec(), seed=3)
    q.load_state_dict(snap)
    replay2 = [q.next() for _ in range(2)]
    np.testing.assert_array_equal(expect[0]["x"], replay2[0]["x"])


def test_pipeline_state_rejects_spec_mismatch():
    p = P.NumpyPipeline(_spec(), seed=3)
    snap = p.state_dict()
    q = P.NumpyPipeline(_spec(batch=8), seed=3)
    with pytest.raises(ValueError, match="different batch spec"):
        q.load_state_dict(snap)


def test_native_resume_does_not_replay_the_stream():
    """The native backend cannot seek, so a resume reseeds — but the
    reseed must be POSITION-dependent: restoring produced=N and then
    restarting the position-0 stream would silently replay batches
    0..N-1 (the exact bug this PR's pipeline sidecars exist to fix)."""
    if not P.native_available():
        pytest.skip("native runtime library unavailable"
                    f" ({RB.load_error()})")
    p = P.Pipeline(_spec(), seed=3, nthreads=1)
    first = p.next()["x"].copy()
    for _ in range(2):
        p.next()
    snap = p.state_dict()
    # the recorded position is the CONSUMED count — the async producers
    # run ahead, but prefilled-yet-unfetched slots are not position
    assert not snap["exact"] and snap["produced"] == 3
    q = P.Pipeline(_spec(), seed=3, nthreads=1)
    q.load_state_dict(snap)
    assert q.produced == 3
    resumed = [q.next()["x"] for _ in range(3)]
    # none of the next batches is the original stream's batch 0
    assert all(not np.array_equal(first, r) for r in resumed)
    # ...and the position-seeded resume is itself deterministic: a second
    # fresh consumer restoring the same sidecar draws the same stream
    r = P.Pipeline(_spec(), seed=3, nthreads=1)
    r.load_state_dict(snap)
    np.testing.assert_array_equal(resumed[0], r.next()["x"])
    p.close(), q.close(), r.close()


def test_native_reshard_does_not_double_count_position():
    """`produced` already includes the resume offset, so consecutive
    recreates (resume -> reshard -> reshard, the shrink-then-rejoin
    sequence) must ASSIGN the new position, not accumulate it — the old
    `+=` doubled every pre-reshard segment, skipping data and persisting
    a compounding-wrong position in later sidecars."""
    if not P.native_available():
        pytest.skip("native runtime library unavailable"
                    f" ({RB.load_error()})")
    p = P.Pipeline(_spec(), seed=3, nthreads=1)
    snap = p.state_dict()
    snap["produced"] = 100  # a long-running stream's checkpoint
    q = P.Pipeline(_spec(), seed=3, nthreads=1)
    q.load_state_dict(snap)
    q.reshard(0, 2, epoch=1)
    q.reshard(0, 3, epoch=2)
    # nothing was consumed, so the position is exactly the restored one
    # (the += bug compounded to >= 300 here; a producer-count-based
    # position drifted by ~nslots per recreate)
    assert q.produced == 100, q.produced
    p.close(), q.close()


def test_numpy_resume_of_native_sidecar_does_not_replay():
    """A native-written sidecar restored on the numpy fallback (the .so
    stopped loading on relaunch) has no PRNG state: the resume must
    position-seed rather than silently replay from batch 0."""
    p = P.NumpyPipeline(_spec(), seed=3)
    first = p.next()["x"].copy()
    snap = p.state_dict()
    snap["backend"], snap["exact"] = "native", False
    snap["produced"] = 3
    del snap["rng"]
    q = P.NumpyPipeline(_spec(), seed=3)
    q.load_state_dict(snap)
    assert q.produced == 3
    assert not np.array_equal(first, q.next()["x"])


def test_reshard_to_larger_world_is_deterministic():
    """Scale-UP reshard: growing the shard count is the same pure
    function of (seed, epoch, slot, world) — survivors recompute their
    new slice and a brand-new joiner derives ITS slice with no
    coordination, all streams disjoint."""
    a = P.NumpyPipeline(_spec(), seed=11, shard=0, num_shards=2)
    b = P.NumpyPipeline(_spec(), seed=11, shard=1, num_shards=2)
    a.next(), b.next()
    a.reshard(0, 3, epoch=1)
    b.reshard(1, 3, epoch=1)
    c = P.NumpyPipeline(_spec(), seed=11)   # the scale-up joiner
    c.reshard(2, 3, epoch=1)
    xa, xb, xc = a.next()["x"], b.next()["x"], c.next()["x"]
    assert not np.array_equal(xa, xb)
    assert not np.array_equal(xa, xc)
    assert not np.array_equal(xb, xc)
    # any rank recomputing slot 2 draws exactly the joiner's stream
    d = P.NumpyPipeline(_spec(), seed=11)
    d.reshard(2, 3, epoch=1)
    np.testing.assert_array_equal(xc, d.next()["x"])


def test_reshard_is_a_pure_function_of_assignment():
    a = P.NumpyPipeline(_spec(), seed=11, shard=0, num_shards=3)
    b = P.NumpyPipeline(_spec(), seed=11, shard=1, num_shards=3)
    xa, xb = a.next()["x"], b.next()["x"]
    assert not np.array_equal(xa, xb)  # disjoint shard streams
    # survivors recompute the identical post-shrink assignment
    a.reshard(0, 2, epoch=1)
    b.reshard(0, 2, epoch=1)
    np.testing.assert_array_equal(a.next()["x"], b.next()["x"])
    assert a.shard == 0 and a.num_shards == 2
    # a different slot of the same epoch draws a different stream
    b.reshard(1, 2, epoch=1)
    assert not np.array_equal(a.next()["x"], b.next()["x"])


def test_default_assignment_is_byte_compatible_with_pre_elastic():
    plain = np.random.default_rng(7)  # what the pre-elastic backend drew
    p = P.NumpyPipeline(_spec(), seed=7)  # shard 0 of 1, epoch 0
    np.testing.assert_array_equal(
        p.next()["x"], plain.normal(0.0, 1.0, (4, 3)).astype(np.float32))


def test_pipeline_counters_fire():
    tracer = T.Tracer([T.MemoryExporter()])
    prev = T._tracer
    T.set_tracer(tracer)
    try:
        p = P.NumpyPipeline(_spec(), seed=1)
        p.reshard(1, 4, epoch=2)
        p.load_state_dict(p.state_dict())
        c = tracer.counters()
        assert c.get("pipeline.reshards") == 1
        assert c.get("pipeline.resumes") == 1
    finally:
        T.set_tracer(prev)


# -- retry: decorrelated jitter + elapsed budget ------------------------------


def test_jitter_is_deterministic_per_label_and_decorrelated():
    def delays_for(label):
        sleeps = []
        calls = {"n": 0}

        def flaky():
            calls["n"] += 1
            if calls["n"] < 5:
                raise OSError("transient")
            return "ok"

        assert R.retry_call(flaky, attempts=5, name=label,
                            base_delay_s=0.01, max_delay_s=10.0,
                            sleep=sleeps.append) == "ok"
        return sleeps

    a1, a2 = delays_for("siteA"), delays_for("siteA")
    b = delays_for("siteB")
    assert a1 == a2, "same (rank, label) must replay the same schedule"
    assert a1 != b, "different call sites must decorrelate"
    assert all(d >= 0.01 for d in a1)


def test_jitter_off_restores_legacy_exponential():
    sleeps = []
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 4:
            raise OSError("x")
        return 1

    R.retry_call(flaky, attempts=4, jitter=False, base_delay_s=0.05,
                 backoff=2.0, max_delay_s=2.0, sleep=sleeps.append)
    assert sleeps == [0.05, 0.1, 0.2]


def test_elapsed_budget_caps_total_time():
    sleeps = []

    def always_fails():
        raise OSError("down")

    with pytest.raises(R.RetryError, match="budget"):
        R.retry_call(always_fails, attempts=100, jitter=False,
                     base_delay_s=0.2, max_delay_s=5.0,
                     max_elapsed_s=0.3, sleep=sleeps.append)
    # the loop stopped when the NEXT sleep would cross the budget —
    # far short of the 100-attempt allowance
    assert len(sleeps) <= 2


# -- the guard's membership-transition path (scripted coordinator) ------------


class _ElasticStub:
    """Scripts one shrink verdict against a single-process guard: the
    coordinated membership branches (hook order, reshard-after-restore,
    sidecar epochs) are unit-testable without threads or processes."""

    max_candidates = 16

    def __init__(self):
        self.epoch = 0
        self.members = (0, 1, 2)
        self.rank = 0
        self.shrink_at = None
        self.restore_calls = 0

    @property
    def process_count(self):
        return len(self.members)

    @property
    def index(self):
        return self.members.index(self.rank)

    def view(self):
        return M.MembershipView(epoch=self.epoch, members=self.members,
                                rank=self.rank, index=self.index,
                                world=len(self.members))

    def health_check(self, ok, *, fingerprint="", step=None,
                     preempted=False):
        if step == self.shrink_at:
            self.epoch += 1
            self.members = (0, 1)
            return M.ElasticVerdict(
                ok=False, unhealthy_ranks=(), desync=False,
                any_preempted=False, fingerprints=(), epoch=self.epoch,
                members=self.members, reconfigured=True, lost=(2,))
        return M.ElasticVerdict(
            ok=ok, unhealthy_ranks=() if ok else (0,), desync=False,
            any_preempted=False, fingerprints=(fingerprint,),
            epoch=self.epoch, members=self.members)

    #: when set, the FIRST consensus_restore_step call commits another
    #: shrink mid-exchange (a second failure during the restore — the
    #: elastic cluster retries the exchange over the survivors)
    restore_bumps_to = None

    def consensus_restore_step(self, local_steps):
        self.restore_calls += 1
        if self.restore_bumps_to is not None:
            self.epoch, self.members = self.restore_bumps_to
            self.restore_bumps_to = None
        return max(local_steps) if local_steps else None


def test_guard_membership_transition_order(tmp_path, mesh):
    """On a membership_changed verdict the guard must: run the hook
    (plan rescale) BEFORE the restore, restore the pipeline sidecar
    state, reshard AFTER the restore, stamp later sidecars with the new
    epoch, and count guard.membership_changes."""
    from dear_pytorch_tpu.ops.fused_sgd import fused_sgd
    from dear_pytorch_tpu.parallel import build_train_step
    from dear_pytorch_tpu.utils.guard import GuardedTrainer

    from tests.test_dear_numerics import _data, _loss_fn, _mlp_params

    tracer = T.Tracer([T.MemoryExporter()])
    prev = T._tracer
    T.set_tracer(tracer)
    try:
        params = _mlp_params(jax.random.PRNGKey(0))
        ts = build_train_step(
            _loss_fn, params, mesh=mesh, threshold_mb=0.0008,
            donate=False, optimizer=fused_sgd(lr=0.05, momentum=0.9),
        )
        co = _ElasticStub()
        co.shrink_at = 6
        pipe = P.NumpyPipeline(_spec(), seed=5, shard=0, num_shards=3)
        events = []
        guard = GuardedTrainer(
            ts, str(tmp_path / "g"), params, check_every=1,
            checkpoint_every=4, coordinator=co, pipeline=pipe,
            on_membership_change=lambda v: events.append(("hook", v)),
        )
        guard.on_rollback = lambda c, at: events.append(("rollback", at))
        state = ts.init(params)
        for i in range(8):
            state, m = guard.step(state, _data(jax.random.PRNGKey(i)))
        # hook BEFORE the rollback's restore, with the committed view
        assert [e[0] for e in events] == ["hook", "rollback"]
        assert events[0][1].epoch == 1 and events[0][1].world == 2
        assert events[1][1] == 4 and co.restore_calls == 1
        # pipeline: sidecar resume first, then the epoch-1 reshard
        assert pipe.shard == 0 and pipe.num_shards == 2
        assert pipe._epoch == 1
        c = tracer.counters()
        assert c.get("guard.membership_changes") == 1
        assert c.get("pipeline.resumes") == 1
        assert c.get("pipeline.reshards") == 1
        # post-transition checkpoints carry the new epoch in the sidecar.
        # Cadence: the transition fired at attempt 6 (rollback to step 4),
        # so attempts 7-8 advance the state to step 6, where the
        # checkpoint_every=4 cadence (attempt 8) persists it.
        assert ckpt.read_mem_epoch(str(tmp_path / "g"), 6) == 1
        pstate = ckpt.read_pipeline_state(str(tmp_path / "g"), 6)
        assert pstate["num_shards"] == 2 and pstate["epoch"] == 1
    finally:
        T.set_tracer(prev)


def test_guard_second_failure_during_restore_rebuilds_again(tmp_path, mesh):
    """A membership move committed INSIDE the consensus-restore exchange
    (second failure mid-recovery) must re-fire the transition hook with
    the newest view before unpacking — otherwise the restore lands in a
    plan built for a membership that no longer exists and later sidecars
    stamp an epoch the plan doesn't carry."""
    from dear_pytorch_tpu.ops.fused_sgd import fused_sgd
    from dear_pytorch_tpu.parallel import build_train_step
    from dear_pytorch_tpu.utils.guard import GuardedTrainer

    from tests.test_dear_numerics import _data, _loss_fn, _mlp_params

    tracer = T.Tracer([T.MemoryExporter()])
    prev = T._tracer
    T.set_tracer(tracer)
    try:
        params = _mlp_params(jax.random.PRNGKey(0))
        ts = build_train_step(
            _loss_fn, params, mesh=mesh, threshold_mb=0.0008,
            donate=False, optimizer=fused_sgd(lr=0.05, momentum=0.9),
        )
        co = _ElasticStub()
        co.shrink_at = 6
        co.restore_bumps_to = (2, (0,))  # second shrink mid-restore
        pipe = P.NumpyPipeline(_spec(), seed=5, shard=0, num_shards=3)
        hooks = []
        guard = GuardedTrainer(
            ts, str(tmp_path / "g"), params, check_every=1,
            checkpoint_every=4, coordinator=co, pipeline=pipe,
            on_membership_change=lambda v: hooks.append(v),
        )
        state = ts.init(params)
        for i in range(8):
            state, m = guard.step(state, _data(jax.random.PRNGKey(i)))
        # the hook fired TWICE: the health-sync shrink, then the
        # mid-restore one with the even-newer view
        assert [(v.epoch, v.world) for v in hooks] == [(1, 2), (2, 1)]
        # the pipeline landed on the FINAL view, not the intermediate one
        assert pipe.num_shards == 1 and pipe._epoch == 2
        assert tracer.counters().get("guard.membership_changes") == 2
        # post-transition sidecars agree with the final epoch
        assert ckpt.read_mem_epoch(str(tmp_path / "g"), 6) == 2
    finally:
        T.set_tracer(prev)


def test_guard_elastic_resume_aligns_cadence(tmp_path, mesh):
    """The rejoiner's re-entry: elastic_resume restores through the SAME
    consensus exchange, re-seats the pipeline, and adopts the fleet's
    attempt cadence from the admission ack."""
    from dear_pytorch_tpu.ops.fused_sgd import fused_sgd
    from dear_pytorch_tpu.parallel import build_train_step
    from dear_pytorch_tpu.utils.guard import GuardedTrainer

    from tests.test_dear_numerics import _data, _loss_fn, _mlp_params

    params = _mlp_params(jax.random.PRNGKey(0))
    ts = build_train_step(
        _loss_fn, params, mesh=mesh, threshold_mb=0.0008, donate=False,
        optimizer=fused_sgd(lr=0.05, momentum=0.9),
    )
    co = _ElasticStub()
    guard = GuardedTrainer(
        ts, str(tmp_path / "g"), params, check_every=1,
        checkpoint_every=2, coordinator=co,
        pipeline=P.NumpyPipeline(_spec(), seed=5),
    )
    state = ts.init(params)
    for i in range(4):
        state, _ = guard.step(state, _data(jax.random.PRNGKey(i)))
    co.epoch, co.members = 2, (0, 1)  # "admitted at epoch 2"
    state, step = guard.elastic_resume({"steps_seen": 11})
    assert step == 4 and guard.steps_seen == 11
    assert int(jax.device_get(state.step)) == 4
    assert guard._last_good_step == 4
    # the loop continues from the fleet's cadence
    state, m = guard.step(state, _data(jax.random.PRNGKey(11)))
    assert guard.steps_seen == 12 and np.isfinite(float(m["loss"]))


# -- object store + durable checkpoint streaming ------------------------------


def test_local_object_store_roundtrip(tmp_path):
    st = LocalObjectStore(str(tmp_path / "store"))
    st.put_bytes("a/b/obj", b"hello")
    assert st.get_bytes("a/b/obj") == b"hello"
    st.put_bytes("a/b/obj", b"hello2")  # atomic overwrite
    assert st.get_bytes("a/b/obj") == b"hello2"
    with pytest.raises(KeyError):
        st.get_bytes("a/missing")
    src = tmp_path / "payload.bin"
    src.write_bytes(b"\x00\x01\x02")
    st.put_file("files/payload", str(src))
    dest = tmp_path / "out" / "payload.bin"
    st.get_file("files/payload", str(dest))
    assert dest.read_bytes() == b"\x00\x01\x02"
    assert st.exists("files/payload") and not st.exists("files/nope")
    assert st.list("a") == ["a/b/obj"]
    st.delete_prefix("a")
    assert st.list("a") == []


def _saved_run(tmp_path, mesh, n=3):
    from dear_pytorch_tpu.ops.fused_sgd import fused_sgd
    from dear_pytorch_tpu.parallel import build_train_step

    from tests.test_dear_numerics import _data, _loss_fn, _mlp_params

    params = _mlp_params(jax.random.PRNGKey(0))
    ts = build_train_step(
        _loss_fn, params, mesh=mesh, threshold_mb=0.0008, donate=False,
        optimizer=fused_sgd(lr=0.05, momentum=0.9),
    )
    state = ts.init(params)
    for i in range(n):
        state, _ = ts.step(state, _data(jax.random.PRNGKey(i)))
        ckpt.save_checkpoint(str(tmp_path), state, ts.plan,
                             pipeline_state={"backend": "numpy",
                                             "produced": i + 1},
                             mem_epoch=0)
    return ts, params, state


def test_checkpoint_streamer_uploads_and_cold_restores(tmp_path, mesh):
    """The durable tier end to end: committed steps stream to the object
    store (manifest last), remote retention pins the newest K, and a
    machine with NO local checkpoints restores the newest upload —
    sha256-reverified — through the ordinary local restore path."""
    tracer = T.Tracer([T.MemoryExporter()])
    prev = T._tracer
    T.set_tracer(tracer)
    try:
        local = tmp_path / "ckpts"
        ts, params, _state = _saved_run(local, mesh)
        store = LocalObjectStore(str(tmp_path / "remote"))
        with ckpt.CheckpointStreamer(str(local), store,
                                     pin_last=2) as streamer:
            for s in (1, 2, 3):
                assert streamer.enqueue(s)
            assert streamer.flush(30.0)
        assert streamer.uploaded == [1, 2, 3] and not streamer.failed
        # last-K pinned retention: step 1 rotated out remotely
        assert ckpt.remote_steps(store) == [3, 2]
        c = tracer.counters()
        assert c.get("ckpt.uploads") == 3
        assert "ckpt.upload_errors" not in c

        cold = tmp_path / "cold"
        restored = ckpt.restore_from_object_store(store, str(cold))
        assert restored == 3
        assert ckpt.verify_checkpoint(str(cold), 3)
        assert ckpt.read_pipeline_state(str(cold), 3)["produced"] == 3
        assert ckpt.read_mem_epoch(str(cold), 3) == 0
        state = ckpt.restore_checkpoint(str(cold), ts, step=3,
                                        template=ts.init(params))
        assert int(jax.device_get(state.step)) == 3
        assert tracer.counters().get("ckpt.remote_restores") == 1
    finally:
        T.set_tracer(prev)


def test_streamer_upload_every_and_archive_cadence(tmp_path, mesh):
    local = tmp_path / "ckpts"
    _saved_run(local, mesh, n=4)
    store = LocalObjectStore(str(tmp_path / "remote"))
    with ckpt.CheckpointStreamer(str(local), store, upload_every=2,
                                 pin_last=2, keep_every=4) as streamer:
        assert not streamer.enqueue(1)   # off the every-Nth cadence
        assert streamer.enqueue(2)
        # an EMERGENCY save must reach the durable tier no matter where
        # it lands relative to the cadence (uploads stay chronological:
        # the emergency step is always the newest at signal time)
        assert streamer.enqueue(3, force=True)
        assert streamer.enqueue(4)
        assert streamer.flush(30.0)
    # pin_last=2 keeps the newest two uploads BY STEP (4, 3); step 2
    # survives only on the keep_every archive cadence (2 % 4 != 0)
    assert ckpt.remote_steps(store) == [4, 3]


class _FailingStore:
    """Object store whose writes always fail (dead bucket)."""

    def __init__(self):
        self.attempts = 0

    def put_file(self, key, path):
        self.attempts += 1
        raise OSError("bucket is down")

    def put_bytes(self, key, data):
        raise OSError("bucket is down")

    def list(self, prefix):
        return []

    def delete_prefix(self, prefix):
        pass


def test_streamer_retry_exhaustion_falls_back_to_local_only(tmp_path, mesh):
    """Upload-retry exhaustion must degrade durability, not the run: the
    worker counts ``ckpt.upload_errors``, records the step as failed, and
    keeps accepting later steps — while the LOCAL checkpoints stay fully
    restorable (local-only retention)."""
    tracer = T.Tracer([T.MemoryExporter()])
    prev = T._tracer
    T.set_tracer(tracer)
    try:
        local = tmp_path / "ckpts"
        ts, params, _state = _saved_run(local, mesh)
        store = _FailingStore()
        with ckpt.CheckpointStreamer(str(local), store, attempts=3,
                                     base_delay_s=0.01,
                                     max_delay_s=0.02) as streamer:
            assert streamer.enqueue(2)
            assert streamer.flush(30.0)
            assert streamer.failed == [2] and not streamer.uploaded
            assert store.attempts == 3  # every retry actually hit the store
            # the streamer survives and keeps trying later steps
            assert streamer.enqueue(3)
            assert streamer.flush(30.0)
            assert streamer.failed == [2, 3]
        c = tracer.counters()
        assert c.get("ckpt.upload_errors") == 2
        assert c.get("retry.giveups", 0) >= 2
        # local-only retention: the run's own restore path is untouched
        state = ckpt.restore_checkpoint(str(local), ts, step=3,
                                        template=ts.init(params))
        assert int(jax.device_get(state.step)) == 3
    finally:
        T.set_tracer(prev)


def test_remote_restore_walks_past_corruption(tmp_path, mesh):
    """sha256 reverify on download: a bit-flipped remote object must not
    become a poisoned restore — the walk degrades to the previous
    upload, exactly like the local corruption-fallback walk."""
    local = tmp_path / "ckpts"
    _saved_run(local, mesh)
    root = tmp_path / "remote"
    store = LocalObjectStore(str(root))
    with ckpt.CheckpointStreamer(str(local), store, pin_last=3) as s:
        for n in (2, 3):
            s.enqueue(n)
        assert s.flush(30.0)
    # flip bytes in the newest upload's largest payload file
    files = [k for k in store.list(ckpt._remote_step_key(3))
             if "/files/" in k]
    victim = max(files, key=lambda k: len(store.get_bytes(k)))
    blob = bytearray(store.get_bytes(victim))
    blob[len(blob) // 2] ^= 0xFF
    store.put_bytes(victim, bytes(blob))
    cold = tmp_path / "cold"
    assert ckpt.restore_from_object_store(store, str(cold)) == 2
    assert ckpt.verify_checkpoint(str(cold), 2)
    # a manifest that parses but lists NO files is torn, not empty:
    # walked past like any corruption (previously crashed the restore)
    store.put_bytes(f"{ckpt._remote_step_key(3)}/MANIFEST.json",
                    json.dumps({"step": 3, "files": {}}).encode())
    cold2 = tmp_path / "cold2"
    assert ckpt.restore_from_object_store(store, str(cold2)) == 2


# -- preemption grace window --------------------------------------------------


def test_preempt_grace_window_budget(monkeypatch):
    monkeypatch.setenv("DEAR_PREEMPT_GRACE_S", "30")
    with PreemptionHandler() as pre:
        assert pre.grace_s == 30.0
        assert pre.remaining() is None  # no signal yet: no deadline
        os.kill(os.getpid(), signal.SIGTERM)
        assert pre.requested
        rem = pre.remaining()
        assert rem is not None and 0 < rem <= 30.0
        pre.clear()
        assert pre.remaining() is None  # re-arms with the next signal
    monkeypatch.delenv("DEAR_PREEMPT_GRACE_S")
    with PreemptionHandler() as pre:
        assert pre.grace_s is None
        os.kill(os.getpid(), signal.SIGTERM)
        assert pre.requested and pre.remaining() is None


# -- the capacity-driven scale policy -----------------------------------------


def _cap_writer(path):
    def write(doc):
        with open(str(path) + ".tmp", "w") as f:
            json.dump(doc, f)
        os.replace(str(path) + ".tmp", str(path))
    return write


def test_scale_policy_hysteresis_and_decisions(tmp_path):
    cap = tmp_path / "capacity.json"
    write = _cap_writer(cap)
    clk = {"t": 0.0}
    pol = SC.ScalePolicy(capacity_file=str(cap), hysteresis_s=1.0,
                         max_world=4, clock=lambda: clk["t"])
    # no file yet: no opinion
    assert pol.decide(live_world=2, live_ranks=(0, 1)) is None
    write({"target_world": 3})
    # hysteresis leg 1: the hint must hold for hysteresis_s
    assert pol.decide(live_world=2, live_ranks=(0, 1)) is None
    clk["t"] = 0.5
    assert pol.decide(live_world=2, live_ranks=(0, 1)) is None
    clk["t"] = 1.1
    d = pol.decide(live_world=2, live_ranks=(0, 1))
    assert d is not None and d.kind == "scale_up" and d.count == 1
    # a flapping hint cannot thrash: the down-hint must also hold
    write({"target_world": 2})
    clk["t"] = 1.2
    assert pol.decide(live_world=3, live_ranks=(0, 1, 2)) is None
    clk["t"] = 2.5
    d = pol.decide(live_world=3, live_ranks=(0, 1, 2))
    assert d is not None and d.kind == "scale_down" and d.ranks == (2,)
    assert [x.kind for x in pol.decisions] == ["scale_up", "scale_down"]


def test_scale_policy_explicit_drain_is_immediate(tmp_path):
    """A spot reclaim is a deadline, not a preference: explicit drain
    requests bypass the hysteresis dwell and are acted on once."""
    cap = tmp_path / "capacity.json"
    write = _cap_writer(cap)
    clk = {"t": 0.0}
    pol = SC.ScalePolicy(capacity_file=str(cap), hysteresis_s=100.0,
                         clock=lambda: clk["t"])
    write({"target_world": 3, "drain": [1]})
    d = pol.decide(live_world=3, live_ranks=(0, 1, 2))
    assert d is not None and d.kind == "drain" and d.ranks == (1,)
    # acted on exactly once — the next tick does not re-drain
    assert pol.decide(live_world=3, live_ranks=(0, 1, 2),
                      draining=(1,)) is None
    # ...and the STALE file must not re-drain the backfilled rank either
    assert pol.decide(live_world=3, live_ranks=(0, 1, 2)) is None
    # but the latch is EDGE-triggered on the hint: once the pool removes
    # the rank from the list and later re-requests it, it is honored
    # again (a permanent latch would ignore a second legitimate reclaim
    # for the policy's whole lifetime)
    write({"target_world": 3})
    assert pol.decide(live_world=3, live_ranks=(0, 1, 2)) is None
    write({"target_world": 3, "drain": [1]})
    d = pol.decide(live_world=3, live_ranks=(0, 1, 2))
    assert d is not None and d.kind == "drain" and d.ranks == (1,)


def test_scale_policy_waits_out_draining_rank_then_backfills(tmp_path):
    """While a drained rank is still exiting it COUNTS toward capacity:
    the replacement is backfilled after the clean drain, not pre-spawned
    next to it (which would mint a spurious extra rank)."""
    cap = tmp_path / "capacity.json"
    write = _cap_writer(cap)
    clk = {"t": 0.0}
    pol = SC.ScalePolicy(capacity_file=str(cap), hysteresis_s=0.1,
                         clock=lambda: clk["t"])
    write({"target_world": 3, "drain": [0]})
    d = pol.decide(live_world=3, live_ranks=(0, 1, 2))
    assert d.kind == "drain"
    clk["t"] = 1.0
    # rank 0 still draining: live 3 == target 3, hold
    assert pol.decide(live_world=3, live_ranks=(0, 1, 2),
                      draining=(0,)) is None
    clk["t"] = 2.0
    # rank 0 exited: backfill
    d = pol.decide(live_world=2, live_ranks=(1, 2))
    assert d is not None and d.kind == "scale_up" and d.count == 1


def test_scale_policy_anomaly_vetoes_scale_up(tmp_path):
    cap = tmp_path / "capacity.json"
    _cap_writer(cap)({"target_world": 3})
    clk = {"t": 0.0}
    pol = SC.ScalePolicy(capacity_file=str(cap), hysteresis_s=0.1,
                         anomaly_veto_s=5.0, clock=lambda: clk["t"])
    pol.decide(live_world=2, live_ranks=(0, 1))  # records the hint
    clk["t"] = 1.0
    pol.note_anomaly("step_time_spike", {})
    assert pol.decide(live_world=2, live_ranks=(0, 1)) is None  # vetoed
    clk["t"] = 7.0  # the fleet has been quiet past the veto window
    d = pol.decide(live_world=2, live_ranks=(0, 1))
    assert d is not None and d.kind == "scale_up"


# -- the supervisor's sliding-window relaunch budget --------------------------


def _supervisor_module():
    import importlib.util

    path = os.path.join(os.path.dirname(__file__), "..", "launch",
                        "supervisor.py")
    spec = importlib.util.spec_from_file_location("dear_sup_test", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_supervisor_sliding_window_budget(tmp_path):
    """The long-running-service budget: a rank crashing repeatedly gets
    at most ``max_relaunches`` relaunches per trailing window — and the
    budget REFILLS as the window slides (a lifetime cap, which any
    continuous service exhausts by design, is only the no-window
    fallback)."""
    sup_mod = _supervisor_module()
    sup = sup_mod.ElasticSupervisor(
        1, [sys.executable, "-c", "import sys; sys.exit(3)"],
        elastic_dir=str(tmp_path), max_relaunches=1,
        relaunch_window_s=60.0, relaunch_delay_s=0.01,
        log=lambda s: None,
    ).start()
    rc = sup.wait(deadline_s=60)
    assert rc == 1                       # the rank never came up healthy
    assert sup.relaunches[0] == 1        # budget spent, gave up
    # the window slides: pruning old timestamps refills the budget
    sup2 = sup_mod.ElasticSupervisor(
        1, ["true"], elastic_dir=str(tmp_path / "w2"), max_relaunches=1,
        relaunch_window_s=0.05, log=lambda s: None)
    sup2._relaunch_times[0] = [time.monotonic()]
    assert not sup2._budget_ok(0)
    time.sleep(0.08)
    assert sup2._budget_ok(0)
    # legacy alias semantics: no window -> lifetime cap
    sup3 = sup_mod.ElasticSupervisor(
        1, ["true"], elastic_dir=str(tmp_path / "w3"), max_relaunches=1,
        log=lambda s: None)
    sup3.relaunches[0] = 1
    assert not sup3._budget_ok(0)


def test_supervisor_dirty_drain_is_not_relaunched(tmp_path):
    """A draining rank that crashes inside its grace window is STILL a
    drain: the policy asked for its removal, so relaunching it would
    override the capacity decision and burn its relaunch budget — it
    goes to the backfill pool instead (and a requested removal is not a
    job failure)."""
    sup_mod = _supervisor_module()
    sup = sup_mod.ElasticSupervisor(
        1, [sys.executable, "-c",
            "import signal,sys,time;"
            "signal.signal(signal.SIGTERM, lambda *a: sys.exit(5));"
            "time.sleep(30)"],
        elastic_dir=str(tmp_path), max_relaunches=2,
        relaunch_delay_s=0.01, log=lambda s: None,
    ).start()
    deadline = time.monotonic() + 20
    while sup.pid(0) is None and time.monotonic() < deadline:
        time.sleep(0.05)
    time.sleep(0.3)  # let the handler install
    assert sup.drain(0)
    assert sup.wait(deadline_s=20, poll_s=0.05) == 0  # removal != failure
    assert ("drained_dirty", 0) in sup.events
    assert sup._backfill == [0]
    assert sup.relaunches[0] == 0  # the budget was never touched


def test_supervisor_policy_stands_down_on_clean_completion(tmp_path):
    """Ghost-rank regression: a fleet finishing its job exits in
    lockstep, but the EXITS are staggered at the OS level — the policy
    must not read the shrinking live count as lost capacity and spawn
    replacement ranks that then wait out a rejoin timeout against a dead
    fleet (observed). The first clean (non-drained) completion stands
    the policy down."""
    sup_mod = _supervisor_module()
    cap = tmp_path / "capacity.json"
    _cap_writer(cap)({"target_world": 3})
    pol = SC.ScalePolicy(capacity_file=str(cap), hysteresis_s=0.0)
    # ranks exit cleanly but STAGGERED (rank 1 lives 0.6s longer)
    sup = sup_mod.ElasticSupervisor(
        2, [sys.executable, "-c",
            "import os,time;"
            "time.sleep(0.6*int(os.environ['DEAR_ELASTIC_RANK']))"],
        elastic_dir=str(tmp_path / "el"), policy=pol,
        log=lambda s: None,
    ).start()
    assert sup.wait(deadline_s=30, poll_s=0.05) == 0
    ghosts = [e for e in sup.events if e[0] == "scale_up"]
    assert not ghosts, f"policy spawned ghost ranks: {ghosts}"
    assert sorted(sup._final_rc) == [0, 1]


# -- the guard's drain-on-preempt path (scripted coordinator) -----------------


class _DrainStub(_ElasticStub):
    """Scripted elastic coordinator that speaks the drain protocol."""

    supports_draining = True

    def __init__(self):
        super().__init__()
        self.saw_draining = []

    def health_check(self, ok, *, fingerprint="", step=None,
                     preempted=False, draining=False):
        self.saw_draining.append(bool(draining))
        if draining:
            # the survivors commit the shrink; my verdict says save+exit
            return M.ElasticVerdict(
                ok=True, unhealthy_ranks=(), desync=False,
                any_preempted=False, fingerprints=(fingerprint,),
                epoch=self.epoch, members=self.members,
                drained=(self.rank,))
        return super().health_check(ok, fingerprint=fingerprint,
                                    step=step, preempted=preempted)


def test_guard_drain_on_preempt(tmp_path, mesh, monkeypatch):
    """A SIGTERM under an elastic coordinator becomes a DRAIN
    announcement (not fleet-wide preemption): the guard passes
    ``draining=True`` into the health sync, and a `self_draining`
    verdict produces the emergency save + ``preempted`` exit WITHOUT a
    rollback."""
    from dear_pytorch_tpu.ops.fused_sgd import fused_sgd
    from dear_pytorch_tpu.parallel import build_train_step
    from dear_pytorch_tpu.utils.guard import GuardedTrainer

    from tests.test_dear_numerics import _data, _loss_fn, _mlp_params

    monkeypatch.setenv("DEAR_PREEMPT_GRACE_S", "25")
    params = _mlp_params(jax.random.PRNGKey(0))
    ts = build_train_step(
        _loss_fn, params, mesh=mesh, threshold_mb=0.0008, donate=False,
        optimizer=fused_sgd(lr=0.05, momentum=0.9),
    )
    co = _DrainStub()
    rollbacks = []
    with PreemptionHandler() as pre:
        guard = GuardedTrainer(
            ts, str(tmp_path / "g"), params, check_every=1,
            checkpoint_every=100, coordinator=co, preemption=pre,
        )
        guard.on_rollback = lambda c, at: rollbacks.append(at)
        state = ts.init(params)
        state, m = guard.step(state, _data(jax.random.PRNGKey(0)))
        assert co.saw_draining == [False]
        os.kill(os.getpid(), signal.SIGTERM)
        state, m = guard.step(state, _data(jax.random.PRNGKey(1)))
    assert co.saw_draining == [False, True]
    assert m.get("preempted") and not rollbacks
    # the emergency save landed at the drained step
    assert m.get("preempt_checkpoint_step") == 2
    assert ckpt.latest_valid_step(str(tmp_path / "g")) == 2
    # DEAR_PREEMPT_DRAIN=0 restores full-fleet preemption propagation
    monkeypatch.setenv("DEAR_PREEMPT_DRAIN", "0")
    co2 = _DrainStub()
    with PreemptionHandler() as pre2:
        guard2 = GuardedTrainer(
            ts, str(tmp_path / "g2"), params, check_every=1,
            checkpoint_every=100, coordinator=co2, preemption=pre2,
        )
        state = ts.init(params)
        os.kill(os.getpid(), signal.SIGTERM)
        state, m = guard2.step(state, _data(jax.random.PRNGKey(0)))
    assert co2.saw_draining == [False]  # propagate path, not drain
