"""Test configuration: emulate an 8-device TPU slice on the CPU backend.

The reference had no fake-cluster story — multi-node behavior was only
testable on a real 16×4-GPU cluster under mpirun (SURVEY.md §4). The XLA CPU
backend gives us a true multi-device world on one host: real ReduceScatter /
AllGather / AllReduce semantics, deterministic, CI-friendly.

Must run before any `import jax` in the test process.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"  # override the session's axon/TPU platform
os.environ["DEAR_DISABLE_DISTRIBUTED"] = "1"  # sitecustomize sets TPU_WORKER_HOSTNAMES
# No persistent compilation cache in the suite: /tmp/dear_jax_cache can
# carry XLA:CPU AOT results compiled on a DIFFERENT host CPU generation
# (this container's /tmp outlives host moves), and loading them is at
# best a warning and at worst a SIGILL/abort mid-test (observed:
# cpu_aot_loader "machine features ... prefer-no-scatter" then a fatal
# abort in a compiled executable). CPU test compiles are cheap; the
# cache's real value is the TPU tunnel's 20-min compiles, which
# non-test entry points still get.
os.environ.setdefault("DEAR_COMPILATION_CACHE_DIR", "off")

import jax  # noqa: E402

from dear_pytorch_tpu import _jax_compat  # noqa: E402  (installs jax.P etc.)

# jax may already be imported by sitecustomize with JAX_PLATFORMS=axon baked
# in; the config update works as long as no backend has been initialized yet.
# The device count goes through the compat helper: jax_num_cpu_devices on
# current jax, the XLA_FLAGS escape hatch on older releases. scrub_env
# keeps the fallback flag OUT of os.environ so subprocess-spawning tests
# (bench smoke, examples, multiprocess clusters) don't inherit an 8-device
# world they never asked for.
jax.config.update("jax_platforms", "cpu")
_jax_compat.set_cpu_device_count(8, scrub_env=True)

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def mesh():
    """Global 1-D data-parallel mesh over the 8 emulated devices."""
    from dear_pytorch_tpu.comm import backend

    m = backend.init()
    yield m


@pytest.fixture(scope="session")
def world(mesh):
    return mesh.shape["dp"]


@pytest.fixture
def rng():
    import numpy as np

    return np.random.default_rng(10)  # seed mirrors test_comm.py:6
