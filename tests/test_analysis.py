"""dearlint framework tests: one planted-violation fixture per rule
(red) with a clean twin (green), pragma suppression, baseline
add/expire semantics, registry audits in both directions, the CLI exit
codes, ``--changed`` filtering, the import-graph isolation contract,
and the zero-unbaselined-findings gate over the live package.

Fixtures are written under tmp_path as a fake repo layout
(``dear_pytorch_tpu/<area>/mod.py``) because several rules scope by
relpath (waist modules, serving/, the runtime-package filter)."""

import json
import os
import textwrap

import pytest

from dear_pytorch_tpu.analysis import (
    ALL_RULES, BASELINE_NAME, Baseline, Scanner, main, make_rules,
    repo_root, run_rules,
)
from dear_pytorch_tpu.analysis.cli import changed_files
from dear_pytorch_tpu.analysis.rules_host import (
    AtomicWriteRule, BareExceptHotPathRule, LockHeldIORule,
    SignalHandlerImportRule,
)
from dear_pytorch_tpu.analysis.rules_registry import (
    CounterDocsRule, EnvRegistryRule,
)
from dear_pytorch_tpu.analysis.rules_sim import SimDeterminismRule
from dear_pytorch_tpu.analysis.rules_trace import (
    DcnBlockingRule, DonationAliasRule, HotPathSyncRule, TraceSchemaRule,
    UngatedSpanStreamRule, UngatedTelemetryRule,
)

REPO = repo_root()


def _plant(tmp_path, relpath, src):
    p = tmp_path / relpath
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(textwrap.dedent(src))
    return p


def _findings(tmp_path, rule, paths=("dear_pytorch_tpu",)):
    scanner = Scanner([str(tmp_path / p) for p in paths],
                      root=str(tmp_path))
    return scanner.run([rule])


# ---------------------------------------------------------------------------
# one red fixture + one green twin per rule
# ---------------------------------------------------------------------------


def test_lock_held_io_red_and_green(tmp_path):
    _plant(tmp_path, "dear_pytorch_tpu/x/red.py", """
        import os

        class R:
            def flush(self):
                with self._lock:
                    with open(self.path, "w") as f:
                        f.write("x")
                    os.replace(self.path, self.path + ".1")
                    self.store.put_bytes("k", b"v")
    """)
    _plant(tmp_path, "dear_pytorch_tpu/x/green.py", """
        import os

        class G:
            def flush(self):
                body = self.render()
                with self._lock:
                    self.dirty = False      # state transition only
                with open(self.path + ".tmp", "w") as f:
                    f.write(body)
                os.replace(self.path + ".tmp", self.path)

            def closure_is_fine(self):
                with self._lock:
                    def later():
                        return open(self.path)  # runs outside the lock
                    self.cb = later
    """)
    found = _findings(tmp_path, LockHeldIORule())
    assert {(f.path, f.key) for f in found} == {
        ("dear_pytorch_tpu/x/red.py", "open"),
        ("dear_pytorch_tpu/x/red.py", "os.replace"),
        ("dear_pytorch_tpu/x/red.py", "put_bytes"),
    }


def test_atomic_write_red_and_green(tmp_path):
    _plant(tmp_path, "dear_pytorch_tpu/utils/objectstore.py", """
        import os

        def torn(path, data):
            with open(path, "w") as f:     # RED: no tmp, no replace
                f.write(data)

        def atomic(path, data):
            tmp = path + ".tmp"
            with open(tmp, "w") as f:      # green: the staging half
                f.write(data)
            os.replace(tmp, path)

        def reader(path):
            with open(path) as f:          # green: read mode
                return f.read()
    """)
    # the same torn write OUTSIDE a waist module is not this rule's
    # business (green twin by scope)
    _plant(tmp_path, "dear_pytorch_tpu/models/misc.py", """
        def torn(path, data):
            with open(path, "w") as f:
                f.write(data)
    """)
    found = _findings(tmp_path, AtomicWriteRule())
    assert [(f.path, f.qualname, f.key) for f in found] == [
        ("dear_pytorch_tpu/utils/objectstore.py", "torn", "path")]


def test_hot_path_sync_red_and_green(tmp_path):
    _plant(tmp_path, "dear_pytorch_tpu/x/red.py", """
        import numpy as np

        def _helper(metrics):
            return float(metrics["loss"])      # RED: reachable from step

        def step(state, batch):
            out = run(state, batch)
            host = np.asarray(out)             # RED: sync in the entry
            return _helper(host)
    """)
    _plant(tmp_path, "dear_pytorch_tpu/x/green.py", """
        import numpy as np

        def offline_report(rows):
            # green: not reachable from any step/tick entry
            return np.asarray(rows).mean()

        def step(state):
            xs = np.asarray([1, 2, 3])         # green: literal host data
            n = int(jax.process_index())       # green: host-side jax
            return xs, n
    """)
    found = _findings(tmp_path, HotPathSyncRule())
    assert {(f.path, f.qualname, f.key) for f in found} == {
        ("dear_pytorch_tpu/x/red.py", "_helper", "float(metrics['loss'])"),
        ("dear_pytorch_tpu/x/red.py", "step", "np.asarray"),
    }


def test_ungated_telemetry_red_and_green(tmp_path):
    _plant(tmp_path, "dear_pytorch_tpu/x/red.py", """
        def hot():
            tr = get_tracer()
            tr.count("x.steps")                    # RED
            get_tracer().event("x.rebuilt", n=1)   # RED: chained

        def wrong_branch():
            tr = get_tracer()
            if tr.enabled:
                pass
            else:
                tr.count("x.disabled_path")        # RED: runs when OFF
            if not tr.enabled:
                tr.count("x.negated_body")         # RED: runs when OFF
    """)
    _plant(tmp_path, "dear_pytorch_tpu/x/green.py", """
        def gated():
            tr = get_tracer()
            if tr.enabled:
                tr.count("x.steps")
                tr.event("x.rebuilt", n=1)

        def early_return():
            tr = get_tracer()
            if not tr.enabled:
                return run()
            tr.count("x.steps")
            return run()

        def negated_orelse():
            tr = get_tracer()
            if not tr.enabled:
                pass
            else:
                tr.count("x.on_path")   # green: executes only when ON
    """)
    found = _findings(tmp_path, UngatedTelemetryRule())
    assert {(f.path, f.key) for f in found} == {
        ("dear_pytorch_tpu/x/red.py", "count:x.steps"),
        ("dear_pytorch_tpu/x/red.py", "event:x.rebuilt"),
        ("dear_pytorch_tpu/x/red.py", "count:x.disabled_path"),
        ("dear_pytorch_tpu/x/red.py", "count:x.negated_body"),
    }


def test_ungated_span_stream_red_and_green(tmp_path):
    _plant(tmp_path, "dear_pytorch_tpu/x/red.py", """
        def hot():
            ds = get_stream()
            ds.emit("x.span", dur_s=0.1)        # RED
            get_stream().clock_sample()          # RED: chained
    """)
    _plant(tmp_path, "dear_pytorch_tpu/x/green.py", """
        def gated():
            ds = get_stream()
            if ds.enabled:
                ds.emit("x.span", dur_s=0.1)
                ds.clock_sample()

        def early_return():
            ds = get_stream()
            if not ds.enabled:
                return run()
            ds.emit("x.span")
            return run()

        def other_receiver():
            db.emit("not.a.stream")   # green: not a stream receiver
    """)
    found = _findings(tmp_path, UngatedSpanStreamRule())
    assert {(f.path, f.key) for f in found} == {
        ("dear_pytorch_tpu/x/red.py", "emit:x.span"),
        ("dear_pytorch_tpu/x/red.py", "clock_sample:<dynamic>"),
    }


def test_trace_schema_red_and_green(tmp_path):
    _plant(tmp_path, "dear_pytorch_tpu/serving/red.py", """
        def dispatch(rid, prompt):
            return {"id": rid, "prompt": prompt,      # RED: request
                    "max_new_tokens": 8}

        def respond(rid, tokens):
            payload = {"id": rid, "tokens": tokens,   # RED: response
                       "model_version": "v1"}
            return payload
    """)
    _plant(tmp_path, "dear_pytorch_tpu/serving/green.py", """
        def dispatch(rid, prompt, ctx):
            return {"id": rid, "prompt": prompt,
                    "trace": ctx.to_dict()}           # green: in literal

        def respond(rid, tokens, trace):
            payload = {"id": rid, "tokens": tokens}
            if trace is not None:
                payload["trace"] = trace              # green: stamped later
            return payload

        def canonical(payload):
            # green: key-by-key projection of one source record (the
            # sha256 canonicalization) — deliberately trace-free
            return {"id": payload["id"], "tokens": payload["tokens"],
                    "model_version": payload["model_version"]}
    """)
    _plant(tmp_path, "dear_pytorch_tpu/x/elsewhere.py", """
        def not_serving(rid, tokens):
            return {"id": rid, "tokens": tokens}      # green: not serving/
    """)
    found = _findings(tmp_path, TraceSchemaRule())
    assert {(f.path, f.qualname) for f in found} == {
        ("dear_pytorch_tpu/serving/red.py", "dispatch"),
        ("dear_pytorch_tpu/serving/red.py", "respond"),
    }


def test_signal_handler_import_red_and_green(tmp_path):
    _plant(tmp_path, "dear_pytorch_tpu/x/red.py", """
        import signal

        class H:
            def _on_signal(self, signum, frame):
                from dear_pytorch_tpu.resilience import membership  # RED
                self.epoch = membership.current_epoch()

            def install(self):
                signal.signal(signal.SIGTERM, self._on_signal)
    """)
    _plant(tmp_path, "dear_pytorch_tpu/x/green.py", """
        import signal

        class H:
            def _on_signal(self, signum, frame):
                self.flag = True               # green: pre-bound only

            def install(self):
                from dear_pytorch_tpu.resilience import membership
                self._epoch_fn = membership.current_epoch
                signal.signal(signal.SIGTERM, self._on_signal)

        def not_a_handler():
            import os                          # green: never registered
            return os
    """)
    found = _findings(tmp_path, SignalHandlerImportRule())
    assert [(f.path, f.qualname) for f in found] == [
        ("dear_pytorch_tpu/x/red.py", "H._on_signal")]


def test_donation_alias_red_and_green(tmp_path):
    _plant(tmp_path, "dear_pytorch_tpu/x/red.py", """
        import jax

        def repack(state, fresh):
            leaves = [jax.device_put(v, ref.sharding)       # RED
                      for v, ref in zip(state, fresh)]
            return leaves
    """)
    _plant(tmp_path, "dear_pytorch_tpu/x/green.py", """
        import jax
        import jax.numpy as jnp

        def repack(state, fresh):
            leaves = [jax.device_put(v, ref.sharding)
                      for v, ref in zip(state, fresh)]
            return jax.tree.map(jnp.copy, leaves)   # defensive copy

        def place(x, mesh):
            s = jax.sharding.NamedSharding(mesh, jax.P())
            return jax.device_put(x, s)             # constructed sharding
    """)
    found = _findings(tmp_path, DonationAliasRule())
    assert [(f.path, f.qualname) for f in found] == [
        ("dear_pytorch_tpu/x/red.py", "repack")]


def test_bare_except_red_and_green(tmp_path):
    _plant(tmp_path, "dear_pytorch_tpu/serving/red.py", """
        def loop():
            try:
                run()
            except Exception:
                pass                       # RED: swallowed, unobservable
    """)
    _plant(tmp_path, "dear_pytorch_tpu/serving/green.py", """
        import os

        def loop(tr):
            try:
                run()
            except Exception:
                tr.count("serve.errors")   # green: counted
            try:
                os.unlink("x")
            except OSError:
                pass                       # green: narrow best-effort
    """)
    # same swallow outside serving/guard scope: not this rule's business
    _plant(tmp_path, "dear_pytorch_tpu/models/red.py", """
        def loop():
            try:
                run()
            except Exception:
                pass
    """)
    found = _findings(tmp_path, BareExceptHotPathRule())
    assert [(f.path, f.key) for f in found] == [
        ("dear_pytorch_tpu/serving/red.py", "Exception")]


def test_dcn_blocking_red_and_green(tmp_path):
    _plant(tmp_path, "dear_pytorch_tpu/x/red.py", """
        class R:
            def publish(self):
                with self._lock:
                    self._transport.get("k", 5.0)   # RED: peer wait
                    #                                 under a lock

            def _fetch(self):
                return self.dcn.exchange(0, {})     # RED: reachable

            def step(self, state, batch):
                return self._fetch()
    """)
    _plant(tmp_path, "dear_pytorch_tpu/x/green.py", """
        class G:
            def publish(self):
                val = self._transport.get("k", 5.0)  # green: no lock,
                with self._lock:                     # not on a hot path
                    self.cache = val

            def offline_audit(self):
                # green: not reachable from any step/tick entry
                return self._transport.get("k", 5.0)

            def step(self, state):
                return self.cfg.get("mode")          # green: dict get,
                #                                      not a transport
    """)
    found = _findings(tmp_path, DcnBlockingRule())
    assert {(f.path, f.qualname, f.key) for f in found} == {
        ("dear_pytorch_tpu/x/red.py", "R.publish", "self._transport.get"),
        ("dear_pytorch_tpu/x/red.py", "R._fetch", "self.dcn.exchange"),
    }


def test_sim_determinism_red_and_green(tmp_path):
    # the rule is scoped to the one module carrying the determinism
    # contract — fixtures plant the fake sim.py at that exact relpath
    _plant(tmp_path, "dear_pytorch_tpu/observability/sim.py", """
        import random
        import time

        def jittered(seed):
            rng = random.Random(seed)            # green: seeded
            arrivals = random.Random(x=seed)     # green: seeded kwarg
            t0 = time.monotonic()                # RED: wall clock
            time.sleep(0.01)                     # RED: wall clock
            bad = random.Random()                # RED: unseeded
            v = random.gauss(0.0, 1.0)           # RED: process-global
            return rng.gauss(0.0, v)             # green: instance call

        def healer(ev, thread):
            ev.wait(1.0)                         # green: bounded wait
            thread.join(0.2)                     # green: bounded join
    """)
    # the identical violations OUTSIDE sim.py are other code's
    # business, not this rule's (green twin by scope)
    _plant(tmp_path, "dear_pytorch_tpu/observability/other.py", """
        import random
        import time

        def bench():
            return time.monotonic(), random.random()
    """)
    found = _findings(tmp_path, SimDeterminismRule())
    assert {(f.path, f.qualname, f.key) for f in found} == {
        ("dear_pytorch_tpu/observability/sim.py", "jittered",
         "time.monotonic"),
        ("dear_pytorch_tpu/observability/sim.py", "jittered",
         "time.sleep"),
        ("dear_pytorch_tpu/observability/sim.py", "jittered",
         "random.Random"),
        ("dear_pytorch_tpu/observability/sim.py", "jittered",
         "random.gauss"),
    }


def test_sim_determinism_live_module_clean():
    # the shipping simulator itself must satisfy its own contract
    scanner = Scanner(
        [os.path.join(REPO, "dear_pytorch_tpu", "observability",
                      "sim.py")], root=REPO)
    assert scanner.run([SimDeterminismRule()]) == []


def test_env_registry_both_directions(tmp_path):
    _plant(tmp_path, "dear_pytorch_tpu/x/mod.py", """
        import os

        UNDOC = os.environ.get("DEAR_UNDOCUMENTED_KNOB")       # RED
        DOCD = os.environ.get("DEAR_DOCUMENTED_KNOB", "1")
        HELPER_ENV = "DEAR_HELPER_READ"                        # RED
        PREFIXED = [k for k in os.environ
                    if k.startswith("DEAR_FAMILY_")]           # prefix
    """)
    doc = tmp_path / "docs" / "ENV.md"
    doc.parent.mkdir(parents=True)
    doc.write_text(textwrap.dedent("""
        | variable | effect |
        |---|---|
        | `DEAR_DOCUMENTED_KNOB` | documented and read: green |
        | `DEAR_FAMILY_<AXIS>` | documents the whole prefix family |
        | `DEAR_STALE_KNOB` | RED: nothing reads this |
        | `DEAR_BUILT_AT_RUNTIME` | (dynamic) name built at runtime |
    """))
    found = _findings(tmp_path, EnvRegistryRule())
    assert {(f.path, f.key) for f in found} == {
        ("dear_pytorch_tpu/x/mod.py", "DEAR_UNDOCUMENTED_KNOB"),
        ("dear_pytorch_tpu/x/mod.py", "DEAR_HELPER_READ"),
        ("docs/ENV.md", "DEAR_STALE_KNOB"),
    }


def test_counter_docs_both_directions(tmp_path):
    _plant(tmp_path, "dear_pytorch_tpu/x/mod.py", """
        def hot(tr, leg):
            if tr.enabled:
                tr.count("x.documented")
                tr.count("x.undocumented")          # RED
                tr.count(f"x.{leg}_bytes", 4)       # documented as <leg>
                tr.count(f"x.{leg}_drops")          # RED: no doc pattern
    """)
    doc = tmp_path / "docs" / "OBSERVABILITY.md"
    doc.parent.mkdir(parents=True)
    doc.write_text(textwrap.dedent("""
        | source | counters |
        |---|---|
        | x | `x.documented`, `x.<leg>_bytes` |
        | x | `x.stale` |
        | other namespace | `foreign.counter` is NOT held to the audit |
    """))
    rule = CounterDocsRule()
    found = _findings(tmp_path, rule)
    assert {(f.path, f.key) for f in found} == {
        ("dear_pytorch_tpu/x/mod.py", "x.undocumented"),
        ("dear_pytorch_tpu/x/mod.py", "x.*_drops"),
        ("docs/OBSERVABILITY.md", "x.stale"),
    }


# ---------------------------------------------------------------------------
# pragmas, baseline, report plumbing
# ---------------------------------------------------------------------------


def test_pragma_line_and_file_suppression(tmp_path):
    _plant(tmp_path, "dear_pytorch_tpu/x/line.py", """
        def hot():
            tr = get_tracer()
            tr.count("x.a")  # dearlint: disable=ungated-telemetry
            tr.count("x.b")  # dearlint: disable=some-other-rule
    """)
    _plant(tmp_path, "dear_pytorch_tpu/x/file.py", """
        # dearlint: disable-file=ungated-telemetry
        def hot():
            tr = get_tracer()
            tr.count("x.c")
            tr.count("x.d")
    """)
    _plant(tmp_path, "dear_pytorch_tpu/x/all.py", """
        def hot():
            tr = get_tracer()
            tr.count("x.e")  # dearlint: disable=all
    """)
    found = _findings(tmp_path, UngatedTelemetryRule())
    assert [(f.path, f.key) for f in found] == [
        ("dear_pytorch_tpu/x/line.py", "count:x.b")]


def test_pragma_inside_string_is_not_a_pragma(tmp_path):
    _plant(tmp_path, "dear_pytorch_tpu/x/s.py", """
        def hot():
            tr = get_tracer()
            tr.count("x.a # dearlint: disable=ungated-telemetry")
    """)
    found = _findings(tmp_path, UngatedTelemetryRule())
    assert len(found) == 1  # the fake pragma lives in the literal


def test_baseline_add_expire_and_justification(tmp_path):
    mod = """
        def hot():
            tr = get_tracer()
            tr.count("x.a")
    """
    _plant(tmp_path, "dear_pytorch_tpu/x/mod.py", mod)
    rule = UngatedTelemetryRule()
    fp = _findings(tmp_path, rule)[0].fingerprint
    assert fp == ("ungated-telemetry:dear_pytorch_tpu/x/mod.py:hot:"
                  "count:x.a")

    # accepted finding: does not gate; report still carries it
    bl = Baseline({fp: "cold path, deliberate"})
    rep = run_rules([str(tmp_path / "dear_pytorch_tpu")], [rule],
                    baseline=bl, root=str(tmp_path))
    assert rep.clean and len(rep.findings) == 1

    # fingerprints survive unrelated edits (lines shift, same code)
    _plant(tmp_path, "dear_pytorch_tpu/x/mod.py",
           "# a new leading comment\n# another\n" + textwrap.dedent(mod))
    rep = run_rules([str(tmp_path / "dear_pytorch_tpu")], [rule],
                    baseline=bl, root=str(tmp_path))
    assert rep.clean

    # the violation is fixed -> the entry is STALE and gates
    _plant(tmp_path, "dear_pytorch_tpu/x/mod.py", """
        def hot():
            tr = get_tracer()
            if tr.enabled:
                tr.count("x.a")
    """)
    rep = run_rules([str(tmp_path / "dear_pytorch_tpu")], [rule],
                    baseline=bl, root=str(tmp_path))
    assert not rep.clean and rep.stale_baseline == [fp]

    # a justification is mandatory on disk
    p = tmp_path / "bl.json"
    p.write_text(json.dumps({"findings": [{"fingerprint": fp}]}))
    with pytest.raises(ValueError, match="justification"):
        Baseline.load(str(p))
    # round-trip keeps entries
    bl.save(str(p))
    assert Baseline.load(str(p)).entries == bl.entries


def test_changed_mode_filters_reporting_not_parsing(tmp_path):
    _plant(tmp_path, "dear_pytorch_tpu/x/touched.py", """
        def hot():
            tr = get_tracer()
            tr.count("x.a")
    """)
    _plant(tmp_path, "dear_pytorch_tpu/x/untouched.py", """
        def hot():
            tr = get_tracer()
            tr.count("x.b")
    """)
    rule = UngatedTelemetryRule()
    rep = run_rules([str(tmp_path / "dear_pytorch_tpu")], [rule],
                    root=str(tmp_path),
                    only_files={"dear_pytorch_tpu/x/touched.py"})
    assert [f.path for f in rep.unbaselined] == [
        "dear_pytorch_tpu/x/touched.py"]
    # a partial view never judges baseline staleness
    bl = Baseline({"ungated-telemetry:gone.py:f:count:x.z": "old"})
    rep = run_rules([str(tmp_path / "dear_pytorch_tpu")], [rule],
                    baseline=bl, root=str(tmp_path),
                    only_files={"dear_pytorch_tpu/x/touched.py"})
    assert rep.stale_baseline == []


def test_rules_subset_never_judges_foreign_baseline_entries(tmp_path):
    """A --rules subset run is a partial view: entries belonging to
    rules that did not run must neither gate as stale nor be expired
    by --write-baseline (the justified-entry-erasure regression)."""
    _plant(tmp_path, "dear_pytorch_tpu/x/mod.py", """
        def hot():
            tr = get_tracer()
            tr.count("x.a")
    """)
    foreign = "hot-path-sync:dear_pytorch_tpu/y.py:f:np.asarray"
    bl = Baseline({foreign: "host data"})
    rep = run_rules([str(tmp_path / "dear_pytorch_tpu")],
                    make_rules(["ungated-telemetry"]),
                    baseline=bl, root=str(tmp_path))
    assert rep.stale_baseline == []          # hot-path-sync never ran
    rep = run_rules([str(tmp_path / "dear_pytorch_tpu")],
                    make_rules(["ungated-telemetry", "hot-path-sync"]),
                    baseline=bl, root=str(tmp_path))
    assert rep.stale_baseline == [foreign]   # now it did — stale gates


def test_cli_explicit_paths_filter_reporting_not_parsing(tmp_path,
                                                         capsys):
    """Naming one clean file must not flood it with cross-file
    registry findings: the whole standard tree is parsed, the named
    files only filter what is reported."""
    _plant(tmp_path, "dear_pytorch_tpu/x/clean.py", """
        import os

        KNOB = os.environ.get("DEAR_FIXTURE_KNOB")
    """)
    _plant(tmp_path, "dear_pytorch_tpu/x/dirty.py", """
        def hot():
            tr = get_tracer()
            tr.count("x.a")
    """)
    doc = tmp_path / "docs" / "ENV.md"
    doc.parent.mkdir(parents=True)
    doc.write_text("| variable | effect |\n|---|---|\n"
                   "| `DEAR_FIXTURE_KNOB` | documented |\n")
    base = ["--root", str(tmp_path), "--no-baseline"]
    # the clean file alone: env-registry judges it against the SAME
    # full-tree view -> clean, exit 0 (not a storm of stale doc rows)
    assert main([str(tmp_path / "dear_pytorch_tpu/x/clean.py")]
                + base) == 0
    # the dirty file alone still reds
    assert main([str(tmp_path / "dear_pytorch_tpu/x/dirty.py")]
                + base) == 2
    capsys.readouterr()


def test_changed_files_parses_git_output():
    calls = []

    class _P:
        returncode = 0
        stderr = ""

        def __init__(self, out):
            self.stdout = out

    def fake_run(args, **kw):
        calls.append(args)
        if "diff" in args:
            return _P("dear_pytorch_tpu/a.py\ndocs/ENV.md\n")
        return _P("tests/new_test.py\n")

    out = changed_files("/nowhere", run=fake_run)
    assert out == {"dear_pytorch_tpu/a.py", "tests/new_test.py"}
    assert len(calls) == 2


def test_parse_error_is_a_finding(tmp_path):
    _plant(tmp_path, "dear_pytorch_tpu/x/bad.py", "def broken(:\n")
    rep = run_rules([str(tmp_path / "dear_pytorch_tpu")],
                    make_rules(["ungated-telemetry"]),
                    root=str(tmp_path))
    assert not rep.clean
    assert rep.unbaselined[0].rule == "parse-error"


# ---------------------------------------------------------------------------
# CLI exit codes
# ---------------------------------------------------------------------------


def test_cli_exit_codes_and_listing(tmp_path, capsys):
    # clean scan of an empty dir -> 0
    (tmp_path / "empty").mkdir()
    assert main([str(tmp_path / "empty"), "--root", str(tmp_path),
                 "--rules", "ungated-telemetry", "--no-baseline"]) == 0
    # unbaselined finding -> 2
    _plant(tmp_path, "dear_pytorch_tpu/x/mod.py", """
        def hot():
            tr = get_tracer()
            tr.count("x.a")
    """)
    assert main([str(tmp_path / "dear_pytorch_tpu"),
                 "--root", str(tmp_path),
                 "--rules", "ungated-telemetry", "--no-baseline"]) == 2
    # unknown rule -> 1 (usage error)
    assert main(["--rules", "nonesuch"]) == 1
    # --list-rules names every registered rule
    capsys.readouterr()
    assert main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for cls in ALL_RULES:
        assert cls.name in out
    # --json is machine-parseable and carries the verdict
    assert main([str(tmp_path / "dear_pytorch_tpu"),
                 "--root", str(tmp_path),
                 "--rules", "ungated-telemetry", "--no-baseline",
                 "--json"]) == 2
    doc = json.loads(capsys.readouterr().out)
    assert doc["clean"] is False and len(doc["unbaselined"]) == 1


def test_cli_write_baseline_roundtrip(tmp_path, capsys):
    _plant(tmp_path, "dear_pytorch_tpu/x/mod.py", """
        def hot():
            tr = get_tracer()
            tr.count("x.a")
    """)
    bl_path = str(tmp_path / "bl.json")
    args = [str(tmp_path / "dear_pytorch_tpu"), "--root", str(tmp_path),
            "--rules", "ungated-telemetry", "--baseline", bl_path]
    assert main(args) == 2
    assert main(args + ["--write-baseline"]) == 0
    doc = json.loads(open(bl_path).read())
    assert doc["findings"][0]["justification"].startswith("TODO")
    capsys.readouterr()
    assert main(args) == 0  # accepted now


# ---------------------------------------------------------------------------
# the live-tree gate + isolation contract
# ---------------------------------------------------------------------------


def test_repo_is_clean_under_committed_baseline():
    """THE tier-1 gate: zero unbaselined findings and zero stale
    baseline entries over the live package, scripts, launch helpers,
    and bench.py — i.e. `python -m dear_pytorch_tpu.analysis` exits 0."""
    from dear_pytorch_tpu.analysis.core import default_paths

    baseline = Baseline.load(os.path.join(REPO, BASELINE_NAME))
    rep = run_rules(default_paths(), make_rules(), baseline=baseline)
    assert rep.files_scanned > 50, "scan set collapsed — path rot?"
    msgs = [f.render() for f in rep.unbaselined]
    assert not msgs, "unbaselined dearlint findings:\n" + "\n".join(msgs)
    assert not rep.stale_baseline, (
        "stale LINT_BASELINE.json entries (fix shipped — delete them):\n"
        + "\n".join(rep.stale_baseline))


def test_analysis_never_imported_by_runtime_modules():
    """Import-graph isolation: the analyzer is host tooling; if any
    runtime module imported it, it would ride into the training/serving
    processes (and its cost would stop being zero). Checked statically
    over every import statement in the runtime package."""
    import ast as pyast

    offenders = []
    pkg = os.path.join(REPO, "dear_pytorch_tpu")
    scanner = Scanner([pkg], root=REPO)
    for mod in scanner.modules:
        if mod.relpath.startswith("dear_pytorch_tpu/analysis/"):
            continue
        for node in mod.walk():
            names = []
            if isinstance(node, pyast.Import):
                names = [a.name for a in node.names]
            elif isinstance(node, pyast.ImportFrom):
                names = [node.module or ""]
            if any(n.startswith("dear_pytorch_tpu.analysis")
                   for n in names):
                offenders.append(f"{mod.relpath}:{node.lineno}")
    assert not offenders, (
        f"runtime modules import the analysis suite: {offenders}")


def test_analysis_package_is_jax_free():
    """The suite must load without jax (check_telemetry_overhead's
    'pure host tooling' contract): no analysis module may import jax,
    numpy, or any runtime subsystem at module level."""
    import ast as pyast

    pkg = os.path.join(REPO, "dear_pytorch_tpu", "analysis")
    scanner = Scanner([pkg], root=REPO)
    banned = ("jax", "numpy", "flax", "optax")
    offenders = []
    for mod in scanner.modules:
        for node in mod.walk():
            names = []
            if isinstance(node, pyast.Import):
                names = [a.name for a in node.names]
            elif isinstance(node, pyast.ImportFrom):
                names = [node.module or ""]
            for n in names:
                root = n.split(".", 1)[0]
                if root in banned:
                    offenders.append(f"{mod.relpath}:{node.lineno}:{n}")
                if (n.startswith("dear_pytorch_tpu")
                        and not n.startswith("dear_pytorch_tpu.analysis")):
                    offenders.append(f"{mod.relpath}:{node.lineno}:{n}")
    assert not offenders, f"analysis imports runtime deps: {offenders}"


def test_overhead_script_reports_analysis_clean(capsys):
    """The telemetry-overhead harness now also asserts the analyzer
    stayed out of the measured process (analysis_imported=false feeds
    its ok verdict)."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "check_telemetry_overhead_analysis_probe",
        os.path.join(REPO, "scripts", "check_telemetry_overhead.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    rc = mod.main(["--iters", "200"])
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rc == 0
    assert out["analysis_imported"] is False
