"""Sequence-parallel end-to-end: BERT with ring attention on a dp×sp mesh,
trained with the DeAR decoupled RS+AG schedule over BOTH axes, must match
single-device training step for step (exact attention + correct gradient
normalization: sum over sp, mean over dp)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dear_pytorch_tpu.models import data
from dear_pytorch_tpu.models.bert import (
    BertConfig,
    BertForPreTraining,
    bert_pretraining_loss,
)
from dear_pytorch_tpu.ops.fused_sgd import fused_sgd
from dear_pytorch_tpu.parallel import build_train_step, sp as SP

CFG = BertConfig(
    num_hidden_layers=2, hidden_size=32, num_attention_heads=4,
    intermediate_size=64, vocab_size=64, max_position_embeddings=32,
    hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0,
)
B, S = 4, 32


@pytest.fixture(scope="module")
def mesh2d():
    devices = np.asarray(jax.devices()[:8]).reshape(2, 4)
    return jax.sharding.Mesh(devices, ("dp", "sp"))


def _batch():
    # masked_fraction=1.0: every token labeled, so per-shard valid counts are
    # equal and dp-mean-of-means == global mean (exact parity)
    return data.synthetic_bert_batch(
        jax.random.PRNGKey(5), B, seq_len=S, vocab_size=CFG.vocab_size,
        masked_fraction=1.0,
    )


def _dense_baseline(params, batch, steps, lr=0.05, momentum=0.9):
    model = BertForPreTraining(CFG)

    def loss_fn(p):
        logits, nsp = model.apply(
            {"params": p}, batch["input_ids"], batch["token_type_ids"],
            batch["attention_mask"], train=False,
        )
        return bert_pretraining_loss(
            logits, nsp, batch["masked_lm_labels"],
            batch["next_sentence_labels"],
        )

    opt = fused_sgd(lr=lr, momentum=momentum)
    flat, treedef = jax.tree_util.tree_flatten(params)
    states = [opt.init(p.reshape(-1)) for p in flat]
    losses = []
    for _ in range(steps):
        loss, grads = jax.value_and_grad(loss_fn)(params)
        losses.append(float(loss))
        gflat = jax.tree_util.tree_leaves(grads)
        new = []
        for i, (p, g) in enumerate(zip(flat, gflat)):
            q, states[i] = opt.update(g.reshape(-1), states[i], p.reshape(-1))
            new.append(q.reshape(p.shape))
        flat = new
        params = jax.tree_util.tree_unflatten(treedef, flat)
    return losses


@pytest.mark.parametrize("attention", ["ring", "ring_flash", "ulysses"])
def test_sp_bert_training_matches_dense(mesh2d, attention):
    batch = _batch()
    dense_model = BertForPreTraining(CFG)
    params = dense_model.init(
        {"params": jax.random.PRNGKey(0)}, batch["input_ids"], train=False
    )["params"]

    ref_losses = _dense_baseline(params, batch, steps=4)

    sp_model = SP.sp_bert_model(CFG, attention=attention)
    loss_fn = SP.make_sp_bert_loss_fn(sp_model, train=False)

    ts = build_train_step(
        loss_fn,
        params,
        mesh=mesh2d,
        axis_name=("dp", "sp"),
        mean_axes=("dp",),
        batch_spec_fn=SP.bert_sp_batch_specs,
        threshold_mb=0.05,  # several buckets
        optimizer=fused_sgd(lr=0.05, momentum=0.9),
        donate=False,
    )
    assert ts.plan.num_buckets >= 2
    state = ts.init(params)
    # master buffers are sharded over BOTH axes: 8-way ZeRO on a 2x4 mesh
    buf = state.buffers[0]
    assert buf.addressable_shards[0].data.size == buf.size // 8

    losses = []
    for _ in range(4):
        state, m = ts.step(state, batch)
        losses.append(float(m["loss"]))
    np.testing.assert_allclose(losses, ref_losses, rtol=2e-4, atol=2e-5)


def test_sp_cls_pool_picks_global_first_token(mesh2d):
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 16, 8))  # [B, S, H]

    def fn(xb):
        return SP.sp_cls_pool("sp")(xb)[None]

    xs = x.reshape(2, 4, 4, 8).transpose(1, 0, 2, 3)  # [sp, B, S_loc, H]
    mapped = jax.jit(jax.shard_map(
        lambda t: fn(t[0]),
        mesh=mesh2d, in_specs=jax.P("sp"), out_specs=jax.P("sp"),
        check_vma=False,
    ))
    out = mapped(xs)
    for r in range(4):
        np.testing.assert_allclose(
            np.asarray(out[r]), np.asarray(x[:, 0]), rtol=1e-6
        )


# ---------------------------------------------------------------------------
# Causal (GPT) sequence parallelism
# ---------------------------------------------------------------------------

def _gpt_cfg():
    from dear_pytorch_tpu.models.gpt import GptConfig

    return GptConfig(
        vocab_size=61, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=64,
        max_position_embeddings=32, embd_dropout_prob=0.0,
        hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0,
    )


def _gpt_dense_losses(cfg, params, ids, steps, lr=0.05, momentum=0.9):
    from dear_pytorch_tpu.models.gpt import GptLmHeadModel, gpt_lm_loss

    model = GptLmHeadModel(cfg)

    def loss_fn(p):
        logits = model.apply({"params": p}, ids, train=False)
        return gpt_lm_loss(logits, ids, vocab_size=cfg.vocab_size)

    opt = fused_sgd(lr=lr, momentum=momentum)
    flat, treedef = jax.tree_util.tree_flatten(params)
    states = [opt.init(p.reshape(-1)) for p in flat]
    losses = []
    for _ in range(steps):
        loss, grads = jax.value_and_grad(loss_fn)(params)
        losses.append(float(loss))
        gflat = jax.tree_util.tree_leaves(grads)
        new = []
        for i, (p, g) in enumerate(zip(flat, gflat)):
            q, states[i] = opt.update(g.reshape(-1), states[i], p.reshape(-1))
            new.append(q.reshape(p.shape))
        flat = new
        params = jax.tree_util.tree_unflatten(treedef, flat)
    return losses


@pytest.mark.parametrize("attention", ["ring", "ring_flash", "ulysses"])
def test_sp_gpt_training_matches_dense(mesh2d, attention):
    """Causal sp: the cross-shard next-token shift, global-position causal
    masking, and sp-sum/dp-mean gradient accounting must reproduce dense
    single-device GPT training step for step."""
    from dear_pytorch_tpu.models import data
    from dear_pytorch_tpu.models.gpt import GptLmHeadModel
    from dear_pytorch_tpu.parallel import sp as SP

    cfg = _gpt_cfg()
    batch = data.synthetic_gpt_batch(
        jax.random.PRNGKey(9), B, seq_len=S, vocab_size=cfg.vocab_size
    )
    dense = GptLmHeadModel(cfg)
    params = dense.init(
        {"params": jax.random.PRNGKey(0)}, batch["input_ids"], train=False
    )["params"]
    ref_losses = _gpt_dense_losses(cfg, params, batch["input_ids"], steps=3)

    model = SP.sp_gpt_model(cfg, attention=attention)
    ts = build_train_step(
        SP.make_sp_gpt_loss_fn(model, vocab_size=cfg.vocab_size,
                               train=False),
        params,
        mesh=mesh2d,
        axis_name=("dp", "sp"),
        mean_axes=("dp",),
        batch_spec_fn=SP.bert_sp_batch_specs,  # [B,S] -> (dp, sp): generic
        threshold_mb=0.01,
        optimizer=fused_sgd(lr=0.05, momentum=0.9),
        donate=False,
    )
    state = ts.init(params)
    losses = []
    for _ in range(3):
        state, m = ts.step(state, batch)
        losses.append(float(m["loss"]))
    np.testing.assert_allclose(losses, ref_losses, rtol=2e-4, atol=2e-5)


def test_sp_gpt_zigzag_training_matches_dense(mesh2d):
    """The load-balanced zigzag layout: pre-permuted batches, per-token
    position offsets, cross-CHUNK next-token targets — all of it must
    still reproduce dense single-device GPT training step for step."""
    from dear_pytorch_tpu.models import data
    from dear_pytorch_tpu.models.gpt import GptLmHeadModel
    from dear_pytorch_tpu.parallel import sp as SP
    from dear_pytorch_tpu.parallel.ring_attention import zigzag_permutation

    cfg = _gpt_cfg()
    batch = data.synthetic_gpt_batch(
        jax.random.PRNGKey(21), B, seq_len=S, vocab_size=cfg.vocab_size
    )
    dense = GptLmHeadModel(cfg)
    params = dense.init(
        {"params": jax.random.PRNGKey(0)}, batch["input_ids"], train=False
    )["params"]
    ref_losses = _gpt_dense_losses(cfg, params, batch["input_ids"], steps=3)

    sp_world = mesh2d.shape["sp"]
    perm = zigzag_permutation(S, sp_world)
    zbatch = {"input_ids": batch["input_ids"][:, perm]}

    model = SP.sp_gpt_model(cfg, attention="zigzag")
    ts = build_train_step(
        SP.make_sp_gpt_loss_fn(model, vocab_size=cfg.vocab_size,
                               train=False, zigzag=True),
        params,
        mesh=mesh2d,
        axis_name=("dp", "sp"),
        mean_axes=("dp",),
        batch_spec_fn=SP.bert_sp_batch_specs,
        threshold_mb=0.01,
        optimizer=fused_sgd(lr=0.05, momentum=0.9),
        donate=False,
    )
    state = ts.init(params)
    losses = []
    for _ in range(3):
        state, m = ts.step(state, zbatch)
        losses.append(float(m["loss"]))
    np.testing.assert_allclose(losses, ref_losses, rtol=2e-4, atol=2e-5)

    # zigzag is causal-only and refuses silent fallbacks
    with pytest.raises(ValueError, match="causal-only"):
        SP.sp_bert_model(CFG, attention="zigzag")
