"""Expert parallelism: the GShard einsum-dispatch MoE must equal direct
per-token expert application (no-drop capacity), train correctly with
expert weights sharded over 'ep', and show partitioner-inserted
collectives."""

import jax
import jax.numpy as jnp
import numpy as np

from dear_pytorch_tpu.parallel import ep as EP
from dear_pytorch_tpu.parallel import tp as TP
from dear_pytorch_tpu.utils import hlo

T, H, F, E = 64, 16, 32, 8


def _setup(capacity_factor=float(E)):
    model = EP.MoeMlp(num_experts=E, mlp_dim=F,
                      capacity_factor=capacity_factor)
    x = jax.random.normal(jax.random.PRNGKey(1), (T, H))
    params = model.init(jax.random.PRNGKey(0), x)["params"]
    return model, params, x


def test_moe_equals_direct_expert_application():
    model, params, x = _setup()  # capacity == T: nothing can drop
    got = model.apply({"params": params}, x)

    logits = x @ params["router"]
    expert = np.asarray(jnp.argmax(logits, axis=-1))
    probs = np.asarray(jax.nn.softmax(logits, axis=-1))
    want = np.zeros((T, H), np.float32)
    for t in range(T):
        e = expert[t]
        h = jax.nn.gelu(x[t] @ params["wi"][e])
        want[t] = np.asarray(h @ params["wo"][e]) * probs[t, e]
    np.testing.assert_allclose(np.asarray(got), want, rtol=2e-5, atol=2e-5)


def test_moe_drops_overflow_tokens():
    model, params, x = _setup(capacity_factor=0.25)  # C = 2 per expert
    y = model.apply({"params": params}, x)
    # dropped tokens produce exactly zero output
    nonzero_rows = np.count_nonzero(
        np.abs(np.asarray(y)).sum(axis=-1) > 1e-9
    )
    assert nonzero_rows <= E * 2


def test_ep_sharded_training_matches_replicated():
    model, params, x = _setup()
    y = jax.random.normal(jax.random.PRNGKey(2), (T, H))

    def loss_fn(p, batch):
        bx, by = batch
        out = model.apply({"params": p}, bx)
        return jnp.mean((out - by) ** 2)

    def run(mesh):
        ts = TP.make_tp_train_step(
            loss_fn, params, mesh=mesh, rules=EP.EP_RULES, tp_axis="ep",
            lr=0.05, momentum=0.9, donate=False,
            batch_spec=jax.P(),  # tiny T: keep the batch replicated
        )
        state = ts.init(params)
        losses = []
        for _ in range(4):
            state, m = ts.step(state, (x, y))
            losses.append(float(m["loss"]))
        return ts, state, losses

    mesh1 = jax.sharding.Mesh(
        np.asarray(jax.devices()[:1]).reshape(1, 1), ("dp", "ep")
    )
    meshe = jax.sharding.Mesh(
        np.asarray(jax.devices()[:8]).reshape(1, 8), ("dp", "ep")
    )
    _, _, want = run(mesh1)
    ts, state, got = run(meshe)
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)
    assert got[-1] < got[0]

    # expert weights actually sharded 1 expert/device
    wi = state.params["wi"]
    assert tuple(wi.sharding.spec)[0] == "ep"
    assert wi.addressable_shards[0].data.shape[0] == 1

    # partitioner inserted cross-device collectives for the dispatch
    text = ts.lower(state, (x, y)).compile().as_text()
    ops = hlo.parse_entry(text)
    kinds = {o.kind for o in ops}
    assert kinds & {"all-to-all", "all-reduce", "all-gather",
                    "reduce-scatter", "collective-permute"}, kinds
