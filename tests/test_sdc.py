"""SDC sentinel (`resilience.sdc`): per-bucket fingerprint voting,
replay-based blame, and the durable quarantine ledger.

The detection premise is DeAR-specific: post-reduce bucket state is
replica-identical by construction, so an exact uint32 checksum per
bucket — computed IN-PROGRAM by the compiled step and gathered only at
health-sync cadence — turns silent per-host corruption into a minority
vote localized to (rank, bucket). The red/green test here pins the
sensitivity ordering the subsystem exists for: a one-ulp weight flip
that the loss-bits desync sentinel cannot see for multiple steps moves
the bucket fingerprint on the very first corrupt step.

Blame and quarantine are pure-python (transport-backed) and tested
directly; the full arc — vote, rollback replay, conviction, rc-75
drain, fresh-host backfill, probation readmission, and the serving
shadow-replay twin — runs as `scripts/chaos_check.py --sdc`, gated
three-consecutive-green below.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from dear_pytorch_tpu.resilience import inject as INJ
from dear_pytorch_tpu.resilience import sdc
from dear_pytorch_tpu.resilience.cluster import LocalTransport

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _fp(*words):
    return sdc.encode_fingerprints(np.asarray(words, dtype=np.uint32))


# -- the fingerprint vote -----------------------------------------------------


def test_vote_localizes_minority_to_rank_and_bucket():
    clean = _fp(10, 20, 30)
    bad = _fp(10, 21, 30)
    assert sdc.vote({0: clean, 1: bad, 2: clean}) == [(1, 1)]


def test_vote_needs_three_voters_to_blame():
    # with two voters a disagreement is detectable but not attributable
    assert sdc.vote({0: _fp(1), 1: _fp(2)}) == []
    # abstainers (empty fingerprint) don't count toward the quorum
    assert sdc.vote({0: _fp(1), 1: _fp(2), 2: ""}) == []


def test_vote_requires_strict_majority_per_bucket():
    # three-way split: nobody holds a majority, nobody is blamed
    assert sdc.vote({0: _fp(1), 1: _fp(2), 2: _fp(3)}) == []


def test_vote_shape_stragglers_abstain():
    # a mid-rescale rank with a different bucket count must not poison
    # the vote; with it abstaining only 2 comparable voters remain
    assert sdc.vote({0: _fp(1, 2), 1: _fp(1, 2, 3), 2: _fp(1, 9)}) == []
    # with 3 comparable voters the straggler is simply ignored
    assert sdc.vote(
        {0: _fp(1, 2), 1: _fp(1, 2, 3), 2: _fp(1, 9), 3: _fp(1, 2)}
    ) == [(2, 1)]


def test_fingerprint_roundtrip_and_reference_checksum():
    words = np.asarray([0, 1, 0xFFFFFFFF], dtype=np.uint32)
    enc = sdc.encode_fingerprints(words)
    assert isinstance(enc, str) and enc
    # the host-side reference agrees with itself across layouts
    a = np.arange(12, dtype=np.float32).reshape(3, 4)
    assert sdc.fingerprint_array(a) == sdc.fingerprint_array(a.ravel())
    b = a.copy()
    b[1, 2] = np.float32(np.frombuffer(
        (np.frombuffer(b[1, 2].tobytes(), np.uint32) | 1).tobytes(),
        np.float32)[0])
    assert sdc.fingerprint_array(a) != sdc.fingerprint_array(b)


# -- the quarantine ledger ----------------------------------------------------


def test_ledger_strikeout_quarantines_and_readmit_clears():
    led = sdc.SdcLedger(LocalTransport(), strike_threshold=3)
    assert not led.strike("h1", rank=1, bucket=0, step=5)["quarantined"]
    assert not led.strike("h1", rank=1, bucket=2, step=9)["quarantined"]
    st = led.strike("h1", rank=1, bucket=1, step=12)
    assert st["quarantined"] and st["strikes"] == 3
    assert led.quarantined("h1")
    kinds = [e["kind"] for e in led.events("h1")]
    assert kinds == ["strike", "strike", "strike", "quarantine"]
    assert led.quarantined_hosts() == ["h1"]
    st = led.readmit("h1", proof="selftest")
    assert not st["quarantined"] and st["strikes"] == 0
    # strike history restarts after readmission
    assert not led.strike("h1", rank=1, bucket=0, step=40)["quarantined"]


def test_ledger_conviction_is_idempotent_while_quarantined():
    led = sdc.SdcLedger(LocalTransport(), strike_threshold=3)
    st = led.convict("h2", rank=2, bucket=1, step=7)
    assert st["quarantined"] and st["convicted"]
    led.convict("h2", rank=2, bucket=1, step=8)
    assert len(led.events("h2")) == 1  # no-op while already quarantined
    led.readmit("h2")
    led.convict("h2", rank=2, bucket=0, step=30)  # re-offence lands
    assert [e["kind"] for e in led.events("h2")] == [
        "conviction", "readmit", "conviction"]


def test_ledger_replicated_writers_dedupe_first_writer_wins():
    # every rank appends the same deterministic vote outcome: one record
    t = LocalTransport()
    a = sdc.SdcLedger(t, strike_threshold=3)
    b = sdc.SdcLedger(t, strike_threshold=3)
    a.convict("h3", rank=1, bucket=0, step=5)
    b.convict("h3", rank=1, bucket=0, step=5)
    assert len(a.events("h3")) == 1
    # a genuinely different record (a real race) lands as its own event
    b.readmit("h3")
    a.strike("h3", rank=1, bucket=0, step=9)
    b.strike("h3", rank=2, bucket=1, step=9)
    assert len([e for e in a.events("h3") if e["kind"] == "strike"]) == 2


# -- the replay arbiter -------------------------------------------------------


def _sentinel(host="h-self", transport=None):
    led = sdc.SdcLedger(transport or LocalTransport(), strike_threshold=2)
    return sdc.SdcSentinel(host=host, ledger=led), led


def test_replay_reproduction_convicts():
    s, led = _sentinel()
    hosts = {0: "h0", 1: "h1", 2: "h2"}
    acts = s.note_votes([(1, 0)], hosts, step=5)
    assert acts["opened"] == ["h1"] and not acts["convicted"]
    assert not led.quarantined("h1")  # one vote is suspicion, not proof
    # the coordinated rollback re-ran the window; same minority again
    acts = s.note_votes([(1, 0)], hosts, step=5)
    assert acts["convicted"] == ["h1"]
    assert led.quarantined("h1")
    ev = [e for e in led.events("h1") if e["kind"] == "conviction"][0]
    assert ev["rank"] == 1 and ev["bucket"] == 0 and ev["step"] == 5


def test_clean_replay_is_a_strike_not_a_conviction():
    s, led = _sentinel()
    hosts = {0: "h0", 1: "h1", 2: "h2"}
    s.note_votes([(1, 2)], hosts, step=5)
    acts = s.note_votes([], hosts, step=5)
    assert acts["struck"] == ["h1"] and not acts["convicted"]
    st = led.state("h1")
    assert st["strikes"] == 1 and not st["quarantined"]
    # strikes accumulate across separate transients to a strikeout
    s.note_votes([(1, 2)], hosts, step=9)
    acts = s.note_votes([], hosts, step=9)
    assert acts["convicted"] == ["h1"]  # threshold=2 crossed
    assert led.quarantined("h1")


def test_undecidable_sync_keeps_the_case_pending():
    # a sync too thin to vote (shrink mid-flight) must not read as a
    # clean replay — the open case waits for the next decidable vote
    s, led = _sentinel()
    hosts = {0: "h0", 1: "h1", 2: "h2"}
    s.note_votes([(1, 0)], hosts, step=5)
    acts = s.note_votes([], hosts, step=6, voted=False)
    assert acts == {"opened": [], "convicted": [], "struck": []}
    assert "h1" in s.open_cases
    acts = s.note_votes([(1, 0)], hosts, step=5)
    assert acts["convicted"] == ["h1"]


def test_own_conviction_requests_drain():
    s, led = _sentinel(host="h1")
    hosts = {0: "h0", 1: "h1", 2: "h2"}
    s.note_votes([(1, 0)], hosts, step=5)
    assert not s.drain_requested
    s.note_votes([(1, 0)], hosts, step=5)
    assert s.drain_requested


# -- the fault: a flip the loss-bits sentinel cannot see ----------------------


def test_flip_grammar_arms_persistent_faults():
    faults = INJ.parse_faults("flip@5:2:r1,flip_logits@3:r0")
    assert faults[0] == INJ.Fault(kind="flip", step=5, arg=2.0, rank=1)
    assert faults[1] == INJ.Fault(kind="flip_logits", step=3, rank=0)
    inj = INJ.FaultInjector(faults, own_rank=1)
    assert inj.flip_bucket_for(4) is None
    assert inj.flip_bucket_for(5) == 2
    # a stuck lane, not a hiccup: armed for every later attempt — the
    # post-rollback replay reproduces it and the arbiter convicts
    assert inj.flip_bucket_for(6) == 2
    other = INJ.FaultInjector(faults, own_rank=0)
    assert other.flip_bucket_for(5) is None  # rank-targeted
    assert other.corrupt_tokens(3, [4, 5]) == [5, 5]
    assert other.corrupt_tokens(4, [4, 5]) == [5, 5]  # persistent


def test_fingerprint_catches_what_loss_bits_miss(mesh, monkeypatch):
    """The red/green sensitivity ordering: a one-ulp flip of a real
    weight leaves the loss BITWISE IDENTICAL for several steps (the
    desync sentinel is blind) while the exact per-bucket checksum
    diverges on the first corrupt step — and the 3-voter minority vote
    localizes it to (rank, flipped bucket)."""
    import jax

    from dear_pytorch_tpu.ops.fused_sgd import fused_sgd
    from dear_pytorch_tpu.parallel import build_train_step
    from tests.test_dear_numerics import _data, _loss_fn, _mlp_params

    monkeypatch.setenv("DEAR_SDC", "1")  # resolved at build time
    params = _mlp_params(jax.random.PRNGKey(0))
    ts = build_train_step(
        _loss_fn, params, mesh=mesh, threshold_mb=0.0008, donate=False,
        optimizer=fused_sgd(lr=0.05, momentum=0.9))
    clean = dirty = ts.init(params)
    batches = [_data(jax.random.PRNGKey(100 + i)) for i in range(4)]
    loss_blind_steps = 0
    flipped_bucket = None
    for i, batch in enumerate(batches):
        clean, mc = ts.step(clean, batch)
        dirty, flipped_bucket, idx = INJ.flip_state_bucket(
            dirty, 0, ts.plan)
        assert idx == ts.plan.buckets[flipped_bucket].size - 1
        dirty, md = ts.step(dirty, batch)
        fc = np.asarray(jax.device_get(mc["sdc_fp"]))
        fd = np.asarray(jax.device_get(md["sdc_fp"]))
        # caught within ONE check interval, localized to the bucket
        assert (fc != fd).any(), f"fingerprint blind at step {i}"
        assert (fc != fd)[flipped_bucket]
        lc = np.asarray(jax.device_get(mc["loss"]))
        ld = np.asarray(jax.device_get(md["loss"]))
        if lc.tobytes() == ld.tobytes():
            loss_blind_steps += 1
        suspects = sdc.vote({
            0: sdc.encode_fingerprints(fc),
            1: sdc.encode_fingerprints(fd),
            2: sdc.encode_fingerprints(fc)})
        assert (1, int(flipped_bucket)) in suspects
        assert all(r == 1 for r, _ in suspects)
    # ...while the loss-bits sentinel misses the corruption for >= K
    # steps (one-ulp perturbations drown in the float32 reductions)
    assert loss_blind_steps >= 2, (
        f"loss bits diverged too fast ({loss_blind_steps} blind steps) "
        "— the fingerprint no longer demonstrates extra sensitivity")


def test_flip_state_bucket_is_idempotent():
    import jax  # noqa: F401 — flip_state_bucket device_gets

    class _S:
        def __init__(self, buffers):
            self.buffers = buffers

        def _replace(self, buffers):
            return _S(buffers)

    buf = np.arange(8, dtype=np.float32)
    s1, b, idx = INJ.flip_state_bucket(_S((buf,)), 0, None)
    assert (b, idx) == (0, 7)
    s2, _, _ = INJ.flip_state_bucket(s1, 0, None)
    one = np.asarray(s1.buffers[0])
    two = np.asarray(s2.buffers[0])
    assert one.tobytes() == two.tobytes()  # |=, not XOR: replay-stable
    assert one.tobytes() != buf.tobytes()


# -- host identity: strikes follow the HOST, not the rank ---------------------


def _supervisor(tmp_path, **kw):
    from launch.supervisor import ElasticSupervisor

    env = {"DEAR_SDC": "1", "PATH": os.environ.get("PATH", "")}
    return ElasticSupervisor(
        2, [sys.executable, "-c", "pass"],
        elastic_dir=str(tmp_path / "elastic"), env=env, **kw)


def test_supervisor_charges_strikes_to_the_host_across_incarnations(
        tmp_path):
    sup = _supervisor(tmp_path)
    host = sup._seat_host(0)
    assert host  # minted once
    # the seat keeps its host across relaunches while the host is clean:
    # a respawned rank INHERITS the ledger state its hardware earned
    assert sup._seat_host(0) == host
    led = sup.ledger()
    led.strike(host, rank=0, bucket=0, step=5)
    led.strike(host, rank=0, bucket=0, step=9)
    assert sup._seat_host(0) == host  # struck but not out: same host
    assert led.state(host)["strikes"] == 2
    led.strike(host, rank=0, bucket=1, step=13)  # threshold (default 3)
    assert led.quarantined(host)
    # quarantined: the seat is re-seated on a FRESH host, never the
    # convicted one — and probation for the old host is kicked off
    sup._probation_done.add(host)  # keep the unit test subprocess-free
    fresh = sup._seat_host(0)
    assert fresh != host
    assert ("sdc_reseat", 0) in sup.events
    # the fresh host starts clean while the old host's record persists
    assert not led.quarantined(fresh)
    assert led.quarantined(host)
    # identity is durable: a restarted supervisor reads the same pool
    sup2 = _supervisor(tmp_path)
    assert sup2._seat_host(0) == fresh
    assert sup2._seat_host(1) not in (host, fresh)


def test_probation_gate_blocks_until_selftest_passes(tmp_path):
    led = sdc.ledger_from_dir(str(tmp_path / "sdc"))
    led.convict("badhost", rank=1, bucket=0, step=5)
    # a clean host passes straight through, no self-test
    assert sdc.probation_gate(led, "cleanhost")
    # the quarantined host must pass the known-answer burn-in, which
    # writes its own readmit record (steps=2 keeps the test fast)
    assert sdc.probation_gate(led, "badhost", steps=2)
    assert not led.quarantined("badhost")
    assert [e["kind"] for e in led.events("badhost")] == [
        "conviction", "readmit"]


def test_scale_policy_caps_capacity_by_quarantined_hosts(tmp_path):
    from dear_pytorch_tpu.resilience.scale import ScalePolicy

    cap = tmp_path / "capacity.json"
    cap.write_text(json.dumps({"target_world": 3}))
    pol = ScalePolicy(capacity_file=str(cap), hysteresis_s=0.0,
                      max_world=3)
    # while a host sits in the ledger the usable pool is smaller: the
    # backfill that would re-seat it is HELD (this is what makes
    # quarantine deadlock-free only together with drain-time probation)
    for _ in range(3):
        d = pol.decide(live_world=2, live_ranks=(0, 2), quarantined=1)
        assert d is None
    # readmission lifts the cap and the backfill proceeds
    decisions = [pol.decide(live_world=2, live_ranks=(0, 2), quarantined=0)
                 for _ in range(3)]
    ups = [d for d in decisions if d is not None]
    assert ups and ups[0].kind == "scale_up" and ups[0].count == 1


# -- serving-side quality gauge ----------------------------------------------


def test_held_out_headroom_scores_real_eval_not_just_finiteness():
    from dear_pytorch_tpu.serving.weights import held_out_headroom

    rng = np.random.default_rng(0)
    good = {"w": rng.standard_normal((32, 32)).astype(np.float32) * 0.02}
    h = held_out_headroom(good)
    assert 0.5 < h <= 1.0  # near-uniform prediction reads high
    # NaN poisoning reads 0.0 (everything the old placeholder caught)
    poisoned = {"w": good["w"].copy()}
    poisoned["w"][0, 0] = np.nan
    assert held_out_headroom(poisoned) == 0.0
    # finite but value-damaged weights move the gauge DOWN — the
    # sensitivity the finite-fraction placeholder lacked by construction
    damaged = {"w": good["w"] * 1e4}
    assert held_out_headroom(damaged) < h
    # the gauge is a real NLL eval: a confidently-wrong forward scores 0
    # while a uniform one scores ~1, with ALL-FINITE params in both
    def confident_wrong(params, ctx):
        logits = np.full(32, -10.0)
        logits[0] = 10.0
        return logits
    assert held_out_headroom(good, apply_fn=confident_wrong) == 0.0
    assert held_out_headroom(
        good, apply_fn=lambda p, c: np.zeros(32)) > 0.99


# -- offline policy search ----------------------------------------------------


def test_simulate_sdc_models_the_full_quarantine_arc():
    from dear_pytorch_tpu.observability import sim

    topo = sim.SimTopology(num_slices=1, chips_per_slice=8)
    trace = sim.TrafficTrace.poisson(rps=100.0, duration_s=1.5,
                                     prompt_tokens=16, decode_tokens=4,
                                     seed=3)
    out = sim.simulate_sdc(topo, trace, replicas=3, shadow_every=2,
                           strike_threshold=1, corrupt_replica=1,
                           corrupt_at_s=0.3, probation_s=0.5)
    # the arc: corruption starts, the shadow replay detects, the culprit
    # quarantines, probation readmits — in that order
    assert out["detect_s"] is not None and out["detect_s"] >= 0.0
    assert out["quarantined_at_s"] is not None
    assert out["readmit_at_s"] is not None
    assert out["readmit_at_s"] > out["quarantined_at_s"] >= 0.3
    # exposure is bounded (possibly zero: the detecting shadow can land
    # on the culprit before it serves a corrupt primary) and the
    # policy's overhead is priced, not free
    assert 0 <= out["exposed"] < out["requests"]
    assert out["mismatches"] >= 1
    assert out["shadows"] > 0 and out["arbiters"] >= 1
    # zero-drop: fencing re-dispatches, it never loses requests
    assert out["requests"] >= len(trace.requests)
    # a tighter cadence can only expose fewer corrupted responses
    tight = sim.simulate_sdc(topo, trace, replicas=3, shadow_every=1,
                             strike_threshold=1, corrupt_replica=1,
                             corrupt_at_s=0.3, probation_s=0.5)
    assert tight["exposed"] <= out["exposed"]


# -- the acceptance storm: three consecutive greens ---------------------------


@pytest.mark.timeout(1300, method="signal")
def test_chaos_check_sdc_storm_three_consecutive(tmp_path):
    """scripts/chaos_check.py --sdc, 3/3 consecutive (ISSUE-20
    acceptance): the fingerprint vote localizes the flipped bucket to
    the injected rank, the rollback replay convicts, the supervisor
    quarantine-drains the host and backfills the seat on a FRESH host
    while probation readmits the old one, no corrupt step is reachable
    from any published checkpoint, and the serving leg catches a
    post-signing token corruption via the router's shadow replay into
    the same ledger — with the quarantine capacity cap holding the
    backfill until readmission and zero dropped requests throughout.
    Three consecutive runs guard against vote/drain races that a single
    green would leave latent."""
    script = os.path.join(REPO, "scripts", "chaos_check.py")
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    for attempt in range(3):
        proc = subprocess.run(
            [sys.executable, script, "--sdc", "--checkpoint-every", "4",
             "--workdir", str(tmp_path / f"run{attempt}")],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, timeout=420)
        assert proc.returncode == 0, (
            f"run {attempt}: " + proc.stdout[-3000:])
        assert "CHAOS CHECK PASSED" in proc.stdout, (
            f"run {attempt}: " + proc.stdout[-3000:])
