"""Property tests for the fusion engine (reference had none — SURVEY.md §4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dear_pytorch_tpu.ops import fusion


def _params(rng, sizes):
    """A nested dict pytree with layer-grouped kernel/bias leaves."""
    tree = {}
    for i, n in enumerate(sizes):
        tree[f"layer{i:02d}"] = {
            "kernel": jnp.asarray(rng.standard_normal((n, 4)), jnp.float32),
            "bias": jnp.asarray(rng.standard_normal((4,)), jnp.float32),
        }
    return tree


def test_roundtrip_threshold(rng):
    params = _params(rng, [8, 16, 128, 3, 700, 9])
    plan = fusion.plan_by_threshold(params, world=8, threshold_mb=0.002)
    bufs = fusion.pack_all(params, plan)
    for b, buf in zip(plan.buckets, bufs):
        assert buf.shape == (b.padded_size,)
        assert b.padded_size % 8 == 0
        assert b.shard_size * 8 == b.padded_size
    out = fusion.unpack_all(bufs, plan)
    jax.tree.map(np.testing.assert_array_equal, out, params)


def test_threshold_none_single_bucket(rng):
    params = _params(rng, [8, 16, 32])
    plan = fusion.plan_by_threshold(params, world=4, threshold_mb=None)
    assert plan.num_buckets == 1
    assert plan.buckets[0].size == plan.total_size


def test_layer_atomicity(rng):
    # kernel+bias of one layer must never be split across buckets
    params = _params(rng, [100, 100, 100, 100])
    plan = fusion.plan_by_threshold(params, world=2, threshold_mb=0.0001)
    for b in plan.buckets:
        layers = {plan.leaves[i].layer for i in b.leaf_ids}
        for other in plan.buckets:
            if other.index != b.index:
                assert layers.isdisjoint(
                    {plan.leaves[i].layer for i in other.leaf_ids}
                )


def test_nearby_layers(rng):
    params = _params(rng, [4] * 10)
    plan = fusion.plan_by_nearby_layers(params, world=2, k=4)
    # 10 layers, k=4 -> buckets of 4,4,2 layers = 8,8,4 leaves
    assert [len(b.leaf_ids) for b in plan.buckets] == [8, 8, 4]
    plan1 = fusion.plan_by_nearby_layers(params, world=2, k=1)
    assert plan1.num_buckets == 10
    plan_all = fusion.plan_by_nearby_layers(params, world=2, k=-1)
    assert plan_all.num_buckets == 1


def test_flags(rng):
    params = _params(rng, [4] * 6)
    flags = [0, 0, 1, 0, 1, 0]  # split before layers 2 and 4
    plan = fusion.plan_by_flags(params, world=2, flags=flags)
    assert plan.num_buckets == 3
    assert [len(b.leaf_ids) // 2 for b in plan.buckets] == [2, 2, 2]
    with pytest.raises(ValueError):
        fusion.plan_by_flags(params, world=2, flags=[0, 1])


def test_offsets_contiguous(rng):
    params = _params(rng, [5, 7, 11])
    plan = fusion.plan_by_threshold(params, world=8, threshold_mb=None)
    b = plan.buckets[0]
    expect = 0
    for leaf_id, off in zip(b.leaf_ids, b.offsets):
        assert off == expect
        expect += plan.leaves[leaf_id].size
    assert b.size == expect


def test_make_plan_precedence(rng):
    params = _params(rng, [4] * 6)
    p = fusion.make_plan(params, 2, threshold_mb=1.0, nearby_layers=2)
    assert p.num_buckets == 3  # nearby wins over threshold
    p = fusion.make_plan(params, 2, nearby_layers=2, flags=[1] * 6)
    assert p.num_buckets == 6  # flags win over nearby


def test_pack_inside_jit(rng):
    params = _params(rng, [16, 8])
    plan = fusion.make_plan(params, world=4, threshold_mb=None)

    @jax.jit
    def f(p):
        bufs = fusion.pack_all(p, plan)
        return fusion.unpack_all(bufs, plan)

    out = f(params)
    jax.tree.map(np.testing.assert_array_equal, out, params)


def test_scalar_and_empty_edge_cases(rng):
    params = {"a": {"w": jnp.float32(3.0)}, "b": {"w": jnp.ones((3,))}}
    plan = fusion.make_plan(params, world=8, threshold_mb=None)
    assert plan.total_size == 4
    bufs = fusion.pack_all(params, plan)
    assert bufs[0].shape == (8,)  # padded 4 -> 8
    out = fusion.unpack_all(bufs, plan)
    assert np.asarray(out["a"]["w"]) == 3.0

    with pytest.raises(ValueError):
        fusion.make_plan(params, world=0)


def test_segment_ids_searchsorted_equivalence():
    """The train step derives per-element parameter ids via searchsorted
    over bucket offsets (no O(params) constant); it must agree with the
    explicit FusionPlan.segment_ids map everywhere, padding included."""
    import jax.numpy as jnp

    from dear_pytorch_tpu.ops import fusion as F

    params = {
        "a": {"kernel": jnp.zeros((5, 3)), "bias": jnp.zeros((3,))},
        "b": {"kernel": jnp.zeros((3, 7))},
    }
    plan = F.make_plan(params, world=8, nearby_layers=2)
    for b in plan.buckets:
        ref = plan.segment_ids(b.index)
        starts = jnp.asarray(b.offsets, jnp.int32)
        pos = jnp.arange(b.padded_size, dtype=jnp.int32)
        seg = jnp.searchsorted(starts, pos, side="right").astype(jnp.int32) - 1
        seg = jnp.where(pos < b.size, seg, len(b.leaf_ids))
        np.testing.assert_array_equal(np.asarray(seg), ref)
        assert b.pad > 0 or b.padded_size == b.size
