"""bench.py is the driver contract (ONE JSON line, primary metric first);
these tests pin its helper logic and the contract itself so a regression
is caught in CI rather than in the driver's end-of-round capture."""

import importlib.util
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_bench():
    spec = importlib.util.spec_from_file_location(
        "bench_under_test", os.path.join(REPO, "bench.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def bench():
    return _load_bench()


def test_env_enabled(bench, monkeypatch):
    monkeypatch.delenv("DEAR_BENCH_VIT", raising=False)
    assert bench._env_enabled("DEAR_BENCH_VIT")
    for off in ("0", "false", "no", ""):
        monkeypatch.setenv("DEAR_BENCH_VIT", off)
        assert not bench._env_enabled("DEAR_BENCH_VIT")
    monkeypatch.setenv("DEAR_BENCH_VIT", "1")
    assert bench._env_enabled("DEAR_BENCH_VIT")


def test_gather_dtype_world_gating(bench, monkeypatch):
    import jax.numpy as jnp

    monkeypatch.delenv("DEAR_BENCH_GATHER_DTYPE", raising=False)
    assert bench._gather_dtype(1) is None          # no gather traffic
    assert bench._gather_dtype(8) is jnp.bfloat16  # halve AG bytes on ICI
    monkeypatch.setenv("DEAR_BENCH_GATHER_DTYPE", "bf16")
    assert bench._gather_dtype(1) is jnp.bfloat16  # explicit override wins
    monkeypatch.setenv("DEAR_BENCH_GATHER_DTYPE", "fp32")
    assert bench._gather_dtype(8) is None
    monkeypatch.setenv("DEAR_BENCH_GATHER_DTYPE", "bogus")
    with pytest.raises(SystemExit, match="bogus"):
        bench._gather_dtype(1)


def test_bert_baseline_pin_on_first_capture(bench, monkeypatch, tmp_path):
    """The BERT pin must come from the EARLIEST BENCH_r*.json that carries
    a bert_base value (pin-on-first-capture), tolerating malformed files."""
    (tmp_path / "BENCH_r01.json").write_text("not json")
    (tmp_path / "BENCH_r02.json").write_text(json.dumps({
        "rc": 1, "parsed": None}))
    (tmp_path / "BENCH_r03.json").write_text(json.dumps({
        "parsed": {"metric": "resnet50_bs64_train_img_sec_per_chip",
                   "value": 2000.0,
                   "extra_metrics": [
                       {"metric": "bert_base_sen_sec_per_chip",
                        "value": 1111.0}]}}))
    (tmp_path / "BENCH_r04.json").write_text(json.dumps({
        "parsed": {"metric": "resnet50_bs64_train_img_sec_per_chip",
                   "value": 2300.0,
                   "extra_metrics": [
                       {"metric": "bert_base_sen_sec_per_chip",
                        "value": 2222.0}]}}))
    # _bert_baseline derives its directory from the module's __file__ —
    # patch that, not the process-global os.path.dirname
    monkeypatch.setattr(bench, "__file__", str(tmp_path / "bench.py"))
    # protocol tag follows the RESOLVED record's round, not a constant
    assert bench._bert_baseline() == (1111.0, "per-iter-fetch-r03")


def test_smoke_contract_one_json_line():
    """End-to-end: the smoke bench must emit EXACTLY one stdout line and it
    must parse as the contract object, primary metric first."""
    env = {k: v for k, v in os.environ.items()
           if not k.startswith("DEAR_")}  # ambient knobs must not leak in
    env.update(
        JAX_PLATFORMS="cpu", DEAR_BENCH_SMOKE="1",
        DEAR_BENCH_BERT_LARGE="0", DEAR_BENCH_VIT="0",
        DEAR_DISABLE_DISTRIBUTED="1",
        # cross-host CPU AOT cache entries can SIGILL (see tests/conftest)
        DEAR_COMPILATION_CACHE_DIR="off",
        PYTHONPATH=REPO + os.pathsep + env.get("PYTHONPATH", ""),
    )
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        env=env, capture_output=True, text=True, timeout=480,
    )
    assert proc.returncode == 0, proc.stderr[-800:]
    lines = [l for l in proc.stdout.splitlines() if l.strip()]
    assert len(lines) == 1, lines
    out = json.loads(lines[0])
    assert out["metric"] == "resnet50_bs64_train_img_sec_per_chip"
    assert out["value"] > 0 and out["unit"] == "img/s"
    assert {m["metric"] for m in out["extra_metrics"]} == {
        "bert_base_sen_sec_per_chip", "gpt2_s1024_tok_sec_per_chip"}
    for m in out["extra_metrics"]:
        assert "error" not in m and m["value"] > 0, m
