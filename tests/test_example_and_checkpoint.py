"""End-to-end user-journey tests: the MNIST example converges (the
reference's convergence smoke test, SURVEY.md §4.3) and checkpoint/resume
round-trips exactly."""

import importlib.util
import os

import jax
import numpy as np
import pytest

from dear_pytorch_tpu.ops.fused_sgd import fused_sgd
from dear_pytorch_tpu.parallel import build_train_step
from dear_pytorch_tpu.utils import checkpoint as ckpt

from tests.test_dear_numerics import _data, _loss_fn, _mlp_params


def _load_example(filename: str = "mnist.py"):
    root = os.path.join(os.path.dirname(__file__), "..", "examples",
                        filename)
    name = filename.removesuffix(".py") + "_example"
    spec = importlib.util.spec_from_file_location(name, root)
    m = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(m)
    return m


def test_mnist_example_converges(mesh):
    m = _load_example()
    acc = m.main([
        "--data", "synthetic",
        "--epochs", "3", "--batch-size", "64", "--train-size", "2048",
        "--test-size", "512", "--lr", "0.05",
    ])
    assert acc > 0.9, acc


def test_mnist_example_learns_real_data(mesh):
    """REAL-data convergence through the full dear schedule (delayed
    update + sharded buffers + ShardedSampler input path): >= 90% held-out
    accuracy on scikit-learn's real handwritten digits. This is the test
    that fails if the delayed-update semantics break real learning —
    synthetic class-template data is too separable to falsify that
    (reference examples/mnist/pytorch_mnist.py:189-203 is the analogous
    real-MNIST demo)."""
    m = _load_example()
    acc = m.main([
        "--data", "real", "--epochs", "10", "--batch-size", "64",
        "--lr", "0.05", "--momentum", "0.9",
    ])
    assert acc >= 0.9, acc


def test_char_gpt_example_learns_real_text():
    """Causal-LM real-data convergence: the byte-level GPT must cut
    held-out bits/byte on the checked-in REAL English corpus from ~8.0
    (untrained) to < 5.5 in 100 quick steps through the dear schedule —
    below the ~5.6 of an English byte histogram, so it fails if the
    delayed-update semantics stop real sequence learning.

    Runs as a subprocess: the example asserts its own bar via exit code
    (main() < 5.5), and process isolation keeps a rare XLA:CPU allocator
    abort (SIGABRT mid-suite, not reproducible in isolation) from
    sinking the whole session."""
    import subprocess
    import sys

    repo = os.path.join(os.path.dirname(__file__), "..")
    env = dict(os.environ)
    env["PYTHONPATH"] = (os.path.abspath(repo) + os.pathsep
                         + env.get("PYTHONPATH", ""))
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "examples", "char_gpt.py"),
         "--steps", "100", "--sample-chars", "0"],
        capture_output=True, text=True, timeout=800, env=env,
    )
    assert proc.returncode == 0, (proc.stdout[-500:], proc.stderr[-500:])
    assert "bits/byte" in proc.stdout


def test_checkpoint_roundtrip_and_plan_guard(mesh, tmp_path):
    params = _mlp_params(jax.random.PRNGKey(0))
    batches = [_data(jax.random.PRNGKey(100 + i)) for i in range(4)]
    opt = fused_sgd(lr=0.1, momentum=0.9)
    ts = build_train_step(_loss_fn, params, mesh=mesh, optimizer=opt,
                          threshold_mb=0.0008, donate=False)
    state = ts.init(params)
    for b in batches[:2]:
        state, _ = ts.step(state, b)

    d = str(tmp_path / "ckpts")
    ckpt.save_checkpoint(d, state, ts.plan)
    assert ckpt.latest_step(d) == 2

    template = ts.init(params)
    restored = ckpt.restore_checkpoint(d, ts, template=template)
    # restore lands ON the template's shardings (multi-host safe: no
    # host-replicated detour through device_get)
    def _check_sharding(r, t):
        assert r.sharding.is_equivalent_to(t.sharding, r.ndim), (
            r.sharding, t.sharding,
        )

    jax.tree.map(_check_sharding, restored, template)
    # exact roundtrip of every leaf (incl. sharded buffers and momentum)
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(jax.device_get(a)), np.asarray(jax.device_get(b))
        ),
        restored, state,
    )
    # ... and training continues identically from the restored state
    s1, m1 = ts.step(state, batches[2])
    s2, m2 = ts.step(restored, batches[2])
    assert float(m1["loss"]) == pytest.approx(float(m2["loss"]), rel=1e-6)

    # a different plan must be refused (single fused bucket vs 3 buckets)
    ts2 = build_train_step(_loss_fn, params, mesh=mesh, optimizer=opt,
                           threshold_mb=None, donate=False)
    with pytest.raises(ValueError, match="plan"):
        ckpt.restore_checkpoint(d, ts2, template=ts2.init(params))


def test_production_example_runs_and_resumes(mesh, tmp_path):
    """examples/production.py: fsdp + guarded async checkpoints + metrics +
    pipeline end-to-end, then resume-from-latest continues the step count."""
    m = _load_example("production.py")

    wd = str(tmp_path / "run")
    m.main(["--steps", "12", "--checkpoint-every", "5", "--log-every", "3",
            "--workdir", wd])
    from dear_pytorch_tpu.utils import checkpoint as ckpt_mod
    from dear_pytorch_tpu.utils import read_metrics

    assert ckpt_mod.latest_step(os.path.join(wd, "ckpts")) == 10
    n_recs = len(read_metrics(os.path.join(wd, "metrics.jsonl")))
    assert n_recs >= 3

    m.main(["--steps", "18", "--checkpoint-every", "5", "--log-every", "3",
            "--workdir", wd])  # resumes from step 10
    assert ckpt_mod.latest_step(os.path.join(wd, "ckpts")) == 15
    recs = read_metrics(os.path.join(wd, "metrics.jsonl"))
    assert len(recs) > n_recs
    # replayed steps (11-12) must not leave duplicate step records behind
    steps = [r["step"] for r in recs if "step" in r]
    assert len(steps) == len(set(steps)), steps


def test_async_checkpoint_roundtrip(mesh, tmp_path):
    """save_checkpoint(asynchronous=True) returns before the write commits;
    after wait_for_checkpoints the checkpoint restores exactly, and the
    state mutating AFTER the async save must not corrupt what was saved
    (Orbax snapshots the arrays up front; donate=False here, but the
    snapshot guarantee is what this pins)."""
    params = _mlp_params(jax.random.PRNGKey(0))
    batches = [_data(jax.random.PRNGKey(200 + i)) for i in range(3)]
    opt = fused_sgd(lr=0.1, momentum=0.9)
    ts = build_train_step(_loss_fn, params, mesh=mesh, optimizer=opt,
                          threshold_mb=0.0008, donate=False)
    state = ts.init(params)
    state, _ = ts.step(state, batches[0])
    saved_buf0 = np.asarray(jax.device_get(state.buffers[0]))

    d = str(tmp_path / "async_ckpts")
    ckpt.save_checkpoint(d, state, ts.plan, asynchronous=True)
    # keep training while the write is in flight
    for b in batches[1:]:
        state, _ = ts.step(state, b)
    ckpt.wait_for_checkpoints()

    assert ckpt.latest_step(d) == 1
    restored = ckpt.restore_checkpoint(d, ts, template=ts.init(params))
    assert int(jax.device_get(restored.step)) == 1
    np.testing.assert_array_equal(
        np.asarray(jax.device_get(restored.buffers[0])), saved_buf0
    )


def test_wait_for_checkpoints_noop():
    ckpt.wait_for_checkpoints()  # nothing in flight: must not raise


def test_broadcast_helpers_single_process():
    import dear_pytorch_tpu as dear

    params = {"w": np.ones((3,))}
    out = dear.broadcast_parameters(params)
    assert out is params  # identity in single-process runs
    with pytest.raises(NotImplementedError):
        dear.broadcast_parameters(params, root_rank=1)


def test_checkpoint_roundtrip_with_model_state(mesh, tmp_path):
    """Non-empty model_state (BN stats) must survive restore with fields in
    the right slots (guards the orbax dict-ordering scramble)."""
    import flax.linen as nn
    import jax.numpy as jnp

    class TinyBN(nn.Module):
        @nn.compact
        def __call__(self, x, train: bool = True):
            x = nn.Dense(8)(x)
            x = nn.BatchNorm(use_running_average=not train)(x)
            return nn.Dense(4)(x)

    model = TinyBN()
    x = jax.random.normal(jax.random.PRNGKey(0), (16, 12)) + 2.0
    y = jax.random.randint(jax.random.PRNGKey(1), (16,), 0, 4)
    variables = model.init({"params": jax.random.PRNGKey(2)}, x, train=False)
    params = variables["params"]
    mstate = {"batch_stats": variables["batch_stats"]}

    def loss_fn(p, ms, b):
        bx, by = b
        logits, new_state = model.apply(
            {"params": p, **ms}, bx, train=True, mutable=["batch_stats"]
        )
        logp = jax.nn.log_softmax(logits)
        return -jnp.mean(
            jnp.sum(logp * jax.nn.one_hot(by, 4), axis=-1)
        ), new_state

    ts = build_train_step(loss_fn, params, mesh=mesh, threshold_mb=None,
                          optimizer=fused_sgd(lr=0.05),
                          model_state_template=mstate, donate=False)
    state = ts.init(params, mstate)
    for _ in range(3):
        state, _ = ts.step(state, (x, y))

    d = str(tmp_path / "bn_ckpts")
    ckpt.save_checkpoint(d, state, ts.plan)
    restored = ckpt.restore_checkpoint(
        d, ts, template=ts.init(params, mstate)
    )
    assert int(jax.device_get(restored.step)) == 3  # step in the right slot
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(jax.device_get(a)), np.asarray(jax.device_get(b))
        ),
        restored, state,
    )


def test_compressed_multi_axis_rejected():
    import jax.numpy as jnp

    devices = np.asarray(jax.devices()[:8]).reshape(2, 4)
    mesh2d = jax.sharding.Mesh(devices, ("dp", "sp"))
    params = {"w": {"kernel": jnp.ones((4, 4))}}

    def loss_fn(p, b):
        return jnp.sum((b @ p["w"]["kernel"]) ** 2)

    with pytest.raises(ValueError, match="mean_axes"):
        build_train_step(
            loss_fn, params, mesh=mesh2d, mode="allreduce",
            axis_name=("dp", "sp"), mean_axes=("dp",),
            compressor="eftopk", density=0.5,
        )


@pytest.mark.parametrize("axis", ["tp", "pp", "pp-1f1b", "ep"])
def test_parallelism_example_smoke(axis):
    """examples/parallelism.py runs and improves for the model-sharding
    axes (dp/sp are covered end-to-end elsewhere)."""
    m = _load_example("parallelism.py")
    losses = m.main(["--axis", axis, "--steps", "4"])
    assert all(np.isfinite(v) for v in losses)
    assert losses[-1] < losses[0]  # actually trains, not just runs


def test_elastic_restore_world_resize(mesh, tmp_path):
    """Elastic recovery: a world=8 run's checkpoint resumes on a 4-device
    mesh (different padding, different shard sizes, different bucketing)
    and the continued loss trajectory matches the run that never resized —
    the global batch math is world-independent, so an exact restore of
    params + momentum must reproduce it."""
    params = _mlp_params(jax.random.PRNGKey(11))
    batches = [_data(jax.random.PRNGKey(700 + i)) for i in range(6)]
    opt = lambda: fused_sgd(lr=0.05, momentum=0.9)  # noqa: E731

    ts8 = build_train_step(_loss_fn, params, mesh=mesh, optimizer=opt(),
                          threshold_mb=0.0008, donate=False)
    state = ts8.init(params)
    for b in batches[:3]:
        state, _ = ts8.step(state, b)
    ckpt.save_checkpoint(str(tmp_path), state, ts8.plan)

    # the unresized continuation (ground truth)
    ref_losses = []
    for b in batches[3:]:
        state, m = ts8.step(state, b)
        ref_losses.append(float(m["loss"]))

    # resume on HALF the devices with a different fusion threshold
    mesh4 = jax.sharding.Mesh(
        np.asarray(jax.devices()[:4]).reshape(4), ("dp",)
    )
    ts4 = build_train_step(_loss_fn, params, mesh=mesh4, optimizer=opt(),
                          threshold_mb=0.002, donate=False)
    assert ckpt.plan_fingerprint(ts4.plan) != ckpt.plan_fingerprint(ts8.plan)
    restored = ckpt.elastic_restore(str(tmp_path), ts4)
    assert int(restored.step) == 3
    losses = []
    for b in batches[3:]:
        restored, m = ts4.step(restored, b)
        losses.append(float(m["loss"]))
    np.testing.assert_allclose(losses, ref_losses, rtol=1e-5, atol=1e-6)

    # sanity: the strict path still refuses the mismatched plan
    with pytest.raises(ValueError, match="plan"):
        ckpt.restore_checkpoint(str(tmp_path), ts4,
                                template=ts4.init(params))


def test_generate_example_smoke(mesh, capsys):
    m = _load_example("generate.py")
    m.main(["--steps", "4", "--new-tokens", "3"])
    out = capsys.readouterr().out
    assert "greedy :" in out and "sampled:" in out
    assert "step 0: loss" in out
