"""dearsim tests: wire-byte/pricing parity between the simulator and the
static accounting, determinism of every entry point, calibration/topology
round-trips, the recorded-ordering invariants `scripts/sim_check.py`
gates on, the virtual-time transport, the tuner sim backends — and the
tier-1 headline: a 1000-rank / 8-slice membership storm that resolves
slice loss -> shrink epoch -> rejoin -> lockstep against the REAL
`ElasticCluster` protocol in seconds."""

import json
import time

import pytest

from dear_pytorch_tpu.observability import counters as CTR
from dear_pytorch_tpu.observability import overlap as OV
from dear_pytorch_tpu.observability import sim
from dear_pytorch_tpu.observability.costmodel import Calibration, LinkFit

TOPO8 = sim.SimTopology(num_slices=1, chips_per_slice=8)
# bert-base-ish element counts: comm saturates the overlap windows so
# schedule differences are visible (the regime the recorded A/Bs ran in)
LAYERS = [30_000_000] + [7_000_000] * 10 + [10_000_000]


def plan8(threshold_mb=25.0):
    return sim.synthetic_plan(LAYERS, 8, threshold_mb=threshold_mb)


# ---------------------------------------------------------------------------
# parity: the simulator prices EXACTLY what the accounting emits
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("mode", sorted(CTR.MODE_LEGS))
@pytest.mark.parametrize("compressor", [None, "eftopk", "qint8"])
@pytest.mark.parametrize("partition_mb", [None, 4.0])
def test_sim_accounting_parity(mode, compressor, partition_mb):
    """Same plan, same knobs: every simulated leg carries the accounting
    row's wire/payload bytes verbatim, and its priced duration equals
    `predict_leg_times` on a homogeneous topology — including the
    gather-shaped compressed wire factor (sparse RS wire = payload x
    (world-1), dense AG)."""
    topo = sim.SimTopology(num_slices=2, chips_per_slice=8)
    plan = sim.synthetic_plan([8_000_000, 3_000_000, 2_000_000], 16,
                              threshold_mb=16.0)
    acct = CTR.plan_comm_accounting(
        plan, mode=mode, compressor=compressor, density=0.01,
        num_slices=2, dcn_partition_mb=partition_mb)
    want = OV.predict_leg_times(acct, topo.ici.alpha, topo.ici.beta)
    got = [sim._price_row_topo(r, topo, acct.world) for r in acct.rows]
    assert got == pytest.approx(want, abs=0.0, rel=1e-12)

    rep = sim.simulate_training(
        plan, topo, mode=mode, compressor=compressor, density=0.01,
        partition_mb=partition_mb, steps=1, jitter=0.0)["report"]
    assert [(l["bucket"], l["leg"], l["wire_bytes"], l["payload_bytes"])
            for l in rep["legs"]] == \
           [(r.bucket, r.leg, r.wire_bytes, r.payload_bytes)
            for r in acct.rows]
    assert rep["legs"] and all(
        l["pred_time_s"] == pytest.approx(t, rel=1e-12)
        for l, t in zip(rep["legs"], want))


def test_compressed_gather_shaped_wire_parity():
    """The compressed-RS wire model is gather-shaped (wire = compressed
    payload x (world-1), NOT ring-scaled) and the AG stays dense — the
    simulator must inherit both from the accounting, not re-derive."""
    plan = plan8()
    dense = CTR.plan_comm_accounting(plan, mode="dear")
    sparse = CTR.plan_comm_accounting(plan, mode="dear",
                                      compressor="eftopk", density=0.01)
    rep = sim.simulate_training(plan, TOPO8, mode="dear",
                                compressor="eftopk", density=0.01,
                                steps=1, jitter=0.0)["report"]
    by_leg = {}
    for l in rep["legs"]:
        by_leg.setdefault(l["leg"], 0)
        by_leg[l["leg"]] += l["wire_bytes"]
    rs_sparse = sum(r.wire_bytes for r in sparse.rows
                    if r.leg == "reduce_scatter")
    ag_dense = sum(r.wire_bytes for r in dense.rows
                   if r.leg == "all_gather")
    assert by_leg["reduce_scatter"] == rs_sparse
    assert by_leg["all_gather"] == ag_dense  # AG unaffected by compression
    # and the gather shape itself: wire = payload x (world - 1)
    for r in sparse.rows:
        if r.leg == "reduce_scatter":
            assert r.wire_bytes == r.payload_bytes * (plan.world - 1)


def test_heterogeneous_link_prices_at_slowest():
    """A degraded slice drags every ICI leg to its rate (synchronous
    ring = slowest link), never below the healthy price."""
    slow = LinkFit(alpha=1e-4, beta=1.0 / 4e9)
    topo_bad = sim.SimTopology(num_slices=2, chips_per_slice=4,
                               ici_overrides=((1, slow),))
    topo_ok = sim.SimTopology(num_slices=2, chips_per_slice=4)
    plan = sim.synthetic_plan([4_000_000], 8)
    acct = CTR.plan_comm_accounting(plan, mode="dear")
    for row in acct.rows:
        bad = sim._price_row_topo(row, topo_bad, acct.world)
        ok = sim._price_row_topo(row, topo_ok, acct.world)
        assert bad == sim._price_row(row, acct.world, slow)
        assert bad > ok


# ---------------------------------------------------------------------------
# determinism + artifact shape
# ---------------------------------------------------------------------------


def test_training_sim_deterministic_and_seed_sensitive():
    a = sim.simulate_training(plan8(), TOPO8, mode="dear", steps=16, seed=7)
    b = sim.simulate_training(plan8(), TOPO8, mode="dear", steps=16, seed=7)
    c = sim.simulate_training(plan8(), TOPO8, mode="dear", steps=16, seed=8)
    assert a == b
    assert a["quantiles"] != c["quantiles"]


def test_training_sim_emits_overlap_report_shape():
    """`report.py` must render simulated runs like live ones: the dict
    is a faithful `OverlapReport.to_dict()`."""
    out = sim.simulate_training(plan8(), TOPO8, mode="dear", steps=4)
    rep = out["report"]
    for key in ("mode", "world", "num_buckets", "alpha", "beta",
                "compute_time_s", "comm_time_s", "measured_step_s",
                "ideal_step_s", "serial_step_s", "exposed_comm_s",
                "hidden_comm_s", "overlap_efficiency", "legs"):
        assert key in rep, key
    # exposed + hidden partitions each leg's predicted duration
    for l in rep["legs"]:
        assert l["exposed_s"] + l["hidden_s"] == \
            pytest.approx(l["pred_time_s"], rel=1e-9)
    assert 0.0 <= rep["overlap_efficiency"] <= 1.0
    # ... and the live renderer accepts it verbatim
    from dear_pytorch_tpu.observability import report as R
    rendered = R.render_text(OV.OverlapReport(**{
        **rep, "legs": tuple(OV.BucketLegReport(**l) for l in rep["legs"]),
    }))
    assert "dear" in rendered
    assert out["quantiles"]["n"] == 4


def test_recorded_mode_ordering_reproduced():
    """The structural invariants behind the archived A/Bs
    (perf/tuning_r07: dear 2.7 > allreduce 2.4 > rb 2.0; fsdp 2.2):
    decoupled AG overlaps the next forward, fsdp's gather blocks it,
    rb moves more wire — so simulated step time must order
    dear < allreduce < rb and dear < fsdp."""
    plan = plan8()
    t = {m: sim.simulate_training(plan, TOPO8, mode=m, steps=1,
                                  jitter=0.0,
                                  compute_time_s=0.012)["step_time_s"]
         for m in ("dear", "allreduce", "fsdp", "rb")}
    assert t["dear"] < t["allreduce"] < t["rb"]
    assert t["dear"] < t["fsdp"] <= t["rb"]


def test_gather_dtype_speedup_reproduced():
    """BENCH_r04's recorded '+4.5% on BERT from the world-aware gather
    dtype': a bf16 gather must price strictly faster at world 8."""
    plan = plan8()
    f32 = sim.simulate_training(plan, TOPO8, mode="dear",
                                gather_itemsize=4, steps=1, jitter=0.0,
                                compute_time_s=0.012)
    bf16 = sim.simulate_training(plan, TOPO8, mode="dear",
                                 gather_itemsize=2, steps=1, jitter=0.0,
                                 compute_time_s=0.012)
    assert bf16["wire_bytes_per_step"] < f32["wire_bytes_per_step"]
    assert bf16["step_time_s"] < f32["step_time_s"]


def test_trace_calibration_replay_reproduces_recorded_quantiles():
    """perf/trace_r19's fleet-trace calibration (harvested by
    scripts/fleet_trace.py from the recorded --multislice chaos storm)
    replayed through the sim: with compute unpinned the fixed-point
    rebase must land the simulated p50 within 10% of the recorded p50
    and the p99 within [0.5x, 1.5x] of the recorded p99 (the tail is
    the storm's kill/stall mass — it must EMERGE from the replayed
    scale distribution, it is never fit); with compute pinned the same
    replay must preserve dear < allreduce.  Mirrors
    scripts/sim_check.py check_trace_calibration."""
    import os

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    cal_path = os.path.join(repo, "perf", "trace_r19", "calibration.json")
    with open(cal_path) as f:
        rec = json.load(f)["step_time_s"]
    plan = plan8()
    rep = sim.simulate_training(plan, TOPO8, mode="dear", steps=400,
                                seed=0, trace_calibration=cal_path)
    assert rep["jitter_model"] == "trace-replay"
    q = rep["quantiles"]
    assert abs(q["p50"] - rec["p50"]) <= 0.10 * rec["p50"]
    assert 0.5 * rec["p99"] <= q["p99"] <= 1.5 * rec["p99"]
    # pinned compute skips the rebase (which would force both modes
    # onto the recorded p50) — the replay must keep the recorded A/B
    t = {m: sim.simulate_training(plan, TOPO8, mode=m, steps=400,
                                  seed=0, compute_time_s=0.012,
                                  trace_calibration=cal_path)
         ["step_time_s"]
         for m in ("dear", "allreduce")}
    assert t["dear"] < t["allreduce"]


def test_multislice_partition_tradeoff_visible():
    """Bigger DCN partitions -> fewer messages -> less α cost: the axis
    `PlanTuner(sim)` searches must actually move the objective."""
    topo = sim.SimTopology(num_slices=2, chips_per_slice=8,
                           dcn=LinkFit(alpha=1e-4, beta=1.0 / 5e9))
    plan = sim.synthetic_plan(LAYERS, 16)
    fine = sim.simulate_training(plan, topo, mode="dear",
                                 partition_mb=1.0, steps=1, jitter=0.0)
    coarse = sim.simulate_training(plan, topo, mode="dear",
                                   partition_mb=64.0, steps=1, jitter=0.0)
    assert coarse["step_time_s"] < fine["step_time_s"]


# ---------------------------------------------------------------------------
# degraded-mode DCN: skip-vs-stall policy replay
# ---------------------------------------------------------------------------

DCN_TOPO = sim.SimTopology(num_slices=2, chips_per_slice=2,
                           dcn=LinkFit(alpha=2e-3, beta=1.0 / 2e9))


def test_price_degraded_round_bounds():
    from dear_pytorch_tpu.observability.costmodel import (
        price_degraded_round,
    )
    fit = LinkFit(alpha=1e-3, beta=1.0 / 1e9)
    healthy = price_degraded_round(fit, 8 * 2**20, timeout_s=3.0)
    assert healthy == pytest.approx(1e-3 + 8 * 2**20 / 1e9)
    # chunking at partition_mb pays one α per chunk
    chunked = price_degraded_round(fit, 8 * 2**20, timeout_s=3.0,
                                   partition_mb=1.0)
    assert chunked == pytest.approx(8e-3 + 8 * 2**20 / 1e9)
    # an outage charges exactly the retry budget — the bounded cost of
    # deciding to skip, regardless of payload
    assert price_degraded_round(fit, 8 * 2**20, timeout_s=3.0,
                                outage=True) == 3.0


def test_degraded_dcn_sim_deterministic():
    kw = dict(staleness=2, steps=12, timeout_s=3.0, outages={1: [4, 5]})
    a = sim.simulate_degraded_dcn(DCN_TOPO, **kw)
    b = sim.simulate_degraded_dcn(DCN_TOPO, **kw)
    assert a == b


def test_degraded_dcn_flap_skip_beats_stall():
    """The recorded flap-storm fact (perf/dcn_degraded_r18): a
    sub-budget flap costs zero rollbacks under the ladder, while
    strict mode pays a rollback per flapped exchange — and the sweep
    ranks the skip policy first."""
    kw = dict(steps=12, timeout_s=3.0, outages={1: [4, 5]},
              ckpt_every=4)
    ranked = sim.sweep_staleness_policies(DCN_TOPO, policies=(0, 2),
                                          **kw)
    skip = next(r for r in ranked if r["staleness"] == 2)
    stall = next(r for r in ranked if r["staleness"] == 0)
    assert ranked[0]["staleness"] == 2
    assert skip["finished"] and stall["finished"]
    assert skip["rollbacks"] == 0 and skip["skips"] == 2
    assert skip["escalations"] == 0
    assert stall["rollbacks"] >= 1
    assert skip["steps_per_hour"] > stall["steps_per_hour"]


def test_degraded_dcn_partition_walks_the_ladder():
    """A past-budget outage escalates to eviction (rung 3), trains on
    without the slice, and readmits it when the outage ends — no
    rollbacks anywhere on the degraded path."""
    kw = dict(steps=12, timeout_s=2.0, outages={1: list(range(3, 9))},
              ckpt_every=2)
    deg = sim.simulate_degraded_dcn(DCN_TOPO, staleness=1, **kw)
    strict = sim.simulate_degraded_dcn(DCN_TOPO, staleness=0, **kw)
    assert deg["finished"]
    assert deg["rollbacks"] == 0
    assert deg["escalations"] == 1 and deg["rejoins"] == 1
    # skips stop accruing once the slice is evicted
    assert deg["skips"] == 2
    assert strict["rollbacks"] >= 6
    assert deg["steps_per_hour"] > strict["steps_per_hour"]


# ---------------------------------------------------------------------------
# topology / calibration round-trips
# ---------------------------------------------------------------------------


def test_topology_roundtrip(tmp_path):
    topo = sim.SimTopology(
        num_slices=4, chips_per_slice=16, replicas=3,
        ici=LinkFit(alpha=2e-6, beta=1.0 / 90e9, source="measured"),
        dcn=LinkFit(alpha=1e-4, beta=1.0 / 6e9),
        ici_overrides=((2, LinkFit(alpha=1e-5, beta=1.0 / 10e9)),),
        dcn_overrides=((0, LinkFit(alpha=2e-4, beta=1.0 / 3e9)),))
    again = sim.SimTopology.from_dict(topo.to_dict())
    assert again.to_dict() == topo.to_dict()
    assert again.world == 64
    p = tmp_path / "topo.json"
    p.write_text(json.dumps(topo.to_dict()))
    assert sim.load_topology(str(p)).to_dict() == topo.to_dict()
    assert sim.load_topology(json.dumps(topo.to_dict())).world == 64


def test_topology_from_calibration_artifact(tmp_path):
    """`--calibration perf/...json` style: an artifact embedding a
    calibration block seeds the topology's fits."""
    calib = Calibration(ici=LinkFit(alpha=3e-6, beta=1.0 / 80e9),
                        dcn=LinkFit(alpha=2e-4, beta=1.0 / 4e9))
    p = tmp_path / "artifact.json"
    p.write_text(json.dumps({"run": "r99",
                             "calibration": calib.to_dict()}))
    from dear_pytorch_tpu.observability.costmodel import load_calibration
    topo = sim.SimTopology.from_calibration(load_calibration(str(p)),
                                            num_slices=2)
    assert topo.ici.alpha == 3e-6
    assert topo.dcn.beta == 1.0 / 4e9


# ---------------------------------------------------------------------------
# serving fleet
# ---------------------------------------------------------------------------


def _trace():
    return sim.TrafficTrace.poisson(rps=500.0, duration_s=1.0,
                                    prompt_tokens=16, decode_tokens=4,
                                    seed=3)


def test_serving_sim_deterministic_and_episode_shaped():
    tr = _trace()
    a = sim.simulate_serving(TOPO8, tr, prefill_chunk=4, slots=4)
    b = sim.simulate_serving(TOPO8, tr, prefill_chunk=4, slots=4)
    assert a == b
    for key in ("p50_s", "p99_s", "requests", "requests_per_s", "ticks",
                "wall_s"):
        assert key in a, key
    assert a["requests"] == len(tr.requests)


def test_serving_chunked_beats_token_on_p99_and_rps():
    """serving_r08's recorded chunked:token win (rps 1247.8 vs 864.3,
    p99 3.28ms vs 5.0ms) is structural: chunked prefill needs fewer
    engine ticks per request."""
    tr = _trace()
    chunked = sim.simulate_serving(TOPO8, tr, prefill_chunk=4, slots=4)
    token = sim.simulate_serving(TOPO8, tr, prefill_chunk=1, slots=4)
    assert chunked["p99_s"] < token["p99_s"]
    assert chunked["requests_per_s"] > token["requests_per_s"]


def test_serving_tp_ring_priced_per_tick():
    tr = _trace()
    base = sim.simulate_serving(TOPO8, tr, prefill_chunk=4, slots=4)
    tp = sim.simulate_serving(TOPO8, tr, prefill_chunk=4, slots=4,
                              tp_decode=True, weight_bytes=2e6,
                              n_projections=4)
    assert tp["p99_s"] > base["p99_s"]


def test_phase_priced_sim_matches_recorded_serving_episode():
    """Parity against the RECORDED serving_r08 A/B cells (ISSUE-17
    satellite): feed the chunk-1 and chunk-4 cells' measured
    seconds-per-tick into a live `AdmissionController`'s split-phase
    EWMAs, convert through `phase_ticks_from_admission`, and replay the
    tuner's exact workload — the phase-priced sim must land within 35%
    of each recorded wall and price the chunked:token speedup STRICTLY
    closer to the recorded ratio than the one-blended-tick model does."""
    import os

    from dear_pytorch_tpu.serving.admission import AdmissionController

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    with open(os.path.join(repo, "perf", "serving_r08",
                           "summary.json")) as f:
        eps = json.load(f)["episodes"]
    rec_tok = eps["1/2/bf16/False/False"]
    rec_chk = eps["4/2/bf16/False/False"]
    t_tok = rec_tok["wall_s"] / rec_tok["ticks"]   # s per engine tick
    t_chk = rec_chk["wall_s"] / rec_chk["ticks"]
    # the serve_tune episode workload: 24 requests, prompts 4..16,
    # 4 new tokens, all pending at t=0, slots=2 (scripts/serve_tune.py)
    trace = sim.TrafficTrace(requests=tuple(
        (0.0, 4 + (i * 5) % 13, 4) for i in range(24)))

    def arm(chunk, tick):
        adm = AdmissionController(max_depth=64)
        adm.complete(prefill_tokens=chunk, prefill_s=tick,
                     decode_tokens=1, decode_s=tick)
        pt, dt = sim.phase_ticks_from_admission(adm, chunk)
        assert pt == pytest.approx(tick) and dt == pytest.approx(tick)
        return sim.simulate_serving(TOPO8, trace, prefill_chunk=chunk,
                                    slots=2, prefill_tick_s=pt,
                                    decode_tick_s=dt)

    sim_tok = arm(1, t_tok)
    sim_chk = arm(4, t_chk)
    assert sim_tok["ticks"] == 337 and sim_chk["ticks"] == 165
    assert sim_tok["wall_s"] == pytest.approx(rec_tok["wall_s"], rel=0.35)
    assert sim_chk["wall_s"] == pytest.approx(rec_chk["wall_s"], rel=0.35)
    rec_ratio = rec_tok["wall_s"] / rec_chk["wall_s"]
    sim_ratio = sim_tok["wall_s"] / sim_chk["wall_s"]
    assert sim_ratio > 1.0                  # chunked wins, as recorded
    # the blended-tick model prices both phases identically, so its
    # ratio is fixed at total-ticks/total-ticks regardless of the tick
    blend_tok = sim.simulate_serving(TOPO8, trace, prefill_chunk=1,
                                     slots=2)
    blend_chk = sim.simulate_serving(TOPO8, trace, prefill_chunk=4,
                                     slots=2)
    blend_ratio = blend_tok["wall_s"] / blend_chk["wall_s"]
    assert abs(sim_ratio - rec_ratio) < abs(blend_ratio - rec_ratio)


def test_serving_autoscaler_relieves_backlog():
    tr = sim.TrafficTrace.poisson(rps=900.0, duration_s=1.5,
                                  prompt_tokens=16, decode_tokens=4,
                                  seed=5)
    fixed = sim.simulate_serving(TOPO8, tr, prefill_chunk=4, slots=4,
                                 replicas=1)
    auto = sim.simulate_serving(
        TOPO8, tr, prefill_chunk=4, slots=4, replicas=1,
        autoscale={"min": 1, "max": 4, "up_q": 2.0, "down_q": 0.5,
                   "interval_s": 0.25})
    assert auto["scale_events"] > 0
    assert auto["p99_s"] < fixed["p99_s"]


# ---------------------------------------------------------------------------
# SimTransport: virtual time under the real protocol's access pattern
# ---------------------------------------------------------------------------


def test_sim_transport_kv_semantics():
    from dear_pytorch_tpu.resilience.cluster import PeerTimeout

    st = sim.SimTransport()
    st.attach()
    st.set("ns/a/1/k", "v")
    assert st.get("ns/a/1/k", 5.0) == "v"
    with pytest.raises(PeerTimeout):
        st.get("ns/missing", 0.05)       # sub-min-park probe: no hang
    assert st.decide_once("ns/d", "first") == "first"
    assert st.decide_once("ns/d", "second") == "first"
    st.set("ns/a/2/k", "w")
    assert st.list_prefix("ns/a") == ["1", "2"]
    st.prune_prefix("ns/a")
    assert st.list_prefix("ns/a") == []
    st.detach()


def test_sim_transport_virtual_timeout_advances_clock():
    """A lone parked actor's timeout advances virtual time without
    burning real time."""
    from dear_pytorch_tpu.resilience.cluster import PeerTimeout

    st = sim.SimTransport(quantum_s=1.0)
    st.attach()
    t0 = time.perf_counter()
    with pytest.raises(PeerTimeout):
        st.get("never", 300.0)
    real = time.perf_counter() - t0
    assert st.now_s >= 300.0
    assert real < 5.0
    assert st.advances >= 1
    st.detach()


# ---------------------------------------------------------------------------
# the headline: 1000-rank / 8-slice storm, tier-1 time
# ---------------------------------------------------------------------------


def _assert_storm_records(out, world, victims, kill_slice):
    e1, e2, e3 = (out["records"][k] for k in ("e1", "e2", "e3"))
    assert out["errors"] == {}
    assert out["stuck_threads"] == []
    assert out["lockstep"] is True
    # decided/e1: one shrink epoch removing exactly the victim slice
    assert e1["delta"]["removed"] == victims
    assert e1["delta"]["added"] == []
    assert e1["delta"]["slices"]["removed"] == [kill_slice]
    assert len(e1["members"]) == world - len(victims)
    assert not (set(victims) & set(e1["members"]))
    # decided/e2: the relaunched slice admitted back in one epoch
    assert e2["delta"]["added"] == victims
    assert e2["delta"]["removed"] == []
    assert e2["delta"]["slices"]["added"] == [kill_slice]
    assert e2["members"] == list(range(world))
    # no third transition: shrink -> rejoin, nothing else
    assert e3 is None


def test_membership_storm_small_world():
    """Protocol shape at a size that runs in milliseconds — the same
    decision-record sequence the live `--multislice` chaos gate
    asserts (slice SIGKILL -> one shrink epoch -> rejoin -> lockstep)."""
    out = sim.run_membership_storm(world=16, ranks_per_slice=4,
                                   kill_slice=2)
    _assert_storm_records(out, 16, list(range(8, 12)), 2)


def test_membership_storm_1000_ranks_resolves_in_tier1_time():
    """The acceptance gate: a 1000-rank / 8-slice world survives a full
    slice SIGKILL and returns to lockstep — one shrink epoch, one
    admission epoch, every rank's final exchange agreeing — in under
    60s of wall clock on one core (the protocol runs unmodified; only
    the transport's clock is virtual)."""
    t0 = time.perf_counter()
    out = sim.run_membership_storm(world=1000, ranks_per_slice=125,
                                   kill_slice=1)
    wall = time.perf_counter() - t0
    _assert_storm_records(out, 1000, list(range(125, 250)), 1)
    assert wall < 60.0, f"storm took {wall:.1f}s (gate: 60s)"


# ---------------------------------------------------------------------------
# tuner sim backends
# ---------------------------------------------------------------------------


def test_tune_plan_sim_prefers_cheaper_wire():
    from dear_pytorch_tpu.tuning.planspace import PlanSpace

    space = PlanSpace(modes=("dear", "dear-fused"),
                      threshold_bound=(1.0, 64.0), compressors=(None,),
                      comm_dtypes=(None, "bf16"),
                      gather_dtypes=(None, "bf16"), remats=(None,))
    out = sim.tune_plan_sim(
        space, lambda thr: plan8(max(thr, 1.0)), TOPO8,
        compute_time_s=0.012, max_trials=6, budget_steps=800)
    assert out["finished"]
    assert out["virtual_steps"] > 0
    # bf16 wire halves the dominant β term — the search must find it
    best = out["best"]
    assert best["comm_dtype"] == "bf16" or best["gather_dtype"] == "bf16"


def test_tune_serve_sim_runs_real_serve_tuner():
    from dear_pytorch_tpu.tuning.planspace import ServeSpace

    space = ServeSpace(chunk_bound=(1, 16), slots=(2, 4),
                       kv_dtypes=(None,), flash=(False,), tp=(False,),
                       world=8, ring_len=8)
    out = sim.tune_serve_sim(space, TOPO8, _trace(), max_trials=6)
    assert out["best_p99_s"] is not None
    assert out["episodes"]
    # the winner can't be worse than the worst episode it explored
    assert out["best_p99_s"] <= max(e["p99_s"]
                                    for e in out["episodes"].values())


def test_tune_fleet_sim_searches_replicas_and_autoscale():
    trace = sim.TrafficTrace.poisson(rps=800.0, duration_s=1.0,
                                     prompt_tokens=16, decode_tokens=4,
                                     seed=4)
    out = sim.tune_fleet_sim(sim.FleetSpace(replicas=(1, 2, 4)), TOPO8,
                             trace, max_trials=6,
                             cost_per_replica_s=0.01)
    assert out["best"]["replicas"] in (1, 2, 4)
    assert out["best_objective"] is not None
    # a 1-replica no-autoscale fleet drowns at this rate — the search
    # must leave the default corner
    assert not (out["best"]["replicas"] == 1
                and not out["best"]["autoscale"])


def test_fleet_space_interface_contract():
    space = sim.FleetSpace(replicas=(1, 2), max_replicas=2)
    cfgs = space.configs()
    assert all(space.feasible(c) is None for c in cfgs)
    assert space.feasible(sim.FleetConfig(replicas=4)) is not None
    d = space.default_config()
    assert d.key() == (1, False)
    assert "R=1" in d.describe()


def test_virtual_clock_is_perf_counter_shaped():
    clock = sim.VirtualClock()
    assert clock() == 0.0
    clock.advance(2.5)
    assert clock() == 2.5
