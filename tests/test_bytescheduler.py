"""ByteScheduler-equivalent schedule: tensor partitioning + priority-shaped
dependencies (reference bytescheduler/imagenet_benchmark.py:73-82,
--partition at :37-38). Numerics must equal plain allreduce exactly; the
compiled program must contain one INDEPENDENT all-reduce per partition."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dear_pytorch_tpu.ops.fused_sgd import fused_sgd
from dear_pytorch_tpu.parallel import build_train_step
from dear_pytorch_tpu.utils import hlo


def _mlp_params(key):
    ks = jax.random.split(key, 3)
    return {
        f"l{i}": {
            "w": jax.random.normal(ks[i], (64, 64)) * 0.1,
            "b": jnp.zeros((64,)),
        }
        for i in range(3)
    }


def _loss(p, b):
    x, y = b
    for i in range(3):
        x = jnp.tanh(x @ p[f"l{i}"]["w"] + p[f"l{i}"]["b"])
    return jnp.mean((x - y) ** 2)


def _batch():
    return (
        jax.random.normal(jax.random.PRNGKey(1), (16, 64)),
        jax.random.normal(jax.random.PRNGKey(2), (16, 64)),
    )


def _run(mode, mesh, steps=4, **kw):
    params = _mlp_params(jax.random.PRNGKey(0))
    ts = build_train_step(
        _loss, params, mesh=mesh, mode=mode, threshold_mb=None,
        optimizer=fused_sgd(lr=0.05, momentum=0.9), donate=False, **kw,
    )
    state = ts.init(params)
    losses = []
    for _ in range(steps):
        state, m = ts.step(state, _batch())
        losses.append(float(m["loss"]))
    return ts, state, losses


def test_bytescheduler_equals_allreduce(mesh):
    """Partitioned reduction is a pure re-association of the same sum —
    losses and final params must match plain allreduce bit-for-bit-ish."""
    _, s_ar, l_ar = _run("allreduce", mesh)
    _, s_bs, l_bs = _run("bytescheduler", mesh, partition_mb=0.01)
    np.testing.assert_allclose(l_bs, l_ar, rtol=1e-6)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-7
        ),
        s_bs.buffers, s_ar.buffers,
    )


def test_partition_count_and_independence(mesh):
    """partition_mb controls the number of per-chunk reductions IN THE
    COMPILED PROGRAM; partitions are mutually independent (the priority
    property: any chunk may complete first). Chunks travel as RS+AG pairs
    because XLA's all-reduce combiner would re-fuse small all-reduces into
    one op and silently undo the partitioning — this test is the proof the
    chunk structure survives compilation."""
    params = _mlp_params(jax.random.PRNGKey(0))
    part_mb = 0.01  # 10 KB -> 2560 f32 elements
    ts = build_train_step(
        _loss, params, mesh=mesh, mode="bytescheduler", threshold_mb=None,
        partition_mb=part_mb, optimizer=fused_sgd(lr=0.05), donate=False,
    )
    state = ts.init(params)
    text = ts.lower(state, _batch()).compile().as_text()
    ops = hlo.parse_entry(text)
    part_elems = int(part_mb * 2**20) // 4
    want = sum(
        -(-b.padded_size // part_elems) for b in ts.plan.buckets
    )
    for kind in ("reduce-scatter", "all-gather"):
        cols = hlo.find(ops, kind)
        assert len(cols) == want > 1, (kind, len(cols), want)
        anc = {c.name: hlo.ancestors(ops, c.name) for c in cols}
        for a in cols:
            for c in cols:
                if a.name != c.name:
                    assert a.name not in anc[c.name], (
                        f"{kind} partitions serialized"
                    )


def test_bytescheduler_rejects_compression(mesh):
    with pytest.raises(ValueError, match="allreduce"):
        build_train_step(
            _loss, _mlp_params(jax.random.PRNGKey(0)), mesh=mesh,
            mode="bytescheduler", compressor="eftopk", density=0.1,
        )
