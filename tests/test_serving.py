"""Serving stack: ring-buffer KV-cache decode parity, the
continuous-batching engine, admission backpressure, the router's
zero-drop re-dispatch machinery, the serving fault grammar — and the
`scripts/chaos_check.py --serve` replica-kill storm as the end-to-end
gate (docs/SERVING.md)."""

import json
import os
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dear_pytorch_tpu.models.bert import (
    BertConfig, BertForPreTraining, dot_product_attention,
)
from dear_pytorch_tpu.models.gpt import GptConfig, GptLmHeadModel, generate
from dear_pytorch_tpu.serving import kvcache as KV
from dear_pytorch_tpu.serving.admission import (
    AdmissionController, SheddingError,
)
from dear_pytorch_tpu.serving.engine import DecodeEngine
from dear_pytorch_tpu.serving.router import ReplicaRouter, response_sha256


def _gpt(dtype=jnp.float32, **kw):
    cfg = GptConfig(
        vocab_size=61, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=2, intermediate_size=64,
        max_position_embeddings=32, embd_dropout_prob=0.0,
        hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0,
        dtype=dtype, **kw)
    model = GptLmHeadModel(cfg)
    params = model.init(
        {"params": jax.random.PRNGKey(0)},
        jnp.zeros((2, 4), jnp.int32), train=False)["params"]
    return model, params


def _bert(dtype=jnp.float32, **kw):
    cfg = BertConfig(
        vocab_size=60, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=2, intermediate_size=64,
        max_position_embeddings=32, hidden_dropout_prob=0.0,
        attention_probs_dropout_prob=0.0, dtype=dtype, **kw)
    model = BertForPreTraining(cfg)
    params = model.init(
        {"params": jax.random.PRNGKey(0)},
        jnp.zeros((2, 4), jnp.int32), train=False)["params"]
    return model, params


def _gpt_decode_logits(model, params, ids):
    """Stepwise decode over every position of ``ids``; stacked logits."""
    cache = model.init(
        {"params": jax.random.PRNGKey(0)}, ids[:, :1], train=False,
        decode=True)["cache"]
    steps = []
    for t in range(ids.shape[1]):
        step, vars_out = model.apply(
            {"params": params, "cache": cache}, ids[:, t:t + 1],
            train=False, decode=True, position_offset=t, mutable=["cache"])
        cache = vars_out["cache"]
        steps.append(np.asarray(step[:, 0]))
    return np.stack(steps, axis=1)


def _bert_decode_logits(model, params, ids):
    cache = model.init(
        {"params": jax.random.PRNGKey(0)}, ids[:, :1], train=False,
        decode=True)["cache"]
    steps = []
    for t in range(ids.shape[1]):
        (step, _nsp), vars_out = model.apply(
            {"params": params, "cache": cache}, ids[:, t:t + 1],
            train=False, decode=True, position_offset=t, mutable=["cache"])
        cache = vars_out["cache"]
        steps.append(np.asarray(step[:, 0]))
    return np.stack(steps, axis=1)


# ---------------------------------------------------------------------------
# KV-cache decode parity (the satellite contract: non-divisible sequence
# lengths, bf16 activations, both model families, flash-backed attend)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("seq_len", [13, 7])
def test_gpt_decode_parity_nondivisible(seq_len):
    model, params = _gpt(kv_cache_len=16)
    ids = jnp.asarray(np.random.RandomState(1).randint(0, 61, (2, seq_len)))
    full = np.asarray(model.apply({"params": params}, ids, train=False))
    dec = _gpt_decode_logits(model, params, ids)
    np.testing.assert_allclose(dec, full, rtol=2e-4, atol=2e-4)


def test_gpt_decode_parity_bf16():
    """bf16 activations through the ring cache: the cached K/V travel in
    bf16 exactly like the full forward's, so decode matches at bf16
    tolerance."""
    model, params = _gpt(dtype=jnp.bfloat16, kv_cache_len=16)
    ids = jnp.asarray(np.random.RandomState(2).randint(0, 61, (2, 13)))
    full = np.asarray(model.apply({"params": params}, ids, train=False))
    dec = _gpt_decode_logits(model, params, ids)
    np.testing.assert_allclose(dec, full, rtol=5e-2, atol=5e-2)


def test_gpt_decode_parity_flash():
    """`decode_use_flash=True` routes the decode attend through the
    Pallas flash kernel (1-row query over the cache, validity as its
    kv_mask) — same logits as the dense path at dtype tolerance."""
    model, params = _gpt(kv_cache_len=16, decode_use_flash=True)
    ids = jnp.asarray(np.random.RandomState(3).randint(0, 61, (2, 13)))
    full = np.asarray(model.apply({"params": params}, ids, train=False))
    dec = _gpt_decode_logits(model, params, ids)
    np.testing.assert_allclose(dec, full, rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("dtype,rtol", [(jnp.float32, 2e-4),
                                        (jnp.bfloat16, 5e-2)])
def test_bert_decode_parity(dtype, rtol):
    """BERT's incremental decode is left-to-right by construction; its
    logits reproduce the full forward under ``causal=True`` — at every
    position, for a non-divisible length, in f32 and bf16."""
    model, params = _bert(dtype=dtype, kv_cache_len=16)
    ids = jnp.asarray(np.random.RandomState(4).randint(0, 60, (2, 13)))
    full, _ = model.apply({"params": params}, ids, train=False, causal=True)
    dec = _bert_decode_logits(model, params, ids)
    np.testing.assert_allclose(dec, np.asarray(full), rtol=rtol, atol=rtol)


def test_bert_causal_rejects_custom_attention_impl():
    model, params = _bert()
    model = BertForPreTraining(model.config,
                               attention_impl=dot_product_attention)
    ids = jnp.zeros((1, 4), jnp.int32)
    with pytest.raises(ValueError, match="causal=True"):
        model.apply({"params": params}, ids, train=False, causal=True)


def test_ring_cache_wraps_to_sliding_window():
    """Past the ring length the cache holds exactly the last L tokens:
    attention equals dense attention over that window, at every step."""
    B, L, H, D, T = 2, 8, 2, 4, 13
    rs = np.random.RandomState(5)
    ks = jnp.asarray(rs.randn(B, T, H, D), jnp.float32)
    vs = jnp.asarray(rs.randn(B, T, H, D), jnp.float32)
    qs = jnp.asarray(rs.randn(B, T, H, D), jnp.float32)
    ck = jnp.zeros((B, L, H, D))
    cv = jnp.zeros((B, L, H, D))
    for t in range(T):
        pos = jnp.full((B,), t, jnp.int32)
        ck, cv = KV.ring_write(ck, cv, pos, ks[:, t:t + 1], vs[:, t:t + 1])
        valid = KV.ring_validity(pos, L)
        out = KV.cache_attend(qs[:, t:t + 1], ck, cv, valid,
                              dtype=jnp.float32)
        lo = max(0, t + 1 - L)
        ref = dot_product_attention(
            qs[:, t:t + 1], ks[:, lo:t + 1], vs[:, lo:t + 1], None,
            dtype=jnp.float32)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)


# ---------------------------------------------------------------------------
# continuous-batching engine
# ---------------------------------------------------------------------------


def test_engine_mixed_prefill_decode_matches_generate():
    """Three requests of different prompt lengths, arriving staggered
    into two slots (continuous batching: one finishes, the next enters),
    must each reproduce the per-request `generate()` tokens exactly —
    prefill and decode phases mix in ONE jitted step."""
    model, params = _gpt(kv_cache_len=16)
    rs = np.random.RandomState(6)
    prompts = [list(rs.randint(0, 61, n)) for n in (4, 7, 5)]
    refs = [list(np.asarray(
        generate(model, params, jnp.asarray([p]), max_new_tokens=5)
        [0, len(p):])) for p in prompts]
    eng = DecodeEngine(model, params, slots=2)
    assert eng.submit(prompts[0], 5, request_id="a") is not None
    assert eng.submit(prompts[1], 5, request_id="b") is not None
    assert eng.submit(prompts[2], 5, request_id="c") is None  # batch full
    done, pending = {}, [("c", prompts[2])]
    for _ in range(100):
        for fin in eng.tick():
            done[fin.request_id] = fin.tokens
            if pending and eng.free:
                rid, p = pending.pop()
                eng.submit(p, 5, request_id=rid)
        if len(done) == 3:
            break
    assert done["a"] == refs[0]
    assert done["b"] == refs[1]
    assert done["c"] == refs[2]  # served in a reused slot
    assert eng.active == 0 and eng.free == 2


def test_engine_rejects_over_budget_and_empty_prompts():
    model, params = _gpt()
    eng = DecodeEngine(model, params, slots=1)
    with pytest.raises(ValueError, match="position budget"):
        eng.submit(list(range(30)), 10)
    with pytest.raises(ValueError, match="empty prompt"):
        eng.submit([], 4)


# ---------------------------------------------------------------------------
# admission control
# ---------------------------------------------------------------------------


def test_admission_depth_and_deadline_shedding():
    adm = AdmissionController(max_depth=2, capacity=1)
    adm.admit(None)
    adm.admit(None)
    with pytest.raises(SheddingError) as exc:
        adm.admit(None)                      # bounded queue depth
    assert exc.value.depth == 2
    adm.complete(1.0)                        # service time learned: 1s
    assert adm.service_time_s == pytest.approx(1.0)
    # depth 1, svc 1s -> predicted wait 1s: a 0.1s budget is hopeless
    with pytest.raises(SheddingError):
        adm.admit(0.1)
    adm.admit(2.0)                           # a 2s budget fits
    assert adm.requests == 5 and adm.admitted == 3 and adm.shed == 2


def test_admission_capacity_scales_predicted_wait():
    adm = AdmissionController(max_depth=10, capacity=1,
                              service_time_s=1.0)
    adm.admit(None)
    adm.admit(None)
    with pytest.raises(SheddingError):
        adm.admit(1.5)                       # 2 deep x 1s / 1 slot = 2s
    adm.set_capacity(4)                      # fleet grew: 2s -> 0.5s
    adm.admit(1.5)


def test_shed_retry_with_decorrelated_jitter():
    """The client contract: SheddingError is retryable through
    `resilience.retry` and eventually lands."""
    from dear_pytorch_tpu.resilience.retry import retry_call

    calls = [0]

    def submit():
        calls[0] += 1
        if calls[0] < 3:
            raise SheddingError("shed", depth=5, predicted_wait_s=1.0)
        return "rid"

    assert retry_call(submit, attempts=5, base_delay_s=0.001,
                      max_delay_s=0.01,
                      retry_on=(SheddingError,)) == "rid"
    assert calls[0] == 3


# ---------------------------------------------------------------------------
# router: health, re-dispatch, checksum, weight swaps (fake replicas —
# plain threads speaking the file protocol; no jax)
# ---------------------------------------------------------------------------


class _FakeReplica:
    """Thread speaking the replica file protocol with scriptable
    behavior: heartbeat-only, serve, or corrupt-once."""

    def __init__(self, root, rank, *, version=1, incarnation="a",
                 serve=True, corrupt_first=False):
        self.root, self.rank = root, rank
        self.version, self.incarnation = version, incarnation
        self.serve, self.corrupt_first = serve, corrupt_first
        self.corrupted = 0
        self._stop = threading.Event()
        self._dir = os.path.join(root, "replicas", str(rank))
        self._inbox = os.path.join(self._dir, "inbox")
        os.makedirs(self._inbox, exist_ok=True)
        os.makedirs(os.path.join(root, "responses"), exist_ok=True)
        self._thread = threading.Thread(target=self._run, daemon=True)

    def start(self):
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        self._thread.join(timeout=5)

    def _beat(self):
        doc = {"ts": time.time(), "pid": os.getpid(),
               "incarnation": self.incarnation, "version": self.version,
               "draining": False, "stopped": False}
        path = os.path.join(self._dir, "health.json")
        with open(path + ".tmp", "w") as f:
            json.dump(doc, f)
        os.replace(path + ".tmp", path)

    def _run(self):
        while not self._stop.is_set():
            self._beat()
            if self.serve:
                for name in sorted(os.listdir(self._inbox)):
                    if not name.endswith(".json"):
                        continue
                    p = os.path.join(self._inbox, name)
                    try:
                        with open(p) as f:
                            rec = json.load(f)
                        os.unlink(p)
                    except (OSError, ValueError):
                        continue
                    payload = {"id": rec["id"],
                               "tokens": rec["prompt"][::-1],
                               "model_version": self.version,
                               "replica": self.rank}
                    payload["sha256"] = response_sha256(payload)
                    if self.corrupt_first and not self.corrupted:
                        payload["sha256"] = "0" * 64
                        self.corrupted += 1
                    rp = os.path.join(self.root, "responses",
                                      rec["id"] + ".json")
                    with open(rp + ".tmp", "w") as f:
                        json.dump(payload, f)
                    os.replace(rp + ".tmp", rp)
            time.sleep(0.01)


def _router(root, **kw):
    kw.setdefault("admission", AdmissionController(max_depth=16))
    kw.setdefault("slots_per_replica", 2)
    kw.setdefault("health_timeout_s", 0.6)
    kw.setdefault("poll_s", 0.01)
    return ReplicaRouter(root, **kw)


def _wait(cond, timeout=10.0):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        if cond():
            return True
        time.sleep(0.01)
    return False


def test_router_serves_and_accounts_deadline_miss(tmp_path):
    root = str(tmp_path)
    rep = _FakeReplica(root, 0).start()
    with _router(root) as router:
        assert _wait(lambda: router.healthy_replicas() == [0])
        rid = router.submit([1, 2, 3], max_new_tokens=2, deadline_s=30.0)
        resp = router.result(rid, timeout=10)
        assert resp["tokens"] == [3, 2, 1]
        # a deadline in the past is still SERVED, but accounted as missed
        rid2 = router.submit([4, 5], max_new_tokens=1, deadline_s=0.0)
        assert router.result(rid2, timeout=10)["tokens"] == [5, 4]
        assert router.deadline_missed == 1
        assert not router.open_requests()
    rep.stop()


def test_router_redispatches_from_dead_replica(tmp_path):
    """The zero-drop mechanism: a replica that heartbeats, takes work,
    and dies has its in-flight requests re-dispatched to a survivor."""
    root = str(tmp_path)
    dead = _FakeReplica(root, 0, serve=False).start()
    with _router(root) as router:
        assert _wait(lambda: router.healthy_replicas() == [0])
        rid = router.submit([7, 8, 9], max_new_tokens=2, deadline_s=None)
        assert _wait(lambda: router.inflight_on(0) == 1)
        dead.stop()                       # heartbeats cease: replica dies
        live = _FakeReplica(root, 1, incarnation="b").start()
        resp = router.result(rid, timeout=15)
        assert resp["tokens"] == [9, 8, 7] and resp["replica"] == 1
        assert router.redispatched >= 1
        assert not router.open_requests()
        live.stop()


def test_router_redispatches_on_incarnation_change(tmp_path):
    """A FAST restart (new incarnation before the staleness window
    expires) also triggers reclaim — the restarted replica cleared its
    inbox, so waiting on it would drop the request."""
    root = str(tmp_path)
    first = _FakeReplica(root, 0, serve=False, incarnation="a").start()
    with _router(root) as router:
        assert _wait(lambda: router.healthy_replicas() == [0])
        rid = router.submit([1, 2], max_new_tokens=1, deadline_s=None)
        assert _wait(lambda: router.inflight_on(0) == 1)
        first.stop()
        # same rank, new life, and it actually serves
        second = _FakeReplica(root, 0, incarnation="b").start()
        assert router.result(rid, timeout=15)["tokens"] == [2, 1]
        assert router.redispatched >= 1
        second.stop()


def test_router_rejects_corrupt_response_and_reserves(tmp_path):
    root = str(tmp_path)
    rep = _FakeReplica(root, 0, corrupt_first=True).start()
    with _router(root) as router:
        assert _wait(lambda: router.healthy_replicas() == [0])
        rid = router.submit([5, 6, 7], max_new_tokens=2, deadline_s=None)
        resp = router.result(rid, timeout=15)
        assert resp["tokens"] == [7, 6, 5]          # re-served, verified
        assert resp["sha256"] == response_sha256(resp)
        assert router.corrupt_responses == 1
    rep.stop()


def test_corrupt_response_after_reclaim_not_requeued_twice(tmp_path):
    """A corrupt response racing its replica's death must not re-queue
    the request a second time — the death reclaim already did; a second
    copy would dispatch the request twice and leak the losing replica's
    decode slot."""
    root = str(tmp_path)
    dead = _FakeReplica(root, 0, serve=False).start()
    with _router(root) as router:
        assert _wait(lambda: router.healthy_replicas() == [0])
        rid = router.submit([1, 2, 3], max_new_tokens=1, deadline_s=None)
        assert _wait(lambda: router.inflight_on(0) == 1)
        dead.stop()                  # replica dies; the reclaim re-queues
        assert _wait(lambda: router.redispatched >= 1)
        # ...and only NOW the dead life's corrupt response surfaces
        payload = {"id": rid, "tokens": [9], "model_version": 1,
                   "replica": 0, "sha256": "0" * 64}
        rp = os.path.join(root, "responses", rid + ".json")
        with open(rp + ".tmp", "w") as f:
            json.dump(payload, f)
        os.replace(rp + ".tmp", rp)
        assert _wait(lambda: router.corrupt_responses >= 1)
        with router._lock:
            copies = list(router._pending).count(rid)
        assert copies == 1
        # a live replica then serves the single copy to completion
        live = _FakeReplica(root, 1, incarnation="b").start()
        assert router.result(rid, timeout=15)["tokens"] == [3, 2, 1]
        assert not router.open_requests()
        live.stop()


def test_replica_answers_poison_request_with_signed_error(tmp_path):
    """An admitted request that violates the engine's position budget
    must NOT crash the replica — the router would re-dispatch the poison
    to the next replica and cascade the crash through the fleet. The
    replica answers it with a SIGNED error response instead (the
    zero-drop contract is 'every accepted request gets a verified
    response')."""
    from dear_pytorch_tpu.serving.replica import ReplicaServer

    model, params = _gpt()
    engine = DecodeEngine(model, params, slots=2)
    root = str(tmp_path)
    srv = ReplicaServer(root, 0, engine, version=1)
    inbox = os.path.join(root, "replicas", "0", "inbox")
    with open(os.path.join(inbox, "poison01.json"), "w") as f:
        json.dump({"id": "poison01", "prompt": list(range(30)),
                   "max_new_tokens": 10}, f)   # 40 > 32-position budget
    srv._take_requests()
    assert engine.active == 0          # the poison never entered a slot
    with open(os.path.join(root, "responses", "poison01.json")) as f:
        resp = json.load(f)
    assert resp["sha256"] == response_sha256(resp)
    assert resp["tokens"] == [] and "error" in resp
    # the replica survived: a well-formed request still serves
    with open(os.path.join(inbox, "ok01.json"), "w") as f:
        json.dump({"id": "ok01", "prompt": [1, 2],
                   "max_new_tokens": 1}, f)
    assert srv._take_requests() == 1
    assert engine.active == 1


def test_router_counts_weight_swap(tmp_path):
    root = str(tmp_path)
    v1 = _FakeReplica(root, 0, version=1, incarnation="a").start()
    with _router(root) as router:
        assert _wait(lambda: router.fleet_versions().get(0) == 1)
        v1.stop()
        v2 = _FakeReplica(root, 0, version=2, incarnation="b").start()
        assert _wait(lambda: router.fleet_versions().get(0) == 2)
        assert router.weight_swaps == 1
        v2.stop()


# ---------------------------------------------------------------------------
# weight-version walk-back (previously only the storm exercised this
# indirectly): corrupt the newest version's MANIFEST in one case and a
# PAYLOAD file in the other — load_params must land on the newest intact
# version both times
# ---------------------------------------------------------------------------


def _publish_versions(tmp_path, n=3):
    from dear_pytorch_tpu.serving import weights as W
    from dear_pytorch_tpu.utils.objectstore import LocalObjectStore

    store = LocalObjectStore(str(tmp_path / "store"))
    for v in range(1, n + 1):
        W.publish_params(
            store, {"layer": {"kernel": np.full((2, 2), float(v))}}, v)
    return store, W


def test_weights_walk_back_past_corrupt_manifest(tmp_path):
    store, W = _publish_versions(tmp_path)
    store.put_bytes("weights/v000003/MANIFEST.json", b"{not json")
    params, version = W.load_params(store)
    assert version == 2
    assert params["layer"]["kernel"][0, 0] == 2.0


def test_weights_walk_back_past_corrupt_payload(tmp_path):
    store, W = _publish_versions(tmp_path)
    data = bytearray(store.get_bytes("weights/v000003/params.npz"))
    data[:16] = bytes(b ^ 0xFF for b in data[:16])  # sha mismatch
    store.put_bytes("weights/v000003/params.npz", bytes(data))
    params, version = W.load_params(store)
    assert version == 2
    assert params["layer"]["kernel"][0, 0] == 2.0


def test_weights_walk_back_counts_and_explicit_version(tmp_path):
    from dear_pytorch_tpu.observability import tracer as T

    store, W = _publish_versions(tmp_path)
    data = bytearray(store.get_bytes("weights/v000003/params.npz"))
    data[0] ^= 0xFF
    store.put_bytes("weights/v000003/params.npz", bytes(data))
    old = T._tracer
    T.set_tracer(T.Tracer([T.MemoryExporter()]))
    try:
        _params, version = W.load_params(store)
        assert version == 2
        counters = T.get_tracer().counters()
        assert counters.get("serve.weight_corrupt_detected", 0) >= 1
    finally:
        T.set_tracer(old)
    # an EXPLICITLY requested corrupt version must fail loudly, not
    # silently serve an older one
    with pytest.raises(KeyError):
        W.load_params(store, version=3)


# ---------------------------------------------------------------------------
# serving fault grammar (resilience.inject satellites)
# ---------------------------------------------------------------------------


def test_parse_slow_and_corrupt_resp_faults():
    from dear_pytorch_tpu.resilience.inject import parse_faults

    faults = parse_faults("slow@3:0.05:r1,corrupt_resp@5")
    assert faults[0].kind == "slow" and faults[0].step == 3
    assert faults[0].arg == pytest.approx(0.05) and faults[0].rank == 1
    assert faults[1].kind == "corrupt_resp" and faults[1].rank is None


def test_slow_fault_is_persistent(monkeypatch):
    """``slow`` arms a PERSISTENT per-step latency (a straggler), unlike
    ``hang``'s one-shot sleep."""
    from dear_pytorch_tpu.resilience import inject as INJ

    sleeps = []
    monkeypatch.setattr(INJ.time, "sleep", sleeps.append)
    inj = INJ.FaultInjector(
        [INJ.Fault(kind="slow", step=2, arg=0.05)], own_rank=0)
    inj.before_step(1)
    assert sleeps == []
    inj.before_step(2)
    inj.before_step(3)
    assert sleeps == [0.05, 0.05] and inj.slow_s == pytest.approx(0.05)
    assert inj.pending == 0


def test_slow_fault_rank_targeted_skip(monkeypatch):
    from dear_pytorch_tpu.resilience import inject as INJ

    sleeps = []
    monkeypatch.setattr(INJ.time, "sleep", sleeps.append)
    inj = INJ.FaultInjector(
        [INJ.Fault(kind="slow", step=1, arg=0.5, rank=1)], own_rank=0)
    inj.before_step(1)
    assert sleeps == [] and inj.slow_s == 0.0
    assert [f.kind for f in inj.skipped] == ["slow"]


def test_corrupt_resp_fault_fires_once():
    from dear_pytorch_tpu.resilience import inject as INJ

    inj = INJ.FaultInjector(
        [INJ.Fault(kind="corrupt_resp", step=2)], own_rank=0)
    data = b'{"id": "x", "tokens": [1, 2], "sha256": "abc"}'
    assert inj.corrupt_payload(1, data) == data
    flipped = inj.corrupt_payload(2, data)
    assert flipped != data and flipped[16:] == data[16:]
    assert inj.corrupt_payload(3, data) == data
    assert inj.pending == 0


# ---------------------------------------------------------------------------
# the end-to-end gate
# ---------------------------------------------------------------------------


@pytest.mark.timeout(560, method="signal")
def test_chaos_check_serve_storm(tmp_path):
    """scripts/chaos_check.py --serve: the fault-tolerant serving fleet
    gate (ISSUE-11 acceptance). A 2-replica supervised fleet absorbs an
    overload burst (explicit 429-style shedding + decorrelated-jitter
    client retries), a SIGKILL mid-traffic (in-flight requests
    re-dispatched — zero accepted-then-lost), a checksum-corrupted
    response, a rolling weight swap through the drain/backfill protocol
    with the fleet continuously serving, and a capacity scale-up to 3 —
    all machine-checked, ending in `bench_gate.py --slo` holding a
    throughput floor AND a p99-latency ceiling across the storm."""
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = os.path.join(repo, "scripts", "chaos_check.py")
    env = dict(os.environ)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(
        [sys.executable, script, "--serve", "--workdir", str(tmp_path)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True, timeout=520,
    )
    assert proc.returncode == 0, proc.stdout[-3000:]
    assert "CHAOS CHECK PASSED" in proc.stdout, proc.stdout[-3000:]
