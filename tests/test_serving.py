"""Serving stack: ring-buffer KV-cache decode parity, the
continuous-batching engine, admission backpressure, the router's
zero-drop re-dispatch machinery, the serving fault grammar — and the
`scripts/chaos_check.py --serve` replica-kill storm as the end-to-end
gate (docs/SERVING.md)."""

import json
import os
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dear_pytorch_tpu.models.bert import (
    BertConfig, BertForPreTraining, dot_product_attention,
)
from dear_pytorch_tpu.models.gpt import GptConfig, GptLmHeadModel, generate
from dear_pytorch_tpu.serving import kvcache as KV
from dear_pytorch_tpu.serving.admission import (
    AdmissionController, SheddingError,
)
from dear_pytorch_tpu.serving.engine import DecodeEngine
from dear_pytorch_tpu.serving.router import ReplicaRouter, response_sha256


def _gpt(dtype=jnp.float32, **kw):
    cfg = GptConfig(
        vocab_size=61, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=2, intermediate_size=64,
        max_position_embeddings=32, embd_dropout_prob=0.0,
        hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0,
        dtype=dtype, **kw)
    model = GptLmHeadModel(cfg)
    params = model.init(
        {"params": jax.random.PRNGKey(0)},
        jnp.zeros((2, 4), jnp.int32), train=False)["params"]
    return model, params


def _bert(dtype=jnp.float32, **kw):
    cfg = BertConfig(
        vocab_size=60, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=2, intermediate_size=64,
        max_position_embeddings=32, hidden_dropout_prob=0.0,
        attention_probs_dropout_prob=0.0, dtype=dtype, **kw)
    model = BertForPreTraining(cfg)
    params = model.init(
        {"params": jax.random.PRNGKey(0)},
        jnp.zeros((2, 4), jnp.int32), train=False)["params"]
    return model, params


def _gpt_decode_logits(model, params, ids):
    """Stepwise decode over every position of ``ids``; stacked logits."""
    cache = model.init(
        {"params": jax.random.PRNGKey(0)}, ids[:, :1], train=False,
        decode=True)["cache"]
    steps = []
    for t in range(ids.shape[1]):
        step, vars_out = model.apply(
            {"params": params, "cache": cache}, ids[:, t:t + 1],
            train=False, decode=True, position_offset=t, mutable=["cache"])
        cache = vars_out["cache"]
        steps.append(np.asarray(step[:, 0]))
    return np.stack(steps, axis=1)


def _bert_decode_logits(model, params, ids):
    cache = model.init(
        {"params": jax.random.PRNGKey(0)}, ids[:, :1], train=False,
        decode=True)["cache"]
    steps = []
    for t in range(ids.shape[1]):
        (step, _nsp), vars_out = model.apply(
            {"params": params, "cache": cache}, ids[:, t:t + 1],
            train=False, decode=True, position_offset=t, mutable=["cache"])
        cache = vars_out["cache"]
        steps.append(np.asarray(step[:, 0]))
    return np.stack(steps, axis=1)


# ---------------------------------------------------------------------------
# KV-cache decode parity (the satellite contract: non-divisible sequence
# lengths, bf16 activations, both model families, flash-backed attend)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("seq_len", [13, 7])
def test_gpt_decode_parity_nondivisible(seq_len):
    model, params = _gpt(kv_cache_len=16)
    ids = jnp.asarray(np.random.RandomState(1).randint(0, 61, (2, seq_len)))
    full = np.asarray(model.apply({"params": params}, ids, train=False))
    dec = _gpt_decode_logits(model, params, ids)
    np.testing.assert_allclose(dec, full, rtol=2e-4, atol=2e-4)


def test_gpt_decode_parity_bf16():
    """bf16 activations through the ring cache: the cached K/V travel in
    bf16 exactly like the full forward's, so decode matches at bf16
    tolerance."""
    model, params = _gpt(dtype=jnp.bfloat16, kv_cache_len=16)
    ids = jnp.asarray(np.random.RandomState(2).randint(0, 61, (2, 13)))
    full = np.asarray(model.apply({"params": params}, ids, train=False))
    dec = _gpt_decode_logits(model, params, ids)
    np.testing.assert_allclose(dec, full, rtol=5e-2, atol=5e-2)


def test_gpt_decode_parity_flash():
    """`decode_use_flash=True` routes the decode attend through the
    Pallas flash kernel (1-row query over the cache, validity as its
    kv_mask) — same logits as the dense path at dtype tolerance."""
    model, params = _gpt(kv_cache_len=16, decode_use_flash=True)
    ids = jnp.asarray(np.random.RandomState(3).randint(0, 61, (2, 13)))
    full = np.asarray(model.apply({"params": params}, ids, train=False))
    dec = _gpt_decode_logits(model, params, ids)
    np.testing.assert_allclose(dec, full, rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("dtype,rtol", [(jnp.float32, 2e-4),
                                        (jnp.bfloat16, 5e-2)])
def test_bert_decode_parity(dtype, rtol):
    """BERT's incremental decode is left-to-right by construction; its
    logits reproduce the full forward under ``causal=True`` — at every
    position, for a non-divisible length, in f32 and bf16."""
    model, params = _bert(dtype=dtype, kv_cache_len=16)
    ids = jnp.asarray(np.random.RandomState(4).randint(0, 60, (2, 13)))
    full, _ = model.apply({"params": params}, ids, train=False, causal=True)
    dec = _bert_decode_logits(model, params, ids)
    np.testing.assert_allclose(dec, np.asarray(full), rtol=rtol, atol=rtol)


def test_bert_causal_rejects_custom_attention_impl():
    model, params = _bert()
    model = BertForPreTraining(model.config,
                               attention_impl=dot_product_attention)
    ids = jnp.zeros((1, 4), jnp.int32)
    with pytest.raises(ValueError, match="causal=True"):
        model.apply({"params": params}, ids, train=False, causal=True)


def test_ring_cache_wraps_to_sliding_window():
    """Past the ring length the cache holds exactly the last L tokens:
    attention equals dense attention over that window, at every step."""
    B, L, H, D, T = 2, 8, 2, 4, 13
    rs = np.random.RandomState(5)
    ks = jnp.asarray(rs.randn(B, T, H, D), jnp.float32)
    vs = jnp.asarray(rs.randn(B, T, H, D), jnp.float32)
    qs = jnp.asarray(rs.randn(B, T, H, D), jnp.float32)
    ck = jnp.zeros((B, L, H, D))
    cv = jnp.zeros((B, L, H, D))
    for t in range(T):
        pos = jnp.full((B,), t, jnp.int32)
        ck, cv = KV.ring_write(ck, cv, pos, ks[:, t:t + 1], vs[:, t:t + 1])
        valid = KV.ring_validity(pos, L)
        out = KV.cache_attend(qs[:, t:t + 1], ck, cv, valid,
                              dtype=jnp.float32)
        lo = max(0, t + 1 - L)
        ref = dot_product_attention(
            qs[:, t:t + 1], ks[:, lo:t + 1], vs[:, lo:t + 1], None,
            dtype=jnp.float32)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)


# ---------------------------------------------------------------------------
# chunked prefill: chunk logits == token-at-a-time == full forward, at
# non-divisible prompt lengths (S=13, C=4), f32 and bf16, including a
# chunk spanning the ring-cache wrap boundary
# ---------------------------------------------------------------------------


def _chunk_decode_logits(model, params, ids, C, *, bert=False):
    """Chunked prefill over every position of ``ids``: ceil(S/C) model
    calls of shape [B, C]; stacked logits at prompt positions."""
    B, S = ids.shape
    cache = model.init(
        {"params": jax.random.PRNGKey(0)},
        jnp.zeros((B, C), jnp.int32), train=False, decode=True,
        prefill_lengths=jnp.zeros((B,), jnp.int32))["cache"]
    out, pos = [], 0
    while pos < S:
        n = min(C, S - pos)
        toks = np.zeros((B, C), np.int32)
        toks[:, :n] = np.asarray(ids[:, pos:pos + n])
        step, vars_out = model.apply(
            {"params": params, "cache": cache}, jnp.asarray(toks),
            train=False, decode=True,
            position_offset=jnp.full((B,), pos, jnp.int32),
            prefill_lengths=jnp.full((B,), n, jnp.int32),
            mutable=["cache"])
        cache = vars_out["cache"]
        logits = step[0] if isinstance(step, tuple) else step
        out.append(np.asarray(logits)[:, :n])
        pos += n
    return np.concatenate(out, axis=1)


@pytest.mark.parametrize("dtype,tol", [(jnp.float32, 2e-4),
                                       (jnp.bfloat16, 5e-2)])
def test_gpt_chunked_prefill_matches_stepwise_and_full(dtype, tol):
    model, params = _gpt(dtype=dtype, kv_cache_len=16)
    ids = jnp.asarray(np.random.RandomState(11).randint(0, 61, (2, 13)))
    full = np.asarray(model.apply({"params": params}, ids, train=False))
    step = _gpt_decode_logits(model, params, ids)
    chunk = _chunk_decode_logits(model, params, ids, 4)
    np.testing.assert_allclose(chunk, step, rtol=tol, atol=tol)
    np.testing.assert_allclose(chunk, full, rtol=tol, atol=tol)


@pytest.mark.parametrize("dtype,tol", [(jnp.float32, 2e-4),
                                       (jnp.bfloat16, 5e-2)])
def test_bert_chunked_prefill_matches_stepwise_and_full(dtype, tol):
    model, params = _bert(dtype=dtype, kv_cache_len=16)
    ids = jnp.asarray(np.random.RandomState(12).randint(0, 60, (2, 13)))
    full, _ = model.apply({"params": params}, ids, train=False, causal=True)
    step = _bert_decode_logits(model, params, ids)
    chunk = _chunk_decode_logits(model, params, ids, 4, bert=True)
    np.testing.assert_allclose(chunk, step, rtol=tol, atol=tol)
    np.testing.assert_allclose(chunk, np.asarray(full), rtol=tol, atol=tol)


def test_gpt_chunked_prefill_across_ring_wrap():
    """L=8 < S=13: chunk [pos 6..9] spans the wrap boundary (position 8
    lands in slot 0, overwriting token 0 mid-chunk) — the pre-write chunk
    attend must still give query 6 its full window. Reference: the
    token-at-a-time sliding-window decode, which is exact by the ring
    contract."""
    model, params = _gpt(kv_cache_len=8)
    ids = jnp.asarray(np.random.RandomState(13).randint(0, 61, (2, 13)))
    step = _gpt_decode_logits(model, params, ids)
    chunk = _chunk_decode_logits(model, params, ids, 4)
    np.testing.assert_allclose(chunk, step, rtol=2e-4, atol=2e-4)


def test_gpt_chunk_larger_than_ring_rejected():
    model, params = _gpt(kv_cache_len=8)
    with pytest.raises(ValueError, match="ring length"):
        model.apply(
            {"params": params}, jnp.zeros((1, 9), jnp.int32), train=False,
            decode=True, position_offset=jnp.zeros((1,), jnp.int32),
            prefill_lengths=jnp.full((1,), 9, jnp.int32),
            mutable=["cache"])


def test_kv_cache_dtype_bf16_decode_parity():
    """`kv_cache_dtype=bf16` halves cache bytes; decode then matches the
    full f32 forward at bf16 tolerance, chunked and token-at-a-time."""
    model, params = _gpt(kv_cache_len=16, kv_cache_dtype=jnp.bfloat16)
    cache = model.init(
        {"params": jax.random.PRNGKey(0)},
        jnp.zeros((2, 1), jnp.int32), train=False, decode=True)["cache"]
    assert jax.tree.leaves(cache)[0].dtype == jnp.bfloat16
    ids = jnp.asarray(np.random.RandomState(14).randint(0, 61, (2, 13)))
    full = np.asarray(model.apply({"params": params}, ids, train=False))
    dec = _gpt_decode_logits(model, params, ids)
    chunk = _chunk_decode_logits(model, params, ids, 4)
    np.testing.assert_allclose(dec, full, rtol=5e-2, atol=5e-2)
    np.testing.assert_allclose(chunk, full, rtol=5e-2, atol=5e-2)


# ---------------------------------------------------------------------------
# continuous-batching engine
# ---------------------------------------------------------------------------


def test_engine_mixed_prefill_decode_matches_generate():
    """Three requests of different prompt lengths, arriving staggered
    into two slots (continuous batching: one finishes, the next enters),
    must each reproduce the per-request `generate()` tokens exactly —
    prefill and decode phases mix in ONE jitted step."""
    model, params = _gpt(kv_cache_len=16)
    rs = np.random.RandomState(6)
    prompts = [list(rs.randint(0, 61, n)) for n in (4, 7, 5)]
    refs = [list(np.asarray(
        generate(model, params, jnp.asarray([p]), max_new_tokens=5)
        [0, len(p):])) for p in prompts]
    eng = DecodeEngine(model, params, slots=2)
    assert eng.submit(prompts[0], 5, request_id="a") is not None
    assert eng.submit(prompts[1], 5, request_id="b") is not None
    assert eng.submit(prompts[2], 5, request_id="c") is None  # batch full
    done, pending = {}, [("c", prompts[2])]
    for _ in range(100):
        for fin in eng.tick():
            done[fin.request_id] = fin.tokens
            if pending and eng.free:
                rid, p = pending.pop()
                eng.submit(p, 5, request_id=rid)
        if len(done) == 3:
            break
    assert done["a"] == refs[0]
    assert done["b"] == refs[1]
    assert done["c"] == refs[2]  # served in a reused slot
    assert eng.active == 0 and eng.free == 2


def test_engine_rejects_over_budget_and_empty_prompts():
    model, params = _gpt()
    eng = DecodeEngine(model, params, slots=1)
    with pytest.raises(ValueError, match="position budget"):
        eng.submit(list(range(30)), 10)
    with pytest.raises(ValueError, match="empty prompt"):
        eng.submit([], 4)


def _drain(eng, prompts, max_new=5, submit_ids=None, budget=300):
    """Submit + tick to completion; returns ({rid: FinishedRequest}, ticks)."""
    done = {}
    pending = list(submit_ids or [])
    ticks = 0
    for _ in range(budget):
        for fin in eng.tick():
            done[fin.request_id] = fin
            while pending and eng.free:
                rid = pending.pop(0)
                eng.submit(prompts[rid], max_new, request_id=rid)
        ticks += 1
        if len(done) == len(prompts):
            return done, ticks
        if eng.active == 0 and not pending:
            break
    return done, ticks


def test_engine_chunked_prefill_matches_generate_staggered():
    """The PR-11 invariant on the fast path: chunked prefill (C=4)
    reproduces per-request `generate()` tokens exactly across staggered
    arrivals and slot reuse — AND takes ceil(P/C) prefill ticks: the
    P=13 request completes in ceil(13/4)+5 = 9 engine ticks instead of
    13+5."""
    model, params = _gpt(kv_cache_len=16)
    rs = np.random.RandomState(21)
    prompts = {i: list(rs.randint(0, 61, n))
               for i, n in enumerate((13, 7, 5))}
    refs = {i: list(np.asarray(
        generate(model, params, jnp.asarray([p]), max_new_tokens=5)
        [0, len(p):])) for i, p in prompts.items()}

    e1 = DecodeEngine(model, params, slots=2)
    e1.submit(prompts[0], 5, request_id=0)
    e1.submit(prompts[1], 5, request_id=1)
    d1, _ = _drain(e1, prompts, submit_ids=[2])

    e4 = DecodeEngine(model, params, slots=2, prefill_chunk=4)
    e4.submit(prompts[0], 5, request_id=0)
    e4.submit(prompts[1], 5, request_id=1)
    d4, _ = _drain(e4, prompts, submit_ids=[2])

    # the tick consuming the last prompt token also samples token 1, so
    # a P-prompt/D-token request takes ceil(P/C) + D - 1 ticks (chunked)
    # vs P + D - 1 (token-at-a-time)
    for i in prompts:
        assert d1[i].tokens == refs[i]
        assert d4[i].tokens == refs[i]       # fast path: same tokens...
    assert d4[0].steps == -(-13 // 4) + 5 - 1  # ...in ceil(P/C)+D-1 ticks
    assert d1[0].steps == 13 + 5 - 1
    # per-phase accounting feeds the split admission estimates
    assert d4[0].prefill_s > 0 and d4[0].decode_s > 0


def test_engine_prefill_burst_budget_interleaves_decodes():
    """A long-prompt arrival must not starve an in-flight decode: with
    `prefill_burst=1` the engine alternates prefill and decode ticks, so
    the decoding request keeps generating while the long prompt
    prefills."""
    model, params = _gpt(kv_cache_len=16)
    rs = np.random.RandomState(22)
    short, long_ = list(rs.randint(0, 61, 2)), list(rs.randint(0, 61, 13))
    eng = DecodeEngine(model, params, slots=2, prefill_chunk=4,
                       prefill_burst=1)
    eng.submit(short, 8, request_id="short")
    eng.tick()                      # short's prompt (2 toks <= one tick's
    eng.tick()                      # worth) consumed; now decoding
    assert eng._slots[0].prompt_remaining == 0
    gen_before = len(eng._slots[0].generated)
    eng.submit(long_, 2, request_id="long")
    eng.tick()                      # prefill tick (streak 1)
    assert eng._slots[1].fed == 4   # the chunk landed...
    assert len(eng._slots[0].generated) == gen_before  # ...short frozen
    eng.tick()                      # burst budget hit -> decode tick
    assert len(eng._slots[0].generated) == gen_before + 1
    # and the tokens still match the interleave-free reference
    done, _ = _drain(eng, {"short": short, "long": long_})
    want_short = list(np.asarray(generate(
        model, params, jnp.asarray([short]), max_new_tokens=8)
        [0, len(short):]))
    want_long = list(np.asarray(generate(
        model, params, jnp.asarray([long_]), max_new_tokens=2)
        [0, len(long_):]))
    assert done["short"].tokens == want_short
    assert done["long"].tokens == want_long


def test_engine_rejects_stochastic_sampler_and_oversize_chunk():
    """The deterministic-generation contract is ASSERTED at construction:
    the router's re-dispatch-after-kill correctness rests on greedy
    argmax, so a stochastic sampler knob must fail loudly, not silently
    break zero-drop."""
    model, params = _gpt(kv_cache_len=8)
    with pytest.raises(ValueError, match="greedy"):
        DecodeEngine(model, params, sampler="temperature")
    with pytest.raises(ValueError, match="ring length"):
        DecodeEngine(model, params, prefill_chunk=9)
    with pytest.raises(ValueError, match="prefill_chunk"):
        DecodeEngine(model, params, prefill_chunk=0)


def test_engine_phase_gauges_exported():
    model, params = _gpt(kv_cache_len=16)
    eng = DecodeEngine(model, params, slots=2, prefill_chunk=4)
    eng.submit(list(range(9)), 2, request_id="r")
    for _ in range(12):
        eng.tick()
        if eng.active == 0:
            break
    g = eng.phase_gauges()
    for name in ("serve.prefill_ms_p50", "serve.prefill_ms_p99",
                 "serve.decode_tick_ms_p50", "serve.decode_tick_ms_p99"):
        assert name in g and g[name] > 0


def test_engine_excludes_compile_tick_from_phase_accounting():
    """Each program's FIRST execution is its XLA compile; attributing it
    to the live slots would poison the admission controller's per-token
    rates and shed deadline-bearing requests on an idle fleet."""
    model, params = _gpt(kv_cache_len=16)
    eng = DecodeEngine(model, params, slots=2, prefill_chunk=4)
    eng.submit(list(range(9)), 2, request_id="r")
    eng.tick()                                   # prefill compile tick
    assert len(eng._prefill_tick_s) == 0
    assert eng._slots[0].prefill_s == 0.0        # nothing attributed
    eng.tick()                                   # warm prefill tick
    assert len(eng._prefill_tick_s) == 1
    assert eng._slots[0].prefill_s > 0.0


@pytest.mark.parametrize("family", ["gpt", "bert"])
def test_engine_ring_tp_decode_matches_dense(mesh, family):
    """Ring-TP decode behind the engine's tp_mesh knob: the QKV/MLP
    projections stream weight shards through the PR-8 ring
    collective-matmul kernels (interpret mode on the emulated mesh) and
    the engine reproduces the dense engine's tokens exactly. The dense
    fallback (tp_mesh=None) is byte-identical to the pre-TP engine."""
    if family == "gpt":
        model, params = _gpt(kv_cache_len=16)
    else:
        model, params = _bert(kv_cache_len=16)
    rs = np.random.RandomState(23)
    prompts = {0: list(rs.randint(0, 60, 5)), 1: list(rs.randint(0, 60, 3))}

    dense = DecodeEngine(model, params, slots=2, prefill_chunk=1)
    for i, p in prompts.items():
        dense.submit(p, 3, request_id=i)
    want, _ = _drain(dense, prompts, max_new=3)

    tp = DecodeEngine(model, params, slots=2, prefill_chunk=1,
                      tp_mesh=mesh)
    for i, p in prompts.items():
        tp.submit(p, 3, request_id=i)
    got, _ = _drain(tp, prompts, max_new=3)
    for i in prompts:
        assert got[i].tokens == want[i].tokens


# ---------------------------------------------------------------------------
# admission control
# ---------------------------------------------------------------------------


def test_admission_depth_and_deadline_shedding():
    adm = AdmissionController(max_depth=2, capacity=1)
    adm.admit(None)
    adm.admit(None)
    with pytest.raises(SheddingError) as exc:
        adm.admit(None)                      # bounded queue depth
    assert exc.value.depth == 2
    adm.complete(1.0)                        # service time learned: 1s
    assert adm.service_time_s == pytest.approx(1.0)
    # depth 1, svc 1s -> predicted wait 1s: a 0.1s budget is hopeless
    with pytest.raises(SheddingError):
        adm.admit(0.1)
    adm.admit(2.0)                           # a 2s budget fits
    assert adm.requests == 5 and adm.admitted == 3 and adm.shed == 2


def test_admission_capacity_scales_predicted_wait():
    adm = AdmissionController(max_depth=10, capacity=1,
                              service_time_s=1.0)
    adm.admit(None)
    adm.admit(None)
    with pytest.raises(SheddingError):
        adm.admit(1.5)                       # 2 deep x 1s / 1 slot = 2s
    adm.set_capacity(4)                      # fleet grew: 2s -> 0.5s
    adm.admit(1.5)


def test_admission_split_phase_rates_spare_short_requests():
    """THE satellite fix: one blended service EWMA lets a burst of long
    prompts shed short decode-bound requests. With split per-token rates
    the controller budgets a request as prefill_est(len) +
    decode_est(max_tokens): a short request still fits its deadline even
    while the blended average is inflated."""
    adm = AdmissionController(max_depth=10, capacity=1)
    # long-prompt burst: 10s requests dominated by prefill (1000 tokens
    # at 10 ms/token), 10 decode tokens at 1 ms
    for _ in range(4):
        adm.admit(None)
    for _ in range(4):
        adm.complete(10.0, prefill_tokens=1000, prefill_s=9.99,
                     decode_tokens=10, decode_s=0.01)
    assert adm.service_time_s > 5.0          # blended EWMA is inflated
    assert adm.prefill_rate_s == pytest.approx(0.00999, rel=1e-3)
    assert adm.decode_rate_s == pytest.approx(0.001, rel=1e-3)
    # empty queue, short decode-bound request (8-token prompt, 20 new):
    # own estimate ~0.1s — a 0.5s deadline budget must ADMIT
    adm.admit(0.5, prompt_tokens=8, max_new_tokens=20)
    adm.complete(0.1, prefill_tokens=8, prefill_s=0.05,
                 decode_tokens=20, decode_s=0.05)
    # ...while a long-prompt request with the same budget is shed on its
    # own shape (1000 x 10ms >> 0.5s), not on queue depth
    with pytest.raises(SheddingError):
        adm.admit(0.5, prompt_tokens=1000, max_new_tokens=10)
    # legacy callers (no shape info) keep the original blended behavior
    adm.admit(None)
    assert adm.shed == 1


def test_shed_retry_with_decorrelated_jitter():
    """The client contract: SheddingError is retryable through
    `resilience.retry` and eventually lands."""
    from dear_pytorch_tpu.resilience.retry import retry_call

    calls = [0]

    def submit():
        calls[0] += 1
        if calls[0] < 3:
            raise SheddingError("shed", depth=5, predicted_wait_s=1.0)
        return "rid"

    assert retry_call(submit, attempts=5, base_delay_s=0.001,
                      max_delay_s=0.01,
                      retry_on=(SheddingError,)) == "rid"
    assert calls[0] == 3


# ---------------------------------------------------------------------------
# router: health, re-dispatch, checksum, weight swaps (fake replicas —
# plain threads speaking the file protocol; no jax)
# ---------------------------------------------------------------------------


class _FakeReplica:
    """Thread speaking the replica file protocol with scriptable
    behavior: heartbeat-only, serve, or corrupt-once."""

    def __init__(self, root, rank, *, version=1, incarnation="a",
                 serve=True, corrupt_first=False):
        self.root, self.rank = root, rank
        self.version, self.incarnation = version, incarnation
        self.serve, self.corrupt_first = serve, corrupt_first
        self.corrupted = 0
        self._stop = threading.Event()
        self._dir = os.path.join(root, "replicas", str(rank))
        self._inbox = os.path.join(self._dir, "inbox")
        os.makedirs(self._inbox, exist_ok=True)
        os.makedirs(os.path.join(root, "responses"), exist_ok=True)
        self._thread = threading.Thread(target=self._run, daemon=True)

    def start(self):
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        self._thread.join(timeout=5)

    def _beat(self):
        doc = {"ts": time.time(), "pid": os.getpid(),
               "incarnation": self.incarnation, "version": self.version,
               "draining": False, "stopped": False}
        path = os.path.join(self._dir, "health.json")
        with open(path + ".tmp", "w") as f:
            json.dump(doc, f)
        os.replace(path + ".tmp", path)

    def _run(self):
        while not self._stop.is_set():
            self._beat()
            if self.serve:
                for name in sorted(os.listdir(self._inbox)):
                    if not name.endswith(".json"):
                        continue
                    p = os.path.join(self._inbox, name)
                    try:
                        with open(p) as f:
                            rec = json.load(f)
                        os.unlink(p)
                    except (OSError, ValueError):
                        continue
                    payload = {"id": rec["id"],
                               "tokens": rec["prompt"][::-1],
                               "model_version": self.version,
                               "replica": self.rank}
                    payload["sha256"] = response_sha256(payload)
                    if self.corrupt_first and not self.corrupted:
                        payload["sha256"] = "0" * 64
                        self.corrupted += 1
                    rp = os.path.join(self.root, "responses",
                                      rec["id"] + ".json")
                    with open(rp + ".tmp", "w") as f:
                        json.dump(payload, f)
                    os.replace(rp + ".tmp", rp)
            time.sleep(0.01)


def _router(root, **kw):
    kw.setdefault("admission", AdmissionController(max_depth=16))
    kw.setdefault("slots_per_replica", 2)
    kw.setdefault("health_timeout_s", 0.6)
    kw.setdefault("poll_s", 0.01)
    return ReplicaRouter(root, **kw)


def _wait(cond, timeout=10.0):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        if cond():
            return True
        time.sleep(0.01)
    return False


def test_router_serves_and_accounts_deadline_miss(tmp_path):
    root = str(tmp_path)
    rep = _FakeReplica(root, 0).start()
    with _router(root) as router:
        assert _wait(lambda: router.healthy_replicas() == [0])
        rid = router.submit([1, 2, 3], max_new_tokens=2, deadline_s=30.0)
        resp = router.result(rid, timeout=10)
        assert resp["tokens"] == [3, 2, 1]
        # a deadline in the past is still SERVED, but accounted as missed
        rid2 = router.submit([4, 5], max_new_tokens=1, deadline_s=0.0)
        assert router.result(rid2, timeout=10)["tokens"] == [5, 4]
        assert router.deadline_missed == 1
        assert not router.open_requests()
    rep.stop()


def test_router_redispatches_from_dead_replica(tmp_path):
    """The zero-drop mechanism: a replica that heartbeats, takes work,
    and dies has its in-flight requests re-dispatched to a survivor."""
    root = str(tmp_path)
    dead = _FakeReplica(root, 0, serve=False).start()
    with _router(root) as router:
        assert _wait(lambda: router.healthy_replicas() == [0])
        rid = router.submit([7, 8, 9], max_new_tokens=2, deadline_s=None)
        assert _wait(lambda: router.inflight_on(0) == 1)
        dead.stop()                       # heartbeats cease: replica dies
        live = _FakeReplica(root, 1, incarnation="b").start()
        resp = router.result(rid, timeout=15)
        assert resp["tokens"] == [9, 8, 7] and resp["replica"] == 1
        assert router.redispatched >= 1
        assert not router.open_requests()
        live.stop()


def test_router_redispatches_on_incarnation_change(tmp_path):
    """A FAST restart (new incarnation before the staleness window
    expires) also triggers reclaim — the restarted replica cleared its
    inbox, so waiting on it would drop the request."""
    root = str(tmp_path)
    first = _FakeReplica(root, 0, serve=False, incarnation="a").start()
    with _router(root) as router:
        assert _wait(lambda: router.healthy_replicas() == [0])
        rid = router.submit([1, 2], max_new_tokens=1, deadline_s=None)
        assert _wait(lambda: router.inflight_on(0) == 1)
        first.stop()
        # same rank, new life, and it actually serves
        second = _FakeReplica(root, 0, incarnation="b").start()
        assert router.result(rid, timeout=15)["tokens"] == [2, 1]
        assert router.redispatched >= 1
        second.stop()


def test_router_rejects_corrupt_response_and_reserves(tmp_path):
    root = str(tmp_path)
    rep = _FakeReplica(root, 0, corrupt_first=True).start()
    with _router(root) as router:
        assert _wait(lambda: router.healthy_replicas() == [0])
        rid = router.submit([5, 6, 7], max_new_tokens=2, deadline_s=None)
        resp = router.result(rid, timeout=15)
        assert resp["tokens"] == [7, 6, 5]          # re-served, verified
        assert resp["sha256"] == response_sha256(resp)
        assert router.corrupt_responses == 1
    rep.stop()


def test_corrupt_response_after_reclaim_not_requeued_twice(tmp_path):
    """A corrupt response racing its replica's death must not re-queue
    the request a second time — the death reclaim already did; a second
    copy would dispatch the request twice and leak the losing replica's
    decode slot."""
    root = str(tmp_path)
    dead = _FakeReplica(root, 0, serve=False).start()
    with _router(root) as router:
        assert _wait(lambda: router.healthy_replicas() == [0])
        rid = router.submit([1, 2, 3], max_new_tokens=1, deadline_s=None)
        assert _wait(lambda: router.inflight_on(0) == 1)
        dead.stop()                  # replica dies; the reclaim re-queues
        assert _wait(lambda: router.redispatched >= 1)
        # ...and only NOW the dead life's corrupt response surfaces
        payload = {"id": rid, "tokens": [9], "model_version": 1,
                   "replica": 0, "sha256": "0" * 64}
        rp = os.path.join(root, "responses", rid + ".json")
        with open(rp + ".tmp", "w") as f:
            json.dump(payload, f)
        os.replace(rp + ".tmp", rp)
        assert _wait(lambda: router.corrupt_responses >= 1)
        with router._lock:
            copies = list(router._pending).count(rid)
        assert copies == 1
        # a live replica then serves the single copy to completion
        live = _FakeReplica(root, 1, incarnation="b").start()
        assert router.result(rid, timeout=15)["tokens"] == [3, 2, 1]
        assert not router.open_requests()
        live.stop()


def test_request_trace_survives_replica_death_with_hop_span(tmp_path):
    """Trace continuity across the zero-drop path: the request keeps ONE
    trace_id from submit through a replica death and front-of-queue
    redispatch; the incarnation boundary it crossed is recorded as a
    `serve.redispatch_hop` span (child of the request trace, carrying
    the dead life's incarnation), and the survivor's completion closes
    the root `serve.request` span on the same trace."""
    from dear_pytorch_tpu.observability import critical_path as CP
    from dear_pytorch_tpu.observability import dtrace

    root = str(tmp_path)
    mw = dtrace.MemoryWriter()
    dtrace.set_stream(dtrace.SpanStream(mw, rank="router"))
    try:
        dead = _FakeReplica(root, 0, serve=False, incarnation="a").start()
        with _router(root) as router:
            assert _wait(lambda: router.healthy_replicas() == [0])
            rid = router.submit([7, 8, 9], max_new_tokens=2,
                                deadline_s=None)
            tid = router._requests[rid].record["trace"]["trace_id"]
            assert tid and not tid.startswith("step-")
            assert _wait(lambda: router.inflight_on(0) == 1)
            dead.stop()                  # heartbeats cease: replica dies
            live = _FakeReplica(root, 1, incarnation="b").start()
            resp = router.result(rid, timeout=15)
            assert resp["tokens"] == [9, 8, 7]
            assert router.redispatched >= 1
            live.stop()
    finally:
        dtrace.disable_stream()

    spans = [r for r in mw.records if r.get("kind") == "span"]
    of_trace = [s for s in spans
                if (s.get("trace") or {}).get("trace_id") == tid]
    names = [s["name"] for s in of_trace]
    # dispatch to the dead life AND to the survivor — same trace id
    assert names.count("serve.dispatch") >= 2
    hop = next(s for s in of_trace
               if s["name"] == "serve.redispatch_hop")
    assert hop["attrs"]["incarnation"] == "a"
    assert hop["attrs"]["request_id"] == rid
    closes = [s for s in of_trace if s["name"] == "serve.request"]
    assert len(closes) == 1 and closes[0]["attrs"]["replica"] == 1

    att = CP.request_attribution(spans)
    req = next(r for r in att["requests"] if r["trace_id"] == tid)
    assert req["redispatches"] >= 1
    assert req["request_id"] == rid


def test_replica_answers_poison_request_with_signed_error(tmp_path):
    """An admitted request that violates the engine's position budget
    must NOT crash the replica — the router would re-dispatch the poison
    to the next replica and cascade the crash through the fleet. The
    replica answers it with a SIGNED error response instead (the
    zero-drop contract is 'every accepted request gets a verified
    response')."""
    from dear_pytorch_tpu.serving.replica import ReplicaServer

    model, params = _gpt()
    engine = DecodeEngine(model, params, slots=2)
    root = str(tmp_path)
    srv = ReplicaServer(root, 0, engine, version=1)
    inbox = os.path.join(root, "replicas", "0", "inbox")
    with open(os.path.join(inbox, "poison01.json"), "w") as f:
        json.dump({"id": "poison01", "prompt": list(range(30)),
                   "max_new_tokens": 10}, f)   # 40 > 32-position budget
    srv._take_requests()
    assert engine.active == 0          # the poison never entered a slot
    with open(os.path.join(root, "responses", "poison01.json")) as f:
        resp = json.load(f)
    assert resp["sha256"] == response_sha256(resp)
    assert resp["tokens"] == [] and "error" in resp
    # the replica survived: a well-formed request still serves
    with open(os.path.join(inbox, "ok01.json"), "w") as f:
        json.dump({"id": "ok01", "prompt": [1, 2],
                   "max_new_tokens": 1}, f)
    assert srv._take_requests() == 1
    assert engine.active == 1


def test_router_counts_weight_swap(tmp_path):
    root = str(tmp_path)
    v1 = _FakeReplica(root, 0, version=1, incarnation="a").start()
    with _router(root) as router:
        assert _wait(lambda: router.fleet_versions().get(0) == 1)
        v1.stop()
        v2 = _FakeReplica(root, 0, version=2, incarnation="b").start()
        assert _wait(lambda: router.fleet_versions().get(0) == 2)
        assert router.weight_swaps == 1
        v2.stop()


# ---------------------------------------------------------------------------
# weight-version walk-back (previously only the storm exercised this
# indirectly): corrupt the newest version's MANIFEST in one case and a
# PAYLOAD file in the other — load_params must land on the newest intact
# version both times
# ---------------------------------------------------------------------------


def _publish_versions(tmp_path, n=3):
    from dear_pytorch_tpu.serving import weights as W
    from dear_pytorch_tpu.utils.objectstore import LocalObjectStore

    store = LocalObjectStore(str(tmp_path / "store"))
    for v in range(1, n + 1):
        W.publish_params(
            store, {"layer": {"kernel": np.full((2, 2), float(v))}}, v)
    return store, W


def test_weights_walk_back_past_corrupt_manifest(tmp_path):
    store, W = _publish_versions(tmp_path)
    store.put_bytes("weights/v000003/MANIFEST.json", b"{not json")
    params, version = W.load_params(store)
    assert version == 2
    assert params["layer"]["kernel"][0, 0] == 2.0


def test_weights_walk_back_past_corrupt_payload(tmp_path):
    store, W = _publish_versions(tmp_path)
    data = bytearray(store.get_bytes("weights/v000003/params.npz"))
    data[:16] = bytes(b ^ 0xFF for b in data[:16])  # sha mismatch
    store.put_bytes("weights/v000003/params.npz", bytes(data))
    params, version = W.load_params(store)
    assert version == 2
    assert params["layer"]["kernel"][0, 0] == 2.0


def test_weights_walk_back_counts_and_explicit_version(tmp_path):
    from dear_pytorch_tpu.observability import tracer as T

    store, W = _publish_versions(tmp_path)
    data = bytearray(store.get_bytes("weights/v000003/params.npz"))
    data[0] ^= 0xFF
    store.put_bytes("weights/v000003/params.npz", bytes(data))
    old = T._tracer
    T.set_tracer(T.Tracer([T.MemoryExporter()]))
    try:
        _params, version = W.load_params(store)
        assert version == 2
        counters = T.get_tracer().counters()
        assert counters.get("serve.weight_corrupt_detected", 0) >= 1
    finally:
        T.set_tracer(old)
    # an EXPLICITLY requested corrupt version must fail loudly, not
    # silently serve an older one
    with pytest.raises(KeyError):
        W.load_params(store, version=3)


# ---------------------------------------------------------------------------
# canary/rollback: deterministic A/B verdicts between live versions,
# store-side rollback markers, fresh-number republish (ISSUE-17)
# ---------------------------------------------------------------------------


def _canary(**kw):
    from dear_pytorch_tpu.serving.router import CanaryController
    kw.setdefault("min_requests", 3)
    kw.setdefault("quality_floor", 0.9)
    kw.setdefault("latency_factor", 3.0)
    kw.setdefault("share", 3)
    return CanaryController(**kw)


def test_canary_quality_floor_fails_candidate():
    c = _canary()
    for _ in range(3):
        c.observe(1, 0.1, 1.0)
        c.observe(2, 0.1, 0.0)    # NaN-poisoned load: gauge 0.0
    assert c.maybe_decide([1, 2]) == (2, "FAIL")
    assert c.failed(2) and not c.failed(1)
    # memoized: judged exactly once per router life
    assert c.maybe_decide([1, 2]) is None
    assert c.decisions == {2: "FAIL"}


def test_canary_latency_regression_fails_against_baseline():
    c = _canary(latency_factor=3.0)
    for _ in range(3):
        c.observe(1, 0.1, 1.0)    # baseline: 100ms
        c.observe(2, 0.5, 1.0)    # candidate: 5x the baseline
    assert c.maybe_decide([1, 2]) == (2, "FAIL")


def test_canary_passes_healthy_candidate_and_none_quality():
    """A pre-canary replica stamps no gauge — absent evidence must not
    fail a version (None counts as healthy)."""
    c = _canary()
    for _ in range(3):
        c.observe(1, 0.1, None)
        c.observe(2, 0.12, None)
    assert c.maybe_decide([1, 2]) == (2, "PASS")
    assert not c.failed(2)


def test_canary_waits_for_two_versions_and_evidence():
    c = _canary(min_requests=3)
    c.observe(2, 0.1, 1.0)
    assert c.maybe_decide([2, 2]) is None       # one distinct version
    c.observe(2, 0.1, 1.0)
    assert c.maybe_decide([1, 2]) is None       # n=2 < min_requests
    c.observe(2, 0.1, 1.0)
    assert c.maybe_decide([1, 2]) == (2, "PASS")


def test_canary_skips_failed_baseline():
    """The latency baseline is the newest QUALIFIED non-failed older
    version — a failed predecessor must not judge its successor."""
    c = _canary(latency_factor=2.0)
    for _ in range(3):
        c.observe(1, 0.4, 1.0)    # old, slow, healthy
        c.observe(2, 0.01, 0.0)   # poisoned (and deceptively fast)
    assert c.maybe_decide([1, 2]) == (2, "FAIL")
    for _ in range(3):
        c.observe(3, 0.1, 1.0)    # candidate: 10x v2 but < 2x v1
    assert c.maybe_decide([1, 2, 3]) == (3, "PASS")


def test_canary_route_split_is_deterministic():
    c = _canary(share=3)
    picks = [c.route_candidate() for _ in range(9)]
    assert picks == [False, False, True] * 3


def test_weights_rollback_marker_and_live_walk(tmp_path):
    store, W = _publish_versions(tmp_path)      # v1..v3
    assert W.latest_live_version(store) == 3
    assert W.mark_rolled_back(store, 3, reason="canary") is True
    # first-writer-wins: the marker commits once, repeats are idempotent
    assert W.mark_rolled_back(store, 3, reason="again") is False
    assert W.rolled_back(store, 3) and not W.rolled_back(store, 2)
    # the default load walks PAST the dead version; numbering authority
    # still sees it (latest_version is raw — numbers are never reused)
    assert W.latest_live_version(store) == 2
    assert W.latest_version(store) == 3
    params, version = W.load_params(store)
    assert version == 2 and params["layer"]["kernel"][0, 0] == 2.0
    # an EXPLICIT version request overrides the marker (forensics)
    params, version = W.load_params(store, version=3)
    assert version == 3


def test_params_finite_fraction_gauge():
    from dear_pytorch_tpu.serving import weights as W

    good = {"a": {"w": np.ones((2, 2))}, "b": np.arange(3)}
    assert W.params_finite_fraction(good) == 1.0
    bad = {"a": {"w": np.full((2, 2), np.nan)}, "b": np.arange(3)}
    frac = W.params_finite_fraction(bad)
    assert 0.0 < frac < 1.0                     # ints count as finite
    assert W.params_finite_fraction({}) == 1.0


def test_publisher_rollback_then_republish_mints_fresh_number(tmp_path):
    """ISSUE-17 satellite: after a canary rollback the next publish
    mints a FRESH store-authoritative number — the dead version is
    skipped, never reused — and the sidecar provenance (consumed_total)
    stays monotonic across the gap."""
    from dear_pytorch_tpu.online.publish import (
        VersionPublisher, read_online_sidecar,
    )
    from dear_pytorch_tpu.serving import weights as W
    from dear_pytorch_tpu.utils.objectstore import LocalObjectStore

    store = LocalObjectStore(str(tmp_path))
    consumed = [0]
    pub = VersionPublisher(
        store, publish_every=2,
        params_fn=lambda: {"w": np.ones((2,)) * (consumed[0] + 1)},
        cursor_fn=lambda: {"consumed_total": consumed[0]})
    for step in (0, 2, 4):
        consumed[0] += 5
        assert pub.maybe_publish(step) == step // 2 + 1
    assert pub.published == [1, 2, 3]
    assert W.mark_rolled_back(store, 3, reason="canary")
    consumed[0] += 5
    assert pub.maybe_publish(6) == 4            # fresh number, never 3
    assert pub.published == [1, 2, 3, 4]
    _params, version = W.load_params(store)
    assert version == 4                         # serving walks onto v4
    prov = [read_online_sidecar(store, v)["cursor"]["consumed_total"]
            for v in pub.published]
    assert prov == sorted(prov) == [5, 10, 15, 20]
    # cadence: a step inside the publish window is a no-op
    assert pub.maybe_publish(7) is None
    # non-leaders never publish
    assert pub.maybe_publish(99, leader=False) is None


def test_publisher_bad_version_fault_poisons_the_artifact(tmp_path):
    """The ``bad_version`` fault NaNs the Nth publish through the REAL
    publish path: the artifact commits byte-valid, only the serving-side
    finiteness gauge can tell — exactly what the canary exists for."""
    from dear_pytorch_tpu.online.publish import VersionPublisher
    from dear_pytorch_tpu.resilience.inject import (
        FaultInjector, parse_faults,
    )
    from dear_pytorch_tpu.serving import weights as W
    from dear_pytorch_tpu.utils.objectstore import LocalObjectStore

    store = LocalObjectStore(str(tmp_path))
    inj = FaultInjector(parse_faults("bad_version@2"), own_rank=0)
    pub = VersionPublisher(store, publish_every=1,
                           params_fn=lambda: {"w": np.ones((4,))},
                           injector=inj)
    assert pub.maybe_publish(1) == 1
    assert pub.maybe_publish(2) == 2            # poisoned on the way out
    assert pub.maybe_publish(3) == 3
    p1, _ = W.load_params(store, version=1)
    p2, _ = W.load_params(store, version=2)
    p3, _ = W.load_params(store, version=3)
    assert W.params_finite_fraction(p1) == 1.0
    assert W.params_finite_fraction(p2) == 0.0  # every leaf NaN
    assert W.params_finite_fraction(p3) == 1.0  # trainer state untouched


def test_publisher_survives_publish_failure(tmp_path):
    from dear_pytorch_tpu.online.publish import VersionPublisher
    from dear_pytorch_tpu.utils.objectstore import LocalObjectStore

    store = LocalObjectStore(str(tmp_path))
    boom = [True]

    def params_fn():
        if boom[0]:
            raise IOError("store down")
        return {"w": np.zeros((2,))}

    pub = VersionPublisher(store, publish_every=1, params_fn=params_fn)
    assert pub.maybe_publish(1) is None
    assert pub.publish_failures == 1 and pub.published == []
    boom[0] = False
    assert pub.maybe_publish(2) == 1            # next cadence recovers


# ---------------------------------------------------------------------------
# serving fault grammar (resilience.inject satellites)
# ---------------------------------------------------------------------------


def test_parse_slow_and_corrupt_resp_faults():
    from dear_pytorch_tpu.resilience.inject import parse_faults

    faults = parse_faults("slow@3:0.05:r1,corrupt_resp@5")
    assert faults[0].kind == "slow" and faults[0].step == 3
    assert faults[0].arg == pytest.approx(0.05) and faults[0].rank == 1
    assert faults[1].kind == "corrupt_resp" and faults[1].rank is None


def test_slow_fault_is_persistent(monkeypatch):
    """``slow`` arms a PERSISTENT per-step latency (a straggler), unlike
    ``hang``'s one-shot sleep."""
    from dear_pytorch_tpu.resilience import inject as INJ

    sleeps = []
    monkeypatch.setattr(INJ.time, "sleep", sleeps.append)
    inj = INJ.FaultInjector(
        [INJ.Fault(kind="slow", step=2, arg=0.05)], own_rank=0)
    inj.before_step(1)
    assert sleeps == []
    inj.before_step(2)
    inj.before_step(3)
    assert sleeps == [0.05, 0.05] and inj.slow_s == pytest.approx(0.05)
    assert inj.pending == 0


def test_slow_fault_rank_targeted_skip(monkeypatch):
    from dear_pytorch_tpu.resilience import inject as INJ

    sleeps = []
    monkeypatch.setattr(INJ.time, "sleep", sleeps.append)
    inj = INJ.FaultInjector(
        [INJ.Fault(kind="slow", step=1, arg=0.5, rank=1)], own_rank=0)
    inj.before_step(1)
    assert sleeps == [] and inj.slow_s == 0.0
    assert [f.kind for f in inj.skipped] == ["slow"]


def test_corrupt_resp_fault_fires_once():
    from dear_pytorch_tpu.resilience import inject as INJ

    inj = INJ.FaultInjector(
        [INJ.Fault(kind="corrupt_resp", step=2)], own_rank=0)
    data = b'{"id": "x", "tokens": [1, 2], "sha256": "abc"}'
    assert inj.corrupt_payload(1, data) == data
    flipped = inj.corrupt_payload(2, data)
    assert flipped != data and flipped[16:] == data[16:]
    assert inj.corrupt_payload(3, data) == data
    assert inj.pending == 0


# ---------------------------------------------------------------------------
# the serving tuner harness (scripts/serve_tune.py): search completes on
# the emulated mesh, emits the SLO-gateable contract + the A/B fixture
# ---------------------------------------------------------------------------


@pytest.mark.timeout(420, method="signal")
def test_serve_tune_harness_and_gates(tmp_path):
    """Miniature `serve_tune.py` run: the ServeTuner searches a restricted
    space against real closed-loop episodes, the summary passes
    `bench_gate.py --slo` (throughput floor + p99 ceiling), and the
    chunked:token A/B fixture gates green — chunking must actually win
    on the emulated mesh."""
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("DEAR_NUM_CPU_DEVICES", None)
    out = str(tmp_path / "serving")
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "scripts", "serve_tune.py"),
         "--out", out, "--trials", "3", "--requests", "8", "--slots", "2",
         "--chunk-bound", "1,4", "--no-flash", "--emulate", "1"],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True, timeout=360)
    assert proc.returncode == 0, proc.stdout[-3000:]
    with open(os.path.join(out, "summary.json")) as f:
        summary = json.load(f)
    assert summary["tuner"]["finished"]
    assert summary["best"]["chunk"] >= 1
    assert "CPU-emulated" in summary["caveat"]
    gate = os.path.join(repo, "scripts", "bench_gate.py")
    for args in (
        [gate, "--run", os.path.join(out, "summary.json"),
         "--slo", "requests_per_s=1", "--slo", "p99_latency_ms<=60000"],
        [gate, "--run", os.path.join(out, "ab_reports.json"),
         "--ab-methods", "chunked:token", "--tolerance", "0.2"],
        # generous tolerance: this pins the --ab-objective latency PATH
        # on a live fixture, not a perf claim (tiny episodes on a shared
        # CPU box are wall-clock noisy; the perf claim lives in the
        # archived perf/serving_r08 run)
        [gate, "--run", os.path.join(out, "ab_reports_p99.json"),
         "--ab-methods", "chunked:token", "--ab-objective", "latency",
         "--tolerance", "1.0"],
    ):
        res = subprocess.run([sys.executable] + args, env=env,
                             stdout=subprocess.PIPE,
                             stderr=subprocess.STDOUT, text=True,
                             timeout=60)
        assert res.returncode == 0, (args[2:], res.stdout[-1500:])


# ---------------------------------------------------------------------------
# the end-to-end gate
# ---------------------------------------------------------------------------


@pytest.mark.timeout(560, method="signal")
def test_chaos_check_serve_storm(tmp_path):
    """scripts/chaos_check.py --serve: the fault-tolerant serving fleet
    gate (ISSUE-11 acceptance). A 2-replica supervised fleet absorbs an
    overload burst (explicit 429-style shedding + decorrelated-jitter
    client retries), a SIGKILL mid-traffic (in-flight requests
    re-dispatched — zero accepted-then-lost), a checksum-corrupted
    response, a rolling weight swap through the drain/backfill protocol
    with the fleet continuously serving, and a capacity scale-up to 3 —
    all machine-checked, ending in `bench_gate.py --slo` holding a
    throughput floor AND a p99-latency ceiling across the storm."""
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = os.path.join(repo, "scripts", "chaos_check.py")
    env = dict(os.environ)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(
        [sys.executable, script, "--serve", "--workdir", str(tmp_path)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True, timeout=520,
    )
    assert proc.returncode == 0, proc.stdout[-3000:]
    assert "CHAOS CHECK PASSED" in proc.stdout, proc.stdout[-3000:]
