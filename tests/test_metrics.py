"""JSONL metrics logging (utils/metrics.py) and its CLI integration."""

import json

import jax.numpy as jnp
import numpy as np

from dear_pytorch_tpu.utils import MetricsLogger, read_metrics


def test_logger_roundtrip(tmp_path):
    p = str(tmp_path / "m.jsonl")
    with MetricsLogger(p) as ml:
        ml.log(step=1, loss=jnp.float32(0.5), acc=0.9, tag="warmup")
        ml.log(step=2, loss=np.float64(0.25), vec=np.arange(3.0))
    recs = read_metrics(p)
    assert [r["step"] for r in recs] == [1, 2]
    assert recs[0]["loss"] == 0.5 and recs[0]["tag"] == "warmup"
    assert recs[1]["vec"] == [0.0, 1.0, 2.0]
    assert all("time" in r for r in recs)


def test_logger_nonfinite_and_append(tmp_path):
    p = str(tmp_path / "m.jsonl")
    with MetricsLogger(p) as ml:
        ml.log(step=1, loss=float("nan"))
    with MetricsLogger(p, append=True) as ml:
        ml.log(step=2, loss=1.0)
    recs = read_metrics(p)
    assert len(recs) == 2  # nan did not break JSON parsing
    assert recs[0]["loss"] == "nan"


def test_logger_nonfinite_in_arrays_stays_strict_json(tmp_path):
    p = str(tmp_path / "m.jsonl")
    with MetricsLogger(p) as ml:
        ml.log(hist=np.array([1.0, float("nan"), float("inf")]))
    line = open(p).read().strip()
    json.loads(line)  # strict: no bare NaN/Infinity tokens
    assert '"nan"' in line and '"inf"' in line


def test_read_skips_torn_tail(tmp_path):
    p = tmp_path / "m.jsonl"
    p.write_text('{"time": 0.1, "step": 1, "loss": 2.0}\n{"time": 0.2, "st')
    recs = read_metrics(str(p))
    assert len(recs) == 1 and recs[0]["loss"] == 2.0


def test_cli_metrics_file(mesh, tmp_path):
    from dear_pytorch_tpu.benchmarks import imagenet

    p = str(tmp_path / "cli.jsonl")
    imagenet.main([
        "--model", "mnistnet", "--batch-size", "4",
        "--num-warmup-batches", "1", "--num-batches-per-iter", "1",
        "--num-iters", "2", "--metrics-file", p,
    ])
    recs = read_metrics(p)
    iters = [r for r in recs if "iter" in r]
    summaries = [r for r in recs if r.get("summary")]
    assert len(iters) == 2
    assert all(r["img_per_sec_per_device"] > 0 for r in iters)
    assert len(summaries) == 1 and summaries[0]["world"] == 8
