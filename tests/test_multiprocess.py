"""Launch a real 2-process CPU cluster (jax.distributed over localhost) and
run tests/mp_worker.py in every rank — the CI-able replacement for the
reference's mpirun-only multi-node checks (common/comm_core/tests/
test_comm.py, runnable only on a GPU cluster). Covers the multi-process
branches of init/barrier/broadcast_parameters/allreduce and a cross-process
dear train step."""

import os
import socket
import subprocess
import sys

import pytest

NPROCS = 2


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.timeout(300)
def test_two_process_cluster():
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    worker = os.path.join(repo, "tests", "mp_worker.py")
    port = _free_port()
    procs = []
    for pid in range(NPROCS):
        env = dict(os.environ)
        env.pop("DEAR_DISABLE_DISTRIBUTED", None)
        env["JAX_PLATFORMS"] = "cpu"
        env["JAX_COORDINATOR_ADDRESS"] = f"127.0.0.1:{port}"
        env["JAX_NUM_PROCESSES"] = str(NPROCS)
        env["JAX_PROCESS_ID"] = str(pid)
        env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
        procs.append(
            subprocess.Popen(
                [sys.executable, worker], env=env,
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            )
        )
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=240)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append(out)
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {pid} failed:\n{out[-3000:]}"
        assert f"MP_WORKER_OK rank={pid}/{NPROCS}" in out, out[-3000:]
