"""Launch real multi-process CPU clusters (jax.distributed over localhost)
and run tests/mp_worker.py in every rank — the CI-able replacement for the
reference's mpirun-only multi-node checks (common/comm_core/tests/
test_comm.py, runnable only on a GPU cluster). Covers the multi-process
branches of init/barrier/broadcast_parameters/broadcast_optimizer_state/
allreduce and a cross-process dear train step.

Worlds covered (process_count x local_device_count):
  - 2 x 1: the minimal real cluster, launched directly.
  - 4 x 1: >2 processes (ring topologies stop being pairwise), launched
    through launch/cpu_cluster.sh so the launcher contract itself is
    exercised (reference equivalent: the 16-host launch surface,
    pytorch-ddp/launch_torch.sh:24-25).
  - 8 x 1: the emulated ceiling for process count, through the launcher
    (reference's validated scale was 64 ranks over 16 hosts,
    configs/cluster64 — 8 localhost ranks is the max multi-host
    confidence obtainable without a pod).
  - 2 x 2: multiple ADDRESSABLE devices per process — the TPU-pod shape
    (one process per host, several chips each); collectives cross both the
    intra-process and inter-process boundary in one mesh.
  - 2 x 4: the 2-D process x device world — the dp x sp mesh's sp axis
    pairs devices from DIFFERENT processes (ring ppermute crosses the
    host boundary) while dp spans each host's remaining devices; plus
    the dear and fsdp data-parallel steps over all 8 devices.

Each rank runs the full worker ladder: bootstrap/barrier,
broadcast_parameters + broadcast_optimizer_state, host allreduce, a dear
train step, an fsdp train step, sharded staging, and (direct worlds) the
cross-process ring-attention sp step.

Hang safety: belt and braces — every subprocess wait carries an explicit
deadline that kills the whole process group on expiry, AND the vendored
--timeout plugin (root conftest.py) arms a per-test alarm as the
outer backstop.
"""

import os
import socket
import subprocess
import sys

import pytest

DEADLINE = 240  # seconds per cluster run (scaled up for bigger worlds)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _base_env(repo: str) -> dict:
    env = dict(os.environ)
    env.pop("DEAR_DISABLE_DISTRIBUTED", None)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    return env


def _deadline(nprocs: int, local_devices: int) -> int:
    """Bigger worlds compile more programs on shared host cores."""
    return DEADLINE + 45 * nprocs * max(local_devices, 1)


def _run_direct(repo: str, worker: str, nprocs: int, local_devices: int,
                extra_env: dict | None = None, expect: str = "MP_WORKER_OK"):
    """Spawn one subprocess per rank with the launcher env contract."""
    port = _free_port()
    deadline = _deadline(nprocs, local_devices)
    procs = []
    for pid in range(nprocs):
        env = _base_env(repo)
        env["JAX_PLATFORMS"] = "cpu"
        env["JAX_COORDINATOR_ADDRESS"] = f"127.0.0.1:{port}"
        env["JAX_NUM_PROCESSES"] = str(nprocs)
        env["JAX_PROCESS_ID"] = str(pid)
        if local_devices > 1:
            env["DEAR_NUM_CPU_DEVICES"] = str(local_devices)
        if extra_env:
            env.update(extra_env)
        procs.append(
            subprocess.Popen(
                [sys.executable, worker], env=env,
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            )
        )
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=deadline)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append(out)
    expects = (expect,) if isinstance(expect, str) else tuple(expect)
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {pid} failed:\n{out[-3000:]}"
        for exp in expects:
            assert f"{exp} rank={pid}/{nprocs}" in out, out[-3000:]


def _run_via_launcher(repo: str, worker: str, nprocs: int):
    """Run the same worker through launch/cpu_cluster.sh (ranks share one
    output stream), so the launcher's env contract is itself under test.
    The launcher runs in its own session so a deadline kill takes the whole
    process GROUP — killing only the shell would leave the rank processes
    holding the coordinator port."""
    import signal

    script = os.path.join(repo, "launch", "cpu_cluster.sh")
    assert os.access(script, os.X_OK), f"{script} must be executable"
    deadline = _deadline(nprocs, 1)
    env = _base_env(repo)
    # the direct worlds already exercise the cross-process sp leg; skip its
    # per-rank compiles here so the launcher world stays fast
    env["DEAR_MP_SP"] = "0"
    proc = subprocess.Popen(
        [script, str(nprocs), "--", sys.executable, worker],
        env=env, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True, start_new_session=True,
    )
    try:
        out, _ = proc.communicate(timeout=deadline)
    except subprocess.TimeoutExpired as e:
        os.killpg(proc.pid, signal.SIGKILL)
        out, _ = proc.communicate()
        raise AssertionError(
            f"cpu_cluster.sh wedged past {deadline}s:\n"
            f"{(e.stdout or out or '')[-3000:]}"
        ) from e
    assert proc.returncode == 0, out[-3000:]
    for pid in range(nprocs):
        assert f"MP_WORKER_OK rank={pid}/{nprocs}" in out, out[-3000:]


@pytest.mark.parametrize(
    "nprocs,local_devices,via_launcher",
    [
        pytest.param(2, 1, False, id="2procs"),
        pytest.param(4, 1, True, id="4procs-cpu_cluster.sh"),
        pytest.param(8, 1, True, id="8procs-cpu_cluster.sh"),
        pytest.param(2, 2, False, id="2procs-x-2localdev"),
        pytest.param(2, 4, False, id="2procs-x-4localdev-2d"),
    ],
)
@pytest.mark.timeout(900, method="signal")
def test_process_cluster(nprocs, local_devices, via_launcher):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    worker = os.path.join(repo, "tests", "mp_worker.py")
    if via_launcher:
        _run_via_launcher(repo, worker, nprocs)
    else:
        _run_direct(repo, worker, nprocs, local_devices)


@pytest.mark.timeout(600, method="signal")
def test_coordinated_recovery_cluster(tmp_path):
    """The coordinated-recovery ladder (mp_worker resilience mode) over a
    real 2-process cluster: a rank-LOCAL NaN / raised exception produces
    the SAME rollback on every rank; a newest checkpoint corrupted on ONE
    host restores the newest COMMONLY verified step on both processes
    with no crash; a silently diverging replica trips the desync sentinel
    and is rolled back into lockstep; a SIGTERM on one rank propagates
    into a cooperative emergency save on all ranks (ISSUE-3 acceptance).

    Unlike the worlds above, every cross-rank decision here is HOST-level
    (the coordination-service KV store) — no cross-process device
    collectives — so this runs wherever `jax.distributed` bootstraps,
    including CPU containers whose XLA backend cannot execute
    multiprocess computations."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    worker = os.path.join(repo, "tests", "mp_worker.py")
    _run_direct(
        repo, worker, 2, 1,
        extra_env={"DEAR_MP_MODE": "resilience",
                   "DEAR_MP_WORKDIR": str(tmp_path)},
        expect="MP_RESILIENCE_OK",
    )


@pytest.mark.timeout(600, method="signal")
def test_elastic_membership(tmp_path):
    """Elastic membership end to end (mp_worker elastic mode) over a REAL
    3-process host-level cluster driven by `launch/supervisor.py`: rank 2
    SIGKILLs itself mid-run; the survivors must two-phase-commit a smaller
    membership epoch, rescale the fusion plan to the reduced world
    (epoch-stamped), reshard the data pipeline, and consensus-restore to
    the newest step valid on every survivor; the supervisor relaunches
    the dead rank with the rejoin env contract and it must be readmitted
    at a later epoch barrier and finish IN LOCKSTEP with the survivors
    (ISSUE-5 acceptance). All coordination is `FileTransport` — no
    `jax.distributed` at all, so the coordination substrate survives rank
    death and the whole scenario runs where cross-process XLA CPU
    computations don't exist."""
    import signal

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    worker = os.path.join(repo, "tests", "mp_worker.py")
    supervisor = os.path.join(repo, "launch", "supervisor.py")
    env = _base_env(repo)
    env["JAX_PLATFORMS"] = "cpu"
    env["DEAR_DISABLE_DISTRIBUTED"] = "1"  # membership != jax.distributed
    env["DEAR_MP_MODE"] = "elastic"
    env["DEAR_MP_WORKDIR"] = str(tmp_path / "work")
    env["DEAR_MP_ELASTIC_KILL"] = "2:5"  # rank 2 dies before attempt 5
    # the deadline must cover a PEER's post-transition XLA recompile
    # (every epoch change rebuilds+recompiles the train step, 10-20s on a
    # loaded container) — a legitimate compile must not read as a death
    env["DEAR_CLUSTER_TIMEOUT_SECS"] = "40"
    env["DEAR_TELEMETRY"] = "1"
    env["DEAR_FLIGHT"] = "8"
    proc = subprocess.Popen(
        [sys.executable, supervisor, "--nprocs", "3",
         "--dir", str(tmp_path / "elastic"), "--deadline", "420",
         "--", sys.executable, worker],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True, start_new_session=True,
    )
    try:
        out, _ = proc.communicate(timeout=480)
    except subprocess.TimeoutExpired as e:
        os.killpg(proc.pid, signal.SIGKILL)
        out, _ = proc.communicate()
        raise AssertionError(
            f"elastic supervisor wedged:\n{(e.stdout or out or '')[-3000:]}"
        ) from e
    assert proc.returncode == 0, out[-5000:]
    for pid in range(3):
        assert f"MP_ELASTIC_OK rank={pid}/3 epoch=2" in out, out[-5000:]
    assert "MP_ELASTIC_REJOINED rank=2 epoch=2" in out, out[-5000:]
    # the supervisor saw the SIGKILL and relaunched exactly that rank,
    # BEFORE the relaunched process reported its admission
    assert "supervisor: rank 2 exited rc=-9" in out, out[-5000:]
    assert "supervisor: rank 2 RELAUNCHED (rejoin)" in out, out[-5000:]
    assert out.index("rank 2 exited rc=-9") < out.index(
        "MP_ELASTIC_REJOINED rank=2")


@pytest.mark.timeout(600, method="signal")
def test_run_health_cluster(tmp_path):
    """The continuous run-health ladder (mp_worker health mode) over a
    real 2-process cluster: with telemetry enabled and one rank
    artificially slowed mid-run, the digest exchange riding the guard's
    health-check cadence produces a rank-0 merged snapshot naming the
    straggler rank; the slow rank raises ``health.step_time_spike``; a
    watchdog-triggered dump carries the last-N flight-ring records (with
    the DEAR_* env redacted); and the prom/stream exporters were fed on
    the check cadence (ISSUE-4 acceptance). Host-level only, like the
    recovery ladder above — runs wherever `jax.distributed` bootstraps."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    worker = os.path.join(repo, "tests", "mp_worker.py")
    _run_direct(
        repo, worker, 2, 1,
        extra_env={"DEAR_MP_MODE": "health",
                   "DEAR_MP_WORKDIR": str(tmp_path),
                   "DEAR_TELEMETRY": "1",
                   "DEAR_FLIGHT": "16",
                   "DEAR_HEALTH_WARMUP": "2",
                   # container-noise margin: the worker's 0.5s slowdown
                   # against ~5ms steps is >10 sigma even with one noisy
                   # warmup interval; z=3 keeps detection robust
                   "DEAR_HEALTH_Z": "3",
                   # predicted skew is ~2x (slow rank p50 0.5s vs fleet
                   # median ~0.25s); 1.35 keeps the verdict stable when
                   # container contention inflates the fast rank too
                   "DEAR_STRAGGLER_SKEW": "1.35",
                   "DEAR_MP_FAKE_TOKEN": "hunter2-must-not-leak"},
        expect="MP_HEALTH_OK",
    )
