"""The scaling-efficiency sweep CLI (benchmarks/scaling.py)."""

import json


def test_scaling_sweep_runs(mesh, capsys, tmp_path):
    from dear_pytorch_tpu.benchmarks import scaling

    out_json = tmp_path / "scaling.json"
    out = scaling.main([
        "--model", "mnistnet", "--batch-size", "4",
        "--worlds", "1,2,4",
        "--num-warmup-batches", "1", "--num-batches-per-iter", "1",
        "--num-iters", "1",
        "--json", str(out_json),
    ])
    assert sorted(out["per_device_img_sec"]) == [1, 2, 4]
    assert out["efficiency"][1] == 1.0
    assert all(v > 0 for v in out["per_device_img_sec"].values())
    captured = capsys.readouterr().out
    # per-world scrape lines (the driver's format) + the summary line
    assert "Total img/sec on 1 CPU(s)" in captured
    assert "Total img/sec on 4 CPU(s)" in captured
    assert "Scaling efficiency (1->4 devices):" in captured
    assert json.loads(out_json.read_text())["model"] == "mnistnet"


def test_collectives_microbench_cli(mesh, capsys, tmp_path):
    from dear_pytorch_tpu.benchmarks import collectives as cb

    out_json = tmp_path / "coll.json"
    out = cb.main([
        "--collectives", "all_reduce,reduce_scatter",
        "--sizes-log2", "8:11:2", "--repeats", "2", "--warmup", "1",
        "--json", str(out_json),
    ])
    assert set(out["collectives"]) == {"all_reduce", "reduce_scatter"}
    ar = out["collectives"]["all_reduce"]
    assert ar["alpha_s"] >= 0 and len(ar["rows"]) == 2
    assert all(r["bw_gbs"] > 0 for r in ar["rows"])
    captured = capsys.readouterr().out
    assert "[all_reduce]" in captured and "busbw GB/s" in captured
    assert json.loads(out_json.read_text())["world"] == 8

    import pytest

    with pytest.raises(SystemExit, match="unknown collective"):
        cb.main(["--collectives", "bogus"])
    with pytest.raises(SystemExit, match="sizes-log2"):
        cb.main(["--sizes-log2", "abc"])


def test_scaling_rejects_bad_worlds(mesh):
    import pytest

    from dear_pytorch_tpu.benchmarks import scaling

    with pytest.raises(SystemExit, match="out of range"):
        scaling.main([
            "--model", "mnistnet", "--worlds", "64",
        ])
