"""The vendored --timeout plugin (root conftest.py): accepted syntax,
signal-method single-test failure, thread-method hard exit.

pytest-timeout itself cannot be installed here; these tests pin the
compatible surface so the suite can be run `python -m pytest
--timeout=1200` exactly as a reference-scale CI would (the reference's
own mpirun harness hangs forever on a wedged rank —
reference common/comm_core/test.sh:29 — which is the failure mode this
plugin exists to bound)."""

import os
import pathlib

import pytest

ROOT_CONFTEST = pathlib.Path(__file__).resolve().parent.parent / "conftest.py"


@pytest.fixture
def timeout_pytester(pytester):
    pytester.makeconftest(ROOT_CONFTEST.read_text())
    return pytester


def test_timeout_option_accepted(timeout_pytester):
    timeout_pytester.makepyfile("def test_ok():\n    assert True\n")
    result = timeout_pytester.runpytest_subprocess("--timeout=1200")
    result.assert_outcomes(passed=1)


def test_signal_method_fails_only_the_hung_test(timeout_pytester):
    timeout_pytester.makepyfile(
        """
        import time

        def test_hangs():
            time.sleep(30)

        def test_survives():
            assert True
        """
    )
    result = timeout_pytester.runpytest_subprocess("--timeout=1")
    result.assert_outcomes(failed=1, passed=1)
    result.stdout.fnmatch_lines(["*timeout: exceeded 1s*"])


def test_marker_overrides_cli(timeout_pytester):
    timeout_pytester.makepyfile(
        """
        import time
        import pytest

        @pytest.mark.timeout(5)
        def test_marked_slow_ok():
            time.sleep(1.2)
        """
    )
    result = timeout_pytester.runpytest_subprocess("--timeout=1")
    result.assert_outcomes(passed=1)


def test_thread_method_kills_the_process(timeout_pytester):
    timeout_pytester.makepyfile(
        """
        import time

        def test_hangs():
            time.sleep(30)
        """
    )
    result = timeout_pytester.runpytest_subprocess(
        "--timeout=1", "--timeout-method=thread"
    )
    assert result.ret == 7
