"""Multi-slice hierarchical training: the two-level DeAR schedule
(RS+AG over ICI + host-level DCN cross-slice exchange, `comm.dcn` +
`parallel.build_train_step(dcn=...)`), slice-granular elastic membership
(`resilience.membership` with ``ranks_per_slice``), the slice-targetable
DCN fault kinds, the multislice plan-space axes, and the nested-mesh
reshard/repack determinism the elastic transitions rely on.

The ISSUE-15 acceptance numerics live here (`test_hier_matches_flat_dear`
pins the hierarchical schedule against flat ``dear`` on the same
8-device world at dtype tolerance); the end-to-end acceptance storm is
`scripts/chaos_check.py --multislice`, driven in tier-1 by
``test_chaos_check_multislice_storm`` at the bottom.
"""

import json
import os
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dear_pytorch_tpu.comm.dcn import (
    DcnChunkReject, DcnExchanger, DcnPeerTimeout, DcnSelfEvict, _encode,
)
from dear_pytorch_tpu.ops import fusion as F
from dear_pytorch_tpu.ops.fused_sgd import fused_sgd
from dear_pytorch_tpu.parallel import dear as D
from dear_pytorch_tpu.resilience import cluster as CL
from dear_pytorch_tpu.resilience import membership as M
from dear_pytorch_tpu.resilience.inject import (
    Fault, FaultInjector, parse_faults,
)
from dear_pytorch_tpu.runtime import build as RB
from dear_pytorch_tpu.runtime import pipeline as P


def _mlp_params(key):
    k1, k2 = jax.random.split(key)
    return {"w1": jax.random.normal(k1, (12, 16)) * 0.1,
            "b1": jnp.zeros((16,)),
            "w2": jax.random.normal(k2, (16, 4)) * 0.1,
            "b2": jnp.zeros((4,))}


def _loss_fn(p, batch):
    h = jnp.tanh(batch["x"] @ p["w1"] + p["b1"])
    return jnp.mean(jnp.square(h @ p["w2"] + p["b2"]))


def _hier_pair(*, gather_dtype=None, comm_dtype=None, partition_mb=0.0001,
               threshold_mb=0.0002):
    """(flat ts, hier ts, exchanger): same optimizer/init on the same
    8-device world — flat 1x8 vs nested 2 slices x 4 ICI."""
    params = _mlp_params(jax.random.PRNGKey(0))
    devs = np.asarray(jax.devices())
    flat = D.build_train_step(
        _loss_fn, params, mesh=jax.sharding.Mesh(devs, ("dp",)),
        axis_name="dp", threshold_mb=threshold_mb, donate=False,
        optimizer=fused_sgd(lr=0.05, momentum=0.9),
        gather_dtype=gather_dtype, comm_dtype=comm_dtype)
    dcn = DcnExchanger(CL.LocalTransport(), local_slices=(0, 1),
                       slices=(0, 1), partition_mb=partition_mb)
    hier = D.build_train_step(
        _loss_fn, params,
        mesh=jax.sharding.Mesh(devs.reshape(2, 4), ("slice", "ici")),
        axis_name="ici", threshold_mb=threshold_mb, donate=False,
        optimizer=fused_sgd(lr=0.05, momentum=0.9),
        gather_dtype=gather_dtype, comm_dtype=comm_dtype,
        dcn=dcn, dcn_slice_axis="slice", partition_mb=partition_mb)
    return params, flat, hier, dcn


# -- the acceptance numerics: hierarchical == flat dear -----------------------


def test_hier_matches_flat_dear():
    """ISSUE-15 acceptance: per-bucket RS+AG over the intra-slice axis
    plus the host DCN averaging reproduces flat `dear` on the same fixed
    8-device world at dtype tolerance, multi-step, parameters included.
    """
    params, flat, hier, dcn = _hier_pair()
    assert hier.plan.world == 4 and flat.plan.world == 8  # ZeRO degrees
    sf, sh = flat.init(params), hier.init(params)
    batch = {"x": jax.random.normal(jax.random.PRNGKey(7), (16, 12))}
    for i in range(5):
        sf, mf = flat.step(sf, batch)
        sh, mh = hier.step(sh, batch)
        assert abs(float(mf["loss"]) - float(mh["loss"])) < 1e-5, i
    pf = jax.device_get(flat.gather_params(sf))
    ph = jax.device_get(hier.gather_params(sh))
    for k in pf:
        np.testing.assert_allclose(pf[k], ph[k], atol=2e-6, rtol=2e-6)
    assert dcn.exchanges == 5


def test_hier_matches_flat_dear_bf16_gather():
    """The gather-dtype wire cast composes with the hierarchical split
    the same way it does with flat dear (bf16 tolerance)."""
    params, flat, hier, _ = _hier_pair(gather_dtype=jnp.bfloat16)
    sf, sh = flat.init(params), hier.init(params)
    batch = {"x": jax.random.normal(jax.random.PRNGKey(3), (16, 12))}
    for _ in range(3):
        sf, mf = flat.step(sf, batch)
        sh, mh = hier.step(sh, batch)
        assert abs(float(mf["loss"]) - float(mh["loss"])) < 2e-2
    pf = jax.device_get(flat.gather_params(sf))
    ph = jax.device_get(hier.gather_params(sh))
    for k in pf:
        np.testing.assert_allclose(pf[k], ph[k], atol=5e-2, rtol=5e-2)


def test_hier_build_guards():
    """Every multislice-illegal combination is rejected loudly at
    plan-build (PR-8 guard style), and multi_step refuses to scan the
    host leg."""
    params = _mlp_params(jax.random.PRNGKey(0))
    devs = np.asarray(jax.devices())
    mesh = jax.sharding.Mesh(devs.reshape(2, 4), ("slice", "ici"))
    dcn = DcnExchanger(CL.LocalTransport(), local_slices=(0, 1),
                       slices=(0, 1))
    kw = dict(mesh=mesh, axis_name="ici", dcn=dcn,
              dcn_slice_axis="slice", threshold_mb=0.0002, donate=False)

    with pytest.raises(ValueError, match="DCN boundary"):
        D.build_train_step(_loss_fn, params, mode="dear-fused", **kw)
    with pytest.raises(ValueError, match="compression"):
        D.build_train_step(_loss_fn, params, compressor="eftopk",
                           density=0.1, **kw)
    with pytest.raises(ValueError, match="clip_norm"):
        D.build_train_step(_loss_fn, params, clip_norm=1.0, **kw)
    with pytest.raises(ValueError, match="model_state"):
        D.build_train_step(_loss_fn, params,
                           model_state_template={"n": jnp.zeros(())}, **kw)
    with pytest.raises(ValueError, match="has_aux"):
        D.build_train_step(_loss_fn, params, has_aux=True, **kw)
    with pytest.raises(ValueError, match="hierarchical"):
        D.build_train_step(_loss_fn, params, mode="allreduce", **kw)
    # the mesh must carry the slice axis, sized to the LOCAL slices
    with pytest.raises(ValueError, match="nested mesh"):
        D.build_train_step(
            _loss_fn, params, dcn=dcn, dcn_slice_axis="slice",
            axis_name="dp", threshold_mb=0.0002, donate=False,
            mesh=jax.sharding.Mesh(devs, ("dp",)))
    ts = D.build_train_step(_loss_fn, params, **kw)
    with pytest.raises(ValueError, match="multi_step"):
        ts.multi_step(4)


# -- the exchanger ------------------------------------------------------------


def _run2(fa, fb, join_s=30):
    out, err = [None, None], [None, None]

    def w(i, f):
        try:
            out[i] = f()
        except BaseException as exc:  # noqa: BLE001 - asserted below
            err[i] = exc
    ts = [threading.Thread(target=w, args=(i, f))
          for i, f in enumerate((fa, fb))]
    for t in ts:
        t.start()
    for t in ts:
        t.join(join_s)
    return out, err


def test_dcn_remote_roundtrip_bitwise_identical():
    """Two single-slice hosts exchange over one shared transport: both
    compute the same mean, BITWISE identical (sorted-slice accumulation
    — different local/remote splits must not change float order), with
    per-fetch timing samples recorded for the link fit."""
    tr = CL.LocalTransport()
    ex0 = DcnExchanger(tr, local_slices=(0,), slices=(0, 1),
                       partition_mb=0.00002, timeout_s=10.0)
    ex1 = DcnExchanger(tr, local_slices=(1,), slices=(0, 1),
                       partition_mb=0.00002, timeout_s=10.0)
    rng = np.random.default_rng(0)
    b0 = [rng.normal(size=24).astype(np.float32),
          rng.normal(size=8).astype(np.float32)]
    b1 = [rng.normal(size=24).astype(np.float32),
          rng.normal(size=8).astype(np.float32)]
    out, err = _run2(
        lambda: ex0.exchange(0, {0: b0}, {0: 1.25}),
        lambda: ex1.exchange(0, {1: b1}, {1: 0.75}))
    assert not any(err), err
    (m0, s0), (m1, s1) = out
    for g in range(2):
        np.testing.assert_array_equal(m0[g], m1[g])
        np.testing.assert_allclose(m0[g], (b0[g] + b1[g]) / 2.0,
                                   rtol=1e-6)
    assert s0 == s1 == 1.0
    assert ex0.samples() and ex1.samples()
    # several chunks per bucket at this partition (24 f32 = 96B > 84B)
    assert all(b >= 0 for b, t in ex0.samples())


def test_dcn_renorm_and_timeout(tmp_path):
    """A renormalized (degraded) exchanger averages over the live set
    only with NO peer traffic; a dead remote slice raises DcnPeerTimeout
    within the deadline."""
    tr = CL.FileTransport(str(tmp_path))
    ex0 = DcnExchanger(tr, local_slices=(0,), slices=(0, 1),
                       timeout_s=0.3)
    ex0.set_slices((0,), epoch=1)
    buf = [np.ones(8, np.float32) * 3.0]
    means, sm = ex0.exchange(0, {0: buf}, {0: 2.0})
    np.testing.assert_allclose(means[0], buf[0])
    assert sm == 2.0
    # back at full membership with nobody home on slice 1: timeout
    ex0.set_slices((0, 1), epoch=2)
    t0 = time.monotonic()
    with pytest.raises(DcnPeerTimeout):
        ex0.exchange(1, {0: buf}, {0: 2.0})
    assert time.monotonic() - t0 < 5.0
    with pytest.raises(ValueError, match="local slice"):
        ex0.set_slices((1,), epoch=3)


def test_dcn_drop_and_slow_faults():
    """dcn_drop suppresses one outbound publish (the peer's fetch times
    out; the replay publishes); dcn_slow arms a persistent latency."""
    tr = CL.LocalTransport()
    inj = FaultInjector(parse_faults("dcn_drop@1:s0,dcn_slow@2:0.05:s0"),
                        own_rank=0, own_slice=0)
    ex0 = DcnExchanger(tr, local_slices=(0,), slices=(0, 1),
                       timeout_s=0.4, injector=inj)
    ex1 = DcnExchanger(tr, local_slices=(1,), slices=(0, 1),
                       timeout_s=0.4)
    b = [np.ones(4, np.float32)]
    out, err = _run2(
        lambda: ex0.exchange(0, {0: b}),   # publish dropped
        lambda: ex1.exchange(0, {1: b}))
    # slice 0 still FETCHED slice 1's publish fine; slice 1 timed out
    assert err[0] is None or isinstance(err[0], DcnPeerTimeout)
    assert isinstance(err[1], DcnPeerTimeout)
    # the replay (same step) re-publishes: both sides converge
    t0 = time.monotonic()
    out, err = _run2(
        lambda: ex0.exchange(0, {0: b}),
        lambda: ex1.exchange(0, {1: b}))
    assert not any(err), err
    assert time.monotonic() - t0 >= 0.05   # the armed straggler latency
    assert inj.dcn_slow_s == 0.05


def test_slice_fault_grammar():
    fs = parse_faults("dcn_slow@3:0.5:s1,nan@6:r2")
    assert fs[0].slice_id == 1 and fs[0].rank is None
    assert fs[1].rank == 2 and fs[1].slice_id is None
    with pytest.raises(ValueError, match="rank OR a slice"):
        Fault(kind="nan", step=1, rank=0, slice_id=0)
    with pytest.raises(ValueError, match="sSLICE"):
        parse_faults("nan@6:sx")
    # own_slice resolves from the elastic env contract
    inj = FaultInjector(parse_faults("exc@1:s1"))
    os.environ["DEAR_ELASTIC_RANK"] = "5"
    os.environ["DEAR_ELASTIC_RANKS_PER_SLICE"] = "4"
    try:
        assert inj.own_slice == 1
    finally:
        del os.environ["DEAR_ELASTIC_RANK"]
        del os.environ["DEAR_ELASTIC_RANKS_PER_SLICE"]


# -- slice-granular membership ------------------------------------------------


def _make_members(n, *, rps, timeout_s=1.0):
    tr = CL.LocalTransport(n)
    return tr, [
        M.ElasticCluster(rank=r, members=range(n), transport=tr,
                         timeout_s=timeout_s, ranks_per_slice=rps)
        for r in range(n)
    ]


def _threads(fns, join_s=60):
    res, errs = [None] * len(fns), [None] * len(fns)

    def w(i):
        try:
            res[i] = fns[i]()
        except BaseException as exc:  # noqa: BLE001
            errs[i] = exc
    ts = [threading.Thread(target=w, args=(i,)) for i in range(len(fns))]
    for t in ts:
        t.start()
    for t in ts:
        t.join(join_s)
    return res, errs


def test_whole_slice_loss_commits_one_epoch():
    """Both ranks of slice 1 vanish: the survivors commit EXACTLY one
    membership epoch removing the whole slice, with a slice-shaped
    signed delta in the durable decision record."""
    tr, ms = _make_members(4, rps=2, timeout_s=0.5)
    out, errs = _threads([
        (lambda c=ms[0]: c.health_check(True, step=3)),
        (lambda c=ms[1]: c.health_check(True, step=3)),
    ])
    assert not any(errs), errs
    for v in out:
        assert v.reconfigured and v.epoch == 1
        assert v.members == (0, 1) and v.lost == (2, 3)
    assert ms[0].slices == (0,)
    rec = json.loads(tr.get(f"{ms[0]._ns}/decided/e1", 0.1))
    assert rec["delta"]["removed"] == [2, 3]
    assert rec["delta"]["slices"] == {"added": [], "removed": [1]}
    # exactly one epoch: no e2 was ever decided
    with pytest.raises(CL.PeerTimeout):
        tr.get(f"{ms[0]._ns}/decided/e2", 0.05)


def test_partial_slice_loss_widens_to_the_slice():
    """One rank of slice 1 dies; its live slice-mate is widened into the
    dead set (the slice's ICI mesh is broken) and self-evicts for
    relaunch+rejoin, while the surviving slice commits one epoch."""
    tr, ms = _make_members(4, rps=2, timeout_s=0.5)
    out, errs = _threads([
        (lambda c=ms[0]: c.health_check(True, step=3)),
        (lambda c=ms[1]: c.health_check(True, step=3)),
        (lambda c=ms[2]: c.health_check(True, step=3)),  # slice-mate of 3
    ])
    assert errs[0] is None and errs[1] is None
    assert isinstance(errs[2], M.EvictedError)
    for v in out[:2]:
        assert v.epoch == 1 and v.members == (0, 1) and v.lost == (2, 3)


def test_slice_gated_admission_defers_partial_slice():
    """A relaunched slice readmits only when COMPLETE: a lone rank's
    request is deferred (left in the store), and the full slice lands as
    ONE admission epoch at the barrier."""
    tr, ms = _make_members(4, rps=2, timeout_s=1.0)
    _threads([
        (lambda c=ms[0]: c.health_check(True, step=1)),
        (lambda c=ms[1]: c.health_check(True, step=1)),
    ])
    assert ms[0].epoch == 1 and ms[0].members == (0, 1)
    # rank 2 alone requests: deferred, request NOT consumed
    tr.set(f"{ms[0]._ns}/rejoin/req/2",
           json.dumps({"rank": 2, "last_epoch": 0, "nonce": "aa"}))
    out, errs = _threads([
        (lambda c=ms[0]: c.health_check(True, step=2)),
        (lambda c=ms[1]: c.health_check(True, step=2)),
    ])
    assert not any(errs), errs
    assert all(not v.membership_changed and v.admitted == () for v in out)
    assert ms[0].epoch == 1
    assert tr.get(f"{ms[0]._ns}/rejoin/req/2", 0.05)  # still pending
    # rank 3 joins the request set: the whole slice admits as ONE epoch
    tr.set(f"{ms[0]._ns}/rejoin/req/3",
           json.dumps({"rank": 3, "last_epoch": 0, "nonce": "bb"}))
    rejoiners = [
        M.ElasticCluster(rank=r, members=range(4), transport=tr,
                         timeout_s=1.0, ranks_per_slice=2)
        for r in (2, 3)
    ]

    def _rejoin(c, nonce):
        ack = json.loads(tr.get(f"{c._ns}/rejoin/ack/{c.rank}/{nonce}",
                                10.0))
        c._commit(int(ack["epoch"]), ack["members"])
        c.exchange("admit.barrier", "{}")
        return c.view()

    out, errs = _threads([
        (lambda c=ms[0]: c.health_check(True, step=3)),
        (lambda c=ms[1]: c.health_check(True, step=3)),
        (lambda c=rejoiners[0]: _rejoin(c, "aa")),
        (lambda c=rejoiners[1]: _rejoin(c, "bb")),
    ])
    assert not any(errs), errs
    assert out[0].admitted == (2, 3) and out[0].epoch == 2
    rec = json.loads(tr.get(f"{ms[0]._ns}/decided/e2", 0.1))
    assert rec["delta"]["slices"] == {"added": [1], "removed": []}
    assert out[2].slices == (0, 1) and out[2].slice_id == 1


def test_view_slice_data_shard():
    """Slice-granular views expose the SLICE as the data-parallel slot:
    a slice's ranks are replicas of one shard."""
    _, ms = _make_members(4, rps=2)
    v = ms[3].view()
    assert v.slices == (0, 1) and v.slice_id == 1
    assert v.data_shard == 1 and v.data_world == 2
    assert v.index == 3 and v.world == 4   # rank-granular fields intact
    # rank-granular views keep member-position sharding
    rv = M.MembershipView(epoch=0, members=(0, 1), rank=1, index=1,
                          world=2)
    assert rv.data_shard == 1 and rv.data_world == 2


def test_slice_drain_closure():
    """A spot SIGTERM on ONE rank of a slice drains the whole slice: the
    announcing rank self-drains cleanly, its slice-mate exits for
    relaunch (EvictedError), the other slice commits one planned-shrink
    epoch."""
    tr, ms = _make_members(4, rps=2, timeout_s=1.0)
    out, errs = _threads([
        (lambda c=ms[0]: c.health_check(True, step=5)),
        (lambda c=ms[1]: c.health_check(True, step=5)),
        (lambda c=ms[2]: c.health_check(True, step=5)),  # slice-mate
        (lambda c=ms[3]: c.health_check(True, step=5, draining=True)),
    ])
    assert errs[0] is None and errs[1] is None and errs[3] is None
    assert isinstance(errs[2], M.EvictedError)
    assert out[3].self_draining and out[3].drained == (2, 3)
    for v in out[:2]:
        assert v.reconfigured and v.members == (0, 1) and v.epoch == 1


# -- satellite: nested-mesh reshard/repack determinism ------------------------


def test_pipeline_reshard_slice_delta_determinism():
    """`reshard()` across a SLICE-COUNT change (2 -> 1 -> 2 data shards,
    arriving as single membership events, never N rank events) is a pure
    function of (seed, epoch, shard, world): two consumers with
    DIFFERENT histories that derive the same slice assignment land on
    bitwise-identical streams — what lets every surviving (or
    rejoining) rank of a slice reshard independently, no coordination.
    """
    spec = P.SyntheticSpec((
        P.Field("x", (8, 4), RB.KIND_NORMAL_F32, 0.0, 1.0),
    ))

    def batches(pipe, n=3):
        return [np.asarray(pipe.next()["x"]) for _ in range(n)]

    # survivor A consumed 3 batches pre-shrink, survivor B consumed 5 —
    # after the SAME slice-delta reshard their streams must agree
    a = P.NumpyPipeline(spec, seed=9, shard=0, num_shards=2)
    b = P.NumpyPipeline(spec, seed=9, shard=0, num_shards=2)
    batches(a, 3)
    batches(b, 5)
    a.reshard(0, 1, epoch=1)             # slice loss: one event, 2 -> 1
    b.reshard(0, 1, epoch=1)
    for xa, xb in zip(batches(a), batches(b)):
        np.testing.assert_array_equal(xa, xb)
    # the rejoining slice's consumer (fresh process, zero history)
    # derives the identical full-membership stream as the survivor
    a.reshard(1, 2, epoch=2)             # slice rejoin: 1 -> 2, slot 1
    c = P.NumpyPipeline(spec, seed=9, shard=1, num_shards=2)
    c.reshard(1, 2, epoch=2)
    for xa, xc in zip(batches(a), batches(c)):
        np.testing.assert_array_equal(xa, xc)
    # and a different epoch is a DIFFERENT stream (no stale replay)
    d = P.NumpyPipeline(spec, seed=9, shard=1, num_shards=2)
    d.reshard(1, 2, epoch=3)
    assert not np.array_equal(batches(a, 1)[0], batches(d, 1)[0])


def test_repack_comp_state_across_slice_delta_world_change():
    """`_repack_comp_state` with a world change arriving as ONE
    slice-shaped delta (8 -> 4: half the world in one event) keeps the
    error-feedback mass invariant: sum(rows)/world — the residuals'
    contribution to the mean gradient — is exactly preserved."""
    from dear_pytorch_tpu.tuning.autotune import _repack_comp_state

    tmpl = {"a": np.zeros((40,), np.float32),
            "b": np.zeros((24,), np.float32)}
    old_plan = F.make_plan(tmpl, 8, threshold_mb=0.0001)
    new_plan = F.rescale_plan(old_plan, 4, epoch=1)
    rng = np.random.default_rng(5)
    old = tuple(
        jnp.asarray(rng.normal(size=(8, b.padded_size)).astype(np.float32))
        for b in old_plan.buckets)
    fresh = tuple(
        jnp.zeros((4, b.padded_size), jnp.float32)
        for b in new_plan.buckets)
    out = _repack_comp_state(old, fresh, old_plan, new_plan)
    # mass per PARAMETER element, not per padded slot (padding moved)
    def mass(entries, plan, world):
        leaves = {}
        for bi, e in enumerate(entries):
            arr = np.asarray(e)
            total = arr.sum(axis=0) / world
            for lid, piece in F.unpack_bucket(
                    jnp.asarray(total), plan, bi).items():
                leaves[lid] = np.asarray(piece)
        return leaves

    m_old = mass(old, old_plan, 8)
    m_new = mass(out, new_plan, 4)
    for lid in m_old:
        np.testing.assert_allclose(m_new[lid], m_old[lid], atol=1e-6)


# -- satellite: the multislice plan space -------------------------------------


def test_planspace_multislice_axes_and_guards():
    from dear_pytorch_tpu.tuning.planspace import (
        CostModel, PlanConfig, PlanSpace,
    )

    sp = PlanSpace(num_slices=2, partition_mbs=(None, 1.0, 4.0))
    names = [a.name for a in sp.axes()]
    assert "partition_mb" in names
    # illegal combos rejected loudly, PR-8 guard style
    assert "DCN" in (sp.feasible(PlanConfig(mode="dear-fused")) or "")
    assert sp.feasible(PlanConfig(compressor="qint8")) is not None
    assert PlanSpace().feasible(PlanConfig(partition_mb=2.0)) is not None
    with pytest.raises(ValueError, match="multi-slice"):
        PlanSpace(partition_mbs=(1.0,))
    cfgs = sp.configs(8.0)
    assert {c.partition_mb for c in cfgs} == {None, 1.0, 4.0}
    assert all(c.mode == "dear" and c.compressor is None for c in cfgs)
    # and the BUILD guard agrees with the space's feasibility rule
    params = _mlp_params(jax.random.PRNGKey(0))
    devs = np.asarray(jax.devices())
    dcn = DcnExchanger(CL.LocalTransport(), local_slices=(0, 1),
                       slices=(0, 1))
    with pytest.raises(ValueError, match="DCN"):
        D.build_train_step(
            _loss_fn, params, mode="dear-fused", dcn=dcn,
            dcn_slice_axis="slice", axis_name="ici", donate=False,
            mesh=jax.sharding.Mesh(devs.reshape(2, 4), ("slice", "ici")))

    # link-aware pricing: a slower-alpha DCN fit separates partition
    # arms (more chunks -> more per-message cost), and the same config
    # under one blind fit would not
    tmpl = {"w": np.zeros((4096,), np.float32)}
    cm = CostModel(lambda thr: F.make_plan(tmpl, 2, threshold_mb=thr),
                   1e-6, 1e-9, num_slices=2,
                   dcn_alpha=1e-3, dcn_beta=1e-8)
    fine = cm.comm(PlanConfig(threshold_mb=8.0, partition_mb=0.001))
    coarse = cm.comm(PlanConfig(threshold_mb=8.0, partition_mb=None))
    assert fine > coarse


def test_accounting_dcn_leg_rows():
    from dear_pytorch_tpu.observability import counters as CTR
    from dear_pytorch_tpu.observability.overlap import predict_leg_times

    tmpl = {"w": np.zeros((1024,), np.float32)}
    plan = F.make_plan(tmpl, 4, threshold_mb=0.001)
    acct = CTR.plan_comm_accounting(plan, num_slices=3,
                                    dcn_partition_mb=0.001)
    dcn_rows = [r for r in acct.rows if r.leg == "dcn"]
    assert len(dcn_rows) == plan.num_buckets
    for r in dcn_rows:
        chunks = len(F.chunk_bounds(r.padded_elements, 4, 0.001))
        assert r.messages == chunks * 2          # (num_slices - 1)
        assert r.wire_bytes == r.payload_bytes * 3
    # link-aware pricing prices dcn rows with the dcn fit
    t_ici = predict_leg_times(acct, 1e-6, 1e-9)
    t_dcn = predict_leg_times(acct, 1e-6, 1e-9, dcn_alpha=1e-3,
                              dcn_beta=1e-7)
    for row, a, b in zip(acct.rows, t_ici, t_dcn):
        assert (b > a) == (row.leg == "dcn")


def test_chunk_bounds_contract():
    assert F.chunk_bounds(10, 4, None) == [(0, 10)]
    assert F.chunk_bounds(0, 4, 1.0) == []
    per = int(0.001 * 2**20) // 4
    bounds = F.chunk_bounds(per * 2 + 3, 4, 0.001)
    assert bounds[0] == (0, per) and bounds[-1][1] == per * 2 + 3
    assert all(hi - lo <= per for lo, hi in bounds)


# -- the supervisor's slice contract ------------------------------------------


def test_supervisor_slice_aligned_scale_up(tmp_path, monkeypatch):
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "sup", os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "launch", "supervisor.py"))
    sup_mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(sup_mod)
    with pytest.raises(ValueError, match="whole number of slices"):
        sup_mod.ElasticSupervisor(6, ["true"], elastic_dir=str(tmp_path),
                                  ranks_per_slice=4)
    sup = sup_mod.ElasticSupervisor(8, ["true"],
                                    elastic_dir=str(tmp_path),
                                    ranks_per_slice=4)
    spawned = []
    monkeypatch.setattr(sup, "_spawn",
                        lambda rank, rejoin: spawned.append(rank)
                        or sup._ever_ranks.add(rank))
    # fresh slice ids mint on slice boundaries (8..11), never mid-group
    assert sup.scale_up(2) == [8, 9]
    assert sup.scale_up(1) == [10]


def test_step_trace_spans_shrink_rejoin_without_id_collisions():
    """Fleet step-trace identity across an elastic shrink -> rejoin:
    the trace id is DERIVED (`step-<mem_epoch>-<step>`), so every rank
    mints the same id for the same (epoch, step) with zero
    coordination, and the epoch component keeps step 5 of the shrunk
    world distinct from step 5 before the loss and step 5 after the
    rejoin. The merged attribution must keep the three lives of step 5
    as three rows instead of folding them together."""
    from dear_pytorch_tpu.observability import critical_path as CP
    from dear_pytorch_tpu.observability import dtrace

    # same derivation on every rank, no coordination
    assert (dtrace.step_trace(1, 5).trace_id
            == dtrace.step_trace(1, 5).trace_id == "step-1-5")
    # ...and no collisions across the elastic transition
    assert len({dtrace.step_trace(e, 5).trace_id
                for e in (0, 1, 2)}) == 3

    # two rank streams through the real SpanStream, emitting the
    # guard's span shape: epoch 0 both ranks -> shrink (epoch 1, rank 0
    # alone) -> rejoin (epoch 2, both ranks), step counter re-walking 5
    writers = {r: dtrace.MemoryWriter() for r in (0, 1)}
    streams = {r: dtrace.SpanStream(w, rank=r)
               for r, w in writers.items()}
    lives = [(0, 5, (0, 1)), (0, 6, (0, 1)),
             (1, 5, (0,)),                       # shrunk world
             (2, 5, (0, 1)), (2, 6, (0, 1))]    # rejoined
    for epoch, step, ranks in lives:
        for r in ranks:
            streams[r].emit(
                "guard.step", dur_s=0.01, cat="step",
                trace=dtrace.step_trace(epoch, step),
                step=step, mem_epoch=epoch, checked=False, healthy=True)
    merged = dtrace.merge_streams(
        [w.records for w in writers.values()])
    att = CP.step_attribution(merged)
    rows = {(s["mem_epoch"], s["step"]): s for s in att["steps"]}
    assert set(rows) == {(0, 5), (0, 6), (1, 5), (2, 5), (2, 6)}
    assert set(rows[(1, 5)]["ranks"]) == {"0"}
    assert set(rows[(2, 5)]["ranks"]) == {"0", "1"}
    tids = {(s.get("mem_epoch"), s.get("step")):
            (s.get("trace") or {}).get("trace_id")
            for s in merged["spans"] if s.get("name") == "guard.step"}
    assert tids[(0, 5)] != tids[(1, 5)] != tids[(2, 5)]


# -- the acceptance storm -----------------------------------------------------


@pytest.mark.timeout(640, method="signal")
def test_chaos_check_multislice_storm(tmp_path):
    """scripts/chaos_check.py --multislice: the ISSUE-15 acceptance gate.
    A 2-slice x 4-rank supervised fleet trains the hierarchical RS+AG
    (ICI) + DCN schedule; the whole of slice 1 is SIGKILLed mid-step and
    must commit as EXACTLY ONE membership epoch (slice-shaped signed
    delta); the survivors renormalize the cross-slice leg and train
    degraded under a slice-targeted dcn_slow straggler fault; the
    relaunched slice hydrates from the remote tier and readmits through
    the slice-gated admission as one epoch; the fleet finishes in
    lockstep at full membership with zero loss of progress past the
    newest uploaded checkpoint. All coordination over `FileTransport`;
    no `jax.distributed`."""
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = os.path.join(repo, "scripts", "chaos_check.py")
    env = dict(os.environ)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(
        [sys.executable, script, "--multislice", "--checkpoint-every",
         "2", "--workdir", str(tmp_path)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True, timeout=600,
    )
    assert proc.returncode == 0, proc.stdout[-3000:]
    assert "CHAOS CHECK PASSED" in proc.stdout, proc.stdout[-3000:]


@pytest.mark.timeout(360, method="signal")
def test_chaos_check_multislice_flap_storm(tmp_path):
    """scripts/chaos_check.py --multislice-flap: the ISSUE-18 acceptance
    gate. A 2-slice x 2-rank fleet trains in bounded-staleness mode
    (DEAR_DCN_STALENESS=2) under a sub-budget dcn_flap transient plus a
    dcn_slow straggler; the gate asserts ZERO guard rollbacks on every
    rank (the transient is absorbed by retry + skip-with-error-feedback,
    never by the recovery machinery), zero membership churn, residual
    carry on the flapped slice, lockstep at the exact step target, and a
    bench_gate --slo steps/hour floor."""
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = os.path.join(repo, "scripts", "chaos_check.py")
    env = dict(os.environ)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(
        [sys.executable, script, "--multislice-flap",
         "--checkpoint-every", "4", "--workdir", str(tmp_path)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True, timeout=330,
    )
    assert proc.returncode == 0, proc.stdout[-3000:]
    assert "CHAOS CHECK PASSED" in proc.stdout, proc.stdout[-3000:]


@pytest.mark.slow
@pytest.mark.timeout(640, method="signal")
def test_chaos_check_multislice_degraded_storm(tmp_path):
    """scripts/chaos_check.py --multislice-degraded: the full ladder —
    a past-budget dcn_partition starves one slice until its own
    staleness clock trips DcnSelfEvict (exit 70, no SIGKILL anywhere);
    survivors escalate, the shrink commits as one slice-shaped epoch,
    the supervisor relaunch readmits the slice (its new life strips the
    armed fault), and survivor rollbacks happen ONLY at the membership
    transitions. Covered in tier-1 at unit granularity by
    test_dcn_sustained_partition_walks_the_ladder; this end-to-end storm
    is the slow-tier variant."""
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = os.path.join(repo, "scripts", "chaos_check.py")
    env = dict(os.environ)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(
        [sys.executable, script, "--multislice-degraded",
         "--checkpoint-every", "2", "--workdir", str(tmp_path)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True, timeout=600,
    )
    assert proc.returncode == 0, proc.stdout[-3000:]
    assert "CHAOS CHECK PASSED" in proc.stdout, proc.stdout[-3000:]


# -- degraded-mode DCN: wire integrity + the escalation ladder ----------------


def _mem_tracer():
    """Install a fresh counting tracer; returns (tracer, restore_fn)."""
    from dear_pytorch_tpu.observability import tracer as T

    prev = T._tracer
    tracer = T.Tracer([T.MemoryExporter()])
    T.set_tracer(tracer)
    return tracer, lambda: T.set_tracer(prev)


def test_dcn_chunk_integrity_rejects(tmp_path):
    """Wire integrity (strict mode): a torn KV payload and a replayed
    stale-step value at a chunk key are REJECTED (counted, never merged);
    with no clean replacement the fetch fails as DcnChunkReject inside
    the deadline; once the honest publisher's value lands, the exchange
    completes with the exact mean."""
    tracer, restore = _mem_tracer()
    try:
        tr = CL.FileTransport(str(tmp_path))
        ex0 = DcnExchanger(tr, local_slices=(0,), slices=(0, 1),
                           partition_mb=None, timeout_s=0.4)
        b0 = [np.arange(6, dtype=np.float32)]
        b1 = [np.ones(6, np.float32)]
        # a REPLAYED stale key: a validly framed chunk from step 0
        # planted at step 3's key (epoch/step header mismatch)
        tr.set(ex0._key(3, 0, 0, 1), _encode(
            b1[0], meta={"epoch": 0, "step": 0, "bucket": 0, "chunk": 0,
                         "seq": 1}))
        with pytest.raises(DcnChunkReject):
            ex0.exchange(3, {0: b0})
        # a TORN write: header promises more bytes than the payload has
        good = _encode(b1[0], meta={"epoch": 0, "step": 4, "bucket": 0,
                                    "chunk": 0, "seq": 2})
        head, _, body = good.partition("\n")
        tr.set(ex0._key(4, 0, 0, 1), head + "\n" + body[:8])
        with pytest.raises(DcnChunkReject):
            ex0.exchange(4, {0: b0})
        assert tracer.counters()["dcn.chunk_rejects"] >= 2
        # the honest value supersedes: exact mean, no residue of the bad
        # bytes (the reject path never accumulates)
        tr.set(ex0._key(5, 0, 0, 1), _encode(
            b1[0], meta={"epoch": 0, "step": 5, "bucket": 0, "chunk": 0,
                         "seq": 3}))
        means, _ = ex0.exchange(5, {0: b0})
        np.testing.assert_array_equal(means[0], (b0[0] + b1[0]) / 2.0)
    finally:
        restore()


def _run_n(fns, join_s=60):
    out, err = [None] * len(fns), [None] * len(fns)

    def w(i, f):
        try:
            out[i] = f()
        except BaseException as exc:  # noqa: BLE001 - asserted below
            err[i] = exc
    ts = [threading.Thread(target=w, args=(i, f))
          for i, f in enumerate(fns)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(join_s)
    return out, err


def test_dcn_degraded_skip_is_replica_identical():
    """The skip rung: a slice whose publish is dropped is excluded on
    EVERY exchanger — its own included (the two-phase participation
    record), all three means bitwise identical per round — and its
    deferred mass returns through the error-feedback residual on the
    next round (nothing lost, nothing double-counted)."""
    tracer, restore = _mem_tracer()
    try:
        tr = CL.LocalTransport()
        inj2 = FaultInjector(parse_faults("dcn_drop@1:s2"),
                             own_rank=0, own_slice=2)
        exs = [
            DcnExchanger(tr, local_slices=(i,), slices=(0, 1, 2),
                         partition_mb=0.00002, timeout_s=1.5, retries=1,
                         staleness=2, injector=inj2 if i == 2 else None)
            for i in range(3)
        ]
        rng = np.random.default_rng(7)
        g1 = [rng.normal(size=9).astype(np.float32) for _ in range(3)]
        g2 = [rng.normal(size=9).astype(np.float32) for _ in range(3)]
        out, err = _run_n([
            (lambda i=i: exs[i].exchange(0, {i: [g1[i]]}))
            for i in range(3)
        ])
        assert not any(err), err
        # round 1: slice 2's publish dropped -> everyone averages {0,1}
        for i in (1, 2):
            np.testing.assert_array_equal(out[0][0][0], out[i][0][0])
        np.testing.assert_allclose(out[0][0][0], (g1[0] + g1[1]) / 2.0,
                                   rtol=1e-6)
        out2, err = _run_n([
            (lambda i=i: exs[i].exchange(1, {i: [g2[i]]}))
            for i in range(3)
        ])
        assert not any(err), err
        # round 2: slice 2 republishes grad+residual -> full membership
        # mean carries the deferred mass exactly (mass preservation:
        # 2*m1 + 3*m2 == every gradient published across both rounds)
        for i in (1, 2):
            np.testing.assert_array_equal(out2[0][0][0], out2[i][0][0])
        total = 2.0 * out[0][0][0] + 3.0 * out2[0][0][0]
        np.testing.assert_allclose(
            total, sum(g1) + sum(g2), rtol=1e-5)
        c = tracer.counters()
        assert c["dcn.skips"] >= 3         # slice 2 skipped on 3 views
        assert c["dcn.degraded_rounds"] >= 3
        assert c["dcn.residual_carries"] >= 1
        assert "dcn.escalations" not in c  # sub-budget: no ladder rung 3
        assert "guard.rollbacks" not in c
    finally:
        restore()


def test_dcn_residual_state_roundtrip_and_repack():
    """EF residual durability: state_dict -> JSON -> load_state_dict is
    bit-exact (the checkpoint sidecar contract), and a fusion-plan
    change re-packs the carried mass with the sum exactly invariant
    (the `_repack_comp_state` algebra at DCN level)."""
    tr = CL.LocalTransport()
    ex = DcnExchanger(tr, local_slices=(0,), slices=(0, 1), staleness=2)
    rng = np.random.default_rng(3)
    params = {"a": rng.normal(size=(5, 4)).astype(np.float32),
              "b": rng.normal(size=(7,)).astype(np.float32),
              "c": rng.normal(size=(3, 3)).astype(np.float32)}
    old_plan = F.plan_by_threshold(params, 1, threshold_mb=1e-4)
    new_plan = F.plan_by_threshold(params, 1, threshold_mb=1.0)
    assert old_plan.num_buckets != new_plan.num_buckets
    leaves = jax.tree_util.tree_leaves(params)
    ex._residual = {0: [np.asarray(F.pack_bucket(leaves, old_plan, b),
                                   np.float32)
                        for b in range(old_plan.num_buckets)]}
    ex._staleness = {0: 1, 1: 0}
    # sidecar round-trip through actual JSON text, bit-exact
    blob = json.loads(json.dumps(ex.state_dict()))
    ex2 = DcnExchanger(tr, local_slices=(0,), slices=(0, 1), staleness=2)
    ex2.load_state_dict(blob)
    for a, b in zip(ex._residual[0], ex2._residual[0]):
        np.testing.assert_array_equal(a, b)
    assert ex2._staleness[0] == 1
    # plan change: unpack-old/pack-new preserves every leaf's mass
    before = float(sum(np.sum(r, dtype=np.float64)
                       for r in ex._residual[0]))
    ex.repack_residual(old_plan, new_plan)
    assert len(ex._residual[0]) == new_plan.num_buckets
    after = float(sum(np.sum(r, dtype=np.float64)
                      for r in ex._residual[0]))
    np.testing.assert_allclose(after, before, rtol=1e-6)
    rec = {}
    for b in range(new_plan.num_buckets):
        rec.update(F.unpack_bucket(ex._residual[0][b], new_plan, b))
    for lid, leaf in enumerate(leaves):
        np.testing.assert_allclose(np.asarray(rec[lid]), leaf, rtol=1e-6)
    # alien payload resets to fresh zeros instead of guessing
    ex2.load_state_dict({"residual": {"0": [{"bogus": True}]}})
    assert ex2._residual == {}


def test_dcn_flap_partition_grammar_and_schedule():
    """dcn_flap@N:K drops exchanges N, N+2, ... for K cycles (recovering
    in between); dcn_partition@N:SECS suppresses outbound for SECS of
    wall time; both slice-targetable, both drained as `skipped` off
    target."""
    fs = parse_faults("dcn_flap@3:2:s1,dcn_partition@5:0.25:s0")
    assert fs[0].kind == "dcn_flap" and fs[0].slice_id == 1
    assert fs[1].kind == "dcn_partition" and fs[1].arg == 0.25
    inj = FaultInjector([Fault(kind="dcn_flap", step=3, arg=2)],
                        own_rank=0, own_slice=0)
    sched = [inj.dcn_outage_due(n) for n in range(1, 9)]
    assert sched == [False, False, True, False, True, False, False,
                     False]
    inj2 = FaultInjector([Fault(kind="dcn_partition", step=2, arg=0.2)],
                         own_rank=0, own_slice=0)
    assert not inj2.dcn_outage_due(1)
    t0 = time.monotonic()
    assert inj2.dcn_outage_due(2)          # arms the wall-clock window
    assert inj2.dcn_outage_due(3)          # still inside it
    time.sleep(max(0.0, 0.25 - (time.monotonic() - t0)))
    assert not inj2.dcn_outage_due(4)      # window elapsed: recovered
    # off-target: consumed into skipped, never fired
    inj3 = FaultInjector(parse_faults("dcn_flap@1:2:s1"),
                         own_rank=0, own_slice=0)
    assert not inj3.dcn_outage_due(1)
    assert inj3.skipped and not inj3.fired


def test_dcn_sustained_partition_walks_the_ladder():
    """Past-budget escalation, both verdicts from the SAME records: the
    survivor escalates the dark slice (stops waiting for it) while the
    partitioned slice — which still sees the survivor's records naming
    a world without it — self-evicts for relaunch + rejoin."""
    tracer, restore = _mem_tracer()
    try:
        tr = CL.LocalTransport()
        inj1 = FaultInjector(parse_faults("dcn_partition@1:30:s1"),
                             own_rank=0, own_slice=1)
        ex0 = DcnExchanger(tr, local_slices=(0,), slices=(0, 1),
                           partition_mb=None, timeout_s=0.8, retries=1,
                           staleness=1)
        ex1 = DcnExchanger(tr, local_slices=(1,), slices=(0, 1),
                           partition_mb=None, timeout_s=0.8, retries=1,
                           staleness=1, injector=inj1)
        b = [np.ones(4, np.float32)]
        evicted = None
        for step in range(4):
            out, err = _run_n([
                lambda s=step: ex0.exchange(s, {0: b}),
                lambda s=step: ex1.exchange(s, {1: b}),
            ])
            assert err[0] is None, err[0]
            if err[1] is not None:
                evicted = err[1]
                break
        assert isinstance(evicted, DcnSelfEvict), evicted
        c = tracer.counters()
        assert c["dcn.escalations"] >= 1    # survivor stopped waiting
        assert c["dcn.self_evicts"] >= 1    # victim exited for relaunch
        assert c["dcn.skips"] >= 2
        # the survivor keeps exchanging alone without stalling: the
        # escalated slice costs it nothing further
        t0 = time.monotonic()
        means, _ = ex0.exchange(9, {0: b})
        assert time.monotonic() - t0 < ex0.timeout_s
        np.testing.assert_array_equal(means[0], b[0])
    finally:
        restore()


def test_dcn_prefetch_overlaps_next_round():
    """staleness=1 as the cross-iteration prefetch primitive: chunks a
    peer already published for THIS step are staged by `prefetch` while
    'the backward pass runs' and consumed without a second fetch
    (dcn.prefetch_hits), with the mean exact."""
    tracer, restore = _mem_tracer()
    try:
        tr = CL.LocalTransport()
        ex0 = DcnExchanger(tr, local_slices=(0,), slices=(0, 1),
                           partition_mb=0.00002, timeout_s=2.0,
                           staleness=1)
        ex1 = DcnExchanger(tr, local_slices=(1,), slices=(0, 1),
                           partition_mb=0.00002, timeout_s=2.0,
                           staleness=1)
        b0 = [np.arange(9, dtype=np.float32)]
        b1 = [np.ones(9, np.float32) * 2.0]
        out, err = _run_n([
            lambda: ex0.exchange(0, {0: b0}),
            lambda: ex1.exchange(0, {1: b1}),
        ])
        assert not any(err), err
        # ex1 publishes step 1 first (a peer one round ahead) ...
        t1 = threading.Thread(
            target=lambda: ex1.exchange(1, {1: b1}))
        t1.start()
        deadline = time.monotonic() + 3.0
        while time.monotonic() < deadline:
            try:
                tr.get(ex0._key(1, 0, 0, 1), 0.05)
                break
            except CL.PeerTimeout:
                pass
        # ... so ex0's prefetch stages them before its own exchange
        ex0.prefetch(1)
        ex0._join_prefetch()
        means, _ = ex0.exchange(1, {0: b0})
        t1.join(10)
        np.testing.assert_allclose(means[0], (b0[0] + b1[0]) / 2.0,
                                   rtol=1e-6)
        assert tracer.counters().get("dcn.prefetch_hits", 0) >= 1
    finally:
        restore()


def test_dcn_bounded_stale_loss_parity_band():
    """Numerics: EF-SGD through the skip rung tracks synchronous SGD.
    Two slices minimize a shared quadratic; the victim's link flaps
    (two drop/recover cycles) under staleness=2. Both replicas stay
    bitwise in lockstep, converge, and land inside a pinned parity band
    of the fault-free synchronous trajectory."""
    tr = CL.LocalTransport()
    inj1 = FaultInjector(parse_faults("dcn_flap@3:2:s1"),
                         own_rank=0, own_slice=1)
    ex0 = DcnExchanger(tr, local_slices=(0,), slices=(0, 1),
                       partition_mb=None, timeout_s=1.0, retries=1,
                       staleness=2)
    ex1 = DcnExchanger(tr, local_slices=(1,), slices=(0, 1),
                       partition_mb=None, timeout_s=1.0, retries=1,
                       staleness=2, injector=inj1)
    rng = np.random.default_rng(11)
    c0 = rng.normal(size=8).astype(np.float32)
    c1 = -c0 + rng.normal(size=8).astype(np.float32) * 0.3
    w0 = rng.normal(size=8).astype(np.float32) * 3.0
    lr, steps = 0.2, 12

    def sync_run():
        w = w0.copy()
        for _ in range(steps):
            w = w - lr * ((w - c0) + (w - c1)) / 2.0
        return w

    def stale_run():
        w = [w0.copy(), w0.copy()]
        for s in range(steps):
            out, err = _run_n([
                lambda s=s: ex0.exchange(s, {0: [w[0] - c0]}),
                lambda s=s: ex1.exchange(s, {1: [w[1] - c1]}),
            ])
            assert not any(err), err
            # replica-identical means -> replica-identical parameters
            np.testing.assert_array_equal(out[0][0][0], out[1][0][0])
            w = [wi - lr * out[i][0][0] for i, wi in enumerate(w)]
            np.testing.assert_array_equal(w[0], w[1])
        return w[0]

    w_sync, w_stale = sync_run(), stale_run()
    opt = (c0 + c1) / 2.0
    d0 = float(np.linalg.norm(w0 - opt))
    # the parity band: bounded staleness costs a bounded trajectory gap
    gap = float(np.linalg.norm(w_stale - w_sync))
    assert gap < 0.25 * d0, (gap, d0)
    # and it still CONVERGES (the flap cost progress, not correctness)
    assert float(np.linalg.norm(w_stale - opt)) < 0.35 * d0
    assert inj1.fired and inj1.fired[0].kind == "dcn_flap"
