"""Resilience subsystem: deterministic fault injection drives every
recovery path — NaN rollback, step-exception rollback, corrupted-checkpoint
fallback via the checksum manifest, SIGTERM preemption saves, the step
watchdog, retry/backoff, and autotuner trial sandboxing. The reference
could only validate failure handling by killing real cluster jobs; here a
multi-fault chaos sequence is a CPU-world-8 unit test.

All guarded-trainer tests share ONE jitted train step (module fixture) to
keep the suite inside the tier-1 time budget.
"""

import os
import signal

import jax
import numpy as np
import pytest

from dear_pytorch_tpu.ops.fused_sgd import fused_sgd
from dear_pytorch_tpu.parallel import build_train_step
from dear_pytorch_tpu.resilience import (
    Fault,
    FaultInjector,
    InjectedFault,
    PreemptionHandler,
    RetryError,
    StepWatchdog,
    corrupt_latest_checkpoint,
    parse_faults,
    retry_call,
)
from dear_pytorch_tpu.utils import checkpoint as ckpt
from dear_pytorch_tpu.utils.guard import GuardedTrainer

from tests.test_dear_numerics import _data, _loss_fn, _mlp_params


@pytest.fixture(scope="module")
def tsp(mesh):
    """One compiled TrainStep shared by every test in this module."""
    params = _mlp_params(jax.random.PRNGKey(0))
    ts = build_train_step(
        _loss_fn, params, mesh=mesh, threshold_mb=0.0008, donate=False,
        optimizer=fused_sgd(lr=0.05, momentum=0.9),
    )
    return params, ts


def _guard(tsp, tmp_path, **kw):
    params, ts = tsp
    kw.setdefault("check_every", 1)
    kw.setdefault("checkpoint_every", 4)
    return params, ts, GuardedTrainer(ts, str(tmp_path / "g"), params, **kw)


def _batches(n, base=100):
    return [_data(jax.random.PRNGKey(base + i)) for i in range(n)]


# -- primitives ---------------------------------------------------------------


def test_parse_faults_grammar():
    faults = parse_faults("nan@6, exc@9,hang@12:0.5,ckpt_corrupt@15,preempt@18")
    assert [f.kind for f in faults] == [
        "nan", "exc", "hang", "ckpt_corrupt", "preempt"]
    assert faults[2].arg == 0.5
    with pytest.raises(ValueError, match="kind@step"):
        parse_faults("nan6")
    with pytest.raises(ValueError, match="valid kinds"):
        Fault(kind="meteor", step=3)
    assert FaultInjector.from_env("") is None
    assert FaultInjector.from_env("nan@2").pending == 1


def test_rank_targeted_fault_grammar():
    faults = parse_faults("nan@6:r1,exc@9:r0,hang@12:0.5:r1,ckpt_corrupt@15")
    assert [(f.kind, f.rank) for f in faults] == [
        ("nan", 1), ("exc", 0), ("hang", 1), ("ckpt_corrupt", None)]
    assert faults[2].arg == 0.5
    # rank-first spelling composes too
    assert parse_faults("hang@3:r1:0.25")[0] == Fault(
        kind="hang", step=3, arg=0.25, rank=1)
    # malformed rank specs raise WITH the valid format in the message
    with pytest.raises(ValueError,
                       match=r"kind@step\[:arg\]\[:rRANK\|:sSLICE\]"):
        parse_faults("nan@6:rX")
    with pytest.raises(ValueError,
                       match="neither a float arg, an rRANK, nor an sSLICE"):
        parse_faults("nan@6:banana")
    with pytest.raises(ValueError, match="duplicate rank"):
        parse_faults("nan@6:r0:r1")
    with pytest.raises(ValueError, match="process index >= 0"):
        Fault(kind="nan", step=3, rank=-2)


def test_rank_targeted_faults_fire_only_on_their_rank():
    """Other ranks consume the fault into ``skipped`` at the same step —
    schedules drain identically everywhere (the lockstep invariant the
    coordinated chaos tests rely on)."""
    sched = [Fault(kind="exc", step=2, rank=1), Fault(kind="nan", step=3)]
    mine = FaultInjector(sched, own_rank=1)
    theirs = FaultInjector(sched, own_rank=0)
    with pytest.raises(InjectedFault):
        mine.before_step(2)
    theirs.before_step(2)  # no raise: not this rank's fault
    assert [f.kind for f in theirs.skipped] == ["exc"]
    assert [f.kind for f in mine.fired] == ["exc"]
    # the untargeted nan still fires on every rank
    for inj in (mine, theirs):
        with pytest.raises(InjectedFault):  # int batch -> degraded error
            inj.poison_batch(3, {"ids": np.zeros((2,), np.int32)})
        assert inj.pending == 0


def test_nan_fault_on_integer_batch_degrades_to_step_error():
    """An all-int batch (BERT/GPT token specs) cannot carry a NaN: the
    fault must degrade to an InjectedFault — which the guard recovers
    from — not a ValueError that kills the run."""
    inj = FaultInjector([Fault(kind="nan", step=1)])
    with pytest.raises(InjectedFault, match="no float leaf"):
        inj.poison_batch(1, {"ids": np.zeros((4,), np.int32)})
    assert inj.pending == 0  # consumed either way


def test_seeded_schedule_is_deterministic():
    a = FaultInjector.from_seed(7, horizon=200, rate=0.05)
    b = FaultInjector.from_seed(7, horizon=200, rate=0.05)
    sched = lambda inj: sorted(
        (s, f.kind) for s, fs in inj._by_step.items() for f in fs)
    assert sched(a) == sched(b)
    assert a.pending > 0


def test_retry_recovers_then_gives_up():
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise OSError("transient")
        return 42

    assert retry_call(flaky, base_delay_s=0.0) == 42
    assert len(calls) == 3

    def doomed():
        raise TimeoutError("forever")

    with pytest.raises(RetryError, match="after 2 attempts") as ei:
        retry_call(doomed, attempts=2, base_delay_s=0.0)
    assert isinstance(ei.value.__cause__, TimeoutError)

    # non-transient errors propagate immediately, unretried
    def bug():
        calls.append("bug")
        raise ValueError("logic error")

    calls.clear()
    with pytest.raises(ValueError):
        retry_call(bug, base_delay_s=0.0)
    assert calls == ["bug"]


def test_retry_telemetry_counters():
    """Retries must be visible in telemetry: `retry.attempts` counts every
    attempt (firsts included), `retry.giveups` exhausted calls — the
    docs/OBSERVABILITY.md counter-table contract."""
    from dear_pytorch_tpu.observability import tracer as T

    prev = T._tracer
    tracer = T.Tracer([T.MemoryExporter()])
    T.set_tracer(tracer)
    try:
        state = {"n": 0}

        def flaky():
            state["n"] += 1
            if state["n"] < 3:
                raise OSError("transient")
            return 1

        def doomed():
            raise OSError("x")

        retry_call(flaky, base_delay_s=0.0)           # 3 attempts, absorbed
        with pytest.raises(RetryError):
            retry_call(doomed, attempts=2, base_delay_s=0.0)  # 2, giveup
        c = tracer.counters()
        assert c["retry.calls"] == 2
        assert c["retry.attempts"] == 5
        assert c["retry.retries"] == 3
        assert c["retry.giveups"] == 1
    finally:
        T.set_tracer(prev)


def test_watchdog_report_carries_rank_and_fault_schedule(monkeypatch):
    """Multi-host hang logs correlate by rank: the dump/report names
    `jax.process_index()` and the active DEAR_FAULTS schedule; `kick()`
    produces the same forensics on demand (the cluster layer's dead-peer
    path) without aborting."""
    monkeypatch.setenv("DEAR_FAULTS", "hang@3:0.5:r1")
    fired = []
    with StepWatchdog(0.05, on_timeout=fired.append, poll_s=0.01) as dog:
        dog.beat(step=7, last_good_step=4)
        import time as _time

        _time.sleep(0.3)
    assert len(fired) == 1
    rep = fired[0]
    assert rep.process_index == jax.process_index()
    assert rep.faults == "hang@3:0.5:r1"
    kicked = dog.kick("cluster peer timeout", step=9)
    assert dog.kicked == 1 and kicked.faults == "hang@3:0.5:r1"
    assert kicked.beat_info["step"] == 9
    assert kicked.beat_info["last_good_step"] == 4  # merged from the beat


# -- injected faults through the guard ----------------------------------------


def test_injected_nan_triggers_rollback(tsp, tmp_path):
    inj = FaultInjector([Fault(kind="nan", step=6)])
    params, ts, tr = _guard(tsp, tmp_path, injector=inj)
    state = ts.init(params)
    rollbacks = []
    tr.on_rollback = lambda n, at: rollbacks.append((n, at))
    for b in _batches(8):
        state, m = tr.step(state, b)
    assert rollbacks == [(1, 4)]
    assert inj.pending == 0 and [f.kind for f in inj.fired] == ["nan"]
    assert int(jax.device_get(state.step)) > 4  # training continued


def test_injected_exception_triggers_rollback(tsp, tmp_path):
    inj = FaultInjector([Fault(kind="exc", step=6)])
    params, ts, tr = _guard(tsp, tmp_path, injector=inj)
    state = ts.init(params)
    rollbacks = []
    tr.on_rollback = lambda n, at: rollbacks.append((n, at))
    for b in _batches(8):
        state, m = tr.step(state, b)
        if not m.get("rolled_back"):
            assert np.isfinite(float(m["loss"]))
    assert rollbacks == [(1, 4)]
    # the injected exception took the real error-recovery path
    assert tr.steps_seen == 7  # step 6 never completed, 7 attempts ran


def test_watchdog_fires_on_injected_hang(tsp, tmp_path):
    inj = FaultInjector([Fault(kind="hang", step=3, arg=0.6)])
    params, ts, tr = _guard(tsp, tmp_path, checkpoint_every=2, injector=inj)
    state = ts.init(params)
    bs = _batches(3)
    for b in bs[:2]:
        state, _ = tr.step(state, b)  # step-2 periodic checkpoint
    fired = []
    with StepWatchdog(0.2, on_timeout=fired.append, poll_s=0.02) as dog:
        tr._watchdog = dog
        dog.beat(step=2, last_good_step=2)  # arm just before the hang
        state, _ = tr.step(state, bs[2])  # injected 0.6s hang mid-step
    assert len(fired) == 1
    # the report names the last-good (checkpointed) step a relaunch resumes
    # from: the step-2 periodic checkpoint
    assert fired[0].beat_info["last_good_step"] == 2
    assert fired[0].waited_s > 0.2


def test_corrupted_checkpoint_falls_back_to_previous(tsp, tmp_path):
    params, ts, tr = _guard(tsp, tmp_path)
    d = str(tmp_path / "g")
    state = ts.init(params)
    for b in _batches(8):
        state, _ = tr.step(state, b)  # checkpoints at 4 and 8
    assert ckpt.latest_step(d) == 8
    assert ckpt.verify_checkpoint(d, 8)
    corrupted = corrupt_latest_checkpoint(d)
    assert corrupted == 8
    assert not ckpt.verify_checkpoint(d, 8)  # manifest catches the flip
    assert ckpt.latest_valid_step(d) == 4  # walks past the corruption
    rollbacks = []
    tr.on_rollback = lambda n, at: rollbacks.append((n, at))
    x, y = _data(jax.random.PRNGKey(999))
    import jax.numpy as jnp

    state, m = tr.step(state, (x.at[0, 0].set(jnp.nan), y))
    assert m.get("rolled_back")
    assert rollbacks == [(1, 4)]  # NOT the corrupted step 8


def test_valid_steps_walks_past_corruption(tsp, tmp_path):
    """`valid_steps` (one host's local view for the cluster layer's
    consensus restore) lists every verifying step newest-first and drops
    corrupted ones."""
    params, ts, tr = _guard(tsp, tmp_path)
    d = str(tmp_path / "g")
    state = ts.init(params)
    for b in _batches(12):
        state, _ = tr.step(state, b)  # checkpoints at 4, 8, 12
    assert ckpt.valid_steps(d) == [12, 8, 4]
    assert ckpt.valid_steps(d, limit=2) == [12, 8]
    corrupt_latest_checkpoint(d)
    assert ckpt.valid_steps(d) == [8, 4]


def test_preemption_emergency_save_and_resume(tsp, tmp_path):
    d = str(tmp_path / "g")
    with PreemptionHandler() as pre:
        params, ts, tr = _guard(tsp, tmp_path, checkpoint_every=100,
                                preemption=pre)
        state = ts.init(params)
        for b in _batches(3):
            state, m = tr.step(state, b)
            assert "preempted" not in m
        os.kill(os.getpid(), signal.SIGTERM)
        assert pre.requested
        state, m = tr.step(state, _batches(1, base=500)[0])
        assert m.get("preempted")
        assert m.get("preempt_checkpoint_step") == 4
    # the emergency save is a verified, manifested, committed checkpoint
    assert ckpt.latest_valid_step(d) == 4
    restored = ckpt.restore_checkpoint(d, ts, template=ts.init(params))
    assert int(jax.device_get(restored.step)) == 4


def test_multi_fault_sequence_recovers_to_consistent_step(tsp, tmp_path):
    """The ISSUE-2 acceptance sequence: NaN, then a raised step exception,
    then preemption — one GuardedTrainer run rolls back twice, emergency-
    saves on SIGTERM, and a relaunch resumes from a consistent step."""
    inj = FaultInjector([
        Fault(kind="nan", step=6),
        Fault(kind="exc", step=9),
        Fault(kind="preempt", step=11),
    ])
    d = str(tmp_path / "g")
    rollbacks = []
    with PreemptionHandler() as pre:
        params, ts, tr = _guard(tsp, tmp_path, injector=inj, preemption=pre)
        tr.on_rollback = lambda n, at: rollbacks.append((n, at))
        state = ts.init(params)
        preempted_at = None
        for b in _batches(14):
            state, m = tr.step(state, b)
            if m.get("preempted"):
                preempted_at = int(jax.device_get(state.step))
                break
        assert preempted_at is not None, "preempt fault never landed"
    assert inj.pending == 0
    # nan at attempt 6 -> rollback to the step-4 checkpoint (device step
    # falls 2 behind the attempt count); the attempt-8 periodic checkpoint
    # persists device step 6 and resets recoveries; exc at attempt 9 ->
    # rollback to 6
    assert rollbacks == [(1, 4), (1, 6)]
    # the emergency checkpoint persisted exactly the live state: relaunch
    # loses nothing
    assert ckpt.latest_valid_step(d) == preempted_at
    restored = ckpt.restore_checkpoint(d, ts, template=ts.init(params))
    assert int(jax.device_get(restored.step)) == preempted_at
    # and the resumed state trains on (finite loss, step advances)
    state2, m2 = ts.step(restored, _batches(1, base=700)[0])
    assert np.isfinite(float(m2["loss"]))
    assert int(jax.device_get(state2.step)) == preempted_at + 1


# -- checkpoint hygiene -------------------------------------------------------


def test_prune_orphaned_tmp_on_startup(tsp, tmp_path, monkeypatch):
    d = str(tmp_path / "g")
    os.makedirs(d)
    junk = os.path.join(d, "step_0000000007.orbax-checkpoint-tmp-3")
    os.makedirs(junk)
    removed = ckpt.prune_orphaned_tmp(d)
    assert removed == ["step_0000000007.orbax-checkpoint-tmp-3"]
    assert not os.path.exists(junk)
    # GuardedTrainer construction runs the same GC — but only in a
    # process that has never run an async save (a second trainer must
    # not sweep a live in-flight write). The process-global async
    # checkpointer outlives earlier suite tests that used one, so pin
    # the gate to the pristine state this test is about (the latent
    # order-dependence failed this test whenever the suite front reached
    # it after test_guard's async tests — pre-existing, fixed here).
    monkeypatch.setattr(ckpt, "has_async_checkpointer", lambda: False)
    os.makedirs(junk)
    _guard(tsp, tmp_path)
    assert not os.path.exists(junk)


def test_async_manifest_backfill_on_finalize(tsp, tmp_path):
    import json

    params, ts, tr = _guard(tsp, tmp_path, async_checkpoints=True)
    d = str(tmp_path / "g")
    state = ts.init(params)
    for b in _batches(4):
        state, _ = tr.step(state, b)
    tr.finalize()  # waits for the commit, then backfills the manifest
    with open(os.path.join(d, "meta_0000000004.json")) as f:
        meta = json.load(f)
    assert meta["manifest"], "finalize must backfill the checksum manifest"
    assert ckpt.verify_checkpoint(d, 4)
    corrupt_latest_checkpoint(d)
    assert not ckpt.verify_checkpoint(d, 4)


# -- the CI chaos gate --------------------------------------------------------


def test_chaos_check_script_passes(mesh, tmp_path):
    """scripts/chaos_check.py end to end: NaN grads, step exception,
    corrupted newest checkpoint, SIGTERM preemption, relaunch-resume, and
    the watchdog hang — all in one short run, zero loss of progress."""
    import importlib.util

    path = os.path.join(os.path.dirname(__file__), "..", "scripts",
                        "chaos_check.py")
    spec = importlib.util.spec_from_file_location("chaos_check", path)
    m = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(m)
    summary = m.run(steps=16, workdir=str(tmp_path))
    assert summary["passed"], summary["failures"]
    assert summary["resumed_at"] == summary["preempted_at"]
    assert summary["guard_counters"]["guard.rollbacks"] == 3
    assert summary["guard_counters"]["watchdog.timeouts"] == 1


@pytest.mark.timeout(420, method="signal")
def test_chaos_check_two_process_storm(tmp_path):
    """scripts/chaos_check.py --procs 2: the fault storm through the
    2-process launcher env contract — rank-targeted NaN/exception/
    checkpoint-corruption faults, per-host checkpoint directories, and
    every recovery a cluster consensus. The parent asserts all ranks
    rolled back to identical steps and finished in lockstep."""
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = os.path.join(repo, "scripts", "chaos_check.py")
    env = dict(os.environ)
    env.pop("DEAR_DISABLE_DISTRIBUTED", None)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(
        [sys.executable, script, "--procs", "2", "--steps", "16",
         "--workdir", str(tmp_path)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True, timeout=360,
    )
    assert proc.returncode == 0, proc.stdout[-3000:]
    assert "CHAOS CHECK PASSED" in proc.stdout, proc.stdout[-3000:]


@pytest.mark.timeout(480, method="signal")
def test_chaos_check_elastic_storm(tmp_path):
    """scripts/chaos_check.py --elastic: SIGKILL one rank of a 3-rank
    host-level cluster mid-run. The gate asserts the survivors committed
    a smaller membership epoch and kept training with a rescaled
    epoch-stamped fusion plan and resharded pipeline — rolling back
    exactly to the newest commonly-valid checkpoint, zero loss of
    progress — and that `launch/supervisor.py`'s relaunch of the dead
    rank was readmitted at a later epoch barrier and finished in lockstep
    (ISSUE-5 acceptance). All coordination over `FileTransport`; no
    `jax.distributed`."""
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = os.path.join(repo, "scripts", "chaos_check.py")
    env = dict(os.environ)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(
        [sys.executable, script, "--elastic", "--checkpoint-every", "2",
         "--workdir", str(tmp_path)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True, timeout=440,
    )
    assert proc.returncode == 0, proc.stdout[-3000:]
    assert "CHAOS CHECK PASSED" in proc.stdout, proc.stdout[-3000:]


@pytest.mark.timeout(560, method="signal")
def test_chaos_check_autoscale_storm(tmp_path):
    """scripts/chaos_check.py --autoscale: the continuous-training
    service gate (ISSUE-7 acceptance). A 2-rank supervised fleet streams
    checkpoints to an object-store tier, a capacity-up hint commits a
    scale-UP epoch to 3 ranks, one rank is SIGKILLed (shrink + relaunch
    within the sliding-window budget), a spot-style SIGTERM drains
    another (planned shrink inside the preemption grace window, then
    policy backfill), and the fleet finishes in lockstep at full
    membership. The gate machine-checks the signed world-delta decision
    records, the steps-per-hour SLO through `bench_gate.py --slo`, zero
    loss of progress past the newest uploaded checkpoint, and a
    scale-from-zero cold start restored from the remote tier alone. All
    coordination over `FileTransport`; no `jax.distributed`."""
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = os.path.join(repo, "scripts", "chaos_check.py")
    env = dict(os.environ)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(
        [sys.executable, script, "--autoscale", "--checkpoint-every", "2",
         "--workdir", str(tmp_path)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True, timeout=520,
    )
    assert proc.returncode == 0, proc.stdout[-3000:]
    assert "CHAOS CHECK PASSED" in proc.stdout, proc.stdout[-3000:]


# -- autotuner sandboxing -----------------------------------------------------


def test_autotune_unknown_strategy_lists_valid_ones():
    from dear_pytorch_tpu.tuning import AutoTuner

    with pytest.raises(ValueError, match="valid strategies are 'bo'"):
        AutoTuner(_loss_fn, {}, strategy="annealing")


def test_autotune_failing_trial_is_sandboxed(mesh, monkeypatch):
    """A trial whose rebuild raises is recorded infeasible (penalty
    observation, consumed trial) and the tuning run keeps training on the
    last good plan instead of dying."""
    from dear_pytorch_tpu.tuning import AutoTuner
    from dear_pytorch_tpu.tuning import autotune as AT

    params = _mlp_params(jax.random.PRNGKey(0))
    batches = _batches(5)
    state_t = {"t": 0.0}

    def clock():
        state_t["t"] += 0.01
        return state_t["t"]

    at = AutoTuner(
        _loss_fn, params, strategy="bo", threshold_mb=0.0008,
        bound=(0.005, 0.02), max_trials=2, interval=5,
        mesh=mesh, optimizer=fused_sgd(lr=0.1, momentum=0.9), donate=False,
        clock=clock,
    )

    def boom(*a, **kw):
        raise RuntimeError("XLA compile OOM (injected)")

    monkeypatch.setattr(AT.D, "build_train_step", boom)
    state = at.init(params)
    losses = []
    for i in range(40):
        state, m = at.step(state, batches[i % 5])
        losses.append(float(m["loss"]))
        if at.tuner.finished:
            break
    assert at.tuner.finished
    assert at.rebuilds == 0  # no trial plan ever installed
    assert all(np.isfinite(losses))
    assert int(jax.device_get(state.step)) == len(losses)


def test_bo_tuner_mark_infeasible_reverts_and_consumes_trial():
    from dear_pytorch_tpu.tuning.bo import Tuner

    t = Tuner(x=25.0, bound=(1.0, 256.0), max_num_steps=2, interval=5,
              log=lambda s: None, clock=lambda: 0.0)
    t.mark_infeasible(200.0, revert_to=25.0)
    assert t.current == 25.0
    assert t._num_steps == 1
    t.mark_infeasible(100.0, revert_to=25.0)
    # both trials consumed and infeasible: finishing adopts nothing
    assert t.step() is None
    assert t.finished
