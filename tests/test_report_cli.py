"""CLI coverage for ``python -m dear_pytorch_tpu.observability.report`` —
exit codes, JSON output shape, and the world-size override. The real run
goes through a subprocess (the CLI forces its own emulated CPU world
BEFORE backend init, which an in-process call could never exercise once
the test session's 8-device world is live); argument errors are cheap and
stay in-process."""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _clean_env():
    env = dict(os.environ)
    # the CLI owns platform/world selection; a leaked test-session world
    # must not override the --world flag under test
    for k in ("DEAR_NUM_CPU_DEVICES", "XLA_FLAGS"):
        env.pop(k, None)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    return env


@pytest.mark.timeout(240, method="signal")
def test_report_cli_json_shape_and_world_override(tmp_path):
    out_json = str(tmp_path / "overlap.json")
    proc = subprocess.run(
        [sys.executable, "-m", "dear_pytorch_tpu.observability.report",
         "--world", "2", "--layers", "1", "--width", "32", "--batch", "8",
         "--steps", "2", "--modes", "dear", "--no-hlo",
         "--json", out_json],
        env=_clean_env(), capture_output=True, text=True, timeout=220,
    )
    assert proc.returncode == 0, proc.stdout[-2000:] + proc.stderr[-2000:]
    assert "overlap audit: mode=dear" in proc.stdout
    assert "== telemetry (enabled=True) ==" in proc.stdout
    doc = json.load(open(out_json))
    # top-level shape
    assert set(doc) >= {"world", "alpha", "beta", "compute_time_s",
                        "modes", "telemetry"}
    assert doc["world"] == 2          # the --world override took effect
    assert doc["alpha"] >= 0 and doc["beta"] >= 0
    # per-mode report shape (OverlapReport.to_dict)
    rep = doc["modes"]["dear"]
    assert rep["mode"] == "dear" and rep["world"] == 2
    assert {"comm_time_s", "measured_step_s", "overlap_efficiency",
            "legs", "num_buckets"} <= set(rep)
    assert len(rep["legs"]) == 2 * rep["num_buckets"]  # RS + AG per bucket
    for leg in rep["legs"]:
        assert leg["leg"] in ("reduce_scatter", "all_gather")
        assert leg["payload_bytes"] > 0
    # the telemetry block is the instrumented truth: steps actually ran
    assert doc["telemetry"]["enabled"] is True
    assert doc["telemetry"]["counters"]["dear.steps"] > 0
    assert json.loads(json.dumps(doc)) == doc  # JSON-safe end to end


def test_report_cli_rejects_bad_args(capsys):
    from dear_pytorch_tpu.observability import report as R

    with pytest.raises(SystemExit) as e:
        R.main(["--bogus-flag"])
    assert e.value.code == 2          # argparse usage error
    capsys.readouterr()
    with pytest.raises(SystemExit) as e:
        R.main(["--world", "not-a-number"])
    assert e.value.code == 2
    capsys.readouterr()


def test_report_renders_without_measurement():
    """render_text must not crash on a report with no measured step (the
    honest-absence path: exposure split absent, never guessed)."""
    import jax.numpy as jnp

    from dear_pytorch_tpu.observability import overlap as OV
    from dear_pytorch_tpu.observability import report as R
    from dear_pytorch_tpu.ops import fusion as F

    class _StubTS:
        plan = F.plan_by_nearby_layers({"w": jnp.zeros((64,))},
                                       world=4, k=1)

        def lower(self, state, batch):
            raise RuntimeError("no backend")

    rep = OV.audit_train_step(_StubTS(), None, None, alpha=1e-3, beta=1e-6,
                              mode="dear", include_hlo=False)
    text = R.render_text(rep)
    assert "n/a" in text and "overlap efficiency n/a" in text
