"""Profiling + perf-model + MG-WFBP tests."""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dear_pytorch_tpu.ops import fusion as F
from dear_pytorch_tpu.tuning import mgwfbp_layer_groups, plan_mgwfbp
from dear_pytorch_tpu.utils import (
    CommunicationProfiler,
    StepTimer,
    TraceWriter,
    fit_alpha_beta,
    measure_layerwise_backward,
    predict_allreduce_time,
)


def test_fit_alpha_beta_recovers_line():
    sizes = [1e3, 1e4, 1e5, 1e6]
    alpha, beta = 2e-4, 3e-10
    times = [predict_allreduce_time(alpha, beta, s) for s in sizes]
    a, b = fit_alpha_beta(sizes, times)
    assert a == pytest.approx(alpha, rel=1e-6)
    assert b == pytest.approx(beta, rel=1e-6)


def test_step_timer():
    t = StepTimer()
    for _ in range(3):
        with t:
            pass
    assert len(t.times) == 3
    assert t.mean >= 0 and "steps" in t.summary()


def test_communication_profiler_fits_positive(mesh):
    prof = CommunicationProfiler(mesh, collective="all_reduce")
    sizes_bytes, times = prof.benchmark(
        sizes=[1024, 4096, 16384], repeats=2, warmup=1
    )
    assert len(sizes_bytes) == 3
    assert all(t > 0 for t in times)
    a, b = fit_alpha_beta(sizes_bytes, times)
    assert a >= 0 and b >= 0


def test_measure_layerwise_backward_orders_by_cost():
    # 2-layer model where layer "b_heavy" dominates compute
    params = {
        "a_light": {"w": jnp.ones((8, 8))},
        "b_heavy": {"w": jnp.ones((8, 512))},
    }
    x = jnp.ones((64, 8))

    def loss_fn(p, batch):
        h = batch @ p["a_light"]["w"]
        y = h @ p["b_heavy"]["w"]
        return jnp.sum((jnp.tanh(y @ p["b_heavy"]["w"].T)) ** 2)

    times = measure_layerwise_backward(loss_fn, params, x, repeats=3,
                                       warmup=1)
    assert len(times) == 2
    assert all(t > 0 for t in times)


def test_trace_writer_roundtrip(tmp_path):
    path = os.path.join(tmp_path, "trace.json")
    with TraceWriter(path) as tw:
        with tw.span("step", step=1):
            pass
        tw.instant("rebuild", buckets=4)
    data = json.load(open(path))
    names = [e["name"] for e in data["traceEvents"]]
    assert "step" in names and "rebuild" in names


# ---------------------------------------------------------------------------
# MG-WFBP
# ---------------------------------------------------------------------------


def test_mgwfbp_merges_when_alpha_dominates():
    # huge startup cost: everything merges into one bucket
    sizes = [4e6] * 6
    tb = [1e-3] * 6
    groups = mgwfbp_layer_groups(sizes, tb, alpha=1.0, beta=0.0)
    assert groups == [[0, 1, 2, 3, 4, 5]]


def test_mgwfbp_keeps_separate_when_comm_free():
    # zero comm cost: communication always finishes instantly -> no merges
    # (except none are under the tiny-layer floor)
    sizes = [4e6] * 6
    tb = [1e-3] * 6
    groups = mgwfbp_layer_groups(sizes, tb, alpha=0.0, beta=0.0,
                                 min_bytes=0.0)
    assert len(groups) == 6
    assert groups[0] == [0] and groups[-1] == [5]


def test_mgwfbp_tiny_layers_always_merge():
    sizes = [4e6, 10.0, 4e6]   # middle layer tiny
    tb = [1e-3] * 3
    groups = mgwfbp_layer_groups(sizes, tb, alpha=0.0, beta=0.0)
    # tiny layer merged into its successor bucket
    assert any(len(g) > 1 and 1 in g for g in groups)


def test_mgwfbp_partial_merge_structure():
    # fast comm relative to backward: few merges; slow: many. Monotonicity.
    rng = np.random.default_rng(10)
    sizes = list(rng.uniform(1e5, 5e6, size=12))
    tb = list(rng.uniform(5e-4, 2e-3, size=12))
    fast = mgwfbp_layer_groups(sizes, tb, alpha=1e-6, beta=1e-12,
                               min_bytes=0.0)
    slow = mgwfbp_layer_groups(sizes, tb, alpha=5e-3, beta=1e-9,
                               min_bytes=0.0)
    assert len(fast) >= len(slow)
    # coverage: every layer exactly once, contiguous forward order
    flat = [i for g in fast for i in g]
    assert sorted(flat) == list(range(12))


def test_plan_mgwfbp_builds_valid_plan(mesh):
    params = {f"l{i:02d}": {"w": jnp.zeros((256, 4))} for i in range(6)}
    plan = plan_mgwfbp(
        params, world=8,
        layer_times=[1e-3] * 6,
        alpha=1.0, beta=0.0,   # alpha-dominant: one bucket
    )
    assert plan.num_buckets == 1
    assert plan.world == 8
    # and it drops into the train-step builder
    from dear_pytorch_tpu.parallel import build_train_step

    def loss_fn(p, b):
        out = b
        for k in sorted(p):
            out = out @ p[k]["w"] @ p[k]["w"].T
        return jnp.sum(out ** 2)

    ts = build_train_step(loss_fn, params, mesh=mesh, plan=plan,
                          donate=False)
    state = ts.init(params)
    state, m = ts.step(state, jnp.ones((8, 256)))
    assert np.isfinite(float(m["loss"]))
