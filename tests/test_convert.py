"""Torch->JAX BERT conversion parity: a HF ``BertForPreTraining`` built from
a LOCAL config (no network) must produce the same forward outputs as this
framework's flax model under the converted parameters — the migration
contract for reference users (the reference trains exactly this HF class,
dear/bert_benchmark.py:63-86)."""

import numpy as np
import pytest

transformers = pytest.importorskip("transformers")
torch = pytest.importorskip("torch")

import jax.numpy as jnp  # noqa: E402

from dear_pytorch_tpu.models.bert import BertForPreTraining  # noqa: E402
from dear_pytorch_tpu.models.convert import (  # noqa: E402
    config_from_hf,
    convert_bert_from_torch,
)


def _hf_model(vocab_size):
    hf_cfg = transformers.BertConfig(
        vocab_size=vocab_size, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=64,
        max_position_embeddings=64, type_vocab_size=2,
        hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0,
        # our gelu is the tanh approximation (the original BERT's)
        hidden_act="gelu_new",
    )
    torch.manual_seed(0)
    model = transformers.BertForPreTraining(hf_cfg)
    model.eval()
    return model, hf_cfg


@pytest.mark.parametrize("vocab", [48, 50])  # %8==0 and padded cases
def test_forward_parity(vocab):
    model, hf_cfg = _hf_model(vocab)
    cfg = config_from_hf(hf_cfg)
    assert cfg.vocab_size == vocab
    params = convert_bert_from_torch(model.state_dict(), cfg)

    rng = np.random.RandomState(1)
    B, S = 3, 16
    input_ids = rng.randint(0, vocab, (B, S))
    token_type = rng.randint(0, 2, (B, S))
    # real padding in one row to exercise the additive mask path
    mask = np.ones((B, S), np.int64)
    mask[1, 10:] = 0

    with torch.no_grad():
        out = model(
            input_ids=torch.tensor(input_ids),
            token_type_ids=torch.tensor(token_type),
            attention_mask=torch.tensor(mask),
        )
    ref_logits = out.prediction_logits.numpy()
    ref_nsp = out.seq_relationship_logits.numpy()

    got_logits, got_nsp = BertForPreTraining(cfg).apply(
        {"params": params}, jnp.asarray(input_ids),
        jnp.asarray(token_type), jnp.asarray(mask), train=False,
    )
    got_logits = np.asarray(got_logits)

    # padded vocab ids must be numerically dead (bias -1e9)
    if cfg.padded_vocab_size > vocab:
        assert np.all(got_logits[..., vocab:] < -1e8)
    np.testing.assert_allclose(
        got_logits[..., :vocab], ref_logits, rtol=2e-4, atol=2e-4
    )
    np.testing.assert_allclose(
        np.asarray(got_nsp), ref_nsp, rtol=2e-4, atol=2e-4
    )


def test_converted_params_train(mesh):
    """Converted params drop straight into the dear train step."""
    from dear_pytorch_tpu.models import bert_pretraining_loss, data
    from dear_pytorch_tpu.ops.fused_sgd import fused_adamw
    from dear_pytorch_tpu.parallel import build_train_step

    model, hf_cfg = _hf_model(48)
    cfg = config_from_hf(hf_cfg)
    params = convert_bert_from_torch(model.state_dict(), cfg)
    jmodel = BertForPreTraining(cfg)

    def loss_fn(p, b):
        logits, nsp = jmodel.apply(
            {"params": p}, b["input_ids"], b["token_type_ids"],
            b["attention_mask"], train=False,
        )
        return bert_pretraining_loss(
            logits, nsp, b["masked_lm_labels"], b["next_sentence_labels"]
        )

    import jax

    batch = data.synthetic_bert_batch(
        jax.random.PRNGKey(0), 8, seq_len=16, vocab_size=48
    )
    ts = build_train_step(
        loss_fn, params, mesh=mesh, mode="dear", threshold_mb=0.01,
        optimizer=fused_adamw(lr=1e-3), donate=False,
    )
    state = ts.init(params)
    losses = []
    for _ in range(4):
        state, m = ts.step(state, batch)
        losses.append(float(m["loss"]))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0], losses
