"""Torch->JAX BERT conversion parity: a HF ``BertForPreTraining`` built from
a LOCAL config (no network) must produce the same forward outputs as this
framework's flax model under the converted parameters — the migration
contract for reference users (the reference trains exactly this HF class,
dear/bert_benchmark.py:63-86)."""

import numpy as np
import pytest

transformers = pytest.importorskip("transformers")
torch = pytest.importorskip("torch")

import jax.numpy as jnp  # noqa: E402

from dear_pytorch_tpu.models.bert import BertForPreTraining  # noqa: E402
from dear_pytorch_tpu.models.convert import (  # noqa: E402
    config_from_hf,
    convert_bert_from_torch,
)


def _hf_model(vocab_size):
    hf_cfg = transformers.BertConfig(
        vocab_size=vocab_size, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=64,
        max_position_embeddings=64, type_vocab_size=2,
        hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0,
        # our gelu is the tanh approximation (the original BERT's)
        hidden_act="gelu_new",
    )
    torch.manual_seed(0)
    model = transformers.BertForPreTraining(hf_cfg)
    model.eval()
    return model, hf_cfg


@pytest.mark.parametrize("vocab", [48, 50])  # %8==0 and padded cases
def test_forward_parity(vocab):
    model, hf_cfg = _hf_model(vocab)
    cfg = config_from_hf(hf_cfg)
    assert cfg.vocab_size == vocab
    params = convert_bert_from_torch(model.state_dict(), cfg)

    rng = np.random.RandomState(1)
    B, S = 3, 16
    input_ids = rng.randint(0, vocab, (B, S))
    token_type = rng.randint(0, 2, (B, S))
    # real padding in one row to exercise the additive mask path
    mask = np.ones((B, S), np.int64)
    mask[1, 10:] = 0

    with torch.no_grad():
        out = model(
            input_ids=torch.tensor(input_ids),
            token_type_ids=torch.tensor(token_type),
            attention_mask=torch.tensor(mask),
        )
    ref_logits = out.prediction_logits.numpy()
    ref_nsp = out.seq_relationship_logits.numpy()

    got_logits, got_nsp = BertForPreTraining(cfg).apply(
        {"params": params}, jnp.asarray(input_ids),
        jnp.asarray(token_type), jnp.asarray(mask), train=False,
    )
    got_logits = np.asarray(got_logits)

    # padded vocab ids must be numerically dead (bias -1e9)
    if cfg.padded_vocab_size > vocab:
        assert np.all(got_logits[..., vocab:] < -1e8)
    np.testing.assert_allclose(
        got_logits[..., :vocab], ref_logits, rtol=2e-4, atol=2e-4
    )
    np.testing.assert_allclose(
        np.asarray(got_nsp), ref_nsp, rtol=2e-4, atol=2e-4
    )


def test_converted_params_train(mesh):
    """Converted params drop straight into the dear train step."""
    from dear_pytorch_tpu.models import bert_pretraining_loss, data
    from dear_pytorch_tpu.ops.fused_sgd import fused_adamw
    from dear_pytorch_tpu.parallel import build_train_step

    model, hf_cfg = _hf_model(48)
    cfg = config_from_hf(hf_cfg)
    params = convert_bert_from_torch(model.state_dict(), cfg)
    jmodel = BertForPreTraining(cfg)

    def loss_fn(p, b):
        logits, nsp = jmodel.apply(
            {"params": p}, b["input_ids"], b["token_type_ids"],
            b["attention_mask"], train=False,
        )
        return bert_pretraining_loss(
            logits, nsp, b["masked_lm_labels"], b["next_sentence_labels"]
        )

    import jax

    batch = data.synthetic_bert_batch(
        jax.random.PRNGKey(0), 8, seq_len=16, vocab_size=48
    )
    ts = build_train_step(
        loss_fn, params, mesh=mesh, mode="dear", threshold_mb=0.01,
        optimizer=fused_adamw(lr=1e-3), donate=False,
    )
    state = ts.init(params)
    losses = []
    for _ in range(4):
        state, m = ts.step(state, batch)
        losses.append(float(m["loss"]))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0], losses


class _TorchBottleneck(torch.nn.Module):
    """Minimal torch bottleneck with torchvision's exact attribute naming
    (conv1/bn1/conv2/bn2/conv3/bn3/downsample.0/.1) — the checkpoint-format
    contract the converter maps from."""

    def __init__(self, inplanes, planes, stride=1):
        super().__init__()
        nn = torch.nn
        self.conv1 = nn.Conv2d(inplanes, planes, 1, bias=False)
        self.bn1 = nn.BatchNorm2d(planes)
        self.conv2 = nn.Conv2d(planes, planes, 3, stride=stride, padding=1,
                               bias=False)
        self.bn2 = nn.BatchNorm2d(planes)
        self.conv3 = nn.Conv2d(planes, planes * 4, 1, bias=False)
        self.bn3 = nn.BatchNorm2d(planes * 4)
        self.relu = nn.ReLU()
        self.downsample = None
        if stride != 1 or inplanes != planes * 4:
            self.downsample = nn.Sequential(
                nn.Conv2d(inplanes, planes * 4, 1, stride=stride, bias=False),
                nn.BatchNorm2d(planes * 4),
            )

    def forward(self, x):
        idn = x if self.downsample is None else self.downsample(x)
        y = self.relu(self.bn1(self.conv1(x)))
        y = self.relu(self.bn2(self.conv2(y)))
        y = self.bn3(self.conv3(y))
        return self.relu(idn + y)


class _TorchResNet(torch.nn.Module):
    """Tiny torchvision-shaped ResNet (names: conv1/bn1/layerN.M/fc)."""

    def __init__(self, stage_sizes=(1, 1), width=8, num_classes=4):
        super().__init__()
        nn = torch.nn
        self.conv1 = nn.Conv2d(3, width, 7, stride=2, padding=3, bias=False)
        self.bn1 = nn.BatchNorm2d(width)
        self.relu = nn.ReLU()
        self.maxpool = nn.MaxPool2d(3, stride=2, padding=1)
        inplanes = width
        for i, n in enumerate(stage_sizes):
            blocks = []
            for j in range(n):
                stride = 2 if i > 0 and j == 0 else 1
                blocks.append(_TorchBottleneck(inplanes, width * 2**i,
                                               stride))
                inplanes = width * 2**i * 4
            setattr(self, f"layer{i + 1}", nn.Sequential(*blocks))
        self.fc = nn.Linear(inplanes, num_classes)
        self.stage_sizes = stage_sizes

    def forward(self, x):
        x = self.maxpool(self.relu(self.bn1(self.conv1(x))))
        for i in range(len(self.stage_sizes)):
            x = getattr(self, f"layer{i + 1}")(x)
        x = x.mean(dim=(2, 3))
        return self.fc(x)


def test_resnet_forward_parity():
    from dear_pytorch_tpu.models.convert import convert_resnet_from_torch
    from dear_pytorch_tpu.models.resnet import BottleneckBlock, ResNet

    torch.manual_seed(0)
    tmodel = _TorchResNet()
    # randomize BN affine + running stats so identity mappings can't hide
    with torch.no_grad():
        for m in tmodel.modules():
            if isinstance(m, torch.nn.BatchNorm2d):
                m.weight.uniform_(0.5, 1.5)
                m.bias.uniform_(-0.3, 0.3)
                m.running_mean.uniform_(-0.2, 0.2)
                m.running_var.uniform_(0.6, 1.4)
    tmodel.eval()

    params, stats = convert_resnet_from_torch(
        tmodel.state_dict(), stage_sizes=(1, 1)
    )
    jmodel = ResNet(stage_sizes=(1, 1), width=8, num_classes=4,
                    block=BottleneckBlock)

    rng = np.random.RandomState(2)
    x = rng.randn(2, 3, 33, 33).astype(np.float32)  # odd size: padding edge
    with torch.no_grad():
        ref = tmodel(torch.tensor(x)).numpy()
    got = jmodel.apply(
        {"params": params, "batch_stats": stats},
        jnp.asarray(x.transpose(0, 2, 3, 1)), train=False,
    )
    np.testing.assert_allclose(np.asarray(got), ref, rtol=2e-4, atol=2e-4)


def test_resnet_forward_parity_s2d_stem():
    """convert_resnet_from_torch(stem='s2d') loads a torchvision-shaped
    checkpoint into the space-to-depth model with identical outputs
    (even input size — s2d packs 2x2 blocks)."""
    from dear_pytorch_tpu.models.convert import convert_resnet_from_torch
    from dear_pytorch_tpu.models.resnet import BottleneckBlock, ResNet

    torch.manual_seed(1)
    tmodel = _TorchResNet()
    tmodel.eval()
    params, stats = convert_resnet_from_torch(
        tmodel.state_dict(), stage_sizes=(1, 1), stem="s2d"
    )
    assert params["stem_conv"]["kernel"].shape == (4, 4, 12, 8)
    jmodel = ResNet(stage_sizes=(1, 1), width=8, num_classes=4,
                    block=BottleneckBlock, stem="s2d")
    rng = np.random.RandomState(3)
    x = rng.randn(2, 3, 34, 34).astype(np.float32)
    with torch.no_grad():
        ref = tmodel(torch.tensor(x)).numpy()
    got = jmodel.apply(
        {"params": params, "batch_stats": stats},
        jnp.asarray(x.transpose(0, 2, 3, 1)), train=False,
    )
    np.testing.assert_allclose(np.asarray(got), ref, rtol=2e-4, atol=2e-4)


def test_gpt2_forward_parity():
    """HF GPT2LMHeadModel from a local config vs our GptLmHeadModel under
    converted params: logits over the real vocab must match."""
    from dear_pytorch_tpu.models.convert import (
        convert_gpt2_from_torch,
        gpt_config_from_hf,
    )
    from dear_pytorch_tpu.models.gpt import GptLmHeadModel

    hf_cfg = transformers.GPT2Config(
        vocab_size=61, n_positions=64, n_embd=32, n_layer=2, n_head=4,
        embd_pdrop=0.0, resid_pdrop=0.0, attn_pdrop=0.0,
    )
    torch.manual_seed(0)
    tmodel = transformers.GPT2LMHeadModel(hf_cfg)
    tmodel.eval()

    cfg = gpt_config_from_hf(hf_cfg)
    assert cfg.padded_vocab_size == 64
    params = convert_gpt2_from_torch(tmodel.state_dict(), cfg)

    ids = np.random.RandomState(3).randint(0, 61, (2, 16))
    with torch.no_grad():
        ref = tmodel(torch.tensor(ids)).logits.numpy()
    got = GptLmHeadModel(cfg).apply(
        {"params": params}, jnp.asarray(ids), train=False
    )
    np.testing.assert_allclose(
        np.asarray(got)[..., :61], ref, rtol=2e-4, atol=2e-4
    )


def test_gpt2_converted_generation_matches_hf():
    """End-to-end interop: greedy decoding from CONVERTED weights through
    our KV-cache generate() must produce the same tokens as transformers'
    own generate() on the original torch model."""
    from dear_pytorch_tpu.models.convert import (
        convert_gpt2_from_torch,
        gpt_config_from_hf,
    )
    from dear_pytorch_tpu.models.gpt import GptLmHeadModel, generate

    hf_cfg = transformers.GPT2Config(
        vocab_size=61, n_positions=32, n_embd=32, n_layer=2, n_head=4,
        embd_pdrop=0.0, resid_pdrop=0.0, attn_pdrop=0.0,
    )
    torch.manual_seed(1)
    tmodel = transformers.GPT2LMHeadModel(hf_cfg)
    tmodel.eval()
    cfg = gpt_config_from_hf(hf_cfg)
    params = convert_gpt2_from_torch(tmodel.state_dict(), cfg)

    prompt = np.random.RandomState(7).randint(0, 61, (2, 6))
    with torch.no_grad():
        ref = tmodel.generate(
            torch.tensor(prompt), max_new_tokens=8, do_sample=False,
            pad_token_id=0,
        ).numpy()
    got = generate(GptLmHeadModel(cfg), params, jnp.asarray(prompt),
                   max_new_tokens=8)
    np.testing.assert_array_equal(np.asarray(got), ref)


def test_bert_export_roundtrip_into_hf():
    """Train-here-serve-there: exported state_dict loads into a fresh HF
    BertForPreTraining with strict key matching and reproduces our
    forward."""
    from dear_pytorch_tpu.models.convert import (
        bert_to_torch_state_dict,
        config_from_hf,
        convert_bert_from_torch,
    )
    from dear_pytorch_tpu.models.bert import BertForPreTraining

    src, hf_cfg = _hf_model(50)
    cfg = config_from_hf(hf_cfg)
    params = convert_bert_from_torch(src.state_dict(), cfg)

    dst = transformers.BertForPreTraining(hf_cfg)
    exported = {k: torch.tensor(v)
                for k, v in bert_to_torch_state_dict(params, cfg).items()}
    missing, unexpected = dst.load_state_dict(exported, strict=False)
    # position_ids buffers are version-dependent; no WEIGHTS may be absent
    assert not [k for k in missing if "position_ids" not in k], missing
    assert not unexpected, unexpected
    dst.eval()

    ids = np.random.RandomState(20).randint(0, 50, (2, 12))
    with torch.no_grad():
        ref = dst(input_ids=torch.tensor(ids)).prediction_logits.numpy()
    ours, _ = BertForPreTraining(cfg).apply(
        {"params": params}, jnp.asarray(ids), train=False
    )
    np.testing.assert_allclose(np.asarray(ours)[..., :50], ref,
                               rtol=2e-4, atol=2e-4)


def test_gpt2_export_roundtrip_into_hf():
    from dear_pytorch_tpu.models.convert import (
        convert_gpt2_from_torch,
        gpt2_to_torch_state_dict,
        gpt_config_from_hf,
    )
    from dear_pytorch_tpu.models.gpt import GptLmHeadModel

    hf_cfg = transformers.GPT2Config(
        vocab_size=61, n_positions=32, n_embd=32, n_layer=2, n_head=4,
        embd_pdrop=0.0, resid_pdrop=0.0, attn_pdrop=0.0,
    )
    torch.manual_seed(3)
    src = transformers.GPT2LMHeadModel(hf_cfg)
    cfg = gpt_config_from_hf(hf_cfg)
    params = convert_gpt2_from_torch(src.state_dict(), cfg)

    dst = transformers.GPT2LMHeadModel(hf_cfg)
    exported = {k: torch.tensor(v)
                for k, v in gpt2_to_torch_state_dict(params, cfg).items()}
    missing, unexpected = dst.load_state_dict(exported, strict=False)
    # attn.bias causal-mask buffers are constructed, not weights
    assert not [k for k in missing if ".attn.bias" not in k
                and ".attn.masked_bias" not in k], missing
    assert not unexpected, unexpected
    dst.eval()

    ids = np.random.RandomState(21).randint(0, 61, (2, 10))
    with torch.no_grad():
        ref = dst(torch.tensor(ids)).logits.numpy()
    ours = GptLmHeadModel(cfg).apply(
        {"params": params}, jnp.asarray(ids), train=False
    )
    np.testing.assert_allclose(np.asarray(ours)[..., :61], ref,
                               rtol=2e-4, atol=2e-4)


def test_vgg_forward_parity():
    """A torchvision-shaped VGG (features Sequential + classifier.0/3/6)
    converted to the flax model must match, including the NCHW-vs-NHWC
    flatten-order permutation on the first classifier layer."""
    from dear_pytorch_tpu.models.convert import convert_vgg_from_torch

    nn_t = torch.nn
    cfg = (8, "M", 16, 16, "M")
    layers, in_ch = [], 3
    for v in cfg:
        if v == "M":
            layers.append(nn_t.MaxPool2d(2, 2))
        else:
            layers.append(nn_t.Conv2d(in_ch, v, 3, padding=1))
            layers.append(nn_t.ReLU())
            in_ch = v

    torch.manual_seed(0)
    tmodel = nn_t.Sequential()
    tmodel.features = nn_t.Sequential(*layers)
    # 12x12 input -> 3x3x16 features
    tmodel.classifier = nn_t.Sequential(
        nn_t.Linear(16 * 3 * 3, 32), nn_t.ReLU(), nn_t.Dropout(0.5),
        nn_t.Linear(32, 32), nn_t.ReLU(), nn_t.Dropout(0.5),
        nn_t.Linear(32, 4),
    )
    tmodel.eval()

    def tforward(x):
        h = tmodel.features(x)
        return tmodel.classifier(h.flatten(1))

    # our VGG hardcodes 4096-wide fcs; build the same tiny shape directly
    import flax.linen as fnn
    import jax

    class TinyVGG(fnn.Module):
        @fnn.compact
        def __call__(self, x, train=False):
            i = 0
            for v in cfg:
                if v == "M":
                    x = fnn.max_pool(x, (2, 2), strides=(2, 2))
                else:
                    i += 1
                    x = fnn.relu(fnn.Conv(v, (3, 3), name=f"conv{i}")(x))
            x = x.reshape((x.shape[0], -1))
            x = fnn.relu(fnn.Dense(32, name="fc1")(x))
            x = fnn.relu(fnn.Dense(32, name="fc2")(x))
            return fnn.Dense(4, name="fc3")(x)

    # remap classifier indices 0/3/6 onto the converter's expectations
    sd = tmodel.state_dict()
    params = convert_vgg_from_torch(sd)

    x = np.random.RandomState(30).randn(2, 3, 12, 12).astype(np.float32)
    with torch.no_grad():
        ref = tforward(torch.tensor(x)).numpy()
    got = TinyVGG().apply({"params": params},
                          jnp.asarray(x.transpose(0, 2, 3, 1)))
    np.testing.assert_allclose(np.asarray(got), ref, rtol=2e-4, atol=2e-4)

    # the converted tree's structure matches models.vgg.VGG's naming
    assert set(params) == {"conv1", "conv2", "conv3", "fc1", "fc2", "fc3"}


def test_vgg_bn_checkpoint_rejected():
    from dear_pytorch_tpu.models.convert import convert_vgg_from_torch

    sd = {"features.0.weight": np.zeros((8, 3, 3, 3), np.float32),
          "features.1.running_mean": np.zeros((8,), np.float32)}
    with pytest.raises(ValueError, match="vgg.*_bn|BatchNorm"):
        convert_vgg_from_torch(sd)


def test_vit_forward_parity():
    """HF ViTForImageClassification vs our VisionTransformer with converted
    weights: same image, rounding-tight logits (hidden_act='gelu_new'
    matches this zoo's tanh gelu, as in the BERT parity test)."""
    from dear_pytorch_tpu.models.convert import convert_vit_from_torch
    from dear_pytorch_tpu.models.vit import VisionTransformer

    hf_cfg = transformers.ViTConfig(
        hidden_size=32, num_hidden_layers=2, num_attention_heads=4,
        intermediate_size=64, image_size=32, patch_size=8,
        num_labels=7, hidden_act="gelu_new",
        hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0,
    )
    torch.manual_seed(0)
    tmodel = transformers.ViTForImageClassification(hf_cfg).eval()

    rng = np.random.RandomState(0)
    img_nchw = rng.randn(2, 3, 32, 32).astype(np.float32)
    with torch.no_grad():
        ref = tmodel(torch.from_numpy(img_nchw)).logits.numpy()

    ours = VisionTransformer(
        hidden_size=32, num_layers=2, num_heads=4, mlp_dim=64,
        patch=8, num_classes=7,
    )
    params = convert_vit_from_torch(tmodel.state_dict())
    got = ours.apply(
        {"params": params},
        jnp.asarray(img_nchw.transpose(0, 2, 3, 1)),  # NCHW -> NHWC
        train=False,
    )
    np.testing.assert_allclose(np.asarray(got), ref, rtol=2e-4, atol=2e-4)
