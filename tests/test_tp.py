"""Tensor parallelism (GSPMD): a dp×tp BERT train step must equal the
replicated single-mesh step numerically, the weights must actually live
sharded over 'tp', and the partitioner must have inserted cross-device
collectives for the row-parallel matmuls."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dear_pytorch_tpu import models
from dear_pytorch_tpu.models import data as mdata
from dear_pytorch_tpu.models.bert import BertConfig, BertForPreTraining
from dear_pytorch_tpu.parallel import tp as TP
from dear_pytorch_tpu.utils import hlo

TP_DEG, DP_DEG = 4, 2


def _problem():
    cfg = BertConfig(
        num_hidden_layers=2, hidden_size=32, num_attention_heads=4,
        intermediate_size=64, vocab_size=64, max_position_embeddings=32,
        hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0,
    )
    model = BertForPreTraining(cfg)
    batch = mdata.synthetic_bert_batch(
        jax.random.PRNGKey(2), 2 * DP_DEG, seq_len=16, vocab_size=64
    )
    params = model.init(
        {"params": jax.random.PRNGKey(0)}, batch["input_ids"], train=False
    )["params"]

    def loss_fn(p, b):
        logits, nsp = model.apply(
            {"params": p}, b["input_ids"], b["token_type_ids"],
            b["attention_mask"], train=False,
        )
        return models.bert_pretraining_loss(
            logits.astype(jnp.float32), nsp.astype(jnp.float32),
            b["masked_lm_labels"], b["next_sentence_labels"],
        )

    return params, batch, loss_fn


def _mesh2d():
    devs = np.asarray(jax.devices()[: DP_DEG * TP_DEG])
    return jax.sharding.Mesh(devs.reshape(DP_DEG, TP_DEG), ("dp", "tp"))


def _run(mesh, params, batch, loss_fn, steps=4, **tp_kwargs):
    ts = TP.make_tp_train_step(
        loss_fn, params, mesh=mesh, lr=0.05, momentum=0.9, donate=False,
        **tp_kwargs,
    )
    state = ts.init(params)
    losses = []
    for _ in range(steps):
        state, m = ts.step(state, batch)
        losses.append(float(m["loss"]))
    return ts, state, losses


def test_tp_matches_replicated():
    params, batch, loss_fn = _problem()
    mesh1 = jax.sharding.Mesh(
        np.asarray(jax.devices()[:1]).reshape(1, 1), ("dp", "tp")
    )
    _, _, want = _run(mesh1, params, batch, loss_fn)
    _, state, got = _run(_mesh2d(), params, batch, loss_fn)
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)


def test_tp_params_are_sharded():
    params, batch, loss_fn = _problem()
    mesh2 = _mesh2d()
    ts, state, _ = _run(mesh2, params, batch, loss_fn, steps=1)
    qk = state.params["layer_0"]["attention"]["query"]["kernel"]
    spec = qk.sharding.spec
    assert tuple(spec) == (None, "tp", None), spec
    # each device holds 1/TP of the heads dim
    shard = qk.addressable_shards[0].data
    assert shard.shape[1] == qk.shape[1] // TP_DEG
    # layernorms replicated
    ln = state.params["layer_0"]["attention_ln"]["scale"]
    assert all(s is None for s in tuple(ln.sharding.spec)), ln.sharding


def test_tp_partitioner_inserted_collectives():
    params, batch, loss_fn = _problem()
    ts = TP.make_tp_train_step(
        loss_fn, params, mesh=_mesh2d(), donate=False,
    )
    state = ts.init(params)
    text = ts.lower(state, batch).compile().as_text()
    ops = hlo.parse_entry(text)
    # row-parallel matmuls + dp gradient reduction both need all-reduces
    assert len(hlo.find(ops, "all-reduce")) >= 1, "no collectives inserted"


def test_tp_rejects_indivisible():
    params, batch, loss_fn = _problem()
    devs = np.asarray(jax.devices()[:6]).reshape(2, 3)  # heads=4, tp=3
    mesh = jax.sharding.Mesh(devs, ("dp", "tp"))
    with pytest.raises(ValueError, match="divide"):
        TP.make_tp_train_step(loss_fn, params, mesh=mesh)


def test_vit_tp_matches_replicated():
    """dp x tp ViT under VIT_TP_RULES == the replicated step numerically,
    and the attention/MLP weights actually shard over 'tp'."""
    m = models.get_model("vit_s16", num_layers=2, num_classes=8)
    batch = {
        "image": jax.random.normal(
            jax.random.PRNGKey(0), (2 * DP_DEG, 32, 32, 3), jnp.float32
        ),
        "label": jnp.arange(2 * DP_DEG) % 8,
    }
    params = m.init(
        {"params": jax.random.PRNGKey(0)}, batch["image"], train=False
    )["params"]

    def loss_fn(p, b):
        logits = m.apply({"params": p}, b["image"], train=False)
        return mdata.softmax_xent(logits, b["label"])

    mesh1 = jax.sharding.Mesh(
        np.asarray(jax.devices()[:1]).reshape(1, 1), ("dp", "tp")
    )
    _, _, want = _run(mesh1, params, batch, loss_fn, steps=3,
                      rules=TP.VIT_TP_RULES)
    _, state, got = _run(_mesh2d(), params, batch, loss_fn, steps=3,
                         rules=TP.VIT_TP_RULES)
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)

    qk = state.params["block1"]["attn"]["query"]["kernel"]
    assert "tp" in str(qk.sharding.spec), qk.sharding.spec
    mlp_down = state.params["block1"]["mlp_out"]["kernel"]
    assert "tp" in str(mlp_down.sharding.spec), mlp_down.sharding.spec
