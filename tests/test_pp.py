"""Pipeline parallelism: the GPipe microbatch pipeline over a 'pp' mesh
axis must equal running the stages sequentially on one device — forward
loss, gradients, and a full training trajectory."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dear_pytorch_tpu.parallel import pp as PP

N_STAGES = 4
WIDTH = 16
MB = 2          # microbatches
BATCH = 8


def _stage_fn(p, x):
    return jnp.tanh(x @ p["w"] + p["b"])


def _stage_params(key, i):
    return {
        "w": jax.random.normal(jax.random.fold_in(key, i),
                               (WIDTH, WIDTH)) * 0.5,
        "b": jnp.zeros((WIDTH,)),
    }


def _loss_fn(outs, batch):
    _, y = batch
    return jnp.mean((outs - y) ** 2)


def _problem():
    key = jax.random.PRNGKey(0)
    stages = [_stage_params(key, i) for i in range(N_STAGES)]
    x = jax.random.normal(jax.random.PRNGKey(1), (BATCH, WIDTH))
    y = jax.random.normal(jax.random.PRNGKey(2), (BATCH, WIDTH))
    return stages, (x, y)


def _mesh():
    devs = np.asarray(jax.devices()[:N_STAGES])
    return jax.sharding.Mesh(devs.reshape(N_STAGES), (PP.PP_AXIS,))


def _sequential_loss(stages, batch):
    x, _ = batch
    for p in stages:
        x = _stage_fn(p, x)
    return _loss_fn(x, batch)


def test_pipeline_matches_sequential_loss_and_grads():
    stages, batch = _problem()
    want_loss = _sequential_loss(stages, batch)
    want_grads = jax.grad(
        lambda s: _sequential_loss(s, batch)
    )(stages)

    ts = PP.make_pp_train_step(
        _stage_fn, stages, mesh=_mesh(), loss_fn=_loss_fn,
        n_microbatches=MB, donate=False,
    )
    state = ts.init(stages)
    _, m = ts.step(state, batch)
    np.testing.assert_allclose(float(m["loss"]), float(want_loss),
                               rtol=1e-5)

    # gradient check: one SGD step (momentum 0 path: momentum*0+g = g) and
    # compare the parameter delta to -lr * sequential grads
    lr = 0.1
    ts2 = PP.make_pp_train_step(
        _stage_fn, stages, mesh=_mesh(), loss_fn=_loss_fn,
        n_microbatches=MB, lr=lr, momentum=0.0, donate=False,
    )
    st = ts2.init(stages)
    st2, _ = ts2.step(st, batch)
    for i in range(N_STAGES):
        got_delta = (
            np.asarray(st2.params["w"][i]) - np.asarray(stages[i]["w"])
        )
        want_delta = -lr * np.asarray(want_grads[i]["w"])
        np.testing.assert_allclose(got_delta, want_delta, rtol=1e-4,
                                   atol=1e-6)


def test_pipeline_training_matches_sequential_trajectory():
    stages, batch = _problem()
    lr, mom, steps = 0.05, 0.9, 5

    ts = PP.make_pp_train_step(
        _stage_fn, stages, mesh=_mesh(), loss_fn=_loss_fn,
        n_microbatches=MB, lr=lr, momentum=mom, donate=False,
    )
    state = ts.init(stages)
    got = []
    for _ in range(steps):
        state, m = ts.step(state, batch)
        got.append(float(m["loss"]))

    # sequential reference trajectory
    params = [dict(s) for s in stages]
    vel = [jax.tree.map(jnp.zeros_like, s) for s in stages]
    want = []
    lfn = jax.jit(jax.value_and_grad(lambda s: _sequential_loss(s, batch)))
    for _ in range(steps):
        loss, g = lfn(params)
        want.append(float(loss))
        for i in range(N_STAGES):
            vel[i] = jax.tree.map(lambda v, gg: mom * v + gg, vel[i], g[i])
            params[i] = jax.tree.map(
                lambda p, v: p - lr * v, params[i], vel[i]
            )
    np.testing.assert_allclose(got, want, rtol=1e-4)
    assert got[-1] < got[0]


def _mb_loss_fn(y_m, batch_m):
    _, ym = batch_m  # the framework pre-slices every batch leaf
    return jnp.mean((y_m - ym) ** 2)


def test_1f1b_matches_sequential_loss_and_grads():
    """The hand-orchestrated 1F1B backward (O(L) activation residency)
    produces the same loss and gradients as autodiff'd GPipe/sequential."""
    stages, batch = _problem()
    want_loss = _sequential_loss(stages, batch)
    want_grads = jax.grad(lambda s: _sequential_loss(s, batch))(stages)

    lr = 0.1
    ts = PP.make_pp_train_step(
        _stage_fn, stages, mesh=_mesh(), schedule="1f1b",
        mb_loss_fn=_mb_loss_fn, n_microbatches=MB, lr=lr, momentum=0.0,
        donate=False,
    )
    state = ts.init(stages)
    st2, m = ts.step(state, batch)
    np.testing.assert_allclose(float(m["loss"]), float(want_loss),
                               rtol=1e-5)
    for i in range(N_STAGES):
        got_delta = (
            np.asarray(st2.params["w"][i]) - np.asarray(stages[i]["w"])
        )
        want_delta = -lr * np.asarray(want_grads[i]["w"])
        np.testing.assert_allclose(got_delta, want_delta, rtol=1e-4,
                                   atol=1e-6)
        got_db = (
            np.asarray(st2.params["b"][i]) - np.asarray(stages[i]["b"])
        )
        np.testing.assert_allclose(got_db, -lr * np.asarray(
            want_grads[i]["b"]), rtol=1e-4, atol=1e-6)


def test_1f1b_training_trajectory_matches_gpipe():
    stages, batch = _problem()
    lr, mom, steps = 0.05, 0.9, 4
    common = dict(mesh=_mesh(), n_microbatches=MB, lr=lr, momentum=mom,
                  donate=False)
    ts_g = PP.make_pp_train_step(_stage_fn, stages, loss_fn=_loss_fn,
                                 **common)
    ts_i = PP.make_pp_train_step(_stage_fn, stages, schedule="1f1b",
                                 mb_loss_fn=_mb_loss_fn, **common)
    sg, si = ts_g.init(stages), ts_i.init(stages)
    for _ in range(steps):
        sg, mg = ts_g.step(sg, batch)
        si, mi = ts_i.step(si, batch)
        np.testing.assert_allclose(float(mi["loss"]), float(mg["loss"]),
                                   rtol=1e-5)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-6
        ),
        si.params, sg.params,
    )


def test_1f1b_deep_pipeline_many_microbatches():
    """M > L (the regime 1F1B exists for: residency stays O(L) while M
    grows): gradients still match the sequential reference."""
    stages, batch = _problem()
    M = 8  # batch 8 -> microbatch size 1, M twice the stage count
    want_grads = jax.grad(lambda s: _sequential_loss(s, batch))(stages)
    lr = 0.1
    ts = PP.make_pp_train_step(
        _stage_fn, stages, mesh=_mesh(), schedule="1f1b",
        mb_loss_fn=_mb_loss_fn, n_microbatches=M, lr=lr, momentum=0.0,
        donate=False,
    )
    st2, m = ts.step(ts.init(stages), batch)
    np.testing.assert_allclose(
        float(m["loss"]), float(_sequential_loss(stages, batch)), rtol=1e-5
    )
    for i in range(N_STAGES):
        got = np.asarray(st2.params["w"][i]) - np.asarray(stages[i]["w"])
        np.testing.assert_allclose(got, -lr * np.asarray(
            want_grads[i]["w"]), rtol=1e-4, atol=1e-6)


@pytest.mark.parametrize("schedule", ["gpipe", "1f1b"])
def test_dp_pp_composition_matches_sequential(schedule):
    """(dp=2, pp=4) mesh: each dp row pipelines its batch shard; losses and
    stage grads average across rows — equal to sequential full-batch."""
    stages, batch = _problem()
    want_loss = _sequential_loss(stages, batch)
    want_grads = jax.grad(lambda s: _sequential_loss(s, batch))(stages)

    devs = np.asarray(jax.devices()[:8]).reshape(2, 4)
    mesh = jax.sharding.Mesh(devs, ("dp", PP.PP_AXIS))
    lr = 0.1
    kw = dict(mesh=mesh, n_microbatches=MB, lr=lr, momentum=0.0,
              donate=False, dp_axis="dp")
    if schedule == "1f1b":
        kw.update(schedule="1f1b", mb_loss_fn=_mb_loss_fn)
    else:
        kw.update(loss_fn=_loss_fn)
    ts = PP.make_pp_train_step(_stage_fn, stages, **kw)
    st2, m = ts.step(ts.init(stages), batch)
    np.testing.assert_allclose(float(m["loss"]), float(want_loss),
                               rtol=1e-5)
    for i in range(N_STAGES):
        got = np.asarray(st2.params["w"][i]) - np.asarray(stages[i]["w"])
        np.testing.assert_allclose(got, -lr * np.asarray(
            want_grads[i]["w"]), rtol=1e-4, atol=1e-6)


def test_1f1b_uses_less_activation_memory_than_gpipe():
    """The point of 1F1B: per-stage residency is O(L) in-flight
    microbatches (ring buffer) while GPipe's autodiff saves every
    microbatch's activations — XLA's memory analysis shows the temp
    allocation gap, widening as M grows at fixed global batch."""
    stages, _ = _problem()
    B, W = 64, WIDTH
    x, y = jnp.ones((B, W)), jnp.ones((B, W))

    def temp_bytes(schedule, M):
        kw = dict(mesh=_mesh(), n_microbatches=M, donate=False)
        if schedule == "1f1b":
            kw.update(schedule="1f1b", mb_loss_fn=_mb_loss_fn)
        else:
            kw.update(loss_fn=_loss_fn)
        ts = PP.make_pp_train_step(_stage_fn, stages, **kw)
        comp = ts.lower(ts.init(stages), (x, y)).compile()
        return comp.memory_analysis().temp_size_in_bytes

    for M, factor in ((4, 0.7), (32, 0.25)):
        g, i = temp_bytes("gpipe", M), temp_bytes("1f1b", M)
        assert i < factor * g, (M, i, g)


def test_1f1b_option_validation():
    stages, _ = _problem()
    with pytest.raises(ValueError, match="mb_loss_fn"):
        PP.make_pp_train_step(_stage_fn, stages, mesh=_mesh(),
                              schedule="1f1b", n_microbatches=MB)
    with pytest.raises(ValueError, match="loss_fn"):
        PP.make_pp_train_step(_stage_fn, stages, mesh=_mesh(),
                              n_microbatches=MB)
    with pytest.raises(ValueError, match="schedule"):
        PP.make_pp_train_step(_stage_fn, stages, mesh=_mesh(),
                              schedule="zb", loss_fn=_loss_fn,
                              n_microbatches=MB)
    with pytest.raises(ValueError, match="dp_axis"):
        PP.make_pp_train_step(_stage_fn, stages, mesh=_mesh(),
                              loss_fn=_loss_fn, n_microbatches=MB,
                              dp_axis=PP.PP_AXIS)
    with pytest.raises(ValueError, match="mesh axes"):
        PP.make_pp_train_step(_stage_fn, stages, mesh=_mesh(),
                              loss_fn=_loss_fn, n_microbatches=MB,
                              dp_axis="nope")


def test_pipeline_rejects_bad_shapes():
    stages, batch = _problem()
    with pytest.raises(ValueError, match="stages"):
        PP.make_pp_train_step(
            _stage_fn, stages[:2], mesh=_mesh(), loss_fn=_loss_fn,
            n_microbatches=MB,
        )
    ts = PP.make_pp_train_step(
        _stage_fn, stages, mesh=_mesh(), loss_fn=_loss_fn,
        n_microbatches=3,  # 8 % 3 != 0
        donate=False,
    )
    state = ts.init(stages)
    with pytest.raises(ValueError, match="microbatches"):
        ts.step(state, batch)
