"""ViT family: forward contract, dropout determinism, and one dear-mode
training step on the emulated mesh (the zoo-integration invariant every
model family carries)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dear_pytorch_tpu import models


def _tiny_vit(**kw):
    return models.get_model(
        "vit_s16", num_layers=2, dropout_rate=kw.pop("dropout_rate", 0.0),
        **kw,
    )


def test_forward_shape_and_dtypes():
    m = _tiny_vit(dtype=jnp.bfloat16)
    x = jnp.ones((2, 64, 64, 3), jnp.bfloat16)
    v = m.init({"params": jax.random.PRNGKey(0)}, x, train=False)
    out = m.apply(v, x, train=False)
    assert out.shape == (2, 1000)
    assert out.dtype == jnp.float32  # fp32 head per zoo convention
    # params stay fp32 masters
    assert all(p.dtype == jnp.float32 for p in jax.tree.leaves(v["params"]))


def test_patch_divisibility_rejected():
    m = _tiny_vit()
    x = jnp.ones((1, 60, 60, 3), jnp.float32)
    with pytest.raises(ValueError, match="not divisible by patch"):
        m.init({"params": jax.random.PRNGKey(0)}, x, train=False)


def test_dropout_train_vs_eval():
    m = _tiny_vit(dropout_rate=0.3)
    x = jnp.ones((2, 64, 64, 3), jnp.float32)
    v = m.init({"params": jax.random.PRNGKey(0)}, x, train=False)
    e1 = m.apply(v, x, train=False)
    e2 = m.apply(v, x, train=False)
    np.testing.assert_array_equal(np.asarray(e1), np.asarray(e2))
    t1 = m.apply(v, x, train=True, rngs={"dropout": jax.random.PRNGKey(1)})
    t2 = m.apply(v, x, train=True, rngs={"dropout": jax.random.PRNGKey(2)})
    assert np.abs(np.asarray(t1) - np.asarray(t2)).max() > 0


def test_vit_dear_train_step(mesh):
    from dear_pytorch_tpu.models import data
    from dear_pytorch_tpu.ops.fused_sgd import fused_sgd
    from dear_pytorch_tpu.parallel import dear as D

    m = _tiny_vit(dtype=jnp.bfloat16, num_classes=10)
    batch = {
        "image": jax.random.normal(
            jax.random.PRNGKey(0), (8, 32, 32, 3), jnp.bfloat16
        ),
        "label": jnp.arange(8) % 10,
    }
    params = m.init(
        {"params": jax.random.PRNGKey(0)}, batch["image"], train=False
    )["params"]

    def loss_fn(p, b):
        logits = m.apply({"params": p}, b["image"], train=False)
        return data.softmax_xent(logits, b["label"])

    ts = D.build_train_step(
        loss_fn, params, mesh=mesh, mode="dear", threshold_mb=0.5,
        optimizer=fused_sgd(lr=0.05, momentum=0.9),
    )
    state = ts.init(params)
    losses = []
    for _ in range(4):
        state, metrics = ts.step(state, batch)
        losses.append(float(metrics["loss"]))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]  # tiny overfit batch must descend
