"""GPT (decoder-only causal LM) family — beyond the reference zoo.

Pins: the causality property (future tokens cannot influence past logits),
dense vs Pallas-flash causal equivalence, training under the dear schedule,
the padded-vocab loss contract, and the benchmark CLI's scrape-able output.
"""

import re

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dear_pytorch_tpu.models import GptConfig, GptLmHeadModel, gpt_lm_loss
from dear_pytorch_tpu.models.gpt import flash_causal_attention_impl

TINY = GptConfig(
    vocab_size=61,  # odd: exercises padding to 64
    hidden_size=32, num_hidden_layers=2, num_attention_heads=4,
    intermediate_size=64, max_position_embeddings=64,
    embd_dropout_prob=0.0, hidden_dropout_prob=0.0,
    attention_probs_dropout_prob=0.0,
)


def _params(cfg=TINY, seq=16):
    model = GptLmHeadModel(cfg)
    ids = jnp.zeros((1, seq), jnp.int32)
    return model, model.init({"params": jax.random.PRNGKey(0)}, ids,
                             train=False)["params"]


def test_causality():
    """Changing token t+1.. must not change logits at positions <= t."""
    model, params = _params()
    rng = np.random.RandomState(0)
    ids = rng.randint(0, 61, (2, 16))
    t = 7
    ids2 = ids.copy()
    ids2[:, t + 1:] = rng.randint(0, 61, (2, 16 - t - 1))
    a = model.apply({"params": params}, jnp.asarray(ids), train=False)
    b = model.apply({"params": params}, jnp.asarray(ids2), train=False)
    np.testing.assert_allclose(
        np.asarray(a[:, : t + 1]), np.asarray(b[:, : t + 1]),
        rtol=1e-5, atol=1e-6,
    )
    # and they DO differ after t (the model is not degenerate)
    assert not np.allclose(np.asarray(a[:, t + 1:]), np.asarray(b[:, t + 1:]))


def test_flash_causal_matches_dense():
    model, params = _params()
    fmodel = GptLmHeadModel(TINY,
                            attention_impl=flash_causal_attention_impl())
    ids = jnp.asarray(np.random.RandomState(1).randint(0, 61, (2, 16)))
    dense = model.apply({"params": params}, ids, train=False)
    flash = fmodel.apply({"params": params}, ids, train=False)
    np.testing.assert_allclose(np.asarray(flash), np.asarray(dense),
                               rtol=2e-4, atol=2e-4)


def test_padded_vocab_is_dead_in_loss():
    """Loss must equal the unpadded-softmax value: padded ids are masked
    out of the support."""
    model, params = _params()
    ids = jnp.asarray(np.random.RandomState(2).randint(0, 61, (2, 16)))
    logits = model.apply({"params": params}, ids, train=False)
    assert logits.shape[-1] == 64  # padded to vocab_pad_multiple=8
    loss = gpt_lm_loss(logits, ids, vocab_size=61)
    # reference value: softmax over the REAL vocab only
    ref = gpt_lm_loss(logits[..., :61], ids)
    np.testing.assert_allclose(float(loss), float(ref), rtol=1e-6)


def test_gpt_lm_loss_streamed_equivalence():
    """The streamed logsumexp formulation must equal the naive
    mask + log_softmax + gather form in VALUE and GRADIENT (it is the
    same mathematical function, restructured to avoid materializing the
    [B, S, V] log-prob tensor)."""
    rng = np.random.RandomState(3)
    logits = jnp.asarray(rng.randn(2, 9, 64).astype(np.float32)) * 3.0
    ids = jnp.asarray(rng.randint(0, 61, (2, 9)))

    def naive(lg):
        lg = lg[:, :-1]
        targets = ids[:, 1:]
        pad = jnp.arange(lg.shape[-1]) >= 61
        lg = jnp.where(pad[None, None], -1e9, lg)
        logp = jax.nn.log_softmax(lg, axis=-1)
        return jnp.mean(
            -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
        )

    def streamed(lg):
        return gpt_lm_loss(lg, ids, vocab_size=61)

    np.testing.assert_allclose(float(streamed(logits)), float(naive(logits)),
                               rtol=1e-6)
    g_s = jax.grad(streamed)(logits)
    g_n = jax.grad(naive)(logits)
    np.testing.assert_allclose(np.asarray(g_s), np.asarray(g_n),
                               rtol=1e-5, atol=1e-7)


def test_remat_config_same_function():
    """cfg.remat=True must not change values or gradients — only the
    backward-pass memory/recompute tradeoff."""
    import dataclasses

    model, params = _params()
    rmodel = GptLmHeadModel(dataclasses.replace(TINY, remat=True))
    ids = jnp.asarray(np.random.RandomState(5).randint(0, 61, (2, 16)))

    def loss(m):
        def f(p):
            logits = m.apply({"params": p}, ids, train=False)
            return gpt_lm_loss(logits, ids, vocab_size=61)
        return f

    base_v, base_g = jax.value_and_grad(loss(model))(params)
    re_v, re_g = jax.value_and_grad(loss(rmodel))(params)
    np.testing.assert_allclose(float(re_v), float(base_v), rtol=1e-6)
    chex = jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6),
        base_g, re_g,
    )
    del chex


def test_trains_under_dear(mesh):
    from dear_pytorch_tpu.models import data
    from dear_pytorch_tpu.ops.fused_sgd import fused_adamw
    from dear_pytorch_tpu.parallel import build_train_step

    model, params = _params()

    def loss_fn(p, b):
        logits = model.apply({"params": p}, b["input_ids"], train=False)
        return gpt_lm_loss(logits, b["input_ids"], vocab_size=61)

    batch = data.synthetic_gpt_batch(
        jax.random.PRNGKey(3), 8, seq_len=16, vocab_size=61
    )
    ts = build_train_step(
        loss_fn, params, mesh=mesh, mode="dear", threshold_mb=0.01,
        optimizer=fused_adamw(lr=1e-3), donate=False,
    )
    assert ts.plan.num_buckets >= 2
    state = ts.init(params)
    losses = []
    for _ in range(5):
        state, m = ts.step(state, batch)
        losses.append(float(m["loss"]))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0], losses


def test_gpt_cli_output_contract(mesh, capsys):
    from dear_pytorch_tpu.benchmarks import gpt as gpt_bench

    res = gpt_bench.main([
        "--model", "gpt2", "--batch-size", "2", "--sequence-len", "32",
        "--num-hidden-layers", "2", "--num-warmup-batches", "1",
        "--num-batches-per-iter", "2", "--num-iters", "2",
    ])
    out = capsys.readouterr().out
    m = re.search(r"Total sen/sec on (\d+) \w+\(s\): ([\d.]+) \+-([\d.]+)",
                  out)
    assert m, out
    assert int(m.group(1)) == 8
    assert abs(float(m.group(2)) - res.total_mean) < 0.1
    assert re.search(r"Tokens/sec on 8 \w+\(s\): \d+", out), out


def test_kv_cache_decode_matches_full_forward():
    """Stepwise decoding through the KV cache must reproduce the full
    forward's logits at every position — the cache is an optimization, not
    an approximation."""
    model, params = _params()
    ids = jnp.asarray(np.random.RandomState(4).randint(0, 61, (2, 12)))
    full = model.apply({"params": params}, ids, train=False)

    cache = model.init(
        {"params": jax.random.PRNGKey(0)}, ids[:, :1], train=False,
        decode=True,
    )["cache"]
    for t in range(ids.shape[1]):
        step, vars_out = model.apply(
            {"params": params, "cache": cache}, ids[:, t:t + 1],
            train=False, decode=True, position_offset=t, mutable=["cache"],
        )
        cache = vars_out["cache"]
        np.testing.assert_allclose(
            np.asarray(step[:, 0]), np.asarray(full[:, t]),
            rtol=2e-4, atol=2e-4,
        )


def test_generate_greedy_matches_iterated_argmax():
    from dear_pytorch_tpu.models.gpt import generate

    model, params = _params()
    prompt = jnp.asarray(np.random.RandomState(5).randint(0, 61, (2, 5)))
    out = generate(model, params, prompt, max_new_tokens=6)
    assert out.shape == (2, 11)
    np.testing.assert_array_equal(np.asarray(out[:, :5]),
                                  np.asarray(prompt))
    # reference: repeatedly run the FULL forward and take argmax
    cur = prompt
    for _ in range(6):
        logits = model.apply({"params": params}, cur, train=False)
        nxt = jnp.argmax(logits[:, -1, :61], axis=-1).astype(cur.dtype)
        cur = jnp.concatenate([cur, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(cur))
    # sampled ids never leave the real vocab (padding masked)
    assert int(jnp.max(out)) < 61


def test_generate_temperature_sampling_runs():
    from dear_pytorch_tpu.models.gpt import generate

    model, params = _params()
    prompt = jnp.asarray(np.random.RandomState(6).randint(0, 61, (1, 4)))
    out = generate(model, params, prompt, max_new_tokens=5,
                   temperature=0.8, rng=jax.random.PRNGKey(1))
    assert out.shape == (1, 9)
    assert int(jnp.max(out)) < 61
    with pytest.raises(ValueError, match="rng"):
        generate(model, params, prompt, max_new_tokens=2, temperature=0.5)


def test_top_p_filter_properties():
    """Nucleus filter: the most-probable token always survives; with a
    tiny top_p only it survives; with top_p=1 nothing is filtered; sampled
    ids stay inside the filtered support."""
    from dear_pytorch_tpu.models.gpt import _top_p_filter, generate

    logits = jnp.asarray([[2.0, 1.0, 0.5, -1.0], [0.0, 3.0, 2.9, -2.0]])
    tight = _top_p_filter(logits, 1e-6)
    # only the argmax survives a near-zero nucleus
    np.testing.assert_array_equal(
        np.asarray(jnp.isfinite(tight)),
        np.asarray(jax.nn.one_hot(jnp.argmax(logits, -1), 4) > 0),
    )
    full = _top_p_filter(logits, 1.0)
    np.testing.assert_array_equal(np.asarray(jnp.isfinite(full)),
                                  np.ones((2, 4), bool))
    # mid nucleus keeps a prefix of the sorted tokens (monotone support)
    mid = _top_p_filter(logits, 0.7)
    kept = np.asarray(jnp.isfinite(mid))
    assert kept[0].sum() >= 1 and kept[1].sum() >= 1
    assert kept[0, 0] and kept[1, 1]  # argmax rows kept

    model, params = _params()
    prompt = jnp.asarray(np.random.RandomState(8).randint(0, 61, (1, 4)))
    out = generate(model, params, prompt, max_new_tokens=4,
                   temperature=0.9, top_p=0.9, rng=jax.random.PRNGKey(2))
    assert out.shape == (1, 8) and int(jnp.max(out)) < 61
    with pytest.raises(ValueError, match="top_p"):
        generate(model, params, prompt, max_new_tokens=2, top_p=0.0)


def test_moe_gpt_trains_with_expert_parallelism(mesh):
    """GptConfig(num_experts>0) swaps every block's MLP for the switch
    MoE; training runs through the GSPMD machinery with EP_RULES so the
    expert dim shards over an 'ep' axis. Causality must survive routing
    (each token routes on its own hidden state), loss must decrease, and
    the expert weights must actually be sharded."""
    import dataclasses

    from dear_pytorch_tpu.models import data
    from dear_pytorch_tpu.parallel import ep as EP
    from dear_pytorch_tpu.parallel import tp as TP

    cfg = dataclasses.replace(
        TINY, num_experts=4,
        expert_capacity_factor=8.0,  # no token drops: deterministic tests
    )
    model = GptLmHeadModel(cfg)
    ids = jnp.asarray(np.random.RandomState(9).randint(0, 61, (2, 16)))
    params = model.init({"params": jax.random.PRNGKey(0)}, ids,
                        train=False)["params"]
    assert params["h_0"]["moe"]["wi"].shape == (4, 32, 64)

    # causality holds under routing
    ids2 = np.asarray(ids).copy()
    ids2[:, 9:] = np.random.RandomState(10).randint(0, 61, (2, 7))
    a = model.apply({"params": params}, ids, train=False)
    b = model.apply({"params": params}, jnp.asarray(ids2), train=False)
    np.testing.assert_allclose(np.asarray(a[:, :9]), np.asarray(b[:, :9]),
                               rtol=1e-5, atol=1e-6)

    meshep = jax.sharding.Mesh(
        np.asarray(jax.devices()[:8]).reshape(2, 4), ("dp", "ep")
    )
    batch = data.synthetic_gpt_batch(jax.random.PRNGKey(5), 8, seq_len=16,
                                     vocab_size=61)

    def loss_fn(p, b):
        logits = model.apply({"params": p}, b["input_ids"], train=False)
        return gpt_lm_loss(logits, b["input_ids"], vocab_size=61)

    ts = TP.make_tp_train_step(
        lambda p, b: loss_fn(p, b), params, mesh=meshep,
        rules=EP.EP_RULES, tp_axis="ep", lr=0.05,
        batch_spec=jax.P("dp"),
    )
    state = ts.init(params)
    wi = state.params["h_0"]["moe"]["wi"]
    assert wi.addressable_shards[0].data.shape[0] == 1  # 4 experts / 4 'ep'
    losses = []
    for _ in range(5):
        state, m = ts.step(state, batch)
        losses.append(float(m["loss"]))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0], losses


def test_moe_kv_cache_decode_matches_full_forward():
    """MoE blocks through the KV-cache decode path: with drop-free routing
    (expert_capacity_factor >= num_experts) stepwise decode must equal the
    full forward — the capacity collapse at T=B tokens per tick must not
    zero colliding tokens."""
    import dataclasses

    cfg = dataclasses.replace(TINY, num_experts=4,
                              expert_capacity_factor=8.0)
    model = GptLmHeadModel(cfg)
    ids = jnp.asarray(np.random.RandomState(11).randint(0, 61, (4, 10)))
    params = model.init({"params": jax.random.PRNGKey(0)}, ids,
                        train=False)["params"]
    full = model.apply({"params": params}, ids, train=False)
    cache = model.init(
        {"params": jax.random.PRNGKey(0)}, ids[:, :1], train=False,
        decode=True,
    )["cache"]
    for t in range(ids.shape[1]):
        step, vars_out = model.apply(
            {"params": params, "cache": cache}, ids[:, t:t + 1],
            train=False, decode=True, position_offset=t, mutable=["cache"],
        )
        cache = vars_out["cache"]
        np.testing.assert_allclose(
            np.asarray(step[:, 0]), np.asarray(full[:, t]),
            rtol=2e-4, atol=2e-4,
        )


def test_checkpointed_attention_matches_dense():
    """The attention-only-remat impl is the SAME function as dense causal
    attention — identical logits and gradients (only backward memory
    changes)."""
    from dear_pytorch_tpu.models.gpt import checkpointed_causal_attention_impl

    model, params = _params()
    cmodel = GptLmHeadModel(TINY,
                            attention_impl=checkpointed_causal_attention_impl())
    ids = jnp.asarray(np.random.RandomState(8).randint(0, 61, (2, 16)))

    def loss(m):
        def f(p):
            return gpt_lm_loss(m.apply({"params": p}, ids, train=False),
                               ids, vocab_size=61)
        return f

    v0, g0 = jax.value_and_grad(loss(model))(params)
    v1, g1 = jax.value_and_grad(loss(cmodel))(params)
    np.testing.assert_allclose(float(v1), float(v0), rtol=1e-6)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6),
        g0, g1,
    )
