"""Native runtime tests: C++ pipeline builds, produces statistically sound
batches concurrently, and the numpy fallback is interface-identical."""

import math

import numpy as np
import pytest

from dear_pytorch_tpu.runtime import (
    NumpyPipeline,
    Pipeline,
    SyntheticSpec,
    bert_spec,
    image_spec,
    mnist_spec,
    native_available,
    now_ns,
)


def test_native_library_builds():
    if not native_available():
        from dear_pytorch_tpu.runtime import build as B

        err = B.load_error() or ""
        if "loader mismatch" in err or "compile failed" in err:
            # environmental, not a code break: a prebuilt .so linked
            # against a different glibc than this container's AND no
            # local toolchain to rebuild with — skip with the reason
            # instead of carrying a known-environmental red
            pytest.skip(f"native library unavailable here: {err}")
    # the environment ships g++; the native path must actually build here
    assert native_available()


def test_now_ns_monotonic():
    a = now_ns()
    b = now_ns()
    assert b >= a > 0


@pytest.mark.parametrize("cls", [Pipeline, NumpyPipeline])
def test_mnist_batch_shapes_and_ranges(cls):
    with cls(mnist_spec(32), seed=7) as p:
        batch = p.next()
    assert batch["image"].shape == (32, 28, 28, 1)
    assert batch["image"].dtype == np.float32
    assert batch["label"].shape == (32,)
    assert batch["label"].dtype == np.int32
    assert 0 <= batch["label"].min() and batch["label"].max() < 10


def test_normal_statistics():
    with Pipeline(image_spec(8, image_size=64, classes=100), seed=3) as p:
        batch = p.next()
    x = batch["image"]
    n = x.size
    assert abs(float(x.mean())) < 5.0 / math.sqrt(n)
    assert abs(float(x.std()) - 1.0) < 0.02
    assert 0 <= batch["label"].min() and batch["label"].max() < 100


def test_bert_batch_contract():
    with Pipeline(bert_spec(16, 64, vocab=1000, masked_fraction=0.5),
                  seed=1) as p:
        b = p.next()
    assert b["input_ids"].shape == (16, 64)
    assert b["input_ids"].max() < 1000 and b["input_ids"].min() >= 0
    assert (b["token_type_ids"] == 0).all()
    assert (b["attention_mask"] == 1).all()
    lab = b["masked_lm_labels"]
    frac = float((lab != -1).mean())
    assert 0.35 < frac < 0.65  # ~masked_fraction
    assert lab.max() < 1000
    assert set(np.unique(b["next_sentence_labels"])) <= {0, 1}


def test_batches_vary_and_production_counts():
    with Pipeline(mnist_spec(4), nslots=3, nthreads=2, seed=9) as p:
        b1 = p.next()
        b2 = p.next()
        assert not np.array_equal(b1["image"], b2["image"])
        for _ in range(10):
            p.next()
        assert p.produced >= 12


def test_slot_recycling_does_not_corrupt_copies():
    with Pipeline(mnist_spec(2), nslots=2, nthreads=2, seed=4) as p:
        first = p.next()
        snapshot = first["image"].copy()
        for _ in range(8):  # force slot reuse
            p.next()
        np.testing.assert_array_equal(first["image"], snapshot)


def test_feeds_train_step(mesh):
    """Pipeline output drives the real train step (end-to-end host->device)."""
    import jax
    import jax.numpy as jnp

    from dear_pytorch_tpu import models
    from dear_pytorch_tpu.models.data import softmax_xent
    from dear_pytorch_tpu.parallel import build_train_step

    model = models.MnistNet()
    with Pipeline(mnist_spec(16), seed=0) as p:
        b0 = p.next()
        params = model.init({"params": jax.random.PRNGKey(0)},
                            jnp.asarray(b0["image"]), train=False)["params"]

        def loss_fn(pr, b, rng):
            logp = model.apply({"params": pr}, b["image"], train=True,
                               rngs={"dropout": rng})
            return softmax_xent(logp, b["label"])

        ts = build_train_step(loss_fn, params, mesh=mesh, threshold_mb=None,
                              rng_seed=0, donate=False)
        state = ts.init(params)
        for _ in range(3):
            batch = {k: jnp.asarray(v) for k, v in p.next().items()}
            state, m = ts.step(state, batch)
        assert np.isfinite(float(m["loss"]))
