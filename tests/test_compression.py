"""Compression tests: payload round-trips, residual/error-feedback algebra,
distributed sparse reductions (allgather-accumulate, gTop-k, majority vote),
and end-to-end compressed training. The reference had no asserts for any of
this (verification was eyeballing printed norms, SURVEY.md §4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dear_pytorch_tpu.comm import collectives as C
from dear_pytorch_tpu.comm.backend import DP_AXIS
from dear_pytorch_tpu.ops import compression as Z


def test_registry_names():
    for name in ("none", "topk", "eftopk", "gaussian", "signum", "efsignum"):
        assert Z.get_compressor(name).name == name
    assert Z.get_compressor(None).name == "none"
    with pytest.raises(KeyError):
        Z.get_compressor("bogus")


def test_topk_selects_largest_and_is_stateless():
    comp = Z.get_compressor("topk")
    x = jnp.array([0.1, -5.0, 0.2, 3.0, -0.3, 0.05, 2.0, -0.01])
    state = comp.init(8, x.dtype)
    assert state == ()  # plain topk carries no residual buffer
    payload, new_state = comp.compress(x, state, density=3 / 8)
    assert new_state == ()
    dense = comp.decompress(payload, 8, x.dtype)
    # the three largest-|.| coordinates survive
    np.testing.assert_allclose(
        np.asarray(dense), [0, -5.0, 0, 3.0, 0, 0, 2.0, 0], atol=1e-7
    )


def test_eftopk_residual_is_unsent_mass():
    comp = Z.get_compressor("eftopk")
    x = jnp.array([0.1, -5.0, 0.2, 3.0, -0.3, 0.05, 2.0, -0.01])
    payload, residual = comp.compress(x, comp.init(8, x.dtype), density=3 / 8)
    dense = comp.decompress(payload, 8, x.dtype)
    # residual keeps exactly the unsent mass: dense + residual == x
    np.testing.assert_allclose(
        np.asarray(dense + residual), np.asarray(x), atol=1e-7
    )


def test_eftopk_error_feedback_accumulates():
    comp = Z.get_compressor("eftopk")
    state = comp.init(4, jnp.float32)
    x = jnp.array([1.0, 0.4, 0.3, 0.2])
    # k=1: only the 1.0 goes out; 0.4/0.3/0.2 accumulate in the residual
    payload, state = comp.compress(x, state, density=0.25)
    assert float(comp.decompress(payload, 4, jnp.float32)[0]) == 1.0
    # second round with zero grad: pure error feedback — the carried 0.4
    # residual is now the biggest entry and gets sent
    payload, state = comp.compress(jnp.zeros(4), state, density=0.25)
    dense = comp.decompress(payload, 4, jnp.float32)
    assert float(dense[1]) == pytest.approx(0.4)


def test_gaussian_capacity_and_residual():
    comp = Z.get_compressor("gaussian")
    rng = np.random.default_rng(10)
    x = jnp.asarray(rng.normal(size=1024).astype(np.float32))
    state = comp.init(1024, jnp.float32)
    payload, residual = comp.compress(x, state, density=0.05)
    assert payload["values"].shape == (51,)  # static capacity k
    dense = comp.decompress(payload, 1024, jnp.float32)
    kept = np.count_nonzero(np.asarray(dense))
    assert 0 < kept <= 51
    # selected mass is removed from the residual
    np.testing.assert_allclose(
        np.asarray(dense + residual), np.asarray(x), atol=1e-6
    )


def test_sign_pack_unpack_roundtrip():
    rng = np.random.default_rng(10)
    for n in (5, 32, 33, 1000):
        x = jnp.asarray(rng.normal(size=n).astype(np.float32))
        words = Z.pack_signs(x)
        assert words.shape == ((n + 31) // 32,) and words.dtype == jnp.uint32
        signs = Z.unpack_signs(words, n)
        np.testing.assert_array_equal(
            np.asarray(signs), np.where(np.asarray(x) >= 0, 1.0, -1.0)
        )


def test_efsignum_residual():
    comp = Z.get_compressor("efsignum")
    x = jnp.array([0.3, -2.0])
    state = comp.init(2, jnp.float32)
    payload, state = comp.compress(x, state, density=1.0)
    # residual = x - sign(x)
    np.testing.assert_allclose(np.asarray(state), [0.3 - 1.0, -2.0 + 1.0],
                               atol=1e-7)


# ---------------------------------------------------------------------------
# distributed reductions (8 emulated devices)
# ---------------------------------------------------------------------------


def _stacked(rng, world, n):
    return jnp.asarray(rng.normal(size=(world, n)).astype(np.float32))


def test_sparse_allreduce_equals_dense_at_density_1(mesh, world, rng):
    n = 64
    x = _stacked(rng, world, n)

    def per_device(t):
        comp = Z.get_compressor("topk")
        payload, _ = comp.compress(t, comp.init(n, t.dtype), density=1.0)
        return Z.sparse_allreduce(payload, n, t.dtype, DP_AXIS)

    got = C.spmd_call(per_device, x, mesh=mesh)
    want = np.mean(np.asarray(x), axis=0)
    np.testing.assert_allclose(np.asarray(got[0]), want, rtol=1e-5, atol=1e-6)


def test_gtopk_matches_topk_of_sum(mesh, world, rng):
    n, k = 64, 8
    x = _stacked(rng, world, n)

    def per_device(t):
        comp = Z.get_compressor("topk")
        payload, _ = comp.compress(t, comp.init(n, t.dtype), density=k / n)
        return Z.gtopk_sparse_allreduce(payload, n, t.dtype, DP_AXIS, k)[0]

    got = np.asarray(C.spmd_call(per_device, x, mesh=mesh))
    # every device agrees
    for d in range(1, world):
        np.testing.assert_allclose(got[0], got[d], atol=1e-6)
    # nonzero support has size <= k and each kept coordinate's value is the
    # mean of per-device contributions that survived each round; at density
    # k/n with random data the algorithm approximates topk(sum)/world — check
    # the support is a subset of the true top-2k of the partial-sums surface
    assert np.count_nonzero(got[0]) <= k


def test_sign_majority_vote(mesh, world):
    n = 40
    # make device d's tensor all +1 for d < 5, all -1 otherwise: majority +1
    x = jnp.concatenate(
        [jnp.ones((5, n)), -jnp.ones((world - 5, n))], axis=0
    )

    def per_device(t):
        words = Z.pack_signs(t)
        return Z.sign_majority_vote_allreduce(words, n, t.dtype, DP_AXIS)

    got = np.asarray(C.spmd_call(per_device, x, mesh=mesh))
    np.testing.assert_array_equal(got, np.ones((world, n), np.float32))


# ---------------------------------------------------------------------------
# end-to-end: compressed training step
# ---------------------------------------------------------------------------


def _mlp_problem():
    from tests.test_dear_numerics import _data, _loss_fn, _mlp_params

    params = _mlp_params(jax.random.PRNGKey(0))
    batches = [_data(jax.random.PRNGKey(100 + i)) for i in range(6)]
    return params, batches, _loss_fn


@pytest.mark.parametrize("name,gtopk", [("eftopk", False), ("eftopk", True),
                                        ("efsignum", False)])
def test_compressed_training_learns(mesh, world, name, gtopk):
    from dear_pytorch_tpu.ops.fused_sgd import fused_sgd
    from dear_pytorch_tpu.parallel import build_train_step

    params, batches, loss_fn = _mlp_problem()
    lr = 0.003 if name == "efsignum" else 0.1  # signSGD needs a small lr
    ts = build_train_step(
        loss_fn, params, mesh=mesh, mode="allreduce",
        optimizer=fused_sgd(lr=lr, momentum=0.9),
        threshold_mb=0.0008,
        compressor=name, density=0.25, gtopk=gtopk, donate=False,
    )
    state = ts.init(params)
    losses = []
    for _ in range(8):  # fixed batch: isolate optimization from batch noise
        state, m = ts.step(state, batches[0])
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0], (name, losses)
    if name == "eftopk":
        # residual state exists, is per-device (sharded), and is nonzero
        res = state.comp_state[0]
        assert res.shape[0] == world
        assert np.abs(np.asarray(res)).sum() > 0


def test_compression_mode_guards(mesh):
    """Compression composes with 'allreduce' AND 'dear'; every other
    schedule rejects it at plan-build time — dear-fused with its own
    loud message (the ring kernels cannot exchange packed payloads; a
    silent dense fallback would fake compressed-trial timings)."""
    from dear_pytorch_tpu.parallel import build_train_step

    params, batches, loss_fn = _mlp_problem()
    with pytest.raises(ValueError, match="ring kernels"):
        build_train_step(loss_fn, params, mesh=mesh, mode="dear-fused",
                         compressor="eftopk", density=0.1)
    for mode in ("rsag", "rb", "bytescheduler", "fsdp"):
        with pytest.raises(ValueError, match="allreduce"):
            build_train_step(loss_fn, params, mesh=mesh, mode=mode,
                             compressor="topk", density=0.1)
    with pytest.raises(ValueError, match="top-k"):
        build_train_step(loss_fn, params, mesh=mesh, mode="allreduce",
                         compressor="signum", gtopk=True)
    with pytest.raises(ValueError, match="exclude_parts"):
        build_train_step(loss_fn, params, mesh=mesh, mode="dear",
                         compressor="eftopk", density=0.1,
                         exclude_parts=("allgather",))


def test_qint8_roundtrip_and_error_feedback():
    comp = Z.get_compressor("qint8")
    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.normal(size=256).astype(np.float32))
    state = comp.init(256, jnp.float32)
    payload, residual = comp.compress(x, state, density=1.0)
    assert payload["q"].dtype == jnp.int8
    dense = comp.decompress(payload, 256, jnp.float32)
    # 8-bit symmetric quantization: max error <= scale/2 per coordinate
    scale = float(payload["scale"])
    np.testing.assert_allclose(np.asarray(dense), np.asarray(x),
                               atol=scale / 2 + 1e-7)
    # error feedback carries exactly the quantization error
    np.testing.assert_allclose(np.asarray(dense + residual), np.asarray(x),
                               atol=1e-6)


def test_int8_allreduce_approximates_mean(mesh, world, rng):
    n = 128
    x = _stacked(rng, world, n)

    def per_device(t):
        comp = Z.get_compressor("qint8")
        payload, _ = comp.compress(t, comp.init(n, t.dtype), density=1.0)
        return Z.int8_allreduce(payload, n, t.dtype, DP_AXIS)

    got = np.asarray(C.spmd_call(per_device, x, mesh=mesh))
    want = np.mean(np.asarray(x), axis=0)
    # every device agrees bitwise; values match the true mean within the
    # summed per-device quantization error
    for d in range(1, world):
        np.testing.assert_array_equal(got[0], got[d])
    tol = float(np.max(np.abs(np.asarray(x)))) / 127.0
    np.testing.assert_allclose(got[0], want, atol=tol)


def test_wire_ratio_accounting():
    n = 1024
    assert Z.wire_ratio(None, n, 1.0) == 1.0
    assert Z.wire_ratio("eftopk", n, 0.01) == pytest.approx(
        (10 * 8) / (n * 4))
    assert Z.wire_ratio("signum", n, 1.0) == pytest.approx(1 / 32)
    assert Z.wire_ratio("qint8", n, 1.0) == pytest.approx(
        (n + 4) / (4 * n))
    assert Z.wire_ratio("custom_thing", n, 1.0) == 1.0  # conservative


# ---------------------------------------------------------------------------
# the live 'dear' training path: all six compressors (satellite — they were
# benchmark-only before the plan-space autotuner wired them into the bucket
# legs of parallel/dear.py)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize(
    "name", ["topk", "eftopk", "gaussian", "signum", "efsignum", "qint8"])
def test_all_compressors_train_on_dear(mesh, world, name):
    """Every registry compressor is reachable from the real training path
    (mode='dear', sharded buffers) and still optimizes: the bucket's
    gradient leg becomes a compressed reduction and each device keeps its
    reduce-scatter slice of the reconstructed dense mean."""
    from dear_pytorch_tpu.ops.fused_sgd import fused_sgd
    from dear_pytorch_tpu.parallel import build_train_step

    params, batches, loss_fn = _mlp_problem()
    lr = 0.003 if "sign" in name else 0.1
    ts = build_train_step(
        loss_fn, params, mesh=mesh, mode="dear",
        optimizer=fused_sgd(lr=lr, momentum=0.9),
        threshold_mb=0.0008,   # multi-bucket: the shard slicing is real
        compressor=name, density=0.25, donate=False,
    )
    assert ts.plan.num_buckets > 1
    state = ts.init(params)
    losses = []
    for _ in range(8):
        state, m = ts.step(state, batches[0])
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0], (name, losses)
    if name in ("eftopk", "gaussian", "efsignum", "qint8"):
        # error-feedback state exists, is per-device, and is nonzero
        res = jax.tree.leaves(state.comp_state[0])[0]
        assert res.shape[0] == world
        assert np.abs(np.asarray(res)).sum() > 0


@pytest.mark.parametrize("name", ["eftopk", "qint8"])
def test_dear_error_feedback_survives_checkpoint_and_rescale(
        mesh, world, name, tmp_path):
    """Acceptance: error-feedback state survives the checkpoint
    save/restore roundtrip bit-exactly on the same plan, and an elastic
    rescale to a smaller world carries it mass-preservingly
    (``sum(rows)/world`` invariant — `_repack_comp_state`)."""
    from dear_pytorch_tpu.ops import fusion as F
    from dear_pytorch_tpu.ops.fused_sgd import fused_sgd
    from dear_pytorch_tpu.parallel import build_train_step
    from dear_pytorch_tpu.utils import checkpoint as ckpt

    params, batches, loss_fn = _mlp_problem()
    opt = fused_sgd(lr=0.1, momentum=0.9)
    ts = build_train_step(
        loss_fn, params, mesh=mesh, mode="dear", optimizer=opt,
        threshold_mb=0.0008, compressor=name, density=0.25, donate=False,
    )
    state = ts.init(params)
    for i in range(3):
        state, _ = ts.step(state, batches[i])
    res_leaves = [np.asarray(x) for x in jax.tree.leaves(state.comp_state)]
    assert sum(float(np.abs(r).sum()) for r in res_leaves) > 0

    d = str(tmp_path / "ck")
    ckpt.save_checkpoint(d, state, ts.plan)
    restored = ckpt.restore_checkpoint(d, ts, template=ts.init(params))
    for a, b in zip(res_leaves, jax.tree.leaves(restored.comp_state)):
        np.testing.assert_array_equal(a, np.asarray(b))
    # training continues from the restored residuals
    restored, m = ts.step(restored, batches[3])
    assert np.isfinite(float(m["loss"]))

    # elastic rescale to half the world: residual contribution to the
    # mean gradient (sum over rows / world) is exactly preserved
    half = world // 2
    plan_h = F.rescale_plan(ts.plan, half)
    mesh_h = jax.sharding.Mesh(np.asarray(jax.devices()[:half]), (DP_AXIS,))
    ts_h = build_train_step(
        loss_fn, params, plan=plan_h, mesh=mesh_h, mode="dear",
        optimizer=opt, compressor=name, density=0.25, donate=False,
    )
    r_h = ckpt.elastic_restore(d, ts_h)

    def contribution(comp, w):
        return sum(float(np.asarray(x).sum())
                   for x in jax.tree.leaves(comp)) / w

    np.testing.assert_allclose(
        contribution(r_h.comp_state, half),
        sum(float(r.sum()) for r in res_leaves) / world,
        rtol=1e-4, atol=1e-6)
    smaller = jax.tree.map(lambda x: x[: x.shape[0] // 2], batches[4])
    r_h, m = ts_h.step(r_h, smaller)
    assert np.isfinite(float(m["loss"]))


def test_gtopk_error_feedback_preserves_rejected_mass(mesh, world):
    """Coordinates a device SENT but the global top-k REJECTED must return
    to its error-feedback residual (reference wfbp/dopt.py:726-728) —
    without the re-add their gradient mass is silently discarded."""
    from dear_pytorch_tpu.ops.fused_sgd import fused_sgd
    from dear_pytorch_tpu.parallel import build_train_step

    n = 32
    params = {"w": jnp.zeros((n,), jnp.float32)}
    # device d's gradient: value (d+1) at indices {2d, 2d+1}. Local top-2
    # sends exactly those; the global top-2 keeps only the last device's
    # {2(w-1), 2(w-1)+1}.
    c = np.zeros((world, n), np.float32)
    for d in range(world):
        c[d, 2 * d] = d + 1.0
        c[d, 2 * d + 1] = d + 1.0
    batch = jnp.asarray(c)

    def loss_fn(p, b):
        return jnp.sum(p["w"] * b[0])

    ts = build_train_step(
        loss_fn, params, mesh=mesh, mode="allreduce",
        compressor="eftopk", density=2 / n, gtopk=True,
        threshold_mb=None, donate=False,
        optimizer=fused_sgd(lr=0.1),
    )
    state = ts.init(params)
    state, _ = ts.step(state, batch)
    res = np.asarray(state.comp_state[0])  # (world, padded)
    for d in range(world - 1):  # globally rejected: mass back in residual
        np.testing.assert_allclose(
            res[d, 2 * d : 2 * d + 2], c[d, 2 * d : 2 * d + 2], rtol=1e-6
        )
    w = world - 1  # globally kept: applied to params, NOT residualized
    np.testing.assert_allclose(res[w, 2 * w : 2 * w + 2], 0.0, atol=1e-7)
    # nothing leaked anywhere else
    mask = np.zeros((world, n), bool)
    for d in range(world - 1):
        mask[d, 2 * d : 2 * d + 2] = True
    np.testing.assert_allclose(res[:, :n][~mask], 0.0, atol=1e-7)
