"""Auto-tuning tests: the numpy GP+EI optimizer, the step-driven Tuner
protocol, wait-time split flags, and live re-bucketing with state repack
(the reference could only validate tuning live on a cluster)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dear_pytorch_tpu.ops.fused_sgd import fused_sgd
from dear_pytorch_tpu.parallel import build_train_step
from dear_pytorch_tpu.tuning import (
    AutoTuner,
    BayesianOptimizer,
    Tuner,
    estimate_layer_backward_times,
    wait_time_flags,
)
from dear_pytorch_tpu.tuning.autotune import repack_state

from tests.test_dear_numerics import _baseline, _data, _loss_fn, _mlp_params


def test_bayesian_optimizer_minimizes_quadratic():
    f = lambda x: 0.1 + ((x - 70.0) / 100.0) ** 2
    opt = BayesianOptimizer((1.0, 256.0), seed=3)
    x = 25.0
    for _ in range(12):
        opt.register(x, f(x))
        x = opt.suggest()
        assert 1.0 <= x <= 256.0
    best_x, best_y = opt.best
    assert best_y <= f(25.0)  # improved on the starting point
    assert abs(best_x - 70.0) < 40.0  # homed into the basin


def test_tuner_protocol_with_fake_clock():
    # iteration time depends on the current threshold; minimum near 64
    state = {"t": 0.0, "x": 25.0}

    def clock():
        return state["t"]

    tuner = Tuner(x=25.0, bound=(1.0, 256.0), max_num_steps=6, interval=5,
                  log=lambda s: None, clock=clock)

    def iter_time(x):
        return 0.1 + abs(x - 64.0) / 640.0

    proposals = []
    for _ in range(200):
        if tuner.finished:
            break
        state["t"] += iter_time(state["x"])
        p = tuner.step()
        if p is not None:
            proposals.append(p)
            state["x"] = p
    assert tuner.finished
    assert len(proposals) >= 2
    assert all(1.0 <= p <= 256.0 for p in proposals)
    # the adopted point (last proposal) is at least as good as the start
    assert iter_time(proposals[-1]) <= iter_time(25.0) + 1e-9


def test_wait_time_flags_every_cycle():
    # 9 layers x 2ms, cycle 5ms: walking backward, a split lands every 3
    # layers; forward-order flags mark bucket starts
    flags = wait_time_flags([0.002] * 9, cycle_time_s=0.005)
    assert flags[0] == 1
    assert sum(flags) == 3
    # plan_by_flags consumes them (layer atomicity preserved)
    from dear_pytorch_tpu.ops import fusion as F

    params = {f"l{i:02d}": {"w": jnp.zeros((4,))} for i in range(9)}
    plan = F.plan_by_flags(params, world=8, flags=flags)
    assert plan.num_buckets == 3


def test_estimate_layer_times_proportional_to_bytes():
    params = {"a_small": {"w": jnp.zeros((10,))},
              "b_big": {"w": jnp.zeros((1000,))}}
    t = estimate_layer_backward_times(params)
    assert len(t) == 2
    assert t[1] / t[0] == pytest.approx(100.0)


def test_repack_preserves_numerics(mesh):
    """Re-bucketing mid-run must not disturb training: momentum and params
    survive the plan change, so losses keep matching the no-rebucket
    baseline step for step."""
    params = _mlp_params(jax.random.PRNGKey(0))
    batches = [_data(jax.random.PRNGKey(100 + i)) for i in range(6)]
    _, ref_losses = _baseline(params, batches, lr=0.1, momentum=0.9, steps=6)

    opt = fused_sgd(lr=0.1, momentum=0.9)
    ts1 = build_train_step(_loss_fn, params, mesh=mesh, optimizer=opt,
                           threshold_mb=None, donate=False)  # single bucket
    ts2 = build_train_step(_loss_fn, params, mesh=mesh, optimizer=opt,
                           nearby_layers=1, donate=False)
    assert ts1.plan.num_buckets != ts2.plan.num_buckets

    state = ts1.init(params)
    losses = []
    for b in batches[:3]:
        state, m = ts1.step(state, b)
        losses.append(float(m["loss"]))
    state = repack_state(state, ts1, ts2)
    assert int(state.step) == 3  # step counter carried
    for b in batches[3:6]:
        state, m = ts2.step(state, b)
        losses.append(float(m["loss"]))
    np.testing.assert_allclose(losses, ref_losses, rtol=1e-5, atol=1e-6)


def test_autotuner_bo_rebuilds_and_learns(mesh):
    """Plan-rebuild behavior of the BO loop, with the trial RNG pinned.

    Deflaked (seed-identical flake since r01): the old assertion gated on
    ``losses[-1] < losses[0]`` — a loss-trajectory threshold that the
    re-bucketing trial schedule does not guarantee step-for-step — so it
    failed intermittently on identical seeds. What the test actually
    covers is the TUNER: trials are proposed, a different threshold forces
    a real re-bucketing, state survives it, and the run finishes with the
    trial budget consumed — asserted directly, plus numerics-only checks
    (every loss finite; the repack exactness itself is covered by
    test_repack_preserves_numerics)."""
    params = _mlp_params(jax.random.PRNGKey(0))
    batches = [_data(jax.random.PRNGKey(100 + i)) for i in range(5)]

    # fake clock driven by call count (deterministic, fast)
    state_t = {"t": 0.0}

    def clock():
        state_t["t"] += 0.01
        return state_t["t"]

    # start at per-layer bucketing (0.0008 MB); every threshold in the bound
    # fuses the whole 0.004 MB model into one bucket, so the first proposal
    # forces a real re-bucketing
    at = AutoTuner(
        _loss_fn, params, strategy="bo", threshold_mb=0.0008,
        bound=(0.005, 0.02), max_trials=2, interval=5,
        mesh=mesh, optimizer=fused_sgd(lr=0.1, momentum=0.9), donate=False,
        clock=clock, tuner_seed=0,
    )
    assert at.ts.plan.num_buckets > 1  # per-layer start
    state = at.init(params)
    losses = []
    for i in range(30):
        state, m = at.step(state, batches[i % 5])
        losses.append(float(m["loss"]))
    assert at.rebuilds >= 1  # the tuner actually tried another plan
    assert at.tuner.finished  # ...and consumed its whole trial budget
    assert all(np.isfinite(x) for x in losses)  # repacks never broke a step
    assert int(state.step) == 30  # the step counter survived every rebuild


def test_autotuner_wait_time_switches_plan(mesh):
    params = _mlp_params(jax.random.PRNGKey(0))
    batches = [_data(jax.random.PRNGKey(100 + i)) for i in range(5)]
    at = AutoTuner(
        _loss_fn, params, strategy="wait_time",
        cycle_time_s=1e-9,  # absurdly small cycle: every layer splits
        warmup_steps=2,
        mesh=mesh, optimizer=fused_sgd(lr=0.1, momentum=0.9), donate=False,
    )
    state = at.init(params)
    assert at.ts.plan.num_buckets == 1  # starts fused-all (nearby=-1)
    for i in range(4):
        state, m = at.step(state, batches[i % 5])
    assert at.rebuilds == 1
    assert at.ts.plan.num_buckets == 3  # one bucket per layer now
    assert int(state.step) == 4
