"""Worker body for the 2-process CPU cluster test (launched by
tests/test_multiprocess.py, one subprocess per rank).

Exercises the REAL multi-process branches that single-process tests can
only early-return from: `jax.distributed` bootstrap through `dear.init()`,
`backend.barrier`, `api.broadcast_parameters` (fabric broadcast), host-level
`collectives.allreduce`, and a dear-mode train step over a global mesh whose
devices live in different processes (reference equivalence: the
mpirun-driven common/comm_core/tests/test_comm.py invariants).
"""

import os
import sys

os.environ.pop("DEAR_DISABLE_DISTRIBUTED", None)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import jax.numpy as jnp  # noqa: E402


def main() -> None:
    import dear_pytorch_tpu as dear
    from dear_pytorch_tpu.comm import backend
    from dear_pytorch_tpu.comm import collectives as C
    from dear_pytorch_tpu.ops.fused_sgd import fused_sgd
    from dear_pytorch_tpu.parallel import build_train_step

    mesh = dear.init()  # multi-process branch: jax.distributed.initialize
    n = int(os.environ["JAX_NUM_PROCESSES"])
    pid = jax.process_index()
    assert jax.process_count() == n, (jax.process_count(), n)
    assert backend.size() == n and backend.rank() == pid
    assert mesh.shape[backend.DP_AXIS] == jax.device_count()
    # TPU-pod shape: several addressable devices per process when the
    # launcher exports DEAR_NUM_CPU_DEVICES (emulating chips-per-host)
    want_local = int(os.environ.get("DEAR_NUM_CPU_DEVICES") or 1)
    assert jax.local_device_count() == want_local, (
        jax.local_device_count(), want_local,
    )

    backend.barrier()  # multi-process sync_global_devices branch

    # rank-0-decides contract: every process starts with different values,
    # all end with rank 0's (reference dear_dopt.py:400-425)
    params = {"w": jnp.full((4,), float(pid)), "b": jnp.ones((2,)) * (pid + 1)}
    out = dear.broadcast_parameters(params)
    np.testing.assert_allclose(np.asarray(out["w"]), 0.0)
    np.testing.assert_allclose(np.asarray(out["b"]), 1.0)

    # start-state contract for the optimizer too (reference
    # dear_dopt.py:428-544): host-side state with mixed float/int leaves,
    # perturbed per rank, must come back as rank 0's everywhere
    opt_state = {
        "momentum": {"w": np.full((3, 2), float(pid)),
                     "b": np.full((2,), float(pid))},
        "step": np.asarray(pid, np.int32),
    }
    synced = dear.broadcast_optimizer_state(opt_state)
    np.testing.assert_allclose(np.asarray(synced["momentum"]["w"]), 0.0)
    np.testing.assert_allclose(np.asarray(synced["momentum"]["b"]), 0.0)
    assert int(synced["step"]) == 0

    # host-level allreduce helper (metrics aggregation across processes)
    got = C.allreduce(np.array([1.0 + pid]), average=True)
    np.testing.assert_allclose(np.asarray(got), [1.0 + (n - 1) / 2.0])
    got = C.allreduce(np.array([1.0 + pid]), average=False)
    np.testing.assert_allclose(np.asarray(got), [n + n * (n - 1) / 2.0])

    # dear-mode train step over the global mesh: devices in DIFFERENT
    # processes jointly reduce-scatter/all-gather. Same params everywhere
    # (same seed); per-process batch shards differ.
    def loss_fn(p, b):
        x, y = b
        pred = jnp.tanh(x @ p["w1"]) @ p["w2"]
        return jnp.mean((pred - y) ** 2)

    k = jax.random.PRNGKey(0)
    tparams = {
        "w1": jax.random.normal(k, (8, 16)) * 0.3,
        "w2": jax.random.normal(jax.random.fold_in(k, 1), (16, 4)) * 0.3,
    }
    ts = build_train_step(
        loss_fn, tparams, mesh=mesh, mode="dear", threshold_mb=0.0001,
        optimizer=fused_sgd(lr=0.05, momentum=0.9), donate=False,
    )
    state = ts.init(tparams)
    # identical global batch on every process; device_put shards it
    bk = jax.random.PRNGKey(7)
    batch = (
        jax.random.normal(bk, (4 * jax.device_count(), 8)),
        jax.random.normal(jax.random.fold_in(bk, 1), (4 * jax.device_count(), 4)),
    )
    losses = []
    for _ in range(4):
        state, m = ts.step(state, batch)
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0], losses

    # explicit cross-process staging (the CLIs' path): each process
    # materializes only its addressable shards of the host-global batch
    from dear_pytorch_tpu.benchmarks import runner

    sharding = jax.sharding.NamedSharding(mesh, jax.P(backend.DP_AXIS))
    staged = runner.stage_global(
        {"x": np.asarray(batch[0]), "y": np.asarray(batch[1])}, sharding
    )
    assert staged["x"].shape == batch[0].shape  # global logical shape
    local = sum(s.data.shape[0] for s in staged["x"].addressable_shards)
    assert local == batch[0].shape[0] // n  # only this host's rows live here
    state, m = ts.step(state, (staged["x"], staged["y"]))
    assert np.isfinite(float(m["loss"]))

    # every process computed the identical loss sequence (the collectives
    # actually coupled them)
    from jax.experimental import multihost_utils

    all_losses = np.asarray(
        multihost_utils.process_allgather(jnp.asarray(losses))
    )
    np.testing.assert_allclose(
        all_losses, np.tile(all_losses[0], (n, 1)), rtol=1e-6
    )

    # fsdp (ZeRO-3 shape) across the process boundary: AD-transposed
    # parameter gathers + grad reduce-scatters cross hosts; one step must
    # be finite and identical everywhere (verdict-r4 #5 asked for a
    # cross-process fsdp leg alongside the dear one)
    if os.environ.get("DEAR_MP_FSDP", "1").strip() not in ("0", ""):
        tsf = build_train_step(
            loss_fn, tparams, mesh=mesh, mode="fsdp", threshold_mb=0.0001,
            optimizer=fused_sgd(lr=0.05, momentum=0.9), donate=False,
        )
        stf = tsf.init(tparams)
        stf, mf = tsf.step(stf, batch)
        f_loss = float(mf["loss"])
        assert np.isfinite(f_loss)
        from jax.experimental import multihost_utils as mhu

        f_all = np.asarray(mhu.process_allgather(jnp.asarray([f_loss])))
        np.testing.assert_allclose(f_all, np.tile(f_all[0], (n, 1)),
                                   rtol=1e-6)

    # sequence parallelism ACROSS processes: a dp x sp mesh whose sp axis
    # spans the process boundary, causal ring attention rotating K/V
    # between hosts via ppermute — one GPT train step must be finite and
    # identical on every process (long-context multi-host evidence the
    # reference has no analog for)
    from dear_pytorch_tpu.models import data as gdata
    from dear_pytorch_tpu.models.gpt import GptConfig, GptLmHeadModel
    from dear_pytorch_tpu.parallel import sp as SP

    devs = jax.devices()
    sp_enabled = os.environ.get("DEAR_MP_SP", "1").strip() not in ("0", "")
    if sp_enabled and len(devs) >= 2:
        sp_deg = 2
        # transpose so the sp axis pairs devices from DIFFERENT processes
        # (a straight reshape would pair each process's own local devices
        # and the ring ppermute would never cross the host boundary)
        grid = (
            np.asarray(devs[: 2 * (len(devs) // 2)])
            .reshape(sp_deg, len(devs) // 2).T
        )
        meshsp = jax.sharding.Mesh(grid, ("dp", "sp"))
        cfg = GptConfig(
            vocab_size=32, hidden_size=16, num_hidden_layers=2,
            num_attention_heads=2, intermediate_size=32,
            max_position_embeddings=8, embd_dropout_prob=0.0,
            hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0,
        )
        gbatch = gdata.synthetic_gpt_batch(
            jax.random.PRNGKey(4), 2 * meshsp.shape["dp"], seq_len=8,
            vocab_size=32,
        )
        gparams = GptLmHeadModel(cfg).init(
            {"params": jax.random.PRNGKey(0)}, gbatch["input_ids"],
            train=False,
        )["params"]
        tssp = build_train_step(
            SP.make_sp_gpt_loss_fn(
                SP.sp_gpt_model(cfg, attention="ring"),
                vocab_size=32, train=False,
            ),
            gparams, mesh=meshsp, axis_name=("dp", "sp"),
            mean_axes=("dp",), batch_spec_fn=SP.bert_sp_batch_specs,
            threshold_mb=0.01, optimizer=fused_sgd(lr=0.05, momentum=0.9),
            donate=False,
        )
        shardings = jax.tree.map(
            lambda s: jax.sharding.NamedSharding(meshsp, s),
            SP.bert_sp_batch_specs(gbatch),
        )
        gbatch = jax.tree.map(
            lambda x, sh: runner.stage_global(np.asarray(x), sh),
            gbatch, shardings,
        )
        stsp = tssp.init(gparams)
        sp_losses = []
        for _ in range(2):
            stsp, msp = tssp.step(stsp, gbatch)
            sp_losses.append(float(msp["loss"]))
        assert all(np.isfinite(sp_losses)), sp_losses
        gathered = np.asarray(
            multihost_utils.process_allgather(jnp.asarray(sp_losses))
        )
        np.testing.assert_allclose(
            gathered, np.tile(gathered[0], (n, 1)), rtol=1e-6
        )

    print(f"MP_WORKER_OK rank={pid}/{n}", flush=True)


if __name__ == "__main__":
    sys.exit(main())
