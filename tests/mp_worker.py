"""Worker body for the 2-process CPU cluster test (launched by
tests/test_multiprocess.py, one subprocess per rank).

Exercises the REAL multi-process branches that single-process tests can
only early-return from: `jax.distributed` bootstrap through `dear.init()`,
`backend.barrier`, `api.broadcast_parameters` (fabric broadcast), host-level
`collectives.allreduce`, and a dear-mode train step over a global mesh whose
devices live in different processes (reference equivalence: the
mpirun-driven common/comm_core/tests/test_comm.py invariants).

``DEAR_MP_MODE=health`` runs the run-health ladder (flight recorder +
cluster metric aggregation + anomaly detection + streaming exporters over
a REAL 2-process cluster, host-level only): one rank is artificially
slowed mid-run and every rank must agree — through the digest exchange
riding the guard's health-check cadence — on WHICH rank is the straggler;
the slow rank must raise ``health.step_time_spike``; a watchdog kick must
ship the flight ring (with redacted env context); the prom/stream
exporters must have been fed on the check cadence.

``DEAR_MP_MODE=resilience`` runs the coordinated-recovery ladder instead
(`resilience.cluster` through a real 2-process `GuardedTrainer`): each
rank trains an independent replica (local mesh, per-host checkpoint
directory via ``DEAR_CKPT_SHARED=0``) and ALL recovery coordination is
host-level — which keeps the ladder runnable even where the XLA CPU
backend cannot execute cross-process device collectives. Legs: a
rank-LOCAL NaN and a rank-LOCAL raised exception must produce the SAME
rollback on every rank; a newest checkpoint corrupted on ONE host must
degrade both ranks to the newest commonly verified step (no crash); a
diverging replica must trip the desync sentinel and be rolled back into
lockstep; a SIGTERM on one rank must propagate into a cooperative
emergency save on all ranks.
"""

import os
import sys

os.environ.pop("DEAR_DISABLE_DISTRIBUTED", None)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import jax.numpy as jnp  # noqa: E402


def _resilience_main() -> None:
    """Coordinated multi-host recovery over a REAL 2-process cluster.

    Each rank trains its own replica on a LOCAL mesh (lockstep comes from
    identical seeds/batches, as in data-parallel training) with a
    PER-HOST checkpoint directory — so a rank-local fault really is
    local, a corrupted checkpoint really is one host's view, and every
    recovery decision must flow through `resilience.cluster`'s host-level
    consensus. Every leg asserts that all ranks end in the identical
    recovered state (the DeAR lockstep invariant)."""
    import json

    import dear_pytorch_tpu as dear
    from dear_pytorch_tpu.observability import tracer as T
    from dear_pytorch_tpu.ops.fused_sgd import fused_sgd
    from dear_pytorch_tpu.parallel import build_train_step
    from dear_pytorch_tpu.resilience import (
        Fault, FaultInjector, PreemptionHandler, corrupt_latest_checkpoint,
    )
    from dear_pytorch_tpu.resilience import cluster as CL
    from dear_pytorch_tpu.utils import checkpoint as ckpt
    from dear_pytorch_tpu.utils.guard import GuardedTrainer

    os.environ["DEAR_CKPT_SHARED"] = "0"  # per-host checkpoint storage
    dear.init()  # joins the cluster: the coordination service comes alive
    n = int(os.environ["JAX_NUM_PROCESSES"])
    pid = jax.process_index()
    assert jax.process_count() == n and ckpt.per_host_storage()
    workdir = os.path.join(os.environ["DEAR_MP_WORKDIR"], f"rank{pid}")

    tracer = T.Tracer([T.MemoryExporter()])
    T.set_tracer(tracer)

    # host-level assertion collective: every rank must hold the same values
    probe = CL.ClusterCoordinator(namespace="assert")

    def assert_replicated(tag, vals):
        views = probe.exchange(tag, json.dumps([float(v) for v in vals]))
        ref = json.loads(views[0])
        for v in views[1:]:
            np.testing.assert_allclose(json.loads(v), ref, rtol=1e-6)

    # replica training is process-local: collectives over a 1-device mesh
    mesh = jax.sharding.Mesh(np.asarray(jax.local_devices()), ("dp",))

    def loss_fn(p, b):
        x, y = b
        pred = jnp.tanh(x @ p["w1"]) @ p["w2"]
        return jnp.mean((pred - y) ** 2)

    k = jax.random.PRNGKey(0)
    tparams = {
        "w1": jax.random.normal(k, (8, 16)) * 0.3,
        "w2": jax.random.normal(jax.random.fold_in(k, 1), (16, 4)) * 0.3,
    }
    ts = build_train_step(
        loss_fn, tparams, mesh=mesh, mode="dear", threshold_mb=0.0001,
        optimizer=fused_sgd(lr=0.05, momentum=0.9), donate=False,
    )

    bk = jax.random.PRNGKey(7)

    def batch_at(i):
        kk = jax.random.fold_in(bk, i)
        return (jax.random.normal(kk, (8, 8)),
                jax.random.normal(jax.random.fold_in(kk, 1), (8, 4)))

    def run_leg(subdir, injector, steps, batch_fn=batch_at, preemption=None):
        tr = GuardedTrainer(
            ts, os.path.join(workdir, subdir), tparams,
            check_every=1, checkpoint_every=4, injector=injector,
            preemption=preemption,
        )
        assert tr._coordinated, "2-process guard must auto-coordinate"
        rolls, losses = [], []
        tr.on_rollback = lambda c, at: rolls.append(at)
        state = ts.init(tparams)
        last_m = {}
        for i in range(steps):
            state, last_m = tr.step(state, batch_fn(i))
            if not last_m.get("rolled_back"):
                losses.append(float(last_m["loss"]))
            if last_m.get("preempted"):
                break
        return tr, state, rolls, losses, last_m

    # leg A — NaN on rank 1 ONLY: rank 0's replica is perfectly healthy,
    # yet the health sync must roll BOTH ranks back to the same step.
    inj = FaultInjector([Fault(kind="nan", step=6, rank=1)])
    _, _, rolls, losses, _ = run_leg("legA", inj, 8)
    assert rolls == [4], rolls
    assert_replicated("legA.roll", [rolls[0]])
    assert_replicated("legA.loss", losses[-2:])  # resumed in lockstep
    if pid == 1:
        assert [f.kind for f in inj.fired] == ["nan"] and not inj.skipped
    else:
        assert not inj.fired and [f.kind for f in inj.skipped] == ["nan"]

    # leg B — raised exception on rank 0 ONLY (host-side, pre-dispatch):
    # the old policy crashed the whole job for relaunch; now the failing
    # rank completes the step, defers to the sync, and BOTH ranks roll
    # back to the identical step and resume to matching losses.
    inj = FaultInjector([Fault(kind="exc", step=6, rank=0)])
    _, _, rolls, losses, _ = run_leg("legB", inj, 8)
    assert rolls == [4], rolls
    assert_replicated("legB.roll", [rolls[0]])
    assert_replicated("legB.loss", losses[-2:])

    # leg C — newest checkpoint corrupted on ONE host: rank 0's local
    # walk sees only step 4 while rank 1 still verifies {8, 4}; consensus
    # must restore the newest COMMONLY verified step (4) on both ranks,
    # with no crash (the ISSUE acceptance scenario).
    tr, state, rolls, _, _ = run_leg("legC", None, 8)  # ckpts at 4 and 8
    if pid == 0:
        assert corrupt_latest_checkpoint(os.path.join(workdir, "legC")) == 8
        assert ckpt.valid_steps(os.path.join(workdir, "legC")) == [4]
    else:
        assert ckpt.valid_steps(os.path.join(workdir, "legC")) == [8, 4]
    x, y = batch_at(9)
    state, m = tr.step(state, (jnp.full_like(x, jnp.nan), y))
    assert m.get("rolled_back"), m
    restored = int(jax.device_get(state.step))
    assert restored == 4, restored  # past the corrupted 8, on BOTH ranks
    assert_replicated("legC.step", [restored])

    # leg D — desync sentinel end to end: rank 1 trains one step on the
    # WRONG batch (a diverging dataloader); every loss stays finite, yet
    # the fingerprint exchange flags the divergence and rolls both ranks
    # back into lockstep.
    def skewed(i):
        if pid == 1 and i == 5:  # attempt 6: silently divergent input
            return batch_at(1000 + i)
        return batch_at(i)

    before = tracer.counters().get("cluster.desync_detected", 0)
    _, _, rolls, losses, _ = run_leg("legD", None, 8, batch_fn=skewed)
    assert rolls == [4], rolls
    assert tracer.counters().get("cluster.desync_detected", 0) > before
    assert_replicated("legD.loss", losses[-2:])  # back in lockstep

    # leg E — preemption propagation: SIGTERM lands on rank 1 only; the
    # sync propagates it and BOTH ranks perform the cooperative emergency
    # save at the same boundary.
    inj = FaultInjector([Fault(kind="preempt", step=6, rank=1)])
    with PreemptionHandler() as pre:
        _, state, _, _, m = run_leg("legE", inj, 10, preemption=pre)
    assert m.get("preempted"), m
    saved = m.get("preempt_checkpoint_step")
    assert saved == int(jax.device_get(state.step)) == 6, (saved, m)
    assert ckpt.latest_valid_step(os.path.join(workdir, "legE")) == 6
    assert_replicated("legE.saved", [saved])

    # leg F — coordinator primitives against hand-built divergent views
    co = CL.ClusterCoordinator(namespace="probe")
    assert co.consensus_restore_step([8, 4] if pid == 0 else [4]) == 4
    v = co.health_check(ok=True, fingerprint=f"fp{pid}", step=1)
    assert v.desync and not v.ok
    v = co.health_check(ok=(pid != 1), step=2, preempted=(pid == 1))
    assert v.unhealthy_ranks == (1,) and v.any_preempted and not v.ok
    v = co.health_check(ok=True, fingerprint="same", step=3)
    assert v.ok and not v.desync

    print(f"MP_RESILIENCE_OK rank={pid}/{n}", flush=True)


def _health_main() -> None:
    """Continuous run-health over a REAL 2-process cluster (ISSUE-4
    acceptance): rank 1 is artificially slowed from mid-run; the digest
    exchange riding the guard's health-check cadence must produce a
    merged snapshot naming rank 1 as the straggler (on rank 0 — and,
    since the merge is a pure function of the gathered views, identically
    everywhere); the slow rank's anomaly monitor must raise
    ``health.step_time_spike``; watchdog forensics must carry the
    flight ring with redacted env; the prom/stream exporters must have
    been fed. All coordination is HOST-level (the coordination-service KV
    store), so this runs where cross-process XLA CPU computations
    don't exist."""
    import time

    import dear_pytorch_tpu as dear
    from dear_pytorch_tpu.observability import export as EX
    from dear_pytorch_tpu.observability import flight as FL
    from dear_pytorch_tpu.observability import tracer as T
    from dear_pytorch_tpu.ops.fused_sgd import fused_sgd
    from dear_pytorch_tpu.parallel import build_train_step
    from dear_pytorch_tpu.resilience import StepWatchdog
    from dear_pytorch_tpu.utils import read_metrics
    from dear_pytorch_tpu.utils.guard import GuardedTrainer

    os.environ["DEAR_CKPT_SHARED"] = "0"  # per-host checkpoint storage
    dear.init()
    n = int(os.environ["JAX_NUM_PROCESSES"])
    pid = jax.process_index()
    assert jax.process_count() == n
    workdir = os.path.join(os.environ["DEAR_MP_WORKDIR"], f"rank{pid}")

    # the acceptance scenario runs through the env grammar end to end:
    # the launcher set DEAR_TELEMETRY=1 and DEAR_FLIGHT=16, so the
    # tracer/ring resolve themselves; the streaming sinks are rank-local
    # paths, attached through the exporter protocol
    prom_path = os.path.join(workdir, "dear.prom")
    stream_path = os.path.join(workdir, "health.jsonl")
    tracer = T.get_tracer()
    assert tracer.enabled, "DEAR_TELEMETRY must be set for health mode"
    tracer.add_exporter(EX.PromFileExporter(prom_path))
    tracer.add_exporter(EX.HealthStreamExporter(stream_path))
    assert FL.get_recorder().enabled and FL.get_recorder().capacity == 16

    # replica training is process-local: collectives over a 1-device mesh
    mesh = jax.sharding.Mesh(np.asarray(jax.local_devices()), ("dp",))

    def loss_fn(p, b):
        x, y = b
        pred = jnp.tanh(x @ p["w1"]) @ p["w2"]
        return jnp.mean((pred - y) ** 2)

    k = jax.random.PRNGKey(0)
    tparams = {
        "w1": jax.random.normal(k, (8, 16)) * 0.3,
        "w2": jax.random.normal(jax.random.fold_in(k, 1), (16, 4)) * 0.3,
    }
    ts = build_train_step(
        loss_fn, tparams, mesh=mesh, mode="dear", threshold_mb=0.0001,
        optimizer=fused_sgd(lr=0.05, momentum=0.9), donate=False,
    )
    bk = jax.random.PRNGKey(7)

    def batch_at(i):
        kk = jax.random.fold_in(bk, i)
        return (jax.random.normal(kk, (8, 8)),
                jax.random.normal(jax.random.fold_in(kk, 1), (8, 4)))

    dog = StepWatchdog(deadline_s=300, name="health-watchdog").start()
    # check_every=3, not 2: rank 0 waits for the slow rank inside every
    # health exchange, and that wait lands in rank 0's OWN flight-ring
    # step gaps — at check_every=2 half of rank 0's ring would be
    # exchange waits and its p50 would chase the straggler's
    guard = GuardedTrainer(
        ts, os.path.join(workdir, "ckpts"), tparams,
        check_every=3, checkpoint_every=1000, watchdog=dog,
    )
    assert guard._coordinated, "2-process guard must auto-coordinate"
    assert guard._aggregator is not None and guard._anomaly is not None
    assert guard._flight.enabled

    state = ts.init(tparams)
    # the slowdown must be unmistakable against container-scheduler noise
    # (an early ~0.2s hiccup inflates the warmup EWMA): 0.5s against
    # ~5ms steps, with DEAR_HEALTH_Z=3 from the launcher
    steps, slow_from, slow_s = 18, 8, 0.5
    for i in range(steps):
        if pid == 1 and i >= slow_from:
            time.sleep(slow_s)  # the artificially slowed rank
        state, m = guard.step(state, batch_at(i))
        assert not m.get("rolled_back"), m

    # 1) the merged rank-0 snapshot names the straggler (identical on
    #    every rank: the merge is a pure function of the gathered views)
    merged = guard.merged_health
    assert merged is not None and merged["world"] == n, merged
    assert merged["straggler_rank"] == 1, merged
    assert merged["straggler_skew"] >= merged["skew_threshold"], merged
    assert merged["counters"].get("cluster.health_checks", 0) > 0, merged
    # the fleet's step-time quantiles rode along in the per-rank digests
    assert merged["per_rank"][1]["st"]["p50_s"] >= slow_s * 0.8, merged

    # 2) the slow rank's anomaly monitor fired on the step-time jump
    if pid == 1:
        assert tracer.counters().get("health.step_time_spike", 0) >= 1, \
            tracer.counters()

    # 3) watchdog forensics ship the flight ring + redacted env (the
    #    "hung rank" dump path, triggered via the immediate-kick API)
    report = dog.kick("health probe")
    dog.stop()
    assert report.flight, "kick report must carry the flight ring"
    assert report.flight[-1]["step"] == guard.steps_seen
    assert any("step_time_s" in r for r in report.flight)
    assert report.env.get("DEAR_MP_FAKE_TOKEN") == "[redacted]", report.env

    # 4) streaming exporters were fed on the check cadence
    prom = open(prom_path).read()
    assert "dear_cluster_health_checks" in prom, prom[:500]
    assert "dear_step_time_p50_seconds" in prom
    assert "DEAR_MP_FAKE_TOKEN=[redacted]" in prom
    if pid == 0:
        assert "dear_cluster_straggler_rank 1" in prom, prom[:800]
    if pid == 1:
        assert "dear_health_step_time_spike" in prom
    stream = read_metrics(stream_path)
    assert stream and all(r["kind"] == "health" for r in stream)
    assert stream[-1]["counters"].get("cluster.health_checks", 0) > 0

    print(f"MP_HEALTH_OK rank={pid}/{n}", flush=True)


def _elastic_main() -> None:
    """Elastic membership over a REAL 3-process host-level cluster
    (ISSUE-5 acceptance): one rank SIGKILLs itself mid-run; the survivors
    must commit a smaller membership epoch (two-phase reconfiguration),
    rescale the fusion plan to the new replica count
    (`AutoTuner.rescale`, epoch-stamped), reshard the input pipeline, and
    consensus-restore to the newest step valid on every survivor — then
    the supervisor relaunches the dead rank with ``DEAR_ELASTIC_REJOIN=1``
    and it must be readmitted at a later epoch barrier
    (`ElasticCluster.rejoin` + `GuardedTrainer.elastic_resume`), after
    which ALL members finish in lockstep.

    No ``jax.distributed`` anywhere: the coordination substrate must
    outlive rank death (the jax coordination service dies with process 0),
    so membership runs over `FileTransport` and each rank is a
    single-process jax world with enough EMULATED CPU devices to rescale
    across. The replicas train a COMMON batch stream (in real data-
    parallel training the gradient all-reduce couples the replicas, so
    the checked loss is replicated even though each rank feeds its own
    shard; these emulated replicas are uncoupled, so a common stream is
    what preserves the lockstep invariant the desync sentinel checks).
    The `runtime.pipeline` object rides along as the guarded input stream
    whose shard assignment, sidecar persistence, and reshard-on-epoch
    behavior are asserted directly."""
    import json

    # BEFORE any backend touch: stay single-process, emulate 4 devices
    # (world shrinks 3 -> 2 and grows back; the mesh is rebuilt per epoch)
    os.environ["DEAR_DISABLE_DISTRIBUTED"] = "1"
    os.environ["DEAR_CKPT_SHARED"] = "0"  # every rank owns its ckpt dir
    from dear_pytorch_tpu import _jax_compat

    _jax_compat.set_cpu_device_count(4, scrub_env=True)

    from dear_pytorch_tpu.observability import flight as FL
    from dear_pytorch_tpu.observability import tracer as T
    from dear_pytorch_tpu.ops.fused_sgd import fused_sgd
    from dear_pytorch_tpu.resilience import membership as M
    from dear_pytorch_tpu.runtime import build as B
    from dear_pytorch_tpu.runtime import pipeline as P
    from dear_pytorch_tpu.tuning.autotune import AutoTuner
    from dear_pytorch_tpu.utils import checkpoint as ckpt
    from dear_pytorch_tpu.utils.guard import GuardedTrainer

    import elastic_harness as EH  # tests/ is sys.path[0] (script launch)

    cluster = M.ElasticCluster.from_env(max_candidates=256)
    rejoining = M.ElasticCluster.rejoining_by_env()
    rank, world0 = cluster.rank, int(os.environ["DEAR_ELASTIC_WORLD"])
    workdir = os.path.join(os.environ["DEAR_MP_WORKDIR"], f"rank{rank}")
    ckpt_dir = os.path.join(workdir, "ckpts")
    tracer = T.get_tracer()
    assert tracer.enabled, "DEAR_TELEMETRY must be set for elastic mode"
    assert FL.get_recorder().enabled

    kill_rank = kill_at = None
    if os.environ.get("DEAR_MP_ELASTIC_KILL"):
        kr, ka = os.environ["DEAR_MP_ELASTIC_KILL"].split(":")
        kill_rank, kill_at = int(kr), int(ka)

    def loss_fn(p, b):
        x, y = b
        pred = jnp.tanh(x @ p["w1"]) @ p["w2"]
        return jnp.mean((pred - y) ** 2)

    k = jax.random.PRNGKey(0)
    tparams = {
        "w1": jax.random.normal(k, (8, 16)) * 0.3,
        "w2": jax.random.normal(jax.random.fold_in(k, 1), (16, 4)) * 0.3,
    }
    bk = jax.random.PRNGKey(7)

    def batch_at(i):
        kk = jax.random.fold_in(bk, i)
        # batch 12 shards evenly over world 3 AND the post-shrink world 2
        return (jax.random.normal(kk, (12, 8)),
                jax.random.normal(jax.random.fold_in(kk, 1), (12, 4)))

    mesh = jax.sharding.Mesh(np.asarray(jax.devices()[:cluster.world]),
                             ("dp",))
    tuner = AutoTuner(
        loss_fn, tparams, strategy="bo", threshold_mb=0.0001,
        interval=10**9,  # the tuner never proposes; rescale() is the point
        mesh=mesh, optimizer=fused_sgd(lr=0.05, momentum=0.9), donate=False,
    )

    # the guarded input stream: per-member shard assignment folded into
    # the seed, position persisted in every checkpoint sidecar
    spec = P.SyntheticSpec((
        P.Field("x", (12, 8), B.KIND_NORMAL_F32, 0.0, 1.0),
        P.Field("y", (12, 4), B.KIND_NORMAL_F32, 0.0, 1.0),
    ))
    pipe = P.NumpyPipeline(spec, seed=123, shard=cluster.index,
                           num_shards=cluster.world)

    guard = GuardedTrainer(
        tuner.ts, ckpt_dir, tparams,
        check_every=1, checkpoint_every=2, max_keep=1000, max_recoveries=8,
        coordinator=cluster, pipeline=pipe,
    )
    EH.attach_elastic(guard, tuner)
    assert guard._coordinated, "elastic guard must coordinate via members"

    POST = 6  # lockstep steps every member runs after the last transition
    t_target = None
    rollbacks = []
    guard.on_rollback = lambda c, at: rollbacks.append(at)

    if rejoining:
        state, at_step, last_epoch = EH.reenter(cluster, tuner, guard,
                                                ckpt_dir)
        t_target = guard.steps_seen + POST
        print(f"MP_ELASTIC_REJOINED rank={rank} epoch={cluster.epoch} "
              f"resumed_step={at_step} steps_seen={guard.steps_seen}",
              flush=True)
        assert last_epoch == 0, last_epoch  # died before any transition
        assert cluster.epoch == 2 and cluster.world == world0
        assert tracer.counters().get("pipeline.resumes", 0) >= 1
    else:
        state = tuner.init(tparams)

    state, m = EH.run_loop(
        cluster, guard, pipe, state, batch_at, tracer,
        rejoining=rejoining,
        kill=None if kill_rank is None else (kill_rank, kill_at),
        post=POST, t_target=t_target, no_kill_target=10,
    )

    counters = tracer.counters()
    view = cluster.view()
    if kill_rank is not None:
        # every member ends at epoch 2 (shrink + admission), full strength
        assert view.epoch == 2 and view.members == tuple(range(world0)), view
        assert guard.ts.plan.world == world0 and \
            guard.ts.plan.epoch == 2, guard.ts.plan
        assert pipe.shard == view.index and pipe.num_shards == world0
        assert pipe._epoch == 2
        if rank != kill_rank:
            # survivors transitioned through the in-loop rollback path
            # (the rejoiner re-entered through elastic_resume instead)
            assert rollbacks, "the transitions must have rolled back"
            assert counters.get("cluster.reconfigs", 0) >= 1, counters
            assert counters.get("cluster.rejoins", 0) >= 1, counters
            assert counters.get("guard.membership_changes", 0) >= 2, counters
            assert counters.get("autotune.rescales", 0) >= 2, counters
            assert counters.get("pipeline.reshards", 0) >= 2, counters
            assert counters.get("pipeline.resumes", 0) >= 1, counters
        # the flight ring stamps rows with the membership epoch
        ring = FL.get_recorder().dump()["records"]
        assert ring and ring[-1]["mem_epoch"] == 2, ring[-1]
        # ... and the newest checkpoint sidecar carries it (the relaunch
        # contract: this is the "last known epoch" a future rejoin presents)
        assert ckpt.read_mem_epoch(ckpt_dir, guard._last_good_step) == 2

    # lockstep epilogue: every member must agree on the final loss AND
    # final parameter step (one member-scoped exchange, member-ordered)
    final_loss = float(m["loss"])
    final_step = int(jax.device_get(state.step))
    views = cluster.exchange("verdict", json.dumps(
        {"loss": final_loss, "step": final_step,
         "steps_seen": guard.steps_seen, "epoch": cluster.epoch}))
    parsed = [json.loads(v) for v in views]
    assert all(p["epoch"] == cluster.epoch for p in parsed), parsed
    assert all(p["steps_seen"] == guard.steps_seen for p in parsed), parsed
    assert all(p["step"] == final_step for p in parsed), parsed
    assert all(abs(p["loss"] - final_loss) < 1e-6 for p in parsed), parsed
    assert np.isfinite(final_loss)

    print(f"MP_ELASTIC_OK rank={rank}/{world0} epoch={cluster.epoch} "
          f"final_step={final_step}", flush=True)


def main() -> None:
    mode = os.environ.get("DEAR_MP_MODE", "").strip()
    if mode == "health":
        return _health_main()
    if mode == "resilience":
        return _resilience_main()
    if mode == "elastic":
        return _elastic_main()
    import dear_pytorch_tpu as dear
    from dear_pytorch_tpu.comm import backend
    from dear_pytorch_tpu.comm import collectives as C
    from dear_pytorch_tpu.ops.fused_sgd import fused_sgd
    from dear_pytorch_tpu.parallel import build_train_step

    mesh = dear.init()  # multi-process branch: jax.distributed.initialize
    n = int(os.environ["JAX_NUM_PROCESSES"])
    pid = jax.process_index()
    assert jax.process_count() == n, (jax.process_count(), n)
    assert backend.size() == n and backend.rank() == pid
    assert mesh.shape[backend.DP_AXIS] == jax.device_count()
    # TPU-pod shape: several addressable devices per process when the
    # launcher exports DEAR_NUM_CPU_DEVICES (emulating chips-per-host)
    want_local = int(os.environ.get("DEAR_NUM_CPU_DEVICES") or 1)
    assert jax.local_device_count() == want_local, (
        jax.local_device_count(), want_local,
    )

    backend.barrier()  # multi-process sync_global_devices branch

    # rank-0-decides contract: every process starts with different values,
    # all end with rank 0's (reference dear_dopt.py:400-425)
    params = {"w": jnp.full((4,), float(pid)), "b": jnp.ones((2,)) * (pid + 1)}
    out = dear.broadcast_parameters(params)
    np.testing.assert_allclose(np.asarray(out["w"]), 0.0)
    np.testing.assert_allclose(np.asarray(out["b"]), 1.0)

    # start-state contract for the optimizer too (reference
    # dear_dopt.py:428-544): host-side state with mixed float/int leaves,
    # perturbed per rank, must come back as rank 0's everywhere
    opt_state = {
        "momentum": {"w": np.full((3, 2), float(pid)),
                     "b": np.full((2,), float(pid))},
        "step": np.asarray(pid, np.int32),
    }
    synced = dear.broadcast_optimizer_state(opt_state)
    np.testing.assert_allclose(np.asarray(synced["momentum"]["w"]), 0.0)
    np.testing.assert_allclose(np.asarray(synced["momentum"]["b"]), 0.0)
    assert int(synced["step"]) == 0

    # host-level allreduce helper (metrics aggregation across processes)
    got = C.allreduce(np.array([1.0 + pid]), average=True)
    np.testing.assert_allclose(np.asarray(got), [1.0 + (n - 1) / 2.0])
    got = C.allreduce(np.array([1.0 + pid]), average=False)
    np.testing.assert_allclose(np.asarray(got), [n + n * (n - 1) / 2.0])

    # dear-mode train step over the global mesh: devices in DIFFERENT
    # processes jointly reduce-scatter/all-gather. Same params everywhere
    # (same seed); per-process batch shards differ.
    def loss_fn(p, b):
        x, y = b
        pred = jnp.tanh(x @ p["w1"]) @ p["w2"]
        return jnp.mean((pred - y) ** 2)

    k = jax.random.PRNGKey(0)
    tparams = {
        "w1": jax.random.normal(k, (8, 16)) * 0.3,
        "w2": jax.random.normal(jax.random.fold_in(k, 1), (16, 4)) * 0.3,
    }
    ts = build_train_step(
        loss_fn, tparams, mesh=mesh, mode="dear", threshold_mb=0.0001,
        optimizer=fused_sgd(lr=0.05, momentum=0.9), donate=False,
    )
    state = ts.init(tparams)
    # identical global batch on every process; device_put shards it
    bk = jax.random.PRNGKey(7)
    batch = (
        jax.random.normal(bk, (4 * jax.device_count(), 8)),
        jax.random.normal(jax.random.fold_in(bk, 1), (4 * jax.device_count(), 4)),
    )
    losses = []
    for _ in range(4):
        state, m = ts.step(state, batch)
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0], losses

    # explicit cross-process staging (the CLIs' path): each process
    # materializes only its addressable shards of the host-global batch
    from dear_pytorch_tpu.benchmarks import runner

    sharding = jax.sharding.NamedSharding(mesh, jax.P(backend.DP_AXIS))
    staged = runner.stage_global(
        {"x": np.asarray(batch[0]), "y": np.asarray(batch[1])}, sharding
    )
    assert staged["x"].shape == batch[0].shape  # global logical shape
    local = sum(s.data.shape[0] for s in staged["x"].addressable_shards)
    assert local == batch[0].shape[0] // n  # only this host's rows live here
    state, m = ts.step(state, (staged["x"], staged["y"]))
    assert np.isfinite(float(m["loss"]))

    # every process computed the identical loss sequence (the collectives
    # actually coupled them)
    from jax.experimental import multihost_utils

    all_losses = np.asarray(
        multihost_utils.process_allgather(jnp.asarray(losses))
    )
    np.testing.assert_allclose(
        all_losses, np.tile(all_losses[0], (n, 1)), rtol=1e-6
    )

    # fsdp (ZeRO-3 shape) across the process boundary: AD-transposed
    # parameter gathers + grad reduce-scatters cross hosts; one step must
    # be finite and identical everywhere (verdict-r4 #5 asked for a
    # cross-process fsdp leg alongside the dear one)
    if os.environ.get("DEAR_MP_FSDP", "1").strip() not in ("0", ""):
        tsf = build_train_step(
            loss_fn, tparams, mesh=mesh, mode="fsdp", threshold_mb=0.0001,
            optimizer=fused_sgd(lr=0.05, momentum=0.9), donate=False,
        )
        stf = tsf.init(tparams)
        stf, mf = tsf.step(stf, batch)
        f_loss = float(mf["loss"])
        assert np.isfinite(f_loss)
        from jax.experimental import multihost_utils as mhu

        f_all = np.asarray(mhu.process_allgather(jnp.asarray([f_loss])))
        np.testing.assert_allclose(f_all, np.tile(f_all[0], (n, 1)),
                                   rtol=1e-6)

    # sequence parallelism ACROSS processes: a dp x sp mesh whose sp axis
    # spans the process boundary, causal ring attention rotating K/V
    # between hosts via ppermute — one GPT train step must be finite and
    # identical on every process (long-context multi-host evidence the
    # reference has no analog for)
    from dear_pytorch_tpu.models import data as gdata
    from dear_pytorch_tpu.models.gpt import GptConfig, GptLmHeadModel
    from dear_pytorch_tpu.parallel import sp as SP

    devs = jax.devices()
    sp_enabled = os.environ.get("DEAR_MP_SP", "1").strip() not in ("0", "")
    if sp_enabled and len(devs) >= 2:
        sp_deg = 2
        # transpose so the sp axis pairs devices from DIFFERENT processes
        # (a straight reshape would pair each process's own local devices
        # and the ring ppermute would never cross the host boundary)
        grid = (
            np.asarray(devs[: 2 * (len(devs) // 2)])
            .reshape(sp_deg, len(devs) // 2).T
        )
        meshsp = jax.sharding.Mesh(grid, ("dp", "sp"))
        cfg = GptConfig(
            vocab_size=32, hidden_size=16, num_hidden_layers=2,
            num_attention_heads=2, intermediate_size=32,
            max_position_embeddings=8, embd_dropout_prob=0.0,
            hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0,
        )
        gbatch = gdata.synthetic_gpt_batch(
            jax.random.PRNGKey(4), 2 * meshsp.shape["dp"], seq_len=8,
            vocab_size=32,
        )
        gparams = GptLmHeadModel(cfg).init(
            {"params": jax.random.PRNGKey(0)}, gbatch["input_ids"],
            train=False,
        )["params"]
        tssp = build_train_step(
            SP.make_sp_gpt_loss_fn(
                SP.sp_gpt_model(cfg, attention="ring"),
                vocab_size=32, train=False,
            ),
            gparams, mesh=meshsp, axis_name=("dp", "sp"),
            mean_axes=("dp",), batch_spec_fn=SP.bert_sp_batch_specs,
            threshold_mb=0.01, optimizer=fused_sgd(lr=0.05, momentum=0.9),
            donate=False,
        )
        shardings = jax.tree.map(
            lambda s: jax.sharding.NamedSharding(meshsp, s),
            SP.bert_sp_batch_specs(gbatch),
        )
        gbatch = jax.tree.map(
            lambda x, sh: runner.stage_global(np.asarray(x), sh),
            gbatch, shardings,
        )
        stsp = tssp.init(gparams)
        sp_losses = []
        for _ in range(2):
            stsp, msp = tssp.step(stsp, gbatch)
            sp_losses.append(float(msp["loss"]))
        assert all(np.isfinite(sp_losses)), sp_losses
        gathered = np.asarray(
            multihost_utils.process_allgather(jnp.asarray(sp_losses))
        )
        np.testing.assert_allclose(
            gathered, np.tile(gathered[0], (n, 1)), rtol=1e-6
        )

    print(f"MP_WORKER_OK rank={pid}/{n}", flush=True)


if __name__ == "__main__":
    sys.exit(main())
