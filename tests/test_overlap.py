"""Prove the DeAR overlap schedule materializes in the compiled program.

The reference implements RS-under-backward / AG-under-forward with CUDA
streams and module hooks (dear/dear_dopt.py:242-308) and verifies it by
eyeballing nvprof timelines. Here the train step is ONE XLA program, so the
promise is checkable mechanically from the optimized HLO:

  * per-bucket collectives exist (nothing collapsed them into one),
  * they are mutually INDEPENDENT (no data path from one to another — a
    spurious dependency would force any scheduler on any backend to
    serialize them),
  * forward compute depends on its OWN bucket's all-gather but not all of
    them (so gather g+1 can run under layer-group g's forward),
  * each reduce-scatter is independent of most compute (so it can run
    under the rest of the backward), and the CPU scheduler actually
    interleaves RS with backward compute in the scheduled sequence.

If a refactor serializes the collectives (e.g. threads a token through
them), these assertions fail — which is exactly the regression DeAR cares
about.
"""

import jax
import jax.numpy as jnp
import pytest

# Quarantine (tracking: ISSUE 7 satellite; flaky since at least r04): this
# module and test_ring_attention.py fail intermittently ONLY under heavy
# host load — 8-way CPU-device emulation plus a parallel compile storm can
# time out XLA's own scheduler or wedge a collective long enough to trip
# the per-test timeout, wobbling tier-1 dot counts from run to run. The
# `flaky` marker makes the root conftest rerun a failure (fresh setup) up
# to twice before reporting it, so a load blip no longer flips CI while a
# genuine schedule regression (deterministic) still fails all three runs.
pytestmark = pytest.mark.flaky(reason="load-flaky: XLA CPU scheduling "
                               "under oversubscription", reruns=2)

from dear_pytorch_tpu.ops.fused_sgd import fused_sgd
from dear_pytorch_tpu.parallel import build_train_step
from dear_pytorch_tpu.utils import hlo

N_LAYERS = 4


def _mlp_params(key):
    ks = jax.random.split(key, N_LAYERS)
    return {
        f"l{i:02d}": {
            "w": jax.random.normal(ks[i], (64, 64)) * 0.1,
            "b": jnp.zeros((64,)),
        }
        for i in range(N_LAYERS)
    }


def _loss(p, b):
    x, y = b
    for i in range(N_LAYERS):
        x = jnp.tanh(x @ p[f"l{i:02d}"]["w"] + p[f"l{i:02d}"]["b"])
    return jnp.mean((x - y) ** 2)


@pytest.fixture(scope="module")
def entry_ops(mesh):
    params = _mlp_params(jax.random.PRNGKey(0))
    ts = build_train_step(
        _loss, params, mesh=mesh, nearby_layers=1,  # one bucket per layer
        optimizer=fused_sgd(lr=0.01, momentum=0.9), donate=False,
    )
    assert ts.plan.num_buckets == N_LAYERS
    state = ts.init(params)
    batch = (
        jax.random.normal(jax.random.PRNGKey(1), (16, 64)),
        jax.random.normal(jax.random.PRNGKey(2), (16, 64)),
    )
    text = ts.lower(state, batch).compile().as_text()
    assert "is_scheduled=true" in text
    return hlo.parse_entry(text)


def test_per_bucket_collectives_exist(entry_ops):
    ags = hlo.find(entry_ops, "all-gather")
    rss = hlo.find(entry_ops, "reduce-scatter")
    assert len(ags) == N_LAYERS, [o.line for o in ags]
    assert len(rss) == N_LAYERS, [o.line for o in rss]


def test_collectives_are_mutually_independent(entry_ops):
    """No data path between any two gathers (or any two reduce-scatters):
    a dependency would force serialization on every backend."""
    for kind in ("all-gather", "reduce-scatter"):
        cols = hlo.find(entry_ops, kind)
        anc = {c.name: hlo.ancestors(entry_ops, c.name) for c in cols}
        for a in cols:
            for b in cols:
                if a.name != b.name:
                    assert a.name not in anc[b.name], (
                        f"{kind} {b.name} depends on {a.name} — serialized"
                    )


def test_forward_needs_only_its_own_gather(entry_ops):
    """Some compute depends on >=1 but not ALL gathers — i.e. the first
    layer group's forward can start while later buckets still gather."""
    ags = {o.name for o in hlo.find(entry_ops, "all-gather")}
    partial_seen = False
    for c in hlo.compute_ops(entry_ops):
        dep = hlo.ancestors(entry_ops, c.name) & ags
        if 0 < len(dep) < len(ags):
            partial_seen = True
            break
    assert partial_seen, (
        "every compute op depends on all gathers — forward is serialized "
        "behind the full gather phase"
    )


def test_reduce_scatters_overlap_backward(entry_ops):
    """Each RS has compute it does NOT depend on and that does not depend
    on it (free to run concurrently), and the scheduler interleaves: in the
    scheduled sequence there is compute between consecutive RSs."""
    rss = hlo.find(entry_ops, "reduce-scatter")
    computes = hlo.compute_ops(entry_ops)
    anc_of = {c.name: hlo.ancestors(entry_ops, c.name) for c in computes}
    for r in rss:
        r_anc = hlo.ancestors(entry_ops, r.name)
        independent = [
            c for c in computes
            if c.name not in r_anc and r.name not in anc_of[c.name]
        ]
        assert independent, f"no compute can overlap {r.name}"

    # scheduled-order evidence (CPU backend schedules sync collectives in
    # sequence): consecutive RSs have compute between them
    order = sorted(rss, key=lambda o: o.index)
    gaps_with_compute = 0
    for a, b in zip(order, order[1:]):
        if any(a.index < c.index < b.index for c in computes):
            gaps_with_compute += 1
    assert gaps_with_compute >= len(order) - 1, (
        "reduce-scatters are clumped — not interleaved with backward"
    )


def test_hlo_parser_ignores_attribute_refs_and_done_halves():
    """Parser unit check: control-predecessors / to_apply / calls are NOT
    data operands, and async '-done' halves don't double-count."""
    text = """ENTRY %main (p0: f32[4]) -> f32[4] {
  %p0 = f32[4] parameter(0)
  %rs.2 = f32[4] reduce-scatter(%p0), replica_groups={}
  %ag.3 = f32[4] all-gather-start(%rs.2), control-predecessors={%rs.9}
  %ag.4 = f32[4] all-gather-done(%ag.3)
  %rs.9 = f32[4] reduce-scatter(%p0), to_apply=%add.1
  ROOT %t = f32[4] fusion(%ag.4, %rs.9), calls=%fused_computation
}
"""
    ops = hlo.parse_entry(text)
    by = {o.name: o for o in ops}
    assert by["ag.3"].operands == ("rs.2",)
    assert by["rs.9"].operands == ("p0",)
    assert by["t"].operands == ("ag.4", "rs.9")
    assert [o.name for o in hlo.find(ops, "all-gather")] == ["ag.3"]
    assert [o.name for o in hlo.find(ops, "all-gather-done")] == ["ag.4"]
    assert "rs.9" not in hlo.ancestors(ops, "ag.3")


def test_dear_overlappability_beats_allreduce_quantitatively(mesh):
    """The round-5 quantitative overlap claim (scripts/overlap_report.py):
    mean independent-compute fraction across collectives must be higher
    for dear than for the naive allreduce schedule. At world=8 XLA's
    all-reduce combiner collapses allreduce-mode buckets into one
    terminal all-reduce with ~2.5% overlappable compute; dear's RS/AG
    decoupling holds ~37% (measured r5: 0.3667 vs 0.025)."""
    import importlib.util
    import os

    path = os.path.join(os.path.dirname(__file__), "..", "scripts",
                        "overlap_report.py")
    spec = importlib.util.spec_from_file_location("overlap_report", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)

    dear = mod.hlo_overlap_metric("dear")
    ar = mod.hlo_overlap_metric("allreduce")
    assert dear["mean_independent_compute_frac"] is not None
    assert ar["mean_independent_compute_frac"] is not None
    assert (dear["mean_independent_compute_frac"]
            > ar["mean_independent_compute_frac"]), (dear, ar)


def test_dear_fused_hlo_metric_and_accounting(mesh):
    """The fused-kernel mode compiles at world=8 and the auditor inputs
    exist for it: the structural HLO metric evaluates (its ring transport
    is sub-XLA, so only scheduler-visible structure is scored — recorded
    with that note by scripts/overlap_report.py), and the static leg
    accounting carries the same RS/AG legs as dear so the exposed-comm
    rows are directly comparable."""
    import importlib.util
    import os

    path = os.path.join(os.path.dirname(__file__), "..", "scripts",
                        "overlap_report.py")
    spec = importlib.util.spec_from_file_location("overlap_report", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)

    fused = mod.hlo_overlap_metric("dear-fused")
    assert isinstance(fused["mean_independent_compute_frac"], float)

    from dear_pytorch_tpu.observability import counters as CTR
    from dear_pytorch_tpu.ops import fusion as F

    plan = F.make_plan({"w": jnp.zeros((64, 64))}, world=8)
    acct = CTR.plan_comm_accounting(plan, mode="dear-fused")
    assert sorted({r.leg for r in acct.rows}) == ["all_gather",
                                                  "reduce_scatter"]
    dear_acct = CTR.plan_comm_accounting(plan, mode="dear")
    assert acct.payload_bytes_per_step == dear_acct.payload_bytes_per_step
