"""Headline benchmark: ResNet-50 end-to-end training throughput per chip.

Reproduces the reference's measurement protocol (dear/imagenet_benchmark.py:
151-172): 10 warmup batches, then 5 timed runs of 10 batches each; reports
images/sec as mean over runs. Runs the full DeAR train step (pack →
reduce-scatter → fused-SGD → all-gather schedule; trivial collectives at
world=1) with bf16 compute / f32 master params — the TPU-first configuration.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "img/s", "vs_baseline": N}

``vs_baseline`` is relative to BASELINE_IMG_SEC, the first end-to-end
measurement of this framework on the session's single TPU v5e chip (round 1);
the reference publishes no numbers of its own (BASELINE.md), so progress is
tracked against our own round-1 throughput.

Timing protocol for the axon tunnel (remote device): dispatch each timed
run's steps asynchronously and fetch ONE scalar that depends on the last
step; per-step host syncs would add ~60ms RPC latency each and
``block_until_ready`` on a remote buffer may return early.
"""

from __future__ import annotations

import json
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

# Round-1 pin: ResNet-50 bs=64 bf16 train step, TPU v5 lite (1 chip),
# ~33.5 ms/step.
BASELINE_IMG_SEC = 1910.0

BATCH_SIZE = 64
WARMUP_BATCHES = 10
NUM_ITERS = 5
NUM_BATCHES_PER_ITER = 10


def main() -> None:
    from dear_pytorch_tpu import models
    from dear_pytorch_tpu.comm import backend
    from dear_pytorch_tpu.models import data
    from dear_pytorch_tpu.ops.fused_sgd import fused_sgd
    from dear_pytorch_tpu.parallel import dear as D

    mesh = backend.init()
    model = models.get_model("resnet50", dtype=jnp.bfloat16)
    batch = data.synthetic_image_batch(
        jax.random.PRNGKey(0), BATCH_SIZE, dtype=jnp.bfloat16
    )
    variables = model.init(
        {"params": jax.random.PRNGKey(0)}, batch["image"], train=False
    )
    params = variables["params"]
    model_state = {"batch_stats": variables["batch_stats"]}

    def loss_fn(p, mstate, b):
        logits, new_state = model.apply(
            {"params": p, **mstate}, b["image"], train=True,
            mutable=["batch_stats"],
        )
        return data.softmax_xent(logits, b["label"]), new_state

    ts = D.build_train_step(
        loss_fn,
        params,
        mesh=mesh,
        mode="dear",
        threshold_mb=25.0,
        optimizer=fused_sgd(lr=0.01, momentum=0.9),
        comm_dtype=jnp.bfloat16,
        model_state_template=model_state,
    )
    state = ts.init(params, model_state)

    for _ in range(WARMUP_BATCHES):
        state, metrics = ts.step(state, batch)
    float(metrics["loss"])  # drain the pipeline once before timing

    times = []
    for _ in range(NUM_ITERS):
        t0 = time.perf_counter()
        for _ in range(NUM_BATCHES_PER_ITER):
            state, metrics = ts.step(state, batch)
        float(metrics["loss"])  # one device->host scalar fetch per run
        times.append(time.perf_counter() - t0)

    img_secs = [BATCH_SIZE * NUM_BATCHES_PER_ITER / t for t in times]
    value = float(np.mean(img_secs))
    print(
        json.dumps(
            {
                "metric": "resnet50_bs64_train_img_sec_per_chip",
                "value": round(value, 2),
                "unit": "img/s",
                "vs_baseline": round(value / BASELINE_IMG_SEC, 3),
            }
        )
    )


if __name__ == "__main__":
    sys.exit(main())
