"""Headline benchmarks: ResNet-50 and BERT-Base end-to-end training
throughput per chip, with MFU accounting.

Follows the reference's measurement shape (dear/imagenet_benchmark.py:
151-172, dear/bert_benchmark.py:160-175): warmup batches, then a timed
window of NUM_ITERS x NUM_BATCHES_PER_ITER training steps. Unlike the
reference (which averages per-run rates with a sync per run), the timed
window here is ONE contiguous dispatch queue with a single end-of-window
device->host fetch — on this container the device is remote behind a
~60 ms round-trip tunnel, and a per-run sync would charge that RTT to
every run (measurement-harness overhead a local TPU host never pays). Runs the full DeAR
train step (pack → reduce-scatter → fused-SGD → all-gather schedule; trivial
collectives at world=1) with bf16 compute / f32 master params — the
TPU-first configuration.

Prints ONE JSON line (the driver contract), primary metric first:
  {"metric": "resnet50_bs64_train_img_sec_per_chip", "value": N,
   "unit": "img/s", "vs_baseline": N, "mfu": F,
   "extra_metrics": [{"metric": "bert_base_sen_sec_per_chip", ...}]}

``vs_baseline`` is relative to BASELINE_IMG_SEC, this framework's own
round-4 capture on the session's single TPU v5e chip under the same
single-fetch protocol this file implements (the reference publishes no
numbers of its own, BASELINE.md); the emitted ``baseline_protocol`` tag
names the pin's protocol so JSON consumers can tell re-bases apart.
``mfu`` = achieved FLOP/s
(XLA cost analysis of the compiled step) over the chip's bf16 peak.

Timing protocol for the axon tunnel (remote device): dispatch each timed
run's steps asynchronously and fetch ONE scalar that depends on the last
step; per-step host syncs would add ~60ms RPC latency each and
``block_until_ready`` on a remote buffer may return early.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time

import jax
import jax.numpy as jnp

# Round-4 pin: ResNet-50 bs=64 bf16 train step, TPU v5 lite (1 chip),
# 2304.13 img/s measured under the SINGLE-FETCH protocol this file now
# implements (perf/onchip_r04/bench.json). Re-based in round 5 from the
# round-1 pin of 1910.0 img/s: that number was captured with the
# pre-round-4 per-iter-fetch loop, which charged a ~57 ms tunnel
# round-trip to every 10-step window (~1.20x harness overhead a local
# TPU host never pays — same-protocol re-measurement was 1909 img/s,
# exact parity). With pin and capture now under the same protocol,
# vs_baseline measures the device, not the harness. The emitted
# "baseline_protocol" tag lets JSON consumers tell the pins apart.
BASELINE_IMG_SEC = 2304.13
BASELINE_PROTOCOL = "single-fetch-r04"
# BERT pin: pinned automatically to the FIRST successful driver capture
# found in BENCH_r*.json history (pin-on-first-capture — no manual edit
# needed when the first on-chip BERT number lands). None until then.
BASELINE_BERT_SEN_SEC = None
# GPT pin: the metric joined the driver contract in round 5, so there is
# no BENCH_r*.json history yet; until one exists, the pin is the round-4
# on-chip headline measured under the SAME single-fetch scanned protocol
# (perf/onchip_r04/gpt_headline.txt: 48,121 tok/s at S=1024 — the
# pre-optimization configuration this round's sweep started from).
BASELINE_GPT_TOK_SEC = 48121.0
# deliberately its own literal, not an alias of BASELINE_PROTOCOL: this
# tags the GPT pin's capture protocol, which stays r04-single-fetch even
# if the ResNet pin is later re-based under a different protocol
BASELINE_GPT_PROTOCOL = "single-fetch-r04"
# The fallback GPT pin was captured under a DIFFERENT training config than
# bench_gpt now measures, so vs_baseline against it mixes config changes
# with framework/device speedup (PERF.md documents the split). Emitted as
# "baseline_config" so JSON consumers see the delta without reading docs;
# self-heals to 'pinned-from-history' once pin-on-first-capture resolves.
BASELINE_GPT_CONFIG = ("r04 config: bs8, dropout on, naive LM loss "
                       "(measured config is bs16, dropout 0, streamed loss)")

PRIMARY_METRIC = "resnet50_bs64_train_img_sec_per_chip"


def _history_baseline(metric: str, fallback=None):
    """(value, protocol) of the first captured ``metric`` from
    BENCH_r*.json history, else (fallback, None) — pin-on-first-capture
    without manual edits. The driver stores each round as {"n", "cmd",
    "rc", "tail", "parsed"} where "parsed" is our contract line
    (extra_metrics carries the secondary entries). The protocol tag is
    derived from the resolved round (rounds >= 4 measured single-fetch;
    earlier rounds charged a tunnel RTT per timed window), not
    hardcoded, so a backfilled early round can't mislabel the pin."""
    import glob
    import re

    here = os.path.dirname(os.path.abspath(__file__))
    rounds = []
    for p in glob.glob(os.path.join(here, "BENCH_r*.json")):
        m = re.fullmatch(r"BENCH_r(\d+)\.json", os.path.basename(p))
        if m:
            rounds.append((int(m.group(1)), p))
    for n, path in sorted(rounds):
        try:
            with open(path) as f:
                record = json.load(f)
            parsed = record.get("parsed") if isinstance(record, dict) else None
            if not isinstance(parsed, dict):
                continue
            candidates = [parsed] + list(parsed.get("extra_metrics") or [])
            for m in candidates:
                if (
                    isinstance(m, dict)
                    and m.get("metric") == metric
                    and isinstance(m.get("value"), (int, float))
                    and m["value"] > 0
                ):
                    protocol = (f"single-fetch-r{n:02d}" if n >= 4
                                else f"per-iter-fetch-r{n:02d}")
                    return float(m["value"]), protocol
        except Exception:
            continue
    return fallback, None


def _bert_baseline():
    return _history_baseline("bert_base_sen_sec_per_chip",
                             BASELINE_BERT_SEN_SEC)


# The driver contract is ONE JSON line on stdout; the watchdog thread and the
# main thread may both reach their print under a race (phase completes right
# at the timeout), so all emission goes through this gate.
_EMIT_LOCK = threading.Lock()
_EMITTED = False


def _emit(out: dict) -> bool:
    """Print the contract JSON line exactly once, process-wide."""
    global _EMITTED
    with _EMIT_LOCK:
        if _EMITTED:
            return False
        _EMITTED = True
        print(json.dumps(out), flush=True)
        return True

SMOKE = bool(os.environ.get("DEAR_BENCH_SMOKE"))  # tiny shapes, CPU-safe


def _env_enabled(name: str) -> bool:
    """Opt-out env flag: on unless set to a falsy marker."""
    return os.environ.get(name, "1").strip().lower() not in (
        "", "0", "false", "no")


def _gather_dtype(world: int):
    """Cast master shards to bf16 before the per-bucket all-gather ONLY
    when there is gather traffic to halve (world > 1: half the AG bytes on
    ICI). At world=1 the gather is a local copy and the pre-cast is pure
    overhead — the 2026-07-31 on-chip A/B measured f32 gathers at +4.5%
    BERT-Base throughput and parity on ResNet (1225.37 sen/s in
    perf/onchip_r04/bench_gather_f32.json vs 1170.92 with bf16 gathers in
    perf/onchip_r04/bench_rerun.log), so the choice follows the mesh.
    Override with DEAR_BENCH_GATHER_DTYPE=bf16|f32."""
    v = os.environ.get("DEAR_BENCH_GATHER_DTYPE", "").strip().lower()
    if v in ("f32", "fp32", "float32", "none"):
        return None
    if v in ("bf16", "bfloat16"):
        return jnp.bfloat16
    if v:
        raise SystemExit(
            f"DEAR_BENCH_GATHER_DTYPE={v!r}: use 'bf16' or 'f32'"
        )
    return jnp.bfloat16 if world > 1 else None

WARMUP_BATCHES = 2 if SMOKE else 10
# 10 iters x 10 scanned steps per timed window: the single end-of-window
# fetch (~60 ms through the tunnel) amortizes to 0.6 ms over 100 steps.
NUM_ITERS = 2 if SMOKE else 10
NUM_BATCHES_PER_ITER = 2 if SMOKE else 10


def _compile_once(ts, state, batch):
    """(iter_fn, flops_per_step, peak_hbm_bytes): ONE AOT compilation of the
    scanned NUM_BATCHES_PER_ITER-step program. One program per timed
    iteration: dispatch cost amortizes over the scan, and XLA schedules step
    i+1's all-gathers under step i's tail (DeAR's cross-iteration
    pipelining, inside one executable)."""
    from dear_pytorch_tpu.utils import perf_model

    runner = ts.multi_step(NUM_BATCHES_PER_ITER)
    compiled = runner.lower(state, batch).compile()
    try:
        # XLA cost analysis counts a scan (while-loop) BODY once, so the
        # scanned program already reports one step's flops — no division.
        # (cost_analysis() is a one-element list on the 0.4.x jax line.)
        from dear_pytorch_tpu.benchmarks.runner import _cost_dict

        flops = float(_cost_dict(compiled.cost_analysis()).get("flops", 0.0))
    except Exception:
        flops = 0.0
    return compiled, flops, perf_model.peak_hbm_bytes(compiled)


def _timed(iter_fn, state, batch, items_per_batch: int):
    """(value items/s, secs/step, state); each ``iter_fn`` call runs
    NUM_BATCHES_PER_ITER steps as one program.

    All NUM_ITERS programs are dispatched back-to-back (state threads
    through, so the device runs them as one contiguous queue) and ONE
    scalar that depends on the final step is fetched — exactly the
    protocol the module docstring promises. Fetching inside every timed
    iteration (the pre-round-4 loop) charged a full tunnel round-trip
    (~60 ms) to each 10-step window, which is measurement overhead of the
    remote-host setup, not device or framework time: the 2026-07-31
    profile showed the same program at 29.7 ms/step device-bound while
    the per-iter-fetch loop read 33.5 ms/step."""
    n_warm_iters = max(WARMUP_BATCHES // NUM_BATCHES_PER_ITER, 1)
    metrics = None
    for _ in range(n_warm_iters):
        state, metrics = iter_fn(state, batch)
    float(metrics["loss"])  # drain the pipeline once before timing
    t0 = time.perf_counter()
    for _ in range(NUM_ITERS):
        state, metrics = iter_fn(state, batch)
    float(metrics["loss"])  # ONE device->host fetch for the whole window
    total = time.perf_counter() - t0
    steps = NUM_ITERS * NUM_BATCHES_PER_ITER
    secs_per_step = total / steps
    return items_per_batch / secs_per_step, secs_per_step, state


def bench_resnet(mesh):
    from dear_pytorch_tpu import models
    from dear_pytorch_tpu.models import data
    from dear_pytorch_tpu.ops.fused_sgd import fused_sgd
    from dear_pytorch_tpu.parallel import dear as D

    batch_size = 8 if SMOKE else 64
    model = models.get_model(
        "resnet18" if SMOKE else "resnet50", dtype=jnp.bfloat16
    )
    batch = data.synthetic_image_batch(
        jax.random.PRNGKey(0), batch_size,
        image_size=64 if SMOKE else 224, dtype=jnp.bfloat16,
    )
    variables = model.init(
        {"params": jax.random.PRNGKey(0)}, batch["image"], train=False
    )
    params = variables["params"]
    model_state = {"batch_stats": variables["batch_stats"]}

    def loss_fn(p, mstate, b):
        logits, new_state = model.apply(
            {"params": p, **mstate}, b["image"], train=True,
            mutable=["batch_stats"],
        )
        return data.softmax_xent(logits, b["label"]), new_state

    ts = D.build_train_step(
        loss_fn,
        params,
        mesh=mesh,
        mode="dear",
        threshold_mb=25.0,
        optimizer=fused_sgd(lr=0.01, momentum=0.9),
        comm_dtype=jnp.bfloat16,
        gather_dtype=_gather_dtype(mesh.size),
        model_state_template=model_state,
    )
    state = ts.init(params, model_state)
    step_fn, flops, hbm = _compile_once(ts, state, batch)
    value, secs_per_step, _ = _timed(step_fn, state, batch, batch_size)
    out = {
        "metric": "resnet50_bs64_train_img_sec_per_chip",
        "value": round(value, 2),
        "unit": "img/s",
        "vs_baseline": round(value / BASELINE_IMG_SEC, 3),
        "baseline_protocol": BASELINE_PROTOCOL,
        "mfu": _mfu(flops, secs_per_step),
    }
    if hbm:
        out["peak_hbm_gb"] = round(hbm / 2**30, 3)
    return out


def bench_vit(mesh):
    """ViT-B/16 bs64 bf16 — the GEMM-dominated vision headline (beyond the
    reference zoo). Demonstrates the framework's MFU ceiling is set by the
    model's op mix, not the schedule: on-chip 2026-07-31 it ran 59.0% MFU
    under this protocol (53.1% via the CLI's per-iter-fetch protocol,
    perf/onchip_r04/vit_b16.txt) vs ResNet-50's conv-bound ~28%."""
    from dear_pytorch_tpu import models
    from dear_pytorch_tpu.models import data
    from dear_pytorch_tpu.ops.fused_sgd import fused_sgd
    from dear_pytorch_tpu.parallel import dear as D

    batch_size = 8 if SMOKE else 64
    model = models.get_model(
        "vit_s16" if SMOKE else "vit_b16", dtype=jnp.bfloat16,
        **({"num_layers": 2} if SMOKE else {}),
    )
    batch = data.synthetic_image_batch(
        jax.random.PRNGKey(0), batch_size,
        image_size=32 if SMOKE else 224, dtype=jnp.bfloat16,
    )
    params = model.init(
        {"params": jax.random.PRNGKey(0)}, batch["image"], train=False
    )["params"]

    def loss_fn(p, b):
        logits = model.apply({"params": p}, b["image"], train=False)
        return data.softmax_xent(logits, b["label"])

    ts = D.build_train_step(
        loss_fn,
        params,
        mesh=mesh,
        mode="dear",
        threshold_mb=25.0,
        optimizer=fused_sgd(lr=0.01, momentum=0.9),
        comm_dtype=jnp.bfloat16,
        gather_dtype=_gather_dtype(mesh.size),
    )
    state = ts.init(params)
    step_fn, flops, hbm = _compile_once(ts, state, batch)
    value, secs_per_step, _ = _timed(step_fn, state, batch, batch_size)
    out = {
        "metric": "vit_b16_bs64_train_img_sec_per_chip",
        "value": round(value, 2),
        "unit": "img/s",
        "mfu": _mfu(flops, secs_per_step),
    }
    if hbm:
        out["peak_hbm_gb"] = round(hbm / 2**30, 3)
    return out


def bench_bert(mesh, variant: str = "bert_base"):
    """BERT pretraining throughput (the reference's second headline,
    dear/bert_benchmark.py:160-175; sentence length from the launcher,
    horovod_mpi_cj.sh:6). ``variant`` may be 'bert' (= BERT-Large, the
    reference's flagship config) — measured by default; skip with
    DEAR_BENCH_BERT_LARGE=0."""
    from dear_pytorch_tpu import models
    from dear_pytorch_tpu.models import data
    from dear_pytorch_tpu.ops.fused_sgd import fused_sgd
    from dear_pytorch_tpu.parallel import dear as D

    large = variant != "bert_base"
    batch_size = 4 if SMOKE else (16 if large else 32)
    seq_len = 32 if SMOKE else 64
    model = models.get_model(variant, dtype=jnp.bfloat16)
    if SMOKE:
        import dataclasses

        model = models.BertForPreTraining(
            dataclasses.replace(model.config, num_hidden_layers=2)
        )
    cfg = model.config
    batch = data.synthetic_bert_batch(
        jax.random.PRNGKey(0), batch_size, seq_len=seq_len,
        vocab_size=cfg.vocab_size,
    )
    params = model.init(
        {"params": jax.random.PRNGKey(0)}, batch["input_ids"], train=False
    )["params"]

    def loss_fn(p, b, rng):
        logits, nsp = model.apply(
            {"params": p}, b["input_ids"], b["token_type_ids"],
            b["attention_mask"], train=True, rngs={"dropout": rng},
        )
        return models.bert_pretraining_loss(
            logits.astype(jnp.float32), nsp.astype(jnp.float32),
            b["masked_lm_labels"], b["next_sentence_labels"],
        )

    ts = D.build_train_step(
        loss_fn,
        params,
        mesh=mesh,
        mode="dear",
        threshold_mb=25.0,
        optimizer=fused_sgd(lr=2e-5, momentum=0.0),
        comm_dtype=jnp.bfloat16,
        gather_dtype=_gather_dtype(mesh.size),
        rng_seed=42,
    )
    state = ts.init(params)
    step_fn, flops, hbm = _compile_once(ts, state, batch)
    value, secs_per_step, _ = _timed(step_fn, state, batch, batch_size)
    name = "bert_large" if large else "bert_base"
    out = {
        "metric": f"{name}_sen_sec_per_chip",
        "value": round(value, 2),
        "unit": "sen/s",
        "mfu": _mfu(flops, secs_per_step),
    }
    if hbm:
        out["peak_hbm_gb"] = round(hbm / 2**30, 3)
    baseline, protocol = (None, None) if large else _bert_baseline()
    if baseline:
        out["vs_baseline"] = round(value / baseline, 3)
        if protocol:
            # the protocol of whatever record pin-on-first-capture resolved
            # to — so both vs_baseline fields carry their own pin's protocol
            out["baseline_protocol"] = protocol
    return out


def bench_gpt(mesh):
    """GPT-2 (124M) S=1024 causal-LM pretraining throughput — the
    transformer-decoder headline (beyond the reference zoo; harness analog
    of dear/bert_benchmark.py:160-175). Round-5 configuration from the
    on-chip sweep (perf/onchip_r05/gpt_sweep/): batch 16, dropout 0 (the
    modern pretraining default — attention-probs dropout alone draws a
    [B,12,1024,1024] random mask per layer and halves throughput),
    streamed logsumexp LM loss, default %8 vocab padding (the %128
    lane-width A/B was a null result — GptConfig.vocab_pad_multiple).
    38.9% MFU on-chip vs the r04 headline's 22.9%."""
    import dataclasses

    from dear_pytorch_tpu import models
    from dear_pytorch_tpu.models import data
    from dear_pytorch_tpu.ops.fused_sgd import fused_sgd
    from dear_pytorch_tpu.parallel import dear as D

    batch_size = 2 if SMOKE else 16
    seq_len = 32 if SMOKE else 1024
    model = models.get_model("gpt2", dtype=jnp.bfloat16)
    cfg = models.dropout_free(model.config)
    if SMOKE:
        cfg = dataclasses.replace(
            cfg, num_hidden_layers=2, hidden_size=64,
            num_attention_heads=4, intermediate_size=128,
            vocab_size=128, max_position_embeddings=seq_len)
    model = models.GptLmHeadModel(cfg)
    batch = data.synthetic_gpt_batch(
        jax.random.PRNGKey(0), batch_size, seq_len=seq_len,
        vocab_size=cfg.vocab_size,
    )
    params = model.init({"params": jax.random.PRNGKey(0)},
                        batch["input_ids"], train=False)["params"]

    def loss_fn(p, b, rng):
        del rng  # dropout-free config
        logits = model.apply({"params": p}, b["input_ids"], train=True)
        return models.gpt_lm_loss(logits, b["input_ids"],
                                  vocab_size=cfg.vocab_size)

    ts = D.build_train_step(
        loss_fn, params, mesh=mesh, mode="dear", threshold_mb=25.0,
        optimizer=fused_sgd(lr=0.01, momentum=0.9),
        comm_dtype=jnp.bfloat16, gather_dtype=_gather_dtype(mesh.size),
        rng_seed=7,
    )
    state = ts.init(params)
    step_fn, flops, hbm = _compile_once(ts, state, batch)
    value, secs_per_step, _ = _timed(step_fn, state, batch,
                                     batch_size * seq_len)
    out = {
        "metric": "gpt2_s1024_tok_sec_per_chip",
        "value": round(value, 1),
        "unit": "tok/s",
        "mfu": _mfu(flops, secs_per_step),
    }
    if hbm:
        out["peak_hbm_gb"] = round(hbm / 2**30, 3)
    baseline, protocol = _history_baseline(
        "gpt2_s1024_tok_sec_per_chip", BASELINE_GPT_TOK_SEC)
    if baseline:
        out["vs_baseline"] = round(value / baseline, 3)
        out["baseline_protocol"] = protocol or BASELINE_GPT_PROTOCOL
        # the config delta behind vs_baseline, machine-readable: history
        # pins were captured by this same bench_gpt configuration; the
        # fallback literal was not (ADVICE.md)
        out["baseline_config"] = (
            "pinned-from-history (same bench_gpt config)" if protocol
            else BASELINE_GPT_CONFIG)
    return out


def _mfu(flops: float, secs_per_step: float):
    from dear_pytorch_tpu.utils import perf_model

    value = perf_model.mfu(flops, secs_per_step, jax.devices()[0])
    return round(value, 4) if value else None


class _Watchdog:
    """Per-phase hang guard: the session's tunneled TPU backend is known to
    hang indefinitely (device init / compile RPCs) when the tunnel drops.
    Built on `resilience.watchdog.StepWatchdog` (daemon thread +
    ``os._exit`` fires even while the main thread is stuck in a C call,
    which a signal handler would not; the firing report carries open
    telemetry spans and every thread's stack). Each phase gets its own
    budget (``arm`` beats the clock), and once the primary metric exists a
    late hang emits the partial result and exits 0 — a wedged second metric
    must not sink the primary. Disable with DEAR_BENCH_WATCHDOG_SECS=0."""

    def __init__(self):
        self.secs = float(os.environ.get("DEAR_BENCH_WATCHDOG_SECS", "2400"))
        self.primary = None
        self.extras: list = []  # completed secondary metrics so far
        self._dog = None
        self._phase = ""
        self._metric = ""

    def arm(self, phase: str, metric: str) -> None:
        if self.secs <= 0:
            return
        self._phase, self._metric = phase, metric
        if self._dog is None:
            from dear_pytorch_tpu.resilience import StepWatchdog

            self._dog = StepWatchdog(
                self.secs, on_timeout=self._fire, name="bench-watchdog"
            ).start()
        self._dog.beat(phase=phase, metric=metric)

    def disarm(self) -> None:
        if self._dog is not None:
            self._dog.pause()

    def _fire(self, report) -> None:
        phase, metric = self._phase, self._metric
        sys.stderr.write(
            f"bench.py watchdog: phase {phase!r} still running after "
            f"{report.waited_s:.0f}s — device backend likely wedged (tunnel "
            "down?); aborting\n"
        )
        sys.stderr.flush()
        err = {
            "metric": metric,
            "error": f"watchdog: {phase} wedged after {self.secs:.0f}s",
        }
        if self.primary is not None:
            out = dict(self.primary)
            # keep every secondary metric that already completed; if the
            # phase finished right at the timeout its result is already
            # in extras — don't also report it as wedged
            done = list(self.extras)
            if not any(m.get("metric") == metric for m in done):
                done.append(err)
            out["extra_metrics"] = done
            _emit(out)
            os._exit(0)
        # no primary yet: still honor the one-JSON-line contract so a
        # red round leaves machine-readable evidence, then exit red
        _emit(dict(err, metric=PRIMARY_METRIC))
        os._exit(3)


def main() -> None:
    from dear_pytorch_tpu.benchmarks import runner
    from dear_pytorch_tpu.comm import backend

    # Honor JAX_PLATFORMS/DEAR_NUM_CPU_DEVICES via jax.config: this
    # container's sitecustomize imports jax before us, so env-only platform
    # selection is too late (and CPU smoke runs would hang in the tunneled
    # backend's device init whenever the tunnel is down).
    runner.apply_platform_env()
    from dear_pytorch_tpu import observability

    if os.environ.get(observability.tracer.TELEMETRY_ENV) is None:
        # default-on, counters only (memory=False: no span records — the
        # timed loops must accumulate nothing) so the emitted JSON always
        # carries a telemetry block; an explicit DEAR_TELEMETRY value —
        # including an explicit disable — is honored as-is
        observability.configure(memory=False)
    dog = _Watchdog()
    dog.arm("resnet", PRIMARY_METRIC)
    try:
        mesh = backend.init()
    except Exception as exc:
        # a down backend must still yield the contract JSON line (plus a
        # documented nonzero rc), not a raw traceback
        dog.disarm()
        _emit({
            "metric": PRIMARY_METRIC,
            "error": f"backend unavailable: "
                     f"{type(exc).__name__}: {exc}"[:300],
        })
        sys.stderr.write(f"bench.py: backend init failed: {exc}\n")
        return 2
    resnet = bench_resnet(mesh)
    dog.primary = resnet
    dog.arm("bert", "bert_base_sen_sec_per_chip")
    try:
        bert = bench_bert(mesh)
    except Exception as exc:  # second metric must not sink the primary
        bert = {"metric": "bert_base_sen_sec_per_chip",
                "error": f"{type(exc).__name__}: {exc}"[:200]}
    extras = [bert]
    dog.extras = extras
    if _env_enabled("DEAR_BENCH_BERT_LARGE"):
        # the reference's flagship BERT config (dear/bert_config.json:
        # 1024h/24L) — BASELINE.md's second headline target. On by
        # default; set DEAR_BENCH_BERT_LARGE=0 to skip (it roughly
        # doubles the bench wall time, and a wedge mid-phase still emits
        # the earlier metrics via the watchdog).
        dog.arm("bert_large", "bert_large_sen_sec_per_chip")
        try:
            extras.append(bench_bert(mesh, "bert"))
        except Exception as exc:
            extras.append({"metric": "bert_large_sen_sec_per_chip",
                           "error": f"{type(exc).__name__}: {exc}"[:200]})
    if _env_enabled("DEAR_BENCH_VIT"):
        # GEMM-dominated vision headline; DEAR_BENCH_VIT=0 skips
        dog.arm("vit", "vit_b16_bs64_train_img_sec_per_chip")
        try:
            extras.append(bench_vit(mesh))
        except Exception as exc:
            extras.append({"metric": "vit_b16_bs64_train_img_sec_per_chip",
                           "error": f"{type(exc).__name__}: {exc}"[:200]})
    if _env_enabled("DEAR_BENCH_GPT"):
        # decoder headline (round-5 sweep config); DEAR_BENCH_GPT=0 skips
        dog.arm("gpt", "gpt2_s1024_tok_sec_per_chip")
        try:
            extras.append(bench_gpt(mesh))
        except Exception as exc:
            extras.append({"metric": "gpt2_s1024_tok_sec_per_chip",
                           "error": f"{type(exc).__name__}: {exc}"[:200]})
    dog.disarm()
    out = dict(resnet)
    out["extra_metrics"] = extras
    # counters + span aggregates from the run (plan builds, program
    # compiles, per-mode comm accounting when instrumented code ran)
    out["telemetry"] = observability.snapshot()
    # feed any DEAR_TELEMETRY prom:/stream: run-health sinks one final
    # snapshot (throughput + MFU as gauges), so a scraper sees the bench
    # round without parsing the contract line
    from dear_pytorch_tpu.observability import export as _export

    gauges = {}
    for m in [out] + extras:
        if isinstance(m.get("value"), (int, float)):
            gauges[m["metric"]] = m["value"]
            if isinstance(m.get("mfu"), (int, float)):
                gauges[f"{m['metric']}_mfu"] = m["mfu"]
    _export.write_streams(out["telemetry"], gauges)  # never raises
    _emit(out)


if __name__ == "__main__":
    sys.exit(main())
