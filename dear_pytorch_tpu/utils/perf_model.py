"""Analytic α-β communication/computation cost models.

The reference hard-codes α-β constants measured on its GPU clusters for
10GbE/56Gbps interconnects per worker count (reference dear/utils.py:62-88,
wfbp/dopt.py:385-400) and fits fresh ones with sklearn LinearRegression
(wfbp/dopt.py:260-285). On TPU the constants come from measuring XLA
collectives over ICI with `profiling.CommunicationProfiler` and fitting here
with a plain least-squares — no sklearn, no hard-coded tables (ICI bandwidth
is uniform enough within a pod that one (α, β) pair per topology suffices).
"""

from __future__ import annotations

import math
from typing import Sequence

import numpy as np


def predict_allreduce_time(alpha: float, beta: float, nbytes: float) -> float:
    """t = α + β·nbytes (reference ``predict_allreduce_time_with_size``,
    dear/utils.py:151-154)."""
    return alpha + beta * nbytes


def fit_alpha_beta(
    sizes_bytes: Sequence[float], times_s: Sequence[float]
) -> tuple[float, float]:
    """Least-squares fit of t ≈ α + β·size (replaces the sklearn
    LinearRegression fit, wfbp/dopt.py:260-285). Returns (α, β), clipped to
    be non-negative."""
    A = np.vstack([np.ones(len(sizes_bytes)), np.asarray(sizes_bytes)]).T
    (alpha, beta), *_ = np.linalg.lstsq(A, np.asarray(times_s), rcond=None)
    return max(float(alpha), 0.0), max(float(beta), 0.0)


#: bf16 peak FLOP/s per chip by device-kind substring.
DEVICE_PEAK_FLOPS = {
    "v5 lite": 197e12,
    "v5e": 197e12,
    "v4": 275e12,
    "v5p": 459e12,
    "v6": 918e12,
}


def device_peak_flops(device) -> float:
    """bf16 peak FLOP/s for a jax.Device (0.0 when unknown — callers should
    then report MFU as unavailable rather than guessing)."""
    kind = getattr(device, "device_kind", "").lower()
    for key, peak in DEVICE_PEAK_FLOPS.items():
        if key in kind:
            return peak
    return 0.0


def mfu(flops_per_step: float, secs_per_step: float, device) -> float:
    """Model FLOPs utilization: achieved FLOP/s over the chip's bf16 peak
    (the accounting the reference derives from nvprof dumps,
    horovod/prof.sh:1-2 + extract_profilings.py:3-11 — here XLA cost
    analysis makes it exact and free)."""
    peak = device_peak_flops(device)
    if not (flops_per_step and peak and secs_per_step):
        return 0.0
    return flops_per_step / secs_per_step / peak


def peak_hbm_bytes(compiled) -> float:
    """Peak device memory of a compiled executable (argument + output +
    temp + generated code), from XLA's memory analysis. 0.0 when the
    backend doesn't expose it. The reference has no analog — GPU peak
    memory there is whatever nvidia-smi happens to show; on TPU the
    compiler knows the exact static allocation."""
    try:
        m = compiled.memory_analysis()
        return float(
            getattr(m, "argument_size_in_bytes", 0)
            + getattr(m, "output_size_in_bytes", 0)
            + getattr(m, "temp_size_in_bytes", 0)
            + getattr(m, "generated_code_size_in_bytes", 0)
            - getattr(m, "alias_size_in_bytes", 0)
        )
    except Exception:
        return 0.0


def topk_perf_model(n: int, s: float = 2.18e-9) -> float:
    """Cost model of a top-k over n elements, s·n·log2 n (reference
    dear/utils.py:95-102)."""
    if n <= 1:
        return 0.0
    return s * n * math.log2(n)


def allgather_perf_model(
    nbytes: float, world: int, alpha: float, beta: float
) -> float:
    """Ring all-gather cost: (world-1) rounds of α + β·(nbytes/world)
    (reference dear/utils.py:104-117 models allgather for the sparse path)."""
    if world <= 1:
        return 0.0
    return (world - 1) * (alpha + beta * nbytes / world)
