"""Analytic α-β communication/computation cost models.

The reference hard-codes α-β constants measured on its GPU clusters for
10GbE/56Gbps interconnects per worker count (reference dear/utils.py:62-88,
wfbp/dopt.py:385-400) and fits fresh ones with sklearn LinearRegression
(wfbp/dopt.py:260-285). On TPU the constants come from measuring XLA
collectives over ICI with `profiling.CommunicationProfiler` and fitting here
with a plain least-squares — no sklearn, no hard-coded tables (ICI bandwidth
is uniform enough within a pod that one (α, β) pair per topology suffices).
"""

from __future__ import annotations

import math
from typing import Sequence

import numpy as np


def predict_allreduce_time(alpha: float, beta: float, nbytes: float) -> float:
    """t = α + β·nbytes (reference ``predict_allreduce_time_with_size``,
    dear/utils.py:151-154)."""
    return alpha + beta * nbytes


def fit_alpha_beta(
    sizes_bytes: Sequence[float], times_s: Sequence[float]
) -> tuple[float, float]:
    """Least-squares fit of t ≈ α + β·size (replaces the sklearn
    LinearRegression fit, wfbp/dopt.py:260-285). Returns (α, β), clipped to
    be non-negative."""
    A = np.vstack([np.ones(len(sizes_bytes)), np.asarray(sizes_bytes)]).T
    (alpha, beta), *_ = np.linalg.lstsq(A, np.asarray(times_s), rcond=None)
    return max(float(alpha), 0.0), max(float(beta), 0.0)


def topk_perf_model(n: int, s: float = 2.18e-9) -> float:
    """Cost model of a top-k over n elements, s·n·log2 n (reference
    dear/utils.py:95-102)."""
    if n <= 1:
        return 0.0
    return s * n * math.log2(n)


def allgather_perf_model(
    nbytes: float, world: int, alpha: float, beta: float
) -> float:
    """Ring all-gather cost: (world-1) rounds of α + β·(nbytes/world)
    (reference dear/utils.py:104-117 models allgather for the sparse path)."""
    if world <= 1:
        return 0.0
    return (world - 1) * (alpha + beta * nbytes / world)
