"""Object-store-shaped durable tier for checkpoint streaming.

The resilience stack's checkpoints are only as durable as the disk they
land on: per-host storage (``DEAR_CKPT_SHARED=0``) dies with the host,
and even shared NFS dies with the filesystem. The continuous-training
service (docs/RESILIENCE.md "Autoscaling") adds a **remote tier**: a
background uploader (`utils.checkpoint.CheckpointStreamer`) streams
committed step dirs to an object store, so a fully-lost fleet — or a
scale-from-zero cold start — restores from the remote tier alone with
zero loss past the newest uploaded step.

This module defines the store *shape* and its local-directory reference
implementation. The interface is deliberately the narrow waist every
real object store offers (GCS/S3 semantics, no rename, no append):

    put_bytes(key, data)     atomic whole-object write
    get_bytes(key) -> bytes  whole-object read (KeyError when absent)
    put_file(key, path)      upload one local file
    get_file(key, dest)      download one object to a local path
    list(prefix) -> [key]    every key under a prefix
    delete_prefix(prefix)    best-effort recursive delete
    exists(key) -> bool

A production deployment implements the same seven methods over its
bucket client; everything above the waist (manifest commit protocol,
retry, sha256 reverify, retention) lives in `utils.checkpoint` and is
backend-agnostic.

`LocalObjectStore` maps keys to files under a root directory with
tmp-then-``os.replace`` atomicity — a reader can never observe a torn
object, which is what lets ``MANIFEST.json`` act as the per-step commit
marker (a remote step exists iff its manifest does).
"""

from __future__ import annotations

import os
import shutil
from typing import List

__all__ = ["LocalObjectStore"]


class LocalObjectStore:
    """Local-directory object store (the GCS/S3 stand-in).

    Keys are '/'-separated and mirror onto a directory tree so the store
    stays human-debuggable (``ls`` the root to watch an upload land).
    """

    def __init__(self, root: str):
        self.root = os.path.abspath(root)
        os.makedirs(self.root, exist_ok=True)

    def _path(self, key: str) -> str:
        parts = [p for p in key.split("/") if p not in ("", ".", "..")]
        return os.path.join(self.root, *parts)

    # -- the seven-method waist ----------------------------------------------

    def put_bytes(self, key: str, data: bytes) -> None:
        path = self._path(key)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, path)  # readers see the whole object or none

    def get_bytes(self, key: str) -> bytes:
        try:
            with open(self._path(key), "rb") as f:
                return f.read()
        except OSError:
            raise KeyError(key) from None

    def put_file(self, key: str, path: str) -> None:
        dest = self._path(key)
        os.makedirs(os.path.dirname(dest), exist_ok=True)
        tmp = f"{dest}.tmp.{os.getpid()}"
        shutil.copyfile(path, tmp)
        os.replace(tmp, dest)

    def get_file(self, key: str, dest: str) -> None:
        src = self._path(key)
        if not os.path.isfile(src):
            raise KeyError(key)
        os.makedirs(os.path.dirname(os.path.abspath(dest)), exist_ok=True)
        tmp = f"{dest}.tmp.{os.getpid()}"
        shutil.copyfile(src, tmp)
        os.replace(tmp, dest)

    def list(self, prefix: str) -> List[str]:
        """Every committed key under ``prefix`` (in-flight tmp files
        excluded), as full keys relative to the store root."""
        base = self._path(prefix)
        if not os.path.isdir(base):
            return []
        out = []
        for dirpath, _dirs, files in os.walk(base):
            for fn in files:
                if ".tmp." in fn:
                    continue
                rel = os.path.relpath(os.path.join(dirpath, fn), self.root)
                out.append(rel.replace(os.sep, "/"))
        return sorted(out)

    def delete_prefix(self, prefix: str) -> None:
        shutil.rmtree(self._path(prefix), ignore_errors=True)

    def exists(self, key: str) -> bool:
        return os.path.isfile(self._path(key))
