"""Object-store-shaped durable tier for checkpoint streaming.

The resilience stack's checkpoints are only as durable as the disk they
land on: per-host storage (``DEAR_CKPT_SHARED=0``) dies with the host,
and even shared NFS dies with the filesystem. The continuous-training
service (docs/RESILIENCE.md "Autoscaling") adds a **remote tier**: a
background uploader (`utils.checkpoint.CheckpointStreamer`) streams
committed step dirs to an object store, so a fully-lost fleet — or a
scale-from-zero cold start — restores from the remote tier alone with
zero loss past the newest uploaded step.

This module defines the store *shape* and its local-directory reference
implementation. The interface is deliberately the narrow waist every
real object store offers (GCS/S3 semantics, no rename, no append):

    put_bytes(key, data)     atomic whole-object write
    get_bytes(key) -> bytes  whole-object read (KeyError when absent)
    put_file(key, path)      upload one local file
    get_file(key, dest)      download one object to a local path
    list(prefix) -> [key]    every key under a prefix, **sorted
                             lexicographically by key** — pinned: readers
                             (feedback-log segment walks, version scans)
                             rely on the order being stable under
                             concurrent appenders
    delete_prefix(prefix)    best-effort recursive delete
    exists(key) -> bool
    put_bytes_if_absent(key, data) -> bool
                             first-writer-wins whole-object publish
                             (GCS ``ifGenerationMatch=0`` / S3
                             ``If-None-Match:*`` semantics)

A production deployment implements the same eight methods over its
bucket client; everything above the waist (manifest commit protocol,
retry, sha256 reverify, retention) lives in `utils.checkpoint` /
`online.feedback` and is backend-agnostic.

`LocalObjectStore` maps keys to files under a root directory with
tmp-then-``os.replace`` atomicity — a reader can never observe a torn
object, which is what lets ``MANIFEST.json`` act as the per-step commit
marker (a remote step exists iff its manifest does).
"""

from __future__ import annotations

import os
import shutil
import threading
from typing import List

__all__ = ["LocalObjectStore"]


class LocalObjectStore:
    """Local-directory object store (the GCS/S3 stand-in).

    Keys are '/'-separated and mirror onto a directory tree so the store
    stays human-debuggable (``ls`` the root to watch an upload land).
    """

    def __init__(self, root: str):
        self.root = os.path.abspath(root)
        os.makedirs(self.root, exist_ok=True)

    def _path(self, key: str) -> str:
        parts = [p for p in key.split("/") if p not in ("", ".", "..")]
        return os.path.join(self.root, *parts)

    # -- the seven-method waist ----------------------------------------------

    def put_bytes(self, key: str, data: bytes) -> None:
        path = self._path(key)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, path)  # readers see the whole object or none

    def get_bytes(self, key: str) -> bytes:
        try:
            with open(self._path(key), "rb") as f:
                return f.read()
        except OSError:
            raise KeyError(key) from None

    def put_file(self, key: str, path: str) -> None:
        dest = self._path(key)
        os.makedirs(os.path.dirname(dest), exist_ok=True)
        tmp = f"{dest}.tmp.{os.getpid()}"
        shutil.copyfile(path, tmp)
        os.replace(tmp, dest)

    def get_file(self, key: str, dest: str) -> None:
        src = self._path(key)
        if not os.path.isfile(src):
            raise KeyError(key)
        os.makedirs(os.path.dirname(os.path.abspath(dest)), exist_ok=True)
        tmp = f"{dest}.tmp.{os.getpid()}"
        shutil.copyfile(src, tmp)
        os.replace(tmp, dest)

    def put_bytes_if_absent(self, key: str, data: bytes) -> bool:
        """First-writer-wins whole-object publish: write ``data`` under
        ``key`` unless a committed object is already there; returns True
        when this call created the object, False when it lost (the
        existing object is left intact either way). Atomic via the
        hard-link idiom (`resilience.cluster.FileTransport.decide_once`):
        the tmp file is complete before linking, so a reader can never
        observe a torn winner, and ``link`` fails with EEXIST when
        another writer won. Real bucket clients map this to conditional
        puts (GCS ``ifGenerationMatch=0``, S3 ``If-None-Match: *``).
        This is what makes duplicate segment publication idempotent for
        the feedback log's commit markers (`online.feedback`)."""
        path = self._path(key)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
        with open(tmp, "wb") as f:
            f.write(data)
        try:
            os.link(tmp, path)
            return True
        except FileExistsError:
            return False
        except OSError:
            # filesystem without hard links (some FUSE mounts): exclusive
            # create of the final path — racier (a concurrent reader can
            # catch the value mid-write) but still first-writer-wins
            try:
                fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
                with os.fdopen(fd, "wb") as f:
                    f.write(data)
                return True
            except FileExistsError:
                return False
        finally:
            try:
                os.remove(tmp)
            except OSError:
                pass

    def list(self, prefix: str) -> List[str]:
        """Every committed key under ``prefix`` (in-flight tmp files
        excluded), as full keys relative to the store root, **sorted
        lexicographically by key** — the ordering contract concurrent
        appenders and segment-walking readers rely on."""
        base = self._path(prefix)
        if not os.path.isdir(base):
            return []
        out = []
        for dirpath, _dirs, files in os.walk(base):
            for fn in files:
                if ".tmp." in fn:
                    continue
                rel = os.path.relpath(os.path.join(dirpath, fn), self.root)
                out.append(rel.replace(os.sep, "/"))
        return sorted(out)

    def delete_prefix(self, prefix: str) -> None:
        shutil.rmtree(self._path(prefix), ignore_errors=True)

    def exists(self, key: str) -> bool:
        return os.path.isfile(self._path(key))
