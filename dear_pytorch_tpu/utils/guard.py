"""Failure detection + recovery around the train step.

The reference has NONE of this (SURVEY.md §5: any MPI/CUDA/NCCL error
aborts the process via CHECK macros; its batch driver retries at whole-job
granularity). On TPU the failure surface is different — device errors
surface as Python exceptions from a blocked fetch, and the classic silent
killer is numerical: a NaN/Inf loss that poisons every parameter within a
few donated steps. `GuardedTrainer` wraps a `TrainStep` with:

  - **divergence detection**: the loss is fetched and checked every
    ``check_every`` steps (fetch = one scalar device->host sync; keep the
    cadence coarse on remote devices),
  - **rollback**: on a non-finite loss (or a raised step error) the state
    restores from the newest periodic checkpoint and training continues,
    skipping forward past the poisoned step,
  - **periodic checkpoints**: every ``checkpoint_every`` steps through
    `utils.checkpoint` (plan-fingerprinted, sharded, multi-host safe),
  - **step-time accounting**: wall-clock EMA + max, so a hung collective
    shows up in logs with the last-good step number.

This is single-program recovery (the process survives). Whole-process
elasticity (host loss on a pod) composes on top: pass a
`resilience.membership.ElasticCluster` as the ``coordinator`` and a
confirmed peer loss SHRINKS the membership instead of crashing the job —
the guard treats any ``membership_changed`` health verdict as a
transition point: it invokes ``on_membership_change`` (where the loop
rebuilds its train step for the new replica count, e.g.
`tuning.autotune.AutoTuner.rescale`), reshards the input ``pipeline``
(`runtime.pipeline.reshard`), and rolls every survivor back to the
newest step valid on all of them. A checkpoint packed under the
pre-change plan restores through `utils.checkpoint.elastic_restore`
(the plan fingerprint carries the membership epoch, so the mismatch is
detected, never silently unpacked). A relaunched rank re-enters through
`ElasticCluster.rejoin` + `elastic_resume` (docs/RESILIENCE.md
"Elastic membership").

The resilience layer (`dear_pytorch_tpu.resilience`, docs/RESILIENCE.md)
plugs in here:

  - **fault injection**: a `FaultInjector` (or ``DEAR_FAULTS`` in the
    environment) fires deterministic NaN/exception/hang/corruption/
    preemption faults inside the guarded step, so every branch below is
    exercised code (`scripts/chaos_check.py`),
  - **watchdog heartbeats**: pass a `StepWatchdog` and every completed
    step beats it with the last-good checkpoint step,
  - **preemption**: pass a `PreemptionHandler` and a SIGTERM triggers a
    verified, synchronous emergency checkpoint at the next step boundary
    (``metrics["preempted"]`` tells the loop to exit),
  - **corruption fallback**: restores verify the sidecar checksum
    manifest and walk back past corrupted checkpoints,
  - **cluster coordination**: on multi-process runs every recovery
    decision is a *consensus* decision through a
    `resilience.cluster.ClusterCoordinator` (created automatically;
    ``DEAR_CLUSTER=0`` restores the legacy crash-for-relaunch behavior):
    a per-check-interval any-rank-unhealthy exchange turns a local
    exception or NaN on one rank into the SAME rollback on all ranks,
    restores go to the newest checkpoint verified on *every* host, a
    desync sentinel fingerprints the replicated loss to catch silent
    replica divergence, and a preemption signal seen by one rank
    propagates so emergency saves stay cooperative. A hung peer trips the
    exchange's bounded timeout and degrades to the old crash behavior
    (after kicking the watchdog's forensic dump) instead of deadlocking,
  - **telemetry**: every recovery event lands in `observability` counters
    (``guard.rollbacks``, ``guard.restores``, ``guard.steps_skipped``,
    ``cluster.*``, ...) so it shows up in `bench.py` telemetry blocks,
  - **run health** (docs/OBSERVABILITY.md "Run health"): with telemetry
    enabled every step lands in the `observability.flight` ring (dumped
    on rollback and by watchdog forensics), the check cadence feeds the
    `observability.anomaly` detectors (``health.*`` counters; set
    ``DEAR_HEALTH_KICK=1`` to escalate an anomaly into a watchdog
    forensic dump), coordinated runs piggyback an
    `observability.aggregate` digest exchange on the health sync (rank 0
    holds the merged cluster snapshot in ``merged_health`` — straggler
    rank, fleet counters), and any configured ``prom:``/``stream:``
    exporters are fed each interval.
"""

from __future__ import annotations

import json
import logging
import math
import os
import time
from typing import Any, Callable, Optional

import jax

from dear_pytorch_tpu.observability import aggregate as _aggregate
from dear_pytorch_tpu.observability import anomaly as _anomaly
from dear_pytorch_tpu.observability import dtrace as _dtrace
from dear_pytorch_tpu.observability import export as _export
from dear_pytorch_tpu.observability import flight as _flight
from dear_pytorch_tpu.observability import tracer as _telemetry
from dear_pytorch_tpu.resilience import cluster as _cluster
from dear_pytorch_tpu.resilience import inject as _inject
from dear_pytorch_tpu.resilience import sdc as _sdc
from dear_pytorch_tpu.utils import checkpoint as ckpt

logger = logging.getLogger("dear_pytorch_tpu")


def _is_dcn_error(exc: BaseException) -> bool:
    """Is this a cross-slice (host DCN leg) failure? Lazy import: the
    guard must not pull the hierarchical machinery into single-level
    runs; the isinstance check caches the class after first use."""
    from dear_pytorch_tpu.comm.dcn import DcnError

    return isinstance(exc, DcnError)


def _is_self_evict(exc: BaseException) -> bool:
    """Is this the degraded-DCN ladder's self-eviction verdict? Must NOT
    be handled as a step error: a rollback cannot fix an outbound
    partition — the rank exits for relaunch + rejoin instead."""
    from dear_pytorch_tpu.comm.dcn import DcnSelfEvict

    return isinstance(exc, DcnSelfEvict)


class DivergenceError(RuntimeError):
    """Raised when training diverges and no checkpoint exists to restore."""


class PeerLostError(RuntimeError):
    """A peer never reached the coordinated health sync (hung or dead
    host); raised after the forensic dump so the job crashes for
    whole-job relaunch instead of deadlocking."""


class GuardedTrainer:
    """Wrap ``ts`` (a `parallel.TrainStep`) with detection + recovery.

    Usage::

        trainer = GuardedTrainer(ts, directory, params)
        for batch in batches:
            state, metrics = trainer.step(state, batch)
    """

    def __init__(
        self,
        ts,
        directory: str,
        params_template,
        *,
        check_every: int = 50,
        checkpoint_every: int = 500,
        max_recoveries: int = 3,
        max_keep: int = 3,
        on_rollback: Optional[Callable[[int, int], None]] = None,
        async_checkpoints: bool = False,
        injector: Optional[Any] = None,
        watchdog: Optional[Any] = None,
        preemption: Optional[Any] = None,
        coordinator: Optional[Any] = None,
        pipeline: Optional[Any] = None,
        on_membership_change: Optional[Callable[[Any], None]] = None,
        streamer: Optional[Any] = None,
    ):
        self.ts = ts
        self.directory = directory
        self.async_checkpoints = async_checkpoints
        self.check_every = max(int(check_every), 1)
        self.checkpoint_every = max(int(checkpoint_every), 1)
        self.max_recoveries = max_recoveries
        self.max_keep = max(int(max_keep), 1)
        self.on_rollback = on_rollback
        # resilience hooks: an explicit injector wins; otherwise consult
        # DEAR_FAULTS (None when unset — zero per-step overhead)
        self._injector = (injector if injector is not None
                          else _inject.FaultInjector.from_env())
        self._watchdog = watchdog
        self._preemption = preemption
        # cluster coordination: an explicit coordinator wins; multi-process
        # runs get one automatically (consensus recovery is the default
        # multi-host policy) unless DEAR_CLUSTER=0 keeps the legacy
        # crash-for-relaunch branches.
        if (coordinator is None and jax.process_count() > 1
                and _cluster.enabled_by_env()):
            # the namespace must be identical on every rank — never derive
            # it from the directory, which is rank-specific under per-host
            # checkpoint storage; the coordinator's SPMD instance counter
            # already separates multiple trainers in one process
            coordinator = _cluster.ClusterCoordinator(namespace="guard")
        self._coordinator = coordinator
        # deterministic data resume/resharding: a pipeline handed to the
        # guard has its state_dict persisted in every checkpoint sidecar,
        # restored on rollback, and resharded on membership changes
        self._pipeline = pipeline
        self.on_membership_change = on_membership_change
        # durable remote tier: a `ckpt.CheckpointStreamer` handed to the
        # guard gets every committed save enqueued (emergency saves are
        # additionally flushed inside the preemption grace budget). The
        # caller owns the streamer's lifecycle; `finalize` only flushes.
        self._streamer = streamer
        self._pending_reshard = False
        # SDC sentinel (resilience.sdc): per-bucket fingerprint voting on
        # the health exchange, the replay arbiter over the rollback path,
        # and the host-keyed quarantine ledger. Armed by DEAR_SDC on
        # coordinated runs only — the vote needs peers.
        self._sdc: Optional[_sdc.SdcSentinel] = None
        self._sdc_drain = False
        if self._coordinator is not None and _sdc.sdc_enabled():
            sdc_rank = getattr(self._coordinator, "rank",
                               getattr(self._coordinator, "index", None))
            self._sdc = _sdc.SdcSentinel.from_env(rank=sdc_rank)
        # run-health layer: flight ring (enabled alongside telemetry; see
        # the _flight property), anomaly detectors on the check cadence,
        # and — on coordinated runs — the digest aggregation that rides
        # the health exchanges.
        self._anomaly: Optional[_anomaly.AnomalyMonitor] = None
        if (_telemetry.get_tracer().enabled
                and _anomaly.AnomalyMonitor.enabled_by_env()):
            self._anomaly = _anomaly.AnomalyMonitor.from_env(
                on_anomaly=self._on_anomaly)
        self._aggregator: Optional[_aggregate.MetricAggregator] = None
        if self._coordinated and hasattr(self._coordinator, "exchange"):
            # aggregation needs the raw exchange primitive; a scripted
            # verdict-only coordinator (tests) simply skips it
            self._aggregator = _aggregate.MetricAggregator(
                self._coordinator)
        self.merged_health: Optional[dict] = None
        self._prev_step_t: Optional[float] = None
        self._last_loss: Optional[float] = None
        self._pending_error: Optional[BaseException] = None
        self._peer_preempt = False
        self._preempt_handled = False
        self._preempt_saved_step: Optional[int] = None
        self._template = None
        self._params_template = params_template
        self.recoveries = 0          # CONSECUTIVE rollbacks without a new
        self.steps_seen = 0          # healthy checkpoint in between
        self.ema_step_s = None
        self.max_step_s = 0.0
        self._last_good_step = None
        self._last_check_t = None
        self._last_check_steps = 0
        # startup GC: a previous crash may have left unrestorable Orbax
        # atomic-write temp dirs. Skipped once this process has ever run
        # an async save — a second trainer on the same directory must not
        # sweep the first one's legitimately in-flight write (the
        # post-save prune, which knows the in-flight step, covers GC then).
        if not ckpt.has_async_checkpointer():
            ckpt.prune_orphaned_tmp(directory)

    # -- internals -----------------------------------------------------------

    @property
    def _flight(self):
        """The process-global flight recorder, resolved per access (one
        module-dict lookup) rather than cached at construction — the ring
        follows `tracer.configure()`/`disable()` after the trainer is
        built, keeping guard dumps in step with the watchdog's and the
        digest's view of it."""
        return _flight.get_recorder()

    @property
    def _coordinated(self) -> bool:
        """True when recovery decisions go through the cluster consensus
        protocol — a coordinator over a real multi-process world, OR an
        elastic membership (`supports_membership`) at ANY world size: a
        fleet shrunk to a sole survivor must keep running its health
        sync (the world-1 exchange is a no-op, but the sync is where
        rejoin requests are polled) or the relaunched ranks are never
        admitted and the fleet can never grow back (observed: a 2-rank
        fleet whose victim was SIGKILLed stayed world-1 forever while
        the relaunch waited out its entire admission timeout)."""
        if self._coordinator is None:
            return False
        return (self._coordinator.process_count > 1
                or getattr(self._coordinator, "supports_membership",
                           False))

    @property
    def _mem_epoch(self) -> Optional[int]:
        """The elastic membership epoch (None outside elastic runs) —
        stamped into every checkpoint sidecar so a relaunched rank can
        present its last known epoch to the rejoin protocol."""
        return getattr(self._coordinator, "epoch", None)

    def _pipeline_state(self) -> Optional[dict]:
        if self._pipeline is None:
            return None
        try:
            return self._pipeline.state_dict()
        except Exception as exc:  # a stats bug must not block the save
            logger.error("guard: pipeline.state_dict() failed: %s", exc)
            return None

    def _restore_pipeline(self, step: int) -> None:
        """Resume the input pipeline at the position persisted with the
        checkpoint being restored — without this every rollback silently
        replays (or skips) data."""
        if self._pipeline is None:
            return
        pstate = ckpt.read_pipeline_state(self.directory, step)
        if pstate is None:
            logger.warning(
                "guard: checkpoint step %d has no pipeline sidecar state; "
                "the data stream position is NOT restored", step)
            return
        try:
            self._pipeline.load_state_dict(pstate)
        except Exception as exc:  # a spec change must not kill recovery
            logger.error(
                "guard: pipeline state restore failed (%s); continuing "
                "with the live stream position", exc)

    def _dcn_state(self) -> Optional[dict]:
        """The cross-slice exchanger's ladder state (error-feedback
        residual + staleness clocks) for the checkpoint sidecar — None
        on non-hierarchical schedules or when there is nothing carried
        (keeps legacy sidecars byte-identical)."""
        dcn = getattr(self.ts, "dcn", None)
        if dcn is None or not hasattr(dcn, "state_dict"):
            return None
        try:
            state = dcn.state_dict()
        except Exception as exc:  # a ladder bug must not block the save
            logger.error("guard: dcn.state_dict() failed: %s", exc)
            return None
        if not state.get("residual") and not state.get("staleness"):
            return None
        return state

    def _restore_dcn(self, step: int) -> None:
        """Re-seat the degraded-DCN error-feedback residual persisted
        with the checkpoint being restored — the deferred gradient mass
        belongs to THESE parameters; keeping the live residual across a
        rollback would double-count every skipped round the replay
        re-earns."""
        dcn = getattr(self.ts, "dcn", None)
        if dcn is None or not hasattr(dcn, "load_state_dict"):
            return
        try:
            dcn.load_state_dict(ckpt.read_dcn_state(self.directory, step))
        except Exception as exc:  # a sidecar bug must not kill recovery
            logger.error(
                "guard: dcn ladder state restore failed (%s); continuing "
                "with fresh (empty) residuals", exc)

    def _reshard_pipeline(self) -> None:
        """Reassign this rank's data slice after a committed membership
        transition. The shard slot is the view's ``data_shard`` — the
        member position on rank-granular fleets, the SLICE position on
        slice-granular ones (a slice's ranks are lockstep replicas of
        one shard; see `resilience.membership.MembershipView`)."""
        self._pending_reshard = False
        view_fn = getattr(self._coordinator, "view", None)
        if self._pipeline is None or view_fn is None:
            return
        view = view_fn()
        shard = getattr(view, "data_shard", view.index)
        world = getattr(view, "data_world", view.world)
        try:
            self._pipeline.reshard(shard, world, epoch=view.epoch)
        except Exception as exc:
            logger.error(
                "guard: pipeline reshard to %d/%d (epoch %d) failed: %s",
                shard, world, view.epoch, exc)

    def _restore_step(self, step: int):
        """Restore one step into the live plan's layout; a checkpoint
        packed under a DIFFERENT plan (pre-membership-change epoch, or a
        different world size) re-packs through `ckpt.elastic_restore`
        instead of failing — the fingerprint mismatch is the signal, the
        sidecar's plan_desc is the recovery path."""
        try:
            return ckpt.restore_checkpoint(
                self.directory, self.ts, step=step,
                template=self._template_state(),
            )
        except ckpt.PlanMismatchError:
            logger.warning(
                "guard: checkpoint step %d predates the live plan "
                "(membership epoch %s); elastic re-pack restore",
                step, self._mem_epoch)
            tr = _telemetry.get_tracer()
            if tr.enabled:
                tr.event("guard.elastic_restore", step=step,
                         epoch=self._mem_epoch or 0)
            return ckpt.elastic_restore(self.directory, self.ts, step=step)

    @property
    def _drain_on_preempt(self) -> bool:
        """Should a SIGTERM become a single-rank planned shrink instead of
        a fleet-wide preemption? Only coordinators that speak the drain
        protocol (`ElasticCluster.supports_draining`) can; the env knob
        keeps the full-fleet propagate semantics selectable."""
        if not getattr(self._coordinator, "supports_draining", False):
            return False
        return os.environ.get("DEAR_PREEMPT_DRAIN", "").strip().lower() \
            not in ("0", "false", "no", "off")

    @property
    def _preempt_requested(self) -> bool:
        """Should this step act on a preemption? Coordinated runs act only
        once the signal has propagated through the health sync, so every
        rank performs the (cooperative, collective) emergency save at the
        same boundary — a lone rank's save would wedge the pod. The cost:
        up to one check interval of propagation latency, so on coordinated
        runs size ``check_every`` such that ``check_every × step_time`` is
        well inside the platform's preemption grace window."""
        if self._coordinated:
            return self._peer_preempt
        return self._preemption is not None and self._preemption.requested

    def _template_state(self):
        if self._template is None:
            self._template = self.ts.init(self._params_template)
        return self._template

    def _save(self, state) -> bool:
        """True when the save committed (or was enqueued after a clean
        handoff); False on a swallowed async failure — the caller must NOT
        treat that as persisted progress."""
        step = int(jax.device_get(state.step))
        try:
            ckpt.save_checkpoint(self.directory, state, self.ts.plan,
                                 asynchronous=self.async_checkpoints,
                                 pipeline_state=self._pipeline_state(),
                                 mem_epoch=self._mem_epoch,
                                 dcn_state=self._dcn_state())
        except Exception as exc:
            if not self.async_checkpoints:
                raise
            # Orbax surfaces a PREVIOUS async write's deferred failure at
            # the next save call. The training state in hand is healthy —
            # losing one checkpoint must not kill the run this class exists
            # to keep alive. Log, skip this save, try again next interval —
            # but still run retention: a failure streak would otherwise
            # accumulate failed-write tmp dirs and orphan sidecars without
            # bound. THIS call's write may have been enqueued before the
            # exception (e.g. a sidecar failure after AsyncCheckpointer
            # created its tmp dir), so its tmp dir must survive the prune.
            logger.error("guard: async checkpoint save failed: %s", exc)
            tr = _telemetry.get_tracer()
            if tr.enabled:
                tr.count("guard.checkpoint_failures")
                tr.event("guard.checkpoint_failed", step=step,
                         error=type(exc).__name__)
            self._prune(skip_tmp_step=step)
            return False
        self._last_good_step = step
        tr = _telemetry.get_tracer()
        if tr.enabled:
            tr.count("guard.checkpoints")
        # async: the save's own atomic-write temp dir is legitimately alive
        # right now — pruning it would corrupt the in-flight write
        self._prune(
            skip_tmp_step=(self._last_good_step
                           if self.async_checkpoints else None)
        )
        if self._streamer is not None:
            # remote tier: the streamer's worker waits for the local
            # commit itself (async saves land late), so this is a queue
            # put — nothing on the step path
            self._streamer.enqueue(step)
        return True

    def _prune(self, skip_tmp_step: Optional[int] = None) -> None:
        """Keep the newest ``max_keep`` checkpoints (the guard only ever
        restores the latest; unbounded retention would eventually fill the
        filesystem and crash the very trainer meant to survive faults).
        The GC itself lives in `utils.checkpoint.prune_checkpoints`."""
        ckpt.prune_checkpoints(self.directory, max_keep=self.max_keep,
                               skip_tmp_step=skip_tmp_step)

    def _restore(self, cause: Optional[BaseException] = None):
        # an async save may still be in flight: its step dir only appears
        # on commit, so wait — rolling back to the older checkpoint while a
        # newer healthy one is mid-write would lose good progress. A FAILED
        # in-flight write must not kill the rollback itself: fall back to
        # the newest committed checkpoint.
        try:
            ckpt.wait_for_checkpoints()
        except Exception as exc:
            logger.error(
                "guard: in-flight async checkpoint failed (%s); restoring "
                "the newest committed checkpoint instead", exc,
            )
        tr = _telemetry.get_tracer()
        if self._coordinated:
            # multi-host consensus restore: every process contributes its
            # locally VERIFIED steps and all restore the newest step valid
            # on every host — a checkpoint corrupted anywhere degrades the
            # whole pod to the previous common step, in lockstep, instead
            # of crashing (old policy) or desynchronizing (per-host walk).
            # On SHARED storage all ranks see one directory, so rank 0
            # verifies for everyone (N ranks re-hashing identical
            # multi-GB files would multiply recovery latency for nothing);
            # per-host storage genuinely has one view per rank.
            if ckpt.per_host_storage() or self._coordinator.index == 0:
                local = ckpt.valid_steps(
                    self.directory, limit=self._coordinator.max_candidates)
            else:
                local = None  # defer to rank 0's verification
            epoch_before = getattr(self._coordinator, "epoch", None)
            step = self._coordinator.consensus_restore_step(local)
            if step is None:
                raise DivergenceError(
                    "no checkpoint step is verified on every host; "
                    "nothing commonly restorable (see the chained cause)"
                ) from cause
            if (epoch_before is not None
                    and getattr(self._coordinator, "epoch",
                                epoch_before) != epoch_before):
                # a SECOND failure during the restore exchange
                # reconfigured the membership again (elastic clusters
                # retry the exchange over the survivors): rebuild for the
                # newest view BEFORE unpacking, or the restore lands in a
                # plan built for a membership that no longer exists and
                # later sidecars stamp an epoch the plan doesn't carry
                logger.critical(
                    "guard: membership moved during the restore exchange "
                    "(epoch %s -> %s); rebuilding for the newest view",
                    epoch_before, self._coordinator.epoch)
                self._pending_reshard = True
                if self.on_membership_change is not None:
                    self.on_membership_change(self._coordinator.view())
                    self._template = None
                if tr.enabled:
                    tr.count("guard.membership_changes")
                    tr.event("guard.membership_change",
                             epoch=self._coordinator.epoch,
                             during="restore")
            # every rank is now committed to this step: a restore failure
            # here must propagate (crash for whole-job relaunch) — falling
            # back locally would desynchronize replicas.
            state = self._restore_step(step)
            self._template = None
            self._restore_pipeline(step)
            self._restore_dcn(step)
            # the consensus step may be OLDER than this rank's newest
            # (elastic rejoin, a step corrupted elsewhere): anything newer
            # is now an abandoned timeline — replay will re-reach those
            # step numbers with different parameters
            ckpt.prune_future_steps(self.directory, above=step)
            logger.warning(
                "guard: consensus rollback to checkpoint step %d", step)
            if tr.enabled:
                tr.count("guard.restores")
                tr.event("guard.restore", step=step, consensus=1)
            return state, step
        if jax.process_count() > 1:
            # legacy multi-host (DEAR_CLUSTER=0 / no coordinator): every
            # process must restore the SAME step. The verification/
            # fallback walk below decides per process (a transient local
            # fs error on one host would silently pick an older step
            # there, desynchronizing replicas) — so restore the newest
            # committed step deterministically and let a failure crash
            # for whole-job relaunch, same policy as local step
            # exceptions above.
            step = ckpt.latest_step(self.directory)
            if step is None:
                raise DivergenceError(
                    "training failed before the first checkpoint; nothing "
                    "to restore (see the chained cause)"
                ) from cause
            state = ckpt.restore_checkpoint(
                self.directory, self.ts, step=step,
                template=self._template_state(),
            )
            self._template = None
            self._restore_pipeline(step)
            self._restore_dcn(step)
            logger.warning("guard: rolled back to checkpoint step %d", step)
            return state, step
        # single-host: walk newest -> oldest. Checksum verification skips
        # corrupted payloads up front, and a restore that still fails
        # (manifest-less async save torn mid-write, unreadable shard)
        # falls back to the next older checkpoint instead of killing the
        # run.
        last_exc: Optional[BaseException] = cause
        failed_steps: list[int] = []
        step = ckpt.latest_valid_step(self.directory)
        while step is not None:
            try:
                state = self._restore_step(step)
            except Exception as exc:
                logger.error(
                    "guard: restore of checkpoint step %d failed (%s: %s); "
                    "falling back to the previous checkpoint",
                    step, type(exc).__name__, exc,
                )
                if tr.enabled:
                    tr.count("guard.ckpt_fallbacks")
                    tr.event("guard.ckpt_fallback", step=step,
                             error=type(exc).__name__)
                failed_steps.append(step)
                last_exc = exc
                step = ckpt.latest_valid_step(self.directory, below=step)
                continue
            # the template is only needed for structure/shardings during
            # the restore; caching it would permanently double device memory
            self._template = None
            self._restore_pipeline(step)
            self._restore_dcn(step)
            # a corrupted/unrestorable newer step just became an abandoned
            # timeline; sweep it so replayed saves don't collide with it
            ckpt.prune_future_steps(self.directory, above=step)
            logger.warning("guard: rolled back to checkpoint step %d", step)
            if tr.enabled:
                tr.count("guard.restores")
                tr.event("guard.restore", step=step)
            return state, step
        self._template = None
        if not failed_steps:
            raise DivergenceError(
                "training failed before the first checkpoint; nothing to "
                "restore (see the chained cause; if it is a NaN loss, "
                "lower the lr or reduce checkpoint_every)"
            ) from cause
        raise DivergenceError(
            f"no restorable checkpoint under {self.directory}: steps "
            f"{failed_steps} failed to restore (newest failure chained)"
        ) from last_exc

    def _check(self, metrics) -> bool:
        # the guard's contract IS this per-step sync: divergence must be
        # caught before the next donated step destroys the rollback state
        loss = float(jax.device_get(metrics["loss"]))  # dearlint: disable=hot-path-sync
        self._last_loss = loss  # the run-health layer reuses the fetch
        return math.isfinite(loss)

    def _on_anomaly(self, kind: str, detail: dict) -> None:
        """Escalation hook for the online detectors: always logged; with
        ``DEAR_HEALTH_KICK=1`` an anomaly additionally triggers the step
        watchdog's immediate forensic dump (open spans, thread stacks,
        flight ring) — for hunting creeping regressions that never quite
        hang. A tuner harness can install its own monitor with an
        ``on_anomaly`` that calls ``Tuner.mark_infeasible`` instead."""
        logger.warning("guard: health anomaly %s: %s", kind, detail)
        if (self._watchdog is not None
                and os.environ.get("DEAR_HEALTH_KICK", "").strip().lower()
                in ("1", "true", "yes", "on")):
            self._watchdog.kick(
                f"health anomaly: {kind}",
                **{k: v for k, v in detail.items()
                   if isinstance(v, (int, float, str))})

    def _health_tick(self, tr, per_step_s: Optional[float]) -> None:
        """Per-check-interval run-health work: feed the anomaly detectors
        and push the current snapshot to any streaming exporters. Host-
        side only and O(#counters) — stays off the dispatch path."""
        ds = _dtrace.get_stream()
        if ds.enabled:
            # the lockstep health cadence doubles as the span stream's
            # wall-vs-monotonic sampling point: the collector medians
            # these per rank to clock-align the merged fleet timeline
            ds.clock_sample()
        if self._anomaly is not None:
            self._anomaly.observe(
                step=self.steps_seen, step_time_s=per_step_s,
                loss=self._last_loss,
                counters=tr.counters() if tr.enabled else None)
        if not tr.enabled:
            return
        gauges: dict = {}
        if self._flight.enabled:
            st = self._flight.step_time_stats()
            if st:
                gauges["step_time_p50_seconds"] = st["p50_s"]
                gauges["step_time_p90_seconds"] = st["p90_s"]
                gauges["step_time_max_seconds"] = st["max_s"]
        if per_step_s is not None:
            gauges["check_interval_step_seconds"] = round(per_step_s, 6)
        merged = self.merged_health
        if merged:
            if merged.get("straggler_rank") is not None:
                gauges["cluster_straggler_rank"] = merged["straggler_rank"]
            if merged.get("straggler_skew") is not None:
                gauges["cluster_straggler_skew"] = merged["straggler_skew"]
        # write_streams never raises: a failing monitoring sink counts
        # health.export_errors and logs once, training continues
        _export.write_streams({"counters": tr.counters()}, gauges,
                              tracer=tr)

    def _attempt(self, state, batch, tr):
        """Run one step attempt and its cadence bookkeeping. The normal
        path and the coordinated deferred-error path MUST share this:
        every rank has to reach the consensus sync at the same attempt
        number, so the steps_seen/is_check arithmetic cannot be allowed
        to diverge between the two call sites."""
        ds = _dtrace.get_stream()
        t0 = time.monotonic() if ds.enabled else 0.0
        if self._injector is not None:
            flip = self._injector.flip_bucket_for(self.steps_seen + 1)
            if flip is not None:
                # silent-corruption injection: a bit-flip in the bucket
                # state entering this step — the corrupted value is
                # validly checksummed everywhere downstream (wire
                # integrity cannot see it) and sits in the bucket's
                # padded tail (the loss-bits sentinel cannot either);
                # only the cross-rank fingerprint vote can. Applied on
                # the INPUT state so the in-program fingerprint of this
                # step reflects it — a deterministic fault reproduces on
                # the post-rollback replay and convicts.
                state, used, idx = _inject.flip_state_bucket(
                    state, flip, plan=getattr(self.ts, "plan", None))
                if tr.enabled:
                    tr.count("faults.sdc_flips")
                if used is not None:
                    logger.warning(
                        "guard: injected SDC bit-flip at attempt %d — "
                        "bucket %d element %d",
                        self.steps_seen + 1, used, idx)
        new_state, metrics = self.ts.step(state, batch)
        self.steps_seen += 1
        is_ckpt = self.steps_seen % self.checkpoint_every == 0
        is_check = self.steps_seen % self.check_every == 0 or is_ckpt
        # a checkpoint step ALWAYS verifies first: persisting an
        # unchecked state could immortalize NaN-poisoned parameters
        # (rollback would then restore the poison)
        healthy = not is_check or self._check(metrics)
        if is_check and not healthy and tr.enabled:
            tr.count("guard.nan_detected")
        if ds.enabled:
            # one "guard.step" span per attempt, on the deterministic
            # (mem_epoch, step) fleet step trace — the same id every
            # rank computes without coordination, so the collector can
            # line the attempt up with its DCN round and ICI legs
            ds.emit("guard.step", t0=t0,
                    dur_s=time.monotonic() - t0, cat="step",
                    trace=_dtrace.step_trace(self._mem_epoch,
                                             self.steps_seen),
                    step=self.steps_seen, mem_epoch=self._mem_epoch,
                    checked=is_check, healthy=healthy)
            if tr.enabled:
                tr.count("trace.step_spans")
        return new_state, metrics, is_ckpt, is_check, healthy

    # -- public --------------------------------------------------------------

    def step(self, state, batch):
        """One guarded step. May return a ROLLED-BACK state instead of the
        stepped one when divergence or a device error is detected; a
        handled preemption sets ``metrics["preempted"]`` (exit the loop)."""
        error: Optional[BaseException] = None
        tr = _telemetry.get_tracer()
        fl = self._flight
        self._last_loss = None
        step_dt: Optional[float] = None
        if fl.enabled:
            # per-step cadence for the flight ring: the gap between step()
            # entries covers the WHOLE loop (input fetch included — under
            # async dispatch this is dispatch cadence, not device time;
            # the check-interval timing below is the fetched truth)
            now0 = time.perf_counter()
            if self._prev_step_t is not None:
                step_dt = now0 - self._prev_step_t
            self._prev_step_t = now0
        dispatched = False
        try:
            if self._injector is not None:
                # faults fire INSIDE the guarded region: an injected
                # exception takes the same recovery path a real one would
                attempt = self.steps_seen + 1
                self._injector.before_step(attempt, directory=self.directory)
                batch = self._injector.poison_batch(attempt, batch)
            dispatched = True
            new_state, metrics, is_ckpt, is_check, healthy = \
                self._attempt(state, batch, tr)
        except (FloatingPointError, RuntimeError) as exc:
            if _is_self_evict(exc):
                # the degraded-DCN ladder's last rung: the fleet's
                # replica-identical participation view says THIS slice is
                # unmerged past the staleness budget. A rollback cannot
                # fix an outbound partition — re-raise so the rank exits
                # like an `EvictedError` (supervisor relaunch → hydrate →
                # slice-gated rejoin), while the survivors' membership
                # sync books the slice loss. Every rank of the slice
                # reaches the same verdict from the same gathered records.
                if tr.enabled:
                    tr.count("guard.step_errors")
                    tr.event("guard.step_error", error=type(exc).__name__)
                logger.error(
                    "guard: DCN ladder escalated to self-eviction: %s — "
                    "exiting for relaunch + rejoin", exc)
                raise
            if self._coordinated and dispatched and _is_dcn_error(exc):
                # hierarchical schedule: the CROSS-SLICE leg failed (dead
                # slice, DCN partition, dropped publish). Unlike a failure
                # inside a dispatched SPMD program, the host-level leg
                # leaves no cross-process collective in flight — the
                # intra-slice program completed on this process — so the
                # rank can stay in lockstep by deferring straight to the
                # coordinated sync as UNHEALTHY. No re-attempt: retrying
                # would burn another full peer deadline against a slice
                # the membership layer is about to remove.
                if tr.enabled:
                    tr.count("guard.step_errors")
                    tr.event("guard.step_error", error=type(exc).__name__)
                logger.error(
                    "guard: cross-slice (DCN) leg failed: %s — deferring "
                    "to the coordinated health sync", exc)
                self._pending_error = exc
                healthy, new_state, metrics, error = False, None, None, exc
                is_ckpt, is_check = False, True
            elif self._coordinated:
                # coordinated multi-host: a LOCAL failure must not fork
                # the SPMD program. An exception raised BEFORE the step
                # dispatched (injected faults, host-side input bugs) lets
                # this rank still run the real step — peers' in-flight
                # collectives need its participation — and defer the
                # verdict to the next health sync, where every rank rolls
                # back together. A failure DURING the dispatched step
                # cannot be papered over: re-raise, and peers degrade
                # through their bounded sync timeout.
                if tr.enabled:
                    tr.count("guard.step_errors")
                    tr.event("guard.step_error", error=type(exc).__name__)
                if dispatched:
                    logger.error(
                        "guard: dispatched step raised %s: %s — cannot "
                        "stay in lockstep; crashing for whole-job relaunch",
                        type(exc).__name__, exc)
                    raise
                logger.error(
                    "guard: step raised %s: %s (deferred to the "
                    "coordinated health sync)", type(exc).__name__, exc)
                self._pending_error = exc
                if self._injector is not None:
                    # a batch fault co-scheduled at THIS attempt (e.g.
                    # "exc@8:r0,nan@8") must still be consumed — fault
                    # schedules drain identically on every rank, and the
                    # poison just makes this already-doomed attempt's
                    # loss non-finite too
                    try:
                        batch = self._injector.poison_batch(
                            self.steps_seen + 1, batch)
                    except _inject.InjectedFault:
                        pass  # already deferring an error for this attempt
                new_state, metrics, is_ckpt, is_check, healthy = \
                    self._attempt(state, batch, tr)
            elif jax.process_count() > 1:
                # legacy multi-host (DEAR_CLUSTER=0 / no coordinator): a
                # local rollback would desynchronize replicas (the other
                # processes step on while this one restores). Crash
                # instead — whole-job relaunch restores every process
                # from the same periodic checkpoints (the NaN path below
                # is safe: the checked loss is replicated, so every
                # process makes the same decision).
                raise
            else:
                logger.error("guard: step raised %s: %s",
                             type(exc).__name__, exc)
                if tr.enabled:
                    tr.count("guard.step_errors")
                    tr.event("guard.step_error", error=type(exc).__name__)
                healthy, new_state, metrics, error = False, None, None, exc
                is_check = is_ckpt = False

        if fl.enabled:
            fl.record(self.steps_seen, step_time_s=step_dt,
                      loss=self._last_loss, checked=int(is_check))

        per_step_s: Optional[float] = None
        if is_check and healthy:
            # timing across the sync interval: under async dispatch only a
            # checked (fetched) step gives a meaningful wall-clock point;
            # checkpoint steps also check, so use the ACTUAL step delta
            now = time.perf_counter()
            interval = self.steps_seen - self._last_check_steps
            if self._last_check_t is not None and interval > 0:
                per_step = (now - self._last_check_t) / interval
                per_step_s = per_step
                if (
                    self.ema_step_s is not None
                    and per_step > 10 * self.ema_step_s
                ):
                    logger.warning(
                        "guard: %.2fs/step over the last interval (ema "
                        "%.3fs) — possible hung collective; last "
                        "checkpointed step: %s",
                        per_step, self.ema_step_s, self._last_good_step,
                    )
                self.ema_step_s = (
                    per_step if self.ema_step_s is None
                    else 0.9 * self.ema_step_s + 0.1 * per_step
                )
                self.max_step_s = max(self.max_step_s, per_step)
            self._last_check_t = now
            self._last_check_steps = self.steps_seen

        if self._coordinated and is_check:
            # the per-check-interval consensus point: any-rank-unhealthy,
            # the desync-sentinel fingerprint of the replicated loss, and
            # preemption propagation — all in ONE bounded exchange. Every
            # rank reaches this at the same attempt number (steps_seen
            # advances on every attempt, including deferred-error ones).
            local_ok = healthy and self._pending_error is None
            fp = ""
            if healthy and metrics is not None:
                fp = _cluster.ClusterCoordinator.fingerprint(
                    jax.device_get(metrics["loss"]))
            sfp = ""
            if self._sdc is not None and healthy and metrics is not None:
                # the per-bucket checksums were computed IN-PROGRAM by
                # the train step; this is the lazy gather, paid only at
                # check cadence (same host sync as the loss fingerprint)
                words = metrics.get("sdc_fp")
                dcn = getattr(self.ts, "dcn", None)
                extra = getattr(dcn, "last_mean_fp", "") if dcn else ""
                if words is not None or extra:
                    # deliberate sync: a tiny uint32[buckets] vector at
                    # health-sync cadence, never per step
                    sfp = self._sdc.local_fingerprint(
                        None if words is None
                        else jax.device_get(words), extra)  # dearlint: disable=hot-path-sync
            pre_req = (self._preemption is not None
                       and self._preemption.requested
                       and not self._preempt_handled)
            # elastic runs turn a SIGTERM into a single-rank PLANNED
            # shrink (spot semantics: each reclaimed rank gets its own
            # signal) instead of propagating full-fleet preemption;
            # DEAR_PREEMPT_DRAIN=0 restores propagate-and-save-everywhere
            drain = (pre_req and self._drain_on_preempt
                     or self._sdc_drain)
            sync_kwargs = dict(
                ok=local_ok, fingerprint=fp, step=self.steps_seen,
                preempted=pre_req and not drain)
            if self._sdc is not None:
                sync_kwargs["sdc_fingerprint"] = sfp
                sync_kwargs["host"] = self._sdc.host
            if drain:
                sync_kwargs["draining"] = True
            try:
                verdict = self._coordinator.health_check(**sync_kwargs)
                membership_changed = bool(
                    getattr(verdict, "membership_changed", False))
                if (self._aggregator is not None
                        and not membership_changed
                        and not getattr(verdict, "self_draining", False)):
                    # metric aggregation rides the same cadence (and the
                    # same bounded deadline): one lockstep digest exchange
                    # per health sync. Every rank computes the identical
                    # merged snapshot; rank 0's is the exported copy.
                    # Skipped across a membership transition: the member
                    # set just changed under the exchange, and a freshly
                    # admitted rank only enters the digest cadence at the
                    # NEXT sync (after its consensus restore). Skipped by
                    # a DRAINING rank too — the survivors are inside
                    # their shrink rollback and will never join this
                    # exchange; entering it would hang the drainer's
                    # whole grace window and turn the clean drain into a
                    # dirty crash (observed).
                    self.merged_health = self._aggregator.exchange()
            except _cluster.PeerTimeout:
                # dead-peer detection: dump forensics (open spans + all
                # thread stacks) through the watchdog, then degrade to
                # the old crash-for-relaunch behavior.
                if self._watchdog is not None:
                    self._watchdog.kick(
                        "cluster peer timeout", step=self.steps_seen,
                        last_good_step=self._last_good_step)
                if self._pending_error is not None:
                    raise PeerLostError(
                        "a peer never reached the coordinated health "
                        "sync; crashing for whole-job relaunch"
                    ) from self._pending_error
                raise
            if verdict.any_preempted:
                self._peer_preempt = True
            if self._sdc is not None:
                hosts_by_rank = {
                    int(r): h
                    for r, h in getattr(verdict, "hosts", ()) if h}
                acts = self._sdc.note_votes(
                    getattr(verdict, "sdc_suspects", ()), hosts_by_rank,
                    step=self.steps_seen,
                    voted=getattr(verdict, "sdc_voted", False))
                if acts["opened"]:
                    logger.critical(
                        "guard: SDC case opened against host(s) %s at "
                        "step %d — the coordinated rollback is the "
                        "replay arbiter (deterministic re-run from the "
                        "last verified checkpoint on suspect AND peers)",
                        acts["opened"], self.steps_seen)
                if acts["struck"]:
                    logger.warning(
                        "guard: SDC replay came back clean for host(s) "
                        "%s — transient fault, strike recorded",
                        acts["struck"])
                if acts["convicted"]:
                    logger.critical(
                        "guard: SDC conviction — host(s) %s quarantined "
                        "in the ledger", acts["convicted"])
                if self._sdc.drain_requested and not self._sdc_drain:
                    # THIS host was convicted: fence checkpoint saves and
                    # announce a planned-shrink drain at the next sync
                    self._sdc_drain = True
                    logger.critical(
                        "guard: host %s is quarantined — draining via "
                        "planned shrink; checkpoint saves fenced",
                        self._sdc.host)
            if getattr(verdict, "self_draining", False) and self._sdc_drain:
                # the survivors acknowledged the quarantine drain and are
                # committing the planned shrink without me. NO emergency
                # save — this host's state is the corrupt copy; the
                # supervisor reads the exit code as "backfill this seat
                # on a FRESH host".
                raise _sdc.SdcQuarantined(
                    f"host {self._sdc.host} is quarantined in the SDC "
                    "ledger; planned-shrink drain committed — exiting "
                    "for backfill on a fresh host")
            if getattr(verdict, "self_draining", False):
                # the fleet acknowledged my drain announcement and is
                # committing the planned shrink without me: emergency-save
                # and exit inside the grace budget
                self._peer_preempt = True
                rem = (self._preemption.remaining()
                       if self._preemption is not None else None)
                logger.warning(
                    "guard: drain acknowledged at step %d — planned shrink "
                    "committed by the survivors (grace remaining: %s)",
                    self.steps_seen,
                    "unknown" if rem is None else f"{rem:.1f}s")
            if membership_changed:
                # a committed transition (survivor shrink or rejoin
                # admission) is a transition point: the loop rebuilds its
                # train step for the new replica count (the hook — e.g.
                # AutoTuner.rescale — runs BEFORE the restore so the
                # elastic re-pack lands in the new plan), the pipeline is
                # resharded after the restore, and every member rolls
                # back to the newest step valid on all of them (the
                # verdict is never ok, so the rollback path below runs).
                self._pending_reshard = True
                if tr.enabled:
                    tr.count("guard.membership_changes")
                    tr.event(
                        "guard.membership_change",
                        epoch=getattr(verdict, "epoch", -1),
                        lost=",".join(map(str, getattr(verdict, "lost", ()))),
                        admitted=",".join(
                            map(str, getattr(verdict, "admitted", ()))),
                    )
                logger.critical(
                    "guard: membership transition at step %d — epoch %s, "
                    "members %s (lost %s, admitted %s); coordinated "
                    "rollback + reshard",
                    self.steps_seen, getattr(verdict, "epoch", "?"),
                    list(getattr(verdict, "members", ())),
                    list(getattr(verdict, "lost", ())),
                    list(getattr(verdict, "admitted", ())))
                if self.on_membership_change is not None:
                    self.on_membership_change(self._coordinator.view())
            if not verdict.ok:
                if error is None:
                    error = self._pending_error
                healthy = False
            self._pending_error = None

        if is_check:
            self._health_tick(tr, per_step_s)

        if not healthy:
            self.recoveries += 1
            if self.recoveries > self.max_recoveries:
                raise DivergenceError(
                    f"diverged {self.recoveries} consecutive times "
                    f"(max_recoveries={self.max_recoveries})"
                ) from error
            if fl.enabled:
                # every failure report ships the last N steps of context:
                # one JSON line (counter deltas, live spans, redacted
                # DEAR_* env) so multi-rank logs stay machine-separable
                dump = fl.dump()
                logger.warning(
                    "guard: flight ring at rollback (%d records): %s",
                    len(dump["records"]), json.dumps(dump),
                )
                if tr.enabled:
                    tr.count("guard.flight_dumps")
                    tr.event("guard.flight_dump",
                             records=len(dump["records"]))
            restored, at_step = self._restore(cause=error)
            # futures were just pruned: the restored step IS the newest
            # durable checkpoint now
            self._last_good_step = at_step
            self._last_check_t = None  # restore time must not skew timing
            self._prev_step_t = None   # ditto for the flight cadence
            if self._pending_reshard:
                # AFTER the restore: the sidecar state re-seats the stream
                # at the checkpointed position first, then the reshard
                # reassigns this rank's slice under the new epoch — a pure
                # function of (seed, epoch, slot, world), so every
                # survivor derives the identical assignment independently
                self._reshard_pipeline()
            if tr.enabled:
                # counted only after the restore actually happened — the
                # give-up/restore-failure paths above must not inflate the
                # forensics counters
                tr.count("guard.rollbacks")
                tr.count("guard.steps_skipped")  # the bad batch is skipped
                tr.event("guard.rollback", recoveries=self.recoveries,
                         restored_step=at_step)
            ds = _dtrace.get_stream()
            if ds.enabled:
                # the rollback rides the failed attempt's step trace so
                # the fleet timeline shows verdict -> restore in one chain
                ds.emit("guard.rollback", cat="step",
                        trace=_dtrace.step_trace(self._mem_epoch,
                                                 self.steps_seen),
                        step=self.steps_seen, mem_epoch=self._mem_epoch,
                        restored_step=at_step,
                        recoveries=self.recoveries)
            if self.on_rollback is not None:
                self.on_rollback(self.recoveries, at_step)
            if self._watchdog is not None:
                # a completed recovery is liveness too
                self._watchdog.beat(step=self.steps_seen,
                                    last_good_step=at_step)
            out = {"loss": float("nan"), "rolled_back": True}
            if self._preempt_requested and not self._preempt_handled:
                # SIGTERM during an unhealthy stretch: the restored state
                # IS the newest durable checkpoint — nothing to save;
                # signal the loop to exit now instead of burning the grace
                # window replaying steps
                self._preempt_handled = True
                self._preempt_saved_step = at_step
                logger.warning(
                    "guard: preemption during rollback — durable step is "
                    "the restored checkpoint %d", at_step,
                )
            if self._preempt_handled:
                out["preempted"] = True
                if self._preempt_saved_step is not None:
                    out["preempt_checkpoint_step"] = self._preempt_saved_step
            return restored, out

        if is_ckpt and not self._sdc_drain and self._save(new_state):
            # persisted healthy progress: a future rollback is a NEW
            # incident, not a continuation of an old one. A FAILED async
            # save must not reset the counter — nothing was persisted, and
            # resetting would let a diverge/rollback loop spin forever past
            # max_recoveries.
            self.recoveries = 0
        if self._preempt_requested and not self._preempt_handled:
            saved = self._emergency_save(new_state, metrics)
            self._preempt_handled = True
            self._preempt_saved_step = saved
            metrics = dict(metrics)
            metrics["preempted"] = True
            if saved is not None:
                metrics["preempt_checkpoint_step"] = saved
        elif self._preempt_handled:
            # keep signalling until the loop actually exits
            metrics = dict(metrics)
            metrics["preempted"] = True
            if self._preempt_saved_step is not None:
                metrics["preempt_checkpoint_step"] = self._preempt_saved_step
        if self._watchdog is not None:
            self._watchdog.beat(step=self.steps_seen,
                                last_good_step=self._last_good_step)
        return new_state, metrics

    def elastic_resume(self, context: Optional[dict] = None):
        """Re-entry for a relaunched rank that was just admitted through
        `resilience.membership.ElasticCluster.rejoin`. The surviving
        members are, right now, inside their membership-change rollback —
        this performs the SAME consensus-restore exchange from this side
        (the rejoiner's locally verified steps participate in the
        decision), re-seats the pipeline, and aligns the guard's attempt
        cadence with the fleet via the admission ack's ``steps_seen``
        so the next health sync lands on the same attempt everywhere.
        Returns ``(state, step)`` — resume the training loop from there.
        """
        if context:
            self.steps_seen = int(context.get("steps_seen",
                                              self.steps_seen))
        self._last_check_steps = self.steps_seen
        self._last_check_t = None
        self._prev_step_t = None
        state, step = self._restore()
        self._reshard_pipeline()
        self._last_good_step = step
        tr = _telemetry.get_tracer()
        if tr.enabled:
            tr.event("guard.elastic_resume", step=step,
                     steps_seen=self.steps_seen,
                     epoch=self._mem_epoch or 0)
        logger.warning(
            "guard: elastic resume at checkpoint step %d (attempt cadence "
            "%d, membership epoch %s)", step, self.steps_seen,
            self._mem_epoch)
        return state, step

    def _stream_emergency(self, step: int) -> None:
        """Push an emergency save to the remote tier INSIDE the grace
        budget: enqueue, then flush bounded by what remains of the
        platform's SIGTERM->SIGKILL window (`DEAR_PREEMPT_GRACE_S`) — an
        upload that can't finish in time must not stall the clean exit."""
        if self._streamer is None:
            return
        rem = (self._preemption.remaining()
               if self._preemption is not None else None)
        budget = 10.0 if rem is None else max(min(rem - 1.0, 10.0), 0.5)
        # force=True: the emergency save is the resume point no matter
        # where it lands relative to the every-Nth upload cadence
        self._streamer.enqueue(step, force=True)
        if not self._streamer.flush(budget):
            logger.error(
                "guard: emergency upload of step %d did not finish inside "
                "the %.1fs grace budget; the remote tier keeps the "
                "previous upload", step, budget)

    def _emergency_save(self, state, metrics) -> Optional[int]:
        """Preemption checkpoint: synchronous, verified, at the current
        step — the grace window is short, so no async handoff. Returns the
        persisted step (None when the state could not be verified). With
        a known grace window (`DEAR_PREEMPT_GRACE_S`) the remaining
        budget is logged and bounds the remote-tier flush."""
        tr = _telemetry.get_tracer()
        rem = (self._preemption.remaining()
               if self._preemption is not None else None)
        if rem is not None:
            logger.warning(
                "guard: emergency save starting with %.1fs of the "
                "preemption grace window remaining", rem)
        try:
            healthy = self._check(metrics)
        except Exception as exc:
            logger.error("guard: preemption-save loss check failed: %s", exc)
            healthy = False
        if not healthy:
            # the periodic-save invariant holds under preemption too: an
            # unverified state must never become the newest checkpoint
            logger.error(
                "guard: preemption save SKIPPED (non-finite loss); newest "
                "durable step stays %s", self._last_good_step,
            )
            return None
        step = int(jax.device_get(state.step))
        if step == self._last_good_step:
            if not self.async_checkpoints:
                logger.warning(
                    "guard: preemption at step %d — already checkpointed",
                    step,
                )
                if tr.enabled:
                    # the preemption WAS handled with a durable checkpoint;
                    # landing on a boundary must not vanish from telemetry
                    tr.count("guard.preempt_saves")
                    tr.event("guard.preempt_save", step=step)
                self._stream_emergency(step)
                return step
            # the newest async save may still be an UNCOMMITTED enqueue:
            # make it durable before claiming it as the resume point
            try:
                ckpt.wait_for_checkpoints()
            except Exception as exc:
                logger.error(
                    "guard: in-flight async save failed during preemption "
                    "(%s); writing a fresh synchronous checkpoint", exc,
                )
                # fall through to the fresh synchronous save below
            else:
                ckpt.write_manifest(self.directory, step)
                logger.warning(
                    "guard: preemption at step %d — async checkpoint "
                    "committed and manifested", step,
                )
                if tr.enabled:
                    tr.count("guard.preempt_saves")
                    tr.event("guard.preempt_save", step=step)
                self._stream_emergency(step)
                return step
        else:
            try:
                # don't race an in-flight async save
                ckpt.wait_for_checkpoints()
            except Exception as exc:
                logger.error(
                    "guard: in-flight async save failed during preemption "
                    "(%s); writing a fresh synchronous checkpoint", exc,
                )
        try:
            ckpt.save_checkpoint(self.directory, state, self.ts.plan,
                                 asynchronous=False,
                                 pipeline_state=self._pipeline_state(),
                                 mem_epoch=self._mem_epoch,
                                 dcn_state=self._dcn_state())
        except Exception as exc:
            # the grace window must still end in a clean preempted exit:
            # a failed emergency save (disk full, shared-fs error) means
            # the relaunch resumes from the previous durable step, which
            # beats dying mid-save with the loop never told to stop
            logger.error(
                "guard: preemption save FAILED (%s: %s); newest durable "
                "step stays %s", type(exc).__name__, exc,
                self._last_good_step,
            )
            if tr.enabled:
                tr.count("guard.checkpoint_failures")
                tr.event("guard.checkpoint_failed", step=step,
                         error=type(exc).__name__)
            return None
        self._last_good_step = step
        self._prune()
        logger.warning("guard: preemption checkpoint committed at step %d",
                       step)
        if tr.enabled:
            tr.count("guard.preempt_saves")
            tr.event("guard.preempt_save", step=step)
        self._stream_emergency(step)
        return step

    def finalize(self) -> None:
        """Wait for in-flight async checkpoint writes and surface their
        errors. Call when training ends (or use the trainer as a context
        manager) — otherwise a failed LAST async save is silently dropped
        and resume finds an older step than `_last_good_step` claims.
        Once committed, the newest async save's checksum manifest is
        backfilled so a relaunch can verify it."""
        ckpt.wait_for_checkpoints()
        if self.async_checkpoints and self._last_good_step is not None:
            ckpt.write_manifest(self.directory, self._last_good_step)
        if self._streamer is not None and not self._streamer.flush(30.0):
            logger.error(
                "guard: remote-tier uploads still pending at finalize; "
                "the newest local checkpoint may not be durable remotely")

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if exc_type is None:
            self.finalize()
        else:
            # already failing: don't let a deferred write error mask it
            try:
                self.finalize()
            except Exception:
                logger.exception("guard: finalize failed during unwind")
