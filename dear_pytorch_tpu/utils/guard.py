"""Failure detection + recovery around the train step.

The reference has NONE of this (SURVEY.md §5: any MPI/CUDA/NCCL error
aborts the process via CHECK macros; its batch driver retries at whole-job
granularity). On TPU the failure surface is different — device errors
surface as Python exceptions from a blocked fetch, and the classic silent
killer is numerical: a NaN/Inf loss that poisons every parameter within a
few donated steps. `GuardedTrainer` wraps a `TrainStep` with:

  - **divergence detection**: the loss is fetched and checked every
    ``check_every`` steps (fetch = one scalar device->host sync; keep the
    cadence coarse on remote devices),
  - **rollback**: on a non-finite loss (or a raised step error) the state
    restores from the newest periodic checkpoint and training continues,
    skipping forward past the poisoned step,
  - **periodic checkpoints**: every ``checkpoint_every`` steps through
    `utils.checkpoint` (plan-fingerprinted, sharded, multi-host safe),
  - **step-time accounting**: wall-clock EMA + max, so a hung collective
    shows up in logs with the last-good step number.

This is single-program recovery (the process survives). Whole-process
elasticity (host loss on a pod) composes on top: the same periodic
checkpoints are what a relaunched job restores from.
"""

from __future__ import annotations

import logging
import math
import time
from typing import Any, Callable, Optional

import jax

from dear_pytorch_tpu.utils import checkpoint as ckpt

logger = logging.getLogger("dear_pytorch_tpu")


class DivergenceError(RuntimeError):
    """Raised when training diverges and no checkpoint exists to restore."""


class GuardedTrainer:
    """Wrap ``ts`` (a `parallel.TrainStep`) with detection + recovery.

    Usage::

        trainer = GuardedTrainer(ts, directory, params)
        for batch in batches:
            state, metrics = trainer.step(state, batch)
    """

    def __init__(
        self,
        ts,
        directory: str,
        params_template,
        *,
        check_every: int = 50,
        checkpoint_every: int = 500,
        max_recoveries: int = 3,
        max_keep: int = 3,
        on_rollback: Optional[Callable[[int, int], None]] = None,
        async_checkpoints: bool = False,
    ):
        self.ts = ts
        self.directory = directory
        self.async_checkpoints = async_checkpoints
        self.check_every = max(int(check_every), 1)
        self.checkpoint_every = max(int(checkpoint_every), 1)
        self.max_recoveries = max_recoveries
        self.max_keep = max(int(max_keep), 1)
        self.on_rollback = on_rollback
        self._template = None
        self._params_template = params_template
        self.recoveries = 0          # CONSECUTIVE rollbacks without a new
        self.steps_seen = 0          # healthy checkpoint in between
        self.ema_step_s = None
        self.max_step_s = 0.0
        self._last_good_step = None
        self._last_check_t = None
        self._last_check_steps = 0

    # -- internals -----------------------------------------------------------

    def _template_state(self):
        if self._template is None:
            self._template = self.ts.init(self._params_template)
        return self._template

    def _save(self, state) -> bool:
        """True when the save committed (or was enqueued after a clean
        handoff); False on a swallowed async failure — the caller must NOT
        treat that as persisted progress."""
        step = int(jax.device_get(state.step))
        try:
            ckpt.save_checkpoint(self.directory, state, self.ts.plan,
                                 asynchronous=self.async_checkpoints)
        except Exception as exc:
            if not self.async_checkpoints:
                raise
            # Orbax surfaces a PREVIOUS async write's deferred failure at
            # the next save call. The training state in hand is healthy —
            # losing one checkpoint must not kill the run this class exists
            # to keep alive. Log, skip this save, try again next interval —
            # but still run retention: a failure streak would otherwise
            # accumulate failed-write tmp dirs and orphan sidecars without
            # bound. THIS call's write may have been enqueued before the
            # exception (e.g. a sidecar failure after AsyncCheckpointer
            # created its tmp dir), so its tmp dir must survive the prune.
            logger.error("guard: async checkpoint save failed: %s", exc)
            self._prune(skip_tmp_step=step)
            return False
        self._last_good_step = step
        # async: the save's own atomic-write temp dir is legitimately alive
        # right now — pruning it would corrupt the in-flight write
        self._prune(
            skip_tmp_step=(self._last_good_step
                           if self.async_checkpoints else None)
        )
        return True

    def _prune(self, skip_tmp_step: Optional[int] = None) -> None:
        """Keep the newest ``max_keep`` checkpoints (the guard only ever
        restores the latest; unbounded retention would eventually fill the
        filesystem and crash the very trainer meant to survive faults)."""
        if jax.process_index() != 0:
            return
        import os
        import shutil

        try:
            names = os.listdir(self.directory)
        except OSError:
            return
        steps = sorted(
            int(name[len("step_"):])
            for name in names
            if name.startswith("step_") and name[len("step_"):].isdigit()
        )
        # crash-leftover Orbax atomic-write temp dirs
        # (step_XXXXXXXXXX.orbax-checkpoint-tmp-N) are never restorable;
        # delete them too, or a crash-restart loop fills the disk the
        # retention policy exists to protect
        for name in names:
            if name.startswith("step_") and ".orbax-checkpoint-tmp" in name:
                if (skip_tmp_step is not None
                        and name.startswith(f"step_{skip_tmp_step:010d}.")):
                    continue  # in-flight async write, not a crash leftover
                shutil.rmtree(
                    os.path.join(self.directory, name), ignore_errors=True
                )
        for s in steps[: -self.max_keep]:
            shutil.rmtree(
                os.path.join(self.directory, f"step_{s:010d}"),
                ignore_errors=True,
            )
            try:
                os.remove(
                    os.path.join(self.directory, f"meta_{s:010d}.json")
                )
            except OSError:
                pass
        # orphan sidecars: meta written eagerly for a save that never
        # committed (async failure / crash mid-write). Restores never read
        # them (they go through committed dirs), but a crash-restart loop
        # would accumulate them unboundedly.
        committed = set(steps)
        for name in names:
            if not (name.startswith("meta_") and name.endswith(".json")):
                continue
            digits = name[len("meta_"):-len(".json")]
            if not digits.isdigit():
                continue
            s = int(digits)
            if s not in committed and s != skip_tmp_step:
                try:
                    os.remove(os.path.join(self.directory, name))
                except OSError:
                    pass

    def _restore(self, cause: Optional[BaseException] = None):
        # an async save may still be in flight: its step dir only appears
        # on commit, so wait — rolling back to the older checkpoint while a
        # newer healthy one is mid-write would lose good progress. A FAILED
        # in-flight write must not kill the rollback itself: fall back to
        # the newest committed checkpoint.
        try:
            ckpt.wait_for_checkpoints()
        except Exception as exc:
            logger.error(
                "guard: in-flight async checkpoint failed (%s); restoring "
                "the newest committed checkpoint instead", exc,
            )
        step = ckpt.latest_step(self.directory)
        if step is None:
            raise DivergenceError(
                "training failed before the first checkpoint; nothing to "
                "restore (see the chained cause; if it is a NaN loss, "
                "lower the lr or reduce checkpoint_every)"
            ) from cause
        state = ckpt.restore_checkpoint(
            self.directory, self.ts, template=self._template_state()
        )
        # the template is only needed for structure/shardings during the
        # restore; caching it would permanently double device memory
        self._template = None
        logger.warning("guard: rolled back to checkpoint step %d", step)
        return state, step

    def _check(self, metrics) -> bool:
        loss = float(jax.device_get(metrics["loss"]))
        return math.isfinite(loss)

    # -- public --------------------------------------------------------------

    def step(self, state, batch):
        """One guarded step. May return a ROLLED-BACK state instead of the
        stepped one when divergence or a device error is detected."""
        error: Optional[BaseException] = None
        try:
            new_state, metrics = self.ts.step(state, batch)
            self.steps_seen += 1
            is_ckpt = self.steps_seen % self.checkpoint_every == 0
            is_check = self.steps_seen % self.check_every == 0 or is_ckpt
            # a checkpoint step ALWAYS verifies first: persisting an
            # unchecked state could immortalize NaN-poisoned parameters
            # (rollback would then restore the poison)
            healthy = not is_check or self._check(metrics)
        except (FloatingPointError, RuntimeError) as exc:
            if jax.process_count() > 1:
                # a LOCAL exception must not trigger a local rollback on a
                # multi-host run: the other processes would step on while
                # this one restores, silently desynchronizing replicas.
                # Crash instead — whole-job relaunch restores every process
                # from the same periodic checkpoints (the NaN path below is
                # safe: the checked loss is replicated, so every process
                # makes the same decision).
                raise
            logger.error("guard: step raised %s: %s", type(exc).__name__, exc)
            healthy, new_state, metrics, error = False, None, None, exc
            is_check = is_ckpt = False

        if is_check and healthy:
            # timing across the sync interval: under async dispatch only a
            # checked (fetched) step gives a meaningful wall-clock point;
            # checkpoint steps also check, so use the ACTUAL step delta
            now = time.perf_counter()
            interval = self.steps_seen - self._last_check_steps
            if self._last_check_t is not None and interval > 0:
                per_step = (now - self._last_check_t) / interval
                if (
                    self.ema_step_s is not None
                    and per_step > 10 * self.ema_step_s
                ):
                    logger.warning(
                        "guard: %.2fs/step over the last interval (ema "
                        "%.3fs) — possible hung collective; last "
                        "checkpointed step: %s",
                        per_step, self.ema_step_s, self._last_good_step,
                    )
                self.ema_step_s = (
                    per_step if self.ema_step_s is None
                    else 0.9 * self.ema_step_s + 0.1 * per_step
                )
                self.max_step_s = max(self.max_step_s, per_step)
            self._last_check_t = now
            self._last_check_steps = self.steps_seen

        if not healthy:
            self.recoveries += 1
            if self.recoveries > self.max_recoveries:
                raise DivergenceError(
                    f"diverged {self.recoveries} consecutive times "
                    f"(max_recoveries={self.max_recoveries})"
                ) from error
            restored, at_step = self._restore(cause=error)
            self._last_check_t = None  # restore time must not skew timing
            if self.on_rollback is not None:
                self.on_rollback(self.recoveries, at_step)
            return restored, {"loss": float("nan"), "rolled_back": True}

        if is_ckpt and self._save(new_state):
            # persisted healthy progress: a future rollback is a NEW
            # incident, not a continuation of an old one. A FAILED async
            # save must not reset the counter — nothing was persisted, and
            # resetting would let a diverge/rollback loop spin forever past
            # max_recoveries.
            self.recoveries = 0
        return new_state, metrics

    def finalize(self) -> None:
        """Wait for in-flight async checkpoint writes and surface their
        errors. Call when training ends (or use the trainer as a context
        manager) — otherwise a failed LAST async save is silently dropped
        and resume finds an older step than `_last_good_step` claims."""
        ckpt.wait_for_checkpoints()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if exc_type is None:
            self.finalize()
        else:
            # already failing: don't let a deferred write error mask it
            try:
                self.finalize()
            except Exception:
                logger.exception("guard: finalize failed during unwind")
