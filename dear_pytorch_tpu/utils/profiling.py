"""Step timing, layer-wise backward measurement, and collective α-β sweeps.

Reference equivalents (dear/profiling.py): ``Profiling`` wraps a model with
per-parameter backward hooks + ``cuda.synchronize`` timestamps (:11-95),
``benchmark()`` drives 50 iterations to produce layer-wise backward times
(:98-129) feeding MG-WFBP, and ``CommunicationProfiler`` sweeps collective
latency vs size (:132-165).

Under XLA there are no backward hooks — the graph is compiled whole. The
TPU-native equivalents:
  - `StepTimer`: wall-clock stats over whole steps (the only
    externally-observable unit under jit), mean ± 1.96σ like the harness.
  - `measure_layerwise_backward`: per-layer backward times via suffix
    truncation — time grad(loss) w.r.t. the parameter suffix starting at
    each layer (earlier layers frozen); consecutive differences isolate one
    layer's backward+weight-grad cost. L jit compiles, measurement-grade
    (offline), but real measured numbers on real hardware — the role the
    reference's hook-based ``benchmark()`` plays for MG-WFBP.
  - `CommunicationProfiler`: times `all_reduce` (or RS/AG) on the mesh over
    a size sweep and fits (α, β) with `perf_model.fit_alpha_beta`.
"""

from __future__ import annotations

import time
from typing import Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from dear_pytorch_tpu.comm import backend
from dear_pytorch_tpu.comm import collectives as C
from dear_pytorch_tpu.comm.backend import DP_AXIS
from dear_pytorch_tpu.ops import fusion as F
from dear_pytorch_tpu.utils import perf_model


class StepTimer:
    """Collect per-step wall times; report mean/std/CI like the reference
    harness (dear/imagenet_benchmark.py:165-172)."""

    def __init__(self):
        self.times: list[float] = []
        self._t: Optional[float] = None

    def __enter__(self):
        self._t = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.times.append(time.perf_counter() - self._t)

    def tick(self) -> None:
        """Lap timer: call once per step."""
        now = time.perf_counter()
        if self._t is not None:
            self.times.append(now - self._t)
        self._t = now

    @property
    def mean(self) -> float:
        return float(np.mean(self.times)) if self.times else 0.0

    @property
    def ci95(self) -> float:
        return float(1.96 * np.std(self.times)) if self.times else 0.0

    def summary(self) -> str:
        return f"{self.mean:.4f} +-{self.ci95:.4f} s over {len(self.times)} steps"


def measure_layerwise_backward(
    loss_fn: Callable,
    params,
    batch,
    *,
    repeats: int = 5,
    warmup: int = 2,
) -> list[float]:
    """Per-layer backward-time measurements in forward order (seconds).

    For each atomic layer i, times ``grad(loss)`` taken w.r.t. layers
    ``i..L-1`` with layers ``0..i-1`` held constant; the difference between
    successive measurements is the marginal cost of extending backprop
    through layer i — the per-layer number MG-WFBP consumes
    (reference benchmark(), dear/profiling.py:98-129).
    """
    plan = F.plan_by_nearby_layers(params, world=1, k=1)
    n_layers = len({s.layer for s in plan.leaves})
    leaves = list(jax.tree.leaves(params))
    treedef = jax.tree.structure(params)

    totals = []
    for start in range(n_layers):
        train_ids = [i for i, s in enumerate(plan.leaves)
                     if s.layer >= start]
        frozen_ids = [i for i, s in enumerate(plan.leaves)
                      if s.layer < start]

        def split_loss(train_leaves, frozen_leaves):
            flat = [None] * len(leaves)
            for j, i in enumerate(train_ids):
                flat[i] = train_leaves[j]
            for j, i in enumerate(frozen_ids):
                flat[i] = frozen_leaves[j]
            return loss_fn(jax.tree.unflatten(treedef, flat), batch)

        g = jax.jit(jax.grad(split_loss))
        train_leaves = [leaves[i] for i in train_ids]
        frozen_leaves = [jax.lax.stop_gradient(leaves[i])
                         for i in frozen_ids]
        for _ in range(warmup):
            jax.block_until_ready(g(train_leaves, frozen_leaves))
        t0 = time.perf_counter()
        for _ in range(repeats):
            out = g(train_leaves, frozen_leaves)
        jax.block_until_ready(out)
        totals.append((time.perf_counter() - t0) / repeats)

    # totals[start] = fwd + backward through layers >= start; marginal cost
    # of layer i = totals[i] - totals[i+1] (clamped: timing noise)
    times = []
    for i in range(n_layers):
        nxt = totals[i + 1] if i + 1 < n_layers else min(totals)
        times.append(max(totals[i] - nxt, 1e-7))
    return times


class CommunicationProfiler:
    """Collective latency vs message size on the mesh (reference
    dear/profiling.py:132-165), fitted to t = α + β·bytes."""

    def __init__(
        self,
        mesh: Optional[jax.sharding.Mesh] = None,
        axis_name: str = DP_AXIS,
        collective: str = "all_reduce",
        dtype=jnp.float32,
    ):
        self.mesh = mesh or backend.global_mesh()
        self.axis_name = axis_name
        self.dtype = dtype
        ops = {
            "all_reduce": C.all_reduce,
            "reduce_scatter": C.reduce_scatter,
            "all_gather": C.all_gather,
            "all_reduce_rsag": C.all_reduce_rsag,
        }
        if collective not in ops:
            raise KeyError(f"collective must be one of {sorted(ops)}")
        self._op = ops[collective]
        self.collective = collective

    def benchmark(
        self,
        sizes: Optional[Sequence[int]] = None,
        *,
        repeats: int = 10,
        warmup: int = 3,
    ) -> tuple[list[int], list[float]]:
        """Time the collective for each element count; returns
        (sizes_bytes, times_s)."""
        world = self.mesh.shape[self.axis_name]
        if sizes is None:
            sizes = [2 ** k for k in range(10, 25, 2)]
        sizes = [F.padded_length(s, world) for s in sizes]
        itemsize = jnp.dtype(self.dtype).itemsize

        sizes_bytes, times = [], []
        for n in sizes:
            x = jnp.ones((world, n), self.dtype)
            op = self._op
            axis = self.axis_name

            def run(t):
                return op(t, axis)

            # one compile per size (shape-specialized), excluded from timing
            out = C.spmd_call(run, x, mesh=self.mesh, axis_name=axis)
            for _ in range(warmup):
                out = C.spmd_call(run, x, mesh=self.mesh, axis_name=axis)
            jax.block_until_ready(out)
            t0 = time.perf_counter()
            for _ in range(repeats):
                out = C.spmd_call(run, x, mesh=self.mesh, axis_name=axis)
            jax.block_until_ready(out)
            times.append((time.perf_counter() - t0) / repeats)
            sizes_bytes.append(n * itemsize)
        return sizes_bytes, times

    def fit(self, **kwargs) -> tuple[float, float]:
        """Run the sweep and return fitted (α, β)."""
        sizes_bytes, times = self.benchmark(**kwargs)
        return perf_model.fit_alpha_beta(sizes_bytes, times)
