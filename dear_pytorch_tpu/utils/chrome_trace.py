"""Chrome-trace (about://tracing / Perfetto) event writer + jax.profiler hook.

Reference equivalents: dear/chrome_profiler.py (custom JSON event writer
with a background writer thread, enabled by the ``WFSGD_TIMELINE`` env var —
configs/envs.conf) and nothing else; on TPU the primary tracing tool is
`jax.profiler` (native Perfetto/TensorBoard), so this module offers both:

  - `TraceWriter`: lightweight host-side event log in Chrome trace format —
    step markers, rebuild events, tuner decisions; things jax.profiler does
    not name. Background thread drains a queue so the training loop never
    blocks on file IO (chrome_profiler.py:13-117's design, reimplemented).
  - `timeline(...)`: context manager that starts a jax.profiler trace when
    the ``DEAR_TIMELINE`` env var (or an explicit path) is set.
"""

from __future__ import annotations

import contextlib
import json
import os
import queue
import threading
import time
from typing import Optional

import jax


class TraceWriter:
    """Asynchronous Chrome-trace JSON writer.

    Events use the 'X' (complete) phase: name, ts/dur in microseconds.
    `event()` may be called from the training loop at any rate; a daemon
    thread serializes to disk. Call `close()` (or use as context manager)
    to flush.
    """

    def __init__(self, path: str, pid: int = 0):
        self._path = path
        self._pid = pid
        self._q: queue.Queue = queue.Queue()
        self._events: list[dict] = []
        self._t0 = time.perf_counter()
        self._closed = False
        self._thread = threading.Thread(target=self._drain, daemon=True)
        self._thread.start()

    def _now_us(self) -> float:
        return (time.perf_counter() - self._t0) * 1e6

    def event(self, name: str, start_us: float, dur_us: float,
              tid: int = 0, **args) -> None:
        self._q.put({
            "name": name, "ph": "X", "ts": start_us, "dur": dur_us,
            "pid": self._pid, "tid": tid, "args": args,
        })

    @contextlib.contextmanager
    def span(self, name: str, tid: int = 0, **args):
        t0 = self._now_us()
        try:
            yield
        finally:
            self.event(name, t0, self._now_us() - t0, tid=tid, **args)

    def instant(self, name: str, **args) -> None:
        self._q.put({
            "name": name, "ph": "i", "ts": self._now_us(), "s": "g",
            "pid": self._pid, "tid": 0, "args": args,
        })

    def _drain(self) -> None:
        while True:
            item = self._q.get()
            if item is None:
                return
            self._events.append(item)

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._q.put(None)
        self._thread.join(timeout=5)
        with open(self._path, "w") as f:
            json.dump({"traceEvents": self._events,
                       "displayTimeUnit": "ms"}, f)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


TIMELINE_ENV = "DEAR_TIMELINE"


@contextlib.contextmanager
def timeline(path: Optional[str] = None):
    """Start a jax.profiler trace if a path is given or ``DEAR_TIMELINE`` is
    set (the reference's WFSGD_TIMELINE switch); no-op otherwise."""
    path = path or os.environ.get(TIMELINE_ENV)
    if not path:
        yield None
        return
    jax.profiler.start_trace(path)
    try:
        yield path
    finally:
        jax.profiler.stop_trace()
