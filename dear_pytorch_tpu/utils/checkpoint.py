"""Checkpoint / resume for `DearState` — a capability gap in the reference
(SURVEY.md §5: "Checkpoint/resume: none at training level"), filled here
with Orbax.

The carried state is already fully explicit (sharded master buffers,
optimizer state, step counter, model collections, compressor residuals), so
checkpointing is: save the pytree + a fingerprint of the fusion plan it was
packed under. On restore the fingerprint is checked against the live train
step's plan — restoring into a re-bucketed setup is an error with a pointer
to `tuning.autotune.repack_state` (which converts between plans).
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Optional

import jax

from dear_pytorch_tpu.ops import fusion as F
from dear_pytorch_tpu.parallel import dear as D


def plan_fingerprint(plan: F.FusionPlan) -> str:
    """Stable hash of everything that determines buffer layout."""
    desc = {
        "world": plan.world,
        "leaves": [(s.name, list(s.shape), str(s.dtype)) for s in plan.leaves],
        "buckets": [
            [list(b.leaf_ids), b.padded_size] for b in plan.buckets
        ],
    }
    return hashlib.sha256(
        json.dumps(desc, sort_keys=True).encode()
    ).hexdigest()[:16]


def plan_desc(plan: F.FusionPlan) -> dict:
    """JSON-serializable description from which the plan's buffer layout
    can be REBUILT (not just checked) — the sidecar payload that makes
    `elastic_restore` possible on a different world size."""
    return {
        "world": plan.world,
        "leaves": [
            {"name": s.name, "layer": s.layer, "shape": list(s.shape),
             "dtype": str(s.dtype)}
            for s in plan.leaves
        ],
        "groups": [list(b.leaf_ids) for b in plan.buckets],
    }


def plan_from_desc(desc: dict, treedef) -> F.FusionPlan:
    """Rebuild a `FusionPlan` from `plan_desc` output. ``treedef`` comes
    from a live plan over the SAME model (the pytree structure is not
    serializable; leaf order is the flatten order both plans share)."""
    import jax.numpy as jnp

    specs = tuple(
        F.LeafSpec(
            name=d["name"], layer=d["layer"], shape=tuple(d["shape"]),
            dtype=jnp.dtype(d["dtype"]),
            size=int(max(1, _prod(d["shape"]))),
        )
        for d in desc["leaves"]
    )
    return F._build_plan(specs, [list(g) for g in desc["groups"]],
                         desc["world"], treedef)


def _prod(shape) -> int:
    out = 1
    for s in shape:
        out *= int(s)
    return out


def _ckpt_dir(directory: str, step: int) -> str:
    return os.path.join(directory, f"step_{step:010d}")


_async_ckptr = None


def _get_async_checkpointer():
    """One process-wide AsyncCheckpointer (it owns the writer threads; Orbax
    requires saves to be serialized through a single instance)."""
    global _async_ckptr
    if _async_ckptr is None:
        import orbax.checkpoint as ocp

        _async_ckptr = ocp.AsyncCheckpointer(ocp.PyTreeCheckpointHandler())
    return _async_ckptr


def save_checkpoint(
    directory: str, state: D.DearState, plan: F.FusionPlan,
    *, asynchronous: bool = False,
) -> str:
    """Write a checkpoint for the state's current step; returns its path.

    ``asynchronous=True`` returns as soon as the on-device arrays are
    snapshotted; serialization to disk proceeds on Orbax's writer threads
    while training continues (the step dir appears atomically when the write
    commits). Call `wait_for_checkpoints` before reading the files or
    exiting the process.
    """
    import orbax.checkpoint as ocp

    step = int(jax.device_get(state.step))
    path = _ckpt_dir(directory, step)
    # Hand Orbax the live (possibly sharded) arrays: each process writes its
    # addressable shards. A jax.device_get here would fail on non-addressable
    # shards in multi-host runs and replicate everything through host RAM.
    if asynchronous:
        _get_async_checkpointer().save(os.path.abspath(path), state)
    else:
        ocp.PyTreeCheckpointer().save(os.path.abspath(path), state)
    if jax.process_index() == 0:  # one writer for the sidecar on shared fs
        # written eagerly even for async saves: restore only ever reaches a
        # sidecar through a COMMITTED step dir (latest_step scans dirs), so
        # a crash mid-write leaves an orphan sidecar, never a broken restore
        meta = {"plan": plan_fingerprint(plan), "step": step,
                "plan_desc": plan_desc(plan)}
        with open(os.path.join(directory, f"meta_{step:010d}.json"), "w") as f:
            json.dump(meta, f)
    return path


def wait_for_checkpoints() -> None:
    """Block until every `save_checkpoint(asynchronous=True)` has committed.
    No-op when none are in flight."""
    if _async_ckptr is not None:
        _async_ckptr.wait_until_finished()


def latest_step(directory: str) -> Optional[int]:
    if not os.path.isdir(directory):
        return None
    steps = [
        int(name[len("step_"):])
        for name in os.listdir(directory)
        # exclude Orbax's atomic-write temp dirs
        # (step_XXXXXXXXXX.orbax-checkpoint-tmp-N) left by a crash mid-save
        if name.startswith("step_") and name[len("step_"):].isdigit()
    ]
    return max(steps) if steps else None


def restore_checkpoint(
    directory: str,
    ts: D.TrainStep,
    *,
    step: Optional[int] = None,
    template: Optional[D.DearState] = None,
) -> D.DearState:
    """Restore into the layout of ``ts`` (shardings taken from a template
    state — ``ts.init`` output — or built fresh here).

    Raises if the checkpoint was written under a different fusion plan.
    """
    import orbax.checkpoint as ocp

    if step is None:
        step = latest_step(directory)
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {directory}")
    meta_path = os.path.join(directory, f"meta_{step:010d}.json")
    with open(meta_path) as f:
        meta = json.load(f)
    live = plan_fingerprint(ts.plan)
    if meta["plan"] != live:
        raise ValueError(
            f"checkpoint step {step} was packed under plan {meta['plan']} "
            f"but the train step uses plan {live}; rebuild the step with "
            "the original plan, or restore there and carry across with "
            "tuning.autotune.repack_state"
        )
    if template is None:
        raise ValueError("pass template=ts.init(...) output for shardings")
    ckptr = ocp.PyTreeCheckpointer()
    # restore INTO the template's structure (a structureless restore returns
    # a dict whose alphabetical key order would scramble DearState fields)
    # and ONTO the template's shardings: each process reads only its own
    # shards — no host-RAM replication, multi-host safe.
    restore_args = ocp.checkpoint_utils.construct_restore_args(template)
    return ckptr.restore(
        os.path.abspath(_ckpt_dir(directory, step)),
        item=template,
        restore_args=restore_args,
    )


class _PlanShim:
    """The one attribute `repack_state` reads from its train steps."""

    def __init__(self, plan):
        self.plan = plan


def elastic_restore(
    directory: str,
    ts: D.TrainStep,
    *,
    step: Optional[int] = None,
) -> D.DearState:
    """Restore a checkpoint written under a DIFFERENT world size or fusion
    plan into ``ts`` — elastic recovery: a world=8 run resumes on 4 chips
    (or vice versa, or after re-bucketing) with parameters, elementwise
    optimizer state, and the step counter carried over exactly.

    The sidecar's ``plan_desc`` rebuilds the original plan's buffer layout;
    the checkpoint is read to host and re-packed/re-sharded through
    `tuning.autotune.repack_state` (compressor residuals reset, scalar
    optimizer leaves carried per that function's contract). Numerics: the
    global batch math is world-independent, so training continues with the
    same loss trajectory it would have had without the resize.

    Single-controller path: the full state passes through host RAM of each
    process (fine for recovery; the fast same-plan path is
    `restore_checkpoint`). Use that one when the plan fingerprints match.
    """
    import numpy as np
    import orbax.checkpoint as ocp

    from dear_pytorch_tpu.tuning.autotune import repack_state

    if step is None:
        step = latest_step(directory)
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {directory}")
    with open(os.path.join(directory, f"meta_{step:010d}.json")) as f:
        meta = json.load(f)
    if "plan_desc" not in meta:
        raise ValueError(
            f"checkpoint step {step} predates plan_desc sidecars; elastic "
            "restore needs the original layout description"
        )
    old_plan = plan_from_desc(meta["plan_desc"], ts.plan.treedef)
    if [s.name for s in old_plan.leaves] != [s.name for s in ts.plan.leaves]:
        raise ValueError(
            "checkpoint parameters do not match the live model "
            "(leaf names differ) — elastic restore resizes worlds, it does "
            "not migrate architectures"
        )

    # Restore to HOST numpy explicitly: a structureless restore would use
    # the SAVED shardings, which reference devices that no longer exist
    # after a genuine downsize (orbax warns exactly about this).
    ckptr = ocp.PyTreeCheckpointer()
    path = os.path.abspath(_ckpt_dir(directory, step))
    item_md = ckptr.metadata(path).item_metadata
    item_tree = item_md.tree if hasattr(item_md, "tree") else item_md
    restore_args = jax.tree.map(
        lambda _: ocp.RestoreArgs(restore_type=np.ndarray), item_tree
    )
    raw = ckptr.restore(path, restore_args=restore_args)
    # NamedTuples come back as field-name dicts from a structureless
    # restore; tolerate either form
    get = raw.get if isinstance(raw, dict) else \
        (lambda k, d=None: getattr(raw, k, d))

    def host(x):
        return jax.tree.map(np.asarray, x)

    state = D.DearState(
        buffers=tuple(host(b) for b in _as_sequence(get("buffers"))),
        opt_state=tuple(
            host(s) for s in _as_sequence(get("opt_state"))
        ),
        step=np.asarray(get("step")),
        model_state=host(get("model_state", ())) or (),
        comp_state=(),
    )
    return repack_state(state, _PlanShim(old_plan), ts)


def _as_sequence(tree):
    """Per-bucket entries of a restored tuple field (dict with stringified
    indices, or an actual sequence)."""
    if isinstance(tree, dict):
        return [tree[k] for k in sorted(tree, key=lambda s: int(s))]
    return list(tree)
